"""Build entry (reference setup.py). Stamps git info into
deepspeed_tpu/git_version_info.py at build time; op building is JIT-only on
TPU (the native host ops compile on first use via op_builder), so the
DS_BUILD_* ahead-of-time machinery of the reference is unnecessary."""
import subprocess

from setuptools import setup


def _git(cmd):
    try:
        return subprocess.check_output(
            ["git"] + cmd, stderr=subprocess.DEVNULL).decode().strip()
    except Exception:
        return "unknown"


def stamp_git_version():
    hash_ = _git(["rev-parse", "--short", "HEAD"])
    branch = _git(["rev-parse", "--abbrev-ref", "HEAD"])
    with open("deepspeed_tpu/git_version_info.py", "w") as fd:
        fd.write('git_hash = "{}"\ngit_branch = "{}"\n'.format(hash_, branch))


if __name__ == "__main__":
    stamp_git_version()
    setup()
