#!/usr/bin/env python3
"""Bench-trajectory scoreboard: assemble every ``BENCH_r*.json`` rung
into one table (MFU, tokens/s/chip, goodput, wire reduction ratios per
rung) and gate regressions.

    python bin/ds_scoreboard.py                      # markdown to stdout
    python bin/ds_scoreboard.py --json scoreboard.json
    python bin/ds_scoreboard.py --md SCOREBOARD.md
    python bin/ds_scoreboard.py --regression-pct 10  # the gate (default)

Exit codes: 0 = trajectory healthy (or nothing to compare), **1** =
the newest measured rung's MFU sits more than ``--regression-pct``
below the best prior rung — the scoreboard is the CI tripwire that
keeps the MFU trajectory from silently decaying. Failed rungs (rc != 0
/ ``value: null``) stay in the table with their error, excluded from
the regression math.

Serving rungs (``BENCH_SERVING*.json``, swept from ``tests/perf/`` and
the repo root) get their own trajectory: per-config goodput / p95 TTFT
rows plus the same >10% same-device gate — goodput falling or p95 TTFT
rising past the threshold against the best prior rung exits 1 (CPU
rungs exempt unless ``--gate-cpu``).

Long-context rungs (``BENCH_LONGCTX*.json``, same sweep —
tests/perf/bench_longctx.py's block-sparse 8-16k rung) get the same
treatment: per-seq/mode tokens/s rows, headline = the best timed
sparse row, >10% same-device tokens/s gate; the analytic dense-OOM
accounting rows ride the table but never gate.

Repo-root ``BENCH_r*.json`` files are driver run records
(``{"n", "cmd", "rc", "tail"}``) whose bench JSON line is embedded in
the tail — the same unwrap ``bin/check_bench_schema.py`` applies.
Stdlib-only. The JSON artifact (``kind: "bench_scoreboard"``) is
validated by check_bench_schema.py.
"""
import argparse
import glob
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KIND_SCOREBOARD = "bench_scoreboard"

# every trajectory row carries exactly these keys
SCOREBOARD_ROW_KEYS = (
    "rung", "file", "rc", "metric", "value", "unit", "mfu",
    "tokens_per_sec_per_chip", "goodput_tokens_per_sec", "reduction_x",
    "overlap_efficiency", "device", "error",
)

# every serving-trajectory row (one per BENCH_SERVING*.json config)
# carries exactly these keys — check_bench_schema.check_scoreboard
# pins them on the artifact
SERVING_ROW_KEYS = (
    "rung", "file", "config", "device",
    "goodput_tokens_per_sec", "ttft_p95_s",
)

# every long-context trajectory row (one per BENCH_LONGCTX*.json
# timed/accounting row) carries exactly these keys —
# check_bench_schema.check_scoreboard pins them on the artifact
LONGCTX_ROW_KEYS = (
    "rung", "file", "seq", "mode", "device", "tokens_per_sec",
)


def unwrap_driver_record(payload):
    """Driver run record -> the embedded bench JSON line (or None for
    an honestly failed rung)."""
    inner = None
    for line in payload.get("tail", "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and "metric" in cand:
                inner = cand
    return inner


def _rung_index(path, payload):
    if isinstance(payload.get("n"), int):
        return payload["n"]
    m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def load_rung(path):
    """-> one scoreboard row for a BENCH_r*.json file."""
    with open(path) as fh:
        payload = json.load(fh)
    rung = _rung_index(path, payload)
    rc = payload.get("rc") if "rc" in payload else 0
    inner = unwrap_driver_record(payload) if "tail" in payload \
        else payload
    row = {
        "rung": rung,
        "file": os.path.basename(path),
        "rc": rc,
        "metric": None, "value": None, "unit": None, "mfu": None,
        "tokens_per_sec_per_chip": None, "goodput_tokens_per_sec": None,
        "reduction_x": None, "overlap_efficiency": None,
        "device": None, "error": None,
    }
    if inner is None:
        row["error"] = "no bench JSON line in the run record " \
            "(rc={})".format(rc)
        return row
    extra = inner.get("extra") or {}
    row.update({
        "metric": inner.get("metric"),
        "value": inner.get("value"),
        "unit": inner.get("unit"),
        "mfu": extra.get("mfu"),
        "device": extra.get("device"),
        "error": inner.get("error"),
    })
    if inner.get("unit") == "tokens/s/chip":
        row["tokens_per_sec_per_chip"] = inner.get("value")
    trace = extra.get("serving_trace") or {}
    best_goodput = None
    for cfg in (trace.get("configs") or {}).values():
        val = cfg.get("goodput_tokens_per_sec")
        if val is not None:
            best_goodput = val if best_goodput is None \
                else max(best_goodput, val)
    row["goodput_tokens_per_sec"] = best_goodput
    executor = extra.get("executor") or {}
    eff = executor.get("overlap_efficiency")
    row["overlap_efficiency"] = eff if isinstance(eff, (int, float)) \
        and not isinstance(eff, bool) else None
    comm = extra.get("comm") or {}
    red = comm.get("reduction_x")
    row["reduction_x"] = red if isinstance(red, dict) else (
        {"total": comm.get("total_reduction_x")}
        if comm.get("total_reduction_x") is not None else None)
    return row


def _serving_rung_index(path, payload):
    m = re.search(r"BENCH_SERVING_r(\d+)", os.path.basename(path))
    if m:
        return int(m.group(1))
    if isinstance(payload.get("n"), int):
        return payload["n"]
    return -1


def load_serving_rung(path):
    """-> list of serving-trajectory rows (one per serving_trace
    config) for one BENCH_SERVING*.json file. Files without a
    serving_trace yield no rows (they were a failed or foreign rung)."""
    with open(path) as fh:
        payload = json.load(fh)
    inner = unwrap_driver_record(payload) if "tail" in payload \
        else payload
    if inner is None:
        return []
    extra = inner.get("extra") or {}
    trace = extra.get("serving_trace") or {}
    rung = _serving_rung_index(path, payload)
    rows = []
    for name, cfg in sorted((trace.get("configs") or {}).items()):
        if not isinstance(cfg, dict):
            continue
        rows.append({
            "rung": rung,
            "file": os.path.basename(path),
            "config": name,
            "device": extra.get("device"),
            "goodput_tokens_per_sec": cfg.get("goodput_tokens_per_sec"),
            "ttft_p95_s": cfg.get("ttft_p95_s"),
        })
    return rows


def build_serving_board(paths, regression_pct=10.0, gate_cpu=False):
    """Serving regression gate (ISSUE 17): the newest rung's headline
    numbers against the best PRIOR rung of the same device kind —
    goodput (higher-better) must not drop more than ``regression_pct``
    below the best prior, and p95 TTFT (lower-better) must not rise
    more than ``regression_pct`` above the best prior. A rung's
    headline is its best config (max goodput / min ttft_p95 across the
    configs it measured), so adding a slower comparison config never
    trips the gate."""
    rows = []
    for path in sorted(paths):
        rows.extend(load_serving_rung(path))
    rows.sort(key=lambda r: (r["rung"], r["file"], r["config"]))
    per_rung = {}
    for row in rows:
        key = (row["rung"], row["file"])
        slot = per_rung.setdefault(key, {
            "rung": row["rung"], "file": row["file"],
            "device": row["device"], "goodput": None, "ttft_p95": None})
        val = row["goodput_tokens_per_sec"]
        if val is not None and (slot["goodput"] is None or
                                val > slot["goodput"]):
            slot["goodput"] = val
        val = row["ttft_p95_s"]
        if val is not None and (slot["ttft_p95"] is None or
                                val < slot["ttft_p95"]):
            slot["ttft_p95"] = val
    rungs = [per_rung[k] for k in sorted(per_rung)
             if per_rung[k]["goodput"] is not None]
    latest = rungs[-1] if rungs else None
    regression = False
    gate = None
    best_prior = None
    if latest is not None:
        same_device = [r for r in rungs[:-1]
                       if r["device"] == latest["device"]]
        if latest["device"] == "cpu" and not gate_cpu:
            gate = "skipped: latest serving rung is a cpu-fallback " \
                   "rung (pass --gate-cpu to include)"
        elif not same_device:
            gate = "skipped: no prior serving rung on device " \
                   "{!r}".format(latest["device"])
        else:
            best_prior = {
                "rung": max(same_device,
                            key=lambda r: r["goodput"])["rung"],
                "goodput": max(r["goodput"] for r in same_device),
                "ttft_p95": min((r["ttft_p95"] for r in same_device
                                 if r["ttft_p95"] is not None),
                                default=None),
            }
            frac = regression_pct / 100.0
            goodput_bad = latest["goodput"] < \
                best_prior["goodput"] * (1.0 - frac)
            ttft_bad = (latest["ttft_p95"] is not None and
                        best_prior["ttft_p95"] is not None and
                        latest["ttft_p95"] >
                        best_prior["ttft_p95"] * (1.0 + frac))
            regression = goodput_bad or ttft_bad
            if regression:
                gate = "tripped: " + ", ".join(
                    name for name, bad in (("goodput", goodput_bad),
                                           ("ttft_p95", ttft_bad))
                    if bad)
            else:
                gate = "passed"
    return {
        "rows": rows,
        "measured_rungs": len(rungs),
        "latest_rung": latest["rung"] if latest else None,
        "latest_goodput": latest["goodput"] if latest else None,
        "latest_ttft_p95_s": latest["ttft_p95"] if latest else None,
        "best_prior_rung": best_prior["rung"] if best_prior else None,
        "best_prior_goodput": best_prior["goodput"]
        if best_prior else None,
        "best_prior_ttft_p95_s": best_prior["ttft_p95"]
        if best_prior else None,
        "regression_pct": regression_pct,
        "regression": regression,
        "gate": gate,
    }


def _longctx_rung_index(path, payload):
    m = re.search(r"BENCH_LONGCTX_r(\d+)", os.path.basename(path))
    if m:
        return int(m.group(1))
    if isinstance(payload.get("n"), int):
        return payload["n"]
    return -1


def load_longctx_rung(path):
    """-> list of long-context trajectory rows (one per
    ``extra.longctx`` seq/mode row) for one BENCH_LONGCTX*.json file.
    Files without a longctx payload yield no rows."""
    with open(path) as fh:
        payload = json.load(fh)
    inner = unwrap_driver_record(payload) if "tail" in payload \
        else payload
    if inner is None:
        return []
    extra = inner.get("extra") or {}
    longctx = extra.get("longctx") or {}
    rung = _longctx_rung_index(path, payload)
    rows = []
    for row in longctx.get("rows") or []:
        if not isinstance(row, dict):
            continue
        rows.append({
            "rung": rung,
            "file": os.path.basename(path),
            "seq": row.get("seq"),
            "mode": row.get("mode"),
            "device": extra.get("device"),
            "tokens_per_sec": row.get("tokens_per_sec")
            if row.get("timed") else None,
        })
    return rows


def build_longctx_board(paths, regression_pct=10.0, gate_cpu=False):
    """Long-context regression gate (ISSUE 18): the newest rung's
    headline tokens/s — its best TIMED sparse row — against the best
    PRIOR rung of the same device kind, with the same >10% gate the MFU
    and serving trajectories use. Accounting-only rows (the analytic
    dense-OOM evidence) ride the table but never enter the gate."""
    rows = []
    for path in sorted(paths):
        rows.extend(load_longctx_rung(path))
    rows.sort(key=lambda r: (r["rung"], r["file"], r["seq"] or 0,
                             r["mode"] or ""))
    per_rung = {}
    for row in rows:
        if row["tokens_per_sec"] is None:
            continue
        key = (row["rung"], row["file"])
        slot = per_rung.setdefault(key, {
            "rung": row["rung"], "file": row["file"],
            "device": row["device"], "tokens_per_sec": None,
            "seq": None})
        if slot["tokens_per_sec"] is None or \
                row["tokens_per_sec"] > slot["tokens_per_sec"]:
            slot["tokens_per_sec"] = row["tokens_per_sec"]
            slot["seq"] = row["seq"]
    rungs = [per_rung[k] for k in sorted(per_rung)]
    latest = rungs[-1] if rungs else None
    regression = False
    gate = None
    best_prior = None
    if latest is not None:
        same_device = [r for r in rungs[:-1]
                       if r["device"] == latest["device"]]
        if latest["device"] == "cpu" and not gate_cpu:
            gate = "skipped: latest longctx rung is a cpu-fallback " \
                   "rung (pass --gate-cpu to include)"
        elif not same_device:
            gate = "skipped: no prior longctx rung on device " \
                   "{!r}".format(latest["device"])
        else:
            best_prior = max(same_device,
                             key=lambda r: r["tokens_per_sec"])
            regression = latest["tokens_per_sec"] < \
                best_prior["tokens_per_sec"] * \
                (1.0 - regression_pct / 100.0)
            gate = "tripped: tokens_per_sec" if regression else "passed"
    return {
        "rows": rows,
        "measured_rungs": len(rungs),
        "latest_rung": latest["rung"] if latest else None,
        "latest_tokens_per_sec": latest["tokens_per_sec"]
        if latest else None,
        "latest_seq": latest["seq"] if latest else None,
        "best_prior_rung": best_prior["rung"] if best_prior else None,
        "best_prior_tokens_per_sec": best_prior["tokens_per_sec"]
        if best_prior else None,
        "regression_pct": regression_pct,
        "regression": regression,
        "gate": gate,
    }


def build_scoreboard(paths, regression_pct=10.0, gate_cpu=False,
                     serving_paths=None, longctx_paths=None):
    """MFU regression gate: the newest measured rung against the best
    PRIOR rung **of the same device kind** — MFU is a fraction of that
    chip's peak, so a TPU rung never gates against a CPU one. CPU
    (backend-fallback) rungs are correctness vehicles whose MFU swings
    with box co-tenancy; they are exempt from the gate unless
    ``gate_cpu`` (the trajectory still shows them)."""
    rows = sorted((load_rung(p) for p in paths),
                  key=lambda r: (r["rung"], r["file"]))
    measured = [r for r in rows if r["mfu"] is not None and r["rc"] == 0]
    best_prior = latest = None
    regression = False
    gate = None
    if measured:
        latest = measured[-1]
        same_device = [r for r in measured[:-1]
                       if r["device"] == latest["device"]]
        if latest["device"] == "cpu" and not gate_cpu:
            gate = "skipped: latest rung is a cpu-fallback rung " \
                   "(pass --gate-cpu to include)"
        elif not same_device:
            gate = "skipped: no prior rung on device " \
                   "{!r}".format(latest["device"])
        else:
            best_prior = max(same_device, key=lambda r: r["mfu"])
            regression = latest["mfu"] < \
                best_prior["mfu"] * (1.0 - regression_pct / 100.0)
            gate = "tripped" if regression else "passed"
    # overlap-efficiency trajectory (PR 19, extra.executor): the same
    # same-device newest-vs-best-prior gate MFU gets — a plan-rewrite
    # or scheduler change that quietly re-exposes transfer waits trips
    # here even when MFU noise hides it
    overlap = [r for r in rows
               if r["overlap_efficiency"] is not None and r["rc"] == 0]
    ov_latest = ov_best_prior = None
    ov_regression = False
    ov_gate = None
    if overlap:
        ov_latest = overlap[-1]
        same_device = [r for r in overlap[:-1]
                       if r["device"] == ov_latest["device"]]
        if ov_latest["device"] == "cpu" and not gate_cpu:
            ov_gate = "skipped: latest rung is a cpu-fallback rung " \
                      "(pass --gate-cpu to include)"
        elif not same_device:
            ov_gate = "skipped: no prior overlap-measured rung on " \
                      "device {!r}".format(ov_latest["device"])
        else:
            ov_best_prior = max(same_device,
                                key=lambda r: r["overlap_efficiency"])
            ov_regression = ov_latest["overlap_efficiency"] < \
                ov_best_prior["overlap_efficiency"] * \
                (1.0 - regression_pct / 100.0)
            ov_gate = "tripped" if ov_regression else "passed"
    serving = build_serving_board(
        serving_paths, regression_pct=regression_pct,
        gate_cpu=gate_cpu) if serving_paths else None
    longctx = build_longctx_board(
        longctx_paths, regression_pct=regression_pct,
        gate_cpu=gate_cpu) if longctx_paths else None
    return {
        "kind": KIND_SCOREBOARD,
        "rows": rows,
        "serving": serving,
        "longctx": longctx,
        "measured_rungs": len(measured),
        "best_prior_mfu": best_prior["mfu"] if best_prior else None,
        "best_prior_rung": best_prior["rung"] if best_prior else None,
        "latest_mfu": latest["mfu"] if latest else None,
        "latest_rung": latest["rung"] if latest else None,
        "latest_overlap_efficiency":
        ov_latest["overlap_efficiency"] if ov_latest else None,
        "best_prior_overlap_efficiency":
        ov_best_prior["overlap_efficiency"] if ov_best_prior else None,
        "overlap_regression": ov_regression,
        "overlap_gate": ov_gate,
        "regression_pct": regression_pct,
        "regression": regression,
        "gate": gate,
    }


def _fmt(val, spec="{:.4f}"):
    if val is None:
        return "-"
    if isinstance(val, dict):
        return ",".join("{}={}".format(k, "-" if v is None else
                                       "{:.1f}".format(v))
                        for k, v in sorted(val.items()))
    return spec.format(val)


def render_markdown(board):
    lines = [
        "# Bench trajectory",
        "",
        "| rung | file | rc | MFU | tokens/s/chip | goodput tok/s | "
        "wire reduction_x | overlap eff | device | error |",
        "|---:|---|---:|---:|---:|---:|---|---:|---|---|",
    ]
    for row in board["rows"]:
        lines.append(
            "| {rung} | {file} | {rc} | {mfu} | {tps} | {goodput} | "
            "{red} | {overlap} | {device} | {error} |".format(
                rung=row["rung"], file=row["file"], rc=row["rc"],
                mfu=_fmt(row["mfu"]),
                tps=_fmt(row["tokens_per_sec_per_chip"], "{:.1f}"),
                goodput=_fmt(row["goodput_tokens_per_sec"], "{:.1f}"),
                red=_fmt(row["reduction_x"]),
                overlap=_fmt(row["overlap_efficiency"]),
                device=row["device"] or "-",
                error=(row["error"] or "-").replace("|", "/")[:60]))
    lines.append("")
    if board["regression"]:
        lines.append(
            "**REGRESSION**: rung {} MFU {} is more than {}% below the "
            "best prior rung {} ({}).".format(
                board["latest_rung"], _fmt(board["latest_mfu"]),
                board["regression_pct"], board["best_prior_rung"],
                _fmt(board["best_prior_mfu"])))
    else:
        lines.append("Trajectory healthy: latest measured MFU {} "
                     "(best same-device prior {}; gate {}).".format(
                         _fmt(board["latest_mfu"]),
                         _fmt(board["best_prior_mfu"]),
                         board["gate"] or "n/a"))
    if board.get("overlap_regression"):
        lines.append("")
        lines.append(
            "**OVERLAP REGRESSION**: latest overlap efficiency {} is "
            "more than {}% below the best same-device prior {}.".format(
                _fmt(board["latest_overlap_efficiency"]),
                board["regression_pct"],
                _fmt(board["best_prior_overlap_efficiency"])))
    elif board.get("latest_overlap_efficiency") is not None:
        lines.append(
            "Overlap efficiency: latest {} (best same-device prior {}; "
            "gate {}).".format(
                _fmt(board["latest_overlap_efficiency"]),
                _fmt(board["best_prior_overlap_efficiency"]),
                board["overlap_gate"] or "n/a"))
    serving = board.get("serving")
    if serving and serving["rows"]:
        lines += [
            "",
            "## Serving trajectory",
            "",
            "| rung | file | config | goodput tok/s | ttft p95 s | "
            "device |",
            "|---:|---|---|---:|---:|---|",
        ]
        for row in serving["rows"]:
            lines.append(
                "| {rung} | {file} | {config} | {goodput} | {ttft} | "
                "{device} |".format(
                    rung=row["rung"], file=row["file"],
                    config=row["config"],
                    goodput=_fmt(row["goodput_tokens_per_sec"],
                                 "{:.1f}"),
                    ttft=_fmt(row["ttft_p95_s"], "{:.4f}"),
                    device=row["device"] or "-"))
        lines.append("")
        if serving["regression"]:
            lines.append(
                "**SERVING REGRESSION**: rung {} goodput {} / ttft_p95 "
                "{} against best prior rung {} (goodput {}, ttft_p95 "
                "{}) breaches the {}% gate ({}).".format(
                    serving["latest_rung"],
                    _fmt(serving["latest_goodput"], "{:.1f}"),
                    _fmt(serving["latest_ttft_p95_s"], "{:.4f}"),
                    serving["best_prior_rung"],
                    _fmt(serving["best_prior_goodput"], "{:.1f}"),
                    _fmt(serving["best_prior_ttft_p95_s"], "{:.4f}"),
                    serving["regression_pct"], serving["gate"]))
        else:
            lines.append(
                "Serving trajectory healthy: latest goodput {} tok/s, "
                "ttft_p95 {} s (gate {}).".format(
                    _fmt(serving["latest_goodput"], "{:.1f}"),
                    _fmt(serving["latest_ttft_p95_s"], "{:.4f}"),
                    serving["gate"] or "n/a"))
    longctx = board.get("longctx")
    if longctx and longctx["rows"]:
        lines += [
            "",
            "## Long-context trajectory",
            "",
            "| rung | file | seq | mode | tokens/s | device |",
            "|---:|---|---:|---|---:|---|",
        ]
        for row in longctx["rows"]:
            lines.append(
                "| {rung} | {file} | {seq} | {mode} | {tps} | "
                "{device} |".format(
                    rung=row["rung"], file=row["file"],
                    seq=row["seq"] if row["seq"] is not None else "-",
                    mode=row["mode"] or "-",
                    tps=_fmt(row["tokens_per_sec"], "{:.1f}"),
                    device=row["device"] or "-"))
        lines.append("")
        if longctx["regression"]:
            lines.append(
                "**LONGCTX REGRESSION**: rung {} tokens/s {} is more "
                "than {}% below the best prior rung {} ({}).".format(
                    longctx["latest_rung"],
                    _fmt(longctx["latest_tokens_per_sec"], "{:.1f}"),
                    longctx["regression_pct"],
                    longctx["best_prior_rung"],
                    _fmt(longctx["best_prior_tokens_per_sec"],
                         "{:.1f}")))
        else:
            lines.append(
                "Long-context trajectory healthy: latest {} tokens/s "
                "at seq {} (gate {}).".format(
                    _fmt(longctx["latest_tokens_per_sec"], "{:.1f}"),
                    longctx["latest_seq"] if longctx["latest_seq"]
                    is not None else "-",
                    longctx["gate"] or "n/a"))
    return "\n".join(lines) + "\n"


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="assemble BENCH_r*.json rungs into the MFU "
                    "trajectory scoreboard")
    parser.add_argument("paths", nargs="*", default=None,
                        help="BENCH files (default: repo-root "
                             "BENCH_r*.json)")
    parser.add_argument("--json", dest="json_out", default=None)
    parser.add_argument("--md", dest="md_out", default=None)
    parser.add_argument("--regression-pct", type=float, default=10.0)
    parser.add_argument("--gate-cpu", action="store_true",
                        help="apply the regression gate to cpu-fallback "
                             "rungs too (off: cpu MFU swings with box "
                             "co-tenancy)")
    args = parser.parse_args(argv)
    # serving rungs (BENCH_SERVING*.json) ride along whatever path list
    # is in play: explicitly passed ones are split out by name, and the
    # default glob also sweeps tests/perf + the repo root for them
    explicit = args.paths or []
    serving_paths = [p for p in explicit
                     if os.path.basename(p).startswith("BENCH_SERVING")]
    longctx_paths = [p for p in explicit
                     if os.path.basename(p).startswith("BENCH_LONGCTX")]
    paths = [p for p in explicit
             if p not in serving_paths and p not in longctx_paths]
    if not explicit:
        paths = sorted(glob.glob(os.path.join(_REPO, "BENCH_r*.json")))
        serving_paths = sorted(
            glob.glob(os.path.join(_REPO, "tests", "perf",
                                   "BENCH_SERVING*.json")) +
            glob.glob(os.path.join(_REPO, "BENCH_SERVING*.json")))
        longctx_paths = sorted(
            glob.glob(os.path.join(_REPO, "tests", "perf",
                                   "BENCH_LONGCTX*.json")) +
            glob.glob(os.path.join(_REPO, "BENCH_LONGCTX*.json")))
    if not paths:
        print("ds_scoreboard: no BENCH_r*.json rungs found",
              file=sys.stderr)
        return 1
    board = build_scoreboard(paths, regression_pct=args.regression_pct,
                             gate_cpu=args.gate_cpu,
                             serving_paths=serving_paths,
                             longctx_paths=longctx_paths)
    md = render_markdown(board)
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(board, fh, indent=2, sort_keys=True)
    if args.md_out:
        with open(args.md_out, "w") as fh:
            fh.write(md)
    print(md, end="")
    if board["regression"]:
        print("ds_scoreboard: REGRESSION gate tripped (>{}% MFU drop)"
              .format(args.regression_pct), file=sys.stderr)
        return 1
    if board.get("overlap_regression"):
        print("ds_scoreboard: OVERLAP regression gate tripped (>{}% "
              "overlap-efficiency drop)".format(args.regression_pct),
              file=sys.stderr)
        return 1
    if board.get("serving") and board["serving"]["regression"]:
        print("ds_scoreboard: SERVING regression gate tripped (>{}% "
              "goodput drop or ttft_p95 rise)"
              .format(args.regression_pct), file=sys.stderr)
        return 1
    if board.get("longctx") and board["longctx"]["regression"]:
        print("ds_scoreboard: LONGCTX regression gate tripped (>{}% "
              "tokens/s drop)".format(args.regression_pct),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
