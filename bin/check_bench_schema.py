#!/usr/bin/env python3
"""Validate telemetry/diagnostics artifact shapes.

Three artifact families, dispatched by shape:

* **BENCH_*.json** — ONE parseable JSON object with metric (str), value
  (number|null), unit (str), vs_baseline (number|null); "error" (str)
  required whenever value is null; optional extra (dict). When
  ``extra.telemetry`` is present it must be a telemetry snapshot:
  ``steps``/``serving_steps`` ints, and — when steps > 0 —
  ``step_time_s``/``mfu``/``tokens_per_sec_per_chip`` dists with
  last/mean/p50/p95 numbers (docs/telemetry.md).
* **crash bundles** (``kind: "crash_bundle"``, flight recorder —
  docs/diagnostics.md): reason/wall, record+span+log rings, env report,
  program registry.
* **analysis reports** (``kind: "analysis_report"``, the shard-lint
  auditor / ``bin/ds_lint.py --json`` — docs/analysis.md): programs
  map, findings/suppressed lists with rule/check/key/severity, summary
  counters.
* **bench scoreboards** (``kind: "bench_scoreboard"``,
  ``bin/ds_scoreboard.py --json`` — docs/fleet.md): non-empty
  trajectory rows with rung/mfu/regression fields.
* **fleet reports** (``kind: "fleet_report"``, ``bin/ds_fleet.py
  --json`` — docs/fleet.md): hosts/offsets/records/straggler plus the
  ISSUE 15 ``divergence`` section (published/digests/mismatch/
  divergent_hosts — docs/concurrency.md).
* **host manifests** (``kind: "host_manifest"``, the collector's
  discovery seam): required keys plus the optional
  ``program_fingerprint`` extension (version/digest/families).
* **Chrome trace-event files** (a JSON array, telemetry.spans'
  trace_events.json and ``bin/ds_fleet.py --trace``'s merged form):
  parsed leniently (a crashed run may leave the Perfetto-tolerated
  trailing-comma/unclosed-array form) and each event checked for
  name/ph/ts/pid/tid.

BENCH ``extra.metrics`` (the embedded final /metrics scrape of the
fleet export plane) is validated for series count + exposition text.
``extra.longctx`` (tests/perf/bench_longctx.py, the long-context
sparse-attention rung) is validated for its rows and for the INTERNAL
CONSISTENCY of its analytic dense-OOM accounting — the published
fits booleans must match their own published operands.

Usage: check_bench_schema.py [FILE...]; with no args, validates every
BENCH_*.json in the repo root and tests/perf/. Exit 1 on any failure.
"""
import glob
import json
import os
import sys

_NUM = (int, float)

# Local copy of telemetry/record.py SERVING_SUBDICT_KEYS: this checker
# must stay runnable as a bare stdlib script (no deepspeed_tpu/jax
# import from bin/). tests/unit/test_serving.py pins the two tables
# equal so they cannot drift.
SERVING_SUBDICT_KEYS = {
    "ttft": ("count", "mean_s", "p50_s", "p95_s"),
    "tpot": ("count", "mean_s", "p50_s", "p95_s"),
    "page_pool": ("num_pages", "pages_in_use", "occupancy"),
    "prefix": ("lookups", "hits", "hit_rate"),
    "speculative": ("proposed", "accepted", "acceptance_rate"),
}

# Local copy of telemetry/record.py SERVING_ROLES (ISSUE 17): the
# closed role vocabulary a serving_step record / fleet host summary may
# carry. Pinned equal by tests/unit/test_serving_fleet.py.
SERVING_ROLES = ("monolith", "prefill", "decode", "router")

# Local copies of inference/fleet/events.py ROUTER_EVENT_KEYS /
# ROUTER_DECISIONS (same stdlib-only constraint; pinned equal by
# tests/unit/test_serving_fleet.py).
ROUTER_EVENT_KEYS = (
    "kind", "wall", "decision", "request_uid", "host", "reason",
    "predicted_cost_s", "detail",
)
ROUTER_DECISIONS = ("admit", "deny", "route_away", "preempt_migrate",
                    "enroll", "enroll_refusal")

# Local copies of runtime/controller/ledger.py DECISION_KEYS /
# CONTROLLER_EVENT_TYPES / CONTROLLER_KNOBS and telemetry/record.py
# CONTROLLER_SNAPSHOT_KEYS (same stdlib-only constraint; pinned equal
# by tests/unit/test_controller.py). Every closed-loop controller
# decision is replayable from these records alone (docs/controller.md).
CONTROLLER_EVENTS_JSONL = "controller_events.jsonl"
KIND_CONTROLLER_EVENT = "controller_event"
DECISION_KEYS = (
    "kind", "wall", "seq", "event", "decision_id", "policy", "knob",
    "target", "old", "new", "signal", "predicted_win_s",
    "measured_win_s", "reason",
)
CONTROLLER_EVENT_TYPES = ("decision", "outcome", "revert")
CONTROLLER_KNOBS = (
    "launch_ahead_window", "h2d_bucket_elems", "spec_k",
    "prefill_chunk_tokens", "quantized_collectives", "prefill_buckets",
)
CONTROLLER_SNAPSHOT_KEYS = (
    "enabled", "role", "policies", "decisions", "outcomes", "reverts",
    "pending", "overrides", "drift", "ledger_path",
)


def check_controller_event(ev, where):
    """-> list of problems with one controller ledger event (a stdlib
    re-statement of runtime/controller/ledger.py
    ``validate_controller_event`` — the ledger's own checker is the
    source of truth)."""
    problems = []
    if not isinstance(ev, dict):
        return ["{} is not a dict".format(where)]
    for key in DECISION_KEYS:
        if key not in ev:
            problems.append("{} missing key {!r}".format(where, key))
    extra = sorted(set(ev) - set(DECISION_KEYS))
    if extra:
        # the fleet merger stamps the originating host
        extra = [k for k in extra if k != "source"]
    if extra:
        problems.append("{} has unexpected key(s) {}".format(
            where, extra))
    if problems:
        return problems
    if ev["kind"] != KIND_CONTROLLER_EVENT:
        problems.append("{} has kind {!r}".format(where, ev["kind"]))
    if ev["event"] not in CONTROLLER_EVENT_TYPES:
        problems.append("{} has unknown event {!r}".format(
            where, ev["event"]))
    if ev["knob"] not in CONTROLLER_KNOBS:
        problems.append("{} has unknown knob {!r}".format(
            where, ev["knob"]))
    if not _is_num(ev["wall"]):
        problems.append("{}.wall is not a number".format(where))
    if not isinstance(ev["seq"], int) or isinstance(ev["seq"], bool) \
            or ev["seq"] < 0:
        problems.append("{}.seq is not an int >= 0".format(where))
    for key in ("decision_id", "policy"):
        if not isinstance(ev[key], str) or not ev[key]:
            problems.append(
                "{}.{} is not a non-empty string".format(where, key))
    if not isinstance(ev["reason"], str):
        problems.append("{}.reason is not a string".format(where))
    if ev["signal"] is not None and not isinstance(ev["signal"], dict):
        problems.append(
            "{}.signal is neither null nor a dict".format(where))
    for key in ("predicted_win_s", "measured_win_s"):
        if ev[key] is not None and not _is_num(ev[key]):
            problems.append(
                "{}.{} is neither null nor a number".format(where, key))
    if ev["event"] == "decision" and not isinstance(ev["signal"], dict):
        problems.append("{} is a decision without its signal citation"
                        .format(where))
    if ev["event"] in ("outcome", "revert") and \
            not _is_num(ev["measured_win_s"]):
        problems.append("{} is an {} without a measured_win_s".format(
            where, ev["event"]))
    return problems


def check_controller_snapshot(snap, where):
    """-> list of problems with one controller snapshot (the
    ``extra.controller`` bench block / telemetry-snapshot section; a
    stdlib re-statement of telemetry/record.py
    ``validate_controller_snapshot``)."""
    problems = []
    if not isinstance(snap, dict):
        return ["{} is not a dict".format(where)]
    for key in CONTROLLER_SNAPSHOT_KEYS:
        if key not in snap:
            problems.append("{} missing key {!r}".format(where, key))
    extra = sorted(set(snap) - set(CONTROLLER_SNAPSHOT_KEYS))
    if extra:
        problems.append("{} has unexpected key(s) {}".format(
            where, extra))
    if problems:
        return problems
    if not isinstance(snap["enabled"], bool):
        problems.append("{}.enabled is not a bool".format(where))
    if not isinstance(snap["role"], str):
        problems.append("{}.role is not a string".format(where))
    for key in ("decisions", "outcomes", "reverts", "pending"):
        val = snap[key]
        if not isinstance(val, int) or isinstance(val, bool) or val < 0:
            problems.append(
                "{}.{} is not an int >= 0".format(where, key))
    for key in ("policies", "overrides"):
        if not isinstance(snap[key], list):
            problems.append("{}.{} is not a list".format(where, key))
    if snap["drift"] is not None and not _is_num(snap["drift"]):
        problems.append(
            "{}.drift is neither null nor a number".format(where))
    if snap["ledger_path"] is not None and \
            not isinstance(snap["ledger_path"], str):
        problems.append(
            "{}.ledger_path is neither null nor a string".format(where))
    return problems


def check_controller_events_text(text):
    """-> list of problems with one ``controller_events.jsonl`` file's
    text (one schema-pinned event per line)."""
    problems = []
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        return ["controller ledger holds no events"]
    for i, line in enumerate(lines):
        try:
            ev = json.loads(line)
        except ValueError as err:
            problems.append("line {}: unparseable: {}".format(i, err))
            break
        problems.extend(check_controller_event(
            ev, "line {}".format(i)))
        if problems:
            break                       # first bad event names the file
    return problems

# Local copy of telemetry/record.py SEGMENT_KEYS /
# SEGMENT_KIND_KEYS / SEGMENT_OPTIONAL_KEYS (same stdlib-only
# constraint; pinned equal by tests/unit/test_executor.py): the
# unified per-segment stats schema of the executor-lowered offload
# paths' ``offload`` record sub-dict and the benches'
# ``extra.executor`` payload.
SEGMENT_KEYS = (
    "plan_segments", "per_kind", "overlap_efficiency",
    "upload_batches", "upload_elems", "upload_bytes",
    "bucket_elems", "bucket_occupancy",
)
SEGMENT_KIND_KEYS = ("segments", "run_s", "wait_s")
SEGMENT_OPTIONAL_KEYS = (
    "segment_upload_bytes_peak", "groups", "collective_matmul",
    "work_chunks", "mode", "plans_executed", "segments_executed",
    "last_plan_segments", "rewrites",
)

# Local copy of telemetry/record.py REWRITE_KEYS / REWRITE_PASS_KEYS
# (PR 19 plan-rewrite stats; same stdlib-only constraint; pinned equal
# by tests/unit/test_executor.py).
REWRITE_KEYS = ("enabled", "passes", "segments_moved",
                "predicted_exposed_wait_delta_s",
                "measured_exposed_wait_delta_s")
REWRITE_PASS_KEYS = ("name", "segments_moved",
                     "predicted_exposed_wait_delta_s")


def check_rewrite_stats(stats, where):
    """-> list of problems with one REWRITE_KEYS stats dict (a stdlib
    re-statement of telemetry/record.py validate_rewrite_stats)."""
    problems = []
    if not isinstance(stats, dict):
        return ["{} is not a dict".format(where)]
    for key in REWRITE_KEYS:
        if key not in stats:
            problems.append("{} missing key {!r}".format(where, key))
    extra = sorted(set(stats) - set(REWRITE_KEYS))
    if extra:
        problems.append("{} has unexpected key(s) {}".format(
            where, extra))
    if problems:
        return problems
    if not isinstance(stats["enabled"], bool):
        problems.append("{}.enabled is not a bool".format(where))
    if not _is_num(stats["segments_moved"]) or \
            stats["segments_moved"] < 0:
        problems.append("{}.segments_moved is not a nonnegative "
                        "number".format(where))
    for key in ("predicted_exposed_wait_delta_s",
                "measured_exposed_wait_delta_s"):
        val = stats[key]
        if val is not None and not _is_num(val):
            problems.append("{}.{} is neither null nor a number".format(
                where, key))
    passes = stats["passes"]
    if not isinstance(passes, list):
        return problems + ["{}.passes is not a list".format(where)]
    for i, entry in enumerate(passes):
        if not isinstance(entry, dict) or \
                sorted(entry) != sorted(REWRITE_PASS_KEYS):
            problems.append(
                "{}.passes[{}] does not carry exactly {}".format(
                    where, i, sorted(REWRITE_PASS_KEYS)))
            break
    return problems


def check_segment_stats(stats, where):
    """-> list of problems with one SEGMENT_KEYS stats dict (a stdlib
    re-statement of telemetry/record.py validate_segment_stats —
    executor dicts carry the lifetime counter extras; record dicts the
    path extras)."""
    problems = []
    if not isinstance(stats, dict):
        return ["{} is not a dict".format(where)]
    # dispatch marker: dicts without plan_segments are pre-executor
    # artifacts (older BENCH records) — validated only for shape above
    if "plan_segments" not in stats:
        return []
    for key in SEGMENT_KEYS:
        if key not in stats and not (
                where.endswith("executor") and key.startswith(
                    ("upload_", "bucket_"))):
            problems.append("{} missing key {!r}".format(where, key))
    extra = sorted(set(stats) - set(SEGMENT_KEYS)
                   - set(SEGMENT_OPTIONAL_KEYS))
    if extra:
        problems.append("{} has unexpected key(s) {}".format(
            where, extra))
    per_kind = stats.get("per_kind")
    if not isinstance(per_kind, dict):
        problems.append("{}.per_kind is not a dict".format(where))
    else:
        for kind, slot in per_kind.items():
            if not isinstance(slot, dict):
                problems.append(
                    "{}.per_kind.{} is not a dict".format(where, kind))
                continue
            for key in SEGMENT_KIND_KEYS:
                if not _is_num(slot.get(key)):
                    problems.append(
                        "{}.per_kind.{}.{} is not a number".format(
                            where, kind, key))
    if stats.get("rewrites") is not None:
        problems.extend(check_rewrite_stats(
            stats["rewrites"], where + ".rewrites"))
    return problems


# Local copy of telemetry/recorder.py CRASH_BUNDLE_KEYS (same stdlib-
# only constraint; pinned equal by tests/unit/test_diagnostics.py).
CRASH_BUNDLE_KEYS = (
    "kind", "reason", "wall", "job_name", "exception",
    "records", "spans", "open_spans", "log_events",
    "ds_config", "env", "programs", "watchdog", "topology", "state",
)


def _is_num(val):
    return isinstance(val, _NUM) and not isinstance(val, bool)


def _check_dist(d, name, problems):
    if not isinstance(d, dict):
        problems.append("telemetry.{} is not a dict".format(name))
        return
    for key in ("last", "mean", "p50", "p95"):
        if not _is_num(d.get(key)):
            problems.append(
                "telemetry.{}.{} is not a number: {!r}".format(
                    name, key, d.get(key)))


def check_telemetry_snapshot(snap):
    """-> list of problems with one ``extra.telemetry`` payload."""
    problems = []
    if not isinstance(snap, dict):
        return ["extra.telemetry is not a dict"]
    if not snap:
        return ["extra.telemetry is empty (telemetry was disabled — "
                "drop the key instead)"]
    steps = snap.get("steps", 0)
    serving = snap.get("serving_steps", 0)
    for key, val in (("steps", steps), ("serving_steps", serving)):
        if not isinstance(val, int) or isinstance(val, bool) or val < 0:
            problems.append(
                "telemetry.{} is not an int >= 0: {!r}".format(key, val))
            return problems
    if steps == 0 and serving == 0:
        problems.append("telemetry carries neither train nor serving steps")
    if "controller" in snap:
        problems.extend(check_controller_snapshot(
            snap["controller"], "telemetry.controller"))
    if steps > 0:
        for name in ("step_time_s", "mfu", "tokens_per_sec_per_chip"):
            _check_dist(snap.get(name), name, problems)
        if not isinstance(snap.get("phases_mean_s"), dict):
            problems.append("telemetry.phases_mean_s is not a dict")
        if isinstance(snap.get("offload_last"), dict):
            problems.extend(check_segment_stats(
                snap["offload_last"], "telemetry.offload_last"))
    if serving > 0:
        srv = snap.get("serving")
        if not isinstance(srv, dict):
            problems.append("telemetry.serving is not a dict")
        else:
            # serving-memory/latency gauges (ISSUE 7): optional — a
            # slot-layout engine emits none — but when present they
            # must carry their numeric fields
            for key, want in SERVING_SUBDICT_KEYS.items():
                sub = srv.get(key)
                if sub is None:
                    continue
                if not isinstance(sub, dict):
                    problems.append(
                        "telemetry.serving.{} is not a dict".format(key))
                    continue
                for sub_key in want:
                    if not _is_num(sub.get(sub_key)):
                        problems.append(
                            "telemetry.serving.{}.{} is not a number: "
                            "{!r}".format(key, sub_key, sub.get(sub_key)))
    return problems


def check_metrics_payload(payload):
    """-> list of problems with one ``extra.metrics`` payload (the
    bench-embedded final /metrics scrape; docs/fleet.md)."""
    problems = []
    if not isinstance(payload, dict):
        return ["extra.metrics is not a dict"]
    series = payload.get("series")
    if not isinstance(series, int) or isinstance(series, bool) or \
            series < 1:
        problems.append("metrics.series is not an int >= 1: "
                        "{!r}".format(series))
    scrape = payload.get("scrape")
    if not isinstance(scrape, str) or "# TYPE " not in scrape:
        problems.append("metrics.scrape is not Prometheus exposition "
                        "text (no '# TYPE ' line)")
    return problems


# Local copy of bin/ds_scoreboard.py SCOREBOARD_ROW_KEYS (same stdlib-
# only constraint; pinned equal by tests/unit/test_fleet.py).
SCOREBOARD_ROW_KEYS = (
    "rung", "file", "rc", "metric", "value", "unit", "mfu",
    "tokens_per_sec_per_chip", "goodput_tokens_per_sec", "reduction_x",
    "overlap_efficiency", "device", "error",
)


def check_scoreboard(payload):
    """-> list of problems with one bench_scoreboard artifact
    (bin/ds_scoreboard.py --json)."""
    problems = []
    rows = payload.get("rows")
    if not isinstance(rows, list) or not rows:
        return ["scoreboard rows is not a non-empty list"]
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            problems.append("rows[{}] is not an object".format(i))
            break
        for key in SCOREBOARD_ROW_KEYS:
            if key not in row:
                problems.append("rows[{}] missing {!r}".format(i, key))
        if not isinstance(row.get("rung"), int):
            problems.append("rows[{}].rung is not an int".format(i))
        if row.get("mfu") is not None and not _is_num(row["mfu"]):
            problems.append("rows[{}].mfu is neither null nor a "
                            "number".format(i))
        if problems:
            break
    if not isinstance(payload.get("regression"), bool):
        problems.append("regression is not a bool")
    for key in ("latest_mfu", "best_prior_mfu"):
        val = payload.get(key)
        if val is not None and not _is_num(val):
            problems.append("{} is neither null nor a number".format(key))
    serving = payload.get("serving")
    if serving is not None:
        # disaggregated-serving trajectory (ISSUE 17): goodput/p95-TTFT
        # rungs over BENCH_SERVING*.json with the same >10% gate
        if not isinstance(serving, dict):
            problems.append("serving is neither null nor a dict")
            return problems
        srows = serving.get("rows")
        if not isinstance(srows, list):
            problems.append("serving.rows is not a list")
        else:
            for i, row in enumerate(srows):
                if not isinstance(row, dict):
                    problems.append(
                        "serving.rows[{}] is not an object".format(i))
                    break
                for key in ("rung", "file", "config", "device",
                            "goodput_tokens_per_sec", "ttft_p95_s"):
                    if key not in row:
                        problems.append(
                            "serving.rows[{}] missing {!r}".format(
                                i, key))
                if problems:
                    break
        if not isinstance(serving.get("regression"), bool):
            problems.append("serving.regression is not a bool")
    longctx = payload.get("longctx")
    if longctx is not None:
        # long-context trajectory (ISSUE 18): tokens/s rungs over
        # BENCH_LONGCTX*.json with the same >10% gate
        if not isinstance(longctx, dict):
            problems.append("longctx is neither null nor a dict")
            return problems
        lrows = longctx.get("rows")
        if not isinstance(lrows, list):
            problems.append("longctx.rows is not a list")
        else:
            for i, row in enumerate(lrows):
                if not isinstance(row, dict):
                    problems.append(
                        "longctx.rows[{}] is not an object".format(i))
                    break
                for key in ("rung", "file", "seq", "mode", "device",
                            "tokens_per_sec"):
                    if key not in row:
                        problems.append(
                            "longctx.rows[{}] missing {!r}".format(
                                i, key))
                if problems:
                    break
        if not isinstance(longctx.get("regression"), bool):
            problems.append("longctx.regression is not a bool")
    return problems


def check_longctx(payload):
    """-> list of problems with one ``extra.longctx`` payload
    (tests/perf/bench_longctx.py — the ISSUE 18 long-context rung).
    The dense-OOM claim is ANALYTIC (live-bytes arithmetic at the
    declared shape), so the checker re-derives the fits booleans from
    the published operands — a row that says "dense doesn't fit" with
    numbers that say otherwise is a schema failure, not an opinion."""
    problems = []
    if not isinstance(payload, dict):
        return ["extra.longctx is not a dict"]
    rows = payload.get("rows")
    if not isinstance(rows, list) or not rows:
        return ["longctx.rows is not a non-empty list"]
    timed = 0
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            problems.append("longctx.rows[{}] is not an object".format(i))
            break
        for key in ("seq", "mode", "fits", "timed"):
            if key not in row:
                problems.append(
                    "longctx.rows[{}] missing {!r}".format(i, key))
        if row.get("mode") not in ("dense", "sparse"):
            problems.append("longctx.rows[{}] has unknown mode "
                            "{!r}".format(i, row.get("mode")))
        if row.get("timed"):
            timed += 1
            if row.get("fits") and \
                    not _is_num(row.get("tokens_per_sec")):
                problems.append(
                    "longctx.rows[{}] is timed but tokens_per_sec is "
                    "not a number".format(i))
        if problems:
            break
    if not timed:
        problems.append("longctx has no timed row (accounting alone is "
                        "not a rung)")
    oom = payload.get("dense_oom")
    if not isinstance(oom, dict):
        problems.append("longctx.dense_oom is not a dict")
        return problems
    for key in ("hbm_budget_bytes", "dense_bwd_live_bytes",
                "sparse_bwd_live_bytes"):
        if not _is_num(oom.get(key)):
            problems.append(
                "longctx.dense_oom.{} is not a number".format(key))
    if problems:
        return problems
    budget = oom["hbm_budget_bytes"]
    for mode in ("dense", "sparse"):
        fits = oom.get("{}_fits".format(mode))
        derived = oom["{}_bwd_live_bytes".format(mode)] <= budget
        if not isinstance(fits, bool):
            problems.append(
                "longctx.dense_oom.{}_fits is not a bool".format(mode))
        elif fits != derived:
            problems.append(
                "longctx.dense_oom.{}_fits={} contradicts its own "
                "operands ({} bytes vs budget {})".format(
                    mode, fits,
                    oom["{}_bwd_live_bytes".format(mode)], budget))
    if oom.get("dense_fits") is True:
        problems.append("longctx.dense_oom claims dense FITS — the "
                        "rung's shape no longer demonstrates the "
                        "long-context memory wall")
    return problems


# per-config metrics every serving-trace artifact row must report
SERVING_TRACE_CONFIG_KEYS = (
    "goodput_tokens_per_sec", "completed_requests", "completed_tokens",
    "wall_seconds", "ttft_p50_s", "ttft_p95_s", "tpot_p50_s", "tpot_p95_s",
)


def check_serving_trace(trace):
    """-> list of problems with one ``extra.serving_trace`` payload
    (bench_inference.py --serving-trace / tests/perf/BENCH_SERVING.json)."""
    problems = []
    if not isinstance(trace, dict):
        return ["extra.serving_trace is not a dict"]
    configs = trace.get("configs")
    if not isinstance(configs, dict) or not configs:
        return ["serving_trace.configs is not a non-empty dict"]
    # 'slot' is the single-engine trace's baseline; the disaggregated
    # trace (ISSUE 17) compares against the 'single' paged monolith
    if "slot" not in configs and "single" not in configs:
        problems.append("serving_trace.configs lacks a baseline "
                        "('slot' or 'single')")
    for name, cfg in configs.items():
        if not isinstance(cfg, dict):
            problems.append(
                "serving_trace.configs.{} is not a dict".format(name))
            continue
        for key in SERVING_TRACE_CONFIG_KEYS:
            if not _is_num(cfg.get(key)):
                problems.append(
                    "serving_trace.configs.{}.{} is not a number: "
                    "{!r}".format(name, key, cfg.get(key)))
    if not _is_num(trace.get("hbm_budget_tokens")):
        problems.append("serving_trace.hbm_budget_tokens is not a number")
    disagg = trace.get("disagg")
    if disagg is not None:
        # the disaggregated rung's router/handoff evidence (ISSUE 17)
        if not isinstance(disagg, dict):
            problems.append("serving_trace.disagg is not a dict")
            return problems
        handoff = disagg.get("handoff")
        if not isinstance(handoff, dict):
            problems.append("serving_trace.disagg.handoff is not a dict")
        else:
            for key in ("handoffs", "payload_bytes"):
                if not _is_num(handoff.get(key)):
                    problems.append(
                        "serving_trace.disagg.handoff.{} is not a "
                        "number".format(key))
        decisions = disagg.get("router_decisions")
        if not isinstance(decisions, dict):
            problems.append(
                "serving_trace.disagg.router_decisions is not a dict")
        else:
            unknown = sorted(set(decisions) - set(ROUTER_DECISIONS))
            if unknown:
                problems.append(
                    "serving_trace.disagg.router_decisions has unknown "
                    "decision(s) {}".format(unknown))
    return problems


def _unwrap_driver_record(payload):
    """Repo-root BENCH_r*.json are DRIVER run records ({"cmd", "rc",
    "tail"}): the bench's own JSON line is the last {"metric": ...} line
    of the captured tail. Returns (inner_payload, problems)."""
    tail = payload.get("tail", "")
    inner = None
    for line in tail.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and "metric" in cand:
                inner = cand
    if inner is None:
        if payload.get("rc") != 0:
            # historical failed run: the record honestly carries rc + the
            # traceback tail; nothing further to validate
            return None, []
        return None, ["driver record has rc=0 but no bench JSON line "
                      "in its tail"]
    return inner, []


def check_bench_payload(payload):
    """-> list of problems with one parsed BENCH_*.json object. Accepts
    the three artifact shapes in the repo: bench.py's single JSON line,
    perf-table artifacts (metric + rows), and driver run records
    (cmd/rc/tail with the bench line embedded)."""
    problems = []
    if not isinstance(payload, dict):
        return ["top level is not a JSON object"]
    if "rc" in payload and "cmd" in payload:
        payload, problems = _unwrap_driver_record(payload)
        if payload is None:
            return problems
    if "rows" in payload:
        # perf-table shape (e.g. BENCH_BERT_*): non-empty rows; metric
        # is a string when present (earliest artifacts predate it)
        if "metric" in payload and not isinstance(payload["metric"], str):
            problems.append("metric is not a string")
        rows = payload.get("rows")
        if not isinstance(rows, list) or not rows:
            problems.append("rows is not a non-empty list")
        return problems
    if not isinstance(payload.get("metric"), str):
        problems.append("metric is not a string")
    if not isinstance(payload.get("unit"), str):
        problems.append("unit is not a string")
    value = payload.get("value")
    if value is not None and not _is_num(value):
        problems.append("value is neither a number nor null")
    vs = payload.get("vs_baseline")
    if vs is not None and not _is_num(vs):
        problems.append("vs_baseline is neither a number nor null")
    if value is None and not isinstance(payload.get("error"), str):
        problems.append("value is null but no 'error' string names why")
    extra = payload.get("extra")
    if extra is not None:
        if not isinstance(extra, dict):
            problems.append("extra is not a dict")
        else:
            if "telemetry" in extra:
                problems.extend(
                    check_telemetry_snapshot(extra["telemetry"]))
            if "serving_trace" in extra:
                problems.extend(check_serving_trace(extra["serving_trace"]))
            if "longctx" in extra:
                problems.extend(check_longctx(extra["longctx"]))
            if "executor" in extra:
                problems.extend(check_segment_stats(
                    extra["executor"], "extra.executor"))
            if "metrics" in extra:
                problems.extend(check_metrics_payload(extra["metrics"]))
            if "controller" in extra:
                problems.extend(check_controller_snapshot(
                    extra["controller"], "extra.controller"))
    return problems


def check_crash_bundle(bundle):
    """-> list of problems with one flight-recorder crash bundle. A
    stdlib re-statement of telemetry/recorder.py's
    ``validate_crash_bundle`` (the bundle writer's own checker is the
    source of truth; test_diagnostics.py pins the key table equal)."""
    problems = []
    if not isinstance(bundle, dict):
        return ["bundle is not a dict"]
    for key in CRASH_BUNDLE_KEYS:
        if key not in bundle:
            problems.append("missing key {!r}".format(key))
    if problems:
        return problems
    if not isinstance(bundle.get("reason"), str) or not bundle["reason"]:
        problems.append("reason is not a non-empty string")
    if not _is_num(bundle.get("wall")):
        problems.append("wall is not a number")
    for key in ("records", "spans", "open_spans", "log_events"):
        val = bundle[key]
        if not isinstance(val, list) or \
                not all(isinstance(item, dict) for item in val):
            problems.append("{} is not a list of objects".format(key))
    for rec in bundle.get("records") or []:
        if rec.get("kind") not in ("train_step", "serving_step"):
            problems.append(
                "records entry of kind {!r}".format(rec.get("kind")))
            break
    for key in ("env", "programs", "state"):
        if not isinstance(bundle[key], dict):
            problems.append("{} is not a dict".format(key))
    for key in ("exception", "ds_config", "watchdog", "topology"):
        if bundle[key] is not None and not isinstance(bundle[key], dict):
            problems.append("{} is neither null nor a dict".format(key))
    if isinstance(bundle.get("programs"), dict) and \
            "programs" not in bundle["programs"]:
        problems.append("programs is not a registry snapshot "
                        "(no 'programs' table)")
    return problems


# Local copy of analysis/findings.py ANALYSIS_REPORT_KEYS /
# FINDING_KEYS / SEVERITIES (same stdlib-only constraint; pinned equal
# by tests/unit/test_analysis.py).
ANALYSIS_REPORT_KEYS = (
    "kind", "version", "job", "programs", "findings", "suppressed",
    "summary",
)
ANALYSIS_FINDING_KEYS = ("rule", "check", "program", "severity",
                         "message", "key")
ANALYSIS_SEVERITIES = ("error", "warn", "info")


def check_analysis_report(payload):
    """-> list of problems with one shard-lint analysis report. A
    stdlib re-statement of analysis/findings.py's
    ``validate_analysis_report`` (the writer-side checker is the source
    of truth; test_analysis.py pins the key tables equal)."""
    problems = []
    if not isinstance(payload, dict):
        return ["report is not a dict"]
    for key in ANALYSIS_REPORT_KEYS:
        if key not in payload:
            problems.append("missing key {!r}".format(key))
    if problems:
        return problems
    if not isinstance(payload.get("programs"), dict):
        problems.append("programs is not a dict")
    for section in ("findings", "suppressed"):
        entries = payload.get(section)
        if not isinstance(entries, list):
            problems.append("{} is not a list".format(section))
            continue
        for i, ent in enumerate(entries):
            if not isinstance(ent, dict):
                problems.append(
                    "{}[{}] is not an object".format(section, i))
                break
            for key in ANALYSIS_FINDING_KEYS:
                if not isinstance(ent.get(key), str):
                    problems.append("{}[{}].{} is not a string".format(
                        section, i, key))
            if ent.get("severity") not in ANALYSIS_SEVERITIES:
                problems.append("{}[{}] has unknown severity "
                                "{!r}".format(section, i,
                                              ent.get("severity")))
            if section == "suppressed" and \
                    not ent.get("suppressed_reason"):
                problems.append(
                    "suppressed[{}] lacks a suppressed_reason".format(i))
            if problems:
                break
    summary = payload.get("summary")
    if not isinstance(summary, dict):
        problems.append("summary is not a dict")
    else:
        for key in ("programs_audited", "findings", "suppressed"):
            val = summary.get(key)
            if not isinstance(val, int) or isinstance(val, bool) or \
                    val < 0:
                problems.append(
                    "summary.{} is not an int >= 0".format(key))
    return problems


# Local copies of telemetry/fleet/aggregate.py FLEET_REPORT_KEYS /
# HOST_MANIFEST_KEYS / FINGERPRINT_KEYS (same stdlib-only constraint;
# pinned equal by tests/unit/test_concurrency.py).
FLEET_REPORT_KEYS = (
    "kind", "run_dir", "n_hosts", "hosts", "offsets", "records", "gaps",
    "straggler", "ici_health", "trace", "divergence", "rescale",
    "router", "controller",
)
# Local copy of runtime/elastic/events.py RESCALE_EVENT_KEYS (same
# stdlib-only constraint; pinned equal by
# tests/unit/test_elastic_rescale.py).
RESCALE_EVENT_KEYS = (
    "kind", "event", "wall", "reason", "attempt",
    "old_world", "new_world", "old_mesh", "new_mesh",
    "outcome", "detail",
)
HOST_MANIFEST_KEYS = (
    "kind", "job_name", "host", "pid", "process_index", "wall_start",
    "files", "metrics_port",
)
FINGERPRINT_KEYS = ("version", "digest", "families")


def _check_fingerprint(fp, where, problems):
    if not isinstance(fp, dict):
        problems.append("{} is not a dict".format(where))
        return
    for key in FINGERPRINT_KEYS:
        if key not in fp:
            problems.append("{} missing {!r}".format(where, key))
    if not isinstance(fp.get("digest", ""), str):
        problems.append("{}.digest is not a string".format(where))
    fams = fp.get("families")
    if fams is not None and not isinstance(fams, dict):
        problems.append("{}.families is not a dict".format(where))


def check_host_manifest(payload):
    """-> list of problems with one host_manifest.json (the fleet
    merger's discovery seam; the optional ``program_fingerprint``
    extension is ISSUE 15's divergence-auditor seam)."""
    problems = []
    for key in HOST_MANIFEST_KEYS:
        if key not in payload:
            problems.append("missing key {!r}".format(key))
    if not problems and not isinstance(payload.get("files"), dict):
        problems.append("files is not a dict")
    fp = payload.get("program_fingerprint")
    if fp is not None:
        _check_fingerprint(fp, "program_fingerprint", problems)
    return problems


def check_fleet_report(payload):
    """-> list of problems with one fleet_report artifact
    (``bin/ds_fleet.py --json``), including the ISSUE 15 ``divergence``
    section."""
    problems = []
    for key in FLEET_REPORT_KEYS:
        if key not in payload:
            problems.append("missing key {!r}".format(key))
    if problems:
        return problems
    if not isinstance(payload.get("n_hosts"), int) or \
            isinstance(payload.get("n_hosts"), bool):
        problems.append("n_hosts is not an int")
    for key in ("hosts", "records", "gaps"):
        if not isinstance(payload.get(key), list):
            problems.append("{} is not a list".format(key))
    for key in ("offsets", "straggler", "ici_health"):
        if not isinstance(payload.get(key), dict):
            problems.append("{} is not a dict".format(key))
    for i, rec in enumerate(payload.get("records") or []):
        if not isinstance(rec, dict) or rec.get("kind") != "fleet_step":
            problems.append(
                "records[{}] is not a fleet_step record".format(i))
            break
    straggler = payload.get("straggler")
    if isinstance(straggler, dict) and \
            not isinstance(straggler.get("flags"), list):
        problems.append("straggler.flags is not a list")
    div = payload.get("divergence")
    if not isinstance(div, dict):
        problems.append("divergence is not a dict")
    else:
        if not isinstance(div.get("mismatch"), bool):
            problems.append("divergence.mismatch is not a bool")
        if not isinstance(div.get("published"), int) or \
                isinstance(div.get("published"), bool):
            problems.append("divergence.published is not an int")
        for key in ("digests", "families"):
            if not isinstance(div.get(key), dict):
                problems.append(
                    "divergence.{} is not a dict".format(key))
        if not isinstance(div.get("divergent_hosts"), list):
            problems.append("divergence.divergent_hosts is not a list")
        if div.get("mismatch") and not div.get("divergent_hosts"):
            problems.append(
                "divergence.mismatch set with no divergent_hosts")
    rescale = payload.get("rescale")
    if not isinstance(rescale, dict):
        problems.append("rescale is not a dict")
    else:
        for key in ("count", "completed"):
            if not isinstance(rescale.get(key), int) or \
                    isinstance(rescale.get(key), bool):
                problems.append(
                    "rescale.{} is not an int".format(key))
        events = rescale.get("events")
        if not isinstance(events, list):
            problems.append("rescale.events is not a list")
        else:
            for i, ev in enumerate(events):
                if not isinstance(ev, dict) or \
                        ev.get("kind") != "rescale_event":
                    problems.append(
                        "rescale.events[{}] is not a rescale_event"
                        .format(i))
                    break
                missing = [k for k in RESCALE_EVENT_KEYS if k not in ev]
                if missing:
                    problems.append(
                        "rescale.events[{}] missing {}".format(
                            i, missing))
                    break
    router = payload.get("router")
    if not isinstance(router, dict):
        problems.append("router is not a dict")
    else:
        if not isinstance(router.get("count"), int) or \
                isinstance(router.get("count"), bool):
            problems.append("router.count is not an int")
        decisions = router.get("decisions")
        if not isinstance(decisions, dict):
            problems.append("router.decisions is not a dict")
        else:
            unknown = sorted(set(decisions) - set(ROUTER_DECISIONS))
            if unknown:
                problems.append(
                    "router.decisions has unknown decision(s) "
                    "{}".format(unknown))
        events = router.get("events")
        if not isinstance(events, list):
            problems.append("router.events is not a list")
        else:
            for i, ev in enumerate(events):
                if not isinstance(ev, dict) or \
                        ev.get("kind") != "router_event":
                    problems.append(
                        "router.events[{}] is not a router_event"
                        .format(i))
                    break
                missing = [k for k in ROUTER_EVENT_KEYS if k not in ev]
                if missing:
                    problems.append(
                        "router.events[{}] missing {}".format(
                            i, missing))
                    break
                if ev.get("decision") not in ROUTER_DECISIONS:
                    problems.append(
                        "router.events[{}] has unknown decision "
                        "{!r}".format(i, ev.get("decision")))
                    break
    controller = payload.get("controller")
    if not isinstance(controller, dict):
        problems.append("controller is not a dict")
    else:
        if not isinstance(controller.get("count"), int) or \
                isinstance(controller.get("count"), bool):
            problems.append("controller.count is not an int")
        tally = controller.get("tally")
        if not isinstance(tally, dict):
            problems.append("controller.tally is not a dict")
        else:
            unknown = sorted(set(tally) - set(CONTROLLER_EVENT_TYPES))
            if unknown:
                problems.append(
                    "controller.tally has unknown event type(s) "
                    "{}".format(unknown))
        if not isinstance(controller.get("unreverted"), list):
            problems.append("controller.unreverted is not a list")
        events = controller.get("events")
        if not isinstance(events, list):
            problems.append("controller.events is not a list")
        else:
            for i, ev in enumerate(events):
                sub = check_controller_event(
                    ev, "controller.events[{}]".format(i))
                if sub:
                    problems.extend(sub)
                    break
    return problems


# every Chrome trace event must carry these fields
TRACE_EVENT_KEYS = ("name", "ph", "ts", "pid", "tid")


def parse_trace_events(text):
    """Parse a trace-event file LENIENTLY: a live/crashed run's file is
    the Perfetto-tolerated array form with a trailing comma and no
    closing bracket. Returns (events, problems)."""
    text = text.strip()
    try:
        payload = json.loads(text)
    except ValueError:
        try:
            payload = json.loads(text.rstrip(",\n\t ") + "]")
        except ValueError as err:
            return None, ["unparseable trace-event file: {}".format(err)]
    if isinstance(payload, dict):
        payload = payload.get("traceEvents")
    if not isinstance(payload, list):
        return None, ["trace-event payload is not an array"]
    return payload, []


def check_trace_events(text):
    """-> list of problems with one Chrome trace-event file's text."""
    events, problems = parse_trace_events(text)
    if problems:
        return problems
    if not events:
        return ["trace-event file holds no events"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append("event {} is not an object".format(i))
            continue
        for key in TRACE_EVENT_KEYS:
            if key not in ev:
                problems.append(
                    "event {} is missing {!r}".format(i, key))
        if not isinstance(ev.get("name"), str):
            problems.append("event {} name is not a string".format(i))
        if ev.get("ph") not in ("X", "i", "B", "E", "M"):
            problems.append(
                "event {} has unknown phase {!r}".format(i, ev.get("ph")))
        if not _is_num(ev.get("ts")):
            problems.append("event {} ts is not a number".format(i))
        if ev.get("ph") == "X" and not _is_num(ev.get("dur")):
            problems.append(
                "event {} is complete ('X') without a dur".format(i))
        if problems:
            break                       # first bad event names the file
    return problems


def check_file(path):
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError as err:
        return ["unreadable: {}".format(err)]
    if os.path.basename(path) == CONTROLLER_EVENTS_JSONL:
        return check_controller_events_text(text)
    if text.lstrip().startswith("["):
        # only the span tracer's Chrome trace files are arrays
        return check_trace_events(text)
    try:
        payload = json.loads(text)
    except ValueError as err:
        return ["unparseable: {}".format(err)]
    if isinstance(payload, dict) and payload.get("kind") == "crash_bundle":
        return check_crash_bundle(payload)
    if isinstance(payload, dict) and \
            payload.get("kind") == "analysis_report":
        return check_analysis_report(payload)
    if isinstance(payload, dict) and \
            payload.get("kind") == "bench_scoreboard":
        return check_scoreboard(payload)
    if isinstance(payload, dict) and \
            payload.get("kind") == "fleet_report":
        return check_fleet_report(payload)
    if isinstance(payload, dict) and \
            payload.get("kind") == "host_manifest":
        return check_host_manifest(payload)
    if isinstance(payload, dict) and "traceEvents" in payload:
        return check_trace_events(text)
    return check_bench_payload(payload)


def main(argv):
    paths = argv[1:]
    if not paths:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(root, "BENCH_*.json")) +
                       glob.glob(os.path.join(root, "tests", "perf",
                                              "BENCH_*.json")))
    if not paths:
        print("check_bench_schema: no BENCH_*.json files found")
        return 1
    failed = 0
    for path in paths:
        problems = check_file(path)
        if problems:
            failed += 1
            print("FAIL {}".format(path))
            for problem in problems:
                print("  - {}".format(problem))
        else:
            print("OK   {}".format(path))
    print("check_bench_schema: {}/{} files valid".format(
        len(paths) - failed, len(paths)))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
