#!/usr/bin/env python3
"""Fleet doctor CLI: merge a multi-host telemetry run directory (live
or post-mortem, crash bundles included), print the straggler/ICI-health
report, and emit a merged multi-process Perfetto trace.

    python bin/ds_fleet.py RUN_DIR                     # report to stdout
    python bin/ds_fleet.py RUN_DIR --json report.json  # fleet_report artifact
    python bin/ds_fleet.py RUN_DIR --trace merged.json # merged Chrome trace
    python bin/ds_fleet.py RUN_DIR --factor 2 --k 5    # detector thresholds
    python bin/ds_fleet.py RUN_DIR --strict            # exit 2 on flags,
                                                       #   divergence, or
                                                       #   unreverted
                                                       #   regressions

``RUN_DIR`` is a ``telemetry.output_path`` whose per-job subdirectories
each hold one host's ``host_manifest.json`` + ``telemetry.jsonl`` (the
collector writes both; see docs/fleet.md). The merged trace gives each
host its own process lane, offset-corrected onto the reference host's
clock from step-completion skew.

Stdlib-only: the fleet modules mount under a synthetic package name
(the ``bin/ds_lint.py`` trick) so doctoring a crashed run never needs
jax installed.
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_fleet_modules():
    """Load telemetry.fleet.{aggregate,straggler} WITHOUT the
    deepspeed_tpu package __init__ chain (which imports jax): the fleet
    modules are stdlib-only by contract (fleet/__init__.py)."""
    import importlib
    import types
    name = "_ds_fleet_vendor"
    if name not in sys.modules:
        pkg = types.ModuleType(name)
        pkg.__path__ = [os.path.join(_REPO, "deepspeed_tpu",
                                     "telemetry", "fleet")]
        sys.modules[name] = pkg
    return (importlib.import_module(name + ".aggregate"),
            importlib.import_module(name + ".straggler"))


def _fmt_s(val):
    return "-" if val is None else "{:.4f}".format(val)


def print_report(report):
    print("fleet report: {} host(s), {} merged step(s)  [{}]".format(
        report["n_hosts"], len(report["records"]), report["run_dir"]))
    print()
    print("{:<24} {:>6} {:>8} {:>9} {:>8} {:>16}  {}".format(
        "host", "steps", "offset_s", "crashed", "manifest", "roles",
        "gaps"))
    offsets = report["offsets"]
    for host in report["hosts"]:
        # serving-role attribution (ISSUE 17): per-role serving_step
        # counts, so a disaggregated fleet's prefill/decode split is
        # visible in the host table
        roles = host.get("serving_roles") or {}
        role_str = ",".join("{}:{}".format(r, n)
                            for r, n in sorted(roles.items())) or "-"
        print("{:<24} {:>6} {:>8} {:>9} {:>8} {:>16}  {}".format(
            host["name"], host["steps"],
            "{:+.3f}".format(offsets.get(host["name"], 0.0)),
            "yes" if host["crashed"] else "no",
            "yes" if host["manifest"] else "MISSING",
            role_str,
            "; ".join(host["gaps"]) or "-"))
    if report["records"]:
        last = report["records"][-1]
        st = last.get("step_time")
        if st:
            print()
            print("last step {}: wall median {} min {} max {} "
                  "(slowest: {})".format(
                      last["step"], _fmt_s(st["median"]),
                      _fmt_s(st["min"]), _fmt_s(st["max"]),
                      st["max_host"]))
    straggler = report["straggler"]
    print()
    if straggler["flags"]:
        print("STRAGGLERS (>{}x fleet median for >= {} consecutive "
              "steps):".format(straggler["factor"], straggler["k"]))
        for flag in straggler["flags"]:
            print("  - host {host} [{metric}] {worst_ratio:.2f}x worst, "
                  "{steps} step(s), steps {first_step}..{last_step}"
                  .format(**flag))
    else:
        print("no stragglers flagged (factor {}, k {})".format(
            straggler["factor"], straggler["k"]))
    if report["ici_health"]:
        print("ici_health (achieved/nominal, last measured):")
        for host, classes in sorted(report["ici_health"].items()):
            print("  {:<24} {}".format(host, " ".join(
                "{}={:.3f}".format(cls, val)
                for cls, val in sorted(classes.items()))))
    else:
        print("ici_health: no measured exposed-wait walls in this run "
              "(micro/fused paths hide collectives inside one program; "
              "see docs/fleet.md)")
    divergence = report.get("divergence") or {}
    print()
    if divergence.get("mismatch"):
        print("PROGRAM DIVERGENCE: host(s) {} lowered a DIFFERENT "
              "collective sequence than reference host {} — the mesh "
              "hangs at the first divergent collective "
              "(docs/concurrency.md)".format(
                  ", ".join(divergence["divergent_hosts"]),
                  divergence["reference"]))
        for host, digest in sorted(divergence["digests"].items()):
            marker = " <-- DIVERGENT" \
                if host in divergence["divergent_hosts"] else ""
            print("  {:<24} fingerprint {}{}".format(host, digest,
                                                     marker))
    elif divergence.get("published"):
        print("program fingerprints: {} host(s) published, all agree "
              "({})".format(
                  divergence["published"],
                  next(iter(divergence["digests"].values()))))
    else:
        print("program fingerprints: none published (hosts ran without "
              "an audit/fingerprint pass; see docs/concurrency.md)")
    rescale = report.get("rescale") or {}
    print()
    if rescale.get("events"):
        print("RESCALE EVENTS ({} total, {} completed topology "
              "change(s); docs/elasticity.md):".format(
                  rescale.get("count", 0), rescale.get("completed", 0)))
        for ev in rescale["events"]:
            arrow = "-"
            if ev.get("old_world") is not None or \
                    ev.get("new_world") is not None:
                arrow = "{} -> {}".format(ev.get("old_world", "?"),
                                          ev.get("new_world", "?"))
            extras = []
            if ev.get("attempt") is not None:
                extras.append("attempt {}".format(ev["attempt"]))
            if ev.get("outcome"):
                extras.append(ev["outcome"])
            print("  - [{}] {:<18} world {:<10} {}{}".format(
                ev.get("host", "?"), ev.get("event", "?"), arrow,
                ev.get("reason", ""),
                " ({})".format(", ".join(extras)) if extras else ""))
    else:
        print("no rescale events (the run never changed topology)")
    router = report.get("router") or {}
    print()
    if router.get("events"):
        decisions = router.get("decisions") or {}
        print("ROUTER DECISIONS ({} event(s): {}; docs/fleet.md):".format(
            router.get("count", 0),
            ", ".join("{} {}".format(n, d)
                      for d, n in sorted(decisions.items()))))
        for ev in router["events"]:
            extras = []
            if ev.get("request_uid") is not None:
                extras.append("req {}".format(ev["request_uid"]))
            if ev.get("predicted_cost_s") is not None:
                extras.append("cost {:.4f}s".format(
                    ev["predicted_cost_s"]))
            detail = ev.get("detail") or {}
            if detail.get("to"):
                extras.append("-> {}".format(detail["to"]))
            print("  - [{}] {:<16} {}{}".format(
                ev.get("host") or "-", ev.get("decision", "?"),
                ev.get("reason", ""),
                " ({})".format(", ".join(extras)) if extras else ""))
    else:
        print("no router decisions (the run served without a fleet "
              "front-end)")
    controller = report.get("controller") or {}
    print()
    if controller.get("events"):
        tally = controller.get("tally") or {}
        print("CONTROLLER DECISIONS ({} event(s): {}; "
              "docs/controller.md):".format(
                  controller.get("count", 0),
                  ", ".join("{} {}".format(n, e)
                            for e, n in sorted(tally.items()))))
        for ev in controller["events"]:
            extras = []
            if ev.get("target") is not None:
                extras.append("target {}".format(ev["target"]))
            if ev.get("old") is not None or ev.get("new") is not None:
                extras.append("{} -> {}".format(ev.get("old"),
                                                ev.get("new")))
            if ev.get("predicted_win_s") is not None:
                extras.append("predicted {:+.4f}s".format(
                    ev["predicted_win_s"]))
            if ev.get("measured_win_s") is not None:
                extras.append("measured {:+.4f}s".format(
                    ev["measured_win_s"]))
            print("  - [{}] {:<8} {:<22} {}{}".format(
                ev.get("source") or "-", ev.get("event", "?"),
                "{}/{}".format(ev.get("policy", "?"),
                               ev.get("knob", "?")),
                ev.get("reason", ""),
                " ({})".format(", ".join(extras)) if extras else ""))
        unreverted = controller.get("unreverted") or []
        if unreverted:
            print("  UNREVERTED REGRESSIONS: {} (the controller "
                  "measured these decisions making the objective worse "
                  "and did NOT undo them)".format(
                      ", ".join(unreverted)))
    else:
        print("no controller decisions (the run had no closed-loop "
              "controller, or it never moved a knob)")


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="fleet doctor: merge per-host telemetry, attribute "
                    "stragglers/ICI health, emit a merged trace")
    parser.add_argument("run_dir", help="telemetry output_path holding "
                        "per-host job directories")
    parser.add_argument("--json", dest="json_out", default=None,
                        help="write the fleet_report JSON artifact")
    parser.add_argument("--trace", dest="trace_out", default=None,
                        help="write a merged multi-process Chrome trace")
    parser.add_argument("--factor", type=float, default=None,
                        help="straggler deviation factor (default 1.5)")
    parser.add_argument("--k", type=int, default=None,
                        help="consecutive deviating steps to flag "
                             "(default 3)")
    parser.add_argument("--min-hosts", type=int, default=None,
                        help="minimum hosts for median attribution "
                             "(default 2)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 2 when any straggler/ICI flag fired, "
                             "the host program fingerprints diverge, or "
                             "the controller left a measured regression "
                             "unreverted")
    args = parser.parse_args(argv)
    aggregate, _straggler = _load_fleet_modules()
    if not os.path.isdir(args.run_dir):
        print("ds_fleet: {!r} is not a directory".format(args.run_dir),
              file=sys.stderr)
        return 1
    try:
        report = aggregate.merge_run(args.run_dir, factor=args.factor,
                                     k=args.k, min_hosts=args.min_hosts,
                                     trace_out=args.trace_out)
    except FileNotFoundError as err:
        print("ds_fleet: {}".format(err), file=sys.stderr)
        return 1
    print_report(report)
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
        print("\nfleet report -> {}".format(args.json_out))
    if report.get("trace"):
        trace = report["trace"]
        print("merged trace -> {} ({} events from {} host(s); load at "
              "ui.perfetto.dev)".format(trace["path"], trace["events"],
                                        trace["hosts_merged"]))
    if args.strict and (report["straggler"]["flags"] or
                        (report.get("divergence") or {}).get("mismatch") or
                        (report.get("controller") or {}).get("unreverted")):
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
