#!/usr/bin/env python3
"""Shard-lint CLI: repo-wide AST hot-path lint + on-demand program audit.

Repo lint (default; stdlib-only — the linter modules load under a
synthetic package name so the path never imports jax and runs on boxes
without it):

    python bin/ds_lint.py                        # deepspeed_tpu/ vs baseline
    python bin/ds_lint.py path/a path/b          # explicit roots
    python bin/ds_lint.py --write-baseline       # accept current state
    python bin/ds_lint.py --json report.json     # analysis-report artifact

Exit 1 when any occurrence EXCEEDS its baselined count
(bin/ds_lint_baseline.json — every accepted entry is a reviewed
occurrence; new code must come in clean). Stale baseline keys are
reported but do not fail, so refactors that REMOVE hazards never block.

Program audit (imports jax; abstract-evals a demo GPT-2 training engine
plus an inference engine and runs the full shard-lint rule set —
docs/analysis.md; real models audit via ``engine.audit()`` /
``init_inference(..., audit=True)``):

    python bin/ds_lint.py --audit-demo [--hlo] [--json report.json]
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEFAULT_BASELINE = os.path.join(_REPO, "bin", "ds_lint_baseline.json")


def _load_lint_modules():
    """Load analysis.astlint + analysis.findings WITHOUT executing the
    deepspeed_tpu package __init__ chain (which imports jax): both
    modules are stdlib-only, so the repo-lint path stays runnable on a
    box without jax. They mount under a synthetic package name so a
    later real `import deepspeed_tpu` (e.g. --audit-demo) is untouched.
    """
    import importlib
    import types
    name = "_ds_lint_vendor"
    if name not in sys.modules:
        pkg = types.ModuleType(name)
        pkg.__path__ = [os.path.join(_REPO, "deepspeed_tpu", "analysis")]
        sys.modules[name] = pkg
    return (importlib.import_module(name + ".astlint"),
            importlib.import_module(name + ".findings"))


def _report_payload(findings_map, baseline, stale, findings_mod):
    """Serialize a repo-lint run in the analysis-report artifact shape
    (bin/check_bench_schema.py validates it). Occurrence i of a key is
    a finding only when i exceeds the key's baselined count — the same
    per-occurrence split diff_baseline applies, so the artifact's
    counters agree with the CLI's exit status."""
    report = findings_mod.AnalysisReport(job="repo-lint")
    files = sorted({f.program for items in findings_map.values()
                    for f in items})
    for path in files:
        report.add_program(path, family="repo")
    for key, items in sorted(findings_map.items()):
        allowed = baseline.get(key, 0)
        for i, f in enumerate(items):
            if i < allowed:
                report.suppressed.append((f, "baselined occurrence"))
            else:
                report.findings.append(f)
    payload = report.to_dict()
    payload["stale_baseline_keys"] = stale
    return payload


def run_repo_lint(paths, baseline_path, write_baseline, json_out):
    astlint, findings_mod = _load_lint_modules()
    findings = astlint.lint_paths(paths, base=_REPO)
    if write_baseline:
        path = astlint.write_baseline(baseline_path, findings)
        total = sum(len(v) for v in findings.values())
        print("ds_lint: baseline written to {} ({} accepted "
              "occurrence(s) across {} key(s))".format(
                  path, total, len(findings)))
        return 0
    baseline = astlint.load_baseline(baseline_path)
    new, stale = astlint.diff_baseline(findings, baseline)
    if json_out:
        with open(json_out, "w") as fh:
            json.dump(_report_payload(findings, baseline, stale,
                                      findings_mod), fh,
                      indent=2, sort_keys=True)
        print("ds_lint: report written to {}".format(json_out))
    for f in new:
        print("NEW  {}".format(f.message))
    for key in stale:
        print("STALE baseline entry (no longer observed): {}".format(key))
    total = sum(len(v) for v in findings.values())
    print("ds_lint: {} occurrence(s) across {} file-rule key(s); "
          "{} above baseline; {} stale baseline key(s)".format(
              total, len(findings), len(new), len(stale)))
    return 1 if new else 0


def run_audit_demo(hlo, json_out):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import numpy as np
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.GPT2Config(vocab_size=256, max_seq_len=64, n_layers=2,
                          n_heads=2, d_model=64,
                          use_flash_attention=False, remat=False,
                          loss_chunk=0)
    engine, _, _, _ = deepspeed.initialize(
        model=gpt2.make_gpt2_model(config=cfg), config_params={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3,
                                  "stage3_param_persistence_threshold": 0},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9,
        })
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, size=(16, 64)).astype(np.int32)
    report = engine.audit(batch=(ids, ids.copy()), hlo=hlo,
                          report_path=json_out)
    inf = deepspeed.init_inference(
        model=gpt2.make_gpt2_model(config=cfg),
        config={"inference": {"max_batch_size": 2,
                              "prefill_buckets": [8, 16],
                              "dtype": "fp32", "greedy": True}})
    inf_report = inf.audit()
    total = len(report.findings) + len(inf_report.findings)
    print("ds_lint audit-demo: {} train + {} inference program(s) "
          "audited, {} finding(s)".format(
              len(report.programs), len(inf_report.programs), total))
    for f in report.findings + inf_report.findings:
        print("  - [{}] {}".format(f.key, f.message))
    return 1 if total else 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="shard-lint: repo AST linter + program auditor")
    parser.add_argument("paths", nargs="*",
                        default=None, help="lint roots (default: "
                        "deepspeed_tpu/)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept the current violations as baseline")
    parser.add_argument("--json", dest="json_out", default=None,
                        help="write the analysis-report JSON artifact")
    parser.add_argument("--audit-demo", action="store_true",
                        help="abstract-eval + audit a demo engine pair")
    parser.add_argument("--hlo", action="store_true",
                        help="with --audit-demo: also compile + census "
                             "the HLO collectives")
    args = parser.parse_args(argv)
    if args.audit_demo:
        return run_audit_demo(args.hlo, args.json_out)
    paths = args.paths or [os.path.join(_REPO, "deepspeed_tpu")]
    return run_repo_lint(paths, args.baseline, args.write_baseline,
                         args.json_out)


if __name__ == "__main__":
    sys.exit(main())
