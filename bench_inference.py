"""Benchmark: GPT-2 serving throughput through the inference subsystem.

Prints ONE JSON line in bench.py's shape:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

value = decode tokens/s/chip through the continuous-batching scheduler
(the serving steady state). vs_baseline = decode model-flops utilization
(2N flops/token, forward only) against a 5% target — decode is
HBM-bandwidth bound, so single-digit MFU is the healthy regime and 0.05
is the modest north star this harness tracks.
"""
import json
import sys
import time

import numpy as np

from bench import (emit_error_json, peak_for, safe_default_backend,
                   scratch_telemetry_dir)


def main():
    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.utils.monitor import ServingMetrics

    on_tpu = safe_default_backend() == "tpu"
    if on_tpu:
        cfg = gpt2.config_for("gpt2_medium", max_seq_len=1024, remat=False)
        inference = {"max_batch_size": 16, "dtype": "bf16",
                     "prefill_buckets": [128, 256, 512],
                     "max_new_tokens": 64, "greedy": True}
        n_requests, prompt_lens = 48, (64, 180, 400)
    else:
        cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=256, n_layers=2,
                              n_heads=4, d_model=128,
                              use_flash_attention=False, remat=False)
        inference = {"max_batch_size": 4, "dtype": "fp32",
                     "prefill_buckets": [16, 32, 64],
                     "max_new_tokens": 8, "greedy": True}
        n_requests, prompt_lens = 8, (5, 12, 30)

    n_params = gpt2.num_params(cfg)
    model = gpt2.make_gpt2_model(config=cfg)
    engine = deepspeed.init_inference(
        model=model,
        config={"inference": inference,
                # per-decode-step serving records; the final rolling
                # snapshot rides extra.telemetry below
                "telemetry": {"enabled": True,
                              "output_path": scratch_telemetry_dir(
                                  "bench_inf_telemetry_")}})

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size,
                           size=prompt_lens[i % len(prompt_lens)]).tolist()
               for i in range(n_requests)]

    # warmup: compile every prefill bucket + the decode fn off the clock
    engine.generate(prompts[:len(inference["prefill_buckets"])],
                    max_new_tokens=2)

    metrics = ServingMetrics()
    t0 = time.time()
    outs = engine.generate(prompts, metrics=metrics)
    wall = time.time() - t0
    assert len(outs) == n_requests and all(len(o) > 0 for o in outs)

    snap = metrics.snapshot()
    chips = jax.device_count()
    decode_tps = snap["decode_tokens_per_sec"]
    # decode flops/token: forward-only dense path ~ 2N
    flops_per_token = 2.0 * n_params
    mfu = (decode_tps * flops_per_token / chips) / peak_for(jax.devices()[0])

    print(json.dumps({
        "metric": "gpt2_inference_decode_tokens_per_sec_per_chip",
        "value": round(decode_tps / chips, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.05, 4),
        "extra": {
            "prefill_tokens_per_sec": snap["prefill_tokens_per_sec"],
            "decode_tokens_per_sec": decode_tps,
            "decode_mfu": round(mfu, 4),
            "mean_slot_occupancy": snap["mean_slot_occupancy"],
            "peak_queue_depth": snap["peak_queue_depth"],
            "requests": n_requests,
            "slots": engine.num_slots,
            "prefill_buckets": engine.prefill_buckets,
            "prefill_traces": engine.compile_stats["prefill_traces"],
            "wall_seconds": round(wall, 2),
            "params": n_params,
            "kv_cache_mb": round(engine.kv.nbytes / 2 ** 20, 1),
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "backend": jax.default_backend(),
            # omitted (not {}) on non-writer processes: the schema
            # checker rejects an empty snapshot (bin/check_bench_schema)
            **({"telemetry": engine.telemetry_snapshot()}
               if engine.telemetry is not None else {}),
        },
    }))


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as err:  # noqa: BLE001 - emit parseable JSON, not a trace
        emit_error_json("gpt2_inference_decode_tokens_per_sec_per_chip", err)
        sys.exit(1)
