"""Benchmark: GPT-2 serving throughput through the inference subsystem.

Default mode prints ONE JSON line in bench.py's shape:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

value = decode tokens/s/chip through the continuous-batching scheduler
(the serving steady state). vs_baseline = decode model-flops utilization
(2N flops/token, forward only) against a 5% target — decode is
HBM-bandwidth bound, so single-digit MFU is the healthy regime and 0.05
is the modest north star this harness tracks.

``--serving-trace [--out PATH]`` runs the HEAVY-TRAFFIC synthetic trace
instead (ISSUE 7): Zipf-distributed prompt/output lengths, bursty
Poisson arrivals, a shared system prompt on part of the traffic — three
engine configs at EQUAL KV HBM budget (slot baseline; paged; paged +
prefix sharing + ngram speculative decoding + chunked prefill), run
INTERLEAVED per the PR 5/6 microbench discipline, reporting p50/p95
TTFT, p50/p95 per-output-token latency, and goodput (completed-request
tokens/s). The artifact (default tests/perf/BENCH_SERVING.json) is
validated by bin/check_bench_schema.py.

``--disagg [--out PATH]`` runs the DISAGGREGATED rung (ISSUE 17): the
same Zipf/Poisson trace at 10x the load (560 requests) against two
configs at EQUAL aggregate KV budget — ``single`` (one paged chunked-
prefill monolith owning the whole page budget) vs ``disagg`` (a
DisaggServer fleet: 1 prefill host + 2 decode hosts on the simulated
multi-host CPU mesh, KV moving over the serialized page-slice wire,
placement through the SLO router). The artifact (default
tests/perf/BENCH_SERVING_r17.json) carries the handoff/router evidence
in ``extra.serving_trace.disagg`` and feeds bin/ds_scoreboard.py's
serving trajectory gate.
"""
import json
import sys
import time

import numpy as np

from bench import (emit_error_json, peak_for, safe_default_backend,
                   scratch_telemetry_dir)


def main():
    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.utils.monitor import ServingMetrics

    on_tpu = safe_default_backend() == "tpu"
    if on_tpu:
        cfg = gpt2.config_for("gpt2_medium", max_seq_len=1024, remat=False)
        inference = {"max_batch_size": 16, "dtype": "bf16",
                     "prefill_buckets": [128, 256, 512],
                     "max_new_tokens": 64, "greedy": True}
        n_requests, prompt_lens = 48, (64, 180, 400)
    else:
        cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=256, n_layers=2,
                              n_heads=4, d_model=128,
                              use_flash_attention=False, remat=False)
        inference = {"max_batch_size": 4, "dtype": "fp32",
                     "prefill_buckets": [16, 32, 64],
                     "max_new_tokens": 8, "greedy": True}
        n_requests, prompt_lens = 8, (5, 12, 30)

    n_params = gpt2.num_params(cfg)
    model = gpt2.make_gpt2_model(config=cfg)
    engine = deepspeed.init_inference(
        model=model,
        config={"inference": inference,
                # per-decode-step serving records; the final rolling
                # snapshot rides extra.telemetry below
                "telemetry": {"enabled": True,
                              "output_path": scratch_telemetry_dir(
                                  "bench_inf_telemetry_")}})

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size,
                           size=prompt_lens[i % len(prompt_lens)]).tolist()
               for i in range(n_requests)]

    # warmup: compile every prefill bucket + the decode fn off the clock
    engine.generate(prompts[:len(inference["prefill_buckets"])],
                    max_new_tokens=2)

    metrics = ServingMetrics()
    t0 = time.time()
    outs = engine.generate(prompts, metrics=metrics)
    wall = time.time() - t0
    assert len(outs) == n_requests and all(len(o) > 0 for o in outs)

    snap = metrics.snapshot()
    chips = jax.device_count()
    decode_tps = snap["decode_tokens_per_sec"]
    # decode flops/token: forward-only dense path ~ 2N
    flops_per_token = 2.0 * n_params
    mfu = (decode_tps * flops_per_token / chips) / peak_for(jax.devices()[0])

    print(json.dumps({
        "metric": "gpt2_inference_decode_tokens_per_sec_per_chip",
        "value": round(decode_tps / chips, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.05, 4),
        "extra": {
            "prefill_tokens_per_sec": snap["prefill_tokens_per_sec"],
            "decode_tokens_per_sec": decode_tps,
            "decode_mfu": round(mfu, 4),
            "mean_slot_occupancy": snap["mean_slot_occupancy"],
            "peak_queue_depth": snap["peak_queue_depth"],
            "requests": n_requests,
            "slots": engine.num_slots,
            "prefill_buckets": engine.prefill_buckets,
            "prefill_traces": engine.compile_stats["prefill_traces"],
            "wall_seconds": round(wall, 2),
            "params": n_params,
            "kv_cache_mb": round(engine.kv.nbytes / 2 ** 20, 1),
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "backend": jax.default_backend(),
            # omitted (not {}) on non-writer processes: the schema
            # checker rejects an empty snapshot (bin/check_bench_schema)
            **({"telemetry": engine.telemetry_snapshot()}
               if engine.telemetry is not None else {}),
        },
    }))


# ---------------------------------------------------------------------
# heavy-traffic synthetic trace (ISSUE 7): slot vs paged vs paged+spec
# ---------------------------------------------------------------------

TRACE_SEED = 17
HBM_BUDGET_TOKENS = 1024          # slot baseline: 4 slots x 256 max_seq
TRACE_MAX_SEQ = 256
TRACE_PAGE = 16


def _zipf_clipped(rng, a, lo, hi, size):
    vals = rng.zipf(a, size=size) + lo - 1
    return np.clip(vals, lo, hi)


def build_trace(vocab, n_requests=56):
    """One fixed workload every config replays: Zipf prompt/output
    lengths, Poisson-burst arrival offsets (seconds), a shared system
    prompt on ~half the traffic, and document-sliced prompt bodies (so
    prompt-lookup drafting sees the repetitive structure real text
    has). Arrivals are deliberately faster than the slot baseline can
    drain — goodput must measure CAPACITY under backlog, not offered
    load."""
    rng = np.random.RandomState(TRACE_SEED)
    prompt_lens = _zipf_clipped(rng, 1.4, 4, 160, n_requests)
    output_lens = _zipf_clipped(rng, 1.3, 12, 96, n_requests)
    # "document": patterned token stream — windows of it repeat n-grams
    doc = np.tile(rng.randint(0, vocab, size=192), 4)
    system = rng.randint(0, vocab, size=48).tolist()
    requests, t = [], 0.0
    i = 0
    while i < n_requests:
        t += rng.exponential(0.06)                 # burst inter-arrival
        for _ in range(min(1 + rng.poisson(2.0), n_requests - i)):
            n = int(prompt_lens[i])
            if i % 2 == 0 and n > 16:
                body_n = max(n - len(system), 4)
                start = rng.randint(0, len(doc) - body_n)
                prompt = system + doc[start:start + body_n].tolist()
            else:
                start = rng.randint(0, len(doc) - n)
                prompt = doc[start:start + n].tolist()
            requests.append({"arrival_s": t, "prompt": prompt,
                             "max_new_tokens": int(output_lens[i])})
            i += 1
    return requests


def _trace_configs():
    """Three engine configs at EQUAL KV HBM budget. The slot baseline
    spends it as 4 contiguous max_seq rows; the paged configs spend the
    same bytes as a 64-page pool and raise CONCURRENCY instead (mixed
    Zipf lengths leave contiguous rows mostly empty)."""
    # minus one: the paged pool carries a reserved garbage page, and it
    # pays for it INSIDE the budget (usable 63 + garbage 1 = 64 pages =
    # exactly the slot layout's 1024 token-slots)
    pages = HBM_BUDGET_TOKENS // TRACE_PAGE - 1
    base = {"max_seq_len": TRACE_MAX_SEQ, "dtype": "fp32", "greedy": True,
            "prefill_buckets": [32, 64, 128, 256]}
    slot = dict(base, max_batch_size=HBM_BUDGET_TOKENS // TRACE_MAX_SEQ)
    paged = dict(base, max_batch_size=12, kv_layout="paged",
                 kv_block_size=TRACE_PAGE, num_pages=pages)
    paged_spec = dict(paged, prefix_caching=True, prefill_chunk_tokens=64,
                      speculative={"enabled": True, "method": "ngram",
                                   "num_draft_tokens": 6})
    return {"slot": slot, "paged": paged, "paged_spec": paged_spec}


def run_trace(engine, requests):
    """Replay the trace against one engine: submit each request when its
    arrival offset elapses, stepping the scheduler continuously. Returns
    the per-run metrics summary."""
    from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
    from deepspeed_tpu.utils.monitor import ServingMetrics
    if engine.prefix_cache is not None:
        # every round starts COLD: a warm prefix cache from the prior
        # round would hand the treatment config an advantage the slot
        # baseline has no analog of
        engine.prefix_cache.clear()
    metrics = ServingMetrics()
    sched = ContinuousBatchingScheduler(engine, metrics=metrics)
    pending = sorted(requests, key=lambda r: r["arrival_s"])
    t0 = time.perf_counter()
    idx = 0
    while idx < len(pending) or sched.has_work:
        now = time.perf_counter() - t0
        while idx < len(pending) and pending[idx]["arrival_s"] <= now:
            req = pending[idx]
            sched.submit(req["prompt"],
                         max_new_tokens=req["max_new_tokens"])
            # anchor TTFT at the TRACE arrival, not the (slightly
            # later) submit poll — queueing delay is the trace's point
            sched.queue[-1].arrival_t = t0 + req["arrival_s"]
            idx += 1
        if sched.has_work:
            sched.step()
        elif idx < len(pending):
            time.sleep(min(0.005, pending[idx]["arrival_s"] - now))
    wall = time.perf_counter() - t0
    snap = metrics.snapshot()
    out = {
        "wall_seconds": round(wall, 3),
        "goodput_tokens_per_sec": round(snap["completed_tokens"] / wall, 2),
        "completed_requests": snap["completed_requests"],
        "completed_tokens": snap["completed_tokens"],
        "decode_tokens_per_sec": snap["decode_tokens_per_sec"],
        "decode_steps": snap["decode_steps"],
        "ttft_p50_s": snap["ttft"]["p50_s"],
        "ttft_p95_s": snap["ttft"]["p95_s"],
        "tpot_p50_s": snap["tpot"]["p50_s"],
        "tpot_p95_s": snap["tpot"]["p95_s"],
        "mean_slot_occupancy": snap["mean_slot_occupancy"],
        "peak_queue_depth": snap["peak_queue_depth"],
        "preemptions": sched.preemptions,
    }
    if snap.get("speculative"):
        out["spec_acceptance_rate"] = snap["speculative"]["acceptance_rate"]
        out["tokens_per_decode_step"] = round(
            snap["decode_tokens"] / max(snap["decode_steps"], 1), 3)
    if engine.prefix_stats() is not None:
        out["prefix_hit_rate"] = engine.prefix_stats()["hit_rate"]
    return out


def serving_trace_main(out_path):
    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=TRACE_MAX_SEQ,
                          n_layers=2, n_heads=4, d_model=128,
                          use_flash_attention=False, remat=False)
    model = gpt2.make_gpt2_model(config=cfg)
    requests = build_trace(cfg.vocab_size)
    engines = {}
    for name, inf in _trace_configs().items():
        engines[name] = deepspeed.init_inference(
            model=model, config={"inference": inf})
        # KV budget really is equal across configs
        assert engines[name].kv.nbytes == \
            engines["slot"].kv.nbytes, (name, engines[name].kv.nbytes)
        # warmup: compile every bucket + decode/verify off the clock
        engines[name].generate(
            [r["prompt"] for r in requests[:len(inf["prefill_buckets"])]],
            max_new_tokens=8)

    rounds = 3                  # odd: the middle of the sort IS a median
    results = {name: [] for name in engines}
    for _ in range(rounds):
        # interleaved rounds: machine drift hits every config equally
        for name, engine in engines.items():
            results[name].append(run_trace(engine, requests))

    def median_run(runs):
        return sorted(runs,
                      key=lambda r: r["goodput_tokens_per_sec"])[
                          len(runs) // 2]

    configs = {name: median_run(runs) for name, runs in results.items()}
    ratio = (configs["paged_spec"]["goodput_tokens_per_sec"] /
             configs["slot"]["goodput_tokens_per_sec"])
    payload = {
        "metric": "gpt2_serving_goodput_ratio_paged_spec_vs_slot",
        "value": round(ratio, 3),
        "unit": "x",
        # acceptance floor: >= 1.5x goodput at equal HBM budget
        "vs_baseline": round(ratio / 1.5, 4),
        "extra": {
            "serving_trace": {
                "trace": {"requests": len(requests), "seed": TRACE_SEED,
                          "prompt_len_max": max(len(r["prompt"])
                                                for r in requests),
                          "output_len_max": max(r["max_new_tokens"]
                                                for r in requests),
                          "span_s": round(requests[-1]["arrival_s"], 2)},
                "hbm_budget_tokens": HBM_BUDGET_TOKENS,
                "kv_bytes_per_config": engines["slot"].kv.nbytes,
                "rounds": rounds,
                "configs": configs,
            },
            "goodput_ratio_paged_vs_slot": round(
                configs["paged"]["goodput_tokens_per_sec"] /
                configs["slot"]["goodput_tokens_per_sec"], 3),
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "backend": jax.default_backend(),
        },
    }
    line = json.dumps(payload)
    print(line)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(line + "\n")
    return 0


# ---------------------------------------------------------------------
# disaggregated rung (ISSUE 17): single paged monolith vs a 1-prefill +
# 2-decode DisaggServer fleet at equal AGGREGATE page budget, 10x load
# ---------------------------------------------------------------------

DISAGG_REQUESTS = 560             # 10x the ISSUE 7 trace
DISAGG_DECODE_HOSTS = 2


def run_disagg_trace(server_factory, requests):
    """Replay the trace against a fresh DisaggServer, mirroring
    run_trace's arrival-anchored discipline: submit each request when
    its offset elapses (TTFT anchored at the TRACE arrival), pump
    ``server.step()`` continuously. Returns (metrics summary, server)."""
    server = server_factory()
    pending = sorted(requests, key=lambda r: r["arrival_s"])
    t0 = time.perf_counter()
    idx = 0
    while idx < len(pending) or server.has_work:
        now = time.perf_counter() - t0
        while idx < len(pending) and pending[idx]["arrival_s"] <= now:
            req = pending[idx]
            server.submit(req["prompt"],
                          max_new_tokens=req["max_new_tokens"],
                          arrival_t=t0 + req["arrival_s"])
            idx += 1
        if server.has_work:
            server.step()
        elif idx < len(pending):
            time.sleep(min(0.005, pending[idx]["arrival_s"] - now))
    wall = time.perf_counter() - t0
    snap = server.metrics.snapshot()
    return {
        "wall_seconds": round(wall, 3),
        "goodput_tokens_per_sec": round(snap["completed_tokens"] / wall, 2),
        "completed_requests": snap["completed_requests"],
        "completed_tokens": snap["completed_tokens"],
        "decode_tokens_per_sec": snap["decode_tokens_per_sec"],
        "decode_steps": snap["decode_steps"],
        "ttft_p50_s": snap["ttft"]["p50_s"],
        "ttft_p95_s": snap["ttft"]["p95_s"],
        "tpot_p50_s": snap["tpot"]["p50_s"],
        "tpot_p95_s": snap["tpot"]["p95_s"],
        "mean_slot_occupancy": snap["mean_slot_occupancy"],
        "peak_queue_depth": snap["peak_queue_depth"],
        "preemptions": server.preemptions,
    }, server


def disagg_trace_main(out_path):
    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.inference.fleet import DisaggServer
    from deepspeed_tpu.utils.monitor import ServingMetrics

    cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=TRACE_MAX_SEQ,
                          n_layers=2, n_heads=4, d_model=128,
                          use_flash_attention=False, remat=False)
    model = gpt2.make_gpt2_model(config=cfg)
    requests = build_trace(cfg.vocab_size, n_requests=DISAGG_REQUESTS)

    # equal AGGREGATE budget: each fleet host owns a 64-page pool
    # (63 usable + 1 garbage); the monolith owns the fleet's whole
    # page count in one pool (191 usable + 1 garbage = 3 x 64)
    per_host = HBM_BUDGET_TOKENS // TRACE_PAGE - 1
    n_hosts = 1 + DISAGG_DECODE_HOSTS
    base = {"max_seq_len": TRACE_MAX_SEQ, "dtype": "fp32", "greedy": True,
            "prefill_buckets": [32, 64, 128, 256], "kv_layout": "paged",
            "kv_block_size": TRACE_PAGE, "prefill_chunk_tokens": 64}
    mono = deepspeed.init_inference(model=model, config={"inference": dict(
        base, max_batch_size=12 * DISAGG_DECODE_HOSTS,
        num_pages=n_hosts * (per_host + 1) - 1)})
    pre = deepspeed.init_inference(model=model, config={"inference": dict(
        base, max_batch_size=4, num_pages=per_host,
        fleet={"enabled": True, "role": "prefill"})})
    decs = [deepspeed.init_inference(model=model, config={"inference": dict(
        base, max_batch_size=12, num_pages=per_host,
        fleet={"enabled": True, "role": "decode"})})
        for _ in range(DISAGG_DECODE_HOSTS)]
    fleet_nbytes = pre.kv.nbytes + sum(d.kv.nbytes for d in decs)
    assert mono.kv.nbytes == fleet_nbytes, (mono.kv.nbytes, fleet_nbytes)

    def make_server():
        return DisaggServer(
            {"prefill0": pre},
            {"decode{}".format(i): d for i, d in enumerate(decs)},
            metrics=ServingMetrics())

    # warmup: compile every bucket + the decode fns off the clock, on
    # the monolith AND through the fleet wire
    warm = requests[:len(base["prefill_buckets"])]
    mono.generate([r["prompt"] for r in warm], max_new_tokens=8)
    warm_server = make_server()
    for req in warm:
        warm_server.submit(req["prompt"], max_new_tokens=8)
    warm_server.run()

    rounds = 3                  # odd: the middle of the sort IS a median
    singles, disaggs, servers = [], [], []
    for _ in range(rounds):
        # interleaved rounds: machine drift hits every config equally
        singles.append(run_trace(mono, requests))
        result, server = run_disagg_trace(make_server, requests)
        disaggs.append(result)
        servers.append(server)

    def median_i(runs):
        order = sorted(range(len(runs)),
                       key=lambda i: runs[i]["goodput_tokens_per_sec"])
        return order[len(runs) // 2]

    mi = median_i(disaggs)
    configs = {"single": singles[median_i(singles)], "disagg": disaggs[mi]}
    server = servers[mi]
    stats = server.handoff_stats()
    ratio = (configs["disagg"]["goodput_tokens_per_sec"] /
             configs["single"]["goodput_tokens_per_sec"])
    payload = {
        "metric": "gpt2_serving_disagg_goodput_ratio_vs_single",
        "value": round(ratio, 3),
        "unit": "x",
        # acceptance floor: the fleet holds >= 0.8x the monolith's
        # goodput at equal aggregate budget while paying the real
        # serialized-handoff wire cost (its win is TTFT isolation)
        "vs_baseline": round(ratio / 0.8, 4),
        "extra": {
            "serving_trace": {
                "trace": {"requests": len(requests), "seed": TRACE_SEED,
                          "prompt_len_max": max(len(r["prompt"])
                                                for r in requests),
                          "output_len_max": max(r["max_new_tokens"]
                                                for r in requests),
                          "span_s": round(requests[-1]["arrival_s"], 2)},
                "hbm_budget_tokens": n_hosts * HBM_BUDGET_TOKENS,
                "kv_bytes_per_config": mono.kv.nbytes,
                "rounds": rounds,
                "configs": configs,
                "disagg": {
                    "prefill_hosts": 1,
                    "decode_hosts": DISAGG_DECODE_HOSTS,
                    "handoff": {"handoffs": stats["handoffs"],
                                "payload_bytes": stats["payload_bytes"]},
                    "router_decisions": server.router.decision_counts(),
                },
            },
            "ttft_p95_ratio_single_vs_disagg": round(
                configs["single"]["ttft_p95_s"] /
                max(configs["disagg"]["ttft_p95_s"], 1e-9), 3),
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "backend": jax.default_backend(),
        },
    }
    line = json.dumps(payload)
    print(line)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(line + "\n")
    return 0


if __name__ == "__main__":
    if "--disagg" in sys.argv:
        out = "tests/perf/BENCH_SERVING_r17.json"
        if "--out" in sys.argv:
            idx = sys.argv.index("--out") + 1
            if idx >= len(sys.argv):
                emit_error_json(
                    "gpt2_serving_disagg_goodput_ratio_vs_single",
                    ValueError("--out needs a path argument"))
                sys.exit(1)
            out = sys.argv[idx]
        try:
            sys.exit(disagg_trace_main(out))
        except Exception as err:  # noqa: BLE001 - parseable JSON always
            emit_error_json("gpt2_serving_disagg_goodput_ratio_vs_single",
                            err)
            sys.exit(1)
    if "--serving-trace" in sys.argv:
        out = "tests/perf/BENCH_SERVING.json"
        if "--out" in sys.argv:
            idx = sys.argv.index("--out") + 1
            if idx >= len(sys.argv):
                emit_error_json(
                    "gpt2_serving_goodput_ratio_paged_spec_vs_slot",
                    ValueError("--out needs a path argument"))
                sys.exit(1)
            out = sys.argv[idx]
        try:
            sys.exit(serving_trace_main(out))
        except Exception as err:  # noqa: BLE001 - parseable JSON always
            emit_error_json("gpt2_serving_goodput_ratio_paged_spec_vs_slot",
                            err)
            sys.exit(1)
    try:
        sys.exit(main())
    except Exception as err:  # noqa: BLE001 - emit parseable JSON, not a trace
        emit_error_json("gpt2_inference_decode_tokens_per_sec_per_chip", err)
        sys.exit(1)
