"""Benchmark: GPT-2 (350M) causal-LM pretraining throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline = measured MFU / 0.45 — the repo's north-star target
(BASELINE.json: Megatron-GPT2 ZeRO-2 at >=45% MFU).
"""
import json
import sys
import time

import numpy as np


def peak_for(device):
    """Peak bf16 flops/s per chip — the table lives with the telemetry
    subsystem now (deepspeed_tpu/telemetry/mfu.py) so the per-step
    StepRecords and this bench price MFU identically."""
    from deepspeed_tpu.telemetry.mfu import peak_flops_for
    return peak_flops_for(device)


def scratch_telemetry_dir(prefix):
    """Disposable telemetry output dir: the rolling snapshot rides the
    bench JSON line, so the JSONL dir is scratch — removed at process
    exit (atexit runs LIFO, so the collector's own exit handler closes
    the JSONL handle first). Shared by bench_inference.py and the
    telemetry-overhead bench; __graft_entry__._tele_cfg inlines the same
    pattern to stay importable without the repo root on sys.path.
    Without this every run leaked a /tmp directory."""
    import atexit
    import shutil
    import tempfile
    d = tempfile.mkdtemp(prefix=prefix)
    atexit.register(shutil.rmtree, d, ignore_errors=True)
    return d


def safe_default_backend(retries=3, backoff_s=2.0):
    """``jax.default_backend()`` with BOUNDED retry + CPU fallback: a
    broken TPU plugin raises RuntimeError out of backend init (BENCH_r05
    failed there), and a bench run must always emit parseable JSON — so
    retry the probe a few times (transient tunnel hiccups), then force
    the CPU client, and only propagate after the CPU client itself fails
    (main()'s handler still emits the error JSON line in that case)."""
    import jax

    def _drop_backends():
        try:
            import jax.extend.backend as _jeb
            _jeb.clear_backends()
        except Exception:  # noqa: BLE001 - older jax spelling
            try:
                jax.clear_backends()
            except Exception:  # noqa: BLE001
                pass

    last_err = None
    for attempt in range(retries):
        try:
            return jax.default_backend()
        except Exception as err:  # noqa: BLE001 - any backend-init failure
            last_err = err
            print("bench: backend probe failed (attempt {}/{}: {}); "
                  "retrying".format(attempt + 1, retries,
                                    str(err)[:120]), file=sys.stderr)
            _drop_backends()
            time.sleep(backoff_s * (attempt + 1))
    print("bench: backend init failed {} times ({}); forcing CPU".format(
        retries, str(last_err)[:120]), file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    _drop_backends()
    return jax.default_backend()


def main():
    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    on_tpu = safe_default_backend() == "tpu"
    seq = 1024 if on_tpu else 128
    steps = 20 if on_tpu else 3
    warmup = 3 if on_tpu else 1
    # GPT-2 medium (350M): best measured MFU on one v5e chip — d_model
    # 1024 tiles the MXU better than 125M's 768 (sweep:
    # tests/perf/sweep_gpt2_mfu.py). bf16 Adam moments + bf16 grad-accum
    # (lossless at gas=1) free ~2.8 GB of optimizer-state HBM, which
    # buys REMAT OFF at micro_batch 16-20 — executed flops drop from
    # 8/6x to 1x model flops and the measured MFU jumps 0.507 -> 0.587
    # (docs/roofline_gpt2_medium_v5e.md has the full measured grid).
    # Fallback ladder degrades remat/micro-batch on compiler OOM.
    # attempts: (micro_batch, remat, bf16_state)
    attempts = ([(20, False, True), (16, False, True), (24, True, True),
                 (24, True, False), (16, True, False), (8, True, False)]
                if on_tpu else [(2, False, False)])

    if not on_tpu:
        cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=seq, n_layers=2,
                              n_heads=4, d_model=128,
                              use_flash_attention=False, remat=False)

    for micro_batch, remat, bf16_state in attempts:
        if on_tpu:
            cfg = gpt2.config_for("gpt2_medium", max_seq_len=seq,
                                  remat=remat, loss_chunk=128)
        n_params = gpt2.num_params(cfg)
        model = gpt2.make_gpt2_model(config=cfg)
        # the CPU rung runs the classic-offload step so the bench
        # exercises a MULTI-segment plan — that is where the plan
        # rewrite passes (hoist/fuse/widen, docs/executor.md) have
        # segments to move, and extra.executor.rewrites below records
        # their predicted-vs-measured exposed-wait delta
        zero = {"stage": 2} if on_tpu else \
            {"stage": 2, "cpu_offload": True, "sub_group_size": 65536}
        ds_config = {
            "train_micro_batch_size_per_gpu": micro_batch,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": zero,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "runtime": {"executor": "on", "executor_rewrites": {
                "passes": ["hoist", "fuse", "widen"]}},
            "steps_per_print": 10 ** 9,
            # per-step StepRecords; the final rolling snapshot lands in
            # the JSON line below so BENCH_* files carry MFU/phase/comm
            # trajectories from now on
            "telemetry": {"enabled": True,
                          "output_path": scratch_telemetry_dir(
                              "bench_telemetry_"),
                          # fleet export plane (docs/fleet.md): the
                          # final /metrics scrape is embedded under
                          # extra.metrics so every rung carries its
                          # exported series (port 0 = ephemeral)
                          "metrics": {"enabled": True, "port": 0}},
        }
        if bf16_state:
            ds_config["optimizer"]["params"]["moments_dtype"] = "bf16"
            ds_config["data_types"] = {"grad_accum_dtype": "bf16"}
        engine, _, _, _ = deepspeed.initialize(model=model,
                                               config_params=ds_config)

        rng = np.random.RandomState(0)
        global_batch = micro_batch * engine.dp_world_size
        ids = rng.randint(0, cfg.vocab_size, size=(1, global_batch, seq)) \
            .astype(np.int32)
        batch = (ids, ids.copy())

        try:
            # compile + warmup. float(loss) (not block_until_ready) is the
            # sync: through the axon tunnel execution is lazy and only a
            # literal value fetch forces it; steps chain sequentially
            # through the donated state, so fetching the last loss fences
            # the whole loop.
            for _ in range(warmup):
                loss = engine.train_batch(batch=batch)
            float(loss)

            t0 = time.time()
            for _ in range(steps):
                loss = engine.train_batch(batch=batch)
            float(loss)
            dt = time.time() - t0
            break
        except Exception as err:  # noqa: BLE001 - compiler OOM etc.
            print("bench: micro_batch={} failed ({}), falling back".format(
                micro_batch, str(err)[:80]), file=sys.stderr)
            # free the failed attempt's state before building the next
            # engine, or the retry runs with double the HBM footprint
            del engine, model, batch
            jax.clear_caches()
    else:
        raise RuntimeError("no benchmark configuration compiled")

    # per-step collective bytes-on-wire for the run's ZeRO config vs the
    # flat-fp32 baseline — the comm-efficiency win stays visible in the
    # JSON record even on the CPU fallback rung where nothing is measured
    # on a real interconnect
    try:
        from deepspeed_tpu.runtime.comm.wire import \
            estimate_engine_comm_bytes
        comm = estimate_engine_comm_bytes(engine)
    except Exception as err:  # noqa: BLE001 - estimator must never kill bench
        comm = {"error": str(err)[:200]}

    tokens_per_step = global_batch * seq
    tokens_per_sec = tokens_per_step * steps / dt
    # flops/token: 6N for the dense path + 12*L*d*s for attention scores/ctx
    flops_per_token = 6.0 * n_params + 12.0 * cfg.n_layers * cfg.d_model * seq
    achieved = tokens_per_sec * flops_per_token / jax.device_count()
    mfu = achieved / peak_for(jax.devices()[0])

    print(json.dumps({
        "metric": "gpt2_350m_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / jax.device_count(), 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "loss": round(float(loss), 4),
            "seq_len": seq,
            "global_batch": global_batch,
            "params": n_params,
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "backend": jax.default_backend(),
            "rung": {"micro_batch": micro_batch, "remat": remat,
                     "bf16_state": bf16_state},
            "comm": comm,
            # segment-executor accounting (docs/executor.md): plan
            # size and per-kind walls of the step plans this run
            # executed (the fused path is a one-segment plan; the
            # offload microbench reports the multi-segment plans)
            "executor": engine.executor_snapshot(),
            # omitted (not {}) on non-writer processes: the schema
            # checker rejects an empty snapshot (bin/check_bench_schema)
            **({"telemetry": engine.telemetry_snapshot()}
               if engine.telemetry is not None else {}),
            # final Prometheus scrape of the fleet metrics plane
            # (series count + exposition text; None-safe when the
            # metrics section is off or this is a non-writer process)
            **({"metrics": engine.telemetry.metrics_scrape()}
               if engine.telemetry is not None and
               engine.telemetry.metrics is not None else {}),
        },
    }))


def emit_error_json(metric, err):
    """Last-resort bench output: one parseable JSON line naming the
    failure (shared by bench.py and bench_inference.py)."""
    print(json.dumps({
        "metric": metric,
        "value": None, "unit": "tokens/s/chip", "vs_baseline": None,
        "error": "{}: {}".format(type(err).__name__, str(err)[:400]),
    }))


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as err:  # noqa: BLE001 - emit parseable JSON, not a trace
        emit_error_json("gpt2_350m_pretrain_tokens_per_sec_per_chip", err)
        sys.exit(1)
