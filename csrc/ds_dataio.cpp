// Native data IO: mmap'd indexed token dataset + threaded batch prefetch.
//
// TPU-native counterpart of the reference era's C++ dataset helpers (the
// Megatron-GPT2 workloads the reference drives use mmap'd .bin/.idx token
// files with native gather helpers; DeepSpeed itself wraps torch
// DataLoader workers, deepspeed/runtime/dataloader.py). On a TPU host the
// input pipeline runs on CPU while the chip computes, so the reader is:
//   * zero-copy: documents live in one mmap'd .bin, never read up front;
//   * OpenMP batch gather into caller-provided buffers;
//   * double-buffered background prefetch (one producer thread filling a
//     ring while the host thread feeds the previous batch to the device).
//
// File format (created by deepspeed_tpu.runtime.data.indexed_dataset):
//   <name>.bin  raw little-endian tokens, dtype int32 or uint16
//   <name>.idx  header: magic "DSTPUIDX" (8B), u32 version, u32 dtype code
//               (4=int32, 2=uint16), u64 n_docs; then (n_docs+1) u64
//               offsets (token units) into the .bin
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Dataset {
  int bin_fd = -1;
  const uint8_t* bin = nullptr;   // mmap'd token data
  size_t bin_bytes = 0;
  uint32_t dtype_code = 4;        // 4=int32, 2=uint16
  uint64_t n_docs = 0;
  std::vector<uint64_t> offsets;  // n_docs + 1, token units

  // prefetch state
  std::thread producer;
  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::vector<int32_t> ring[2];
  int ready[2] = {0, 0};          // slot filled?
  int next_fill = 0, next_read = 0;
  std::atomic<bool> stop{false};
  uint64_t cursor = 0;            // next sample index
  int batch = 0, seq = 0;
  uint64_t n_samples = 0;         // contiguous seq-token samples available
};

uint64_t read_u64(FILE* f) {
  uint64_t v = 0;
  if (fread(&v, sizeof(v), 1, f) != 1) return 0;
  return v;
}

int32_t token_at(const Dataset* ds, uint64_t i) {
  if (ds->dtype_code == 2) {
    return reinterpret_cast<const uint16_t*>(ds->bin)[i];
  }
  return reinterpret_cast<const int32_t*>(ds->bin)[i];
}

}  // namespace

extern "C" {

// Open <prefix>.idx / <prefix>.bin. Returns opaque handle or null.
void* ds_dataio_open(const char* idx_path, const char* bin_path) {
  FILE* f = fopen(idx_path, "rb");
  if (!f) return nullptr;
  char magic[8];
  if (fread(magic, 1, 8, f) != 8 || memcmp(magic, "DSTPUIDX", 8) != 0) {
    fclose(f);
    return nullptr;
  }
  uint32_t version = 0, dtype_code = 0;
  if (fread(&version, 4, 1, f) != 1 || fread(&dtype_code, 4, 1, f) != 1 ||
      version != 1 || (dtype_code != 4 && dtype_code != 2)) {
    fclose(f);
    return nullptr;
  }
  auto* ds = new Dataset();
  ds->dtype_code = dtype_code;
  ds->n_docs = read_u64(f);
  ds->offsets.resize(ds->n_docs + 1);
  size_t got = fread(ds->offsets.data(), sizeof(uint64_t), ds->n_docs + 1, f);
  fclose(f);
  if (got != ds->n_docs + 1) {
    delete ds;
    return nullptr;
  }

  ds->bin_fd = open(bin_path, O_RDONLY);
  if (ds->bin_fd < 0) {
    delete ds;
    return nullptr;
  }
  struct stat st;
  fstat(ds->bin_fd, &st);
  ds->bin_bytes = static_cast<size_t>(st.st_size);
  // truncated/mismatched .bin would SIGBUS on a past-the-end mmap read in
  // the producer thread; fail the open cleanly instead (caller falls back)
  if (ds->offsets.back() * ds->dtype_code > ds->bin_bytes) {
    close(ds->bin_fd);
    delete ds;
    return nullptr;
  }
  ds->bin = static_cast<const uint8_t*>(
      mmap(nullptr, ds->bin_bytes, PROT_READ, MAP_PRIVATE, ds->bin_fd, 0));
  if (ds->bin == MAP_FAILED) {
    close(ds->bin_fd);
    delete ds;
    return nullptr;
  }
  // advise the kernel we'll stream through it
  madvise(const_cast<uint8_t*>(ds->bin), ds->bin_bytes, MADV_WILLNEED);
  return ds;
}

int64_t ds_dataio_num_docs(void* h) {
  return static_cast<Dataset*>(h)->n_docs;
}

int64_t ds_dataio_num_tokens(void* h) {
  auto* ds = static_cast<Dataset*>(h);
  return ds->offsets.back();
}

int64_t ds_dataio_doc_len(void* h, int64_t doc) {
  auto* ds = static_cast<Dataset*>(h);
  return ds->offsets[doc + 1] - ds->offsets[doc];
}

// Copy one document's tokens into out (int32), returns length copied
// (clamped to max_len).
int64_t ds_dataio_get_doc(void* h, int64_t doc, int32_t* out,
                          int64_t max_len) {
  auto* ds = static_cast<Dataset*>(h);
  uint64_t start = ds->offsets[doc], end = ds->offsets[doc + 1];
  int64_t n = static_cast<int64_t>(end - start);
  if (n > max_len) n = max_len;
#pragma omp parallel for if (n > 1 << 16)
  for (int64_t i = 0; i < n; ++i) out[i] = token_at(ds, start + i);
  return n;
}

// Gather a batch of fixed-length samples by sample index, treating the
// whole .bin as one token stream chopped into seq-length windows (the
// GPT-2 pretraining convention). out is (n_samples, seq) int32.
void ds_dataio_batch(void* h, const int64_t* sample_idx, int64_t n_samples,
                     int64_t seq, int32_t* out) {
  auto* ds = static_cast<Dataset*>(h);
  const uint64_t total = ds->offsets.back();
#pragma omp parallel for
  for (int64_t s = 0; s < n_samples; ++s) {
    uint64_t start = static_cast<uint64_t>(sample_idx[s]) * seq;
    for (int64_t t = 0; t < seq; ++t) {
      uint64_t pos = start + t;
      out[s * seq + t] = pos < total ? token_at(ds, pos) : 0;
    }
  }
}

// ---- background prefetch: seq-window samples in linear-congruential
// shuffled order, double-buffered ----

static void fill_slot(Dataset* ds, int slot) {
  const int64_t b = ds->batch, seq = ds->seq;
  std::vector<int64_t> idx(b);
  for (int64_t i = 0; i < b; ++i) {
    // Weyl-sequence shuffle over n_samples: full-period, stateless
    uint64_t j = (ds->cursor + i) % ds->n_samples;
    idx[i] = (j * 2654435761ULL + 12345) % ds->n_samples;
  }
  ds->cursor += b;
  ds->ring[slot].resize(b * seq);
  ds_dataio_batch(ds, idx.data(), b, seq, ds->ring[slot].data());
}

static void producer_loop(Dataset* ds) {
  while (!ds->stop.load()) {
    std::unique_lock<std::mutex> lk(ds->mu);
    ds->cv_empty.wait(lk, [ds] {
      return ds->stop.load() || !ds->ready[ds->next_fill];
    });
    if (ds->stop.load()) return;
    int slot = ds->next_fill;
    lk.unlock();
    fill_slot(ds, slot);
    lk.lock();
    ds->ready[slot] = 1;
    ds->next_fill ^= 1;
    ds->cv_full.notify_one();
  }
}

int ds_dataio_start_prefetch(void* h, int64_t batch, int64_t seq) {
  auto* ds = static_cast<Dataset*>(h);
  if (ds->producer.joinable()) return -1;
  ds->batch = static_cast<int>(batch);
  ds->seq = static_cast<int>(seq);
  ds->n_samples = ds->offsets.back() / seq;
  if (ds->n_samples == 0) return -2;
  ds->stop.store(false);
  ds->producer = std::thread(producer_loop, ds);
  return 0;
}

// Blocks until the next prefetched batch is ready, copies it into out
// ((batch, seq) int32) and wakes the producer for the slot.
int ds_dataio_next(void* h, int32_t* out) {
  auto* ds = static_cast<Dataset*>(h);
  std::unique_lock<std::mutex> lk(ds->mu);
  ds->cv_full.wait(lk, [ds] { return ds->ready[ds->next_read] != 0; });
  int slot = ds->next_read;
  memcpy(out, ds->ring[slot].data(), ds->ring[slot].size() * sizeof(int32_t));
  ds->ready[slot] = 0;
  ds->next_read ^= 1;
  ds->cv_empty.notify_one();
  return 0;
}

void ds_dataio_close(void* h) {
  auto* ds = static_cast<Dataset*>(h);
  if (ds->producer.joinable()) {
    ds->stop.store(true);
    ds->cv_empty.notify_all();
    ds->cv_full.notify_all();
    ds->producer.join();
  }
  if (ds->bin && ds->bin != MAP_FAILED) {
    munmap(const_cast<uint8_t*>(ds->bin), ds->bin_bytes);
  }
  if (ds->bin_fd >= 0) close(ds->bin_fd);
  delete ds;
}

}  // extern "C"
