// Native data IO: mmap'd indexed token dataset + threaded batch prefetch.
//
// TPU-native counterpart of the reference era's C++ dataset helpers (the
// Megatron-GPT2 workloads the reference drives use mmap'd .bin/.idx token
// files with native gather helpers; DeepSpeed itself wraps torch
// DataLoader workers, deepspeed/runtime/dataloader.py). On a TPU host the
// input pipeline runs on CPU while the chip computes, so the reader is:
//   * zero-copy: documents live in one mmap'd .bin, never read up front;
//   * OpenMP batch gather into caller-provided buffers;
//   * double-buffered background prefetch (one producer thread filling a
//     ring while the host thread feeds the previous batch to the device).
//
// File format (created by deepspeed_tpu.runtime.data.indexed_dataset):
//   <name>.bin  raw little-endian tokens, dtype int32 or uint16
//   <name>.idx  header: magic "DSTPUIDX" (8B), u32 version, u32 dtype code
//               (4=int32, 2=uint16), u64 n_docs; then (n_docs+1) u64
//               offsets (token units) into the .bin
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Dataset {
  int bin_fd = -1;
  const uint8_t* bin = nullptr;   // mmap'd token data
  size_t bin_bytes = 0;
  uint32_t dtype_code = 4;        // 4=int32, 2=uint16
  uint64_t n_docs = 0;
  std::vector<uint64_t> offsets;  // n_docs + 1, token units

  // prefetch state
  std::thread producer;
  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::vector<int32_t> ring[2];
  int ready[2] = {0, 0};          // slot filled?
  int next_fill = 0, next_read = 0;
  std::atomic<bool> stop{false};
  // threads inside ds_dataio_next; atomic and incremented BEFORE the
  // mutex acquisition so close()'s drain also sees consumers still
  // blocked on the lock itself
  std::atomic<int> consumers{0};
  uint64_t cursor = 0;            // next sample index
  int batch = 0, seq = 0;
  uint64_t n_samples = 0;         // contiguous seq-token samples available
};

uint64_t read_u64(FILE* f) {
  uint64_t v = 0;
  if (fread(&v, sizeof(v), 1, f) != 1) return 0;
  return v;
}

int32_t token_at(const Dataset* ds, uint64_t i) {
  if (ds->dtype_code == 2) {
    return reinterpret_cast<const uint16_t*>(ds->bin)[i];
  }
  return reinterpret_cast<const int32_t*>(ds->bin)[i];
}

}  // namespace

extern "C" {

// Open <prefix>.idx / <prefix>.bin. Returns opaque handle or null.
void* ds_dataio_open(const char* idx_path, const char* bin_path) {
  FILE* f = fopen(idx_path, "rb");
  if (!f) return nullptr;
  char magic[8];
  if (fread(magic, 1, 8, f) != 8 || memcmp(magic, "DSTPUIDX", 8) != 0) {
    fclose(f);
    return nullptr;
  }
  uint32_t version = 0, dtype_code = 0;
  if (fread(&version, 4, 1, f) != 1 || fread(&dtype_code, 4, 1, f) != 1 ||
      version != 1 || (dtype_code != 4 && dtype_code != 2)) {
    fclose(f);
    return nullptr;
  }
  auto* ds = new Dataset();
  ds->dtype_code = dtype_code;
  ds->n_docs = read_u64(f);
  ds->offsets.resize(ds->n_docs + 1);
  size_t got = fread(ds->offsets.data(), sizeof(uint64_t), ds->n_docs + 1, f);
  fclose(f);
  if (got != ds->n_docs + 1) {
    delete ds;
    return nullptr;
  }

  ds->bin_fd = open(bin_path, O_RDONLY);
  if (ds->bin_fd < 0) {
    delete ds;
    return nullptr;
  }
  struct stat st;
  fstat(ds->bin_fd, &st);
  ds->bin_bytes = static_cast<size_t>(st.st_size);
  // truncated/mismatched .bin would SIGBUS on a past-the-end mmap read in
  // the producer thread; fail the open cleanly instead (caller falls back)
  if (ds->offsets.back() * ds->dtype_code > ds->bin_bytes) {
    close(ds->bin_fd);
    delete ds;
    return nullptr;
  }
  ds->bin = static_cast<const uint8_t*>(
      mmap(nullptr, ds->bin_bytes, PROT_READ, MAP_PRIVATE, ds->bin_fd, 0));
  if (ds->bin == MAP_FAILED) {
    close(ds->bin_fd);
    delete ds;
    return nullptr;
  }
  // advise the kernel we'll stream through it
  madvise(const_cast<uint8_t*>(ds->bin), ds->bin_bytes, MADV_WILLNEED);
  return ds;
}

int64_t ds_dataio_num_docs(void* h) {
  return static_cast<Dataset*>(h)->n_docs;
}

int64_t ds_dataio_num_tokens(void* h) {
  auto* ds = static_cast<Dataset*>(h);
  return ds->offsets.back();
}

int64_t ds_dataio_doc_len(void* h, int64_t doc) {
  auto* ds = static_cast<Dataset*>(h);
  return ds->offsets[doc + 1] - ds->offsets[doc];
}

// Copy one document's tokens into out (int32), returns length copied
// (clamped to max_len).
int64_t ds_dataio_get_doc(void* h, int64_t doc, int32_t* out,
                          int64_t max_len) {
  auto* ds = static_cast<Dataset*>(h);
  uint64_t start = ds->offsets[doc], end = ds->offsets[doc + 1];
  int64_t n = static_cast<int64_t>(end - start);
  if (n > max_len) n = max_len;
#pragma omp parallel for if (n > 1 << 16)
  for (int64_t i = 0; i < n; ++i) out[i] = token_at(ds, start + i);
  return n;
}

// Gather a batch of fixed-length samples by sample index, treating the
// whole .bin as one token stream chopped into seq-length windows (the
// GPT-2 pretraining convention). out is (n_samples, seq) int32.
void ds_dataio_batch(void* h, const int64_t* sample_idx, int64_t n_samples,
                     int64_t seq, int32_t* out) {
  auto* ds = static_cast<Dataset*>(h);
  const uint64_t total = ds->offsets.back();
#pragma omp parallel for
  for (int64_t s = 0; s < n_samples; ++s) {
    uint64_t start = static_cast<uint64_t>(sample_idx[s]) * seq;
    for (int64_t t = 0; t < seq; ++t) {
      uint64_t pos = start + t;
      out[s * seq + t] = pos < total ? token_at(ds, pos) : 0;
    }
  }
}

// ---- background prefetch: seq-window samples in linear-congruential
// shuffled order, double-buffered ----

static void fill_slot(Dataset* ds, int slot) {
  const int64_t b = ds->batch, seq = ds->seq;
  std::vector<int64_t> idx(b);
  // Epoch-varying affine shuffle. Every multiplier is a prime >= the
  // enforced n_samples bound (2654435761), hence coprime with n_samples
  // -> each epoch's map is a bijection; j*mult < 2^32*2^32 cannot wrap
  // uint64, and the additive term is reduced mod n BEFORE the sum (a
  // wrap of the sum would split the map and break the bijection).
  // Varying the MULTIPLIER per epoch (not just the offset) changes the
  // successor structure of the permutation — a constant-only mix would
  // merely rotate one fixed cyclic order each epoch. MUST stay in
  // lockstep with NativePrefetchLoader._indices (indexed_dataset.py).
  static const uint64_t kMult[16] = {
      2654435761ULL, 2754435769ULL, 2854435811ULL, 2954435791ULL,
      3054435863ULL, 3154435859ULL, 3254435857ULL, 3354435823ULL,
      3454435837ULL, 3554435839ULL, 3654435857ULL, 3754435859ULL,
      3854435863ULL, 3954435869ULL, 4054435873ULL, 4154435867ULL};
  for (int64_t i = 0; i < b; ++i) {
    uint64_t pos = ds->cursor + i;
    uint64_t j = pos % ds->n_samples;
    uint64_t epoch = pos / ds->n_samples;
    uint64_t c = (12345 + epoch * 0x9E3779B97F4A7C15ULL) % ds->n_samples;
    idx[i] = (j * kMult[epoch % 16] % ds->n_samples + c) % ds->n_samples;
  }
  ds->cursor += b;
  ds->ring[slot].resize(b * seq);
  ds_dataio_batch(ds, idx.data(), b, seq, ds->ring[slot].data());
}

static void producer_loop(Dataset* ds) {
  while (!ds->stop.load()) {
    std::unique_lock<std::mutex> lk(ds->mu);
    ds->cv_empty.wait(lk, [ds] {
      return ds->stop.load() || !ds->ready[ds->next_fill];
    });
    if (ds->stop.load()) return;
    int slot = ds->next_fill;
    lk.unlock();
    fill_slot(ds, slot);
    lk.lock();
    ds->ready[slot] = 1;
    ds->next_fill ^= 1;
    ds->cv_full.notify_one();
  }
}

int ds_dataio_start_prefetch(void* h, int64_t batch, int64_t seq) {
  auto* ds = static_cast<Dataset*>(h);
  if (ds->producer.joinable()) return -1;
  ds->batch = static_cast<int>(batch);
  ds->seq = static_cast<int>(seq);
  ds->n_samples = ds->offsets.back() / seq;
  if (ds->n_samples == 0) return -2;
  // bijection precondition of the affine shuffle in fill_slot(): the
  // multiplier must be coprime with n_samples and j*mult must not wrap
  // 2^64 — both guaranteed by n_samples < 2654435761 (prime)
  if (ds->n_samples >= 2654435761ULL) return -3;
  ds->stop.store(false);
  ds->producer = std::thread(producer_loop, ds);
  return 0;
}

// Blocks until the next prefetched batch is ready, copies it into out
// ((batch, seq) int32) and wakes the producer for the slot.
int ds_dataio_next(void* h, int32_t* out) {
  auto* ds = static_cast<Dataset*>(h);
  ds->consumers.fetch_add(1);
  std::unique_lock<std::mutex> lk(ds->mu);
  // stop must be part of the predicate: a consumer blocked here while
  // another thread calls ds_dataio_close would otherwise wait forever.
  ds->cv_full.wait(lk, [ds] {
    return ds->stop.load() || ds->ready[ds->next_read] != 0;
  });
  if (ds->stop.load() && ds->ready[ds->next_read] == 0) {
    ds->consumers.fetch_sub(1);  // under the lock: drain can't miss it
    ds->cv_empty.notify_all();   // wake close()'s drain wait
    return -1;
  }
  int slot = ds->next_read;
  memcpy(out, ds->ring[slot].data(), ds->ring[slot].size() * sizeof(int32_t));
  ds->ready[slot] = 0;
  ds->next_read ^= 1;
  ds->consumers.fetch_sub(1);
  ds->cv_empty.notify_all();
  return 0;
}

// Phase 1 of shutdown: stop the producer and wake every consumer blocked
// in ds_dataio_next (they return -1), WITHOUT freeing the Dataset. Lets a
// caller quiesce its own threads before ds_dataio_close frees memory —
// the two-phase protocol NativePrefetchLoader/IndexedDataset.close use.
void ds_dataio_stop(void* h) {
  auto* ds = static_cast<Dataset*>(h);
  if (ds->producer.joinable()) {
    // stop must be stored under the mutex: a waiter that has evaluated its
    // predicate (stop still false) but not yet released the mutex to block
    // would otherwise miss the notify forever (lost wakeup), deadlocking
    // both the drain below and producer.join()
    {
      std::lock_guard<std::mutex> lk(ds->mu);
      ds->stop.store(true);
    }
    ds->cv_empty.notify_all();
    ds->cv_full.notify_all();
    ds->producer.join();
    // drain: wait until every consumer inside ds_dataio_next has left
    // before the Dataset (and its mutex) is freed below. A simple
    // lock_guard barrier is NOT enough — a notified consumer re-acquires
    // the mutex in unspecified order and could still be blocked on it when
    // delete runs; nor is a lock-protected count — the atomic is bumped
    // BEFORE the lock so threads still blocked acquiring it are counted
    // too. A call racing close() before its fetch_add executes is caller
    // misuse (use-after-close) and not defended.
    {
      std::unique_lock<std::mutex> lk(ds->mu);
      ds->cv_empty.wait(lk, [ds] { return ds->consumers.load() == 0; });
    }
  }
}

void ds_dataio_close(void* h) {
  auto* ds = static_cast<Dataset*>(h);
  ds_dataio_stop(h);
  if (ds->bin && ds->bin != MAP_FAILED) {
    munmap(const_cast<uint8_t*>(ds->bin), ds->bin_bytes);
  }
  if (ds->bin_fd >= 0) close(ds->bin_fd);
  delete ds;
}

}  // extern "C"
