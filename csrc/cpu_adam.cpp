// Host-offloaded Adam/AdamW step, vectorized for the host SIMD ISA.
//
// TPU-native counterpart of the reference's csrc/adam/cpu_adam.cpp
// (Adam_Optimizer::Step with AVX-256/512 intrinsics + OpenMP): here the
// vectorization is left to the compiler (-O3 -march=native with `omp simd`
// pragmas reaches the same AVX/NEON code paths portably) and threading to
// OpenMP. Driven from JAX via a pure_callback during ZeRO-Offload optimizer
// steps (deepspeed_tpu/ops/adam/cpu_adam_native.py).
//
// All buffers are fp32, contiguous, caller-owned. p/m/v are updated
// in place; g is read-only. bc1/bc2 are the precomputed bias-correction
// denominators (1 - beta^t), 1.0 when bias correction is off.

#include <cmath>
#include <cstdint>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

void ds_cpu_adam_step(float* __restrict__ p,
                      const float* __restrict__ g,
                      float* __restrict__ m,
                      float* __restrict__ v,
                      int64_t n,
                      float lr,
                      float beta1,
                      float beta2,
                      float eps,
                      float weight_decay,
                      float bc1,
                      float bc2,
                      int adam_w_mode) {
  const float one_minus_beta1 = 1.0f - beta1;
  const float one_minus_beta2 = 1.0f - beta2;
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);

  if (adam_w_mode) {
    // Decoupled weight decay (AdamW): update += wd * p, applied post-moment.
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
      const float gi = g[i];
      const float mi = beta1 * m[i] + one_minus_beta1 * gi;
      const float vi = beta2 * v[i] + one_minus_beta2 * gi * gi;
      m[i] = mi;
      v[i] = vi;
      const float denom = std::sqrt(vi) * inv_bc2_sqrt + eps;
      const float update = (mi * inv_bc1) / denom + weight_decay * p[i];
      p[i] -= lr * update;
    }
  } else {
    // Classic L2: decay folded into the gradient before the moments.
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
      const float gi = g[i] + weight_decay * p[i];
      const float mi = beta1 * m[i] + one_minus_beta1 * gi;
      const float vi = beta2 * v[i] + one_minus_beta2 * gi * gi;
      m[i] = mi;
      v[i] = vi;
      const float denom = std::sqrt(vi) * inv_bc2_sqrt + eps;
      p[i] -= lr * (mi * inv_bc1) / denom;
    }
  }
}

// Fused variant that also materializes a bf16 copy of the updated params —
// the copy the engine streams back to HBM as the compute-dtype weights
// (reference cpu_adam.cpp's fp16 param copy-back, Step_AVX half path).
void ds_cpu_adam_step_bf16_copy(float* __restrict__ p,
                                const float* __restrict__ g,
                                float* __restrict__ m,
                                float* __restrict__ v,
                                uint16_t* __restrict__ p_bf16,
                                int64_t n,
                                float lr,
                                float beta1,
                                float beta2,
                                float eps,
                                float weight_decay,
                                float bc1,
                                float bc2,
                                int adam_w_mode) {
  ds_cpu_adam_step(p, g, m, v, n, lr, beta1, beta2, eps, weight_decay, bc1,
                   bc2, adam_w_mode);
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    // round-to-nearest-even bf16 truncation, NaN-preserving (rounding a
    // low-mantissa NaN would carry into the exponent and yield inf)
    uint32_t bits;
    __builtin_memcpy(&bits, &p[i], sizeof(bits));
    uint16_t out;
    if ((bits & 0x7fffffffu) > 0x7f800000u) {
      out = static_cast<uint16_t>((bits >> 16) | 0x0040u);  // quiet NaN
    } else {
      const uint32_t rounding = 0x7fffu + ((bits >> 16) & 1u);
      out = static_cast<uint16_t>((bits + rounding) >> 16);
    }
    p_bf16[i] = out;
  }
}

int ds_cpu_adam_num_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
