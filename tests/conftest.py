"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's @distributed_test strategy (tests/unit/common.py) —
multi-"chip" is simulated on one host. Env must be set before jax imports.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU-tunnel plugin can override JAX_PLATFORMS at import time;
# force the CPU mesh explicitly.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_config_file(tmp_path):
    """Dump a config dict to a json file, return the path
    (mirrors reference args_from_dict)."""
    import json

    def _write(config_dict, name="ds_config.json"):
        path = tmp_path / name
        path.write_text(json.dumps(config_dict))
        return str(path)

    return _write
