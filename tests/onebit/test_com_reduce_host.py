"""Standalone correctness check: compressed allreduce vs exact allreduce.

Reference-parity tier-4 script (reference tests/onebit/test_nccl_backend.py
— a manually-launched validation of NcclBackend.compressed_allreduce
against torch.distributed.all_reduce). Here the backend is XLA collectives
on a virtual device mesh, so it runs anywhere:

    python tests/onebit/test_com_reduce_host.py [--devices 8] [--size 16384]

Validates:
  * one compressed round has bounded error vs the exact mean;
  * with error feedback carried across rounds on a CONSTANT input, the
    accumulated compressed estimate converges toward the exact mean
    (the property 1-bit Adam's convergence rests on).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--size", type=int, default=16384)
    parser.add_argument("--rounds", type=int, default=120)
    args = parser.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"   # virtual mesh; override the tunnel
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count={}".format(args.devices))

    import numpy as np
    import jax
    # the axon TPU-tunnel plugin can override JAX_PLATFORMS at import time
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.comm.compressed import CompressedBackend

    world, n = args.devices, args.size
    mesh = build_mesh(data=world)
    backend = CompressedBackend(mesh)

    rng = np.random.RandomState(7)
    values = jnp.asarray(rng.randn(world, n).astype(np.float32))
    exact = np.asarray(values.mean(axis=0))

    # one round: bounded relative error
    out, we, se = backend.compressed_allreduce(values)
    out0 = np.asarray(out[0])
    rel = np.linalg.norm(out0 - exact) / np.linalg.norm(exact)
    print("one-round relative error: {:.3f}".format(rel))
    assert rel < 1.0, "sign-compression error out of bounds"
    assert np.all(np.asarray(out) == out0), "ranks disagree"

    # error feedback: sum of compressed outputs tracks t * exact mean
    we = se = None
    acc = np.zeros_like(exact)
    for t in range(1, args.rounds + 1):
        out, we, se = backend.compressed_allreduce(values, we, se)
        acc += np.asarray(out[0])
        drift = np.linalg.norm(acc / t - exact) / np.linalg.norm(exact)
    print("after {} rounds with error feedback: drift {:.4f}".format(
        args.rounds, drift))
    assert drift < 0.05, "error feedback failed to converge"
    print("PASSED")


if __name__ == "__main__":
    main()
