"""Standalone micro-benchmark: compressed vs exact allreduce wall time.

Reference-parity tier-4 script (reference tests/onebit/test_nccl_perf.py /
test_mpi_perf.py — manually-launched timing of the compressed allreduce).
On a CPU mesh the numbers only show the mechanism; on a pod the compressed
path wins whenever the wire (DCN) is the bottleneck — the reference's
"6.6x compression-stage speedup at 40 Gb Ethernet" regime.

    python tests/onebit/test_com_perf.py [--devices 8] [--size 4194304]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def timeit(fn, *args, reps=10):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.time() - t0) / reps


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--size", type=int, default=1 << 22)
    args = parser.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"   # virtual mesh; override the tunnel
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count={}".format(args.devices))

    import numpy as np
    import jax
    # the axon TPU-tunnel plugin can override JAX_PLATFORMS at import time
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from deepspeed_tpu.parallel.topology import build_mesh, DATA_AXIS
    from deepspeed_tpu.runtime.comm.compressed import CompressedBackend

    world, n = args.devices, args.size
    mesh = build_mesh(data=world)
    backend = CompressedBackend(mesh)

    rng = np.random.RandomState(0)
    values = jnp.asarray(rng.randn(world, n).astype(np.float32))

    @jax.jit
    def exact(v):
        f = shard_map(lambda x: jax.lax.pmean(x, DATA_AXIS),
                      mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))
        return f(v)

    # Error buffers live at the backend's padded width, not n — sizes not
    # divisible by 8*devices would shape-error inside jit otherwise.
    we = jnp.zeros((world, backend.padded_size(n)), jnp.float32)
    se = jnp.zeros((world, backend.padded_size(n) // world), jnp.float32)

    t_exact = timeit(exact, values)
    t_comp = timeit(lambda v: backend.compressed_allreduce(v, we, se), values)
    mb = n * 4 / 1e6
    print("buffer {:.1f} MB x {} ranks".format(mb, world))
    print("exact allreduce:      {:.2f} ms".format(t_exact * 1e3))
    print("compressed allreduce: {:.2f} ms (wire 32x smaller)".format(
        t_comp * 1e3))


if __name__ == "__main__":
    main()
