"""Model-level milestone tests — the five BASELINE.json configs at unit
scale.

Reference parity: tests/model/Megatron_GPT2/run_func_test.py +
run_checkpoint_test.py, which launch real workloads, grep the LM loss out
of logs, and compare runs for equality/closeness. Here the "grep" is
direct loss capture; each milestone keeps the BASELINE config shape
(parallelism mode, optimizer, ZeRO stage) with tiny dims.

  1. cifar10-style DP smoke      (stage 0, fp32, SGD-able convergence)
  2. GPT2 + ZeRO-1               (run-to-run loss equality)
  3. BERT + ZeRO-2 + Adam/Lamb   (convergence both optimizers)
  4. GPT2 + ZeRO-3 + cpu-offload (offloaded optimizer converges)
  5. GPT2 3D parallel            (pipe x model x data vs DP closeness)
plus train->save->resume->loss-equality (run_checkpoint_test behavior).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import gpt2, bert, gpt2_pipe
from deepspeed_tpu.runtime.model import Model


def _gpt2_cfg(**kw):
    base = dict(vocab_size=128, max_seq_len=32, n_layers=2, n_heads=2,
                d_model=32, use_flash_attention=False, remat=False,
                dropout=0.0)
    base.update(kw)
    return gpt2.GPT2Config(**base)


def _gpt2_batch(rs, batch=8, seq=32, vocab=128):
    ids = jnp.asarray(rs.randint(0, vocab, size=(batch, seq)))
    return ids, ids


def _run_gpt2(config_dict, steps=10, seed=0, model_seed=0):
    cfg = _gpt2_cfg()
    model = gpt2.make_gpt2_model(config=cfg, seed=model_seed)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config_params=config_dict)
    rs = np.random.RandomState(seed)
    ids, labels = _gpt2_batch(rs)
    losses = []
    for _ in range(steps):
        loss = engine(ids, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return engine, losses


# --- milestone 1: cifar10-style DP smoke (BASELINE config 1) ---------------
@pytest.mark.slow
def test_milestone1_dp_smoke_convergence():
    """SimpleModel-style conv-free classifier on random 'images', pure DP
    fp32 (the cifar10 smoke config)."""
    rs = np.random.RandomState(0)

    def apply_fn(params, x, y):
        h = jnp.tanh(x.reshape(x.shape[0], -1) @ params["w1"] + params["b1"])
        logits = h @ params["w2"] + params["b2"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    params = {
        "w1": jnp.asarray(rs.randn(3 * 8 * 8, 32) * 0.1),
        "b1": jnp.zeros(32),
        "w2": jnp.asarray(rs.randn(32, 10) * 0.1),
        "b2": jnp.zeros(10),
    }
    config = {"train_batch_size": 16,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
              "steps_per_print": 100}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(apply_fn, params), config_params=config)
    x = jnp.asarray(rs.randn(16, 3, 8, 8).astype(np.float32))
    y = jnp.asarray(rs.randint(0, 10, size=(16,)))
    losses = []
    for _ in range(30):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses


# --- milestone 2: GPT2 + ZeRO-1 (BASELINE config 2) -------------------------
@pytest.mark.slow
def test_milestone2_gpt2_zero1_run_equality():
    """Two identical runs produce identical loss curves (the reference's
    grep-and-compare-equal check)."""
    config = {"train_batch_size": 8,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "bf16": {"enabled": True},
              "zero_optimization": {"stage": 1},
              "steps_per_print": 100}
    _, run_a = _run_gpt2(dict(config))
    _, run_b = _run_gpt2(dict(config))
    np.testing.assert_array_equal(run_a, run_b)
    assert run_a[-1] < run_a[0]


# --- milestone 3: BERT + ZeRO-2, FusedAdam and Lamb (BASELINE config 3) ----
@pytest.mark.parametrize("opt", ["Adam", "Lamb"])
@pytest.mark.slow
def test_milestone3_bert_zero2(opt):
    model = bert.make_bert_model(size="bert_base", n_layers=2, d_model=32,
                                 n_heads=2, d_intermediate=64, vocab_size=96,
                                 max_seq_len=32, dropout=0.0,
                                 attn_dropout=0.0)
    config = {"train_batch_size": 8,
              "optimizer": {"type": opt, "params": {"lr": 1e-3}},
              "bf16": {"enabled": True},
              "zero_optimization": {"stage": 2},
              "steps_per_print": 100}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config_params=config)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 96, size=(8, 32)))
    types = jnp.asarray(rs.randint(0, 2, size=(8, 32)))
    mask = jnp.ones((8, 32), dtype=jnp.int32)
    mlm = jnp.asarray(np.where(rs.rand(8, 32) < 0.15, np.asarray(ids), -100))
    nsp = jnp.asarray(rs.randint(0, 2, size=(8,)))
    losses = []
    for _ in range(10):
        loss = engine(ids, types, mask, mlm, nsp)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


# --- milestone 4: GPT2 + ZeRO-3 + cpu-offload (BASELINE config 4) ----------
def test_milestone4_gpt2_zero3_offload():
    config = {"train_batch_size": 8,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "bf16": {"enabled": True},
              "zero_optimization": {"stage": 3, "cpu_offload": True,
                                    "param_persistence_threshold": 0},
              "steps_per_print": 100}
    engine, losses = _run_gpt2(config, steps=10)
    # offload selected the host-side optimizer
    assert type(engine.optimizer).__name__ == "DeepSpeedCPUAdam"
    assert losses[-1] < losses[0], losses


# --- milestone 5: 3D parallel (BASELINE config 5) ---------------------------
@pytest.mark.slow
def test_milestone5_gpt2_3d_vs_dp():
    """pipe=2 x model=2 x data=2 vs pure-DP: same model seeds, loss curves
    close (the reference's Megatron mp/gpu matrix closeness check)."""
    cfg = _gpt2_cfg()
    ds = {"train_micro_batch_size_per_gpu": 2,
          "gradient_accumulation_steps": 2,
          "bf16": {"enabled": True},
          "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
          "steps_per_print": 100}

    net = gpt2_pipe.make_gpt2_pipeline(config=cfg, num_stages=2, num_dp=2,
                                       num_mp=2)
    e3d, _, _, _ = deepspeed_tpu.initialize(model=net, config_params=ds)
    assert dict(e3d.mesh.shape) == {"pipe": 2, "data": 2, "model": 2}

    dp_model = gpt2.make_gpt2_model(config=cfg, seed=0)
    ds_dp = dict(ds, train_micro_batch_size_per_gpu=1)  # dp=8: same global 8
    e_dp, _, _, _ = deepspeed_tpu.initialize(model=dp_model,
                                             config_params=ds_dp)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, size=(2, 4, 32)).astype(np.int32)
    l3d, ldp = [], []
    for _ in range(5):
        l3d.append(float(e3d.train_batch(batch=(ids, ids.copy()))))
        ldp.append(float(e_dp.train_batch(batch=(ids, ids.copy()))))
    assert l3d[-1] < l3d[0]
    # different init partitioning => closeness, not equality
    np.testing.assert_allclose(l3d, ldp, rtol=0.15)


# --- checkpoint milestone: train -> save -> resume -> compare ---------------
@pytest.mark.slow
def test_checkpoint_resume_loss_equality(tmp_path):
    config = {"train_batch_size": 8,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "bf16": {"enabled": True},
              "zero_optimization": {"stage": 2},
              "steps_per_print": 100}
    engine, _ = _run_gpt2(dict(config), steps=4)
    engine.save_checkpoint(str(tmp_path))

    # continued run
    rs = np.random.RandomState(99)
    ids, labels = _gpt2_batch(rs)
    cont = []
    for _ in range(3):
        loss = engine(ids, labels)
        engine.backward(loss)
        engine.step()
        cont.append(float(loss))

    # resumed run
    cfg = _gpt2_cfg()
    model = gpt2.make_gpt2_model(config=cfg, seed=17)  # different init
    engine2, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                config_params=dict(config))
    engine2.load_checkpoint(str(tmp_path))
    resumed = []
    for _ in range(3):
        loss = engine2(ids, labels)
        engine2.backward(loss)
        engine2.step()
        resumed.append(float(loss))

    np.testing.assert_allclose(cont, resumed, rtol=1e-4)


# --- milestone 6: BingBertSquad-style fine-tune (reference tier-2 e2e) -----
@pytest.mark.slow
def test_milestone6_bert_squad_finetune():
    """Span-extraction fine-tuning e2e (reference tests/model/BingBertSquad
    test_e2e_squad.py: fine-tune, then check quality). Tiny memorizable
    set: loss must collapse and span-start accuracy reach 100%."""
    cfg = bert.config_for("bert_base", vocab_size=128, max_seq_len=32,
                          n_layers=2, n_heads=2, d_model=32,
                          d_intermediate=64, dropout=0.0, attn_dropout=0.0,
                          remat=False)
    model = bert.make_bert_squad_model(config=cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config_params={
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
        "steps_per_print": 1000,
    })
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(0, 128, size=(8, 32)))
    tt = jnp.zeros((8, 32), jnp.int32)
    am = jnp.ones((8, 32), jnp.int32)
    start = jnp.asarray(rs.randint(0, 32, size=(8,)))
    end = jnp.asarray(rs.randint(0, 32, size=(8,)))
    # train_batch takes (gas, global_batch, ...) stacked micro-batches
    batch = tuple(x[None] for x in (ids, tt, am, start, end))
    losses = []
    for _ in range(60):
        losses.append(float(engine.train_batch(batch=batch)))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])

    # quality check: predicted span starts match on the memorized set
    engine_params = engine.get_params()
    hidden2 = bert.encode(engine_params, ids, tt, am, cfg, None, False)
    logits2 = bert.squad_logits(engine_params, hidden2)
    pred = np.asarray(jnp.argmax(logits2[..., 0], axis=-1))
    acc = (pred == np.asarray(start)).mean()
    assert acc >= 0.9, (pred, np.asarray(start))


# --- milestone 7: sequence parallelism trains (ring + ulysses legs) --------
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.slow
def test_milestone7_sequence_parallel_vs_dp(impl):
    """GPT-2 with sequence parallelism over a (data=2, sequence=4) mesh:
    loss curve must track the pure-DP run closely (same model/data; only
    the attention sharding differs)."""
    import dataclasses
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    # ulysses all-to-all shards heads over the sequence axis -> heads must
    # divide by sp degree (4); ring has no such constraint
    base_cfg = _gpt2_cfg(max_seq_len=64,
                         n_heads=4 if impl == "ulysses" else 2)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
    }

    sp_mesh = build_mesh(data=2, sequence=4)
    sp_cfg = dataclasses.replace(base_cfg, sequence_parallel=impl,
                                 sp_mesh=sp_mesh)
    sp_engine = DeepSpeedEngine(
        model=gpt2.make_gpt2_model(config=sp_cfg, seed=0), mesh=sp_mesh,
        config_params=dict(config))

    dp_engine = DeepSpeedEngine(
        model=gpt2.make_gpt2_model(config=base_cfg, seed=0),
        mesh=build_mesh(data=2), config_params=dict(config))

    rs = np.random.RandomState(5)
    ids = rs.randint(0, 128, size=(1, 4, 64)).astype(np.int32)
    sp_losses, dp_losses = [], []
    for _ in range(8):
        sp_losses.append(float(sp_engine.train_batch(batch=(ids, ids))))
        dp_losses.append(float(dp_engine.train_batch(batch=(ids, ids))))
    assert sp_losses[-1] < sp_losses[0], sp_losses
    np.testing.assert_allclose(sp_losses, dp_losses, rtol=0.08)


@pytest.mark.slow
def test_milestone5b_gpt2_3d_ragged_tied_gas4():
    """Milestone-5 hardening: UNEQUAL stage depths (3 layers over 2
    stages), tied embedding/head gradients under 3D, and deeper grad
    accumulation (micro_batches=4) — vs pure-DP loss closeness."""
    cfg = _gpt2_cfg()
    import dataclasses
    cfg = dataclasses.replace(cfg, n_layers=3)
    ds = {"train_micro_batch_size_per_gpu": 1,
          "gradient_accumulation_steps": 4,
          "bf16": {"enabled": True},
          "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
          "steps_per_print": 100}

    net = gpt2_pipe.make_gpt2_pipeline(config=cfg, num_stages=2, num_dp=2,
                                       num_mp=2)
    assert sorted(net.stage_depths.tolist()) == [1, 2]
    assert "embed" in net.tied_keys
    e3d, _, _, _ = deepspeed_tpu.initialize(model=net, config_params=ds)

    # apples-to-apples DP reference: SAME gas=4 accumulated trajectory
    # (one optimizer step per train_batch) so a grad-accum bug in the
    # pipeline cannot hide inside schedule divergence
    dp_model = gpt2.make_gpt2_model(config=cfg, seed=0)
    ds_dp = dict(ds, train_micro_batch_size_per_gpu=1)
    e_dp, _, _, _ = deepspeed_tpu.initialize(model=dp_model,
                                             config_params=ds_dp)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, size=(4, 2, 32)).astype(np.int32)
    l3d, ldp = [], []
    for _ in range(5):
        l3d.append(float(e3d.train_batch(batch=(ids, ids.copy()))))
        ldp.append(float(e_dp.train_batch(batch=(ids, ids.copy()))))
    assert l3d[-1] < l3d[0]
    # tied-weight grads + ragged stages: trajectories stay close to DP
    np.testing.assert_allclose(l3d, ldp, rtol=0.08)
