"""Real 2-process jax.distributed smoke test over localhost (CPU).

The only axis the virtual single-process mesh cannot cover: actual
multi-process init, cross-process batch sharding, multi-process
ZeRO-Offload, and per-process zero checkpoint files. Mirrors how the
reference CI runs NCCL over localhost."""
import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_train_offload_checkpoint(tmp_path):
    worker = os.path.join(os.path.dirname(__file__), "dist_worker.py")
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "RANK": str(rank),
            "WORLD_SIZE": "2",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "JAX_PLATFORMS": "cpu",
        })
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            "rank {} failed:\n{}".format(rank, out[-4000:])
        assert "DIST_OK rank={}".format(rank) in out, out[-2000:]
    # both ranks observed the same training trajectory
    final = [line for out in outs for line in out.splitlines()
             if line.startswith("DIST_OK")]
    l0 = final[0].split("final_loss=")[1].split()[0]
    l1 = final[1].split("final_loss=")[1].split()[0]
    assert l0 == l1, (l0, l1)
