"""Worker for the 2-process jax.distributed CPU smoke test.

Launched twice by test_two_process.py with RANK/WORLD_SIZE/MASTER_ADDR env
(the same launcher surface deepspeed_tpu.init_distributed consumes). Each
process owns 2 virtual CPU devices -> a 4-way data mesh across 2 processes.

Covers the full multi-process engine surface the single-process suite
cannot: distributed init, per-process batch sharding
(make_array_from_process_local_data), multi-process ZeRO-Offload (host
shards per process: reference stage2.py:780-908), and checkpoint
save/load with per-process zero shard files.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=2")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")   # the axon plugin overrides env
import jax.numpy as jnp


def main():
    rank = int(os.environ["RANK"])
    ckpt_dir = sys.argv[1]

    import deepspeed_tpu
    from deepspeed_tpu.runtime.model import Model

    deepspeed_tpu.init_distributed()
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()

    def apply_fn(params, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    config = {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "cpu_offload": True},
        "steps_per_print": 1000,
    }

    def make_engine():
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=Model(apply_fn, {"w": jnp.zeros((32, 8))}),
            config_params=config)
        return engine

    engine = make_engine()
    assert engine.dp_world_size == 4

    # multi-process offload: host shards must cover only OUR grads
    n_shard_elems = sum(int(p.size)
                        for shards in engine.host_state["shard_leaves"]
                        for _, p, _, _ in shards)
    assert n_shard_elems == 32 * 8 // 2, \
        "each process must hold half the master: {}".format(n_shard_elems)

    rs = np.random.RandomState(0)          # SAME data on both ranks...
    W = rs.randn(32, 8).astype(np.float32)
    losses = []
    for step in range(30):
        xg = np.random.RandomState(100 + step).randn(16, 32) \
            .astype(np.float32)
        yg = xg @ W
        # ...but each process feeds only its LOCAL half of the batch
        lo, hi = rank * 8, (rank + 1) * 8
        loss = engine(xg[lo:hi], yg[lo:hi])
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < 0.2 * losses[0], losses

    engine.save_checkpoint(ckpt_dir)

    engine2 = make_engine()
    path, _ = engine2.load_checkpoint(ckpt_dir)
    assert path is not None
    assert engine2.host_state["step"] == 30
    # same shard layout restored bit-exact
    for sh_a, sh_b in zip(engine.host_state["shard_leaves"],
                          engine2.host_state["shard_leaves"]):
        for (ia, pa, ma, va), (ib, pb, mb, vb) in zip(sh_a, sh_b):
            assert ia == ib
            np.testing.assert_array_equal(pa, pb)
            np.testing.assert_array_equal(ma, mb)
            np.testing.assert_array_equal(va, vb)

    xg = np.random.RandomState(999).randn(16, 32).astype(np.float32)
    yg = xg @ W
    lo, hi = rank * 8, (rank + 1) * 8
    l1 = float(engine(xg[lo:hi], yg[lo:hi]))
    l2 = float(engine2(xg[lo:hi], yg[lo:hi]))
    assert abs(l1 - l2) < 1e-6, (l1, l2)

    # --- device-state ZeRO: per-rank zero shard files (no offload) ---
    # Each process writes zero_pp_rank_<rank>; the model file carries no
    # optimizer/master (reference engine.py:1350-1377 layout), and resume
    # reassembles bit-exact state from the shard set.
    dev_dir = os.path.join(ckpt_dir, "device_zero")
    dev_config = dict(config)
    dev_config["zero_optimization"] = {"stage": 2}

    def make_dev_engine():
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=Model(apply_fn, {"w": jnp.zeros((32, 8))}),
            config_params=dev_config)
        return eng

    dev = make_dev_engine()
    for step in range(10):
        xg = np.random.RandomState(200 + step).randn(16, 32) \
            .astype(np.float32)
        yg = xg @ W
        loss = dev(xg[lo:hi], yg[lo:hi])
        dev.backward(loss)
        dev.step()
    dev.save_checkpoint(dev_dir, tag="tag0")

    from deepspeed_tpu.runtime import checkpointing as ckpt_mod
    my_zero = ckpt_mod.zero_ckpt_name(dev_dir, "tag0", dp_rank=rank)
    assert os.path.isfile(my_zero), my_zero
    sd = ckpt_mod.load_state_dict(
        ckpt_mod.model_ckpt_name(dev_dir, "tag0"))
    assert sd["optimizer"] is None and sd["master"] is None, \
        "model file must not duplicate the sharded optimizer state"

    dev2 = make_dev_engine()
    path, _ = dev2.load_checkpoint(dev_dir, tag="tag0")
    assert path is not None

    def assert_shards_equal(ta, tb):
        # leaves span processes; compare this process's shards
        for a, b in zip(jax.tree_util.tree_leaves(ta),
                        jax.tree_util.tree_leaves(tb)):
            for sa, sb in zip(a.addressable_shards, b.addressable_shards):
                assert sa.index == sb.index
                np.testing.assert_array_equal(np.asarray(sa.data),
                                              np.asarray(sb.data))

    assert_shards_equal(dev.state["master"], dev2.state["master"])
    for key in ("exp_avg", "exp_avg_sq"):
        assert_shards_equal(dev.state["opt"][key], dev2.state["opt"][key])
    xg = np.random.RandomState(998).randn(16, 32).astype(np.float32)
    yg = xg @ W
    d1 = float(dev(xg[lo:hi], yg[lo:hi]))
    d2 = float(dev2(xg[lo:hi], yg[lo:hi]))
    assert abs(d1 - d2) < 1e-6, (d1, d2)

    print("DIST_OK rank={} final_loss={:.6f} resume_loss={:.6f}".format(
        rank, losses[-1], l2), flush=True)


if __name__ == "__main__":
    main()
