"""Worker for the 2-process jax.distributed CPU smoke test.

Launched twice by test_two_process.py with RANK/WORLD_SIZE/MASTER_ADDR env
(the same launcher surface deepspeed_tpu.init_distributed consumes). Each
process owns 2 virtual CPU devices -> a 4-way data mesh across 2 processes.

Covers the full multi-process engine surface the single-process suite
cannot: distributed init, per-process batch sharding
(make_array_from_process_local_data), multi-process ZeRO-Offload (host
shards per process: reference stage2.py:780-908), and checkpoint
save/load with per-process zero shard files.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=2")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")   # the axon plugin overrides env
import jax.numpy as jnp


def main():
    rank = int(os.environ["RANK"])
    ckpt_dir = sys.argv[1]

    import deepspeed_tpu
    from deepspeed_tpu.runtime.model import Model

    deepspeed_tpu.init_distributed()
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()

    def apply_fn(params, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    config = {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "cpu_offload": True},
        "steps_per_print": 1000,
    }

    def make_engine():
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=Model(apply_fn, {"w": jnp.zeros((32, 8))}),
            config_params=config)
        return engine

    engine = make_engine()
    assert engine.dp_world_size == 4

    # multi-process offload: host shards must cover only OUR grads
    n_shard_elems = sum(int(p.size)
                        for shards in engine.host_state["shard_leaves"]
                        for _, p, _, _ in shards)
    assert n_shard_elems == 32 * 8 // 2, \
        "each process must hold half the master: {}".format(n_shard_elems)

    rs = np.random.RandomState(0)          # SAME data on both ranks...
    W = rs.randn(32, 8).astype(np.float32)
    losses = []
    for step in range(30):
        xg = np.random.RandomState(100 + step).randn(16, 32) \
            .astype(np.float32)
        yg = xg @ W
        # ...but each process feeds only its LOCAL half of the batch
        lo, hi = rank * 8, (rank + 1) * 8
        loss = engine(xg[lo:hi], yg[lo:hi])
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < 0.2 * losses[0], losses

    engine.save_checkpoint(ckpt_dir)

    engine2 = make_engine()
    path, _ = engine2.load_checkpoint(ckpt_dir)
    assert path is not None
    assert engine2.host_state["step"] == 30
    # same shard layout restored bit-exact
    for sh_a, sh_b in zip(engine.host_state["shard_leaves"],
                          engine2.host_state["shard_leaves"]):
        for (ia, pa, ma, va), (ib, pb, mb, vb) in zip(sh_a, sh_b):
            assert ia == ib
            np.testing.assert_array_equal(pa, pb)
            np.testing.assert_array_equal(ma, mb)
            np.testing.assert_array_equal(va, vb)

    xg = np.random.RandomState(999).randn(16, 32).astype(np.float32)
    yg = xg @ W
    lo, hi = rank * 8, (rank + 1) * 8
    l1 = float(engine(xg[lo:hi], yg[lo:hi]))
    l2 = float(engine2(xg[lo:hi], yg[lo:hi]))
    assert abs(l1 - l2) < 1e-6, (l1, l2)

    print("DIST_OK rank={} final_loss={:.6f} resume_loss={:.6f}".format(
        rank, losses[-1], l2), flush=True)


if __name__ == "__main__":
    main()
