"""Probe: the remaining reference layouts (variable, bslongformer) vs
dense flash at seq 16384 — completes the measured layout matrix
(fixed/bigbird/sliding_window live in sweep_sparse_vs_dense.py).
Writes tests/perf/LAYOUT_MATRIX_16K.json.

    python tests/perf/probe_layout_matrix.py
"""
import json
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import numpy as np, jax, jax.numpy as jnp
from sweep_sparse_vs_dense import timed_scan
from deepspeed_tpu.ops.transformer import flash_attention as fa
from deepspeed_tpu.ops.sparse_attention import make_block_sparse_attention
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    VariableSparsityConfig, BSLongformerSparsityConfig)
HEADS, DHEAD, BATCH, seq, block = 16, 64, 2, 16384, 128
rng = np.random.RandomState(0)
x = jnp.asarray(rng.randn(BATCH, seq, HEADS, DHEAD)*0.1, jnp.bfloat16)

rows = []


def emit(row):
    rows.append(row)
    print(json.dumps(row), flush=True)


def dense_step(t):
    g = jax.grad(lambda q: fa.flash_attention_bshd(q, q, q)
                 .astype(jnp.float32).sum())(t)
    return g.astype(t.dtype)


dense_ms = round(timed_scan(dense_step, x), 2)
emit({"layout": "dense flash", "ms": dense_ms})

cases = [
    ("variable", VariableSparsityConfig(
        num_heads=HEADS, block=block, num_random_blocks=0,
        local_window_blocks=[4], global_block_indices=[0],
        attention="unidirectional")),
    ("bslongformer", BSLongformerSparsityConfig(
        num_heads=HEADS, block=block, num_sliding_window_blocks=3,
        global_block_indices=[0])),
]
for name, cfg in cases:
    lay = np.asarray(cfg.make_layout(seq))
    attn = make_block_sparse_attention(lay, block, causal=(name != "bslongformer"))
    def step(t, attn=attn):
        def loss(q):
            qh = q.transpose(0, 2, 1, 3)
            return attn(qh, qh, qh, None, None).astype(jnp.float32).sum()
        return jax.grad(loss)(t).astype(t.dtype)
    try:
        ms = round(timed_scan(step, x), 2)
    except Exception as e:
        ms = "failed: " + str(e)[:90]
    row = {"layout": name, "density": round(float(lay.mean()), 4),
           "ms": ms}
    if isinstance(ms, float) and dense_ms:
        row["vs_dense"] = round(ms / dense_ms, 2)
    emit(row)

out = {"config": {"batch": BATCH, "heads": HEADS, "d_head": DHEAD,
                  "seq": seq, "block": block,
                  "timing": "fwd+bwd (grad wrt q,k,v), scan-amortized, ms/layer, one v5e"},
       "rows": rows}
path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "LAYOUT_MATRIX_16K.json")
with open(path, "w") as f:
    json.dump(out, f, indent=2)
