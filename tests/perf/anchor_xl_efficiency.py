"""Executed-flop efficiency at gpt2-xl WIDTH (d_model 1600), real chip.

The v5p-64 north-star projection (analyze_v5p64.py) needs an efficiency
anchor measured at the 1.5B model's real width — round 3 anchored it at
the bench width (1024), where the fused flash backward applied but the
xl model then fell back to the split kernels (VERDICT r3 weak #1; the
grouped-fused backward now covers 1600 too, see flash_attention.py).
A full 1.5B step cannot run un-offloaded in 16 GB, but its per-token
compute is width-shaped, not depth-shaped: this measures truncated
gpt2-xl-width stacks on the real chip and splits the efficiency into

  - eff_layers: per-LAYER rate from a least-squares fit of stack-grad
    time over several depths (remat, fused LN+QKV flash attention —
    executed flops = 8/6 x model flops). The fit separates the
    depth-independent intercept (embedding gather + its scatter-add
    backward, final LN, loss readout — ~40% of a 2-layer measurement)
    from the slope the 48-layer projection actually scales with, and
  - eff_head: the chunked LM-head/CE add-on (lm_loss minus the stack).

    python tests/perf/anchor_xl_efficiency.py [--mb 8] [--layers 1 2 4 8]

Writes tests/perf/XL_WIDTH_ANCHOR.json (read by analyze_v5p64.py).
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

SEQ = 1024
V5E_PEAK = 197e12
REMAT_FACTOR = 8.0 / 6.0


def _force(out):
    import jax
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(leaf.ravel()[0])


def timed_grad(loss_fn, params, ids, reps=8, outer=5):
    """Per-step ms for jax.grad(loss_fn), with the reps INSIDE one jit
    call (chained through a param update) so the ~110 ms axon-tunnel
    dispatch latency is amortized away."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    grad = jax.grad(loss_fn)

    @jax.jit
    def loop(p, ids):
        def body(_, p):
            g = grad(p, ids)
            return jax.tree_util.tree_map(
                lambda x, gx: x + jnp.asarray(1e-6, x.dtype)
                * gx.astype(x.dtype), p, g)
        return lax.fori_loop(0, reps, body, p)

    _force(loop(params, ids))
    best = None
    for _ in range(outer):
        t0 = time.time()
        _force(loop(params, ids))
        dt = (time.time() - t0) * 1e3 / reps
        best = dt if best is None else min(best, dt)
    return round(best, 2)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mb", type=int, default=8)
    parser.add_argument("--layers", type=int, nargs="+",
                        default=[1, 2, 4, 8])
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import gpt2

    rng = np.random.RandomState(0)
    tokens = args.mb * SEQ
    depths, stack_ms, head_ms = [], [], []
    d = h = V = None
    for L in args.layers:
        cfg = gpt2.config_for("gpt2_xl", n_layers=L, max_seq_len=SEQ,
                              remat=True, loss_chunk=128)
        d, h, V = cfg.d_model, cfg.n_heads, cfg.vocab_size
        params = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p, jnp.bfloat16),
            gpt2.init_params(cfg, 0))
        ids = jnp.asarray(rng.randint(0, V, size=(args.mb, SEQ)),
                          jnp.int32)

        def full_loss(p, ids, cfg=cfg):
            return gpt2.lm_loss(p, ids, ids, cfg, rng=None, train=False)

        def stack_loss(p, ids, cfg=cfg):
            hid = gpt2.forward_hidden(p, ids, cfg, rng=None, train=False)
            return hid.astype(jnp.float32).mean()

        t_stack = timed_grad(stack_loss, params, ids)
        t_full = timed_grad(full_loss, params, ids)
        depths.append(L)
        stack_ms.append(t_stack)
        head_ms.append(max(t_full - t_stack, 1e-3))
        print(f"L={L}: stack={t_stack} full={t_full}", flush=True)

    # least-squares t_stack = intercept + slope * L
    Ls = np.asarray(depths, float)
    ts = np.asarray(stack_ms, float)
    slope = float(((Ls - Ls.mean()) * (ts - ts.mean())).sum()
                  / ((Ls - Ls.mean()) ** 2).sum())
    intercept = float(ts.mean() - slope * Ls.mean())
    t_head = float(np.median(head_ms))

    # per-token model flops, split the way the projection composes them:
    # per layer = 6 x block params + attention score/context dots;
    # head     = the tied (d, V) matmul fwd+bwd (gather-side embedding is
    # free). Executed flops: layers x 8/6 (full per-block remat re-runs
    # each forward); the chunked head/CE is not under remat (1x).
    p_block = 12 * d * d + 13 * d            # qkv/proj/mlp + ln/bias
    flops_layer_tok = 6.0 * p_block + 12.0 * d * SEQ
    flops_head_tok = 6.0 * d * V
    exec_layer = flops_layer_tok * tokens * REMAT_FACTOR
    exec_head = flops_head_tok * tokens

    from deepspeed_tpu.ops.transformer import flash_attention as fa
    plan, run_w = fa._bwd_dispatch(d, h, SEQ)
    fused_bwd_desc = (f"{plan} (run width {run_w}, mode {fa.BWD_MODE}, "
                      "resident-dq kernel)" if plan != "split" else "split")

    eff_layers = exec_layer / (slope * 1e-3 * V5E_PEAK)
    eff_head = exec_head / (t_head * 1e-3 * V5E_PEAK)

    out = {
        "config": {"d_model": d, "n_heads": h, "depths": depths,
                   "seq": SEQ, "micro_batch": args.mb,
                   "device": jax.devices()[0].device_kind,
                   "remat": True, "fused_bwd": fused_bwd_desc},
        "measured_ms": {"stack_grad_by_depth": stack_ms,
                        "head_ce_by_depth": [round(x, 2) for x in head_ms],
                        "ms_per_layer_fit": round(slope, 2),
                        "overhead_ms_fit": round(intercept, 2),
                        "head_ce_median": round(t_head, 2)},
        "model_flops_per_token": {
            "per_layer": round(flops_layer_tok / 1e6, 1),
            "head": round(flops_head_tok / 1e6, 1), "unit": "MFLOP"},
        "executed_flop_efficiency": {
            "layers_width1600": round(eff_layers, 4),
            "head_width1600": round(min(eff_head, 1.0), 4)},
        "overhead_ms_per_microstep": round(intercept, 2),
        "notes": [
            "executed flops = model x 8/6 for the remat'd block stack, "
            "1x for the chunked head/CE",
            "slope/intercept from a least-squares fit over depths: the "
            "intercept is the depth-independent cost (embedding gather + "
            "scatter-add backward, final LN, loss readout) a "
            "shallow-stack measurement would wrongly fold into the "
            "per-layer rate",
            "timing loops reps inside one jit call to cancel the axon "
            "tunnel's ~110 ms dispatch latency",
        ],
    }
    path = os.path.join(os.path.dirname(__file__), "XL_WIDTH_ANCHOR.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
