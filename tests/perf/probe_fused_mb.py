"""Probe: fused-attention-remat GPT-2 medium throughput at a given
micro-batch (bench.py shape). Usage:

    python tests/perf/probe_fused_mb.py --mb 48
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mb", type=int, default=48)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--chunk", type=int, default=128)
    parser.add_argument("--policy", default="full",
                        choices=["full", "dots", "none"],
                        help="remat policy (none = remat off)")
    parser.add_argument("--state", default="fp32",
                        choices=["fp32", "bf16"],
                        help="bf16 = bf16 Adam moments + bf16 grad accum "
                             "(the round-5 HBM lever; see "
                             "docs/roofline_gpt2_medium_v5e.md)")
    args = parser.parse_args()

    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    seq = 1024
    cfg = gpt2.config_for("gpt2_medium", max_seq_len=seq,
                          remat=args.policy != "none",
                          remat_policy=("full" if args.policy == "none"
                                        else args.policy),
                          loss_chunk=args.chunk)
    model = gpt2.make_gpt2_model(config=cfg)
    ds_config = {
        "train_micro_batch_size_per_gpu": args.mb,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9,
    }
    if args.state == "bf16":
        ds_config["optimizer"]["params"]["moments_dtype"] = "bf16"
        ds_config["data_types"] = {"grad_accum_dtype": "bf16"}
    engine, _, _, _ = deepspeed.initialize(model=model,
                                           config_params=ds_config)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(1, args.mb, seq)) \
        .astype(np.int32)
    batch = (ids, ids.copy())
    for _ in range(3):
        loss = engine.train_batch(batch=batch)
    float(loss)
    t0 = time.time()
    for _ in range(args.steps):
        loss = engine.train_batch(batch=batch)
    float(loss)
    dt = time.time() - t0
    toks = args.mb * seq * args.steps / dt
    n = gpt2.num_params(cfg)
    fpt = 6.0 * n + 12.0 * cfg.n_layers * cfg.d_model * seq
    print(json.dumps({"mb": args.mb, "policy": args.policy,
                      "state": args.state,
                      "tokens_per_sec": round(toks, 1),
                      "mfu": round(toks * fpt / 197e12, 4)}))


if __name__ == "__main__":
    main()
