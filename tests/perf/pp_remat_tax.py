"""Quantify the pipeline executor's recompute (remat) tax vs the DP step.

The 1F1B executor's backward re-runs each stage forward from a saved
input inside ``jax.vjp`` (full remat by design — the W-slot input buffer
is what keeps per-stage activation memory flat in micro_batches). Per
stage per microbatch, with model flops F = fwd(1F) + bwd(2F):

  mode                                   executed   tax vs model (3F)
  DP engine, remat=False                 3F         1.00x
  DP engine, per-block remat             4F         1.33x
  PP, activation_checkpoint_interval=0   4F         1.33x  (vjp saves
                                                    the stage interior
                                                    for the ACTIVE
                                                    microbatch only)
  PP, interval>=1 (per-block ckpt)       5F         1.67x  (NESTED
                                                    remat: the vjp
                                                    forward re-runs the
                                                    stage AND its
                                                    backward recomputes
                                                    block interiors)
  PP, save_stage_residuals=True          3F         1.00x  (fwd-phase
                                                    vjp residuals
                                                    buffered in the
                                                    W-slot ring)

This measures wall time per optimizer step for each mode at an equal
model/batch on the 8-device CPU mesh (compute-dominated shape so time
tracks executed flops) and writes tests/perf/PP_REMAT_TAX.json with the
measured ratios against the analytic ones.

    JAX_PLATFORMS=cpu python tests/perf/pp_remat_tax.py \
        [--d 128 --seq 128 --layers 4 --m 8 --mb 2 --reps 3]

The round-4 run (d 128, seq 128) found the ranking INVERTED at toy
shapes (W-slot buffer traffic outweighs saved flops); round 5 adds a
compute-dominated shape (d 512, seq 512) to locate the crossover. The
artifact accumulates one entry per shape under "shapes".
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402


def timed_interleaved(run_fns, reps=3, warmup=1):
    """Time all modes in interleaved ROUNDS and report per-mode MINIMA:
    host-CPU walls on a shared box swing with tenant contention
    (sequential blocks measured the SAME mode 1.8x apart across runs),
    and the minimum over interleaved rounds is the uncontended floor —
    the same methodology the TPU-side bake-offs use
    (compare_xl_bwd.py)."""
    for fn in run_fns.values():
        for _ in range(warmup):
            fn()
    best = {name: float("inf") for name in run_fns}
    for _ in range(reps):
        for name, fn in run_fns.items():
            t0 = time.time()
            fn()
            best[name] = min(best[name], (time.time() - t0) * 1e3)
    return {name: round(v, 1) for name, v in best.items()}


def main():
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--d", type=int, default=128)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--m", type=int, default=8)
    parser.add_argument("--mb", type=int, default=2)
    parser.add_argument("--reps", type=int, default=3)
    args = parser.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2, gpt2_pipe

    D, L, SEQ = args.d, args.layers, args.seq
    HEADS = max(4, D // 128)
    M = args.m                            # microbatches
    MB = args.mb                          # per-microbatch batch
    REPS = args.reps
    rng = np.random.RandomState(0)

    def cfg(remat):
        return gpt2.GPT2Config(vocab_size=1024, max_seq_len=SEQ,
                               n_layers=L, n_heads=HEADS, d_model=D,
                               use_flash_attention=False, remat=remat)

    run_fns = {}

    # ---- DP baselines -------------------------------------------------
    for name, remat in (("dp_no_remat", False), ("dp_block_remat", True)):
        net = gpt2.make_gpt2_model(config=cfg(remat))
        engine, _, _, _ = deepspeed.initialize(model=net, config_params={
            "train_micro_batch_size_per_gpu": MB,
            "gradient_accumulation_steps": M,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9})
        ids = rng.randint(0, 1024, size=(MB * 8, SEQ)).astype(np.int32)

        def run(engine=engine, ids=ids):
            for _ in range(M):
                loss = engine(ids, ids.copy())
                engine.backward(loss)
                engine.step()
            return float(loss)

        run_fns[name] = run

    # ---- pipeline modes ----------------------------------------------
    def pipe_mode(name, interval, save_residuals=False):
        net = gpt2_pipe.make_gpt2_pipeline(
            config=cfg(False), num_stages=2, num_dp=4, num_mp=1,
            activation_checkpoint_interval=interval,
            save_stage_residuals=save_residuals)
        engine, _, _, _ = deepspeed.initialize(model=net, config_params={
            "train_micro_batch_size_per_gpu": MB,
            "gradient_accumulation_steps": M,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9})
        ids = rng.randint(0, 1024,
                          size=(M, MB * 4, SEQ)).astype(np.int32)

        def run(engine=engine, ids=ids):
            return float(engine.train_batch(batch=(ids, ids.copy())))

        run_fns[name] = run

    pipe_mode("pp_block_remat", interval=1)
    pipe_mode("pp_stage_residuals_transient", interval=0)
    pipe_mode("pp_saved_residuals", interval=0, save_residuals=True)

    rows = timed_interleaved(run_fns, reps=REPS)
    print(rows, flush=True)

    # ---- compile-counted flops (noise-free): XLA's cost_analysis of
    # each compiled program. Loop bodies are counted ONCE (trip counts
    # invisible), so absolute numbers are not executed flops — but the
    # DIFFERENCES between pipeline modes isolate the backward phase's
    # recompute exactly (same warmup/steady/drain structure, same
    # forward). The DP grad of ONE microbatch anchors the scale. ----
    import jax.random as jrandom
    counted = {}

    def flops_of(compiled):
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca["flops"])

    for name, remat in (("dp_grad_1micro_no_remat", False),
                        ("dp_grad_1micro_block_remat", True)):
        cfg_ = cfg(remat)
        import jax.numpy as jnp
        params = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p, jnp.bfloat16),
            gpt2.init_params(cfg_, 0))
        ids1 = rng.randint(0, 1024, size=(MB * 8, SEQ)).astype(np.int32)
        grad = jax.jit(jax.grad(
            lambda p, i: gpt2.lm_loss(p, i, i, cfg_, rng=None,
                                      train=False)))
        counted[name] = flops_of(grad.lower(params, ids1).compile())

    def pipe_counted(name, interval, save_residuals=False):
        net = gpt2_pipe.make_gpt2_pipeline(
            config=cfg(False), num_stages=2, num_dp=4, num_mp=1,
            activation_checkpoint_interval=interval,
            save_stage_residuals=save_residuals)
        engine, _, _, _ = deepspeed.initialize(model=net, config_params={
            "train_micro_batch_size_per_gpu": MB,
            "gradient_accumulation_steps": M,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9})
        ids = rng.randint(0, 1024,
                          size=(M, MB * 4, SEQ)).astype(np.int32)
        batch = engine._to_device_stacked((ids, ids.copy()))
        fn = engine._get_jit("pipe_train", engine._fused_train_fn,
                             donate_argnums=(0,))
        lowered = fn.lower(engine.state, batch,
                           jrandom.PRNGKey(0), engine._hyper())
        counted[name] = flops_of(lowered.compile())

    pipe_counted("pp_block_remat", interval=1)
    pipe_counted("pp_stage_residuals_transient", interval=0)
    pipe_counted("pp_saved_residuals", interval=0, save_residuals=True)

    base = rows["dp_no_remat"]
    out = {
        "config": {"d_model": D, "layers": L, "seq": SEQ,
                   "micro_batches": M, "micro_batch": MB,
                   "mesh": "8 virtual cpu devices",
                   "timing": "ms per optimizer step (M microbatches), MIN over interleaved rounds"},
        "measured_ms": rows,
        "measured_ratio_vs_dp_no_remat": {
            k: round(v / base, 3) for k, v in rows.items()},
        "compile_counted_gflops": {
            k: round(v / 1e9, 2) for k, v in counted.items()},
        "pp_bwd_phase_recompute_gflops": {
            # steady+drain each contain one bwd phase (counted once per
            # loop): block-remat minus saved-residuals = 2x the per-
            # cycle recompute flops the nested remat pays
            "block_vs_saved": round(
                (counted["pp_block_remat"]
                 - counted["pp_saved_residuals"]) / 1e9, 2),
            "transient_vs_saved": round(
                (counted["pp_stage_residuals_transient"]
                 - counted["pp_saved_residuals"]) / 1e9, 2),
        },
        "analytic_executed_flops_ratio": {
            "dp_no_remat": 1.0, "dp_block_remat": 4 / 3,
            "pp_block_remat": 5 / 3,
            "pp_stage_residuals_transient": 4 / 3,
            "pp_saved_residuals": 1.0},
        "notes": [
            "idle-host CPU wall times validate the flops model where "
            "compute dominates (compare dp_block_remat/dp_no_remat "
            "against the compile-counted ratio IN THIS ENTRY). The PP "
            "rows measure the OTHER side of the tradeoff: "
            "lower-recompute modes buy their flop savings with W-slot "
            "buffer traffic (transient mode writes full stage "
            "interiors per vjp; saved-residuals RMWs W pullback copies "
            "per cycle); where that memory traffic outweighs the "
            "saved flops the ranking INVERTS — compare the per-shape "
            "entries to locate the crossover. Pick a mode by which "
            "resource binds: recompute-heavy (interval>=1) when "
            "HBM-limited, save_stage_residuals only when the stage's "
            "residuals are small relative to its compute. CAVEAT: "
            "these are host-CPU wall clocks — run on an otherwise "
            "IDLE machine or the ratios inflate",
            "compile_counted_gflops counts each loop body ONCE (trip "
            "counts are invisible to cost_analysis); mode DIFFERENCES "
            "isolate the backward phase's recompute flops",
            "CAVEAT: dp-vs-pp columns are NOT per-device-work "
            "comparable (S stages divide the layers; dp runs M jit "
            "dispatches where train_batch runs one) — compare within "
            "the pp rows; the dp pair exists to validate the flop "
            "model (dp_block/dp_no vs the compile-counted ratio)",
            "guidance: pp_block_remat (interval>=1) pays 5F/3F NESTED "
            "remat and is only right when one stage's single-microbatch "
            "interior residuals do not fit HBM; interval=0 is the "
            "default-sane choice (4F, DP-remat parity, transient "
            "residuals for ONE microbatch); save_stage_residuals=True "
            "reaches the no-remat 3F floor but buffers W in-flight "
            "pullbacks (W copies of residuals AND stage params) — only "
            "for small/shallow stages",
        ],
    }
    path = os.path.join(os.path.dirname(__file__), "PP_REMAT_TAX.json")
    doc = {"shapes": []}
    if os.path.exists(path):
        try:
            old = json.load(open(path))
            doc["shapes"] = old.get("shapes") or ([old] if "config" in old
                                                  else [])
        except Exception:
            pass
    key = lambda e: (e["config"]["d_model"], e["config"]["seq"])
    doc["shapes"] = [e for e in doc["shapes"] if key(e) != key(out)] + [out]
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(out["measured_ratio_vs_dp_no_remat"]))


if __name__ == "__main__":
    main()
