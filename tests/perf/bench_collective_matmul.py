"""Collective-matmul microbench: fused vs unfused ZeRO-3 + TP step.

Two engines of the same small GPT-2 on a (data x model) mesh — the
unfused XLA oracle vs ``comm.collective_matmul`` (ring-decomposed
stage-3 weight gathers + fused TP GEMMs) — measured in INTERLEAVED
blocks like bench_telemetry_overhead.py (sequential whole-run blocks
alias machine drift on a shared CPU box). Emits one JSON line in
bench.py's shape (validated by bin/check_bench_schema.py) plus the
committed artifact tests/perf/BENCH_COLLECTIVE_MATMUL.json.

value = fused median step time; vs_baseline = unfused/fused (> 1 means
fused is faster). On the CPU rung there is no ICI to hide, so the
honest expectation is ~1.0 (the ring adds real ppermutes XLA's CPU
lowering cannot overlap) — the artifact exists to pin the machinery,
the wire-byte equality, and the per-class overlap_efficiency records;
the latency win is a TPU claim priced by wire.overlap_report.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()

ROUNDS = 6
BLOCK = 4
WARMUP = 2


def _engine(fused):
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from bench import scratch_telemetry_dir
    cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=128, n_layers=4,
                          n_heads=4, d_model=256,
                          use_flash_attention=False, remat=False)
    ds = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3,
                              "stage3_param_persistence_threshold": 0},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9,
        "telemetry": {"enabled": True,
                      "output_path": scratch_telemetry_dir(
                          "cm_bench_{}_".format("on" if fused
                                                else "off"))},
    }
    if fused:
        ds["comm"] = {"collective_matmul": {"enabled": True, "chunks": 2}}
    engine = DeepSpeedEngine(model=gpt2.make_gpt2_model(config=cfg),
                             mesh=build_mesh(data=2, model=2),
                             config_params=ds)
    return engine, cfg


def main():
    import jax
    eng_off, cfg = _engine(False)
    eng_on, _ = _engine(True)
    assert eng_on._cm_tp and eng_on._cm_zero3

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size,
                      size=(1, 2 * eng_off.dp_world_size,
                            cfg.max_seq_len)).astype(np.int32)

    def step(eng):
        return eng.train_batch(batch=(ids, ids.copy()))

    losses = {}
    for name, eng in (("off", eng_off), ("on", eng_on)):
        for _ in range(WARMUP):
            losses[name] = float(step(eng))
    times = {"off": [], "on": []}
    ratios = []
    for r in range(ROUNDS):
        order = [("off", eng_off), ("on", eng_on)]
        if r % 2:
            order.reverse()
        med = {}
        for name, eng in order:
            block = []
            for _ in range(BLOCK):
                t0 = time.time()
                float(step(eng))
                block.append(time.time() - t0)
            times[name].extend(block)
            med[name] = float(np.median(block))
        ratios.append(med["off"] / med["on"])

    off = float(np.median(times["off"]))
    on = float(np.median(times["on"]))
    snap = eng_on.telemetry_snapshot()
    overlap = snap.get("comm_overlap_last")
    rel_loss = abs(losses["on"] - losses["off"]) / \
        max(abs(losses["off"]), 1e-9)
    payload = {
        "metric": "collective_matmul_fused_step_time",
        "value": round(on, 6),
        "unit": "s/step",
        # unfused/fused median-of-paired-ratios: > 1 means fused faster
        "vs_baseline": round(float(np.median(ratios)), 4),
        "extra": {
            "median_step_s_unfused": round(off, 6),
            "median_step_s_fused": round(on, 6),
            "per_round_off_on_ratios": [round(r, 4) for r in ratios],
            "steps_per_engine": WARMUP + ROUNDS * BLOCK,
            "warmup_loss_rel_diff": round(rel_loss, 6),
            "comm_overlap_last": overlap,
            "wire_collective_matmul":
                (snap.get("wire") or {}).get("collective_matmul"),
            "chunks": 2,
            "mesh": {"data": 2, "model": 2},
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "backend": jax.default_backend(),
            "telemetry": snap,
        },
    }
    print(json.dumps(payload))
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_COLLECTIVE_MATMUL.json")
    with open(out, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
