"""Paired serial-vs-overlap microbench of the executor-lowered classic
ZeRO-Offload step (ISSUE 13).

Two engines over identical data — ``runtime.executor: "off"`` (the
serial oracle: every segment inline in plan order, zero constructed
overlap) vs ``"on"`` (async D2H fetches windowed ahead of the host
Adam, uploads riding the coalescing batcher) — interleaved per round so
machine drift cancels. Asserts the two streams are BIT-IDENTICAL
(the executor's numerics contract), then reports the median step-wall
ratio and the constructed per-segment overlap the bespoke pre-executor
path never reported.

Writes tests/perf/BENCH_EXECUTOR_OVERLAP.json (bench.py-shaped;
bin/check_bench_schema.py validates, including the SEGMENT_KEYS
``extra.executor`` block).
"""
import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

ROUNDS = 3
STEPS_PER_ROUND = 5


def _engine(mode, tele_dir):
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.GPT2Config(vocab_size=256, max_seq_len=128, n_layers=4,
                          n_heads=4, d_model=128,
                          use_flash_attention=False, remat=False,
                          loss_chunk=0)
    engine, _, _, _ = deepspeed.initialize(
        model=gpt2.make_gpt2_model(config=cfg),
        config_params={
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2, "cpu_offload": True,
                                  "sub_group_size": 65536},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "runtime": {"executor": mode},
            "steps_per_print": 10 ** 9,
            "telemetry": {"enabled": True, "output_path": tele_dir},
        })
    return engine, cfg


def main():
    from bench import scratch_telemetry_dir
    engines = {}
    for mode in ("off", "on"):
        engines[mode] = _engine(
            mode, scratch_telemetry_dir("bench_exec_%s_" % mode))
    cfg = engines["on"][1]
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size,
                      size=(4, cfg.max_seq_len)).astype(np.int32)

    def step(engine):
        loss = engine(ids, ids.copy())
        engine.backward(loss)
        engine.step()
        return float(loss)

    # warmup/compile both
    losses = {m: [step(e)] for m, (e, _) in engines.items()}
    walls = {"off": [], "on": []}
    for _ in range(ROUNDS):
        for mode in ("off", "on"):
            engine = engines[mode][0]
            t0 = time.time()
            for _ in range(STEPS_PER_ROUND):
                losses[mode].append(step(engine))
            walls[mode].append((time.time() - t0) / STEPS_PER_ROUND)
    assert losses["off"] == losses["on"], \
        "executor modes diverged: {} vs {}".format(
            losses["off"][-1], losses["on"][-1])

    med = {m: statistics.median(w) for m, w in walls.items()}
    snaps = {m: engines[m][0].telemetry_snapshot()["offload_last"]
             for m in ("off", "on")}
    payload = {
        "metric": "offload_executor_overlap_step_ratio",
        # >1.0 = the constructed overlap beat the serial oracle
        "value": round(med["off"] / med["on"], 4),
        "unit": "x (serial wall / overlap wall)",
        "vs_baseline": None,
        "extra": {
            "serial_sec_per_step_median": round(med["off"], 4),
            "overlap_sec_per_step_median": round(med["on"], 4),
            "rounds": ROUNDS, "steps_per_round": STEPS_PER_ROUND,
            "loss_last": losses["on"][-1],
            "bit_identical": True,
            "offload_last": {"serial": snaps["off"],
                             "overlap": snaps["on"]},
            "executor": engines["on"][0].executor_snapshot(),
            "telemetry": engines["on"][0].telemetry_snapshot(),
        },
    }
    out = os.path.join(os.path.dirname(__file__),
                       "BENCH_EXECUTOR_OVERLAP.json")
    with open(out, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    print(json.dumps({k: payload[k] for k in
                      ("metric", "value", "unit")}))
    print("serial {:.4f}s/step overlap {:.4f}s/step -> {}x; "
          "overlap_efficiency serial={} overlap={}".format(
              med["off"], med["on"], payload["value"],
              snaps["off"].get("overlap_efficiency"),
              snaps["on"].get("overlap_efficiency")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
