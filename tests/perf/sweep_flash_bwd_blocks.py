"""Backward-block sweep for the packed flash kernels (fwd pinned at
256/512, the measured best). Amortized scan timing; grad-only deltas.

    python tests/perf/sweep_flash_bwd_blocks.py [--b 96]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

REPS = 8


def timed_scan(step_fn, init, reps=REPS):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(x):
        def body(c, _):
            return step_fn(c), None
        out, _ = jax.lax.scan(body, x, None, length=reps)
        return out.astype(jnp.float32).ravel()[0]

    float(run(init))
    t0 = time.time()
    float(run(init))
    return round(((time.time() - t0) - 0.094) / reps * 1e3, 1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--b", type=int, default=96)
    parser.add_argument("--s", type=int, default=1024)
    parser.add_argument("--h", type=int, default=16)
    parser.add_argument("--d", type=int, default=64)
    args = parser.parse_args()
    b, s, h, d = args.b, args.s, args.h, args.d

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.transformer import flash_attention as fa

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, s, h, d) * 0.1, jnp.bfloat16)

    rows = {}
    for bbq, bbk in [(256, 512), (256, 256), (128, 512), (128, 1024),
                     (256, 1024), (512, 512), (128, 256), (512, 256)]:
        def grad_step(t, bbq=bbq, bbk=bbk):
            g = jax.grad(lambda q: fa.flash_attention_bshd(
                q, q, q, bwd_block_q=bbq, bwd_block_k=bbk)
                .astype(jnp.float32).sum())(t)
            return g.astype(t.dtype)

        key = "bwd_q{}_k{}".format(bbq, bbk)
        try:
            rows[key] = timed_scan(grad_step, x)
        except Exception as e:  # noqa: BLE001
            rows[key] = "failed: " + str(e)[:90]
    print(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
