"""Paged-attention microbench: Pallas page-walk kernel vs XLA gather.

Two paged serving engines of the same small GPT-2 — the XLA
``jnp.take`` gather-back oracle vs the ``ops/pallas/paged_attention``
in-kernel page walk (``inference.paged_attention_kernel``) — driving
the SAME greedy decode workload in INTERLEAVED blocks (sequential
whole-run blocks alias machine drift on a shared box; the
bench_telemetry_overhead.py discipline). Emits one JSON line in
bench.py's shape (validated by bin/check_bench_schema.py) plus the
committed artifact tests/perf/BENCH_PAGED_ATTN.json.

value = kernel-path median decode-step time; vs_baseline = gather /
kernel (> 1 means the kernel is faster). On the CPU rung the kernel
runs under the Pallas INTERPRETER (per-op python dispatch), so the
honest expectation is vs_baseline << 1 — the artifact pins the
harness, the byte-identical greedy streams, and the decode-program
shape; the bytes-touched win (2 pages vs the full logical window per
slot per layer) is a TPU claim (docs/pallas_kernels.md).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROUNDS = 4
BLOCK = 6          # decode steps per block
WARMUP = 2
NUM_SLOTS = 4
PAGE_SIZE = 8


def _engine(kernel):
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=256, n_layers=2,
                          n_heads=4, d_model=128,
                          use_flash_attention=False, remat=False,
                          loss_chunk=0)
    eng = deepspeed.init_inference(
        model=gpt2.make_gpt2_model(config=cfg),
        config={"inference": {
            "max_batch_size": NUM_SLOTS, "prefill_buckets": [64],
            "dtype": "fp32", "greedy": True, "kv_layout": "paged",
            "kv_block_size": PAGE_SIZE,
            "paged_attention_kernel": kernel}})
    assert eng.paged_attention_kernel == kernel
    return eng


def main():
    import jax
    eng_x = _engine("xla")
    eng_p = _engine("pallas")
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 512, size=40 + 7 * i).tolist()
               for i in range(NUM_SLOTS)]

    # occupy every slot with a prefilled sequence, then drive the fused
    # all-slot decode step directly — the program under test
    pend = {}
    for name, eng in (("xla", eng_x), ("pallas", eng_p)):
        toks = []
        for slot, prompt in enumerate(prompts):
            assert eng.try_admit(slot, prompt)
            toks.append(eng.prefill(slot, prompt))
        pend[name] = np.asarray(toks, np.int32)

    def decode(eng, name):
        for slot in range(NUM_SLOTS):
            assert eng.ensure_pages(slot, int(eng.lengths[slot]) + 1)
        chosen = eng.decode_step(pend[name])
        for slot in range(NUM_SLOTS):
            eng.advance(slot)
        pend[name] = np.asarray(chosen, np.int32)
        return chosen

    streams = {"xla": [], "pallas": []}
    for name, eng in (("xla", eng_x), ("pallas", eng_p)):
        for _ in range(WARMUP):
            streams[name].append(decode(eng, name).tolist())
    times = {"xla": [], "pallas": []}
    ratios = []
    for r in range(ROUNDS):
        order = [("xla", eng_x), ("pallas", eng_p)]
        if r % 2:
            order.reverse()
        med = {}
        for name, eng in order:
            block = []
            for _ in range(BLOCK):
                t0 = time.time()
                chosen = decode(eng, name)
                block.append(time.time() - t0)
                streams[name].append(chosen.tolist())
            times[name].extend(block)
            med[name] = float(np.median(block))
        ratios.append(med["xla"] / med["pallas"])

    # the acceptance bit, measured on the bench workload itself: every
    # decode step's chosen tokens byte-identical across read paths
    assert streams["xla"] == streams["pallas"], "streams diverged"

    xla = float(np.median(times["xla"]))
    pal = float(np.median(times["pallas"]))
    payload = {
        "metric": "paged_attention_pallas_decode_step_time",
        "value": round(pal, 6),
        "unit": "s/step",
        # gather/kernel median-of-paired-ratios: > 1 means kernel faster
        "vs_baseline": round(float(np.median(ratios)), 4),
        "extra": {
            "median_step_s_xla_gather": round(xla, 6),
            "median_step_s_pallas": round(pal, 6),
            "per_round_xla_pallas_ratios": [round(r, 4) for r in ratios],
            "decode_steps_per_engine": WARMUP + ROUNDS * BLOCK,
            "greedy_streams_byte_identical": True,
            "num_slots": NUM_SLOTS,
            "page_size": PAGE_SIZE,
            "seq_lens_at_start": [len(p) for p in prompts],
            "interpreter_mode": jax.default_backend() != "tpu",
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "backend": jax.default_backend(),
        },
    }
    print(json.dumps(payload))
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_PAGED_ATTN.json")
    with open(out, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
