"""Flash backward at gpt2-xl width (h*d = 1600): grouped-fused vs split.

The single-pass fused backward caps at hd = 1280 per call; past that
_bwd_packed runs it per head group (25 heads -> 13 + 12, widths 832/768).
This times the full grad path (flash_attention_bshd grad wrt q/k/v) under
both policies on the real chip, at a 1-2-layer-sized batch that fits HBM.

    python tests/perf/compare_xl_bwd.py [--b 8]

Emits JSON {grouped_fused_grad_ms, split_grad_ms, speedup, ...}.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def _force(x):
    import jax
    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(leaf.ravel()[0])


def timed_inner(step, q, k, v, reps=10, outer=3):
    """Amortize the ~110 ms axon-tunnel dispatch latency: run ``step``
    ``reps`` times INSIDE one jit call, chained through a data dependency,
    and report per-rep wall time."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def loop(q, k, v):
        def body(_, carry):
            q, k, v = carry
            dq, dk, dv = step(q, k, v)
            eps = jnp.bfloat16(1e-6)
            return (q + eps * dq.astype(q.dtype),
                    k + eps * dk.astype(k.dtype),
                    v + eps * dv.astype(v.dtype))
        return lax.fori_loop(0, reps, body, (q, k, v))

    _force(loop(q, k, v))
    best = None
    for _ in range(outer):
        t0 = time.time()
        _force(loop(q, k, v))
        dt = (time.time() - t0) * 1e3 / reps
        best = dt if best is None else min(best, dt)
    return round(best, 2)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--b", type=int, default=8)
    parser.add_argument("--s", type=int, default=1024)
    parser.add_argument("--h", type=int, default=25)
    parser.add_argument("--d", type=int, default=64)
    args = parser.parse_args()
    b, s, h, d = args.b, args.s, args.h, args.d

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.transformer import flash_attention as fa

    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d) * 0.1, jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    rows = {"shape": {"b": b, "s": s, "h": h, "d": d, "hd": h * d},
            "device": jax.devices()[0].device_kind}

    def loss(q, k, v):
        return fa.flash_attention_bshd(q, k, v).astype(jnp.float32).sum()

    grad = jax.grad(loss, argnums=(0, 1, 2))

    # grouped fused (opt-in: DS_FLASH_FUSED_BWD=1; split is the
    # measured-faster default on the current chip/runtime)
    fa.FUSED_BWD = True
    groups = fa._head_groups(h, d)
    rows["groups"] = groups
    rows["grouped_auto_blocks"] = fa.auto_blocks(h * d, num_heads=h)
    rows["grouped_fused_grad_ms"] = timed_inner(grad, q, k, v)

    # split (the default path)
    fa.FUSED_BWD = False
    rows["split_auto_blocks"] = fa.auto_blocks(h * d, num_heads=h)
    rows["split_grad_ms"] = timed_inner(grad, q, k, v)

    rows["speedup_grad"] = round(
        rows["split_grad_ms"] / rows["grouped_fused_grad_ms"], 3)
    path = os.path.join(os.path.dirname(__file__), "XL_BWD_COMPARE.json")
    with open(path, "w") as f:
        json.dump(rows, f, indent=2)
    print(json.dumps(rows))


if __name__ == "__main__":
    main()
