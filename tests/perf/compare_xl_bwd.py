"""Flash backward bake-off across model widths: resident-dq fused vs
explicit-DMA fused vs the split dq + dk/dv pair.

The single-pass fused backward (5 dots/pair vs split's 7) comes in two
variants: the resident-dq kernel (dq accumulates in a whole-(s, h*d) fp32
VMEM output block — no cross-walk DMAs) and the older explicit-DMA
read-modify-write kernel. This times the full grad path
(flash_attention_bshd grad wrt q/k/v) under all three policies on the
real chip at GPT-2-medium (hd 1024), 1280, and gpt2-xl (hd 1600, grouped
13+12 heads) widths.

The chip sits behind a SHARED tunnel: single-shot timings swing 10-40%
with tenant contention (one probed sample hit 2x). All paths are
therefore compiled up front and timed in interleaved round-robin ROUNDS;
the reported number is the per-path MINIMUM (the uncontended floor),
with the median alongside so the artifact shows the noise it was
measured under.

    python tests/perf/compare_xl_bwd.py

Writes XL_BWD_COMPARE.json; the shipped default (auto) must match the
per-width winner.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

REPS = 10          # grad steps chained inside one jit call
ROUNDS = 12        # interleaved timing rounds per path


def _force(x):
    import jax
    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(leaf.ravel()[0])


def _make_loop(q, k, v):
    """Compile a REPS-step chained grad loop under the CURRENT dispatch
    mode (the mode is baked in at trace time)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from deepspeed_tpu.ops.transformer import flash_attention as fa

    def loss(q, k, v):
        return fa.flash_attention_bshd(q, k, v).astype(jnp.float32).sum()

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def loop(q, k, v):
        def body(_, carry):
            q, k, v = carry
            dq, dk, dv = grad(q, k, v)
            eps = jnp.bfloat16(1e-6)
            return (q + eps * dq.astype(q.dtype),
                    k + eps * dk.astype(k.dtype),
                    v + eps * dv.astype(v.dtype))
        return lax.fori_loop(0, REPS, body, (q, k, v))

    _force(loop(q, k, v))                      # compile + warm
    return loop


def measure_width(b, s, h, d):
    from deepspeed_tpu.ops.transformer import flash_attention as fa

    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d) * 0.1, jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    row = {"b": b, "s": s, "h": h, "d": d, "hd": h * d}

    saved_budget = fa.RESIDENT_DQ_MAX_BYTES
    loops = {}

    fa.BWD_MODE = "auto"
    row["auto_plan"] = fa._fused_plan(h * d, h, s)
    row["auto_blocks"] = fa.auto_blocks(h * d, num_heads=h, seq_len=s)
    loops["resident_fused"] = _make_loop(q, k, v)

    fa.BWD_MODE = "fused"
    fa.RESIDENT_DQ_MAX_BYTES = 0          # force the explicit-DMA variant
    loops["dma_fused"] = _make_loop(q, k, v)
    fa.RESIDENT_DQ_MAX_BYTES = saved_budget

    fa.BWD_MODE = "split"
    row["split_blocks"] = fa.auto_blocks(h * d, num_heads=h, seq_len=s)
    loops["split"] = _make_loop(q, k, v)
    fa.BWD_MODE = "auto"

    samples = {name: [] for name in loops}
    for _ in range(ROUNDS):
        for name, loop in loops.items():
            t0 = time.time()
            _force(loop(q, k, v))
            samples[name].append((time.time() - t0) * 1e3 / REPS)
    for name, xs in samples.items():
        row[f"{name}_grad_ms"] = round(min(xs), 2)
        row[f"{name}_grad_ms_median"] = round(sorted(xs)[len(xs) // 2], 2)
    row["resident_vs_split"] = round(
        row["split_grad_ms"] / row["resident_fused_grad_ms"], 3)
    row["resident_vs_dma"] = round(
        row["dma_fused_grad_ms"] / row["resident_fused_grad_ms"], 3)
    return row


def main():
    import jax
    out = {"device": jax.devices()[0].device_kind,
           "method": f"min over {ROUNDS} interleaved rounds of {REPS} "
                     "chained grad steps (shared-chip contention makes "
                     "single-shot timings swing 10-40%)",
           "widths": [measure_width(96, 1024, 16, 64),   # bench shape
                      measure_width(24, 1024, 20, 64),   # hd 1280
                      measure_width(8, 1024, 25, 64)]}   # gpt2-xl, grouped
    path = os.path.join(os.path.dirname(__file__), "XL_BWD_COMPARE.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
