"""Sparse vs dense attention crossover sweep on the real chip.

Times fwd+bwd attention (grad wrt q/k/v, scan-amortized) for the dense
packed flash kernel vs the block-sparse kernel (fixed layout: local
window + global blocks, unidirectional) across sequence lengths, and
writes tests/perf/SPARSE_VS_DENSE.json with the measured crossover.

The sparse timing includes the (b,s,h,d)->(b,h,s,d) relayout its kernel
needs — the honest end-to-end cost from the model's activation layout.

    python tests/perf/sweep_sparse_vs_dense.py
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

HEADS, DHEAD = 16, 64
BATCH = 2
REPS = 12


def _roundtrip_s():
    """Per-run calibration of the tunnel/dispatch constant: the wall time
    of fetching one scalar from an already-compiled trivial jit. A fixed
    constant drifts run to run (and once measured -0.6 ms for a 2k dense
    layer); calibrating each sweep keeps the small-ms rows honest."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0.0)
    float(f(x))
    ts = []
    for _ in range(5):
        t0 = time.time()
        float(f(x))
        ts.append(time.time() - t0)
    return sorted(ts)[len(ts) // 2]


_RT = None


def timed_scan(step_fn, init, reps=REPS):
    import jax
    import jax.numpy as jnp
    global _RT
    if _RT is None:
        _RT = _roundtrip_s()

    @jax.jit
    def run(x):
        def body(c, _):
            return step_fn(c), None
        out, _ = jax.lax.scan(body, x, None, length=reps)
        return out.astype(jnp.float32).ravel()[0]

    float(run(init))
    t0 = time.time()
    float(run(init))
    return ((time.time() - t0) - _RT) / reps * 1e3


def main():
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    from deepspeed_tpu.ops.sparse_attention import (
        BigBirdSparsityConfig, FixedSparsityConfig,
        make_block_sparse_attention)
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        causal_sliding_window_layout)

    results = {"config": {
        "batch": BATCH, "heads": HEADS, "d_head": DHEAD,
        "sparse": "fixed, block 128, 4 local blocks + 1 global, "
                  "unidirectional",
        "timing": "fwd+bwd (grad wrt q,k,v), scan-amortized, ms/layer",
        "bigbird_note": "bigbird (a bidirectional-class layout in the "
                        "reference) is run with causal=True: its "
                        "above-diagonal active blocks are fetched and "
                        "computed but fully masked, so the row is "
                        "COST-faithful for the layout while the math is "
                        "causal, and its reported density overstates "
                        "useful (unmasked) work",
    }, "rows": []}

    for seq in (2048, 4096, 8192, 16384, 32768, 65536):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(BATCH, seq, HEADS, DHEAD) * 0.1,
                        jnp.bfloat16)

        def dense_step(t):
            g = jax.grad(lambda q: fa.flash_attention_bshd(q, q, q)
                         .astype(jnp.float32).sum())(t)
            return g.astype(t.dtype)

        # short sequences run sub-ms per layer: scale reps up so the
        # scan-amortized total dwarfs the tunnel roundtrip jitter (a
        # fixed 12 reps once measured a negative dense ms at 2k)
        reps = max(REPS, (16384 // seq) * REPS)

        row = {"seq": seq}
        try:
            row["dense_ms"] = round(timed_scan(dense_step, x, reps=reps), 2)
        except Exception as err:  # noqa: BLE001
            row["dense_ms"] = "failed: " + str(err)[:80]

        block = 128
        cfg = FixedSparsityConfig(num_heads=HEADS, block=block,
                                  num_local_blocks=4, num_global_blocks=1,
                                  attention="unidirectional")
        layout = np.asarray(cfg.make_layout(seq))
        # pure sliding-window (8 blocks = 1024 tokens lookback): the
        # truly LINEAR layout — the fixed mode's global columns keep its
        # active count growing with position (still ~quadratic overall)
        nb = seq // block
        win = causal_sliding_window_layout(HEADS, nb, 8)
        # bigbird (ITC): window + random + leading-global — the SKEWED
        # layout class the balanced grid exists for (global rows/cols
        # populate a few rows far past the mean)
        bb = np.asarray(BigBirdSparsityConfig(
            num_heads=HEADS, block=block, num_random_blocks=2,
            num_sliding_window_blocks=3, num_global_blocks=1,
            seed=0).make_layout(seq))

        for name, lay in (("sparse", layout), ("window", win),
                          ("bigbird", bb)):
            density = float(lay.mean())
            row[name + "_density"] = round(density, 4)
            attn = make_block_sparse_attention(lay, block, causal=True)

            def sparse_step(t, attn=attn):
                def loss(q):
                    qh = q.transpose(0, 2, 1, 3)   # (b,h,s,d) kernel layout
                    out = attn(qh, qh, qh, None, None)
                    return out.astype(jnp.float32).sum()
                g = jax.grad(loss)(t)
                return g.astype(t.dtype)

            try:
                row[name + "_ms"] = round(
                    timed_scan(sparse_step, x, reps=reps), 2)
            except Exception as err:  # noqa: BLE001
                row[name + "_ms"] = "failed: " + str(err)[:80]

        for name in ("sparse", "window", "bigbird"):
            if isinstance(row.get("dense_ms"), float) and \
                    isinstance(row.get(name + "_ms"), float) and \
                    row["dense_ms"] > 0:
                row[name + "_vs_dense"] = round(
                    row[name + "_ms"] / row["dense_ms"], 2)
        results["rows"].append(row)
        print(json.dumps(row), flush=True)

    for name in ("sparse", "window", "bigbird"):
        wins = [r for r in results["rows"]
                if isinstance(r.get(name + "_ms"), float)
                and isinstance(r.get("dense_ms"), float)
                and r[name + "_ms"] < r["dense_ms"]]
        results[name + "_crossover"] = (
            min(w["seq"] for w in wins) if wins else
            "none at tested lengths")
    path = os.path.join(os.path.dirname(__file__), "SPARSE_VS_DENSE.json")
    with open(path, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps({k: results[k] for k in
                      ("sparse_crossover", "window_crossover",
                       "bigbird_crossover")}))


if __name__ == "__main__":
    main()
