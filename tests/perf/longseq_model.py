"""Model-level long-sequence capability: dense flash vs ds_config sparse.

The reference's sparse-attention headline is MODEL-level — "10x longer
sequences" (README.md:17,39 + the 2020-09-08 sparse-attention post) —
while this repo's sparse evidence was kernel sweeps. This trains a
GPT-2-medium-class model end to end THROUGH the engine + the ds_config
"sparse_attention" surface (GPT2Config.sparse_attention =
engine.sparse_attention_config()) on one chip, dense vs sliding-window
sparse, and records tokens/s + finite losses per sequence length, plus
the max trainable length per mode.

    python tests/perf/longseq_model.py [--seqs 16384 32768 65536 131072]

Writes tests/perf/LONGSEQ_MODEL.json.
"""
import argparse
import gc
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

SPARSE = {"mode": "sliding_window", "block": 128,
          "num_sliding_window_blocks": 8}      # 1024-token causal window
LAYERS = 24
D_MODEL = 1024
HEADS = 16
VOCAB = 50304


def run_one(seq, sparse, steps=3):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    ds = {"train_micro_batch_size_per_gpu": 1,
          "gradient_accumulation_steps": 1,
          "bf16": {"enabled": True},
          "zero_optimization": {"stage": 2},
          "optimizer": {"type": "Adam",
                        "params": {"lr": 1e-4, "moments_dtype": "bf16"}},
          "data_types": {"grad_accum_dtype": "bf16"},
          "steps_per_print": 10 ** 9}
    if sparse:
        ds["sparse_attention"] = dict(SPARSE)
    engine = None
    try:
        cfg = gpt2.GPT2Config(
            vocab_size=VOCAB, max_seq_len=seq, n_layers=LAYERS,
            n_heads=HEADS, d_model=D_MODEL, remat=True, loss_chunk=128,
            sparse_attention=dict(SPARSE) if sparse else None)
        engine, _, _, _ = deepspeed.initialize(
            model=gpt2.make_gpt2_model(config=cfg), config_params=ds)
        if sparse:
            # the reference flow: the model consumes the ENGINE's parsed
            # sparse config — assert the two surfaces agree
            assert engine.sparse_attention_config() == SPARSE
            assert cfg.sparse_attention == engine.sparse_attention_config()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, VOCAB, size=(1, seq)).astype(np.int32)
        x = jnp.asarray(ids)
        y = jnp.roll(x, -1, axis=1)
        # TWO warm steps: the first compiles micro+apply; the SECOND
        # recompiles micro once more (the donated state's jit-output
        # layouts differ from the init-time device_put layouts at these
        # shapes) — timing from step 3 measures the steady state
        t0 = time.time()
        losses = [float(_train_step(engine, x, y))]
        losses.append(float(_train_step(engine, x, y)))
        compile_s = time.time() - t0
        t0 = time.time()
        for _ in range(steps):
            losses.append(float(_train_step(engine, x, y)))
        dt = (time.time() - t0) / steps
        row = {"seq": seq, "mode": "sparse" if sparse else "dense",
               "fits": True,
               "tokens_per_sec": round(seq / dt, 1),
               "sec_per_step": round(dt, 2),
               "compile_and_first_step_s": round(compile_s, 1),
               "losses": [round(l, 3) for l in losses],
               "finite": all(np.isfinite(losses))}
    except AssertionError:
        raise                # a wiring bug must not publish as an OOM row
    except Exception as e:  # noqa: BLE001 — OOM rows are the data
        msg = str(e)
        # surface the root-cause line, not the HTTP wrapper
        for marker in ("Ran out of memory", "RESOURCE_EXHAUSTED",
                       "exceeded scoped vmem", "MosaicError"):
            at = msg.find(marker)
            if at >= 0:
                msg = msg[at:at + 400]
                break
        row = {"seq": seq, "mode": "sparse" if sparse else "dense",
               "fits": False, "error": msg[:400]}
    finally:
        del engine
        gc.collect()
        import jax as _jax
        _jax.clear_caches()
    print(json.dumps(row), flush=True)
    return row


def _train_step(engine, x, y):
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    return loss


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seqs", type=int, nargs="+",
                        default=[16384, 32768, 65536, 131072])
    parser.add_argument("--steps", type=int, default=3)
    args = parser.parse_args()
    import jax

    rows = []
    for seq in args.seqs:
        for sparse in (False, True):
            rows.append(run_one(seq, sparse, steps=args.steps))

    max_fit = {m: max([r["seq"] for r in rows
                       if r["mode"] == m and r.get("fits")], default=0)
               for m in ("dense", "sparse")}
    out = {
        "config": {"model": f"GPT-2-medium-class ({LAYERS}L x {D_MODEL}, "
                            f"{HEADS} heads, vocab {VOCAB})",
                   "micro_batch": 1, "zero_stage": 2,
                   "state": "bf16 moments + bf16 grad accum",
                   "sparse": SPARSE,
                   "device": jax.devices()[0].device_kind,
                   "path": "engine + ds_config sparse_attention "
                           "(tests/perf/longseq_model.py)"},
        "rows": rows,
        "max_trainable_seq": max_fit,
        "reference_claim": "'10x longer sequences' "
                           "(reference README.md:17,39)",
    }
    path = os.path.join(os.path.dirname(__file__), "LONGSEQ_MODEL.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({"max_trainable_seq": max_fit}))


if __name__ == "__main__":
    main()
