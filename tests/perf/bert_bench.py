"""BERT-large pretraining throughput on one chip — the reference's
headline benchmark (docs/_posts/2020-05-28-fastest-bert-training.md:
64 TFLOPS/GPU and 272 samples/s at seq 128, 53 TFLOPS and 52 samples/s at
seq 512, on one V100-32G). Prints the same two shapes measured here.

    python tests/perf/bert_bench.py
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def run(seq, micro_batch, steps=10, warmup=3, bf16_state=True):
    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import bert

    cfg = bert.config_for("bert_large", max_seq_len=seq, dropout=0.0,
                          attn_dropout=0.0)
    model = bert.make_bert_model(config=cfg)
    n_params = bert.num_params(cfg)
    engine, _, _, _ = deepspeed.initialize(model=model, config_params={
        "train_micro_batch_size_per_gpu": micro_batch,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Lamb", "params": dict(
            {"lr": 2e-3},
            **({"moments_dtype": "bf16"} if bf16_state else {}))},
        **({"data_types": {"grad_accum_dtype": "bf16"}}
           if bf16_state else {}),
        "steps_per_print": 10 ** 9,
    })
    rs = np.random.RandomState(0)
    b = micro_batch
    batch = tuple(x[None] for x in (
        rs.randint(0, cfg.vocab_size, size=(b, seq)).astype(np.int32),
        np.zeros((b, seq), np.int32),
        np.ones((b, seq), np.int32),
        rs.randint(0, cfg.vocab_size, size=(b, seq)).astype(np.int32),
        rs.randint(0, 2, size=(b,)).astype(np.int32),
    ))
    for _ in range(warmup):
        loss = engine.train_batch(batch=batch)
    float(loss)
    t0 = time.time()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    float(loss)
    dt = (time.time() - t0) / steps
    samples_per_s = b / dt
    # 6N per token + attention scores/ctx (non-causal: full s^2)
    flops_per_token = (6.0 * n_params
                       + 12.0 * cfg.n_layers * cfg.d_model * seq)
    tflops = samples_per_s * seq * flops_per_token / 1e12
    return dict(seq=seq, micro_batch=b, step_ms=round(dt * 1e3, 1),
                samples_per_s=round(samples_per_s, 1),
                tflops_per_chip=round(tflops, 1),
                ref_v100=dict(seq128="64 TFLOPS / 272 samples/s",
                              seq512="53 TFLOPS / 52 samples/s")[
                    "seq{}".format(seq)] if seq in (128, 512) else None)


def main():
    for seq, mb_ladder in [(128, [384, 320, 256, 128]),
                           (512, [96, 80, 64, 32])]:
        for mb in mb_ladder:
            try:
                print(json.dumps(run(seq, mb)), flush=True)
                break
            except Exception as e:  # noqa: BLE001
                print("seq={} mb={} failed: {}".format(seq, mb, str(e)[:80]),
                      file=sys.stderr)


if __name__ == "__main__":
    main()
