"""Probe: block-sparse LAYOUT-granularity trade-off (fixed + bigbird).

With pack-grouping the kernel already amortizes per-step overhead at
block 128 (each grid step runs 1024 tokens' worth of k/v blocks), so
this probe measures the remaining trade: a coarser layout block raises
per-dot MXU efficiency but inflates the layout's density (a global
column doubles its token width with the block). Historically it also
diagnosed the pre-pack kernel's flat per-step overhead.

    python tests/perf/probe_sparse_block.py [--seq 16384]
"""
import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# calibrated timer shared with the sweep (a hardcoded roundtrip constant
# drifts run to run and can go negative at short sequence lengths)
from sweep_sparse_vs_dense import timed_scan  # noqa: E402

HEADS, DHEAD = 16, 64
BATCH = 2


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq", type=int, default=16384)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention import (
        BigBirdSparsityConfig, FixedSparsityConfig,
        make_block_sparse_attention)

    seq = args.seq
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(BATCH, seq, HEADS, DHEAD) * 0.1, jnp.bfloat16)

    # same effective pattern at both granularities: ~512-token local
    # window + one global stripe per fixed window
    cases = []
    for block, nloc in ((128, 4), (256, 2), (512, 1)):
        cases.append(("fixed_b{}".format(block), block, FixedSparsityConfig(
            num_heads=HEADS, block=block, num_local_blocks=nloc,
            num_global_blocks=1, attention="unidirectional")))
    for block, nwin in ((128, 3), (256, 3)):
        cases.append(("bigbird_b{}".format(block), block,
                      BigBirdSparsityConfig(
                          num_heads=HEADS, block=block, num_random_blocks=2,
                          num_sliding_window_blocks=nwin, num_global_blocks=1,
                          seed=0)))

    for name, block, cfg in cases:
        lay = np.asarray(cfg.make_layout(seq))
        attn = make_block_sparse_attention(lay, block, causal=True)

        def step(t, attn=attn):
            def loss(q):
                qh = q.transpose(0, 2, 1, 3)
                return attn(qh, qh, qh, None, None) \
                    .astype(jnp.float32).sum()
            return jax.grad(loss)(t).astype(t.dtype)

        try:
            ms = round(timed_scan(step, x), 1)
        except Exception as err:  # noqa: BLE001
            ms = "failed: " + str(err)[:100]
        print(json.dumps({"case": name, "seq": seq, "block": block,
                          "density": round(float(lay.mean()), 4),
                          "ms": ms}), flush=True)


if __name__ == "__main__":
    main()
