"""Memory-regression guard at REALISTIC widths (VERDICT r2 #9).

Compiles (never runs) the production-config GPT-2-medium fused train step
and the packed flash kernels at bench shapes ON THE TPU and asserts the
compiler's HBM estimates stay inside the v5e budget. A kernel change that
reintroduces a whole-K/V-resident operand (the seq-8k OOM fixed in r1) or
breaks remat turns this red — as a compile failure (scoped-vmem overflow
surfaces as a compile error through the tunnel) or a budget assert.

Needs the real chip (CPU buffer assignment does not model fwd/bwd
liveness — remat is invisible there; tests/unit/test_pipe_memory.py covers
the loop-carry class of regression on the CPU mesh). Run manually:

    python tests/perf/check_memory_budget.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

V5E_HBM = 16 * 2 ** 30
# measured 2026-07-31 (r3): temp+args = 14.88 GB at the bench shape — the
# bench deliberately sits near the HBM ceiling (mb=32 OOMs by ~21 MB), so
# the budget is a thin guard band under the 16 GB chip: any regression
# that grows the step's working set >4% would also kill the bench config
STEP_BUDGET = 15.5 * 2 ** 30


def main():
    import jax
    import jax.numpy as jnp
    import jax.random as jrandom
    assert jax.devices()[0].platform != "cpu", \
        "this guard needs the TPU (CPU buffer stats don't model liveness)"

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    results = {}

    # --- full train step, GPT-2 medium bench shape (mb=24, seq=1024) ---
    cfg = gpt2.config_for("gpt2_medium")
    model = gpt2.make_gpt2_model(config=cfg)
    engine, _, _, _ = deepspeed.initialize(model=model, config_params={
        "train_micro_batch_size_per_gpu": 24,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9,
    })
    ids = np.zeros((1, 24, 1024), np.int32)
    batch = engine._to_device_stacked((ids, ids.copy()))
    fused = engine._get_jit("fused_train", engine._fused_train_fn,
                            donate_argnums=(0,))
    compiled = fused.lower(engine.state, batch, jrandom.PRNGKey(0),
                           engine._hyper(), None).compile()
    ma = compiled.memory_analysis()
    step_total = ma.temp_size_in_bytes + ma.argument_size_in_bytes
    results["gpt2_medium_step"] = {
        "temp_bytes": int(ma.temp_size_in_bytes),
        "arg_bytes": int(ma.argument_size_in_bytes),
        "total_bytes": int(step_total),
        "budget_bytes": int(STEP_BUDGET),
    }
    assert step_total <= STEP_BUDGET, (
        "GPT-2-medium step HBM estimate {:.2f} GB exceeds the {:.2f} GB "
        "guard budget".format(step_total / 2 ** 30, STEP_BUDGET / 2 ** 30))

    # --- flash kernels at long seq (the whole-K/V-residency regression
    # class): compiling fwd+bwd at seq 8192 IS the assertion — resident
    # operands overflow the 16M scoped-vmem budget and fail to compile ---
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    b, s, h, d = 4, 8192, 16, 64
    x = jnp.zeros((b, s, h, d), jnp.bfloat16)

    def attn_loss(q):
        return fa.flash_attention_bshd(q, q, q).astype(jnp.float32).sum()

    c2 = jax.jit(jax.grad(attn_loss)).lower(x).compile()
    ma2 = c2.memory_analysis()
    results["flash_seq8k_grad"] = {
        "temp_bytes": int(ma2.temp_size_in_bytes),
        "arg_bytes": int(ma2.argument_size_in_bytes),
    }

    print(json.dumps(results, indent=2))
    out = os.path.join(os.path.dirname(__file__), "MEMORY_BUDGET.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print("OK — wrote", out)


if __name__ == "__main__":
    main()
