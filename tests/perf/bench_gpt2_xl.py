"""GPT-2 1.5B (gpt2_xl) single-chip pretraining anchor.

The north-star model (BASELINE.json: Megatron-GPT2 1.5B, ZeRO-2) cannot
hold fp32 master+moments in one v5e's 16 GB HBM, so this measures the
ZeRO-3+cpu_offload path (the same configuration the reference uses for
"40B params on one V100"). NOTE the deployment caveat: through the axon
tunnel the per-step grad D2H + param H2D (~9 GB) dominates wall time; on a
real TPU VM the same transfers ride local PCIe at ~10-100x the bandwidth,
so the tokens/s printed here is a LOWER bound for the offload path.

    python tests/perf/bench_gpt2_xl.py [--mb 8] [--steps 2]

Writes tests/perf/BENCH_XL_r06.json (with the per-phase step split).
Round-6 change under test: the offload step's H2D uploads ride the
coalesced transfer batcher (stage3_prefetch_bucket_size buckets packed
on a background worker, one device_put per bucket) instead of one
device_put per leaf, and the D2H/Adam pipeline chunks by
sub_group_size — targeting h2d_dispatch < 30 s (was 116 s in r05) and
sec/step < 350 s (was 462 s) on the same tunnel.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mb", type=int, default=8)
    parser.add_argument("--steps", type=int, default=2)
    parser.add_argument("--seq", type=int, default=1024)
    args = parser.parse_args()

    os.environ.setdefault("DS_OFFLOAD_PROFILE", "1")
    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.config_for("gpt2_xl", max_seq_len=args.seq, remat=True,
                          loss_chunk=128, scan_blocks=True)
    n = gpt2.num_params(cfg)
    model = gpt2.make_gpt2_model(config=cfg)
    ds_config = {
        "train_micro_batch_size_per_gpu": args.mb,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3, "cpu_offload": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9,
    }
    t0 = time.time()
    engine, _, _, _ = deepspeed.initialize(model=model,
                                           config_params=ds_config)
    print("engine ready in {:.0f}s".format(time.time() - t0), flush=True)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(1, args.mb, args.seq)) \
        .astype(np.int32)
    batch = (ids, ids.copy())

    t0 = time.time()
    loss = engine.train_batch(batch=batch)     # compile + warmup
    print("first step (compile) {:.0f}s loss={:.3f}".format(
        time.time() - t0, float(loss)), flush=True)

    t0 = time.time()
    losses = []
    phase_acc = {}
    for _ in range(args.steps):
        losses.append(float(engine.train_batch(batch=batch)))
        for k, v in engine.offload_phase_times.items():
            phase_acc[k] = phase_acc.get(k, 0.0) + v
    dt = (time.time() - t0) / args.steps
    phases = {k: round(v / args.steps, 2) for k, v in phase_acc.items()}
    toks = args.mb * args.seq / dt
    fpt = 6.0 * n + 12.0 * cfg.n_layers * cfg.d_model * args.seq
    phase_sum = sum(phases.values())
    out = {
        "metric": "gpt2_xl_1p5b_offload_tokens_per_sec_per_chip",
        "value": round(toks, 2),
        "unit": "tokens/s/chip",
        "extra": {
            "params": n,
            "phase_split_s": phases,
            "phase_sum_s": round(phase_sum, 2),
            "unattributed_s": round(dt - phase_sum, 2),
            "overlap_note": "the shard pipeline fetches shard j+1 while "
                            "the host Adam steps shard j, so d2h_wait_s "
                            "is the RESIDUAL blocking wait after that "
                            "overlap (d2h_wait + host_adam ~ raw "
                            "transfer wall when transfers dominate); "
                            "phases are disjoint wall-clock and must "
                            "sum to sec_per_step within loop overhead",
            "local_tpu_vm_floor_s": round(
                phases.get("micros_and_check_s", 0.0)
                + phases.get("host_adam_s", 0.0), 2),
            "floor_note": "micros+check (device compute incl. one tunnel "
                          "round-trip) + host Adam; d2h_wait, "
                          "h2d_dispatch and h2d_reshard are "
                          "tunnel-bandwidth-bound and "
                          "shrink 10-100x on a local TPU VM's PCIe, so "
                          "the floor is what the MACHINE does vs what "
                          "the tunnel costs",
            "micro_batch": args.mb,
            "seq_len": args.seq,
            "sec_per_step": round(dt, 1),
            "mfu": round(toks * fpt / 197e12, 5),
            "losses": [round(x, 3) for x in losses],
            "config": "zero3 + cpu_offload on one v5e",
            "caveat": "grad D2H + param H2D ride the axon tunnel; on a "
                      "local TPU VM the offload transfers are 10-100x "
                      "faster, so this is a lower bound",
        },
    }
    path = os.path.join(os.path.dirname(__file__), "BENCH_XL_r06.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
