"""Probe: pack=4 vs pack=8 (k/v blocks per grid step) at seq 16384.

Measured on one v5e (fwd+bwd, ms/layer): fixed 76.8 -> 72.7, bigbird
36.6 -> 31.2 going 4 -> 8; basis for DEFAULT_PACK_WIDTH = 1024.

    python tests/perf/probe_pack8.py
"""
import json
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import numpy as np, jax, jax.numpy as jnp
from sweep_sparse_vs_dense import timed_scan
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, FixedSparsityConfig, make_block_sparse_attention)
HEADS, DHEAD, BATCH, seq, block = 16, 64, 2, 16384, 128
rng = np.random.RandomState(0)
x = jnp.asarray(rng.randn(BATCH, seq, HEADS, DHEAD)*0.1, jnp.bfloat16)
fixed = FixedSparsityConfig(num_heads=HEADS, block=block, num_local_blocks=4,
                            num_global_blocks=1, attention="unidirectional")
bb = BigBirdSparsityConfig(num_heads=HEADS, block=block, num_random_blocks=2,
                           num_sliding_window_blocks=3, num_global_blocks=1,
                           seed=0)
for name, cfg in (("fixed", fixed), ("bigbird", bb)):
    lay = np.asarray(cfg.make_layout(seq))
    for pack in (4, 8):
        attn = make_block_sparse_attention(lay, block, causal=True, pack=pack)
        def step(t, attn=attn):
            def loss(q):
                qh = q.transpose(0, 2, 1, 3)
                return attn(qh, qh, qh, None, None).astype(jnp.float32).sum()
            return jax.grad(loss)(t).astype(t.dtype)
        try:
            ms = round(timed_scan(step, x, reps=12), 2)
        except Exception as e:
            ms = "failed: " + str(e)[:90]
        print(json.dumps({"layout": name, "pack": pack, "ms": ms}), flush=True)
