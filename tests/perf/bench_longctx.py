"""Long-context rung: 8k-token GPT-2 training through the ds_config
``sparse_attention`` surface, with the dense S×S infeasibility asserted
by live-bytes accounting (ISSUE 18).

The reference's block-sparse headline is that attention memory stops
scaling S² so 8-16k-token training fits where dense attention cannot.
This rung makes both halves of that claim measurable on this repo's
surfaces:

* **The run**: a GPT-2-class model trains end to end THROUGH the engine
  + the ds_config ``sparse_attention`` section (the
  ``GPT2Config.sparse_attention = engine.sparse_attention_config()``
  flow of tests/perf/longseq_model.py) at seq 8192 with the Pallas
  block-sparse kernels, telemetry on — tokens/s, finite losses, and the
  telemetry MFU (priced by XLA cost_analysis, or by the kernels' own
  ``pl.CostEstimate`` declarations when cost_analysis sees only an
  opaque custom call — telemetry/collector.py pallas_declared_costs).

* **The OOM assertion — analytic, on purpose**: on CPU hosts
  ``memory_analysis()`` does not model buffer liveness
  (tests/perf/check_memory_budget.py guards on exactly this), and
  host RAM >> chip HBM, so a *simulated* dense OOM at 16k would be
  theater. Instead the rung accounts live bytes arithmetically at the
  declared shape: the backward pass of dense attention must hold the
  S×S score tensor plus its cotangent (a LOWER bound — fp32 score
  tensors alone, no activations), which at batch 1 / 16 heads /
  seq 16384 is 2·16·16384²·4 B = 32 GiB > the 16 GiB v5e HBM budget;
  the sparse kernels' block-pair working set at the same shape is
  ~1 GiB. ``dense_fits: false`` is asserted from that arithmetic and
  published with the operands, never from a synthetic crash.

    python tests/perf/bench_longctx.py [--seq 8192] [--steps 2]

Prints the one-line bench JSON and writes
tests/perf/BENCH_LONGCTX_r01.json (validated by
bin/check_bench_schema.py ``extra.longctx``; gated across rungs by
bin/ds_scoreboard.py's LONGCTX trajectory).
"""
import argparse
import gc
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

SPARSE = {"mode": "sliding_window", "block": 128,
          "num_sliding_window_blocks": 4}       # 512-token causal window
LAYERS = 1
D_MODEL = 1024
HEADS = 16
VOCAB = 8192
BATCH = 1
SEQ_MAX = 16384                 # the accounting shape: dense must NOT fit
HBM_BUDGET_BYTES = 16 * 2 ** 30  # v5e per-chip HBM, this rung's target

OUT = "BENCH_LONGCTX_r01.json"


def _layout(seq):
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        sparsity_config_from_dict)
    cfg = sparsity_config_from_dict(dict(SPARSE), HEADS)
    return np.asarray(cfg.make_layout(seq))


def dense_bwd_live_bytes(seq, batch=BATCH, heads=HEADS, itemsize=4):
    """LOWER bound on dense attention's backward live set: the S×S
    score tensor plus its cotangent, fp32, nothing else counted."""
    return 2 * batch * heads * seq * seq * itemsize


def sparse_bwd_live_bytes(seq, batch=BATCH, itemsize=4):
    """Same lower bound for the block-sparse kernels: only the ACTIVE
    block pairs of the layout are ever materialized (+ cotangents)."""
    layout = _layout(seq)
    block = SPARSE["block"]
    active = int(layout.sum())                  # block pairs, all heads
    if layout.shape[0] == 1:                    # head-shared layout
        active *= HEADS
    return 2 * batch * active * block * block * itemsize


def accounting(seq):
    """The honest OOM row: pure arithmetic at the declared shape, with
    every operand published so the claim is checkable by eye."""
    dense = dense_bwd_live_bytes(seq)
    sparse = sparse_bwd_live_bytes(seq)
    layout = _layout(seq)
    nb = seq // SPARSE["block"]
    density = float(layout.sum()) / float(layout.shape[0] * nb * nb)
    return {
        "shape": {"batch": BATCH, "heads": HEADS, "seq": seq,
                  "block": SPARSE["block"]},
        "hbm_budget_bytes": HBM_BUDGET_BYTES,
        "dense_bwd_live_bytes": dense,
        "sparse_bwd_live_bytes": sparse,
        "dense_fits": dense <= HBM_BUDGET_BYTES,
        "sparse_fits": sparse <= HBM_BUDGET_BYTES,
        "layout_density": round(density, 4),
        "accounting": "analytic lower bound: fp32 score tensors + "
                      "cotangents only (cpu memory_analysis does not "
                      "model liveness — tests/perf/check_memory_budget"
                      ".py)",
    }


def declared_attention_costs(seq):
    """The sparse kernels' own ``pl.CostEstimate`` declarations at the
    run shape — the numbers MFU accounting falls back to when XLA's
    cost_analysis sees only an opaque custom call."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention import (
        make_block_sparse_attention)
    from deepspeed_tpu.telemetry.collector import pallas_declared_costs
    layout = jnp.asarray(_layout(seq))
    attn = make_block_sparse_attention(layout, SPARSE["block"],
                                       causal=True)
    head_dim = D_MODEL // HEADS
    q = jnp.zeros((BATCH, HEADS, seq, head_dim), jnp.float32)
    fwd = pallas_declared_costs(attn, q, q, q)
    grad = pallas_declared_costs(
        jax.grad(lambda q_, k_, v_: attn(q_, k_, v_).sum(),
                 argnums=(0, 1, 2)), q, q, q)
    return {"fwd": fwd, "fwd_plus_bwd": grad}


def _train_step(engine, x, y):
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    return loss


def run_one(seq, steps=2):
    """Train the model at ``seq`` through the engine's sparse_attention
    surface; -> (timed row, telemetry snapshot)."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    tele_dir = tempfile.mkdtemp(prefix="bench_longctx_telemetry_")
    ds = {"train_micro_batch_size_per_gpu": BATCH,
          "gradient_accumulation_steps": 1,
          "bf16": {"enabled": True},
          "zero_optimization": {"stage": 2},
          "optimizer": {"type": "Adam",
                        "params": {"lr": 1e-4, "fused_kernel": "auto"}},
          "sparse_attention": dict(SPARSE),
          "telemetry": {"enabled": True, "output_path": tele_dir},
          "steps_per_print": 10 ** 9}
    engine = None
    try:
        cfg = gpt2.GPT2Config(
            vocab_size=VOCAB, max_seq_len=seq, n_layers=LAYERS,
            n_heads=HEADS, d_model=D_MODEL, remat=False, loss_chunk=128,
            sparse_attention=dict(SPARSE))
        engine, _, _, _ = deepspeed.initialize(
            model=gpt2.make_gpt2_model(config=cfg), config_params=ds)
        # the reference flow: the model consumes the ENGINE's parsed
        # sparse config — the two surfaces must agree
        assert engine.sparse_attention_config() == SPARSE
        assert cfg.sparse_attention == engine.sparse_attention_config()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, VOCAB, size=(BATCH, seq)).astype(np.int32)
        x = jnp.asarray(ids)
        y = jnp.roll(x, -1, axis=1)
        t0 = time.time()
        losses = [float(_train_step(engine, x, y))]
        losses.append(float(_train_step(engine, x, y)))
        compile_s = time.time() - t0
        t0 = time.time()
        for _ in range(steps):
            losses.append(float(_train_step(engine, x, y)))
        dt = (time.time() - t0) / steps
        snap = engine.telemetry_snapshot()
        row = {"seq": seq, "mode": "sparse", "fits": True, "timed": True,
               "tokens_per_sec": round(BATCH * seq / dt, 1),
               "sec_per_step": round(dt, 2),
               "compile_and_first_step_s": round(compile_s, 1),
               "losses": [round(l, 3) for l in losses],
               "finite": bool(np.all(np.isfinite(losses)))}
        return row, snap
    except AssertionError:
        raise                # a wiring bug must not publish as an OOM row
    except Exception as e:  # noqa: BLE001 — OOM rows are the data
        msg = str(e)
        for marker in ("Ran out of memory", "RESOURCE_EXHAUSTED",
                       "exceeded scoped vmem", "MosaicError"):
            at = msg.find(marker)
            if at >= 0:
                msg = msg[at:at + 400]
                break
        return {"seq": seq, "mode": "sparse", "fits": False,
                "timed": False, "error": msg[:400]}, {}
    finally:
        del engine
        gc.collect()
        import jax as _jax
        _jax.clear_caches()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq", type=int, default=8192,
                        help="timed sequence length (must divide by "
                             "block {})".format(SPARSE["block"]))
    parser.add_argument("--steps", type=int, default=2)
    parser.add_argument("--out", default=OUT)
    args = parser.parse_args()
    import jax

    device = jax.devices()[0].device_kind
    backend = jax.default_backend()

    # the accounting rows first: they are cheap, and a broken claim
    # must fail the rung before minutes of interpret-mode training
    books = {seq: accounting(seq) for seq in (args.seq, SEQ_MAX)}
    assert not books[SEQ_MAX]["dense_fits"], \
        "dense attention bwd at seq {} ({:.1f} GiB) was expected to " \
        "exceed the {:.0f} GiB HBM budget".format(
            SEQ_MAX, books[SEQ_MAX]["dense_bwd_live_bytes"] / 2 ** 30,
            HBM_BUDGET_BYTES / 2 ** 30)
    assert books[SEQ_MAX]["sparse_fits"], \
        "sparse attention bwd at seq {} must fit the HBM budget".format(
            SEQ_MAX)

    declared = declared_attention_costs(args.seq)
    assert declared["fwd"].get("flops"), \
        "sparse kernels declared no pl.CostEstimate flops"

    timed, snap = run_one(args.seq, steps=args.steps)
    rows = [timed]
    for seq, book in sorted(books.items()):
        for mode in ("dense", "sparse"):
            rows.append({
                "seq": seq, "mode": mode,
                "fits": book["{}_fits".format(mode)],
                "timed": False,
                "live_bytes": book["{}_bwd_live_bytes".format(mode)],
                "reason": "accounting row (live-bytes arithmetic at "
                          "the declared shape; the timed rung runs "
                          "sparse at seq {})".format(args.seq)})

    payload = {
        "metric": "gpt2_longctx_sparse_tokens_per_sec",
        "value": timed.get("tokens_per_sec"),
        "unit": "tokens/s",
        "vs_baseline": None,
        "extra": {
            "device": device,
            "backend": backend,
            "mfu": (snap.get("mfu") or {}).get("last"),
            "longctx": {
                "model": "GPT-2-class ({}L x {}, {} heads, vocab {})"
                         .format(LAYERS, D_MODEL, HEADS, VOCAB),
                "sparse": dict(SPARSE),
                "rows": rows,
                "declared_attention_costs": declared,
                "dense_oom": books[SEQ_MAX],
            },
        },
    }
    if not timed.get("fits"):
        payload["value"] = None
        payload["error"] = timed.get("error", "timed rung did not run")
    if snap:
        payload["extra"]["telemetry"] = snap
    path = os.path.join(os.path.dirname(__file__), args.out)
    with open(path, "w") as fh:
        json.dump(payload, fh)
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
