"""MFU sweep for the bench workload (GPT-2 125M, ZeRO-2, one chip).

Tries (micro_batch, remat_policy, loss_chunk) combos and prints the MFU of
each, so bench.py can pin the best configuration. Run manually:

    python tests/perf/sweep_gpt2_mfu.py
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def run_one(micro_batch, remat_policy, loss_chunk, seq=1024, steps=10,
            warmup=2, remat=True, size="gpt2_small"):
    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.config_for(size, max_seq_len=seq, remat=remat,
                          remat_policy=remat_policy, loss_chunk=loss_chunk)
    n_params = gpt2.num_params(cfg)
    model = gpt2.make_gpt2_model(config=cfg)
    ds_config = {
        "train_micro_batch_size_per_gpu": micro_batch,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = deepspeed.initialize(model=model,
                                           config_params=ds_config)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size,
                      size=(1, micro_batch, seq)).astype(np.int32)
    batch = (ids, ids.copy())
    # float(loss) is the fence: execution through the axon tunnel is lazy
    # (block_until_ready is a no-op); steps chain through donated state so
    # fetching the last loss fences the whole loop
    for _ in range(warmup):
        loss = engine.train_batch(batch=batch)
    float(loss)
    t0 = time.time()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    float(loss)
    dt = (time.time() - t0) / steps
    toks = micro_batch * seq / dt
    sys.path.insert(0, ".")
    from bench import peak_for
    mfu = 6.0 * n_params * toks / peak_for(jax.devices()[0])
    return dict(micro_batch=micro_batch, remat_policy=remat_policy,
                remat=remat, loss_chunk=loss_chunk,
                step_ms=round(dt * 1e3, 1), tokens_per_s=round(toks),
                mfu=round(mfu, 4))


def main():
    combos = [
        # (size, micro_batch, policy, loss_chunk, remat)
        ("gpt2_small", 192, "full", 128, True),   # current bench config
        ("gpt2_small", 16, "dots", 128, True),    # dots: crash or OOM?
        ("gpt2_small", 48, "dots", 128, True),
        ("gpt2_small", 192, "full", 256, True),
        ("gpt2_small", 256, "full", 64, True),
        ("gpt2_medium", 96, "full", 128, True),   # d=1024: better MXU tiling
        ("gpt2_medium", 64, "full", 128, True),
        ("gpt2_small", 48, "full", 128, False),   # no remat
    ]
    results = []
    for size, mb, pol, chunk, remat in combos:
        try:
            r = run_one(mb, pol, chunk, remat=remat, size=size)
        except Exception as e:  # noqa: BLE001
            r = dict(micro_batch=mb, remat_policy=pol, loss_chunk=chunk,
                     remat=remat, error=str(e)[:200])
        r["size"] = size
        print(json.dumps(r), flush=True)
        results.append(r)
    ok = [r for r in results if "mfu" in r]
    if ok:
        best = max(ok, key=lambda r: r["mfu"])
        print("BEST:", json.dumps(best), flush=True)


if __name__ == "__main__":
    main()
