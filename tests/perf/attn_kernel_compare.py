"""Isolated attention fwd+bwd timings at the bench shape.

Compares (per GPT-2-medium layer shape, b=96 s=1024 h=16 d=64):
  - this repo's packed flash kernel ((b,s,h,d) view, no transposes)
  - JAX's builtin pallas TPU flash kernel ((b,h,s,d), incl. transposes
    from the model's packed layout)
  - plain XLA einsum attention (scores materialize)

Times grad(sum(ctx)) wrt (q,k,v) — the training-path cost. Manual:

    python tests/perf/attn_kernel_compare.py [--b 96]
"""
import argparse
import sys
import os
import time
import json

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def _force(x):
    import jax
    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(leaf.ravel()[0])


def timed(fn, *args, reps=5):
    _force(fn(*args))
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
        _force(out)
    return round((time.time() - t0) / reps * 1e3, 1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--b", type=int, default=96)
    parser.add_argument("--s", type=int, default=1024)
    parser.add_argument("--h", type=int, default=16)
    parser.add_argument("--d", type=int, default=64)
    args = parser.parse_args()
    b, s, h, d = args.b, args.s, args.h, args.d

    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d) * 0.1, jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    rows = {}

    # ---- repo packed kernel --------------------------------------------
    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention_bshd)

    def loss_repo(q, k, v):
        return flash_attention_bshd(q, k, v).astype(jnp.float32).sum()

    rows["repo_packed_fwd"] = timed(
        jax.jit(lambda q, k, v: flash_attention_bshd(q, k, v)), q, k, v)
    rows["repo_packed_grad"] = timed(
        jax.jit(jax.grad(loss_repo, argnums=(0, 1, 2))), q, k, v)

    # ---- jax builtin pallas flash ((b,h,s,d)) ---------------------------
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jflash)

        def to_bhsd(t):
            return t.transpose(0, 2, 1, 3)

        def loss_jax(q, k, v):
            out = jflash(to_bhsd(q), to_bhsd(k), to_bhsd(v), causal=True,
                         sm_scale=1.0 / d ** 0.5)
            return out.astype(jnp.float32).sum()

        rows["jax_flash_fwd"] = timed(
            jax.jit(lambda q, k, v: jflash(
                to_bhsd(q), to_bhsd(k), to_bhsd(v), causal=True,
                sm_scale=1.0 / d ** 0.5)), q, k, v)
        rows["jax_flash_grad"] = timed(
            jax.jit(jax.grad(loss_jax, argnums=(0, 1, 2))), q, k, v)
    except Exception as e:  # noqa: BLE001
        rows["jax_flash"] = "failed: " + str(e)[:120]

    # ---- plain XLA einsum attention ------------------------------------
    def loss_xla(q, k, v):
        qh = q.transpose(0, 2, 1, 3)  # (b,h,s,d)
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                            preferred_element_type=jnp.float32) / d ** 0.5
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1).astype(qh.dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
        return ctx.astype(jnp.float32).sum()

    try:
        rows["xla_einsum_grad"] = timed(
            jax.jit(jax.grad(loss_xla, argnums=(0, 1, 2))), q, k, v)
    except Exception as e:  # noqa: BLE001
        rows["xla_einsum_grad"] = "failed: " + str(e)[:120]

    # ideal MXU time for reference: causal fwd+bwd ~ 3x fwd flops
    fwd_flops = 4.0 * b * h * (s * s / 2) * d * 2  # qk^T + pv, causal half
    rows["_ideal_fwd_ms_at_peak"] = round(fwd_flops / 197e12 * 1e3, 1)
    print(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
