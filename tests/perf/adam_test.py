"""CPU Adam micro-benchmark (reference tests/perf/adam_test*.py).

Standalone: python tests/perf/adam_test.py [numel]
Times the native SIMD C++ op against the XLA-CPU fallback.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))


def main(numel=8 * 1024 * 1024, iters=10):
    import jax
    # host benchmark: force the CPU backend (a TPU-tunnel plugin may
    # override JAX_PLATFORMS, and pure_callback needs a local backend)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from deepspeed_tpu.ops.adam.fused_adam import adam_init, adam_update

    rs = np.random.RandomState(0)
    params = {"flat": jnp.asarray(rs.randn(numel), dtype=jnp.float32)}
    grads = {"flat": jnp.asarray(rs.randn(numel), dtype=jnp.float32)}

    # use_native=True forces the native leg (fails loudly if unbuilt) so
    # the comparison stays meaningful on hosts where the auto gate would
    # pick XLA.
    for use_native, label in ((False, "xla-cpu"), (True, "native")):
        from deepspeed_tpu.ops.adam.fused_adam import DeepSpeedCPUAdam
        opt = DeepSpeedCPUAdam(lr=1e-3, use_native=use_native)
        state = opt.init_state(params)
        h = opt.hyperparams()
        opt.update(grads, state, params, **h)  # warmup/compile/build
        t0 = time.time()
        for _ in range(iters):
            _, state = opt.update(grads, state, params, **h)
        dt = (time.time() - t0) / iters
        print("{}: {:.2f} ms / step for {:,} params ({:.1f} GB/s)".format(
            label, dt * 1e3, numel, numel * 16 / dt / 1e9))


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:]))
