"""Step-time breakdown for the BENCH shape (GPT-2 medium, mb=96, seq=1024).

Times jitted variants on the real chip and prints a ms-per-step table.
Manual harness:

    python tests/perf/ablate_medium_step.py [--mb 96]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

SEQ = 1024


def _force(out):
    import jax
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(leaf.ravel()[0])


def timed(fn, *args, reps=3):
    _force(fn(*args))
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
        _force(out)
    return round((time.time() - t0) / reps * 1e3, 1)  # ms


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mb", type=int, default=96)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.config_for("gpt2_medium", max_seq_len=SEQ, remat=True,
                          loss_chunk=128)
    params = jax.tree_util.tree_map(
        lambda p: jnp.asarray(p, jnp.bfloat16), gpt2.init_params(cfg, 0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(args.mb, SEQ)),
                      jnp.int32)

    rows = {}

    def loss_fn(p, ids):
        return gpt2.lm_loss(p, ids, ids, cfg, rng=None, train=False)

    rows["fwd_only"] = timed(jax.jit(loss_fn), params, ids)
    rows["fwd_bwd"] = timed(jax.jit(jax.grad(loss_fn)), params, ids)

    def hidden_loss(p, ids):
        h = gpt2.forward_hidden(p, ids, cfg, rng=None, train=False)
        return h.astype(jnp.float32).mean()

    rows["fwd_bwd_no_ce"] = timed(jax.jit(jax.grad(hidden_loss)), params, ids)

    import deepspeed_tpu.models.gpt2 as g
    orig_attn = g._attn_ctx
    g._attn_ctx = lambda x, blk, c, t: x
    try:
        rows["fwd_bwd_no_attn"] = timed(jax.jit(jax.grad(loss_fn)),
                                        params, ids)
    finally:
        g._attn_ctx = orig_attn

    print(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
