"""Where does the GPT-2 step time go? Ablation timings on the real chip.

Times jitted variants of the 125M workload at the bench shape and prints a
breakdown: full train step, fwd-only, fwd+bwd without optimizer, CE-only,
blocks-only (no CE), attention on/off. Run manually:

    python tests/perf/ablate_gpt2_step.py
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

MB = 192
SEQ = 1024


def _force(out):
    """Force execution through the axon tunnel: block_until_ready is a no-op
    there (lazy remote execution); a literal value fetch is what runs the
    program. Index ON DEVICE first so only one scalar crosses the tunnel —
    np.asarray of a full leaf would drag the whole array through it."""
    import jax
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(leaf.ravel()[0])


def timed(fn, *args, reps=5):
    _force(fn(*args))
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
        _force(out)
    return (time.time() - t0) / reps * 1e3  # ms


def main():
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.config_for("gpt2_small", max_seq_len=SEQ, remat=True,
                          loss_chunk=128)
    params = jax.tree_util.tree_map(
        lambda p: jnp.asarray(p, jnp.bfloat16), gpt2.init_params(cfg, 0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(MB, SEQ)),
                      jnp.int32)

    rows = {}

    def loss_fn(p, ids):
        return gpt2.lm_loss(p, ids, ids, cfg, rng=None, train=False)

    rows["fwd_only"] = timed(jax.jit(loss_fn), params, ids)

    grad_fn = jax.jit(jax.grad(loss_fn))
    rows["fwd_bwd"] = timed(grad_fn, params, ids)

    # hidden-states only (no CE): mean of final hidden as dummy loss
    def hidden_loss(p, ids):
        h = gpt2.forward_hidden(p, ids, cfg, rng=None, train=False)
        return h.astype(jnp.float32).mean()

    rows["fwd_bwd_no_ce"] = timed(jax.jit(jax.grad(hidden_loss)), params, ids)

    # no attention (identity instead of attention mixing)
    import deepspeed_tpu.models.gpt2 as g
    orig_attn = g._attn_ctx
    g._attn_ctx = lambda x, blk, c, t: x
    try:
        rows["fwd_bwd_no_attn"] = timed(jax.jit(jax.grad(loss_fn)),
                                        params, ids)
    finally:
        g._attn_ctx = orig_attn

    # no remat
    import dataclasses
    cfg_nr = dataclasses.replace(cfg, remat=False)

    def loss_nr(p, ids):
        return gpt2.lm_loss(p, ids, ids, cfg_nr, rng=None, train=False)

    try:
        rows["fwd_bwd_no_remat"] = timed(jax.jit(jax.grad(loss_nr)),
                                         params, ids)
    except Exception as e:  # noqa: BLE001
        rows["fwd_bwd_no_remat"] = "OOM: " + str(e)[:80]

    print(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
