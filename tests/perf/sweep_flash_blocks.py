"""Block-size sweep for the packed flash kernels at the bench shape.

Amortizes the ~94ms axon round-trip with lax.scan inside one jit:
each timing runs REPS chained attention steps and fetches one scalar.

    python tests/perf/sweep_flash_blocks.py [--b 96] [--grad]
"""
import argparse
import itertools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

REPS = 8


def timed_scan(step_fn, init, reps=REPS):
    """step_fn: x -> x (same shape). Returns ms per step, amortized."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(x):
        def body(c, _):
            return step_fn(c), None
        out, _ = jax.lax.scan(body, x, None, length=reps)
        return out.astype(jnp.float32).ravel()[0]

    float(run(init))          # compile + warmup
    t0 = time.time()
    float(run(init))
    dt = time.time() - t0
    return round((dt - 0.094) / reps * 1e3, 1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--b", type=int, default=96)
    parser.add_argument("--s", type=int, default=1024)
    parser.add_argument("--h", type=int, default=16)
    parser.add_argument("--d", type=int, default=64)
    parser.add_argument("--grad", action="store_true")
    args = parser.parse_args()
    b, s, h, d = args.b, args.s, args.h, args.d

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.transformer import flash_attention as fa

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, s, h, d) * 0.1, jnp.bfloat16)

    rows = {}
    for bq, bk in [(256, 256), (256, 512), (512, 256), (512, 512),
                   (256, 1024), (512, 1024), (1024, 1024)]:
        def fwd_step(t, bq=bq, bk=bk):
            # chain: out feeds the next call's q so scan can't CSE
            return fa.flash_attention_bshd(t, t, t, block_q=bq, block_k=bk)

        def grad_step(t, bq=bq, bk=bk):
            # pass bwd blocks explicitly: fwd blocks no longer flow into
            # the backward (the bwd defaults to auto_blocks otherwise)
            g = jax.grad(lambda q: fa.flash_attention_bshd(
                q, q, q, block_q=bq, block_k=bk,
                bwd_block_q=bq, bwd_block_k=bk)
                .astype(jnp.float32).sum())(t)
            return g.astype(t.dtype)

        key = "bq{}_bk{}".format(bq, bk)
        try:
            rows[key + "_fwd"] = timed_scan(fwd_step, x)
            if args.grad:
                rows[key + "_grad"] = timed_scan(grad_step, x)
        except Exception as e:  # noqa: BLE001
            rows[key] = "failed: " + str(e)[:90]
    print(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
