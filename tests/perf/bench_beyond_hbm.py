"""Beyond-HBM training anchor: a >=4B-parameter GPT trained on ONE chip
via streamed parameter offload (zero_optimization.cpu_offload_params).

The point being demonstrated (the analogue of the reference's
13B/40B-params-on-one-32GB-V100 ZeRO-3 Offload story): bf16 params
(~8.5 GB) + fp32 grads (~17 GB) of a 4.2B model CANNOT co-reside in a
single v5e's 16 GB HBM — yet the streamed step trains it with finite
loss, because HBM only ever holds ~2 layer groups of parameters
(budgeted by stage3_max_live_parameters), the boundary activations, and
one group's gradients. Master+moments (~51 GB fp32) live in host RAM.

    python tests/perf/bench_beyond_hbm.py [--layers 36] [--d 3072]
        [--seq 128] [--mb 1] [--steps 1]

Writes tests/perf/BENCH_BEYOND_HBM.json (params, sec/step, phase split,
losses, group plan). On a CPU-only box the run is a correctness + memory
-shape demonstration (the "device" is host RAM); the JSON records the
backend honestly.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--layers", type=int, default=36)
    parser.add_argument("--d", type=int, default=3072)
    parser.add_argument("--heads", type=int, default=24)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--mb", type=int, default=1)
    parser.add_argument("--steps", type=int, default=1)
    parser.add_argument("--max-live", type=int, default=10 ** 9,
                        help="stage3_max_live_parameters (elements)")
    args = parser.parse_args()

    import jax
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.GPT2Config(max_seq_len=args.seq, n_layers=args.layers,
                          n_heads=args.heads, d_model=args.d,
                          use_flash_attention=False, remat=True,
                          loss_chunk=128 if args.seq % 128 == 0 else 0)
    n = gpt2.num_params(cfg)
    print("model: {} layers x d={} -> {:,} params".format(
        args.layers, args.d, n), flush=True)

    t0 = time.time()
    model = gpt2.make_gpt2_model(config=cfg)
    print("init_params in {:.0f}s".format(time.time() - t0), flush=True)

    t0 = time.time()
    engine, _, _, _ = deepspeed.initialize(
        model=model, config_params={
            "train_micro_batch_size_per_gpu": args.mb,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": {
                "stage": 3, "cpu_offload": True,
                "cpu_offload_params": True,
                "stage3_max_live_parameters": args.max_live,
            },
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9,
        })
    print("engine ready in {:.0f}s; groups={}".format(
        time.time() - t0, engine.stream_runner.groups), flush=True)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(1, args.mb, args.seq)) \
        .astype(np.int32)
    batch = (ids, ids.copy())

    t0 = time.time()
    loss = engine.train_batch(batch=batch)      # compile + first step
    compile_step_s = time.time() - t0
    print("first step (compile) {:.0f}s loss={:.3f}".format(
        compile_step_s, float(loss)), flush=True)

    losses = [float(loss)]
    phase_acc = {}      # measured steps only (not the compile step)
    t0 = time.time()
    for _ in range(args.steps):
        losses.append(float(engine.train_batch(batch=batch)))
        for k, v in engine.offload_phase_times.items():
            phase_acc[k] = phase_acc.get(k, 0.0) + v
    dt = (time.time() - t0) / max(args.steps, 1)
    phases = {k: round(v / max(args.steps, 1), 2)
              for k, v in phase_acc.items() if not k.startswith("_")}

    live_elems = max(
        sum(int(np.prod(np.shape(l)))
            for i in range(*engine.stream_runner.groups[g])
            for l in engine.stream_runner._b_leaves[i])
        for g in range(len(engine.stream_runner.groups)))
    hbm_resident_gb = round(
        2 * live_elems * 2 / 2 ** 30, 2)   # 2 groups in flight, bf16
    out = {
        "metric": "beyond_hbm_streamed_offload_params_on_one_chip",
        "value": n,
        "unit": "params",
        "extra": {
            "params": n,
            "params_plus_grads_gb_if_resident": round(
                (2 * n + 4 * n) / 2 ** 30, 1),
            "hbm_16gb_exceeded": bool((2 * n + 4 * n) / 2 ** 30 > 16.0),
            "streamed_live_param_gb_peak": hbm_resident_gb,
            "layer_groups": len(engine.stream_runner.groups),
            "stage3_max_live_parameters": args.max_live,
            "sec_per_step": round(dt, 1),
            "compile_plus_first_step_s": round(compile_step_s, 1),
            "phase_split_s": phases,
            "losses": [round(x, 4) for x in losses],
            "finite": bool(np.all(np.isfinite(losses))),
            "micro_batch": args.mb,
            "seq_len": args.seq,
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "backend": jax.default_backend(),
            "note": "params stream host->HBM per layer group "
                    "(double-buffered, coalesced); master+moments are "
                    "host fp32; grads leave per segment as one packed "
                    "buffer. Phases are disjoint driver-loop wall "
                    "clocks; on async backends a later phase's sync "
                    "absorbs earlier dispatched compute (d2h_grads is "
                    "the step's hard sync point). On a CPU backend this "
                    "demonstrates the memory shape and numerics; v5e "
                    "gives the single-chip beyond-HBM capability the "
                    "metric names.",
        },
    }
    path = os.path.join(os.path.dirname(__file__),
                        "BENCH_BEYOND_HBM.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
