"""Measure the step-time cost of telemetry + wall_clock_breakdown.

ISSUE 5 acceptance: `wall_clock_breakdown: true` (with the full
telemetry pipeline on) must cost < 5% step time vs off. Two engines of
the same small GPT-2 on the micro path — telemetry OFF vs telemetry ON
(records + synchronized phase timers) — measured in INTERLEAVED blocks
(off/on/off/on...), because on a shared CPU box sequential whole-run
blocks alias machine drift into the comparison (a first cut measured
-2%..+22% for the SAME configs depending on run order). Emits one JSON
line in bench.py's shape plus the committed artifact
tests/perf/BENCH_TELEMETRY_OVERHEAD.json.

value = overhead fraction ((on - off) / off, median per-step time);
vs_baseline = overhead / 0.05 (<= 1.0 means within the budget).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROUNDS = 8
BLOCK = 5
WARMUP = 3
BUDGET = 0.05


def _engine(telemetry_on):
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2
    # big enough that a step is tens of ms: the telemetry cost is a
    # FIXED few-hundred-us per step (value fetches + one JSON line +
    # the phase timers' syncs), so a toy-sized step would overstate the
    # fraction real workloads see
    cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=128, n_layers=4,
                          n_heads=4, d_model=256,
                          use_flash_attention=False, remat=False)
    ds = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9,
    }
    if telemetry_on:
        from bench import scratch_telemetry_dir
        ds["wall_clock_breakdown"] = True
        ds["telemetry"] = {"enabled": True,
                           "output_path": scratch_telemetry_dir(
                               "tele_overhead_")}
    engine, _, _, _ = deepspeed.initialize(
        model=gpt2.make_gpt2_model(config=cfg), config_params=ds)
    return engine, cfg


def _stepper(telemetry_on):
    engine, cfg = _engine(telemetry_on)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size,
                      size=(engine.train_batch_size(),
                            cfg.max_seq_len)).astype(np.int32)
    labels = ids.copy()

    def step():
        loss = engine(ids, labels)
        engine.backward(loss)
        engine.step()
        return loss

    for _ in range(WARMUP):
        loss = step()
    float(loss)
    return engine, step


def main():
    import jax
    eng_off, step_off = _stepper(False)
    eng_on, step_on = _stepper(True)
    times = {"off": [], "on": []}
    ratios = []
    for rnd in range(ROUNDS):
        # alternate block order each round so linear machine drift
        # cancels out of the per-round pairing
        order = (("off", step_off), ("on", step_on))
        if rnd % 2:
            order = order[::-1]
        round_med = {}
        for name, step in order:
            block = []
            for _ in range(BLOCK):
                t0 = time.time()
                loss = step()
                float(loss)
                block.append(time.time() - t0)
            times[name].extend(block)
            round_med[name] = float(np.median(block))
        ratios.append(round_med["on"] / round_med["off"])
    snap = eng_on.telemetry_snapshot()
    assert snap["steps"] == WARMUP + ROUNDS * BLOCK, snap
    off = float(np.median(times["off"]))
    on = float(np.median(times["on"]))
    # median of per-round paired ratios: robust to slow drift AND to a
    # single noisy round (a global median is not)
    overhead = float(np.median(ratios)) - 1.0
    payload = {
        "metric": "telemetry_on_step_time_overhead",
        "value": round(overhead, 4),
        "unit": "fraction_of_step_time",
        # <= 1.0 means within the documented < 5% budget
        "vs_baseline": round(overhead / BUDGET, 4),
        "extra": {
            "median_step_s_off": round(off, 6),
            "median_step_s_on": round(on, 6),
            "per_round_on_off_ratios": [round(r, 4) for r in ratios],
            "steps": ROUNDS * BLOCK,
            "interleaved_blocks": [ROUNDS, BLOCK],
            "budget": BUDGET,
            "within_budget": bool(overhead < BUDGET),
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "backend": jax.default_backend(),
        },
    }
    print(json.dumps(payload))
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_TELEMETRY_OVERHEAD.json")
    with open(out, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    return 0 if payload["extra"]["within_budget"] else 1


if __name__ == "__main__":
    sys.exit(main())
