"""Analytic fit + MFU ceiling for the north-star config: Megatron-GPT2
1.5B, ZeRO-2, on a v5p-64 mesh (BASELINE.json target: >= 45% MFU).

Real v5p-64 hardware is not reachable from this environment, so this
compiles the EXACT fused train step (the same `_fused_train_fn`
executable `train_batch` runs) SPMD-partitioned over an 8-way data
mesh of virtual CPU devices and reads XLA's own buffer assignment
(`memory_analysis()`) for the per-chip HBM verdict. Step time/MFU is
an analytic model (6N+attention flops x the full-remat 8/6 factor,
anchored to the bench-measured executed-flop efficiency) — XLA's
cost_analysis() cannot price it because it counts a lax.scan body
once, ignoring trip counts. Per-chip flops at fixed micro-batch are
dp-invariant, and per-chip memory at dp=8 UPPER-BOUNDS dp=64 (the
ZeRO-sharded master/moments/grads only shrink as dp grows; the
replicated bf16 params do not change), so an 8-way compile that fits
v5p HBM certifies the 64-way one. (A true 64-device virtual compile
materializes 64 host copies of the replicated params — 192 GB — and
OOMs the box; dp=8 is the largest honest mesh this host can hold.)
On top of the compile, the script prices the per-step ICI collectives
(ZeRO-2's grad reduce-scatter + param all-gather, reference
stage2.py semantics; per-chip volume is ~dp-invariant at 2 bytes/param
each) at v5p link bandwidth to bound the achievable 64-chip MFU.

    JAX_PLATFORMS=cpu python tests/perf/analyze_v5p64.py [--mb 8]

Writes tests/perf/V5P64_ANALYSIS.json.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# must precede the jax import (and override an axon/TPU plugin pin)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

import __graft_entry__  # noqa: E402

# v5p per-chip specs (public: cloud.google.com/tpu/docs/v5p):
#   bf16 peak 459 TFLOP/s, HBM 95 GB, ICI 4800 Gbps (= 600 GB/s)
#   aggregate bidirectional per chip across the 3D-torus links.
V5P_PEAK_FLOPS = 459e12
V5P_HBM_BYTES = 95 * 1024 ** 3
V5P_ICI_BYTES_PER_S = 600e9 / 2  # one direction; RS and AG each stream
                                 # a full pass of the data one way


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mb", type=int, default=8,
                        help="micro batch per chip")
    parser.add_argument("--seq", type=int, default=1024)
    args = parser.parse_args()

    jax = __graft_entry__._ensure_n_devices(8)
    import jax.random as jrandom
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    assert jax.device_count() >= 8, jax.device_count()

    cfg = gpt2.config_for("gpt2_xl", max_seq_len=args.seq, remat=True,
                          loss_chunk=128, scan_blocks=True,
                          use_flash_attention=False)
    n_params = gpt2.num_params(cfg)
    model = gpt2.make_gpt2_model(config=cfg)
    ds_config = {
        "train_micro_batch_size_per_gpu": args.mb,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9,
    }
    t0 = time.time()
    engine, _, _, _ = deepspeed.initialize(model=model,
                                           config_params=ds_config)
    print("engine ready in {:.0f}s (dp={})".format(
        time.time() - t0, engine.dp_world_size), flush=True)
    dp = engine.dp_world_size
    assert dp == 8, dp

    global_batch = args.mb * dp
    ids = np.zeros((1, global_batch, args.seq), np.int32)
    batch = engine._to_device_stacked((ids, ids.copy()))
    fused = engine._get_jit("fused_train", engine._fused_train_fn,
                            donate_argnums=(0,))
    t0 = time.time()
    lowered = fused.lower(engine.state, batch, jrandom.PRNGKey(0),
                          engine._hyper(), engine._pld_theta())
    compiled = lowered.compile()
    print("compiled in {:.0f}s".format(time.time() - t0), flush=True)

    ma = compiled.memory_analysis()
    # donated args alias outputs, so live per-chip HBM at the step's peak
    # is arguments (train state + batch) + temps (activations/workspace)
    hbm = ma.argument_size_in_bytes + ma.temp_size_in_bytes \
        + ma.generated_code_size_in_bytes

    # dp=64 equivalents: per-chip flops and tokens/chip are identical at
    # fixed micro-batch; per-chip sharded state (fp32 master 4N + Adam
    # moments 8N + bf16 acc-grads 2N, all on the data axis) shrinks 8x
    tokens_chip = args.mb * args.seq
    sharded_bytes = 14.0 * n_params
    hbm64 = hbm - sharded_bytes / dp + sharded_bytes / 64
    # Step-time model. XLA's cost_analysis counts a lax.scan body ONCE
    # (trip counts are invisible to it), so flops come from the model.
    # Efficiency on *executed* flops is anchored to real-chip (v5e)
    # measurements AT THE MODEL'S OWN WIDTH (d_model 1600): the round-3
    # anchor was measured at the bench width 1024 and left a hole at
    # exactly the width that matters (VERDICT r3). XL_WIDTH_ANCHOR.json
    # (tests/perf/anchor_xl_efficiency.py) supplies three pieces, each
    # priced on its own terms:
    #   - per-LAYER rate (remat x8/6, grouped-fused flash backward)
    #   - head/CE rate (chunked, not under remat, x1)
    #   - a depth-independent per-microstep overhead (embedding gather +
    #     scatter-add backward + final LN), kept at its v5e-measured
    #     wall time — conservative, since v5p is faster at everything.
    anchor_path = os.path.join(os.path.dirname(__file__),
                               "XL_WIDTH_ANCHOR.json")
    with open(anchor_path) as f:
        anchor = json.load(f)
    assert anchor["config"]["d_model"] == cfg.d_model, "width mismatch"
    EFF_LAYERS = anchor["executed_flop_efficiency"]["layers_width1600"]
    EFF_HEAD = anchor["executed_flop_efficiency"]["head_width1600"]
    OVERHEAD_S = anchor["overhead_ms_per_microstep"] / 1e3 \
        * (args.mb / anchor["config"]["micro_batch"])
    REMAT_FACTOR = 8.0 / 6.0
    d = cfg.d_model
    p_block = 12 * d * d + 13 * d
    flops_layer_tok = 6.0 * p_block + 12.0 * d * args.seq
    flops_head_tok = 6.0 * d * cfg.vocab_size
    model_flops_tok = flops_layer_tok * cfg.n_layers + flops_head_tok
    model_flops_chip = tokens_chip * model_flops_tok
    compute_s = (tokens_chip * flops_layer_tok * cfg.n_layers
                 * REMAT_FACTOR / (V5P_PEAK_FLOPS * EFF_LAYERS)
                 + tokens_chip * flops_head_tok
                 / (V5P_PEAK_FLOPS * EFF_HEAD)
                 + OVERHEAD_S)
    # ZeRO-2 collectives per step (bf16 wire dtype, ratio (n-1)/n ~ 1):
    #   grads:  reduce-scatter over data  -> 2 bytes/param
    #   params: all-gather updated shards -> 2 bytes/param
    comm_bytes = 2.0 * 2 * n_params
    comm_s = comm_bytes / V5P_ICI_BYTES_PER_S
    # XLA overlaps the RS/AG with backward/next-forward compute; the
    # ceiling assumes no overlap (worst case) and full overlap (best)
    step_worst = compute_s + comm_s
    step_best = max(compute_s, comm_s)
    mfu_worst = model_flops_chip / V5P_PEAK_FLOPS / step_worst
    mfu_best = model_flops_chip / V5P_PEAK_FLOPS / step_best

    out = {
        "config": {
            "model": "gpt2_xl (1.5B)", "params": n_params,
            "mesh": {"data": 64}, "compiled_mesh": {"data": 8},
            "zero_stage": 2,
            "micro_batch_per_chip": args.mb, "seq": args.seq,
            "global_batch_64chip": args.mb * 64,
            "remat": True, "scan_blocks": True,
        },
        "compiled_per_chip": {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "code_bytes": int(ma.generated_code_size_in_bytes),
            "hbm_bytes": int(hbm),
            "hbm_gib_dp8_upper_bound": round(hbm / 1024 ** 3, 2),
            "hbm_gib_dp64_analytic": round(hbm64 / 1024 ** 3, 2),
            "v5p_hbm_gib": round(V5P_HBM_BYTES / 1024 ** 3, 2),
            "fits": bool(hbm < V5P_HBM_BYTES),
        },
        "analytic_v5p64": {
            "peak_flops_per_chip": V5P_PEAK_FLOPS,
            "model_flops_per_chip_step": model_flops_chip,
            "remat_factor": round(REMAT_FACTOR, 4),
            "anchor": {
                "source": "tests/perf/XL_WIDTH_ANCHOR.json",
                "anchor_width": anchor["config"]["d_model"],
                "eff_layers": EFF_LAYERS,
                "eff_head": EFF_HEAD,
                "overhead_s_per_microstep": round(OVERHEAD_S, 4),
            },
            "compute_s_per_step": round(compute_s, 4),
            "zero2_comm_bytes_per_chip": comm_bytes,
            "ici_comm_s_per_step": round(comm_s, 4),
            "step_s_no_overlap": round(step_worst, 4),
            "step_s_full_overlap": round(step_best, 4),
            "mfu_no_overlap": round(mfu_worst, 4),
            "mfu_full_overlap": round(mfu_best, 4),
            "tokens_per_s_per_chip_range": [
                round(tokens_chip / step_worst, 1),
                round(tokens_chip / step_best, 1)],
            "target_mfu": 0.45,
            "meets_target": bool(mfu_worst >= 0.45),
        },
        "notes": [
            "memory/cost numbers are XLA buffer assignment + flop "
            "count for the exact fused ZeRO-2 train step, "
            "SPMD-partitioned over an 8-way data mesh (virtual CPU "
            "devices); per-chip flops are dp-invariant and dp=8 "
            "per-chip memory upper-bounds dp=64 (sharded optimizer "
            "state only shrinks with dp)",
            "comm pricing assumes bf16 wire dtype on the data axis over "
            "the v5p 3D torus at 600 GB/s/chip bidirectional",
            "mfu range brackets zero vs full RS/AG overlap with compute; "
            "XLA's latency-hiding scheduler lands between the brackets",
            "executed-flop efficiencies are real-chip (v5e) "
            "measurements AT WIDTH 1600 (tests/perf/XL_WIDTH_ANCHOR."
            "json: per-layer slope over a 1/2/4/8-depth sweep with the "
            "grouped-fused flash backward, head/CE separately); the "
            "depth-independent overhead keeps its v5e wall time, and "
            "with 95 GB HBM the micro-batch can grow well past 8, "
            "which raises matmul efficiency further — the projection "
            "is conservative",
        ],
    }
    path = os.path.join(os.path.dirname(__file__), "V5P64_ANALYSIS.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out["compiled_per_chip"]))
    print(json.dumps(out["analytic_v5p64"]))


if __name__ == "__main__":
    main()
