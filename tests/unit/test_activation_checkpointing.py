"""Activation checkpointing tests (reference
tests/unit/test_activation_checkpointing.py): gradients through the
checkpointed function must equal gradients through the plain function, with
and without partition/cpu options; RNG tracker semantics."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ckpt


def _reset_options():
    ckpt.PARTITION_ACTIVATIONS = False
    ckpt.CPU_CHECKPOINT = False
    ckpt.CONTIGUOUS_CHECKPOINTING = False
    ckpt.SYNCHRONIZE = False
    ckpt.PROFILE_TIME = False


@pytest.fixture(autouse=True)
def reset_options():
    _reset_options()
    yield
    _reset_options()


def _mlp(w1, w2, x):
    h = jnp.tanh(x @ w1)
    return jnp.sum(jnp.tanh(h @ w2) ** 2)


def _rand_weights(seed=0, d=16):
    rng = np.random.RandomState(seed)
    w1 = jnp.asarray(rng.randn(d, 4 * d), jnp.float32)
    w2 = jnp.asarray(rng.randn(4 * d, d), jnp.float32)
    x = jnp.asarray(rng.randn(8, d), jnp.float32)
    return w1, w2, x


def test_checkpoint_matches_plain_grads():
    w1, w2, x = _rand_weights()

    def loss_plain(w1, w2):
        return _mlp(w1, w2, x)

    def loss_ckpt(w1, w2):
        return ckpt.checkpoint(_mlp, w1, w2, x)

    g_plain = jax.grad(loss_plain, argnums=(0, 1))(w1, w2)
    g_ckpt = jax.grad(loss_ckpt, argnums=(0, 1))(w1, w2)
    # rtol 1e-5 + atol 2e-6, not 1e-6/0: the rematerialized backward
    # re-orders the fp32 reductions, and jax 0.4.x CPU drifts the last
    # digit (~7e-7 abs) on near-zero lanes — same math, different
    # summation tree
    for a, b in zip(g_plain, g_ckpt):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=2e-6)


def test_checkpoint_inside_jit():
    w1, w2, x = _rand_weights(1)

    @jax.jit
    def loss(w1, w2):
        return ckpt.checkpoint(_mlp, w1, w2, x)

    g = jax.grad(loss)(w1, w2)
    assert np.isfinite(np.asarray(g)).all()


def test_partition_activations_grads_match():
    ckpt.configure(partition_activations=True)
    w1, w2, x = _rand_weights(2)

    def loss_ckpt(w1, w2):
        return ckpt.checkpoint(_mlp, w1, w2, x)

    g_ckpt = jax.grad(loss_ckpt, argnums=(0, 1))(w1, w2)
    g_plain = jax.grad(lambda a, b: _mlp(a, b, x), argnums=(0, 1))(w1, w2)
    # same last-digit remat drift as test_checkpoint_matches_plain_grads
    for a, b in zip(g_plain, g_ckpt):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=2e-6)


def test_configure_from_ds_config(tmp_config_file):
    path = tmp_config_file({
        "train_batch_size": 8,
        "activation_checkpointing": {
            "partition_activations": True,
            "cpu_checkpointing": False,
            "number_checkpoints": 4,
            "profile": False,
        },
    })
    ckpt.configure(deepspeed_config=path)
    assert ckpt.is_configured()
    assert ckpt.PARTITION_ACTIVATIONS is True
    assert ckpt.num_layers == 4


def test_contiguous_requires_partition():
    with pytest.raises(ValueError):
        ckpt.configure(partition_activations=False,
                       contiguous_checkpointing=True, num_checkpoints=2)


def test_checkpoint_wrapper_decorator():
    w1, w2, x = _rand_weights(3)
    wrapped = ckpt.checkpoint_wrapper(_mlp)
    np.testing.assert_allclose(np.asarray(wrapped(w1, w2, x)),
                               np.asarray(_mlp(w1, w2, x)), rtol=1e-6)


def test_rng_tracker_fork_advances():
    ckpt.model_parallel_cuda_manual_seed(123, tp_rank=0)
    tracker = ckpt.get_cuda_rng_tracker()
    with tracker.fork() as k1:
        a = jax.random.normal(k1, (4,))
    with tracker.fork() as k2:
        b = jax.random.normal(k2, (4,))
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_rng_tracker_tp_ranks_differ():
    ckpt.model_parallel_cuda_manual_seed(7, tp_rank=0)
    s0 = ckpt.get_cuda_rng_tracker().get_states()["model-parallel-rng"]
    ckpt.model_parallel_cuda_manual_seed(7, tp_rank=1)
    s1 = ckpt.get_cuda_rng_tracker().get_states()["model-parallel-rng"]
    assert not np.array_equal(np.asarray(s0), np.asarray(s1))


def test_rng_tracker_duplicate_seed_raises():
    tracker = ckpt.RNGStatesTracker()
    tracker.add("a", 1)
    with pytest.raises(Exception):
        tracker.add("b", 1)
    with pytest.raises(Exception):
        tracker.add("a", 2)


def test_public_api_reachable():
    assert deepspeed.checkpointing.checkpoint is ckpt.checkpoint


def test_engine_applies_config_section():
    """An activation_checkpointing config block configures the module at
    engine init (the reference requires a manual configure() call)."""
    import deepspeed_tpu
    from deepspeed_tpu.runtime.model import Model
    from deepspeed_tpu.runtime.activation_checkpointing import checkpointing

    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "activation_checkpointing": {"partition_activations": True,
                                     "cpu_checkpointing": True},
    }
    # the engine only auto-applies when unconfigured; earlier tests in
    # this file may have called configure()
    checkpointing.deepspeed_checkpointing_enabled = False
    try:
        deepspeed_tpu.initialize(
            model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                        {"w": jnp.zeros((4, 2))}),
            config_params=config)
        assert checkpointing.is_configured()
        assert checkpointing.PARTITION_ACTIVATIONS
        assert checkpointing.CPU_CHECKPOINT
    finally:
        # restore every global configure() mutated — later tests must see
        # the unconfigured default
        checkpointing.PARTITION_ACTIVATIONS = False
        checkpointing.CPU_CHECKPOINT = False
        checkpointing.deepspeed_checkpointing_enabled = False
        checkpointing.mpu = None
