"""Inference serving: init_inference, KV-cache decode, continuous batching.

The acceptance spec for the subsystem (ISSUE 2): incremental decode
logits match the full forward within 1e-5 (fp32, CPU), continuous
batching returns exactly what sequential generation returns, and prefill
bucketing bounds the number of jit traces.

Most tests share one module-level engine: slot reuse needs no cache
clearing (itself pinned below), so serving state never leaks between
requests — and the shared jit caches keep the file tier-1-fast.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import gpt2

pytestmark = pytest.mark.inference

TINY = dict(vocab_size=128, max_seq_len=64, n_layers=2, n_heads=2,
            d_model=32, use_flash_attention=False, remat=False)


def tiny_model(seed=0, **over):
    cfg = gpt2.GPT2Config(**{**TINY, **over})
    return gpt2.make_gpt2_model(config=cfg, seed=seed)


def make_engine(model=None, **inference):
    inference.setdefault("max_batch_size", 2)
    inference.setdefault("prefill_buckets", [8, 16, 32])
    inference.setdefault("dtype", "fp32")
    inference.setdefault("greedy", True)
    return deepspeed.init_inference(model=model or tiny_model(),
                                    config={"inference": inference})


@pytest.fixture(scope="module")
def shared():
    """(model, engine) reused across tests — exercises slot reuse for free."""
    model = tiny_model()
    return model, make_engine(model)


def full_forward_logits(model, seq):
    """Dense full-forward logits for the whole sequence — the parity spec
    for decode. Causality makes row i valid for every prefix >= i+1, so
    ONE call at the final length checks every decode step."""
    ids = jnp.asarray(np.asarray(seq, np.int32)[None])
    hidden = gpt2.forward_hidden(model.params, ids, model.config,
                                 train=False)
    return np.asarray(hidden[0] @ model.params["wte"].T)


def greedy_chain(model, prompt, n):
    """Reference generation: n greedy tokens via repeated full forwards."""
    seq = list(prompt)
    for _ in range(n):
        seq.append(int(full_forward_logits(model, seq)[-1].argmax()))
    return seq[len(prompt):]


# --------------------------------------------------------------- parity


def test_decode_logits_match_full_forward(shared):
    """Prefill + 6 greedy decode steps produce, at every step, the same
    next-token logits as the full forward over the final sequence
    (fp32, atol 1e-5)."""
    model, eng = shared
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, 128, size=11).tolist()
    n = len(prompt)

    greedy, top_k, _, _ = eng._sampling_key(None)
    fn = eng._get_prefill_fn(eng.bucket_for(n), greedy, top_k)
    ids = np.zeros((1, eng.bucket_for(n)), np.int32)
    ids[0, :n] = prompt
    k, v, token, p_logits = fn(
        eng.params, eng.kv.k, eng.kv.v, jnp.asarray(ids), jnp.int32(0),
        jnp.int32(0), jnp.int32(n), jax.random.PRNGKey(0),
        jnp.float32(1.0), jnp.float32(1.0))
    eng.kv.update((k, v))
    eng.lengths[0] = n

    seq = prompt + [int(token)]
    step_logits = [np.asarray(p_logits)]
    dfn = eng._get_decode_fn(greedy, top_k)
    for _ in range(6):
        tokens = np.zeros((eng.num_slots, 1), np.int32)
        tokens[0, 0] = seq[-1]
        k, v, nxt, d_logits = dfn(
            eng.params, eng.kv.k, eng.kv.v, jnp.asarray(tokens),
            jnp.asarray(eng.lengths), jax.random.PRNGKey(0),
            jnp.float32(1.0), jnp.float32(1.0))
        eng.kv.update((k, v))
        eng.advance(0)
        step_logits.append(np.asarray(d_logits[0, 0]))
        seq.append(int(nxt[0, 0]))
    eng.free_slot(0)

    ref = full_forward_logits(model, seq)      # one dense pass at the end
    for t, got in enumerate(step_logits):
        np.testing.assert_allclose(got, ref[n - 1 + t], atol=1e-5)
    # greedy sampling == argmax of those logits
    assert seq[n:] == [int(ref[n - 1 + t].argmax()) for t in range(7)]


# --------------------------------------------- continuous batching


def test_continuous_batching_matches_sequential(shared):
    """Scheduler output == one-request-at-a-time generation (greedy), with
    prompts spanning buckets."""
    _, eng = shared
    rs = np.random.RandomState(1)
    prompts = [rs.randint(0, 128, size=sz).tolist() for sz in (3, 9, 14, 5)]
    batched = eng.generate(prompts, max_new_tokens=5)
    sequential = [eng.generate([p], max_new_tokens=5)[0] for p in prompts]
    assert batched == sequential
    assert all(len(o) == 5 for o in batched)


def test_scheduler_overlaps_and_retires(shared):
    """Heterogeneous lengths don't serialize: with 2 slots and 3 requests
    of very different budgets, the short ones retire and free their slot
    while the long one keeps decoding."""
    from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
    from deepspeed_tpu.utils.monitor import ServingMetrics
    _, eng = shared
    metrics = ServingMetrics()
    sched = ContinuousBatchingScheduler(eng, metrics=metrics)
    long_uid = sched.submit([1, 2, 3], max_new_tokens=20)
    s1 = sched.submit([4, 5], max_new_tokens=2)
    s2 = sched.submit([6], max_new_tokens=2)
    results = sched.run()
    assert len(results[long_uid]) == 20
    assert len(results[s1]) == 2 and len(results[s2]) == 2
    # total decode steps must be near the LONG request's budget, not the
    # sum of all three (continuous batching, not sequential batches)
    assert sched.steps <= 22, sched.steps
    snap = metrics.snapshot()
    assert snap["prefill_tokens"] == 6
    assert snap["decode_tokens"] >= 20
    assert snap["peak_queue_depth"] >= 1


def test_eos_retires_slot(shared):
    _, eng = shared
    prompt = [7, 7, 7]
    free_run = eng.generate([prompt], max_new_tokens=8)[0]
    eos = free_run[2]
    out = eng.generate([prompt], max_new_tokens=8, eos_token_id=eos)[0]
    # generation stops at the FIRST occurrence of eos (inclusive)
    assert out == free_run[:free_run.index(eos) + 1]
    assert eng.lengths.tolist() == [0] * eng.num_slots  # all slots freed


def test_config_eos_token_id_is_honored(shared):
    """inference.eos_token_id from ds_config applies through generate();
    an explicit eos_token_id=None disables it."""
    model, eng0 = shared                     # no config-level eos
    free = eng0.generate([[7, 7, 7]], max_new_tokens=6)[0]
    eos = free[1]
    eng = make_engine(model, eos_token_id=int(eos))
    out = eng.generate([[7, 7, 7]], max_new_tokens=6)[0]
    assert out == free[:free.index(eos) + 1]
    assert eng.generate([[7, 7, 7]], max_new_tokens=6,
                        eos_token_id=None)[0] == free


def test_slot_reuse_is_clean(shared):
    """A slot reused by a later request must not see the earlier
    request's cache entries (stale tail is masked, prefix overwritten)."""
    model, eng = shared
    rs = np.random.RandomState(2)
    long_p = rs.randint(0, 128, size=14).tolist()
    short_p = rs.randint(0, 128, size=4).tolist()
    eng.generate([long_p], max_new_tokens=6)       # fills slot 0 deep
    out = eng.generate([short_p], max_new_tokens=3)[0]   # reuses it shallow
    assert out == greedy_chain(model, short_p, 3)


def test_scan_blocks_model_serves_after_unstack():
    """A scan_blocks-trained model (stacked (L, ...) block params) is
    unstacked at engine build and serves with exact parity to its own
    full forward."""
    model = tiny_model(scan_blocks=True)
    eng = make_engine(model)
    prompt = [5, 80, 13, 2]
    out = eng.generate([prompt], max_new_tokens=3)[0]
    seq = list(prompt)
    for _ in range(3):   # greedy chain via the scan forward
        ids = jnp.asarray(np.asarray(seq, np.int32)[None])
        hidden = gpt2.forward_hidden(model.params, ids, model.config,
                                     train=False)
        seq.append(int(np.asarray(hidden[0, -1] @ model.params["wte"].T)
                       .argmax()))
    assert out == seq[len(prompt):]


def test_max_seq_len_caps_generation(shared):
    _, eng = shared
    prompt = list(range(30))           # max_seq_len 64 -> at most 35 new
    out = eng.generate([prompt], max_new_tokens=100)[0]
    assert len(out) == 64 - 30 + 1     # decode until the cache is full


# ---------------------------------------------------- recompile bounds


def test_prefill_bucketing_caps_jit_traces():
    """7 distinct prompt lengths, 3 buckets -> at most 3 prefill traces
    and exactly 1 decode trace (fresh engine so the count is exact)."""
    eng = make_engine(max_new_tokens=2)
    rs = np.random.RandomState(3)
    prompts = [rs.randint(0, 128, size=sz).tolist()
               for sz in range(2, 30, 4)]
    eng.generate(prompts)
    assert eng.compile_stats["prefill_traces"] <= 3
    assert eng.compile_stats["decode_traces"] == 1


def test_bucket_for_rejects_oversized_prompt(shared):
    _, eng = shared
    with pytest.raises(ValueError, match="prefill bucket"):
        eng.bucket_for(33)


def test_bad_request_params_rejected_at_submit(shared):
    _, eng = shared
    with pytest.raises(AssertionError, match="max_new_tokens"):
        eng.generate([[1, 2]], max_new_tokens=0)
    # oversized top_k clamps to vocab instead of a trace-time error
    out = eng.generate([[1, 2]], max_new_tokens=2,
                       sampling={"greedy": False, "top_k": 10 ** 6})
    assert len(out[0]) == 2


# ----------------------------------------------------------- sampling


def test_sampler_greedy_is_argmax():
    from deepspeed_tpu.inference.sampling import make_sampler
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(3, 50).astype(np.float32))
    out = make_sampler(True)(logits, jax.random.PRNGKey(0), 1.0, 1.0)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(logits).argmax(-1))


def test_sampler_top_k_masks_tail():
    from deepspeed_tpu.inference.sampling import make_sampler
    sample = make_sampler(False, top_k=2)
    logits = jnp.asarray([[0.0, 5.0, 4.0, -1.0, 1.0]] * 64,
                         dtype=jnp.float32)
    toks = np.asarray(sample(logits, jax.random.PRNGKey(1),
                             jnp.float32(1.0), jnp.float32(1.0)))
    assert set(toks.tolist()) <= {1, 2}


def test_sampler_top_p_keeps_nucleus():
    from deepspeed_tpu.inference.sampling import make_sampler
    sample = make_sampler(False, top_k=0)
    # token 0 has ~98% mass: top_p=0.5 nucleus is exactly {0}
    logits = jnp.asarray([[8.0, 4.0, 3.0, 2.0, 1.0]] * 64,
                         dtype=jnp.float32)
    toks = np.asarray(sample(logits, jax.random.PRNGKey(2),
                             jnp.float32(1.0), jnp.float32(0.5)))
    assert set(toks.tolist()) == {0}


def test_sampler_temperature_flattens():
    from deepspeed_tpu.inference.sampling import make_sampler
    sample = make_sampler(False, top_k=0)
    logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0]] * 512, dtype=jnp.float32)
    cold = np.asarray(sample(logits, jax.random.PRNGKey(3),
                             jnp.float32(0.05), jnp.float32(1.0)))
    hot = np.asarray(sample(logits, jax.random.PRNGKey(3),
                            jnp.float32(20.0), jnp.float32(1.0)))
    assert (cold == 0).all()                  # ~argmax at low temperature
    assert len(np.unique(hot)) >= 3           # near-uniform at high temp


def test_sampled_generation_is_reproducible():
    model = tiny_model()
    kw = dict(max_batch_size=1, prefill_buckets=[8], greedy=False,
              top_k=8, temperature=0.9)
    a = make_engine(model, **kw)
    b = make_engine(model, **kw)
    prompt = [3, 1, 4, 1, 5]
    assert a.generate([prompt], max_new_tokens=5) == \
        b.generate([prompt], max_new_tokens=5)   # same seed -> same keys


# ------------------------------------------------------ config surface


def test_inference_config_parses_and_validates():
    from deepspeed_tpu.inference.config import (DeepSpeedInferenceConfig,
                                                DeepSpeedInferenceConfigError)
    ic = DeepSpeedInferenceConfig({"inference": {
        "max_batch_size": 16, "max_seq_len": 256,
        "prefill_buckets": [128, 32], "dtype": "bf16",
        "max_new_tokens": 10, "eos_token_id": 50256,
        "greedy": False, "temperature": 0.7, "top_k": 40, "top_p": 0.9}})
    assert ic.max_batch_size == 16
    assert ic.prefill_buckets == [32, 128]      # sorted, deduped
    assert ic.dtype == jnp.bfloat16
    assert ic.resolve_buckets(256) == [32, 128]
    # a configured bucket beyond max_seq_len is a config error, not a
    # silently-dropped entry
    with pytest.raises(DeepSpeedInferenceConfigError, match="exceed"):
        ic.resolve_buckets(64)
    # defaults: power-of-two ladder capped by max_seq_len
    assert DeepSpeedInferenceConfig({}).resolve_buckets(256) == [64, 128, 256]
    for bad in ({"max_batch_size": 0}, {"dtype": "int8"},
                {"temperature": 0.0}, {"top_p": 0.0},
                {"prefill_buckets": []}, {"top_k": -1}):
        with pytest.raises(DeepSpeedInferenceConfigError):
            DeepSpeedInferenceConfig({"inference": bad})


def test_inference_only_ds_config_needs_no_batch_triple():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    cfg = DeepSpeedConfig(None, param_dict={
        "inference": {"max_batch_size": 2}}, inference_only=True)
    assert cfg.inference_config.max_batch_size == 2
    assert cfg.train_micro_batch_size_per_gpu == 1
    # the TRAINING parse still demands its batch triple even when an
    # inference section is present (one config may drive both entry points)
    with pytest.raises(AssertionError, match="train_batch_size"):
        DeepSpeedConfig(None, param_dict={"inference": {}})
    # and init_inference works from an empty dict (all defaults)
    eng = deepspeed.init_inference(model=tiny_model(), config={})
    assert eng.num_slots == 8


def test_unknown_inference_key_strict_raises():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    with pytest.raises(DeepSpeedConfigError, match="inference"):
        DeepSpeedConfig(None, param_dict={
            "config_validation": "strict",
            "inference": {"max_batch_sizes": 4}}, inference_only=True)


# ----------------------------------------------------------- sharding


def test_kv_cache_sharded_over_heads_and_decode_parity():
    """TP mesh: params placed with Megatron specs, KV cache heads-sharded,
    and decode still matches the unsharded full forward."""
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.inference.kv_cache import KV_CACHE_SPEC
    mesh = build_mesh(data=4, model=2)
    model = tiny_model()
    eng = deepspeed.init_inference(model=model, mesh=mesh, config={
        "inference": {"max_batch_size": 2, "prefill_buckets": [16],
                      "dtype": "fp32", "greedy": True}})
    assert eng.kv.k.sharding.spec == KV_CACHE_SPEC
    assert "model" in str(
        eng.params["blocks"][0]["attn"]["qkv_kernel"].sharding.spec)
    prompt = [11, 3, 9, 60, 2]
    out = eng.generate([prompt], max_new_tokens=3)[0]
    assert out == greedy_chain(model, prompt, 3)


def test_init_inference_mp_size_builds_mesh():
    eng = deepspeed.init_inference(model=tiny_model(), mp_size=2, config={
        "inference": {"max_batch_size": 2, "dtype": "fp32"}})
    assert eng.mesh is not None and eng.mesh.shape["model"] == 2
