"""End-to-end engine tests: the cifar-smoke equivalent on the CPU mesh.

Mirrors reference tests/unit/test_fp16.py / test_zero.py patterns: tiny
models, a few steps, loss decreases, feature combos agree with each other.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from simple_model import make_simple_model, SimpleDataset, base_config

HIDDEN = 8
WORLD = 8


def train_steps(engine, dataset, steps, micro_batch=None):
    """Classic DeepSpeed loop: forward/backward/step per micro batch."""
    mb = micro_batch or engine.train_micro_batch_size_per_gpu() * \
        engine.dp_world_size
    losses = []
    idx = 0
    for _ in range(steps):
        x = np.stack([dataset[i % len(dataset)][0]
                      for i in range(idx, idx + mb)])
        y = np.stack([dataset[i % len(dataset)][1]
                      for i in range(idx, idx + mb)])
        idx += mb
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def make_engine(config, seed=0, **kwargs):
    model = make_simple_model(HIDDEN, seed=seed)
    engine, _, _, _ = deepspeed.initialize(model=model, config_params=config,
                                           **kwargs)
    return engine


def test_forward_backward_step_reduces_loss():
    engine = make_engine(base_config(WORLD))
    dataset = SimpleDataset(256, HIDDEN)
    losses = train_steps(engine, dataset, 20)
    assert losses[-1] < losses[0] * 0.9, losses


def test_eval_mode_no_grads():
    engine = make_engine(base_config(WORLD))
    dataset = SimpleDataset(64, HIDDEN)
    engine.eval()
    x = np.stack([dataset[i][0] for i in range(32)])
    y = np.stack([dataset[i][1] for i in range(32)])
    loss1 = float(engine(x, y))
    loss2 = float(engine(x, y))
    assert loss1 == pytest.approx(loss2)
    engine.train()


def test_gradient_accumulation_equivalence():
    """gas=2 over half-batches == gas=1 over the full batch."""
    dataset = SimpleDataset(256, HIDDEN)
    cfg1 = base_config(WORLD, micro_batch=8, gas=1)
    cfg2 = base_config(WORLD, micro_batch=4, gas=2)
    e1 = make_engine(cfg1, seed=3)
    e2 = make_engine(cfg2, seed=3)

    full = 8 * WORLD
    half = 4 * WORLD
    for step in range(3):
        x = np.stack([dataset[i][0] for i in range(step * full,
                                                   (step + 1) * full)])
        y = np.stack([dataset[i][1] for i in range(step * full,
                                                   (step + 1) * full)])
        loss = e1(x, y)
        e1.backward(loss)
        e1.step()
        for g in range(2):
            xs = x[g * half:(g + 1) * half]
            ys = y[g * half:(g + 1) * half]
            loss = e2(xs, ys)
            e2.backward(loss)
            e2.step()

    p1 = jax.tree_util.tree_leaves(e1.get_params())
    p2 = jax.tree_util.tree_leaves(e2.get_params())
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_boundary_logic():
    engine = make_engine(base_config(WORLD, gas=4))
    assert engine.is_gradient_accumulation_boundary() is False
    engine.micro_steps = 3
    assert engine.is_gradient_accumulation_boundary() is True


def test_fused_train_batch_matches_unfused():
    dataset = SimpleDataset(256, HIDDEN)
    cfg = base_config(WORLD, micro_batch=4, gas=2)
    e1 = make_engine(cfg, seed=5)
    e2 = make_engine(cfg, seed=5)
    half = 4 * WORLD

    for step in range(2):
        xs = [np.stack([dataset[i][0] for i in range(
            (2 * step + g) * half, (2 * step + g + 1) * half)])
            for g in range(2)]
        ys = [np.stack([dataset[i][1] for i in range(
            (2 * step + g) * half, (2 * step + g + 1) * half)])
            for g in range(2)]
        for g in range(2):
            loss = e1(xs[g], ys[g])
            e1.backward(loss)
            e1.step()
        e2.train_batch(batch=(np.stack(xs), np.stack(ys)))

    for a, b in zip(jax.tree_util.tree_leaves(e1.get_params()),
                    jax.tree_util.tree_leaves(e2.get_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    assert e1.global_steps == e2.global_steps


def test_lr_scheduler_warmup():
    cfg = base_config(WORLD)
    cfg["scheduler"] = {"type": "WarmupLR",
                        "params": {"warmup_min_lr": 0.0,
                                   "warmup_max_lr": 0.01,
                                   "warmup_num_steps": 10}}
    engine = make_engine(cfg)
    dataset = SimpleDataset(128, HIDDEN)
    lrs = []
    mb = engine.train_micro_batch_size_per_gpu() * WORLD
    for step in range(5):
        x = np.stack([dataset[i][0] for i in range(mb)])
        y = np.stack([dataset[i][1] for i in range(mb)])
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        lrs.append(engine.get_lr()[0])
    assert lrs == sorted(lrs)
    assert lrs[-1] < 0.01


def test_fp16_dynamic_loss_scale_overflow_skip():
    cfg = base_config(WORLD)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 8,
                   "loss_scale_window": 1000}
    engine = make_engine(cfg)
    dataset = SimpleDataset(64, HIDDEN)
    mb = engine.train_micro_batch_size_per_gpu() * WORLD

    x = np.stack([dataset[i][0] for i in range(mb)])
    y = np.stack([dataset[i][1] for i in range(mb)])
    scale0 = engine.loss_scale()
    assert scale0 == 2 ** 8

    # poison one micro batch -> inf loss -> overflow skip + scale halves
    params_before = jax.tree_util.tree_map(np.asarray, engine.get_params())
    x_bad = x.copy()
    x_bad[0, 0] = np.float16(1e4) ** 2 if False else 1e30
    loss = engine(x_bad, y)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 1
    # default hysteresis=2: first overflow spends hysteresis, keeps scale
    assert engine.loss_scale() == scale0
    params_after = jax.tree_util.tree_map(np.asarray, engine.get_params())
    for a, b in zip(jax.tree_util.tree_leaves(params_before),
                    jax.tree_util.tree_leaves(params_after)):
        np.testing.assert_array_equal(a, b)

    # second overflow halves the scale
    loss = engine(x_bad, y)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 2
    assert engine.loss_scale() == scale0 / 2

    # clean step trains normally
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 2
    assert engine.global_steps == 3


def test_fp16_converges():
    cfg = base_config(WORLD)
    cfg["fp16"] = {"enabled": True, "loss_scale": 0}
    engine = make_engine(cfg)
    dataset = SimpleDataset(256, HIDDEN)
    losses = train_steps(engine, dataset, 20)
    assert losses[-1] < losses[0] * 0.9


def test_bf16_converges():
    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    engine = make_engine(cfg)
    dataset = SimpleDataset(256, HIDDEN)
    losses = train_steps(engine, dataset, 20)
    assert losses[-1] < losses[0] * 0.9


def test_gradient_clipping_applied():
    cfg = base_config(WORLD, gradient_clipping=1e-4)
    engine = make_engine(cfg)
    dataset = SimpleDataset(64, HIDDEN)
    before = jax.tree_util.tree_map(np.asarray, engine.get_params())
    train_steps(engine, dataset, 1)
    after = jax.tree_util.tree_map(np.asarray, engine.get_params())
    # updates bounded by lr * (clip-influenced update); just check tiny change
    max_delta = max(np.max(np.abs(a - b)) for a, b in
                    zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(after)))
    assert max_delta < 1e-1


def test_lamb_optimizer():
    cfg = base_config(WORLD)
    cfg["optimizer"] = {"type": "Lamb", "params": {"lr": 1e-2}}
    engine = make_engine(cfg)
    dataset = SimpleDataset(256, HIDDEN)
    losses = train_steps(engine, dataset, 10)
    assert losses[-1] < losses[0]


def test_overflow_fetch_policy():
    """Per-step host overflow readback: required for fp16 (the reference's
    FP16_Optimizer runs CheckOverflow even with a STATIC scale), skipped
    for bf16/fp32 (reference non-fp16 path has no overflow machinery; the
    in-jit guard still no-ops a non-finite step)."""
    import jax.numpy as jnp

    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    assert not make_engine(cfg)._overflow_fetch_needed()

    cfg = base_config(WORLD)
    cfg["fp16"] = {"enabled": True, "loss_scale": 128}   # static fp16
    eng = make_engine(cfg)
    if eng.compute_dtype == jnp.float16:  # on TPU fp16 maps to bf16
        assert eng._overflow_fetch_needed()

    cfg = base_config(WORLD)
    cfg["fp16"] = {"enabled": True}                      # dynamic fp16
    eng = make_engine(cfg)
    assert eng.state["scaler"].dynamic
    assert eng._overflow_fetch_needed()


def test_bf16_state_dtypes_and_convergence():
    """Round-5 HBM levers: optimizer.params.moments_dtype=bf16 stores the
    Adam moments in bf16 (update math fp32) and
    data_types.grad_accum_dtype=bf16 stores the accumulation buffer in
    bf16. State dtypes reflect the config; training still converges and
    tracks the fp32-state trajectory closely at gas=1 (where bf16
    accumulation is lossless — micro grads arrive in the compute dtype)."""
    cfg = base_config(WORLD, bf16={"enabled": True})
    cfg["optimizer"]["params"]["moments_dtype"] = "bf16"
    cfg["data_types"] = {"grad_accum_dtype": "bf16"}
    engine = make_engine(cfg, seed=7)
    acc = jax.tree_util.tree_leaves(engine.state["acc_grads"])[0]
    mom = jax.tree_util.tree_leaves(engine.state["opt"]["exp_avg"])[0]
    assert acc.dtype == jnp.bfloat16
    assert mom.dtype == jnp.bfloat16

    ref_cfg = base_config(WORLD, bf16={"enabled": True})
    ref = make_engine(ref_cfg, seed=7)
    assert jax.tree_util.tree_leaves(
        ref.state["acc_grads"])[0].dtype == jnp.float32

    ds = SimpleDataset(64, HIDDEN)
    losses = train_steps(engine, ds, 30)
    ref_losses = train_steps(ref, ds, 30)
    assert losses[-1] < losses[0] * 0.6
    # same data, same seed: trajectories stay close (moments rounding only)
    drift = max(abs(a - b) for a, b in zip(losses, ref_losses))
    assert drift < 0.15 * abs(ref_losses[0]) + 1e-3, drift


def test_grad_accum_dtype_validation():
    """Unknown grad_accum_dtype values are rejected at config parse."""
    cfg = base_config(WORLD, bf16={"enabled": True})
    cfg["data_types"] = {"grad_accum_dtype": "fp8"}
    with pytest.raises(Exception, match="grad_accum_dtype"):
        make_engine(cfg)


def test_bf16_moments_update_math_fp32():
    """adam_update with bf16 stored moments computes in fp32 and matches
    the fp32-state update to bf16 rounding of the state itself."""
    from deepspeed_tpu.ops.adam.fused_adam import adam_init, adam_update
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16, 16), jnp.float32)}
    grads = {"w": jnp.asarray(rng.randn(16, 16) * 0.1, jnp.float32)}
    s32 = adam_init(params)
    s16 = adam_init(params, moments_dtype=jnp.bfloat16)
    p32, n32 = adam_update(grads, s32, params, 1e-2, 0.9, 0.999, 1e-8, 0.0,
                           use_pallas=False)
    p16, n16 = adam_update(grads, s16, params, 1e-2, 0.9, 0.999, 1e-8, 0.0,
                           use_pallas=False)
    assert n16["exp_avg"]["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(p16["w"]), np.asarray(p32["w"]),
                               rtol=2e-2, atol=2e-4)


def test_lamb_bf16_moments():
    """FusedLamb carries the same moments_dtype lever as Adam (the
    round-5 BERT bench rides it): bf16 stored moments, fp32 update
    math, pallas combo rejected loudly."""
    from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb, lamb_update
    import pytest as _pytest
    opt = FusedLamb(lr=1e-3, moments_dtype="bf16")
    params = {"w": jnp.ones((8, 8), jnp.float32)}
    state = opt.init_state(params)
    assert state["exp_avg"]["w"].dtype == jnp.bfloat16
    grads = {"w": jnp.full((8, 8), 0.1, jnp.float32)}
    new_p, new_s = opt.update(grads, state, params, lr=1e-3, beta1=0.9,
                              beta2=0.999, eps=1e-8, weight_decay=0.0)
    assert new_s["exp_avg"]["w"].dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(new_p["w"])).all()
    with _pytest.raises(ValueError, match="incompatible"):
        FusedLamb(use_pallas=True, moments_dtype="bf16")
