"""Checkpoint save/resume (mirrors reference tests/unit/test_checkpointing.py)."""
import os

import numpy as np
import pytest

import jax

import deepspeed_tpu as deepspeed
from simple_model import make_simple_model, SimpleDataset, base_config

HIDDEN = 8
WORLD = 8


def make_engine(config, seed=0):
    model = make_simple_model(HIDDEN, seed=seed)
    engine, _, _, _ = deepspeed.initialize(model=model, config_params=config)
    return engine


def run_steps(engine, dataset, steps, offset=0):
    mb = engine.train_micro_batch_size_per_gpu() * WORLD
    losses = []
    for s in range(steps):
        base = (offset + s) * mb
        x = np.stack([dataset[(base + i) % len(dataset)][0] for i in range(mb)])
        y = np.stack([dataset[(base + i) % len(dataset)][1] for i in range(mb)])
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def checkpoint_correctness_test(config, tmp_path, seed=0):
    dataset = SimpleDataset(512, HIDDEN)
    save_dir = str(tmp_path / "ckpt")

    e1 = make_engine(config, seed=seed)
    run_steps(e1, dataset, 5)
    e1.save_checkpoint(save_dir, client_state={"custom": 123})
    trained_more = run_steps(e1, dataset, 3, offset=5)

    e2 = make_engine(config, seed=seed + 99)  # different init
    path, client_state = e2.load_checkpoint(save_dir)
    assert path is not None
    assert client_state["custom"] == 123
    assert e2.global_steps == e1.global_steps - 3

    # params equal after load
    for a, b in zip(jax.tree_util.tree_leaves(e1.get_master_params()),
                    jax.tree_util.tree_leaves(e2.get_master_params())):
        pass  # e1 trained further; compare e2 against a fresh save instead

    resumed = run_steps(e2, dataset, 3, offset=5)
    np.testing.assert_allclose(np.array(resumed), np.array(trained_more),
                               rtol=2e-4, atol=1e-5)


def test_checkpoint_fp32(tmp_path):
    checkpoint_correctness_test(base_config(WORLD), tmp_path)


def test_checkpoint_fp16(tmp_path):
    cfg = base_config(WORLD)
    cfg["fp16"] = {"enabled": True, "loss_scale": 0}
    checkpoint_correctness_test(cfg, tmp_path)


def test_checkpoint_zero_stage1(tmp_path):
    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    cfg["zero_optimization"] = {"stage": 1}
    checkpoint_correctness_test(cfg, tmp_path)


def test_checkpoint_zero_stage2(tmp_path):
    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    cfg["zero_optimization"] = {"stage": 2}
    checkpoint_correctness_test(cfg, tmp_path)


def test_checkpoint_lr_scheduler(tmp_path):
    cfg = base_config(WORLD)
    cfg["scheduler"] = {"type": "WarmupDecayLR",
                        "params": {"warmup_max_lr": 0.01,
                                   "warmup_num_steps": 4,
                                   "total_num_steps": 100}}
    dataset = SimpleDataset(512, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(cfg)
    run_steps(e1, dataset, 5)
    lr_before = e1.get_lr()[0]
    e1.save_checkpoint(save_dir)

    e2 = make_engine(cfg, seed=7)
    e2.load_checkpoint(save_dir)
    assert e2.lr_scheduler.last_batch_iteration == \
        e1.lr_scheduler.last_batch_iteration
    run_steps(e2, dataset, 1, offset=5)
    assert e2.get_lr()[0] != lr_before  # schedule continued, not restarted


def test_latest_tag(tmp_path):
    cfg = base_config(WORLD)
    dataset = SimpleDataset(128, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    engine = make_engine(cfg)
    run_steps(engine, dataset, 1)
    engine.save_checkpoint(save_dir, tag="mytag")
    assert open(os.path.join(save_dir, "latest")).read().strip() == "mytag"
    engine.save_checkpoint(save_dir)
    assert open(os.path.join(save_dir, "latest")).read().strip() == \
        "global_step1"


def test_load_missing_checkpoint_warns(tmp_path):
    engine = make_engine(base_config(WORLD))
    path, client_state = engine.load_checkpoint(str(tmp_path / "nope"))
    assert path is None and client_state is None


def test_save_without_scheduler_load_with_none(tmp_path):
    cfg = base_config(WORLD)
    dataset = SimpleDataset(128, HIDDEN)
    engine = make_engine(cfg)
    run_steps(engine, dataset, 2)
    save_dir = str(tmp_path / "ckpt")
    engine.save_checkpoint(save_dir)
    e2 = make_engine(cfg, seed=4)
    e2.load_checkpoint(save_dir, load_optimizer_states=False,
                       load_lr_scheduler_states=False)
    assert e2.global_steps == 2
