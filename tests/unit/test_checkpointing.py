"""Checkpoint save/resume (mirrors reference tests/unit/test_checkpointing.py)."""
import os

import numpy as np
import pytest

import jax

import deepspeed_tpu as deepspeed
from simple_model import make_simple_model, SimpleDataset, base_config

HIDDEN = 8
WORLD = 8


def make_engine(config, seed=0):
    model = make_simple_model(HIDDEN, seed=seed)
    engine, _, _, _ = deepspeed.initialize(model=model, config_params=config)
    return engine


def run_steps(engine, dataset, steps, offset=0):
    mb = engine.train_micro_batch_size_per_gpu() * WORLD
    losses = []
    for s in range(steps):
        base = (offset + s) * mb
        x = np.stack([dataset[(base + i) % len(dataset)][0] for i in range(mb)])
        y = np.stack([dataset[(base + i) % len(dataset)][1] for i in range(mb)])
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def checkpoint_correctness_test(config, tmp_path, seed=0):
    dataset = SimpleDataset(512, HIDDEN)
    save_dir = str(tmp_path / "ckpt")

    e1 = make_engine(config, seed=seed)
    run_steps(e1, dataset, 5)
    e1.save_checkpoint(save_dir, client_state={"custom": 123})
    trained_more = run_steps(e1, dataset, 3, offset=5)

    e2 = make_engine(config, seed=seed + 99)  # different init
    path, client_state = e2.load_checkpoint(save_dir)
    assert path is not None
    assert client_state["custom"] == 123
    assert e2.global_steps == e1.global_steps - 3

    # params equal after load
    for a, b in zip(jax.tree_util.tree_leaves(e1.get_master_params()),
                    jax.tree_util.tree_leaves(e2.get_master_params())):
        pass  # e1 trained further; compare e2 against a fresh save instead

    resumed = run_steps(e2, dataset, 3, offset=5)
    np.testing.assert_allclose(np.array(resumed), np.array(trained_more),
                               rtol=2e-4, atol=1e-5)


def test_checkpoint_fp32(tmp_path):
    checkpoint_correctness_test(base_config(WORLD), tmp_path)


def test_checkpoint_fp16(tmp_path):
    cfg = base_config(WORLD)
    cfg["fp16"] = {"enabled": True, "loss_scale": 0}
    checkpoint_correctness_test(cfg, tmp_path)


def test_checkpoint_zero_stage1(tmp_path):
    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    cfg["zero_optimization"] = {"stage": 1}
    checkpoint_correctness_test(cfg, tmp_path)


def test_checkpoint_zero_stage2(tmp_path):
    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    cfg["zero_optimization"] = {"stage": 2}
    checkpoint_correctness_test(cfg, tmp_path)


def test_zero_checkpoint_layout_is_sharded(tmp_path):
    """Device-state ZeRO saves write the master/optimizer ONLY to the
    per-process zero file (reference zero_pp_rank layout) — the model
    file must not duplicate them (VERDICT r2 weak #5)."""
    from deepspeed_tpu.runtime import checkpointing as ckpt
    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    cfg["zero_optimization"] = {"stage": 2}
    dataset = SimpleDataset(128, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    engine = make_engine(cfg)
    run_steps(engine, dataset, 2)
    engine.save_checkpoint(save_dir, tag="t")
    sd = ckpt.load_state_dict(ckpt.model_ckpt_name(save_dir, "t"))
    assert sd["optimizer"] is None and sd["master"] is None
    zsd = ckpt.load_state_dict(ckpt.zero_ckpt_name(save_dir, "t", dp_rank=0))
    assert "device_shards" in zsd
    # sharded master leaves: one shard per unique addressable index; they
    # reassemble to the live master bit-exact
    assembled = ckpt.assemble_shard_lists(
        [zsd["device_shards"]["master"]], "master")
    live = [np.asarray(x, np.float32)
            for x in jax.tree_util.tree_leaves(engine.state["master"])]
    for a, b in zip(assembled, live):
        np.testing.assert_array_equal(a, b)


def test_offload_checkpoint_loads_into_device_engine(tmp_path):
    """Cross-engine resume: a ZeRO-Offload checkpoint (host shard files)
    restores a non-offload ZeRO engine's master AND moments — previously
    the moments silently reset (round-2 ADVICE #2)."""
    dataset = SimpleDataset(128, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    off_cfg = base_config(WORLD)
    off_cfg["bf16"] = {"enabled": True}
    off_cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    e1 = make_engine(off_cfg)
    run_steps(e1, dataset, 4)
    e1.save_checkpoint(save_dir, tag="x")

    dev_cfg = base_config(WORLD)
    dev_cfg["bf16"] = {"enabled": True}
    dev_cfg["zero_optimization"] = {"stage": 2}
    e2 = make_engine(dev_cfg, seed=5)
    path, _ = e2.load_checkpoint(save_dir, tag="x")
    assert path is not None
    for a, b in zip(jax.tree_util.tree_leaves(e1.get_master_params()),
                    jax.tree_util.tree_leaves(e2.get_master_params())):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=1e-7)
    opt_view = e1._opt_state_view()
    for key in ("exp_avg", "exp_avg_sq"):
        for a, b in zip(jax.tree_util.tree_leaves(opt_view[key]),
                        jax.tree_util.tree_leaves(e2.state["opt"][key])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32), atol=1e-7)


def test_device_zero_checkpoint_loads_into_offload_engine(tmp_path):
    """And the reverse: a device-state sharded ZeRO checkpoint restores an
    offload engine's host shards."""
    dataset = SimpleDataset(128, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    dev_cfg = base_config(WORLD)
    dev_cfg["bf16"] = {"enabled": True}
    dev_cfg["zero_optimization"] = {"stage": 2}
    e1 = make_engine(dev_cfg)
    run_steps(e1, dataset, 4)
    e1.save_checkpoint(save_dir, tag="x")

    off_cfg = base_config(WORLD)
    off_cfg["bf16"] = {"enabled": True}
    off_cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    e2 = make_engine(off_cfg, seed=5)
    path, _ = e2.load_checkpoint(save_dir, tag="x")
    assert path is not None
    assert e2.host_state["step"] == 4
    for a, b in zip(jax.tree_util.tree_leaves(e1.get_master_params()),
                    jax.tree_util.tree_leaves(e2.get_master_params())):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=1e-7)


def test_checkpoint_lr_scheduler(tmp_path):
    cfg = base_config(WORLD)
    cfg["scheduler"] = {"type": "WarmupDecayLR",
                        "params": {"warmup_max_lr": 0.01,
                                   "warmup_num_steps": 4,
                                   "total_num_steps": 100}}
    dataset = SimpleDataset(512, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(cfg)
    run_steps(e1, dataset, 5)
    lr_before = e1.get_lr()[0]
    e1.save_checkpoint(save_dir)

    e2 = make_engine(cfg, seed=7)
    e2.load_checkpoint(save_dir)
    assert e2.lr_scheduler.last_batch_iteration == \
        e1.lr_scheduler.last_batch_iteration
    run_steps(e2, dataset, 1, offset=5)
    assert e2.get_lr()[0] != lr_before  # schedule continued, not restarted


def test_latest_tag(tmp_path):
    cfg = base_config(WORLD)
    dataset = SimpleDataset(128, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    engine = make_engine(cfg)
    run_steps(engine, dataset, 1)
    engine.save_checkpoint(save_dir, tag="mytag")
    assert open(os.path.join(save_dir, "latest")).read().strip() == "mytag"
    engine.save_checkpoint(save_dir)
    assert open(os.path.join(save_dir, "latest")).read().strip() == \
        "global_step1"


def test_load_missing_checkpoint_warns(tmp_path):
    engine = make_engine(base_config(WORLD))
    path, client_state = engine.load_checkpoint(str(tmp_path / "nope"))
    assert path is None and client_state is None


def test_save_without_scheduler_load_with_none(tmp_path):
    cfg = base_config(WORLD)
    dataset = SimpleDataset(128, HIDDEN)
    engine = make_engine(cfg)
    run_steps(engine, dataset, 2)
    save_dir = str(tmp_path / "ckpt")
    engine.save_checkpoint(save_dir)
    e2 = make_engine(cfg, seed=4)
    e2.load_checkpoint(save_dir, load_optimizer_states=False,
                       load_lr_scheduler_states=False)
    assert e2.global_steps == 2


def test_atomic_save_crash_leaves_latest_consistent(tmp_path, monkeypatch):
    """Crash-injection (VERDICT r3 #7): kill the writer partway through a
    later save — after bytes hit the temp file but before the rename —
    and `latest` must still name the LAST COMPLETE checkpoint, with no
    truncated .pt file visible at any checkpoint path."""
    from deepspeed_tpu.runtime import checkpointing as ckpt
    config = dict(base_config(WORLD))
    config["zero_optimization"] = {"stage": 2}
    config["bf16"] = {"enabled": True}
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")

    engine = make_engine(config)
    run_steps(engine, dataset, 1)
    engine.save_checkpoint(save_dir, tag="good")
    assert ckpt.read_latest(save_dir) == "good"

    real_dump = ckpt.pickle.dump
    calls = {"n": 0}

    def dying_dump(obj, f, protocol=None):
        real_dump(obj, f, protocol=protocol)  # bytes land in the tmp file
        calls["n"] += 1
        raise RuntimeError("injected crash mid-save")

    monkeypatch.setattr(ckpt.pickle, "dump", dying_dump)
    run_steps(engine, dataset, 1, offset=1)
    with pytest.raises(RuntimeError, match="injected crash"):
        engine.save_checkpoint(save_dir, tag="torn")
    monkeypatch.setattr(ckpt.pickle, "dump", real_dump)
    assert calls["n"] == 1

    # latest still names the complete checkpoint; the torn tag has no
    # visible .pt files (only a .tmp remnant at most)
    assert ckpt.read_latest(save_dir) == "good"
    torn_dir = os.path.join(save_dir, "torn")
    if os.path.isdir(torn_dir):
        assert not [p for p in os.listdir(torn_dir)
                    if p.endswith(".pt")], os.listdir(torn_dir)

    # and the checkpoint latest names actually loads
    e2 = make_engine(config, seed=7)
    path, _ = e2.load_checkpoint(save_dir)
    assert path is not None and "good" in path


def test_async_save_round_trips(tmp_path):
    """async_save=True: writes land on the background thread, drain on
    the next load, and resume exactly like a synchronous save."""
    config = dict(base_config(WORLD))
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")

    e1 = make_engine(config)
    run_steps(e1, dataset, 2)
    e1.save_checkpoint(save_dir, tag="async", async_save=True)
    assert e1._ckpt_futures, "async save should leave in-flight futures"
    trained_more = run_steps(e1, dataset, 2, offset=2)
    e1._drain_ckpt_writes()

    e2 = make_engine(config, seed=3)
    path, _ = e2.load_checkpoint(save_dir)
    assert path is not None
    resumed = run_steps(e2, dataset, 2, offset=2)
    np.testing.assert_allclose(np.array(resumed), np.array(trained_more),
                               rtol=2e-4, atol=1e-5)
