"""Monitor (tensorboard/JSONL scalars) + pipeline per-layer checkpoint
tests (reference: engine TensorBoard writes :1110-1124; pipe/module.py
per-layer files :536-546)."""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.model import Model
from deepspeed_tpu.utils.monitor import SummaryMonitor


def test_monitor_writes_jsonl(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "job")
    mon.add_scalar("Train/Samples/train_loss", 1.5, 16)
    mon.add_scalar("Train/Samples/lr", 0.01, 16)
    mon.close()
    lines = [json.loads(l) for l in
             open(tmp_path / "job" / "events.jsonl").readlines()]
    assert len(lines) == 2
    assert lines[0]["tag"] == "Train/Samples/train_loss"
    assert lines[0]["value"] == 1.5 and lines[0]["step"] == 16


def test_monitor_disabled_noop(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "job", enabled=False)
    mon.add_scalar("x", 1.0, 0)
    mon.close()
    assert not os.path.exists(tmp_path / "job" / "events.jsonl")


def test_engine_writes_monitor_scalars(tmp_path):
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "tensorboard": {"enabled": True, "output_path": str(tmp_path),
                        "job_name": "run1"},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((4, 2))}),
        config_params=config)
    x, y = jnp.ones((8, 4)), jnp.ones((8, 2))
    for _ in range(3):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    events = [json.loads(l) for l in
              open(tmp_path / "run1" / "events.jsonl").readlines()]
    tags = {e["tag"] for e in events}
    assert {"Train/Samples/lr", "Train/Samples/train_loss",
            "Train/Samples/loss_scale"} <= tags
    losses = [e for e in events if e["tag"] == "Train/Samples/train_loss"]
    assert len(losses) == 3
    assert losses[0]["step"] == 8 and losses[-1]["step"] == 24


@pytest.mark.slow
def test_pipeline_per_layer_files_and_repartition(tmp_path):
    from deepspeed_tpu.models import gpt2_pipe, gpt2
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=32, n_layers=4,
                          n_heads=2, d_model=32, use_flash_attention=False,
                          remat=False)
    ds = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }

    net2 = gpt2_pipe.make_gpt2_pipeline(config=cfg, num_stages=2, num_dp=4,
                                        num_mp=1)
    e2, _, _, _ = deepspeed_tpu.initialize(model=net2, config_params=ds)
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 128, size=(2, 8, 32)).astype(np.int32)
    loss_before = float(e2.train_batch(batch=(ids, ids.copy())))
    e2.save_checkpoint(str(tmp_path))

    tag = "global_step1"
    # per-layer files exist (reference naming)
    for i in range(4):
        assert os.path.isfile(os.path.join(
            str(tmp_path), tag,
            "layer_{:02d}-model_00-model_states.pt".format(i))), i

    # reload into a 4-stage engine: body reshapes (2,2,...) -> (4,1,...)
    net4 = gpt2_pipe.make_gpt2_pipeline(config=cfg, num_stages=4, num_dp=2,
                                        num_mp=1)
    e4, _, _, _ = deepspeed_tpu.initialize(model=net4, config_params=ds)
    path, _ = e4.load_checkpoint(str(tmp_path))
    assert path is not None
    l2 = float(e2.eval_batch(batch=(ids, ids.copy())))
    l4 = float(e4.eval_batch(batch=(ids, ids.copy())))
    np.testing.assert_allclose(l4, l2, rtol=1e-4)
