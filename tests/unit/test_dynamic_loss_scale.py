"""Dynamic loss scale schedule tests (reference
tests/unit/test_dynamic_loss_scale.py: fault-free raising, overflow
halving, hysteresis, min scale)."""
import numpy as np

import jax.numpy as jnp

from deepspeed_tpu.runtime.fp16 import loss_scaler as ls


def _scaler(**kw):
    return ls.create_loss_scaler(static_loss_scale=None, **kw)


def test_no_overflow_raises_every_window():
    state = _scaler(init_scale=2 ** 8, scale_window=4)
    scales = []
    for _ in range(12):
        state = ls.update_scale(state, jnp.asarray(False))
        scales.append(float(state.cur_scale))
    # x2 at every 4th clean step
    assert scales[3] == 2 ** 9
    assert scales[7] == 2 ** 10
    assert scales[11] == 2 ** 11


def test_overflow_halves_immediately():
    state = _scaler(init_scale=2 ** 8, scale_window=100)
    state = ls.update_scale(state, jnp.asarray(True))
    assert float(state.cur_scale) == 2 ** 7
    state = ls.update_scale(state, jnp.asarray(True))
    assert float(state.cur_scale) == 2 ** 6


def test_window_resets_after_overflow():
    state = _scaler(init_scale=2 ** 8, scale_window=4)
    for _ in range(2):
        state = ls.update_scale(state, jnp.asarray(False))
    state = ls.update_scale(state, jnp.asarray(True))   # halve, reset window
    assert float(state.cur_scale) == 2 ** 7
    for _ in range(3):
        state = ls.update_scale(state, jnp.asarray(False))
    # only 3 clean steps since overflow: no growth yet
    assert float(state.cur_scale) == 2 ** 7
    state = ls.update_scale(state, jnp.asarray(False))
    assert float(state.cur_scale) == 2 ** 8


def test_min_scale_floor():
    state = _scaler(init_scale=4, min_scale=1.0)
    for _ in range(6):
        state = ls.update_scale(state, jnp.asarray(True))
    assert float(state.cur_scale) == 1.0


def test_delayed_shift_hysteresis():
    state = _scaler(init_scale=2 ** 8, delayed_shift=2)
    # first overflow consumes hysteresis, scale unchanged
    state = ls.update_scale(state, jnp.asarray(True))
    assert float(state.cur_scale) == 2 ** 8
    assert int(state.cur_hysteresis) == 1
    # second overflow drops the scale
    state = ls.update_scale(state, jnp.asarray(True))
    assert float(state.cur_scale) == 2 ** 7


def test_repeated_overflow_clamps_at_min_scale_with_hysteresis():
    """The hysteresis floor: under a storm of overflows the scale must
    clamp at ``min_scale`` (never underflow toward 0) and the hysteresis
    counter must never be driven below 1."""
    state = _scaler(init_scale=2 ** 3, min_scale=1.0, delayed_shift=2,
                    scale_window=100)
    scales = []
    for _ in range(10):
        state = ls.update_scale(state, jnp.asarray(True))
        scales.append(float(state.cur_scale))
        assert float(state.cur_scale) >= 1.0
        assert int(state.cur_hysteresis) >= 1
    assert scales[0] == 2 ** 3   # first overflow absorbed by hysteresis
    assert scales[-1] == 1.0     # clamped at the floor, not 0


def test_scale_never_underflows_to_zero():
    """Even with a tiny min_scale and hundreds of consecutive overflows
    the scale stays strictly positive (a zero scale would silently zero
    every gradient)."""
    state = _scaler(init_scale=2 ** 16, min_scale=2.0 ** -24,
                    delayed_shift=1, scale_window=1000)
    for _ in range(200):
        state = ls.update_scale(state, jnp.asarray(True))
        assert float(state.cur_scale) > 0.0
    assert float(state.cur_scale) == 2.0 ** -24


def test_hysteresis_window_restarts_after_min_scale_clamp():
    """After clamping at the floor, a clean ``scale_window`` must both
    regrow the scale and REFILL the hysteresis budget, so the next
    overflow is absorbed again instead of instantly re-dropping."""
    state = _scaler(init_scale=4, min_scale=1.0, delayed_shift=3,
                    scale_window=2)
    for _ in range(8):
        state = ls.update_scale(state, jnp.asarray(True))
    assert float(state.cur_scale) == 1.0
    state = ls.update_scale(state, jnp.asarray(False))
    assert float(state.cur_scale) == 1.0      # window not yet elapsed
    state = ls.update_scale(state, jnp.asarray(False))
    assert float(state.cur_scale) == 2.0      # regrown...
    assert int(state.cur_hysteresis) == 3     # ...and hysteresis refilled
    state = ls.update_scale(state, jnp.asarray(True))
    assert float(state.cur_scale) == 2.0      # absorbed by fresh budget
    assert int(state.cur_hysteresis) == 2


def test_static_scale_never_moves():
    state = ls.create_loss_scaler(static_loss_scale=128.0)
    for flag in (True, False, True):
        state = ls.update_scale(state, jnp.asarray(flag))
    assert float(state.cur_scale) == 128.0


def test_backward_scale():
    state = ls.create_loss_scaler(static_loss_scale=64.0)
    scaled = ls.backward_scale(jnp.asarray(2.0), state)
    assert float(scaled) == 128.0
