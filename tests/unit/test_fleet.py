"""Fleet observatory tests (ISSUE 14; docs/fleet.md): the metrics /
export plane, the multi-host merger under TORN inputs (mid-line crash,
missing manifest, skewed clock), straggler/ICI attribution, the
scoreboard, and the schema/constant pins that keep the stdlib-only
fleet package honest against the jax-side modules it mirrors.

Marker: ``fleet`` (tier-1 — fast, CPU-only, no engine builds except
the two collector-integration tests which build bare collectors)."""
import importlib.util
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from deepspeed_tpu.telemetry import collector as collector_mod
from deepspeed_tpu.telemetry import record as record_mod
from deepspeed_tpu.telemetry.collector import TelemetryCollector
from deepspeed_tpu.telemetry.config import DeepSpeedTelemetryConfig
from deepspeed_tpu.telemetry.fleet import aggregate, export, metrics, \
    straggler
from deepspeed_tpu.telemetry.fleet.aggregate import (
    estimate_offsets, load_host, merge_chrome_traces, merge_records,
    merge_run, read_jsonl_tolerant, validate_fleet_record,
    validate_host_manifest, write_host_manifest)
from deepspeed_tpu.telemetry.fleet.metrics import (
    Metric, MetricsRegistry, MetricsSink, parse_prometheus_text)
from deepspeed_tpu.telemetry.fleet.straggler import (
    StragglerDetector, detect_stragglers, ici_health_from_record)
from deepspeed_tpu.telemetry.watchdog import Watchdog

pytestmark = pytest.mark.fleet

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_bin(name):
    path = os.path.join(_REPO, "bin", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------- helpers
def _train_rec(step=0, wall=None, step_time_s=0.01, loss=2.0,
               per_kind=None, comm_overlap=None, overflow=False,
               hbm=None):
    """A schema-valid train StepRecord (validate_step_record == [])."""
    rec = {
        "kind": "train_step", "step": step,
        "wall": time.time() if wall is None else wall,
        "step_time_s": step_time_s, "loss": loss, "grad_norm": 1.0,
        "loss_scale": 1.0, "overflow": overflow, "skipped_steps": 0,
        "micro_steps": 1, "tokens_per_step": 256,
        "tokens_per_sec_per_chip": 256.0 / max(step_time_s, 1e-9),
        "model_flops_per_step": 1e9, "mfu": 0.4,
        "peak_flops_per_chip": 1e12, "device": "cpu", "n_devices": 1,
        "phases": {"fwd": step_time_s / 2, "bwd": step_time_s / 2},
        "phase_total_s": step_time_s,
        "hbm": hbm or {"available": False, "bytes_in_use": None,
                       "peak_bytes_in_use": None},
        "wire": None, "comm_overlap": comm_overlap, "offload": None,
        "pipe": None,
    }
    if per_kind is not None:
        rec["offload"] = {"plan_segments": sum(1 for _ in per_kind),
                          "per_kind": per_kind,
                          "overlap_efficiency": 0.5}
    return rec


def _serving_rec(step=0):
    return {
        "kind": "serving_step", "step": step, "wall": time.time(),
        "slot_occupancy": 0.5, "queue_depth": 2, "active_slots": 2,
        "prefill_tokens": 100 + step, "prefill_tokens_per_sec": 50.0,
        "decode_tokens": 10 + step, "decode_steps": step + 1,
        "decode_tokens_per_sec": 20.0,
        "ttft": {"count": 1, "mean_s": 0.1, "p50_s": 0.1, "p95_s": 0.2},
        "tpot": {"count": 1, "mean_s": 0.01, "p50_s": 0.01,
                 "p95_s": 0.02},
        "page_pool": None, "prefix": None, "speculative": None,
    }


def _write_host(root, name, steps, step_time=0.01, skew=0.0,
                manifest=True, torn=False, per_kind=None,
                straggle_from=None, straggle_time=None):
    """Write one synthetic host directory: manifest + telemetry.jsonl
    of schema-valid train records with controlled walls."""
    d = os.path.join(str(root), name)
    os.makedirs(d, exist_ok=True)
    if manifest:
        write_host_manifest(d, job_name=name)
    lines = []
    base = 1000.0 + skew
    wall = base
    for step in range(steps):
        st = step_time
        if straggle_from is not None and step >= straggle_from:
            st = straggle_time
        wall += st
        rec = _train_rec(step=step, wall=wall, step_time_s=st,
                         per_kind=per_kind)
        assert record_mod.validate_step_record(rec) == [], rec
        lines.append(json.dumps(rec))
    body = "\n".join(lines) + "\n"
    if torn:
        body = body[:-len(lines[-1]) // 2 - 1]    # last line cut mid-JSON
    with open(os.path.join(d, aggregate.JSONL_NAME), "w") as fh:
        fh.write(body)
    return d


def _tc(tmp_path, **extra):
    return DeepSpeedTelemetryConfig({"telemetry": dict(
        {"enabled": True, "output_path": str(tmp_path)}, **extra)})


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


# ------------------------------------------------------------------- pins
def test_fleet_constants_pinned_to_jax_side_modules():
    """The stdlib-only fleet package duplicates a handful of constants
    from the jax-importing telemetry modules; they must stay equal."""
    assert metrics.KIND_TRAIN == record_mod.KIND_TRAIN
    assert metrics.KIND_SERVING == record_mod.KIND_SERVING
    assert aggregate.JSONL_NAME == collector_mod.JSONL_NAME
    assert aggregate.SPANS_JSONL_NAME == collector_mod.SPANS_JSONL_NAME
    assert aggregate.CHROME_TRACE_NAME == collector_mod.CHROME_TRACE_NAME
    assert straggler.STRAGGLER_DEFAULTS == \
        __import__("deepspeed_tpu.telemetry.watchdog",
                   fromlist=["STRAGGLER_DEFAULTS"]).STRAGGLER_DEFAULTS


def test_scoreboard_row_keys_pinned_to_checker():
    scoreboard = _load_bin("ds_scoreboard")
    checker = _load_bin("check_bench_schema")
    assert tuple(scoreboard.SCOREBOARD_ROW_KEYS) == \
        tuple(checker.SCOREBOARD_ROW_KEYS)


def test_fleet_clis_run_without_jax(tmp_path):
    """bin/ds_fleet.py must doctor a run directory on a box without
    jax: run it in a subprocess where importing jax raises."""
    import subprocess
    import sys
    _write_host(tmp_path, "host0", steps=3)
    _write_host(tmp_path, "host1", steps=3)
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        "raise ImportError('no jax on this box (test_fleet)')\n")
    env = dict(os.environ, PYTHONPATH=str(poison))
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bin", "ds_fleet.py"),
         str(tmp_path)], capture_output=True, text=True, env=env)
    assert out.returncode == 0, out.stderr
    assert "fleet report: 2 host(s), 3 merged step(s)" in out.stdout


# ------------------------------------------------------ metric primitives
def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry(namespace="t")
    c = reg.counter("reqs_total")
    c.inc()
    c.inc(2.5, route="a")
    assert c.value() == 1.0 and c.value(route="a") == 2.5
    g = reg.gauge("depth")
    g.set(3)
    g.set(7)
    assert g.value() == 7.0
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    state = h.value()
    assert state["count"] == 3 and state["sum"] == pytest.approx(5.55)
    assert state["buckets"] == [1, 2]        # le=0.1 -> 1, le=1.0 -> 2


def test_counter_set_to_is_monotone():
    reg = MetricsRegistry()
    c = reg.counter("tokens_total")
    c.set_to(100)
    c.set_to(40)             # a lower cumulative source value is kept
    assert c.value() == 100.0
    c.set_to(150)
    assert c.value() == 150.0


def test_metric_validation():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="invalid metric name"):
        Metric("bad-name", "gauge")
    with pytest.raises(ValueError, match="kind"):
        Metric("ok_name", "summary")
    reg.counter("dual")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("dual")
    with pytest.raises(ValueError, match="namespace"):
        MetricsRegistry(namespace="bad ns")


def test_render_parse_roundtrip():
    reg = MetricsRegistry(namespace="ds",
                          const_labels={"job": "t", "host": "h1"})
    reg.counter("steps_total", "steps").inc(3)
    reg.gauge("mfu").set(0.42)
    g = reg.gauge("wire_bytes")
    g.set(10, **{"class": "allgather"})
    g.set(20, **{"class": 'wei"rd\\cls'})     # label escaping
    h = reg.histogram("step_seconds", buckets=(0.5, 2.0))
    h.observe(0.1)
    h.observe(1.0)
    text = reg.render_text()
    families, problems = parse_prometheus_text(text)
    assert problems == []
    assert set(families) == {"ds_steps_total", "ds_mfu",
                             "ds_wire_bytes", "ds_step_seconds"}
    flat = {(name, labels.get("class"), labels.get("le")): val
            for name, labels, val
            in families["ds_wire_bytes"]["samples"]}
    assert flat[("ds_wire_bytes", "allgather", None)] == 10.0
    assert flat[("ds_wire_bytes", 'wei"rd\\cls', None)] == 20.0
    hist = families["ds_step_seconds"]["samples"]
    by_le = {labels["le"]: val for name, labels, val in hist
             if name.endswith("_bucket")}
    assert by_le["0.5"] == 1 and by_le["2.0"] == 2
    assert by_le["+Inf"] == 2                 # +Inf bucket == count
    # const labels ride every sample
    for fam in families.values():
        for _, labels, _ in fam["samples"]:
            assert labels["job"] == "t" and labels["host"] == "h1"


def test_parse_prometheus_text_flags_problems():
    families, problems = parse_prometheus_text(
        "# TYPE ds_x gauge\nds_x 1.0\nds_orphan 2\nds_x nan_ish_X\n")
    assert len(problems) == 2
    assert any("no preceding TYPE" in p for p in problems)
    assert any("non-numeric" in p for p in problems)
    assert families["ds_x"]["samples"][0][2] == 1.0


# ------------------------------------------------------------ MetricsSink
def test_sink_folds_train_record_into_families():
    reg = MetricsRegistry()
    sink = MetricsSink(reg, nominal_bytes_per_s=1e9)
    per_kind = {"host": {"run_s": 0.004, "wait_s": 0.0},
                "transfer": {"run_s": 0.001, "wait_s": 0.002}}
    co = {"allgather": {"bytes": 4_000_000, "fused": False,
                        "est_collective_s": 1e-3, "exposed_s": 2e-3,
                        "overlap_efficiency": 0.5}}
    sink.emit(_train_rec(step=0, per_kind=per_kind, comm_overlap=co))
    sink.emit(_train_rec(step=1, per_kind=per_kind, comm_overlap=co,
                         overflow=True))
    assert sink._train_steps.value() == 2.0
    assert sink._overflow.value() == 1.0
    assert sink._mfu.value() == 0.4
    assert sink._phase.value(phase="fwd") == pytest.approx(0.01)
    assert sink._seg_wait.value(kind="transfer") == pytest.approx(0.004)
    assert sink._seg_eff.value() == 0.5
    # ici_health: 4 MB over the 2 ms measured transfer wait = 2e9 B/s
    # against the 1e9 nominal -> 2.0
    assert sink._ici.value(**{"class": "allgather"}) == \
        pytest.approx(2.0, rel=1e-3)
    st = sink._step_time.value()
    assert st["count"] == 2


def test_sink_ici_health_unset_without_measured_waits():
    """micro/fused records (no offload per_kind walls) must leave the
    ici_health gauge honestly unset, never report the analytic 1.0."""
    reg = MetricsRegistry()
    sink = MetricsSink(reg, nominal_bytes_per_s=1e9)
    co = {"allgather": {"bytes": 1000, "fused": False,
                        "est_collective_s": 1e-4, "exposed_s": 1e-4,
                        "overlap_efficiency": 0.0}}
    sink.emit(_train_rec(step=0, comm_overlap=co))
    assert sink._ici.value(**{"class": "allgather"}) is None
    health = ici_health_from_record(
        _train_rec(comm_overlap=co), nominal_bytes_per_s=1e9)
    assert health == {"allgather": None}
    assert ici_health_from_record(_train_rec()) == {}


def test_sink_folds_serving_record_and_watchdog_trips():
    wd = Watchdog({"ttft_slo": {"slo_s": 0.05, "every": 1,
                                "action": "warn"},
                   "straggler": dict(straggler.STRAGGLER_DEFAULTS)})
    reg = MetricsRegistry()
    sink = MetricsSink(reg, watchdog=wd)
    wd.observe_ttft(0.01)
    wd.observe_ttft(0.2)                      # violation -> trip
    sink.emit(_serving_rec(step=0))
    assert sink._serving_steps.value() == 1.0
    assert sink._prefill_tokens.value() == 100.0
    assert sink._ttft_p95.value() == 0.2
    assert sink._slo_burn.value() == pytest.approx(0.5)
    assert sink._trips.value(watchdog="ttft_slo") == 1.0
    assert wd.ttft_burn_rate() == pytest.approx(0.5)


# --------------------------------------------------------------- straggler
def test_ici_health_from_record_hand_computed():
    per_kind = {"collective": {"run_s": 0.0, "wait_s": 0.001},
                "transfer": {"run_s": 0.0, "wait_s": 0.003}}
    co = {"allgather": {"bytes": 3_000_000, "fused": True,
                        "est_collective_s": 0.0, "exposed_s": 0.0,
                        "overlap_efficiency": 1.0},
          "reduce": {"bytes": 1_000_000, "fused": False,
                     "est_collective_s": 0.0, "exposed_s": 0.0,
                     "overlap_efficiency": 1.0}}
    health = ici_health_from_record(
        _train_rec(per_kind=per_kind, comm_overlap=co),
        nominal_bytes_per_s=1e9)
    # total wait 4 ms apportioned by byte share: allgather gets 3 ms,
    # reduce 1 ms -> both achieve 1e9 B/s == nominal -> health 1.0
    assert health["allgather"] == pytest.approx(1.0)
    assert health["reduce"] == pytest.approx(1.0)


def _fleet_steps(walls_by_host, per_kind_by_host=None, ici_by_host=None):
    """Build merged fleet_step records from {host: [step walls...]}."""
    n = len(next(iter(walls_by_host.values())))
    out = []
    for step in range(n):
        hosts = {}
        for name, walls in walls_by_host.items():
            hosts[name] = {
                "wall": 1000.0 + step, "wall_corrected": 1000.0 + step,
                "offset_s": 0.0, "step_time_s": walls[step],
                "loss": 2.0, "mfu": 0.4, "phases": {},
                "per_kind": (per_kind_by_host or {}).get(name),
                "hbm_peak": None,
                "ici_health": (ici_by_host or {}).get(name),
            }
        out.append({"kind": "fleet_step", "step": step,
                    "n_hosts": len(hosts), "wall": 1000.0 + step,
                    "hosts": hosts, "step_time": None,
                    "missing_hosts": []})
    return out


def test_straggler_flags_after_k_consecutive_steps_only():
    clean = [0.010, 0.011, 0.009, 0.010, 0.010, 0.011]
    spike = [0.010, 0.050, 0.009, 0.010, 0.010, 0.011]  # one-off spike
    slow = [0.010, 0.030, 0.031, 0.032, 0.030, 0.031]   # sick from 1
    report = detect_stragglers(_fleet_steps(
        {"h0": clean, "h1": clean, "h2": spike, "h3": slow}), k=3)
    assert report["flagged_hosts"] == ["h3"]
    flag = report["flags"][0]
    # step 1's median is inflated by the spike host (4 hosts, upper
    # median), so h3's streak honestly starts at step 2
    assert flag["metric"] == "step_wall" and flag["first_step"] == 2
    assert flag["steps"] == 4 and flag["last_step"] == 5
    assert flag["worst_ratio"] == pytest.approx(0.031 / 0.009, rel=0.01)


def test_straggler_streak_broken_by_clean_step():
    slow = [0.030, 0.031, 0.010, 0.030, 0.031]    # never 3 consecutive
    clean = [0.010] * 5
    report = detect_stragglers(_fleet_steps(
        {"h0": clean, "h1": clean, "h2": slow}), k=3)
    assert report["flags"] == []


def test_straggler_flagged_in_two_host_fleet():
    """Even-count medians average the middle pair: with the naive
    upper-middle pick a 2-host fleet's slow host would be its own
    median and never flag (regression)."""
    report = detect_stragglers(
        _fleet_steps({"h0": [0.010] * 4, "h1": [0.035] * 4}), k=3)
    assert report["flagged_hosts"] == ["h1"]
    assert straggler.true_median([1.0, 3.0]) == 2.0
    assert straggler.true_median([1.0, 2.0, 4.0]) == 2.0


def test_straggler_min_hosts_gate():
    report = detect_stragglers(
        _fleet_steps({"h0": [0.01] * 4, "h1": [0.05] * 4}),
        k=2, min_hosts=3)
    assert report["flags"] == []


def test_straggler_per_kind_segment_walls_and_min_wall_floor():
    slow_pk = {"host": {"run_s": 0.030, "wait_s": 0.0},
               "transfer": {"run_s": 50e-6, "wait_s": 0.0}}
    ok_pk = {"host": {"run_s": 0.010, "wait_s": 0.0},
             "transfer": {"run_s": 20e-6, "wait_s": 0.0}}
    # equal step walls: only the per-kind channel can flag; the sub-ms
    # transfer walls (2.5x over median!) are jitter, not signal
    report = detect_stragglers(_fleet_steps(
        {"h0": [0.03] * 4, "h1": [0.03] * 4, "h2": [0.03] * 4},
        per_kind_by_host={"h0": ok_pk, "h1": ok_pk, "h2": slow_pk}), k=3)
    assert [f["metric"] for f in report["flags"]] == ["segment:host"]
    assert report["flagged_hosts"] == ["h2"]


def test_straggler_null_run_s_degrades_not_crashes():
    """A degraded/adopted record (crash-bundle ring, _jsonable
    fallback) can carry ``per_kind: {..., run_s: null}`` — the detector
    must read it as 0, never TypeError on exactly the post-mortem
    inputs the merger promises to tolerate (regression)."""
    null_pk = {"host": {"run_s": None, "wait_s": None}}
    ok_pk = {"host": {"run_s": 0.010, "wait_s": 0.0}}
    report = detect_stragglers(_fleet_steps(
        {"h0": [0.01] * 4, "h1": [0.01] * 4, "h2": [0.01] * 4},
        per_kind_by_host={"h0": ok_pk, "h1": ok_pk, "h2": null_pk}), k=3)
    assert report["flagged_hosts"] == []


def test_describe_flag_ratio_wording():
    """Wall ratios are fleet-median deviations; ici:<class> ratios are
    INVERTED achieved/nominal bandwidth — the trip/log wording must not
    claim median semantics for a bandwidth number."""
    assert "over the fleet median" in straggler.describe_flag_ratio(
        "step_wall", 2.5)
    ici = straggler.describe_flag_ratio("ici:allgather", 4.0)
    assert "25%" in ici and "median" not in ici


def test_ici_degraded_link_flagged():
    ok = {"allgather": 1.0}
    bad = {"allgather": 0.3}       # below 1/factor = 1/1.5
    report = detect_stragglers(_fleet_steps(
        {"h0": [0.01] * 4, "h1": [0.01] * 4, "h2": [0.01] * 4},
        ici_by_host={"h0": ok, "h1": ok, "h2": bad}), k=3)
    assert [f["metric"] for f in report["flags"]] == ["ici:allgather"]
    assert report["flagged_hosts"] == ["h2"]


def test_straggler_flag_tracks_live_streak():
    det = StragglerDetector(k=2)
    for rec in _fleet_steps({"h0": [0.01] * 5, "h1": [0.01] * 5,
                             "h2": [0.03, 0.03, 0.04, 0.05, 0.05]}):
        det.observe(rec)
    assert len(det.flags) == 1                # ONE flag for the streak
    assert det.flags[0]["steps"] == 5
    assert det.flags[0]["worst_ratio"] == pytest.approx(5.0, rel=0.05)
    assert det.flags[0]["last_step"] == 4


# --------------------------------------------------------------- aggregate
def test_manifest_roundtrip_and_validation(tmp_path):
    path = write_host_manifest(str(tmp_path), job_name="train",
                               metrics_port=9400, process_index=3,
                               process_count=8)
    with open(path) as fh:
        manifest = json.load(fh)
    assert validate_host_manifest(manifest) == []
    assert manifest["process_index"] == 3
    assert manifest["files"]["telemetry"] == aggregate.JSONL_NAME
    bad = dict(manifest)
    bad.pop("pid")
    assert validate_host_manifest(bad) == ["missing key 'pid'"]
    assert validate_host_manifest({"kind": "nope"}) \
        == ["unknown manifest kind 'nope'"]


def test_read_jsonl_tolerant_torn_tail_vs_interior_corruption(tmp_path):
    p = tmp_path / "t.jsonl"
    p.write_text('{"a": 1}\n{"bro\n{"b": 2}\n{"torn": tr')
    records, problems = read_jsonl_tolerant(str(p))
    assert records == [{"a": 1}, {"b": 2}]
    assert len(problems) == 2
    assert any("corrupt line at t.jsonl:2" in x for x in problems)
    assert any("torn tail" in x and "t.jsonl:4" in x for x in problems)


def test_load_host_missing_manifest_flags_gap(tmp_path):
    d = _write_host(tmp_path, "h0", steps=3, manifest=False)
    host = load_host(d)
    assert host.manifest is None
    assert "missing host manifest" in host.gaps
    assert len(host.records) == 3             # steps stay merged


def test_load_host_adopts_crash_bundle_records(tmp_path):
    d = _write_host(tmp_path, "h0", steps=2, torn=True)
    crash = os.path.join(d, "crash")
    os.makedirs(crash)
    lost = _train_rec(step=1, wall=1000.03)
    with open(os.path.join(crash, "bundle_000.json"), "w") as fh:
        json.dump({"reason": "watchdog:step_deadline",
                   "records": [lost]}, fh)
    host = load_host(d)
    assert host.crashed and host.crash_reason == "watchdog:step_deadline"
    assert [r["step"] for r in host.records] == [0, 1]
    assert any("torn tail" in g for g in host.gaps)
    assert any("adopted from the crash bundle" in g for g in host.gaps)


def test_estimate_offsets_recovers_deliberate_skew(tmp_path):
    _write_host(tmp_path, "h0", steps=8)
    _write_host(tmp_path, "h1", steps=8, skew=5.0)
    hosts = [load_host(os.path.join(str(tmp_path), n))
             for n in ("h0", "h1")]
    offsets = estimate_offsets(hosts)
    assert offsets["h0"] == 0.0
    assert offsets["h1"] == pytest.approx(5.0, abs=0.01)
    merged = merge_records(hosts, offsets)
    for rec in merged:
        slots = rec["hosts"]
        assert abs(slots["h1"]["wall_corrected"]
                   - slots["h0"]["wall_corrected"]) < 0.05


def test_merge_records_flags_missing_host_steps(tmp_path):
    _write_host(tmp_path, "h0", steps=5)
    _write_host(tmp_path, "h1", steps=3)      # stream stops early
    hosts = [load_host(os.path.join(str(tmp_path), n))
             for n in ("h0", "h1")]
    merged = merge_records(hosts)
    assert len(merged) == 5
    for rec in merged:
        assert validate_fleet_record(rec) == [], rec
    assert merged[2]["missing_hosts"] == []
    assert merged[3]["missing_hosts"] == ["h1"]
    assert merged[3]["n_hosts"] == 1
    assert merged[0]["step_time"]["max_host"] in ("h0", "h1")


def test_validate_fleet_record_rejects_bad_shapes():
    assert validate_fleet_record([]) == ["record is not a dict"]
    assert validate_fleet_record({"kind": "nope"}) \
        == ["unknown record kind 'nope'"]
    good = _fleet_steps({"h0": [0.01]})[0]
    assert validate_fleet_record(good) == []
    extra = dict(good, surprise=1)
    assert any("unexpected key" in p
               for p in validate_fleet_record(extra))
    bad_host = dict(good, hosts={"h0": {"wall": "late"}})
    assert any("missing" in p for p in validate_fleet_record(bad_host))


def test_merge_run_end_to_end_torn_missing_skewed(tmp_path):
    """The satellite contract: torn JSONL + missing manifest + skewed
    clock in one run — merged output schema-valid, every gap flagged,
    no host silently dropped."""
    _write_host(tmp_path, "h0", steps=6)
    _write_host(tmp_path, "h1", steps=6, torn=True)
    _write_host(tmp_path, "h2", steps=6, manifest=False)
    _write_host(tmp_path, "h3", steps=6, skew=3600.0)
    report = merge_run(str(tmp_path))
    assert report["kind"] == "fleet_report"
    assert report["n_hosts"] == 4
    for rec in report["records"]:
        assert validate_fleet_record(rec) == [], rec
    assert len(report["records"]) == 6
    gaps = "\n".join(report["gaps"])
    assert "h1: torn tail" in gaps
    assert "h2: missing host manifest" in gaps
    assert report["offsets"]["h3"] == pytest.approx(3600.0, abs=0.01)
    # the torn host lost ONLY its final step; steps 0..4 stay merged
    by_host = {h["name"]: h for h in report["hosts"]}
    assert by_host["h1"]["steps"] == 5
    assert report["records"][-1]["missing_hosts"] == ["h1"]
    # equal per-step sleeps, no straggler: zero false positives
    assert report["straggler"]["flags"] == []


def test_merge_chrome_traces_lanes_and_offsets(tmp_path):
    d0 = _write_host(tmp_path, "h0", steps=2)
    d1 = _write_host(tmp_path, "h1", steps=2, skew=2.0)
    ev = {"name": "train_step", "ph": "X", "ts": 1000.0, "dur": 5.0,
          "pid": 777, "tid": 1}
    with open(os.path.join(d0, aggregate.CHROME_TRACE_NAME), "w") as fh:
        json.dump([ev], fh)
    with open(os.path.join(d1, aggregate.CHROME_TRACE_NAME), "w") as fh:
        # the live/crashed lenient form: unclosed array
        fh.write('[{"name": "train_step", "ph": "X", "ts": 2001000.0, '
                 '"dur": 5.0, "pid": 888, "tid": 1},')
    hosts = [load_host(d) for d in (d0, d1)]
    out = os.path.join(str(tmp_path), "merged.json")
    path, events, merged_hosts = merge_chrome_traces(
        hosts, estimate_offsets(hosts), out)
    assert merged_hosts == 2
    with open(path) as fh:
        merged = json.load(fh)                # strict JSON: loadable
    assert len(merged) == events == 4         # 2 metadata + 2 events
    lanes = {e["pid"] for e in merged}
    assert lanes == {0, 1}                    # host-index lanes, not 777
    names = {e["args"]["name"] for e in merged if e["ph"] == "M"}
    assert names == {"h0", "h1"}
    ts = {e["pid"]: e["ts"] for e in merged if e["ph"] == "X"}
    # h1's 2 s clock skew corrected away (both events ~1000 us apart
    # of each other instead of 2e6 us)
    assert abs(ts[1] - ts[0]) < 2e6


# -------------------------------------------------- export + collector
def test_exporter_serves_metrics_and_healthz(tmp_path):
    reg = MetricsRegistry(namespace="ds")
    reg.gauge("mfu").set(0.5)
    state = {"status": "ok"}
    exp = export.MetricsExporter(reg, port=0, healthz=lambda: dict(state))
    try:
        code, text = _get("http://127.0.0.1:{}/metrics".format(exp.port))
        assert code == 200
        families, problems = parse_prometheus_text(text)
        assert problems == []
        assert "ds_mfu" in families
        assert "ds_metrics_scrapes_total" in families
        code, body = _get("http://127.0.0.1:{}/healthz".format(exp.port))
        assert code == 200 and json.loads(body)["status"] == "ok"
        state["status"] = "degraded"
        with pytest.raises(urllib.error.HTTPError) as err:
            _get("http://127.0.0.1:{}/healthz".format(exp.port))
        assert err.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as err:
            _get("http://127.0.0.1:{}/nope".format(exp.port))
        assert err.value.code == 404
        assert exp.snapshot()["live"] is True
        assert exp.snapshot()["scrapes"] == 1
    finally:
        exp.close()
        exp.close()                            # idempotent
    assert exp.snapshot()["live"] is False


def test_collector_metrics_off_structurally_absent(tmp_path):
    before = {t.name for t in threading.enumerate()}
    col = TelemetryCollector(_tc(tmp_path), job_name="off")
    try:
        assert col.metrics is None and col.exporter is None
        assert col.fleet is None
        assert "fleet" not in col.snapshot()
        after = {t.name for t in threading.enumerate()} - before
        assert not any(n.startswith("ds-metrics") for n in after)
        # the manifest is written for EVERY live collector (metrics on
        # or off) so any telemetry run is mergeable post-mortem
        manifest = os.path.join(col.output_dir, aggregate.MANIFEST_NAME)
        with open(manifest) as fh:
            payload = json.load(fh)
        assert validate_host_manifest(payload) == []
        assert payload["metrics_port"] is None
    finally:
        col.close()


def test_collector_metrics_on_full_plane(tmp_path):
    col = TelemetryCollector(
        _tc(tmp_path, metrics={"enabled": True, "port": 0},
            watchdog={"straggler": True}),
        job_name="on")
    try:
        col.sinks.emit(_train_rec(step=0))
        port = col.exporter.port
        code, text = _get("http://127.0.0.1:{}/metrics".format(port))
        families, problems = parse_prometheus_text(text)
        assert problems == [] and "ds_train_steps_total" in families
        # const labels carry job + host
        _, labels, val = families["ds_train_steps_total"]["samples"][0]
        assert labels == {"job": "on", "host": socket.gethostname()}
        assert val == 1.0
        code, body = _get("http://127.0.0.1:{}/healthz".format(port))
        hz = json.loads(body)
        assert code == 200 and hz["status"] == "ok" and hz["steps"] == 1
        assert hz["fleet"]["metrics_export"]["port"] == port
        snap = col.snapshot()
        assert snap["fleet"]["metrics_export"]["live"] is True
        scrape = col.metrics_scrape()
        assert scrape["series"] >= 1 and "# TYPE " in scrape["scrape"]
        # manifest advertises the live port
        with open(os.path.join(col.output_dir,
                               aggregate.MANIFEST_NAME)) as fh:
            assert json.load(fh)["metrics_port"] == port
    finally:
        col.close()
    assert col.metrics_scrape()["series"] >= 1   # registry survives close


def test_collector_survives_bound_metrics_port(tmp_path):
    """A fixed port already bound (two engines sharing one ds_config,
    two processes on a host) must not kill engine construction: the
    sink stays live, only the HTTP plane is absent — loudly."""
    first = TelemetryCollector(
        _tc(tmp_path, metrics={"enabled": True, "port": 0}),
        job_name="a")
    try:
        taken = first.exporter.port
        second = TelemetryCollector(
            _tc(tmp_path, metrics={"enabled": True, "port": taken}),
            job_name="b")
        try:
            assert second.exporter is None
            assert second.metrics is not None      # sink still folds
            second.sinks.emit(_train_rec(step=0))
            assert second.metrics_scrape()["series"] >= 1
            assert second.snapshot()["fleet"]["metrics_export"] is None
        finally:
            second.close()
    finally:
        first.close()


def test_merge_run_trace_out_single_load(tmp_path):
    """merge_run(trace_out=) merges the Chrome traces from the hosts
    it already loaded — the report carries the trace sub-dict and an
    unparseable per-host trace lands in the gaps, not on throwaway
    HostViews."""
    d0 = _write_host(tmp_path, "h0", steps=2)
    d1 = _write_host(tmp_path, "h1", steps=2)
    with open(os.path.join(d0, aggregate.CHROME_TRACE_NAME), "w") as fh:
        json.dump([{"name": "s", "ph": "X", "ts": 1.0, "dur": 1.0,
                    "pid": 1, "tid": 1}], fh)
    with open(os.path.join(d1, aggregate.CHROME_TRACE_NAME), "w") as fh:
        fh.write("not json at all {{{")
    out = os.path.join(str(tmp_path), "merged.json")
    report = merge_run(str(tmp_path), trace_out=out)
    assert report["trace"]["hosts_merged"] == 1
    assert report["trace"]["path"] == os.path.abspath(out)
    with open(out) as fh:
        json.load(fh)                             # loadable
    assert any("unparseable trace_events.json" in g
               for g in report["gaps"])
    assert merge_run(str(tmp_path))["trace"] is None


def test_sink_fleet_ici_keys_are_host_qualified():
    """FleetLocalState.ici_health keys are '<host>:<class>' from BOTH
    sources (local sink measurements and ingest_fleet) — one schema."""
    from deepspeed_tpu.telemetry.fleet.metrics import FleetLocalState
    fleet = FleetLocalState()
    sink = MetricsSink(MetricsRegistry(), fleet=fleet,
                       nominal_bytes_per_s=1e9, host="me")
    per_kind = {"transfer": {"run_s": 0.0, "wait_s": 0.002}}
    co = {"allgather": {"bytes": 2_000_000, "fused": False,
                        "est_collective_s": 0.0, "exposed_s": 0.0,
                        "overlap_efficiency": 0.0}}
    sink.emit(_train_rec(per_kind=per_kind, comm_overlap=co))
    assert fleet.ici_health == {"me:allgather": pytest.approx(1.0)}


def test_ingest_fleet_trips_straggler_watchdog_once(tmp_path):
    col = TelemetryCollector(
        _tc(tmp_path, metrics={"enabled": True, "port": 0},
            watchdog={"straggler": True}),
        job_name="ingest")
    flag = {"host": "h3", "metric": "step_wall", "worst_ratio": 3.0,
            "steps": 4, "first_step": 2, "last_step": 5}
    report = {"straggler": {"flags": [flag]},
              "ici_health": {"h3": {"allgather": 0.4}}}
    try:
        col.ingest_fleet(report)
        col.ingest_fleet(report)               # same flag: ONE trip
        trips = [t for t in col.watchdog.trips
                 if t["watchdog"] == "straggler"]
        assert len(trips) == 1
        snap = col.snapshot()["fleet"]
        assert snap["straggler_flags"] == [flag]
        assert snap["ici_health"] == {"h3:allgather": 0.4}
        assert snap["ingests"] == 2
        hz = col.healthz()
        assert hz["status"] == "degraded"
        assert hz["watchdog"]["trips"] == 1
        with pytest.raises(urllib.error.HTTPError) as err:
            _get("http://127.0.0.1:{}/healthz".format(col.exporter.port))
        assert err.value.code == 503
    finally:
        col.close()


# ----------------------------------------------------------------- config
def test_metrics_config_matrix():
    base = {"enabled": True, "output_path": "/tmp/x"}

    def cfg(**over):
        return DeepSpeedTelemetryConfig(
            {"telemetry": dict(base, **over)})

    off = cfg()
    assert off.metrics_enabled is False and off.metrics_port == 0
    on = cfg(metrics={"enabled": True, "port": 9400, "namespace": "acme"})
    assert on.metrics_enabled and on.metrics_port == 9400
    assert on.metrics_namespace == "acme"
    assert cfg(metrics={}).metrics_enabled is True     # presence = on
    assert cfg(metrics={"enabled": False}).metrics_enabled is False
    with pytest.raises(ValueError, match="telemetry.metrics.port"):
        cfg(metrics={"port": -1})
    with pytest.raises(ValueError, match="telemetry.metrics.port"):
        cfg(metrics={"port": True})
    with pytest.raises(ValueError, match="telemetry.metrics.port"):
        cfg(metrics={"port": 70000})
    with pytest.raises(ValueError, match="namespace"):
        cfg(metrics={"namespace": ""})
    # unknown keys warn (the PR 4 policy); raise under telemetry.strict
    assert cfg(metrics={"prots": 1}).metrics_enabled is True
    with pytest.raises(ValueError, match="unknown key"):
        cfg(strict=True, metrics={"prots": 1})
    # straggler watchdog sub-config rides the PR 8 matrix
    wd = cfg(watchdog={"straggler": {"factor": 2.0, "k": 5,
                                     "action": "dump"}}).watchdog
    assert wd["straggler"]["factor"] == 2.0
    assert wd["straggler"]["k"] == 5
    assert cfg(watchdog={"straggler": True}).watchdog["straggler"] \
        == straggler.STRAGGLER_DEFAULTS
    assert cfg(watchdog={"straggler": False}).watchdog["straggler"] \
        is None
    with pytest.raises(ValueError, match="action"):
        cfg(watchdog={"straggler": {"action": "page_me"}})


# -------------------------------------------------------------- scoreboard
def _bench_file(tmp_path, rung, mfu, device="tpu", rc=0, wrapped=False):
    inner = {"metric": "train_tokens_per_sec_per_chip",
             "value": 1000.0 * (mfu or 0), "unit": "tokens/s/chip",
             "extra": {"mfu": mfu, "device": device}}
    path = tmp_path / "BENCH_r{:02d}.json".format(rung)
    if wrapped:
        payload = {"n": rung, "cmd": "python bench.py", "rc": rc,
                   "tail": "noise\n" + json.dumps(inner) + "\n"}
    elif rc != 0:
        payload = {"n": rung, "cmd": "python bench.py", "rc": rc,
                   "tail": "Traceback ...\n"}
    else:
        payload = inner
    path.write_text(json.dumps(payload))
    return str(path)


def test_scoreboard_regression_gate_and_unwrap(tmp_path):
    scoreboard = _load_bin("ds_scoreboard")
    paths = [
        _bench_file(tmp_path, 1, 0.50, wrapped=True),
        _bench_file(tmp_path, 2, 0.52),
        _bench_file(tmp_path, 3, None, rc=1),      # failed rung, kept
        _bench_file(tmp_path, 4, 0.51),
    ]
    board = scoreboard.build_scoreboard(paths)
    assert [r["mfu"] for r in board["rows"]] == [0.50, 0.52, None, 0.51]
    assert board["rows"][2]["error"] is not None
    assert board["regression"] is False and board["gate"] == "passed"
    assert board["best_prior_mfu"] == 0.52
    # >10% drop trips
    paths.append(_bench_file(tmp_path, 5, 0.40))
    tripped = scoreboard.build_scoreboard(paths)
    assert tripped["regression"] is True and tripped["gate"] == "tripped"
    md = scoreboard.render_markdown(tripped)
    assert "REGRESSION" in md and "| 5 |" in md


def test_scoreboard_device_gating(tmp_path):
    scoreboard = _load_bin("ds_scoreboard")
    paths = [_bench_file(tmp_path, 1, 0.50, device="tpu"),
             _bench_file(tmp_path, 2, 0.003, device="cpu")]
    board = scoreboard.build_scoreboard(paths)
    assert board["regression"] is False
    assert board["gate"].startswith("skipped: latest rung is a cpu")
    # gate-cpu still finds no same-device prior -> skipped, not tripped
    board = scoreboard.build_scoreboard(paths, gate_cpu=True)
    assert board["regression"] is False
    assert board["gate"].startswith("skipped: no prior rung")
    # a genuine same-device cpu regression trips under --gate-cpu
    paths.append(_bench_file(tmp_path, 3, 0.001, device="cpu"))
    board = scoreboard.build_scoreboard(paths, gate_cpu=True)
    assert board["regression"] is True


def test_check_bench_schema_validates_scoreboard_and_metrics(tmp_path):
    scoreboard = _load_bin("ds_scoreboard")
    checker = _load_bin("check_bench_schema")
    paths = [_bench_file(tmp_path, 1, 0.5), _bench_file(tmp_path, 2, 0.6)]
    board = scoreboard.build_scoreboard(paths)
    good = tmp_path / "scoreboard.json"
    good.write_text(json.dumps(board))
    assert checker.check_file(str(good)) == []
    bad = tmp_path / "bad_scoreboard.json"
    bad.write_text(json.dumps(dict(board, rows=[])))
    assert checker.check_file(str(bad)) != []
    # extra.metrics payloads
    assert checker.check_metrics_payload(
        {"series": 5, "port": 1234,
         "scrape": "# TYPE ds_mfu gauge\nds_mfu 0.5\n"}) == []
    assert checker.check_metrics_payload({"series": 0, "scrape": ""}) \
        != []
    assert checker.check_metrics_payload("nope") != []


# ------------------------------------------------------------------ DSL007
def test_dsl007_metric_name_outside_catalog(tmp_path):
    from deepspeed_tpu.analysis import astlint
    src = tmp_path / "mod.py"
    src.write_text(
        "def build(r):\n"
        "    a = r.counter('documented_series_total')\n"
        "    b = r.gauge('undocumented_series')\n"
        "    c = r.histogram('NotAMetricName')\n"   # shape-mismatch: skip
        "    return a, b, c\n")
    catalog = "| `ds_documented_series_total` | counter | | ok |\n"
    findings = astlint.lint_paths([str(tmp_path)], base=str(tmp_path),
                                  metric_catalog=catalog)
    keys = [k for k in findings if k.startswith("DSL007")]
    assert len(keys) == 1
    assert "undocumented_series" in keys[0] or \
        "undocumented_series" in findings[keys[0]][0].message
    # catalog absent -> the rule is inert (partial checkouts)
    assert astlint.lint_paths([str(tmp_path)],
                              base=str(tmp_path)) == {} or \
        not any(k.startswith("DSL007")
                for k in astlint.lint_paths([str(tmp_path)],
                                            base=str(tmp_path)))


def test_dsl007_repo_metrics_all_documented():
    """Every metric name metrics.py exports is in docs/fleet.md —
    the repo's own DSL007 self-check stays green."""
    from deepspeed_tpu.analysis import astlint
    findings = astlint.lint_paths(
        [os.path.join(_REPO, "deepspeed_tpu", "telemetry", "fleet")],
        base=_REPO)
    assert not any(k.startswith("DSL007") for k in findings), \
        sorted(k for k in findings if k.startswith("DSL007"))
