"""ZeRO++ communication-efficiency layer (runtime/comm/quantize.py).

Codec round-trip error bounds, the shard_map quantized collectives, and
the three engine modes: qwZ (int8 weight all-gather == fp32 gather within
int8 tolerance), hpZ (identical params to flat ZeRO-3), and qgZ
(short-run loss-curve parity with fp32 gradients) — all on the virtual
8-CPU mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel.topology import (DATA_AXIS, DATA_REPLICA_AXIS,
                                             DATA_SHARD_AXIS, build_mesh,
                                             factor_data_axis)
from deepspeed_tpu.runtime.comm import quantize as qz
from deepspeed_tpu.runtime.comm.wire import (estimate_engine_comm_bytes,
                                             estimate_step_comm_bytes)
from simple_model import make_simple_model, SimpleDataset, base_config

pytestmark = pytest.mark.comm

HIDDEN = 16
WORLD = 8


# ----------------------------------------------------------------- the codec
def test_flat_codec_roundtrip_error_bound():
    """|x - dequant(quant(x))| <= scale/2 per lane (symmetric rounding)."""
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(1000).astype(np.float32) * 3.0)
    q, scales = qz.quantize_blockwise(x, block_size=256)
    assert q.dtype == jnp.int8 and scales.dtype == jnp.float32
    rt = qz.dequantize_blockwise(q, scales, x.size, x.dtype)
    per_lane_bound = np.repeat(np.asarray(scales), 256)[:1000] * 0.5
    err = np.abs(np.asarray(rt) - np.asarray(x))
    assert (err <= per_lane_bound + 1e-7).all(), err.max()


def test_flat_codec_scale_dtype_follows_input():
    """bf16 in -> bf16 scales and bf16 round-trip (no fp32 upcast)."""
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(512), jnp.bfloat16)
    q, scales = qz.quantize_blockwise(x)
    assert scales.dtype == jnp.bfloat16
    rt = qz.dequantize_blockwise(q, scales, x.size, x.dtype)
    assert rt.dtype == jnp.bfloat16
    rel = float(jnp.mean(jnp.abs(rt.astype(jnp.float32) -
                                 x.astype(jnp.float32))) /
                jnp.mean(jnp.abs(x.astype(jnp.float32))))
    assert rel < 0.02, rel


def test_param_codec_preserves_shape():
    rs = np.random.RandomState(2)
    w = jnp.asarray(rs.randn(24, 100).astype(np.float32))
    q, scales = qz.quantize_param(w, block_size=32)
    assert q.shape == w.shape and q.dtype == jnp.int8
    # 100 has no divisor in (32, 25]; largest divisor <= 32 is 25
    assert scales.shape == (24, 4)
    rt = qz.dequantize_param(q, scales, w.dtype)
    rel = float(jnp.mean(jnp.abs(rt - w)) / jnp.mean(jnp.abs(w)))
    assert rel < 0.01, rel


def test_zero_block_roundtrips_to_zero():
    x = jnp.zeros(512, jnp.float32)
    rt = qz.quantize_dequantize(x)
    np.testing.assert_array_equal(np.asarray(rt), 0.0)


def test_error_feedback_unbiased():
    """The qgZ accumulator telescopes: mean over T calls -> exact value."""
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(777).astype(np.float32))
    err = jnp.zeros(777, jnp.float32)
    acc = np.zeros(777, np.float64)
    T = 100
    for _ in range(T):
        qd, err = qz.quantize_with_error_feedback(x, err)
        acc += np.asarray(qd, np.float64)
    bias = np.abs(acc / T - np.asarray(x)).mean() / \
        np.abs(np.asarray(x)).mean()
    assert bias < 0.01, bias


def test_error_feedback_scale_invariant():
    """Residuals live in unscaled units: feeding x*s with scale=s carries
    the same correction as feeding x with scale=1, so a loss-scale change
    between calls cannot inject a wrong-magnitude bias."""
    rs = np.random.RandomState(8)
    x = jnp.asarray(rs.randn(512).astype(np.float32))
    _, err_unit = qz.quantize_with_error_feedback(x, jnp.zeros(512))
    qd_scaled, err_scaled = qz.quantize_with_error_feedback(
        x * 1024.0, jnp.zeros(512), scale=1024.0)
    np.testing.assert_allclose(np.asarray(err_scaled),
                               np.asarray(err_unit), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qd_scaled) / 1024.0,
                               np.asarray(x) - np.asarray(err_unit),
                               rtol=1e-3, atol=1e-4)


def test_qgz_error_reset_on_overflow():
    """An overflowed step must zero the qgZ residual (inf grads would
    otherwise poison it permanently)."""
    cfg = _zero_cfg(zero_quantized_gradients=True)
    del cfg["bf16"]
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 4}
    engine = _make_engine(cfg)
    dataset = SimpleDataset(64, HIDDEN, seed=12)
    _run_steps(engine, dataset, 1)  # healthy step: residual becomes nonzero
    assert any(float(jnp.abs(e).max()) > 0
               for e in jax.tree_util.tree_leaves(engine.state["qg_error"]))
    # poison the accumulators the way an inf loss would and take a step
    engine.state["acc_grads"] = jax.tree_util.tree_map(
        lambda g: jnp.full_like(g, jnp.inf), engine.state["acc_grads"])
    engine.state["qg_error"] = jax.tree_util.tree_map(
        lambda e: jnp.full_like(e, jnp.nan), engine.state["qg_error"])
    engine._take_model_step()
    assert engine.skipped_steps >= 1
    for e in jax.tree_util.tree_leaves(engine.state["qg_error"]):
        np.testing.assert_array_equal(np.asarray(e), 0.0)


def test_sign_helpers_scale_dtype():
    """The deduped 1-bit helpers keep a bf16 buffer in bf16."""
    x = jnp.asarray(np.linspace(-1, 1, 64), jnp.bfloat16)
    scale = qz.sign_scale(x, 64.0)
    assert scale.dtype == jnp.bfloat16
    out = qz.unpack_signs(qz.pack_signs(x), scale)
    assert out.dtype == jnp.bfloat16


# -------------------------------------------------- shard_map collectives
def test_quantized_all_gather_matches_fp32_gather():
    mesh = build_mesh(data=WORLD)
    qc = qz.QuantizedCollectives(mesh)
    rs = np.random.RandomState(4)
    vals = jnp.asarray(rs.randn(WORLD, 512).astype(np.float32))
    out = qc.all_gather(vals)
    assert out.shape == (WORLD, WORLD * 512)
    exact = np.asarray(vals).reshape(-1)
    for rank in (0, 3, 7):
        got = np.asarray(out[rank])
        rel = np.abs(got - exact).mean() / np.abs(exact).mean()
        assert rel < 0.01, rel


def test_quantized_reduce_scatter_matches_sum():
    mesh = build_mesh(data=WORLD)
    qc = qz.QuantizedCollectives(mesh)
    rs = np.random.RandomState(5)
    vals = jnp.asarray(rs.randn(WORLD, WORLD * 64).astype(np.float32))
    out = qc.reduce_scatter(vals)
    true = np.asarray(vals).sum(axis=0).reshape(WORLD, 64)
    rel = np.abs(np.asarray(out) - true).mean() / np.abs(true).mean()
    assert rel < 0.02, rel


# ------------------------------------------------------------- qwZ gather
def test_qwz_gather_matches_fp32_gather_within_int8_tolerance():
    """The int8 all-gather reproduces the fp32 gather to within the
    per-block quantization bound, and its vjp is straight-through."""
    mesh = build_mesh(data=WORLD)
    sharded = NamedSharding(mesh, P(DATA_AXIS, None))
    gathered = NamedSharding(mesh, P())
    rs = np.random.RandomState(6)
    w = jax.device_put(
        jnp.asarray(rs.randn(WORLD * 4, 64).astype(np.float32)), sharded)

    gathered_w = jax.jit(
        lambda x: qz.qwz_gather(x, gathered, sharded))(w)
    assert gathered_w.shape == w.shape
    _, scales = qz.quantize_param(np.asarray(w))
    bound = np.asarray(scales, np.float32).max() * 0.51
    err = np.abs(np.asarray(gathered_w) - np.asarray(w)).max()
    assert err <= bound, (err, bound)

    # straight-through backward: grads flow as identity
    g = jax.jit(jax.grad(
        lambda x: jnp.sum(qz.qwz_gather(x, gathered, sharded) * 2.0)))(w)
    np.testing.assert_allclose(np.asarray(g), 2.0)


# ------------------------------------------------------------ engine modes
def _make_engine(config, seed=2):
    model = make_simple_model(HIDDEN, seed=seed)
    engine, _, _, _ = deepspeed.initialize(model=model,
                                           config_params=config)
    return engine


def _run_steps(engine, dataset, steps):
    mb = engine.train_micro_batch_size_per_gpu() * WORLD
    losses = []
    for s in range(steps):
        x = np.stack([dataset[(s * mb + i) % len(dataset)][0]
                      for i in range(mb)])
        y = np.stack([dataset[(s * mb + i) % len(dataset)][1]
                      for i in range(mb)])
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def _zero_cfg(**zero_overrides):
    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    zero = {"stage": 3, "stage3_param_persistence_threshold": 0}
    zero.update(zero_overrides)
    cfg["zero_optimization"] = zero
    return cfg


@pytest.fixture(scope="module")
def flat_zero3():
    dataset = SimpleDataset(512, HIDDEN, seed=11)
    engine = _make_engine(_zero_cfg())
    losses = _run_steps(engine, dataset, 6)
    params = jax.tree_util.tree_map(np.asarray, engine.get_params())
    return dataset, losses, params


def test_hpz_mesh_factoring():
    mesh = factor_data_axis(build_mesh(data=WORLD), 4)
    assert dict(mesh.shape) == {DATA_REPLICA_AXIS: 2, DATA_SHARD_AXIS: 4}
    with pytest.raises(ValueError):
        factor_data_axis(build_mesh(data=WORLD), 3)  # 3 does not divide 8


def test_hpz_identical_params_to_flat_zero3(flat_zero3):
    """hpZ only changes placement: same losses, same params."""
    dataset, ref_losses, ref_params = flat_zero3
    engine = _make_engine(_zero_cfg(zero_hierarchical_partition=4))
    assert engine.zero_hierarchical_partition() == 4
    assert DATA_SHARD_AXIS in engine.mesh.shape
    losses = _run_steps(engine, dataset, 6)
    np.testing.assert_allclose(np.array(losses), np.array(ref_losses),
                               rtol=5e-3, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ref_params),
                    jax.tree_util.tree_leaves(engine.get_params())):
        np.testing.assert_allclose(a, np.asarray(b), rtol=5e-3, atol=1e-5)


def test_qwz_short_run_loss_parity(flat_zero3):
    """int8 weight gathers: loss curve tracks the fp32-gather baseline."""
    dataset, ref_losses, _ = flat_zero3
    engine = _make_engine(_zero_cfg(zero_quantized_weights=True))
    assert engine.zero_quantized_weights()
    losses = _run_steps(engine, dataset, 6)
    np.testing.assert_allclose(np.array(losses), np.array(ref_losses),
                               rtol=0.05, atol=1e-4)


def test_qgz_short_run_loss_parity(flat_zero3):
    """Quantized-gradient mode vs fp32 gradients: loss-curve parity."""
    dataset, ref_losses, _ = flat_zero3
    engine = _make_engine(_zero_cfg(zero_quantized_gradients=True))
    assert engine.zero_quantized_gradients()
    assert "qg_error" in engine.state
    losses = _run_steps(engine, dataset, 6)
    np.testing.assert_allclose(np.array(losses), np.array(ref_losses),
                               rtol=0.05, atol=1e-4)


def test_all_modes_combined_loss_parity(flat_zero3):
    dataset, ref_losses, _ = flat_zero3
    engine = _make_engine(_zero_cfg(zero_quantized_weights=True,
                                    zero_hierarchical_partition=2,
                                    zero_quantized_gradients=True))
    losses = _run_steps(engine, dataset, 6)
    rel = abs(losses[-1] - ref_losses[-1]) / abs(ref_losses[-1])
    assert rel < 0.05, (losses, ref_losses)


def test_modes_ignored_below_their_stage():
    """Toggles are stage-gated: stage 1 config leaves them all off."""
    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    cfg["zero_optimization"] = {"stage": 1, "zero_quantized_weights": True,
                                "zero_hierarchical_partition": 2,
                                "zero_quantized_gradients": True}
    engine = _make_engine(cfg)
    assert not engine.zero_quantized_weights()
    assert not engine.zero_quantized_gradients()
    assert engine.zero_hierarchical_partition() == 0
    assert DATA_AXIS in engine.mesh.shape


def test_hierarchical_partition_must_divide_dp():
    with pytest.raises(ValueError, match="divide"):
        _make_engine(_zero_cfg(zero_hierarchical_partition=3))


# ------------------------------------------------------------ wire estimate
def test_wire_estimate_reduction_ratio():
    """qwZ+hpZ all-gather bytes drop >= 3x vs flat fp32 ZeRO-3."""
    engine = _make_engine(_zero_cfg(zero_quantized_weights=True,
                                    zero_hierarchical_partition=2,
                                    zero_quantized_gradients=True))
    comm = estimate_engine_comm_bytes(engine)
    assert comm["allgather_reduction_x"] >= 3.0, comm
    assert comm["total_bytes_per_step"] < \
        comm["fp32_flat_total_bytes_per_step"]


def test_wire_estimate_flat_fp32_baseline_is_neutral():
    """The flat-fp32 estimate of a flat fp32-wire config equals itself."""
    engine = _make_engine(_zero_cfg())
    plan = engine.zero_plan
    params = engine.state["params"]
    cur = estimate_step_comm_bytes(plan, params, compute_itemsize=4,
                                   grad_itemsize=4)
    base = estimate_step_comm_bytes(plan, params, _force_flat_fp32=True)
    assert cur == base
