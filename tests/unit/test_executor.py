"""Segment-graph executor (ISSUE 13): plan/scheduler semantics, the
classic-offload + streamed lowerings bit-exact against the serial
oracle, the unified SEGMENT_KEYS telemetry schema, and plan_of/audit.

The load-bearing contract: ``runtime.executor`` changes WALL-CLOCK
placement only, never values — serial and overlap runs produce
bit-identical losses, master/optimizer state, and checkpoint bytes on
both lowered paths.
"""
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.config import DeepSpeedConfigError
from deepspeed_tpu.runtime.executor import (PlanError, PlanExecutor,
                                            Segment, SegmentPlan,
                                            SEGMENT_KINDS,
                                            plan_for_engine)
from deepspeed_tpu.runtime.model import Model
from deepspeed_tpu.telemetry import record as rec_mod

pytestmark = pytest.mark.executor

GPT_CFG = gpt2.GPT2Config(vocab_size=64, max_seq_len=32, n_layers=2,
                          n_heads=2, d_model=32,
                          use_flash_attention=False, remat=False,
                          loss_chunk=0)


def _linear_engine(mode="auto", offload=True, telemetry=None, lr=5e-2):
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": lr}},
        "bf16": {"enabled": True},
        "runtime": {"executor": mode},
        "steps_per_print": 10 ** 9,
    }
    if offload:
        config["zero_optimization"] = {"stage": 2, "cpu_offload": True,
                                       "sub_group_size": 16}
    if telemetry is not None:
        config["telemetry"] = telemetry
    engine, _, _, _ = deepspeed.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((8, 4))}),
        config_params=config)
    return engine


def _gpt_engine(mode="auto", streamed=False, extra_zero=None):
    zero = {"stage": 3 if streamed else 2, "cpu_offload": True}
    if streamed:
        zero.update({"cpu_offload_params": True,
                     "stage3_max_live_parameters": 1})
    zero.update(extra_zero or {})
    engine, _, _, _ = deepspeed.initialize(
        model=gpt2.make_gpt2_model(config=GPT_CFG),
        config_params={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": zero,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "runtime": {"executor": mode},
            "steps_per_print": 10 ** 9,
        })
    return engine


def _gpt_ids(seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, GPT_CFG.vocab_size,
                       size=(2, GPT_CFG.max_seq_len)).astype(np.int32)


def _linear_batch(seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(8, 8).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(x @ rs.randn(8, 4)
                                       .astype(np.float32))


def _host_masters(engine):
    return [np.asarray(tup[1])
            for shards in engine.host_state["shard_leaves"]
            for tup in shards]


def _host_moments(engine):
    return [np.asarray(arr)
            for shards in engine.host_state["shard_leaves"]
            for tup in shards for arr in (tup[2], tup[3])]


# ------------------------------------------------------------ plan layer
def test_plan_validate_catches_malformed_plans():
    plan = SegmentPlan("p")
    plan.add(Segment(name="a", kind="compute"))
    with pytest.raises(PlanError):
        plan.add(Segment(name="a", kind="compute"))     # duplicate
    plan.add(Segment(name="b", kind="warp", deps=("a",)))
    plan.add(Segment(name="c", kind="host", deps=("ghost",)))
    plan.add(Segment(name="d", kind="host", deps=("e",)))
    plan.add(Segment(name="e", kind="host"))
    problems = plan.validate()
    assert any("unknown kind 'warp'" in p for p in problems)
    assert any("unknown segment 'ghost'" in p for p in problems)
    assert any("inserted AFTER" in p for p in problems)
    good = SegmentPlan("g", [Segment(name="a", kind="compute"),
                             Segment(name="b", kind="host",
                                     deps=("a",))])
    assert good.validate() == []
    assert good.consumer_counts() == {"a": 1, "b": 0}
    assert good.summary()["segments"] == 2


def test_executor_refuses_invalid_plan():
    plan = SegmentPlan("bad", [Segment(name="x", kind="host",
                                       deps=("nope",))])
    with pytest.raises(PlanError):
        PlanExecutor(mode="serial").execute(plan)


def test_segment_kinds_pinned_to_ir_vocabulary():
    from deepspeed_tpu.analysis.ir import SEGMENT_KINDS as IR_KINDS
    assert tuple(SEGMENT_KINDS) == tuple(IR_KINDS)


# ------------------------------------------------------- scheduler layer
def _toy_plan(log):
    plan = SegmentPlan("toy")
    plan.add(Segment(name="src", kind="compute",
                     run=lambda env: 2, phase="compute_s"))
    plan.add(Segment(name="fetch", kind="transfer", deps=("src",),
                     async_ok=True, pool="d2h", phase="t_s",
                     run=lambda env: env["src"] * 10))
    plan.add(Segment(name="consume", kind="host", deps=("fetch",),
                     wait_phase="wait_s", phase="host_s",
                     run=lambda env: log.append(env["fetch"]) or
                     env["fetch"] + 1))
    return plan


@pytest.mark.parametrize("mode", ["serial", "overlap"])
def test_scheduler_dataflow_and_release(mode):
    log = []
    ex = PlanExecutor(mode=mode)
    env = ex.execute(_toy_plan(log))
    assert log == [20]
    # exhausted intermediates are released; terminal results retained
    assert "src" not in env and "fetch" not in env
    assert env["consume"] == 21
    records = ex.drain_step_records()
    assert [r.name for r in records] == ["src", "fetch", "consume"]
    by_name = {r.name: r for r in records}
    assert by_name["fetch"].async_run == (mode == "overlap")


def test_scheduler_window_blocked_async_runs_inline():
    """More async segments than the pool window: the blocked ones
    execute synchronously at their own plan position — values and
    completion never depend on the window."""
    ex = PlanExecutor(mode="overlap", windows={"d2h": 1})
    plan = SegmentPlan("windowed")
    for i in range(4):
        plan.add(Segment(name="t%d" % i, kind="transfer",
                         async_ok=True, pool="d2h",
                         run=lambda env, i=i: i))
    plan.add(Segment(name="sum", kind="host",
                     deps=tuple("t%d" % i for i in range(4)),
                     run=lambda env: sum(env["t%d" % i]
                                         for i in range(4))))
    assert ex.execute(plan)["sum"] == 6


def test_scheduler_phase_billing_keys():
    log = []
    phases = {}
    PlanExecutor(mode="serial").execute(_toy_plan(log), phases=phases)
    # serial: transfer run wall bills to ITS phase; host+compute to theirs
    assert set(phases) >= {"compute_s", "t_s", "host_s"}


def test_run_program_counts_one_segment():
    ex = PlanExecutor(mode="overlap")
    assert ex.run_program("apply", "compute", lambda: 7) == 7
    snap = ex.lifetime_snapshot()
    assert snap["plans_executed"] == 1
    assert snap["last_plan_segments"] == 1
    assert snap["per_kind"]["compute"]["segments"] == 1


def test_overlap_constructs_real_concurrency():
    """The overlap mode genuinely runs async segments concurrently with
    main-thread segments (sleeps release the GIL, so this pins the
    schedule, not numpy luck): serial pays both walls, overlap hides
    the transfer behind the compute."""
    import time as _time

    def plan():
        p = SegmentPlan("sleepy")
        p.add(Segment(name="t", kind="transfer", async_ok=True,
                      pool="d2h",
                      run=lambda env: _time.sleep(0.15) or 1))
        p.add(Segment(name="c", kind="compute",
                      run=lambda env: _time.sleep(0.15) or 2))
        p.add(Segment(name="join", kind="host", deps=("t", "c"),
                      run=lambda env: env["t"] + env["c"]))
        return p

    t0 = _time.time()
    assert PlanExecutor(mode="serial").execute(plan())["join"] == 3
    serial = _time.time() - t0
    t0 = _time.time()
    assert PlanExecutor(mode="overlap").execute(plan())["join"] == 3
    overlap = _time.time() - t0
    assert serial > 0.28, serial
    assert overlap < 0.25, overlap


def test_worker_exception_propagates():
    plan = SegmentPlan("boom")
    plan.add(Segment(name="t", kind="transfer", async_ok=True,
                     pool="d2h",
                     run=lambda env: (_ for _ in ()).throw(
                         RuntimeError("boom"))))
    plan.add(Segment(name="use", kind="host", deps=("t",),
                     run=lambda env: env["t"]))
    with pytest.raises(RuntimeError, match="boom"):
        PlanExecutor(mode="overlap").execute(plan)


# ----------------------------------------------------- schema pins
def test_segment_keys_pinned_to_checker_copy():
    """bin/check_bench_schema.py must stay a bare stdlib script; its
    local SEGMENT_* tables are pinned equal here so they cannot
    drift."""
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "..", "bin",
                        "check_bench_schema.py")
    spec = importlib.util.spec_from_file_location("_cbs", path)
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    assert tuple(checker.SEGMENT_KEYS) == tuple(rec_mod.SEGMENT_KEYS)
    assert tuple(checker.SEGMENT_KIND_KEYS) == \
        tuple(rec_mod.SEGMENT_KIND_KEYS)
    assert tuple(checker.SEGMENT_OPTIONAL_KEYS) == \
        tuple(rec_mod.SEGMENT_OPTIONAL_KEYS)


def test_validate_segment_stats():
    good = {"plan_segments": 3,
            "per_kind": {"transfer": {"segments": 2, "run_s": 0.1,
                                      "wait_s": 0.0}},
            "overlap_efficiency": 0.8, "upload_batches": 1,
            "upload_elems": 10, "upload_bytes": 40, "bucket_elems": 8,
            "bucket_occupancy": None, "work_chunks": 4}
    assert rec_mod.validate_segment_stats(good) == []
    bad = dict(good)
    bad.pop("per_kind")
    assert rec_mod.validate_segment_stats(bad)
    assert rec_mod.validate_segment_stats(
        dict(good, mystery=1))          # unexpected key flags
    assert rec_mod.validate_segment_stats(
        dict(good, per_kind={"transfer": {"segments": -1, "run_s": 0,
                                          "wait_s": 0}}))


# ------------------------------------------- classic offload, bit-exact
def test_classic_offload_serial_vs_overlap_bitexact():
    engines = {m: _linear_engine(mode=m) for m in ("off", "on")}
    x, y = _linear_batch()
    for step in range(4):
        losses = {}
        for mode, eng in engines.items():
            loss = eng(x, y)
            eng.backward(loss)
            eng.step()
            losses[mode] = float(loss)
        assert losses["off"] == losses["on"], (step, losses)
    for a, b in zip(_host_masters(engines["off"]),
                    _host_masters(engines["on"])):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_host_moments(engines["off"]),
                    _host_moments(engines["on"])):
        np.testing.assert_array_equal(a, b)
    # the overlap engine executed multi-segment plans (chunked d2h/adam
    # + upload/reshard), and both modes saw identical plan shapes
    snaps = {m: e.executor_snapshot() for m, e in engines.items()}
    assert snaps["on"]["last_plan_segments"] > 4
    assert snaps["on"]["last_plan_segments"] == \
        snaps["off"]["last_plan_segments"]
    assert snaps["on"]["mode"] == "overlap"
    assert snaps["off"]["mode"] == "serial"


def test_classic_offload_checkpoints_byte_identical(tmp_path):
    dirs = {}
    for mode in ("off", "on"):
        eng = _linear_engine(mode=mode)
        x, y = _linear_batch()
        for _ in range(2):
            loss = eng(x, y)
            eng.backward(loss)
            eng.step()
        d = tmp_path / mode
        eng.save_checkpoint(str(d), tag="t")
        dirs[mode] = d
    manifests = {}
    for mode, d in dirs.items():
        payload = json.load(open(os.path.join(str(d), "t",
                                              "manifest.json")))
        manifests[mode] = {name: rec["crc32"]
                           for name, rec in payload["files"].items()}
    assert manifests["off"] == manifests["on"]


def test_classic_offload_overlap_efficiency_reported(tmp_path):
    """The bespoke pre-executor classic path reported NO overlap
    efficiency; the lowered plan reports the constructed overlap in
    the unified SEGMENT_KEYS offload record."""
    eng = _linear_engine(mode="on", telemetry={
        "enabled": True, "output_path": str(tmp_path)})
    x, y = _linear_batch()
    for _ in range(2):
        loss = eng(x, y)
        eng.backward(loss)
        eng.step()
    snap = eng.telemetry_snapshot()["offload_last"]
    assert rec_mod.validate_segment_stats(snap) == [], snap
    assert snap["plan_segments"] > 4
    assert snap["overlap_efficiency"] is not None
    assert snap["overlap_efficiency"] > 0
    assert snap["per_kind"]["host"]["segments"] > 0
    assert snap["per_kind"]["transfer"]["segments"] > 0


def test_offload_overflow_skip_still_resets(tmp_path):
    """An overflowing step skips the plan entirely and resets the
    accumulators (the bespoke overflow semantics)."""
    eng = _linear_engine(mode="on", lr=5e-2)
    x, y = _linear_batch()
    loss = eng(x * np.float32(1e38), y * np.float32(1e38))
    eng.backward(loss)
    eng.step()
    assert eng.skipped_steps == 1
    assert eng.host_state["step"] == 0
    # and a sane step afterwards still works
    loss = eng(x, y)
    eng.backward(loss)
    eng.step()
    assert eng.host_state["step"] == 1


# ------------------------------------------------ streamed, bit-exact
def test_streamed_serial_vs_overlap_bitexact():
    engines = {m: _gpt_engine(mode=m, streamed=True)
               for m in ("off", "on")}
    assert len(engines["on"].stream_runner.groups) == GPT_CFG.n_layers
    ids = _gpt_ids()
    for step in range(3):
        losses = {}
        for mode, eng in engines.items():
            loss = eng(ids, ids.copy())
            eng.backward(loss)
            eng.step()
            losses[mode] = float(loss)
        assert losses["off"] == losses["on"], (step, losses)
    for a, b in zip(_host_masters(engines["off"]),
                    _host_masters(engines["on"])):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_host_moments(engines["off"]),
                    _host_moments(engines["on"])):
        np.testing.assert_array_equal(a, b)


def test_streamed_gas2_bitexact_across_modes():
    def run(mode):
        zero = {"stage": 3, "cpu_offload": True,
                "cpu_offload_params": True,
                "stage3_max_live_parameters": 1}
        eng, _, _, _ = deepspeed.initialize(
            model=gpt2.make_gpt2_model(config=GPT_CFG),
            config_params={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "bf16": {"enabled": True},
                "zero_optimization": zero,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "runtime": {"executor": mode},
                "steps_per_print": 10 ** 9,
            })
        ids = np.stack([_gpt_ids(0), _gpt_ids(1)])
        out = [float(eng.train_batch(batch=(ids, ids.copy())))
               for _ in range(2)]
        return out, _host_masters(eng)

    (loss_a, masters_a) = run("off")
    (loss_b, masters_b) = run("on")
    assert loss_a == loss_b
    for a, b in zip(masters_a, masters_b):
        np.testing.assert_array_equal(a, b)


# --------------------------------------------------- plan_of + audit
def test_plan_of_offload_topology_matches_execution():
    eng = _linear_engine(mode="on")
    plan = plan_for_engine(eng)
    assert plan.validate() == []
    assert plan.name == "offload_apply"
    names = {s.name for s in plan.segments}
    assert "upload_finish" in names and "reshard" in names
    # run one real step; the executed update-plan records must carry
    # exactly the abstract plan's nodes (plan construction and
    # execution share one topology builder)
    x, y = _linear_batch()
    loss = eng(x, y)
    eng.backward(loss)
    eng.step()
    # records were drained by the step boundary; run the apply again
    # via another step and intercept before the drain
    loss = eng(x, y)
    eng.backward(loss)
    eng._take_model_step()
    executed = {r.name for r in eng.plan_executor().drain_step_records()}
    assert executed == names


def test_plan_of_streamed_topology_matches_execution():
    eng = _gpt_engine(mode="on", streamed=True)
    ids = _gpt_ids()
    plan = plan_for_engine(eng)
    assert plan.validate() == []
    assert plan.name == "streamed_micro"
    names = {s.name for s in plan.segments}
    assert {"e_fwd", "h_grad", "e_bwd", "resolve", "loss"} <= names
    loss = eng(ids, ids.copy())     # one micro step, no boundary drain
    executed = {r.name for r in eng.plan_executor().drain_step_records()}
    assert executed == names
    assert np.isfinite(float(loss))
    eng.backward(loss)
    eng.step()


def test_ir_plan_of_is_the_executor_entry_point():
    from deepspeed_tpu.analysis.ir import plan_of
    eng = _linear_engine(mode="auto")
    plan = plan_of(eng)
    assert plan.name == "offload_apply" and plan.validate() == []
    with pytest.raises(ValueError):
        plan_of(_linear_engine(mode="auto", offload=False))


def test_audit_plan_reports_shape_and_catches_breakage(monkeypatch):
    from deepspeed_tpu.analysis import AnalysisReport
    from deepspeed_tpu.analysis.auditor import audit_plan
    eng = _linear_engine(mode="auto")
    report = AnalysisReport(job="t")
    audit_plan(eng, report)
    assert not report.findings
    assert any(name.startswith("plan/offload_apply")
               for name in report.programs)
    # a lowering bug (malformed plan) becomes an unsuppressable finding
    import deepspeed_tpu.runtime.executor as ex_mod
    broken = SegmentPlan("offload_apply",
                         [Segment(name="a", kind="host",
                                  deps=("missing",))])
    monkeypatch.setattr(ex_mod, "plan_for_engine",
                        lambda engine, family=None: broken)
    report2 = AnalysisReport(job="t2")
    audit_plan(eng, report2)
    assert report2.findings
    assert report2.findings[0].check == "plan_invalid"


def test_engine_audit_green_on_lowered_paths():
    eng = _gpt_engine(mode="on")
    ids = _gpt_ids()
    report = eng.audit(batch=(ids, ids.copy()))
    assert report.findings == [], [f.message for f in report.findings]
    assert any(name.startswith("plan/") for name in report.programs)


# ------------------------------------------------------- config gate
def test_runtime_executor_config_gate():
    assert _linear_engine(mode="off")._executor_mode == "serial"
    assert _linear_engine(mode="on")._executor_mode == "overlap"
    assert _linear_engine(mode="auto")._executor_mode == "overlap"
    with pytest.raises(DeepSpeedConfigError):
        _linear_engine(mode="sideways")


def test_runtime_section_unknown_key_validated(tmp_path):
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(None, param_dict={
            "train_batch_size": 8,
            "config_validation": "strict",
            "runtime": {"executor": "auto", "warp_drive": True}})


# ---------------------------------------------------------- DSL006
def test_dsl006_flags_scheduling_outside_executor(tmp_path):
    from deepspeed_tpu.analysis import astlint
    dirty = tmp_path / "deepspeed_tpu" / "runtime" / "zero"
    dirty.mkdir(parents=True)
    (dirty / "sneaky.py").write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "import jax\n"
        "def go(bufs, fn):\n"
        "    pool = ThreadPoolExecutor(max_workers=1)\n"
        "    bufs[0].copy_to_host_async()\n"
        "    jitted = jax.jit(fn, donate_argnums=(0,))\n"
        "    return pool, jitted\n")
    exec_dir = tmp_path / "deepspeed_tpu" / "runtime" / "executor"
    exec_dir.mkdir(parents=True)
    (exec_dir / "sched.py").write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "def pool():\n"
        "    return ThreadPoolExecutor(max_workers=1)\n")
    findings = astlint.lint_paths([str(tmp_path / "deepspeed_tpu")],
                                  base=str(tmp_path))
    dsl6 = sorted(k for k in findings if k.startswith("DSL006"))
    assert dsl6 == [
        "DSL006:deepspeed_tpu/runtime/zero/sneaky.py::go"], dsl6
    assert len(findings[dsl6[0]]) == 3      # pool + async copy + donate


def test_repo_lint_green_with_dsl006_baseline():
    from deepspeed_tpu.analysis import astlint
    repo = os.path.join(os.path.dirname(__file__), "..", "..")
    findings = astlint.lint_paths(
        [os.path.join(repo, "deepspeed_tpu")], base=repo)
    baseline = astlint.load_baseline(
        os.path.join(repo, "bin", "ds_lint_baseline.json"))
    new, _stale = astlint.diff_baseline(findings, baseline)
    assert new == [], [f.message for f in new]
    # the executor package itself must be DSL006-clean (it is the one
    # place scheduling is allowed — nothing there needs baselining)
    assert not any("runtime/executor" in k for k in findings
                   if k.startswith("DSL006"))
