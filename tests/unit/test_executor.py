"""Segment-graph executor (ISSUE 13): plan/scheduler semantics, the
classic-offload + streamed lowerings bit-exact against the serial
oracle, the unified SEGMENT_KEYS telemetry schema, and plan_of/audit.

The load-bearing contract: ``runtime.executor`` changes WALL-CLOCK
placement only, never values — serial and overlap runs produce
bit-identical losses, master/optimizer state, and checkpoint bytes on
both lowered paths.
"""
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.config import DeepSpeedConfigError
from deepspeed_tpu.runtime.executor import (PlanError, PlanExecutor,
                                            Segment, SegmentPlan,
                                            SEGMENT_KINDS,
                                            plan_for_engine)
from deepspeed_tpu.runtime.model import Model
from deepspeed_tpu.telemetry import record as rec_mod

pytestmark = pytest.mark.executor

GPT_CFG = gpt2.GPT2Config(vocab_size=64, max_seq_len=32, n_layers=2,
                          n_heads=2, d_model=32,
                          use_flash_attention=False, remat=False,
                          loss_chunk=0)


def _linear_engine(mode="auto", offload=True, telemetry=None, lr=5e-2):
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": lr}},
        "bf16": {"enabled": True},
        "runtime": {"executor": mode},
        "steps_per_print": 10 ** 9,
    }
    if offload:
        config["zero_optimization"] = {"stage": 2, "cpu_offload": True,
                                       "sub_group_size": 16}
    if telemetry is not None:
        config["telemetry"] = telemetry
    engine, _, _, _ = deepspeed.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((8, 4))}),
        config_params=config)
    return engine


def _gpt_engine(mode="auto", streamed=False, extra_zero=None):
    zero = {"stage": 3 if streamed else 2, "cpu_offload": True}
    if streamed:
        zero.update({"cpu_offload_params": True,
                     "stage3_max_live_parameters": 1})
    zero.update(extra_zero or {})
    engine, _, _, _ = deepspeed.initialize(
        model=gpt2.make_gpt2_model(config=GPT_CFG),
        config_params={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": zero,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "runtime": {"executor": mode},
            "steps_per_print": 10 ** 9,
        })
    return engine


def _gpt_ids(seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, GPT_CFG.vocab_size,
                       size=(2, GPT_CFG.max_seq_len)).astype(np.int32)


def _linear_batch(seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(8, 8).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(x @ rs.randn(8, 4)
                                       .astype(np.float32))


def _host_masters(engine):
    return [np.asarray(tup[1])
            for shards in engine.host_state["shard_leaves"]
            for tup in shards]


def _host_moments(engine):
    return [np.asarray(arr)
            for shards in engine.host_state["shard_leaves"]
            for tup in shards for arr in (tup[2], tup[3])]


# ------------------------------------------------------------ plan layer
def test_plan_validate_catches_malformed_plans():
    plan = SegmentPlan("p")
    plan.add(Segment(name="a", kind="compute"))
    with pytest.raises(PlanError):
        plan.add(Segment(name="a", kind="compute"))     # duplicate
    plan.add(Segment(name="b", kind="warp", deps=("a",)))
    plan.add(Segment(name="c", kind="host", deps=("ghost",)))
    plan.add(Segment(name="d", kind="host", deps=("e",)))
    plan.add(Segment(name="e", kind="host"))
    problems = plan.validate()
    assert any("unknown kind 'warp'" in p for p in problems)
    assert any("unknown segment 'ghost'" in p for p in problems)
    assert any("inserted AFTER" in p for p in problems)
    good = SegmentPlan("g", [Segment(name="a", kind="compute"),
                             Segment(name="b", kind="host",
                                     deps=("a",))])
    assert good.validate() == []
    assert good.consumer_counts() == {"a": 1, "b": 0}
    assert good.summary()["segments"] == 2


def test_executor_refuses_invalid_plan():
    plan = SegmentPlan("bad", [Segment(name="x", kind="host",
                                       deps=("nope",))])
    with pytest.raises(PlanError):
        PlanExecutor(mode="serial").execute(plan)


def test_segment_kinds_pinned_to_ir_vocabulary():
    from deepspeed_tpu.analysis.ir import SEGMENT_KINDS as IR_KINDS
    assert tuple(SEGMENT_KINDS) == tuple(IR_KINDS)


# ------------------------------------------------------- scheduler layer
def _toy_plan(log):
    plan = SegmentPlan("toy")
    plan.add(Segment(name="src", kind="compute",
                     run=lambda env: 2, phase="compute_s"))
    plan.add(Segment(name="fetch", kind="transfer", deps=("src",),
                     async_ok=True, pool="d2h", phase="t_s",
                     run=lambda env: env["src"] * 10))
    plan.add(Segment(name="consume", kind="host", deps=("fetch",),
                     wait_phase="wait_s", phase="host_s",
                     run=lambda env: log.append(env["fetch"]) or
                     env["fetch"] + 1))
    return plan


@pytest.mark.parametrize("mode", ["serial", "overlap"])
def test_scheduler_dataflow_and_release(mode):
    log = []
    ex = PlanExecutor(mode=mode)
    env = ex.execute(_toy_plan(log))
    assert log == [20]
    # exhausted intermediates are released; terminal results retained
    assert "src" not in env and "fetch" not in env
    assert env["consume"] == 21
    records = ex.drain_step_records()
    assert [r.name for r in records] == ["src", "fetch", "consume"]
    by_name = {r.name: r for r in records}
    assert by_name["fetch"].async_run == (mode == "overlap")


def test_scheduler_window_blocked_async_runs_inline():
    """More async segments than the pool window: the blocked ones
    execute synchronously at their own plan position — values and
    completion never depend on the window."""
    ex = PlanExecutor(mode="overlap", windows={"d2h": 1})
    plan = SegmentPlan("windowed")
    for i in range(4):
        plan.add(Segment(name="t%d" % i, kind="transfer",
                         async_ok=True, pool="d2h",
                         run=lambda env, i=i: i))
    plan.add(Segment(name="sum", kind="host",
                     deps=tuple("t%d" % i for i in range(4)),
                     run=lambda env: sum(env["t%d" % i]
                                         for i in range(4))))
    assert ex.execute(plan)["sum"] == 6


def test_scheduler_phase_billing_keys():
    log = []
    phases = {}
    PlanExecutor(mode="serial").execute(_toy_plan(log), phases=phases)
    # serial: transfer run wall bills to ITS phase; host+compute to theirs
    assert set(phases) >= {"compute_s", "t_s", "host_s"}


def test_run_program_counts_one_segment():
    ex = PlanExecutor(mode="overlap")
    assert ex.run_program("apply", "compute", lambda: 7) == 7
    snap = ex.lifetime_snapshot()
    assert snap["plans_executed"] == 1
    assert snap["last_plan_segments"] == 1
    assert snap["per_kind"]["compute"]["segments"] == 1


def test_overlap_constructs_real_concurrency():
    """The overlap mode genuinely runs async segments concurrently with
    main-thread segments (sleeps release the GIL, so this pins the
    schedule, not numpy luck): serial pays both walls, overlap hides
    the transfer behind the compute."""
    import time as _time

    def plan():
        p = SegmentPlan("sleepy")
        p.add(Segment(name="t", kind="transfer", async_ok=True,
                      pool="d2h",
                      run=lambda env: _time.sleep(0.15) or 1))
        p.add(Segment(name="c", kind="compute",
                      run=lambda env: _time.sleep(0.15) or 2))
        p.add(Segment(name="join", kind="host", deps=("t", "c"),
                      run=lambda env: env["t"] + env["c"]))
        return p

    t0 = _time.time()
    assert PlanExecutor(mode="serial").execute(plan())["join"] == 3
    serial = _time.time() - t0
    t0 = _time.time()
    assert PlanExecutor(mode="overlap").execute(plan())["join"] == 3
    overlap = _time.time() - t0
    assert serial > 0.28, serial
    assert overlap < 0.25, overlap


def test_worker_exception_propagates():
    plan = SegmentPlan("boom")
    plan.add(Segment(name="t", kind="transfer", async_ok=True,
                     pool="d2h",
                     run=lambda env: (_ for _ in ()).throw(
                         RuntimeError("boom"))))
    plan.add(Segment(name="use", kind="host", deps=("t",),
                     run=lambda env: env["t"]))
    with pytest.raises(RuntimeError, match="boom"):
        PlanExecutor(mode="overlap").execute(plan)


# ----------------------------------------------------- schema pins
def test_segment_keys_pinned_to_checker_copy():
    """bin/check_bench_schema.py must stay a bare stdlib script; its
    local SEGMENT_* tables are pinned equal here so they cannot
    drift."""
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "..", "bin",
                        "check_bench_schema.py")
    spec = importlib.util.spec_from_file_location("_cbs", path)
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    assert tuple(checker.SEGMENT_KEYS) == tuple(rec_mod.SEGMENT_KEYS)
    assert tuple(checker.SEGMENT_KIND_KEYS) == \
        tuple(rec_mod.SEGMENT_KIND_KEYS)
    assert tuple(checker.SEGMENT_OPTIONAL_KEYS) == \
        tuple(rec_mod.SEGMENT_OPTIONAL_KEYS)


def test_validate_segment_stats():
    good = {"plan_segments": 3,
            "per_kind": {"transfer": {"segments": 2, "run_s": 0.1,
                                      "wait_s": 0.0}},
            "overlap_efficiency": 0.8, "upload_batches": 1,
            "upload_elems": 10, "upload_bytes": 40, "bucket_elems": 8,
            "bucket_occupancy": None, "work_chunks": 4}
    assert rec_mod.validate_segment_stats(good) == []
    bad = dict(good)
    bad.pop("per_kind")
    assert rec_mod.validate_segment_stats(bad)
    assert rec_mod.validate_segment_stats(
        dict(good, mystery=1))          # unexpected key flags
    assert rec_mod.validate_segment_stats(
        dict(good, per_kind={"transfer": {"segments": -1, "run_s": 0,
                                          "wait_s": 0}}))


# ------------------------------------------- classic offload, bit-exact
def test_classic_offload_serial_vs_overlap_bitexact():
    engines = {m: _linear_engine(mode=m) for m in ("off", "on")}
    x, y = _linear_batch()
    for step in range(4):
        losses = {}
        for mode, eng in engines.items():
            loss = eng(x, y)
            eng.backward(loss)
            eng.step()
            losses[mode] = float(loss)
        assert losses["off"] == losses["on"], (step, losses)
    for a, b in zip(_host_masters(engines["off"]),
                    _host_masters(engines["on"])):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_host_moments(engines["off"]),
                    _host_moments(engines["on"])):
        np.testing.assert_array_equal(a, b)
    # the overlap engine executed multi-segment plans (chunked d2h/adam
    # + upload/reshard), and both modes saw identical plan shapes
    snaps = {m: e.executor_snapshot() for m, e in engines.items()}
    assert snaps["on"]["last_plan_segments"] > 4
    assert snaps["on"]["last_plan_segments"] == \
        snaps["off"]["last_plan_segments"]
    assert snaps["on"]["mode"] == "overlap"
    assert snaps["off"]["mode"] == "serial"


def test_classic_offload_checkpoints_byte_identical(tmp_path):
    dirs = {}
    for mode in ("off", "on"):
        eng = _linear_engine(mode=mode)
        x, y = _linear_batch()
        for _ in range(2):
            loss = eng(x, y)
            eng.backward(loss)
            eng.step()
        d = tmp_path / mode
        eng.save_checkpoint(str(d), tag="t")
        dirs[mode] = d
    manifests = {}
    for mode, d in dirs.items():
        payload = json.load(open(os.path.join(str(d), "t",
                                              "manifest.json")))
        manifests[mode] = {name: rec["crc32"]
                           for name, rec in payload["files"].items()}
    assert manifests["off"] == manifests["on"]


def test_classic_offload_overlap_efficiency_reported(tmp_path):
    """The bespoke pre-executor classic path reported NO overlap
    efficiency; the lowered plan reports the constructed overlap in
    the unified SEGMENT_KEYS offload record."""
    eng = _linear_engine(mode="on", telemetry={
        "enabled": True, "output_path": str(tmp_path)})
    x, y = _linear_batch()
    for _ in range(2):
        loss = eng(x, y)
        eng.backward(loss)
        eng.step()
    snap = eng.telemetry_snapshot()["offload_last"]
    assert rec_mod.validate_segment_stats(snap) == [], snap
    assert snap["plan_segments"] > 4
    assert snap["overlap_efficiency"] is not None
    assert snap["overlap_efficiency"] > 0
    assert snap["per_kind"]["host"]["segments"] > 0
    assert snap["per_kind"]["transfer"]["segments"] > 0


def test_offload_overflow_skip_still_resets(tmp_path):
    """An overflowing step skips the plan entirely and resets the
    accumulators (the bespoke overflow semantics)."""
    eng = _linear_engine(mode="on", lr=5e-2)
    x, y = _linear_batch()
    loss = eng(x * np.float32(1e38), y * np.float32(1e38))
    eng.backward(loss)
    eng.step()
    assert eng.skipped_steps == 1
    assert eng.host_state["step"] == 0
    # and a sane step afterwards still works
    loss = eng(x, y)
    eng.backward(loss)
    eng.step()
    assert eng.host_state["step"] == 1


# ------------------------------------------------ streamed, bit-exact
def test_streamed_serial_vs_overlap_bitexact():
    engines = {m: _gpt_engine(mode=m, streamed=True)
               for m in ("off", "on")}
    assert len(engines["on"].stream_runner.groups) == GPT_CFG.n_layers
    ids = _gpt_ids()
    for step in range(3):
        losses = {}
        for mode, eng in engines.items():
            loss = eng(ids, ids.copy())
            eng.backward(loss)
            eng.step()
            losses[mode] = float(loss)
        assert losses["off"] == losses["on"], (step, losses)
    for a, b in zip(_host_masters(engines["off"]),
                    _host_masters(engines["on"])):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_host_moments(engines["off"]),
                    _host_moments(engines["on"])):
        np.testing.assert_array_equal(a, b)


def test_streamed_gas2_bitexact_across_modes():
    def run(mode):
        zero = {"stage": 3, "cpu_offload": True,
                "cpu_offload_params": True,
                "stage3_max_live_parameters": 1}
        eng, _, _, _ = deepspeed.initialize(
            model=gpt2.make_gpt2_model(config=GPT_CFG),
            config_params={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "bf16": {"enabled": True},
                "zero_optimization": zero,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "runtime": {"executor": mode},
                "steps_per_print": 10 ** 9,
            })
        ids = np.stack([_gpt_ids(0), _gpt_ids(1)])
        out = [float(eng.train_batch(batch=(ids, ids.copy())))
               for _ in range(2)]
        return out, _host_masters(eng)

    (loss_a, masters_a) = run("off")
    (loss_b, masters_b) = run("on")
    assert loss_a == loss_b
    for a, b in zip(masters_a, masters_b):
        np.testing.assert_array_equal(a, b)


# --------------------------------------------------- plan_of + audit
def test_plan_of_offload_topology_matches_execution():
    eng = _linear_engine(mode="on")
    plan = plan_for_engine(eng)
    assert plan.validate() == []
    assert plan.name == "offload_apply"
    names = {s.name for s in plan.segments}
    assert "upload_finish" in names and "reshard" in names
    # run one real step; the executed update-plan records must carry
    # exactly the abstract plan's nodes (plan construction and
    # execution share one topology builder)
    x, y = _linear_batch()
    loss = eng(x, y)
    eng.backward(loss)
    eng.step()
    # records were drained by the step boundary; run the apply again
    # via another step and intercept before the drain
    loss = eng(x, y)
    eng.backward(loss)
    eng._take_model_step()
    executed = {r.name for r in eng.plan_executor().drain_step_records()}
    assert executed == names


def test_plan_of_streamed_topology_matches_execution():
    eng = _gpt_engine(mode="on", streamed=True)
    ids = _gpt_ids()
    plan = plan_for_engine(eng)
    assert plan.validate() == []
    assert plan.name == "streamed_micro"
    names = {s.name for s in plan.segments}
    assert {"e_fwd", "h_grad", "e_bwd", "resolve", "loss"} <= names
    loss = eng(ids, ids.copy())     # one micro step, no boundary drain
    executed = {r.name for r in eng.plan_executor().drain_step_records()}
    assert executed == names
    assert np.isfinite(float(loss))
    eng.backward(loss)
    eng.step()


def test_ir_plan_of_is_the_executor_entry_point():
    from deepspeed_tpu.analysis.ir import plan_of
    eng = _linear_engine(mode="auto")
    plan = plan_of(eng)
    assert plan.name == "offload_apply" and plan.validate() == []
    with pytest.raises(ValueError):
        plan_of(_linear_engine(mode="auto", offload=False))


def test_audit_plan_reports_shape_and_catches_breakage(monkeypatch):
    from deepspeed_tpu.analysis import AnalysisReport
    from deepspeed_tpu.analysis.auditor import audit_plan
    eng = _linear_engine(mode="auto")
    report = AnalysisReport(job="t")
    audit_plan(eng, report)
    assert not report.findings
    assert any(name.startswith("plan/offload_apply")
               for name in report.programs)
    # a lowering bug (malformed plan) becomes an unsuppressable finding
    import deepspeed_tpu.runtime.executor as ex_mod
    broken = SegmentPlan("offload_apply",
                         [Segment(name="a", kind="host",
                                  deps=("missing",))])
    monkeypatch.setattr(ex_mod, "plan_for_engine",
                        lambda engine, family=None: broken)
    report2 = AnalysisReport(job="t2")
    audit_plan(eng, report2)
    assert report2.findings
    assert report2.findings[0].check == "plan_invalid"


def test_engine_audit_green_on_lowered_paths():
    eng = _gpt_engine(mode="on")
    ids = _gpt_ids()
    report = eng.audit(batch=(ids, ids.copy()))
    assert report.findings == [], [f.message for f in report.findings]
    assert any(name.startswith("plan/") for name in report.programs)


# ------------------------------------------------------- config gate
def test_runtime_executor_config_gate():
    assert _linear_engine(mode="off")._executor_mode == "serial"
    assert _linear_engine(mode="on")._executor_mode == "overlap"
    assert _linear_engine(mode="auto")._executor_mode == "overlap"
    with pytest.raises(DeepSpeedConfigError):
        _linear_engine(mode="sideways")


def test_runtime_section_unknown_key_validated(tmp_path):
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(None, param_dict={
            "train_batch_size": 8,
            "config_validation": "strict",
            "runtime": {"executor": "auto", "warp_drive": True}})


# ---------------------------------------------------------- DSL006
def test_dsl006_flags_scheduling_outside_executor(tmp_path):
    from deepspeed_tpu.analysis import astlint
    dirty = tmp_path / "deepspeed_tpu" / "runtime" / "zero"
    dirty.mkdir(parents=True)
    (dirty / "sneaky.py").write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "import jax\n"
        "def go(bufs, fn):\n"
        "    pool = ThreadPoolExecutor(max_workers=1)\n"
        "    bufs[0].copy_to_host_async()\n"
        "    jitted = jax.jit(fn, donate_argnums=(0,))\n"
        "    return pool, jitted\n")
    exec_dir = tmp_path / "deepspeed_tpu" / "runtime" / "executor"
    exec_dir.mkdir(parents=True)
    (exec_dir / "sched.py").write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "def pool():\n"
        "    return ThreadPoolExecutor(max_workers=1)\n")
    findings = astlint.lint_paths([str(tmp_path / "deepspeed_tpu")],
                                  base=str(tmp_path))
    dsl6 = sorted(k for k in findings if k.startswith("DSL006"))
    assert dsl6 == [
        "DSL006:deepspeed_tpu/runtime/zero/sneaky.py::go"], dsl6
    assert len(findings[dsl6[0]]) == 3      # pool + async copy + donate


def test_repo_lint_green_with_dsl006_baseline():
    from deepspeed_tpu.analysis import astlint
    repo = os.path.join(os.path.dirname(__file__), "..", "..")
    findings = astlint.lint_paths(
        [os.path.join(repo, "deepspeed_tpu")], base=repo)
    baseline = astlint.load_baseline(
        os.path.join(repo, "bin", "ds_lint_baseline.json"))
    new, _stale = astlint.diff_baseline(findings, baseline)
    assert new == [], [f.message for f in new]
    # the executor package itself must be DSL006-clean (it is the one
    # place scheduling is allowed — nothing there needs baselining)
    assert not any("runtime/executor" in k for k in findings
                   if k.startswith("DSL006"))


def test_dsl006_zero_sites_outside_executor():
    """PR 19 endpoint: the whole package carries ZERO step-scheduling
    sites outside runtime/executor/ — the DSL006 baseline is empty and
    must stay empty (a new occurrence fails the baseline diff above,
    this pins that the accepted set itself is zero)."""
    from deepspeed_tpu.analysis import astlint
    repo = os.path.join(os.path.dirname(__file__), "..", "..")
    findings = astlint.lint_paths(
        [os.path.join(repo, "deepspeed_tpu")], base=repo)
    dsl6 = sorted(k for k in findings if k.startswith("DSL006"))
    assert dsl6 == [], dsl6
    baseline = astlint.load_baseline(
        os.path.join(repo, "bin", "ds_lint_baseline.json"))
    assert not any(k.startswith("DSL006") for k in baseline), \
        "DSL006 baseline entries must stay deleted"


# ----------------------------------------------- pipe lowering, bit-exact
class _TanhLayer:
    def __init__(self, dim):
        self.dim = dim

    def init(self, rng):
        import jax
        w = jax.random.normal(rng, (self.dim, self.dim)) * 0.3
        return {"w": w, "b": jnp.zeros((self.dim,))}

    def apply(self, params, x):
        return jnp.tanh(x @ params["w"].astype(x.dtype) +
                        params["b"].astype(x.dtype))


def _pipe_engine(mode, gas=4, rewrites=None):
    from deepspeed_tpu.pipe import PipelineModule, LayerSpec

    def mse(out, labels):
        return jnp.mean((out.astype(jnp.float32) -
                         labels.astype(jnp.float32)) ** 2)

    net = PipelineModule(
        layers=[LayerSpec(_TanhLayer, 16) for _ in range(4)],
        num_stages=2, num_dp=4, loss_fn=mse)
    runtime = {"executor": mode}
    if rewrites is not None:
        runtime["executor_rewrites"] = rewrites
    engine, _, _, _ = deepspeed.initialize(
        model=net, config_params={
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": gas,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "runtime": runtime,
            "steps_per_print": 10 ** 9,
        })
    return engine


def _pipe_batches(gas=4, steps=3, seed=0):
    # micro batch 16 = 4 per gpu * 4 dp, matching test_pipe.py
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        x = rng.randn(gas, 16, 16).astype(np.float32)
        y = np.tanh(x @ (rng.randn(16, 16) * 0.3).astype(np.float32))
        out.append((x, y))
    return out


def test_pipe_serial_vs_overlap_bitexact():
    engines = {m: _pipe_engine(m) for m in ("off", "on")}
    batches = _pipe_batches()
    for step, (x, y) in enumerate(batches):
        losses = {m: float(e.train_batch(batch=(x, y)))
                  for m, e in engines.items()}
        assert losses["off"] == losses["on"], (step, losses)
    import jax
    for a, b in zip(
            jax.tree_util.tree_leaves(engines["off"].get_params()),
            jax.tree_util.tree_leaves(engines["on"].get_params())):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # eval rides the executor too
    x, y = batches[0]
    evals = {m: float(e.eval_batch(batch=(x, y)))
             for m, e in engines.items()}
    assert evals["off"] == evals["on"]
    snaps = {m: e.executor_snapshot() for m, e in engines.items()}
    assert snaps["on"]["mode"] == "overlap"
    assert snaps["off"]["mode"] == "serial"
    assert snaps["on"]["plans_executed"] >= len(batches) + 1


def test_plan_of_pipe_topology_matches_execution():
    eng = _pipe_engine("on")
    plan = plan_for_engine(eng)
    assert plan.name == "pipe_step" and plan.validate() == []
    names = {s.name for s in plan.segments}
    assert names == {"h2d/batch", "cycles", "loss"}
    x, y = _pipe_batches(steps=1)[0]
    eng.train_batch(batch=(x, y))
    executed = {r.name for r in eng.plan_executor().drain_step_records()}
    assert names <= executed
    # the priced plan carries the staged batch's real bytes
    from deepspeed_tpu.runtime.executor.pipe import build_pipe_plan
    priced = build_pipe_plan(eng, batch=(x, y))
    assert priced["h2d/batch"].nbytes == x.nbytes + y.nbytes
    # eval plan is the forward-only twin
    eval_plan = plan_for_engine(eng, family="pipe_eval_step")
    assert {s.name for s in eval_plan.segments} == \
        {"h2d/batch", "cycles_eval", "loss"}


def test_pipe_audit_plan_covered():
    from deepspeed_tpu.analysis import AnalysisReport
    from deepspeed_tpu.analysis.auditor import audit_plan
    eng = _pipe_engine("on")
    report = AnalysisReport(job="t")
    plan = audit_plan(eng, report)
    assert plan is not None and plan.name == "pipe_step"
    assert not report.findings
    assert any(name.startswith("plan/pipe_step")
               for name in report.programs)


# -------------------------------------------- serving lowering, bit-exact
def _serving_engine(mode, rewrites=None):
    model = gpt2.make_gpt2_model(config=GPT_CFG)
    runtime = {"executor": mode}
    if rewrites is not None:
        runtime["executor_rewrites"] = rewrites
    return deepspeed.init_inference(model=model, config={
        "inference": {"max_batch_size": 3, "prefill_buckets": [8, 16],
                      "dtype": "fp32", "greedy": True},
        "runtime": runtime,
    })


def _drain_scheduler(eng, prompts, max_new=6):
    from deepspeed_tpu.inference.scheduler import (
        ContinuousBatchingScheduler)
    sched = ContinuousBatchingScheduler(eng)
    uids = [sched.submit(list(p), max_new_tokens=max_new)
            for p in prompts]
    steps = 0
    while sched.has_work:
        sched.step()
        steps += 1
        assert steps < 200
    return [sched.results[uid] for uid in uids], sched


def test_serving_step_serial_vs_overlap_bitexact():
    prompts = [[1, 2, 3], [5, 6, 7, 8], [9, 10]]
    streams = {}
    scheds = {}
    for mode in ("off", "on"):
        streams[mode], scheds[mode] = _drain_scheduler(
            _serving_engine(mode), prompts)
    assert streams["off"] == streams["on"]
    # every step executed as a serving_step plan on the engine executor
    snap = scheds["on"].engine.executor_snapshot()
    assert snap["mode"] == "overlap"
    assert snap["plans_executed"] == scheds["on"].steps
    assert snap["last_plan_segments"] == 4
    assert scheds["off"].engine.executor_snapshot()["mode"] == "serial"


def test_plan_of_serving_topology():
    eng = _serving_engine("on")
    plan = plan_for_engine(eng)
    assert plan.name == "serving_step" and plan.validate() == []
    assert [s.name for s in plan.segments] == \
        ["admit", "prefill", "decode", "retire"]
    # the auditor covers the serving plan through the same entry point
    from deepspeed_tpu.analysis import AnalysisReport
    from deepspeed_tpu.analysis.auditor import audit_plan
    report = AnalysisReport(job="s")
    assert audit_plan(eng, report) is not None
    assert not report.findings
    assert any(name.startswith("plan/serving_step")
               for name in report.programs)


# ------------------------------------------------- rewrite pass matrix
def _seg(name, kind="compute", deps=(), **kw):
    return Segment(name=name, kind=kind, deps=deps, **kw)


def _hoist_fixture():
    """compute a -> compute b -> async transfer t(deps a) -> compute c
    (deps b, t): t can hoist to right after a."""
    plan = SegmentPlan("fix")
    plan.add(_seg("a"))
    plan.add(_seg("b", deps=("a",)))
    plan.add(_seg("t", kind="transfer", deps=("a",), async_ok=True,
                  nbytes=1024))
    plan.add(_seg("c", deps=("b", "t")))
    return plan


def test_hoist_moves_async_segment_earliest():
    from deepspeed_tpu.runtime.executor.rewrite import hoist_pass
    plan = _hoist_fixture()
    out, moved, predicted = hoist_pass(plan, max_live_bytes=1 << 20)
    assert moved == 1 and predicted > 0
    assert [s.name for s in out.segments] == ["a", "t", "b", "c"]
    assert out.validate() == []
    # the canonical plan is untouched
    assert [s.name for s in plan.segments] == ["a", "b", "t", "c"]


def test_hoist_refuses_to_cross_dependency():
    from deepspeed_tpu.runtime.executor.rewrite import hoist_pass
    plan = SegmentPlan("fix")
    plan.add(_seg("a"))
    plan.add(_seg("b", deps=("a",)))
    # t depends on b: earliest legal slot is where it already is
    plan.add(_seg("t", kind="transfer", deps=("b",), async_ok=True))
    out, moved, _ = hoist_pass(plan, max_live_bytes=1 << 30)
    assert moved == 0 and out is plan


def test_hoist_never_reorders_collectives():
    from deepspeed_tpu.runtime.executor.rewrite import hoist_pass
    plan = SegmentPlan("fix")
    plan.add(_seg("a"))
    plan.add(_seg("ar1", kind="collective", deps=("a",)))
    plan.add(_seg("b", deps=("a",)))
    # ar2 could hoist past ar1 by deps alone — rendezvous order forbids
    plan.add(_seg("ar2", kind="collective", deps=("a",), async_ok=True))
    plan.add(_seg("c", deps=("ar1", "ar2", "b")))
    out, moved, _ = hoist_pass(plan, max_live_bytes=1 << 30)
    names = [s.name for s in out.segments]
    assert names.index("ar1") < names.index("ar2")
    if moved:                      # may still hoist past plain compute b
        assert names == ["a", "ar1", "ar2", "b", "c"]


def test_hoist_respects_live_bytes_budget():
    from deepspeed_tpu.runtime.executor.rewrite import hoist_pass
    plan = _hoist_fixture()
    # budget below the transfer's 1024B pins it in place
    out, moved, _ = hoist_pass(plan, max_live_bytes=512)
    assert moved == 0 and out is plan


def test_fuse_merges_sole_consumer_transfer():
    from deepspeed_tpu.runtime.executor.rewrite import fuse_pass
    plan = SegmentPlan("fix")
    plan.add(_seg("t", kind="transfer", run=lambda env: 21, nbytes=8))
    plan.add(_seg("c", deps=("t",), run=lambda env: env["t"] * 2))
    out, fused = fuse_pass(plan)
    assert fused == 1
    assert [s.name for s in out.segments] == ["c"]
    assert out["c"].nbytes == 8
    env = {}
    assert out["c"].run(env) == 42
    # canonical plan unmutated; fused plan still validates
    assert len(plan) == 2 and out.validate() == []


def test_fuse_refuses_keep_result_and_multi_consumer():
    from deepspeed_tpu.runtime.executor.rewrite import fuse_pass
    keep = SegmentPlan("fix")
    keep.add(_seg("t", kind="transfer", keep_result=True))
    keep.add(_seg("c", deps=("t",)))
    assert fuse_pass(keep)[1] == 0
    multi = SegmentPlan("fix")
    multi.add(_seg("t", kind="transfer"))
    multi.add(_seg("c1", deps=("t",)))
    multi.add(_seg("c2", deps=("t",)))
    assert fuse_pass(multi)[1] == 0
    gap = SegmentPlan("fix")     # non-adjacent producer/consumer
    gap.add(_seg("t", kind="transfer"))
    gap.add(_seg("x"))
    gap.add(_seg("c", deps=("t",)))
    assert fuse_pass(gap)[1] == 0


def test_widen_fires_only_on_measured_waits():
    from deepspeed_tpu.runtime.executor.rewrite import widen_pass

    class _Exec:
        windows = {"d2h": 1}
        plans_total = 1

        def __init__(self, waits):
            self._w = waits

        def measured_totals(self):
            return {}, 1.0, self._w

    plan = SegmentPlan("fix")
    for i in range(4):
        plan.add(_seg("t%d" % i, kind="transfer", async_ok=True))
    # calibration phase: no measured waits -> nothing widens
    out, widened, _ = widen_pass(plan, _Exec(0.0), max_window=8)
    assert widened == 0 and out is plan
    # dominated by exposed wait -> pool window rises to segment count
    out, widened, predicted = widen_pass(plan, _Exec(0.5), max_window=8)
    assert widened == 1 and predicted > 0
    assert out.windows["d2h"] == 4
    assert plan.windows == {}    # canonical untouched


def test_apply_rewrites_respects_pass_gating():
    from deepspeed_tpu.runtime.executor.rewrite import apply_rewrites
    plan = _hoist_fixture()
    out, stats = apply_rewrites(plan, {"enabled": False})
    assert out is plan and stats == []
    # fuse alone: t is adjacent to its sole consumer c -> merges
    out, stats = apply_rewrites(
        plan, {"enabled": True, "passes": ("fuse",)})
    assert [s["name"] for s in stats] == ["fuse"]
    assert [s.name for s in out.segments] == ["a", "b", "c"]
    # hoist runs BEFORE fuse, so t moves away from c and keeps overlap
    out, stats = apply_rewrites(
        plan, {"enabled": True, "passes": ("hoist", "fuse"),
               "hoist_max_live_bytes": 1 << 20})
    assert [s["name"] for s in stats] == ["hoist"]
    assert [s.name for s in out.segments] == ["a", "t", "b", "c"]
    out, stats = apply_rewrites(
        plan, {"enabled": True, "passes": ("hoist",),
               "hoist_max_live_bytes": 1 << 20})
    assert [s["name"] for s in stats] == ["hoist"]
    assert stats[0]["segments_moved"] == 1
    assert sorted(stats[0]) == sorted(
        ["name", "segments_moved", "predicted_exposed_wait_delta_s"])
    assert out.validate() == []


def test_executor_calibrates_then_rewrites_bitexact():
    """First execution of a plan name runs UNREWRITTEN (the measured
    baseline); later executions run the rewritten plan and must produce
    the same values."""
    calls = []

    def build():
        # t sits AFTER b but only deps a: hoist moves it up one slot
        plan = SegmentPlan("p")
        plan.add(_seg("a", run=lambda env: calls.append("a") or 3.0))
        plan.add(_seg("b", run=lambda env: calls.append("b") or 5.0))
        plan.add(_seg("t", kind="transfer", deps=("a",), async_ok=True,
                      nbytes=64, run=lambda env: env["a"] * 2))
        plan.add(_seg("out", deps=("t", "b"), keep_result=True,
                      run=lambda env: env["t"] + env["b"]))
        return plan

    rewrites = {"enabled": True, "passes": ("hoist", "fuse", "widen"),
                "max_window": 8, "hoist_max_live_bytes": 1 << 28}
    ex = PlanExecutor(mode="overlap", rewrites=rewrites)
    vals = [ex.execute(build())["out"] for _ in range(3)]
    assert vals == [11.0, 11.0, 11.0]
    snap = ex.rewrite_snapshot()
    assert snap is not None and snap["enabled"] is True
    assert snap["segments_moved"] >= 1
    assert [p["name"] for p in snap["passes"]] == \
        sorted(p["name"] for p in snap["passes"])
    assert rec_mod.validate_rewrite_stats(snap) == []
    # rewrites land in the lifetime snapshot the bench records publish
    life = ex.lifetime_snapshot()
    assert life["rewrites"] == snap
    # a rewrites-off executor reports no section at all
    off = PlanExecutor(mode="overlap")
    off.execute(build())
    assert off.rewrite_snapshot() is None
    assert "rewrites" not in off.lifetime_snapshot()


def test_rewritten_plan_must_still_validate():
    from deepspeed_tpu.runtime.executor import rewrite as rw

    def bad_pass(plan, *a, **kw):
        broken = SegmentPlan(plan.name)
        broken.add(_seg("z", deps=("missing",)))
        return broken, 1, 0.0

    ex = PlanExecutor(mode="overlap",
                      rewrites={"enabled": True, "passes": ("hoist",),
                                "hoist_max_live_bytes": 1 << 28})
    plan = SegmentPlan("p")
    plan.add(_seg("a", run=lambda env: 1, keep_result=True))
    ex.execute(plan)             # calibration run
    orig = rw.hoist_pass
    rw.hoist_pass = bad_pass
    try:
        with pytest.raises(PlanError):
            ex.execute(plan)
    finally:
        rw.hoist_pass = orig


def test_rewrites_never_touch_abstract_plans():
    """plan_for_engine output (what the auditor fingerprints) is built
    fresh from topology — rewrite config on the engine must not change
    it."""
    for rewrites in (None, {"enabled": True,
                            "passes": ["hoist", "fuse", "widen"]}):
        eng = _pipe_engine("on", rewrites=rewrites)
        plan = plan_for_engine(eng)
        assert [s.name for s in plan.segments] == \
            ["h2d/batch", "cycles", "loss"]
        assert plan.windows == {}


def test_engine_rewrites_bitexact_vs_serial():
    """The whole point: rewrites change WHEN, never WHAT. A rewritten
    overlap engine matches the plain serial engine bit for bit."""
    engine, _, _, _ = deepspeed.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((8, 4))}),
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2, "cpu_offload": True,
                                  "sub_group_size": 16},
            "runtime": {"executor": "on", "executor_rewrites": {
                "passes": ["hoist", "fuse", "widen"]}},
            "steps_per_print": 10 ** 9,
        })
    eng_rw = engine
    eng_off = _linear_engine("off")
    rng = np.random.RandomState(7)
    for _ in range(4):
        x = rng.randn(8, 8).astype(np.float32)
        y = rng.randn(8, 4).astype(np.float32)
        l1 = float(eng_rw(x, y)); eng_rw.backward(l1); eng_rw.step()
        l2 = float(eng_off(x, y)); eng_off.backward(l2); eng_off.step()
        assert l1 == l2
    for a, b in zip(_host_masters(eng_rw), _host_masters(eng_off)):
        np.testing.assert_array_equal(a, b)
    snap = eng_rw.plan_executor().rewrite_snapshot()
    assert snap is not None and snap["segments_moved"] >= 1
    assert rec_mod.validate_rewrite_stats(snap) == []


# -------------------------------------------- config + schema validation
def _rewrites_cfg(val):
    from deepspeed_tpu.runtime.config import get_runtime_executor_rewrites
    return get_runtime_executor_rewrites({"runtime":
                                          {"executor_rewrites": val}})


def test_executor_rewrites_config_matrix():
    assert _rewrites_cfg(False)["enabled"] is False
    on = _rewrites_cfg(True)
    assert on["enabled"] is True
    assert set(on["passes"]) == {"hoist", "widen", "fuse"}
    assert on["max_window"] == 8
    assert on["hoist_max_live_bytes"] == 1 << 28
    picked = _rewrites_cfg({"passes": ["hoist"], "max_window": 2,
                            "hoist_max_live_bytes": 4096})
    assert picked == {"enabled": True, "passes": ("hoist",),
                      "max_window": 2, "hoist_max_live_bytes": 4096}
    for bad in ({"passes": ["hoisted"]}, {"window": 3},
                {"max_window": 0}, {"max_window": True},
                {"hoist_max_live_bytes": 0}, {"enabled": "yes"},
                {"passes": "hoist"}, "on", 3):
        with pytest.raises(DeepSpeedConfigError):
            _rewrites_cfg(bad)
    # default when the section is absent: disabled
    from deepspeed_tpu.runtime.config import get_runtime_executor_rewrites
    assert get_runtime_executor_rewrites({})["enabled"] is False


def test_rewrite_keys_pinned_across_copies():
    """rewrite.py is canonical; telemetry/record.py re-exports it and
    bin/check_bench_schema.py carries a stdlib-only twin."""
    from deepspeed_tpu.runtime.executor import rewrite as rw
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "..", "bin",
                        "check_bench_schema.py")
    spec = importlib.util.spec_from_file_location("_cbs", path)
    cbs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cbs)
    assert rw.REWRITE_KEYS == rec_mod.REWRITE_KEYS == cbs.REWRITE_KEYS
    assert rw.REWRITE_PASS_KEYS == rec_mod.REWRITE_PASS_KEYS == \
        cbs.REWRITE_PASS_KEYS


def test_validate_rewrite_stats_rejects_malformed():
    good = {"enabled": True,
            "passes": [{"name": "hoist", "segments_moved": 2,
                        "predicted_exposed_wait_delta_s": 0.001}],
            "segments_moved": 2,
            "predicted_exposed_wait_delta_s": 0.001,
            "measured_exposed_wait_delta_s": None}
    assert rec_mod.validate_rewrite_stats(good) == []
    bad_keys = dict(good); bad_keys.pop("segments_moved")
    assert rec_mod.validate_rewrite_stats(bad_keys)
    bad_pass = dict(good, passes=[{"name": "hoist"}])
    assert rec_mod.validate_rewrite_stats(bad_pass)
    bad_moved = dict(good, segments_moved=-1)
    assert rec_mod.validate_rewrite_stats(bad_moved)
    bad_delta = dict(good, measured_exposed_wait_delta_s="fast")
    assert rec_mod.validate_rewrite_stats(bad_delta)
    # and the stats flow through validate_segment_stats via "rewrites"
    seg = {"plan_segments": 3,
           "per_kind": {"transfer": {"segments": 2, "run_s": 0.1,
                                     "wait_s": 0.0}},
           "overlap_efficiency": 0.8, "upload_batches": 1,
           "upload_elems": 10, "upload_bytes": 40, "bucket_elems": 8,
           "bucket_occupancy": None, "work_chunks": 4}
    seg["rewrites"] = bad_moved
    assert rec_mod.validate_segment_stats(seg)
    seg["rewrites"] = good
    assert rec_mod.validate_segment_stats(seg) == []
