"""Every parsed ZeRO-3 key changes runtime behavior or warns loudly.

One behavior-change test per key resurrected by the beyond-HBM PR
(ISSUE 4 acceptance: no silent zero_optimization config no-ops):

  stage3_max_live_parameters -> persistence demotion on the stage-3
    gather path (and streamed layer-group sizing, test_stream_offload);
  sub_group_size             -> offload shard-pipeline chunk count;
  stage3_prefetch_bucket_size-> coalesced-H2D transfer batch count;
  stage3_max_reuse_distance / cpu_offload_use_pin_memory -> loud warning
    (raise under zero_optimization.strict);
  cpu_offload_params         -> rejected below stage 3.
"""
import numpy as np
import pytest

import jax

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.zero.partition import ZeroShardingPlan
from deepspeed_tpu.parallel.topology import build_mesh, DATA_AXIS


CFG = gpt2.GPT2Config(vocab_size=256, max_seq_len=64, n_layers=2,
                      n_heads=2, d_model=64, use_flash_attention=False,
                      remat=False, loss_chunk=0)


def _engine(zero_extra, gas=1):
    zero = {"stage": 3, "cpu_offload": True}
    zero.update(zero_extra)
    engine, _, _, _ = deepspeed.initialize(
        model=gpt2.make_gpt2_model(config=CFG),
        config_params={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": gas,
            "bf16": {"enabled": True},
            "zero_optimization": zero,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 10 ** 9,
        })
    return engine


def _batch(engine):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, CFG.vocab_size,
                      size=(engine.train_batch_size(),
                            CFG.max_seq_len)).astype(np.int32)
    return ids, ids.copy()


def _one_step(engine):
    ids, labels = _batch(engine)
    loss = engine(ids, labels)
    engine.backward(loss)
    engine.step()
    return float(loss)


# --------------------------------------------- stage3_max_live_parameters
def test_live_budget_demotes_persistent_leaves():
    """A budget below the persistent set's size forces below-threshold
    leaves to data-shard — the observable live-HBM effect."""
    mesh = build_mesh(data=jax.device_count())
    params = gpt2.init_params(CFG, seed=0)

    free = ZeroShardingPlan(mesh, stage=3,
                            param_persistence_threshold=10 ** 9)
    free.configure_live_budget(params)   # budget None: no demotion
    assert not free._demoted
    assert not free.param_is_data_sharded("wte", np.shape(params["wte"]))

    tight = ZeroShardingPlan(mesh, stage=3,
                             param_persistence_threshold=10 ** 9,
                             max_live_parameters=50_000)
    persistent, demoted = tight.configure_live_budget(params)
    assert demoted, "tight budget must demote persistent leaves"
    assert persistent <= 50_000 or persistent is not None
    # the demoted leaf really shards now
    assert any(tight.param_is_data_sharded(p, np.shape(params["wte"]))
               for p in demoted if p == "wte") or "wte" in demoted


def test_live_budget_changes_engine_sharding():
    free = _engine({"stage3_max_live_parameters": 10 ** 9,
                    "stage3_param_persistence_threshold": 10 ** 9})
    tight = _engine({"stage3_max_live_parameters": 50_000,
                     "stage3_param_persistence_threshold": 10 ** 9})
    free_spec = free.state["params"]["wte"].sharding.spec
    tight_spec = tight.state["params"]["wte"].sharding.spec
    assert DATA_AXIS not in str(free_spec)
    assert DATA_AXIS in str(tight_spec), \
        "budget-demoted wte must shard over the data axis"
    # both still train
    assert np.isfinite(_one_step(tight))


# ----------------------------------------------------------- sub_group_size
def test_sub_group_size_chunks_offload_pipeline():
    default = _engine({})
    tiny = _engine({"sub_group_size": 256})
    l_def = _one_step(default)
    l_tiny = _one_step(tiny)
    assert tiny.offload_work_chunks > default.offload_work_chunks, \
        (tiny.offload_work_chunks, default.offload_work_chunks)
    # chunking changes the pipeline granularity, not the math
    assert l_tiny == l_def
    m_def = default.get_master_params()
    m_tiny = tiny.get_master_params()
    for a, b in zip(jax.tree_util.tree_leaves(m_def),
                    jax.tree_util.tree_leaves(m_tiny)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------ stage3_prefetch_bucket_size
def test_prefetch_bucket_size_batches_h2d():
    coalesced = _engine({"stage3_prefetch_bucket_size": 10 ** 9})
    scattered = _engine({"stage3_prefetch_bucket_size": 1})
    l_c = _one_step(coalesced)
    l_s = _one_step(scattered)
    assert scattered.h2d_batches > coalesced.h2d_batches, \
        (scattered.h2d_batches, coalesced.h2d_batches)
    assert l_c == l_s     # transfer batching is value-preserving


# ------------------------------------------------- unimplementable keys
class _Capture:
    """The repo logger doesn't propagate to root (caplog can't see it);
    capture by temporary handler."""

    def __enter__(self):
        import logging
        from deepspeed_tpu.utils.logging import logger as ds_logger
        self._logger = ds_logger
        self.records = []
        self._handler = logging.Handler()
        self._handler.emit = self.records.append
        ds_logger.addHandler(self._handler)
        return self

    def __exit__(self, *exc):
        self._logger.removeHandler(self._handler)
        return False

    def messages(self):
        return [r.getMessage() for r in self.records]


def test_max_reuse_distance_warns():
    with _Capture() as cap:
        _engine({"stage3_max_reuse_distance": 123})
    assert any("stage3_max_reuse_distance" in m for m in cap.messages())


def test_max_reuse_distance_raises_under_strict():
    with pytest.raises(ValueError, match="stage3_max_reuse_distance"):
        _engine({"stage3_max_reuse_distance": 123, "strict": True})


def test_pin_memory_warns_and_strict_raises():
    with _Capture() as cap:
        _engine({"cpu_offload_use_pin_memory": True})
    assert any("cpu_offload_use_pin_memory" in m for m in cap.messages())
    with pytest.raises(ValueError, match="cpu_offload_use_pin_memory"):
        _engine({"cpu_offload_use_pin_memory": True, "strict": True})


def test_strict_mode_clean_config_builds():
    engine = _engine({"strict": True})
    assert np.isfinite(_one_step(engine))


# ------------------------------------------------------ cpu_offload_params
def test_params_offload_requires_stage3():
    with pytest.raises(ValueError, match="cpu_offload_params"):
        deepspeed.initialize(
            model=gpt2.make_gpt2_model(config=CFG),
            config_params={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2, "cpu_offload": True,
                                      "cpu_offload_params": True},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            })
