"""FP16_Optimizer standalone wrapper tests (reference tests/unit/test_fp16).
The engine path is covered in test_engine; this locks the direct-use API."""
import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
from deepspeed_tpu.runtime.fp16.fused_optimizer import (FP16_Optimizer,
                                                        FP16_UnfusedOptimizer)


def _loss(params, x, y):
    return jnp.mean((x @ params["w"] - y) ** 2)


def test_converges_with_dynamic_scale():
    opt = FP16_Optimizer(FusedAdam(lr=5e-2), dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 2 ** 8})
    rs = np.random.RandomState(0)
    W = rs.randn(16, 4).astype(np.float32)
    x = jnp.asarray(rs.randn(32, 16).astype(np.float32))
    y = x @ jnp.asarray(W)
    params = {"w": jnp.zeros((16, 4), dtype=jnp.bfloat16)}
    losses = []
    for _ in range(40):
        def scaled_loss(p):
            return opt.scale_loss(_loss(p, x, y))
        grads = jax.grad(scaled_loss)(params)
        params, overflow = opt.step(grads, params)
        losses.append(float(_loss(params, x, y)))
    assert losses[-1] < 0.1 * losses[0], losses


def test_overflow_skips_and_halves_scale():
    opt = FP16_Optimizer(FusedAdam(lr=1e-2), dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 2 ** 8})
    params = {"w": jnp.ones((4, 4), dtype=jnp.bfloat16)}
    opt.initialize_state(params)
    bad = {"w": jnp.full((4, 4), jnp.inf, dtype=jnp.float32)}
    new_params, overflow = opt.step(bad, params)
    assert overflow
    assert opt.loss_scale == 2 ** 7
    np.testing.assert_allclose(np.asarray(new_params["w"], dtype=np.float32),
                               np.asarray(params["w"], dtype=np.float32))


def test_static_scale_and_clip():
    opt = FP16_Optimizer(FusedAdam(lr=1e-2), static_loss_scale=64.0,
                         clip_grad=1.0)
    assert opt.loss_scale == 64.0
    loss = opt.scale_loss(jnp.asarray(2.0))
    assert float(loss) == 128.0


def test_state_dict_roundtrip():
    opt = FP16_Optimizer(FusedAdam(lr=1e-2), dynamic_loss_scale=True)
    params = {"w": jnp.ones((4, 2), dtype=jnp.bfloat16)}
    grads = {"w": jnp.ones((4, 2), dtype=jnp.float32)}
    opt.step(grads, params)
    sd = opt.state_dict()
    opt2 = FP16_Optimizer(FusedAdam(lr=1e-2), dynamic_loss_scale=True)
    opt2.initialize_state(params)
    opt2.load_state_dict(sd)
    assert opt2.loss_scale == opt.loss_scale
    np.testing.assert_allclose(np.asarray(opt2._master["w"]),
                               np.asarray(opt._master["w"]))


def test_unfused_is_fused_and_takes_lamb():
    assert FP16_UnfusedOptimizer is FP16_Optimizer
    opt = FP16_UnfusedOptimizer(FusedLamb(lr=1e-2))
    params = {"w": jnp.ones((8, 4), dtype=jnp.bfloat16)}
    grads = {"w": jnp.full((8, 4), 0.1, dtype=jnp.float32)}
    new_params, overflow = opt.step(grads, params)
    assert not overflow
    assert not np.allclose(np.asarray(new_params["w"], dtype=np.float32),
                           np.asarray(params["w"], dtype=np.float32))
