"""Native indexed dataset + prefetch loader (csrc/ds_dataio.cpp).

Mirrors the reference's data tests (tests/unit/test_data.py) for the
mmap'd token-file path; every check runs against BOTH the native reader
and the numpy fallback so their semantics cannot drift."""
import numpy as np
import pytest

from deepspeed_tpu.runtime.data import (IndexedDataset,
                                        IndexedDatasetBuilder,
                                        NativePrefetchLoader)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    rng = np.random.RandomState(0)
    docs = [rng.randint(0, 50000, size=rng.randint(3, 300)).astype(np.int32)
            for _ in range(37)]
    prefix = str(tmp_path_factory.mktemp("data") / "corpus")
    b = IndexedDatasetBuilder(prefix)
    for d in docs:
        b.add_doc(d)
    b.finalize()
    return prefix, docs


@pytest.mark.parametrize("use_native", [True, False])
def test_doc_roundtrip(corpus, use_native):
    prefix, docs = corpus
    ds = IndexedDataset(prefix, use_native=use_native)
    if use_native and ds._lib is None:
        pytest.skip("native op unavailable")
    assert len(ds) == len(docs)
    assert ds.num_tokens == sum(d.size for d in docs)
    for i in [0, 1, 17, len(docs) - 1]:
        np.testing.assert_array_equal(ds[i], docs[i])
    ds.close()


@pytest.mark.parametrize("use_native", [True, False])
def test_batch_windows(corpus, use_native):
    prefix, docs = corpus
    ds = IndexedDataset(prefix, use_native=use_native)
    if use_native and ds._lib is None:
        pytest.skip("native op unavailable")
    stream = np.concatenate(docs)
    seq = 64
    n = ds.num_samples(seq)
    assert n == stream.size // seq
    idx = [0, 3, n - 1, 1]
    got = ds.batch(idx, seq)
    for r, s in enumerate(idx):
        np.testing.assert_array_equal(got[r], stream[s * seq:(s + 1) * seq])
    ds.close()


def test_native_matches_numpy(corpus):
    prefix, _ = corpus
    nat = IndexedDataset(prefix, use_native=True)
    if nat._lib is None:
        pytest.skip("native op unavailable")
    ref = IndexedDataset(prefix, use_native=False)
    idx = np.arange(min(8, nat.num_samples(32)))
    np.testing.assert_array_equal(nat.batch(idx, 32), ref.batch(idx, 32))
    nat.close()


@pytest.mark.parametrize("use_native", [True, False])
def test_prefetch_loader(corpus, use_native):
    prefix, _ = corpus
    ds = IndexedDataset(prefix, use_native=use_native)
    if use_native and ds._lib is None:
        pytest.skip("native op unavailable")
    loader = NativePrefetchLoader(ds, batch_size=4, seq_len=32)
    seen = []
    for _ in range(6):
        b = next(loader)
        assert b.shape == (4, 32) and b.dtype == np.int32
        seen.append(b.copy())
    # shuffled order: successive batches differ
    assert not np.array_equal(seen[0], seen[1])
    # deterministic order: both paths produce the same schedule
    ds2 = IndexedDataset(prefix, use_native=False)
    loader2 = NativePrefetchLoader(ds2, batch_size=4, seq_len=32)
    for b in seen:
        np.testing.assert_array_equal(b, next(loader2))
    loader.close()
    loader2.close()
    ds.close()
    ds2.close()
    with pytest.raises(RuntimeError):
        next(loader)


@pytest.mark.parametrize("use_native", [True, False])
def test_close_while_blocked_in_next(corpus, use_native):
    """close() while a consumer is blocked in next() must raise in the
    consumer, not deadlock (ds_dataio.cpp stop-aware wait + drain; numpy
    fallback _closed check)."""
    import threading
    import time

    prefix, _ = corpus
    ds = IndexedDataset(prefix, use_native=use_native)
    if use_native and ds._lib is None:
        pytest.skip("native op unavailable")
    loader = NativePrefetchLoader(ds, batch_size=4, seq_len=32)
    outcome = []

    def consumer():
        try:
            deadline = time.time() + 10
            while time.time() < deadline:
                next(loader)
            outcome.append("never stopped")
        except RuntimeError:
            outcome.append("raised")

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.2)
    # dataset-first close on BOTH paths: the numpy fallback must also
    # surface a closed dataset as a raise in the consumer, not a hang
    ds.close()
    loader.close()
    t.join(timeout=10)
    assert not t.is_alive(), "consumer deadlocked after close()"
    assert outcome == ["raised"], outcome


def test_epoch_dependent_shuffle(corpus):
    """Each epoch is a bijection over the samples and consecutive epochs
    traverse different permutations (epoch-mixed affine map)."""
    prefix, _ = corpus
    ds = IndexedDataset(prefix, use_native=False)
    loader = NativePrefetchLoader(ds, batch_size=1, seq_len=32)
    n = loader.n_samples
    loader.close()              # stop the producer before poking internals
    loader.batch_size = n       # one call = one full epoch of indices
    e0 = loader._indices(0)
    e1 = loader._indices(n)
    assert sorted(e0.tolist()) == list(range(n))
    assert sorted(e1.tolist()) == list(range(n))
    assert not np.array_equal(e0, e1)
    loader.close()
    ds.close()


def test_native_numpy_shuffle_parity_across_epochs(corpus):
    """The duplicated multiplier tables (kMult in csrc/ds_dataio.cpp and
    _SHUFFLE_MULTS in indexed_dataset.py) must stay in lockstep — drive
    BOTH loaders through several epoch boundaries and compare every batch
    (epoch >= 1 exercises mult[1], mult[2] and the epoch-mixed constant)."""
    prefix, _ = corpus
    nat_ds = IndexedDataset(prefix, use_native=True)
    if nat_ds._lib is None:
        nat_ds.close()
        pytest.skip("native op unavailable")
    np_ds = IndexedDataset(prefix, use_native=False)
    nat = NativePrefetchLoader(nat_ds, batch_size=4, seq_len=32)
    ref = NativePrefetchLoader(np_ds, batch_size=4, seq_len=32)
    n = nat.n_samples
    batches_for_3_epochs = (3 * n) // 4 + 2
    for i in range(batches_for_3_epochs):
        np.testing.assert_array_equal(
            next(nat), next(ref),
            err_msg="native/numpy order diverged at batch {} "
                    "(~epoch {})".format(i, (i * 4) // n))
    nat.close()
    ref.close()
    nat_ds.close()
    np_ds.close()
