"""Pipeline schedule logic (mirrors reference test_pipe_schedule.py)."""
import numpy as np
import pytest

from deepspeed_tpu.runtime.pipe import schedule as sch


def _cmds_of(sched):
    return [step for step in sched.steps()]


def test_inference_schedule_basics():
    s = sch.InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
    steps = _cmds_of(s)
    assert len(steps) == 4 + 2 - 1
    # first stage loads, never recvs activations
    for cmds in steps:
        assert not any(isinstance(c, sch.RecvActivation) for c in cmds)
    loads = [c for cmds in steps for c in cmds
             if isinstance(c, sch.LoadMicroBatch)]
    assert len(loads) == 4


def test_inference_schedule_last_stage():
    s = sch.InferenceSchedule(micro_batches=4, stages=2, stage_id=1)
    steps = _cmds_of(s)
    recvs = [c for cmds in steps for c in cmds
             if isinstance(c, sch.RecvActivation)]
    fwds = [c for cmds in steps for c in cmds
            if isinstance(c, sch.ForwardPass)]
    sends = [c for cmds in steps for c in cmds
             if isinstance(c, sch.SendActivation)]
    assert len(recvs) == 4 and len(fwds) == 4 and len(sends) == 0


@pytest.mark.parametrize("micro_batches,stages", [(4, 2), (8, 4), (3, 3),
                                                  (1, 2)])
def test_train_schedule_counts(micro_batches, stages):
    for stage_id in range(stages):
        s = sch.TrainSchedule(micro_batches=micro_batches, stages=stages,
                              stage_id=stage_id)
        steps = _cmds_of(s)
        assert len(steps) == 2 * (micro_batches + stages - 1)
        fwds = [c for cmds in steps for c in cmds
                if isinstance(c, sch.ForwardPass)]
        bwds = [c for cmds in steps for c in cmds
                if isinstance(c, sch.BackwardPass)]
        assert len(fwds) == micro_batches
        assert len(bwds) == micro_batches
        # exactly one optimizer step at the very end
        opts = [c for cmds in steps for c in cmds
                if isinstance(c, sch.OptimizerStep)]
        assert len(opts) == 1
        assert any(isinstance(c, sch.OptimizerStep) for c in steps[-1])


def test_train_schedule_fwd_before_bwd():
    """Each microbatch's forward precedes its backward on every stage."""
    for stage_id in range(4):
        s = sch.TrainSchedule(micro_batches=8, stages=4, stage_id=stage_id)
        seen_fwd = {}
        for t, cmds in enumerate(s.steps()):
            for c in cmds:
                if isinstance(c, sch.ForwardPass):
                    seen_fwd.setdefault(c.buffer_id, t)
                if isinstance(c, sch.BackwardPass):
                    assert c.buffer_id in seen_fwd


def test_train_schedule_buffer_count():
    s = sch.TrainSchedule(micro_batches=8, stages=4, stage_id=0)
    assert s.num_pipe_buffers() == min(4 - 0 + 1, 8)
    s = sch.TrainSchedule(micro_batches=1, stages=4, stage_id=0)
    assert s.num_pipe_buffers() == 2


def test_send_recv_pairing():
    """Stage i's SendActivation count equals stage i+1's RecvActivation."""
    M, S = 6, 3
    sends = []
    recvs = []
    for sid in range(S):
        s = sch.TrainSchedule(micro_batches=M, stages=S, stage_id=sid)
        cmds = [c for step in s.steps() for c in step]
        sends.append(len([c for c in cmds
                          if isinstance(c, sch.SendActivation)]))
        recvs.append(len([c for c in cmds
                          if isinstance(c, sch.RecvActivation)]))
    assert sends[:-1] == recvs[1:]
    assert sends[-1] == 0 and recvs[0] == 0


@pytest.mark.parametrize("M,S", [(4, 2), (8, 4), (3, 3), (1, 2), (16, 4)])
def test_uniform_train_tables_alignment(M, S):
    """The executed 1F1B tables satisfy the SPMD executor's contract:
    activations/grads ride exactly one ppermute hop per cycle, every
    microbatch forwards then backwards exactly once per stage, and
    in-flight activations per stage stay at the num_pipe_buffers bound —
    independent of micro_batches."""
    import numpy as np
    fwd, bwd = sch.uniform_train_schedule_tables(M, S)
    C = M + 2 * (S - 1)
    assert fwd.shape == bwd.shape == (S, C)

    def cycle_of(tab, s, m):
        (idx,) = np.where(tab[s] == m)
        assert idx.size == 1
        return int(idx[0])

    for s in range(S):
        for m in range(M):
            tf, tb = cycle_of(fwd, s, m), cycle_of(bwd, s, m)
            assert tf <= tb
            if s + 1 < S:
                # activation sent at stage s's fwd lands one cycle later
                assert cycle_of(fwd, s + 1, m) == tf + 1
                # grad sent at stage s+1's bwd lands one cycle later
                assert cycle_of(bwd, s, m) == cycle_of(bwd, s + 1, m) + 1
        # in-flight bound: #(forwarded, not yet backwarded) microbatches
        bound = sch.UniformTrainSchedule(
            micro_batches=M, stages=S, stage_id=s).num_pipe_buffers()
        for k in range(C):
            in_flight = sum(
                1 for m in range(M)
                if cycle_of(fwd, s, m) <= k < cycle_of(bwd, s, m))
            assert in_flight <= bound
        assert bound <= min(2 * S - 1, M) or M == 0


def test_uniform_train_schedule_steps_match_tables():
    """The instruction-stream view and the dense tables are the same
    schedule (the executor indexes the tables; tests read the stream)."""
    M, S = 5, 3
    fwd, bwd = sch.uniform_train_schedule_tables(M, S)
    for sid in range(S):
        s = sch.UniformTrainSchedule(micro_batches=M, stages=S, stage_id=sid)
        steps = _cmds_of(s)
        assert len(steps) == fwd.shape[1]
        W = s.num_pipe_buffers()
        for k, cmds in enumerate(steps):
            fwd_bufs = [c.buffer_id for c in cmds
                        if isinstance(c, sch.ForwardPass)]
            bwd_bufs = [c.buffer_id for c in cmds
                        if isinstance(c, sch.BackwardPass)]
            assert fwd_bufs == ([fwd[sid, k] % W] if fwd[sid, k] >= 0 else [])
            assert bwd_bufs == ([bwd[sid, k] % W] if bwd[sid, k] >= 0 else [])
        # tail instructions close the batch like the reference TrainSchedule
        assert any(isinstance(c, sch.ReduceTiedGrads) for c in steps[-1])
        assert any(isinstance(c, sch.OptimizerStep) for c in steps[-1])


def test_instruction_repr_and_eq():
    a = sch.ForwardPass(3)
    b = sch.ForwardPass(3)
    c = sch.ForwardPass(4)
    assert a == b and a != c
    assert "ForwardPass" in repr(a) and "3" in repr(a)


class TestInterleavedTables:
    """interleaved_train_schedule_tables: the generalized (virtual-chunk)
    tables the phase-split executor runs."""

    def _tabs(self, M, S, v):
        from deepspeed_tpu.runtime.pipe.schedule import (
            interleaved_train_schedule_tables)
        return interleaved_train_schedule_tables(M, S, v)

    @pytest.mark.parametrize("M,S,v", [(8, 4, 1), (8, 4, 2), (8, 2, 4),
                                       (6, 3, 2), (4, 4, 2), (7, 4, 2),
                                       (16, 4, 2), (5, 2, 1)])
    def test_complete_and_unique(self, M, S, v):
        t = self._tabs(M, S, v)
        for r in range(S):
            seen_f, seen_b = set(), set()
            for k in range(t["total_cycles"]):
                if t["fwd_m"][r, k] >= 0:
                    seen_f.add((int(t["fwd_c"][r, k]),
                                int(t["fwd_m"][r, k])))
                if t["bwd_m"][r, k] >= 0:
                    seen_b.add((int(t["bwd_c"][r, k]),
                                int(t["bwd_m"][r, k])))
            assert seen_f == {(c, m) for c in range(v) for m in range(M)}
            assert seen_b == seen_f

    @pytest.mark.parametrize("M,S,v", [(8, 4, 2), (6, 3, 2), (8, 2, 4)])
    def test_hop_alignment_with_wrap(self, M, S, v):
        """Virtual stage j+1's forward of m is exactly one cycle after
        stage j's (chunk transitions wrap S-1 -> 0); gradients mirror."""
        t = self._tabs(M, S, v)

        def fwd_cycle(j, m):
            r, c = j % S, j // S
            ks = [k for k in range(t["total_cycles"])
                  if t["fwd_m"][r, k] == m and t["fwd_c"][r, k] == c]
            assert len(ks) == 1
            return ks[0]

        def bwd_cycle(j, m):
            r, c = j % S, j // S
            ks = [k for k in range(t["total_cycles"])
                  if t["bwd_m"][r, k] == m and t["bwd_c"][r, k] == c]
            assert len(ks) == 1
            return ks[0]

        for m in range(M):
            for j in range(v * S - 1):
                assert fwd_cycle(j + 1, m) == fwd_cycle(j, m) + 1
                assert bwd_cycle(j, m) == bwd_cycle(j + 1, m) + 1
            # 1F1B: the last virtual stage may backward in the same
            # cycle as its forward (fwd phase runs first), never before
            assert bwd_cycle(v * S - 1, m) >= fwd_cycle(v * S - 1, m)

    def test_v1_matches_uniform_tables(self):
        from deepspeed_tpu.runtime.pipe.schedule import (
            uniform_train_schedule_tables)
        for M, S in [(8, 4), (5, 2), (3, 3)]:
            t = self._tabs(M, S, 1)
            fwd, bwd = uniform_train_schedule_tables(M, S)
            np.testing.assert_array_equal(t["fwd_m"], fwd)
            np.testing.assert_array_equal(t["bwd_m"], bwd)
            assert (t["fwd_c"][t["fwd_m"] >= 0] == 0).all()

    @pytest.mark.parametrize("M,S,v", [(8, 4, 1), (8, 4, 2), (8, 2, 4),
                                       (16, 4, 2)])
    def test_phase_windows(self, M, S, v):
        """warmup cycles have no backward anywhere; drain cycles have no
        forward; both windows are contiguous."""
        t = self._tabs(M, S, v)
        T, we, se = t["total_cycles"], t["warmup_end"], t["steady_end"]
        assert 0 <= we <= se <= T
        assert (t["bwd_m"][:, :we] < 0).all()
        assert (t["fwd_m"][:, se:] < 0).all()
        has_f = (t["fwd_m"] >= 0).any(axis=0)
        has_b = (t["bwd_m"] >= 0).any(axis=0)
        # contiguity: active windows are single runs
        for flags in (has_f, has_b):
            idx = np.where(flags)[0]
            assert (np.diff(idx) == 1).all()
        # the advertised totals: T = vM + (v+1)S - 2 when S | M
        if M % S == 0:
            assert T == v * M + (v + 1) * S - 2

    @pytest.mark.parametrize("M,S,v", [(8, 4, 1), (8, 4, 2), (8, 2, 4),
                                       (16, 4, 2), (7, 4, 2)])
    def test_buffer_bound_collision_free(self, M, S, v):
        """slot = m % W never collides among in-flight microbatches of
        the same (rank, chunk), counting the backward's read cycle."""
        t = self._tabs(M, S, v)
        W = t["buffer_slots"]
        for r in range(S):
            live = {}
            for k in range(t["total_cycles"]):
                if t["fwd_m"][r, k] >= 0:
                    c, m = int(t["fwd_c"][r, k]), int(t["fwd_m"][r, k])
                    slot = (c, m % W)
                    assert slot not in live, (r, k, slot)
                    live[slot] = m
                if t["bwd_m"][r, k] >= 0:
                    c, m = int(t["bwd_c"][r, k]), int(t["bwd_m"][r, k])
                    assert live.pop((c, m % W)) == m
        # v=1 keeps the round-3 bound
        if v == 1:
            assert W <= max(1, min(2 * S - 1, M))


class TestPackedInferenceTables:
    """packed_inference_schedule_tables: the forward-only eval tables
    (pipe/engine.py _pipeline_eval_fn walks exactly these cycles)."""

    @pytest.mark.parametrize("M,S,v", [(8, 4, 1), (8, 4, 2), (8, 2, 4),
                                       (16, 4, 2), (4, 2, 2)])
    def test_cycle_count_packed(self, M, S, v):
        """Eval cycle count is M*v + S - 1 when S | M — fill + every
        rank's M*v forwards + drain, no 1F1B spacing."""
        t = sch.packed_inference_schedule_tables(M, S, v)
        assert t["total_cycles"] == M * v + S - 1

    @pytest.mark.parametrize("M,S,v", [(7, 4, 2), (3, 2, 2), (5, 4, 1)])
    def test_ragged_tail_bound(self, M, S, v):
        """Ragged M adds exactly (v-1)*(S - M%S) bubble cycles over the
        divisible count (the one-hop chunk spacing makes them
        unavoidable)."""
        t = sch.packed_inference_schedule_tables(M, S, v)
        assert t["total_cycles"] == \
            M * v + S - 1 + (v - 1) * (S - M % S)

    @pytest.mark.parametrize("M,S,v", [(8, 4, 2), (7, 4, 2), (8, 2, 4),
                                       (5, 3, 1)])
    def test_hop_alignment_and_coverage(self, M, S, v):
        """One hop per cycle: rank r+1 forwards (c, m) exactly one cycle
        after rank r; chunk transitions wrap rank S-1 -> rank 0 one
        cycle later; every (c, m) appears exactly once per rank."""
        t = sch.packed_inference_schedule_tables(M, S, v)
        fwd_m, fwd_c = t["fwd_m"], t["fwd_c"]
        when = {}
        for r in range(S):
            seen = set()
            for k in range(t["total_cycles"]):
                if fwd_m[r, k] >= 0:
                    key = (r, int(fwd_c[r, k]), int(fwd_m[r, k]))
                    assert key[1:] not in seen
                    seen.add(key[1:])
                    when[key] = k
            assert len(seen) == M * v
        for (r, c, m), k in when.items():
            if r + 1 < S:
                assert when[(r + 1, c, m)] == k + 1
            elif c + 1 < v:
                assert when[(0, c + 1, m)] == k + 1

    def test_matches_training_forward_tables(self):
        """The training generator's forward half already packs optimally
        (steady_end == packed total): pin the equivalence so the eval
        path's decoupling can never silently diverge from the 1F1B
        executor's forward placement."""
        for M, S, v in [(8, 4, 2), (7, 4, 2), (6, 2, 3)]:
            packed = sch.packed_inference_schedule_tables(M, S, v)
            train = sch.interleaved_train_schedule_tables(M, S, v)
            T = packed["total_cycles"]
            assert T == train["steady_end"]
            assert (packed["fwd_m"] == train["fwd_m"][:, :T]).all()
            assert (packed["fwd_c"] == train["fwd_c"][:, :T]).all()
