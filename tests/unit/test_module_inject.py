"""Module injection tests (reference: module_inject weight-copy policies).

The conversion is validated two ways: exact roundtrip, and numerical
equivalence of the fused layer on converted weights vs an unfused
HF-semantics (post-LN) BERT layer.
"""
import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.module_inject import (replace_transformer_layer,
                                         revert_transformer_layer,
                                         hf_layer_to_ds_params,
                                         ds_params_to_hf_layer,
                                         hf_gpt2_layer_to_block_params,
                                         block_params_to_hf_gpt2_layer,
                                         hf_gpt2_to_gpt2_params)
from deepspeed_tpu.ops.transformer.transformer import \
    transformer_layer_forward


def _hf_layer(rs, d=32, di=64):
    dense = lambda din, dout: {"kernel": rs.randn(din, dout) * 0.05,
                               "bias": rs.randn(dout) * 0.01}
    ln = lambda: {"scale": 1.0 + rs.randn(d) * 0.01, "bias": rs.randn(d) * 0.01}
    return {
        "attention": {
            "self": {"query": dense(d, d), "key": dense(d, d),
                     "value": dense(d, d)},
            "output": {"dense": dense(d, d), "LayerNorm": ln()},
        },
        "intermediate": {"dense": dense(d, di)},
        "output": {"dense": dense(di, d), "LayerNorm": ln()},
    }


def _hf_model_params(rs, n_layers=2, d=32, di=64):
    return {"params": {"encoder": {"layer": {
        str(i): _hf_layer(rs, d, di) for i in range(n_layers)}}}}


def test_roundtrip_exact():
    rs = np.random.RandomState(0)
    layer = _hf_layer(rs)
    back = ds_params_to_hf_layer(hf_layer_to_ds_params(layer))

    flat_a = jax.tree_util.tree_leaves_with_path(
        jax.tree_util.tree_map(jnp.asarray, layer))
    flat_b = jax.tree_util.tree_leaves_with_path(back)
    assert len(flat_a) == len(flat_b)
    for (pa, va), (pb, vb) in zip(sorted(flat_a, key=lambda t: str(t[0])),
                                  sorted(flat_b, key=lambda t: str(t[0]))):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb), atol=1e-7)


def test_replace_produces_stacked_params():
    rs = np.random.RandomState(1)
    params = _hf_model_params(rs, n_layers=3)
    stacked, config = replace_transformer_layer(model_params=params, heads=4)
    assert stacked["attn_qkvw"].shape == (3, 32, 96)
    assert config.num_hidden_layers == 3
    assert config.hidden_size == 32
    assert config.intermediate_size == 64
    assert not config.pre_layer_norm  # HF BERT is post-LN

    reverted = revert_transformer_layer(stacked)
    orig_q = params["params"]["encoder"]["layer"]["1"]["attention"]["self"][
        "query"]["kernel"]
    np.testing.assert_allclose(
        np.asarray(reverted["1"]["attention"]["self"]["query"]["kernel"]),
        orig_q, atol=1e-7)


def _hf_reference_forward(layer, x, heads):
    """Unfused post-LN BERT layer with HF semantics (exact-gelu close
    enough at tanh tolerance)."""
    d = x.shape[-1]
    dh = d // heads

    def ln(t, p):
        mu = t.mean(-1, keepdims=True)
        var = ((t - mu) ** 2).mean(-1, keepdims=True)
        return (t - mu) / jnp.sqrt(var + 1e-12) * p["scale"] + p["bias"]

    att = layer["attention"]
    q = x @ att["self"]["query"]["kernel"] + att["self"]["query"]["bias"]
    k = x @ att["self"]["key"]["kernel"] + att["self"]["key"]["bias"]
    v = x @ att["self"]["value"]["kernel"] + att["self"]["value"]["bias"]
    b, s, _ = x.shape
    sh = lambda t: t.reshape(b, s, heads, dh)
    scores = jnp.einsum("bqhd,bkhd->bhqk", sh(q), sh(k)) / np.sqrt(dh)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, sh(v)).reshape(b, s, d)
    attn_out = ctx @ att["output"]["dense"]["kernel"] + \
        att["output"]["dense"]["bias"]
    x = ln(x + attn_out, att["output"]["LayerNorm"])
    inter = jax.nn.gelu(
        x @ layer["intermediate"]["dense"]["kernel"] +
        layer["intermediate"]["dense"]["bias"], approximate=True)
    out = inter @ layer["output"]["dense"]["kernel"] + \
        layer["output"]["dense"]["bias"]
    return ln(x + out, layer["output"]["LayerNorm"])


def test_fused_forward_matches_hf_reference():
    rs = np.random.RandomState(2)
    layer = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, jnp.float32), _hf_layer(rs))
    ds_params = hf_layer_to_ds_params(layer)
    stacked, config = replace_transformer_layer(
        model_params={"params": {"encoder": {"layer": {"0": layer}}}},
        heads=4)
    x = jnp.asarray(rs.randn(2, 8, 32), dtype=jnp.float32)
    fused = transformer_layer_forward(ds_params, x, None, config,
                                      train=False)
    ref = _hf_reference_forward(layer, x, heads=4)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               atol=2e-5)


# ----------------------------------------------------- GPT-2 policy


def _hf_gpt2_layer(rs, d=32):
    dense = lambda din, dout: {"kernel": rs.randn(din, dout) * 0.05,
                               "bias": rs.randn(dout) * 0.01}
    ln = lambda: {"scale": 1.0 + rs.randn(d) * 0.01,
                  "bias": rs.randn(d) * 0.01}
    return {
        "ln_1": ln(),
        "attn": {"c_attn": dense(d, 3 * d), "c_proj": dense(d, d)},
        "ln_2": ln(),
        "mlp": {"c_fc": dense(d, 4 * d), "c_proj": dense(4 * d, d)},
    }


def _hf_gpt2_params(rs, n_layers=2, d=32, vocab=128, seq=64):
    return {"params": {"transformer": {
        "wte": {"embedding": rs.randn(vocab, d) * 0.02},
        "wpe": {"embedding": rs.randn(seq, d) * 0.01},
        "h": {str(i): _hf_gpt2_layer(rs, d) for i in range(n_layers)},
        "ln_f": {"scale": np.ones(d), "bias": np.zeros(d)},
    }}}


def test_gpt2_policy_roundtrip_exact():
    rs = np.random.RandomState(4)
    layer = _hf_gpt2_layer(rs)
    back = block_params_to_hf_gpt2_layer(hf_gpt2_layer_to_block_params(layer))
    flat_a = jax.tree_util.tree_leaves_with_path(
        jax.tree_util.tree_map(jnp.asarray, layer))
    flat_b = jax.tree_util.tree_leaves_with_path(back)
    assert len(flat_a) == len(flat_b)
    for (pa, va), (pb, vb) in zip(sorted(flat_a, key=lambda t: str(t[0])),
                                  sorted(flat_b, key=lambda t: str(t[0]))):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb), atol=1e-7)


def test_gpt2_policy_block_params_shape():
    rs = np.random.RandomState(5)
    block = hf_gpt2_layer_to_block_params(_hf_gpt2_layer(rs))
    assert block["attn"]["qkv_kernel"].shape == (32, 96)
    assert block["mlp"]["fc_kernel"].shape == (32, 128)
    assert set(block) == {"ln1", "attn", "ln2", "mlp"}


def test_hf_gpt2_weights_drive_inference():
    """init_inference(replace_method='auto') converts an HF-flax GPT2
    params tree in place (reference module-inject flow) and the injected
    layers serve: decode matches the full forward on the converted
    weights."""
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2

    rs = np.random.RandomState(6)
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=64, n_layers=2,
                          n_heads=2, d_model=32, use_flash_attention=False,
                          remat=False)
    model = gpt2.make_gpt2_model(config=cfg)
    model.params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, jnp.float32), _hf_gpt2_params(rs))

    eng = deepspeed.init_inference(model=model, config={
        "inference": {"max_batch_size": 2, "prefill_buckets": [8],
                      "dtype": "fp32", "greedy": True}})
    params = model.params                     # converted in place
    assert set(params) == {"wte", "wpe", "blocks", "ln_f"}
    prompt = [9, 4, 31, 7]
    first = eng.prefill(0, prompt)
    hidden = gpt2.forward_hidden(params, jnp.asarray([prompt]), cfg,
                                 train=False)
    logits = np.asarray(hidden[0, -1] @ params["wte"].T)
    assert first == int(logits.argmax())
