"""ZeRO-Offload engine tests: fp32 master + moments live on HOST (numpy),
HBM holds only compute params + grads (reference stage2 cpu_offload /
zero3-offload)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.model import Model


def _config(stage=2):
    return {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": stage, "cpu_offload": True},
    }


def _apply(params, x, y):
    return jnp.mean((x @ params["w"] - y) ** 2)


def _make(stage=2):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(_apply, {"w": jnp.zeros((32, 8))}),
        config_params=_config(stage))
    return engine


def test_offload_state_lives_on_host():
    engine = _make()
    assert engine.host_state is not None
    # shard-wise host state: [(index, master, exp_avg, exp_avg_sq)]
    shards = engine.host_state["shard_leaves"][0]
    assert all(isinstance(t, np.ndarray) for _, *arrs in shards
               for t in arrs)
    assert isinstance(engine.get_master_params()["w"], np.ndarray)
    assert isinstance(engine._opt_state_view()["exp_avg"]["w"], np.ndarray)
    # device state has no master/opt copies
    assert engine.state["master"] is None and engine.state["opt"] is None


def test_offload_converges_and_counts_steps():
    engine = _make()
    rs = np.random.RandomState(0)
    W = rs.randn(32, 8).astype(np.float32)
    x = jnp.asarray(rs.randn(16, 32).astype(np.float32))
    y = x @ jnp.asarray(W)
    losses = []
    for _ in range(40):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < 0.2 * losses[0], losses
    assert engine.host_state["step"] == 40
    # moments actually updated on host
    assert np.abs(engine._opt_state_view()["exp_avg"]["w"]).sum() > 0


def test_offload_train_batch_path():
    engine = _make()
    rs = np.random.RandomState(0)
    x = rs.randn(1, 16, 32).astype(np.float32)
    y = (x @ rs.randn(32, 8).astype(np.float32))
    l0 = float(engine.train_batch(batch=(x, y)))
    l1 = float(engine.train_batch(batch=(x, y)))
    assert np.isfinite(l0) and l1 < l0


def test_offload_checkpoint_resume(tmp_path):
    engine = _make()
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(16, 32).astype(np.float32))
    y = x @ jnp.asarray(rs.randn(32, 8).astype(np.float32))
    for _ in range(4):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.save_checkpoint(str(tmp_path))

    engine2 = _make()
    engine2.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(engine2.get_master_params()["w"],
                               engine.get_master_params()["w"])
    assert engine2.host_state["step"] == 4
    np.testing.assert_allclose(float(engine2(x, y)), float(engine(x, y)),
                               rtol=1e-6)
    # resumed training continues
    engine2.backward(engine2._last_loss)
    engine2.step()


def test_offload_rejects_lamb():
    config = _config()
    config["optimizer"] = {"type": "Lamb", "params": {"lr": 1e-3}}
    with pytest.raises(ValueError, match="cpu_offload requires"):
        deepspeed_tpu.initialize(
            model=Model(_apply, {"w": jnp.zeros((32, 8))}),
            config_params=config)


def test_offload_overflow_skips_host_step():
    engine = _make()
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(16, 32).astype(np.float32))
    y = x @ jnp.asarray(rs.randn(32, 8).astype(np.float32))
    loss = engine(x, y)
    # poison the accumulated grads
    engine.state["acc_grads"] = jax.tree_util.tree_map(
        lambda g: g.at[0].set(jnp.inf), engine.state["acc_grads"])
    engine._pending_backward = False
    before = engine.get_master_params()["w"].copy()
    engine.step()
    assert engine.skipped_steps == 1
    np.testing.assert_array_equal(engine.get_master_params()["w"], before)
    # grads were zeroed for the next accumulation round
    assert float(jnp.abs(
        jax.tree_util.tree_leaves(engine.state["acc_grads"])[0]).sum()) == 0.0


def test_stage0_cpu_offload_flag_ignored():
    """cpu_offload without ZeRO must not activate the host Adam path."""
    config = {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 0, "cpu_offload": True},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(_apply, {"w": jnp.zeros((32, 8))}),
        config_params=config)
    assert engine.host_state is None


def test_offload_rejects_non_adam_client_optimizer():
    class NotAdam:
        def hyperparams(self):
            return {}

    with pytest.raises(ValueError, match="Adam-family"):
        deepspeed_tpu.initialize(
            model=Model(_apply, {"w": jnp.zeros((32, 8))}),
            optimizer=NotAdam(), config_params=_config())
