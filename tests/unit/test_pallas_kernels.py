"""Pallas kernel tier (ISSUE 11): paged attention + ring GEMMs vs their
XLA oracles, in interpreter mode on CPU.

Contracts pinned here (docs/pallas_kernels.md):

* the paged-attention page-walk kernel matches the slot/gather oracle
  within 1e-5 across page-boundary-crossing mixed lengths, NaN-poisoned
  recycled pools and garbage-page redirects, and greedy serving streams
  are BYTE-identical with the kernel on vs off;
* the ring-GEMM pallas backend matches the ppermute oracle at the PR 6
  tolerances (column bitwise fp32, row <= 5e-6, grads 1e-4) across
  world sizes 1/2/4, forward and backward;
* both tri-state config keys validate, resolve, and fall back LOUDLY
  (never silently);
* the shard-lint IR walker classifies ``pallas_call`` into the segment
  lattice (compute for the page walk, collective for the remote-copy
  ring) and ``engine.audit()`` stays clean with the kernels enabled;
* ``bin/ds_lint.py`` DSL005 flags ``pl.pallas_call`` sites outside
  ``deepspeed_tpu/ops/``.
"""
import contextlib
import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.models.gpt2 import _attend_cache_rows, _paged_attn_ctx
from deepspeed_tpu.ops.pallas.paged_attention import paged_attention
from deepspeed_tpu.parallel.collective_matmul import (
    CollectiveMatmulBinding, tp_column_matmul, tp_row_matmul)
from deepspeed_tpu.utils.logging import logger as ds_logger

pytestmark = pytest.mark.pallas


@contextlib.contextmanager
def _capture_warnings():
    """The DS logger has propagate=False, so caplog can't see it; attach
    a handler directly (the repo's test_telemetry idiom)."""
    messages = []

    class _Cap(logging.Handler):
        def emit(self, record):
            messages.append(record.getMessage())

    cap = _Cap(level=logging.WARNING)
    ds_logger.addHandler(cap)
    try:
        yield messages
    finally:
        ds_logger.removeHandler(cap)

_MESHES = {}


def _model_mesh(n):
    if n not in _MESHES:
        _MESHES[n] = Mesh(np.array(jax.devices()[:n]).reshape(n),
                          ("model",))
    return _MESHES[n]


def _binding(n, **kw):
    return CollectiveMatmulBinding(mesh=_model_mesh(n), axis="model", **kw)


# ===================================================== paged attention

def _paged_setup(seed=0, b=3, s=2, h=2, dh=8, ps=4, max_pages=8,
                 layers=2, usable_pages=12, poison=True):
    """A hand-built paged pool: NaN garbage page 0, NaN unallocated
    tail pages, random live content, slots at mixed lengths whose live
    windows CROSS page boundaries."""
    rng = np.random.RandomState(seed)
    k_pool = rng.randn(usable_pages + 1, layers, h, ps, dh) \
        .astype(np.float32)
    v_pool = rng.randn(usable_pages + 1, layers, h, ps, dh) \
        .astype(np.float32)
    if poison:
        k_pool[0] = np.nan
        v_pool[0] = np.nan
        k_pool[9:] = np.nan
        v_pool[9:] = np.nan
    # pos 5: mid-page; pos 13: crosses into page 3 with the 2 new
    # tokens landing on a page boundary (13 % 4 = 1 .. 14 % 4 = 2);
    # pos 3: the new tokens straddle pages 0 -> 1
    positions = np.array([5, 13, 3], np.int32)
    valid_lens = np.full((b,), s, np.int32)
    page_tables = np.zeros((b, max_pages), np.int32)
    page_tables[0, :2] = [3, 4]
    page_tables[1, :4] = [1, 2, 5, 6]
    page_tables[2, :2] = [7, 8]
    q = jnp.asarray(rng.randn(b, s, h, dh).astype(np.float32))
    return (q, jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(page_tables), jnp.asarray(positions),
            jnp.asarray(valid_lens), ps, max_pages)


def _gather_oracle(q, k_pool, v_pool, page_tables, positions, valid_lens,
                   ps, max_pages, layer):
    b, _, h, dh = q.shape

    def rows_of(cache):
        g = jnp.take(cache[:, layer], page_tables, axis=0)
        return g.transpose(0, 2, 1, 3, 4).reshape(b, h, max_pages * ps, dh)

    return _attend_cache_rows(q, rows_of(k_pool), rows_of(v_pool),
                              positions, dh, valid_lens=valid_lens)


@pytest.mark.parametrize("layer", [0, 1])
def test_paged_attention_matches_gather_oracle(layer):
    # mixed lengths crossing page boundaries, NaN-poisoned garbage page
    # AND NaN unallocated pages: every live row within atol 1e-5
    (q, kp, vp, pt, pos, vl, ps, mp) = _paged_setup()
    got = paged_attention(q, kp, vp, pt, pos, vl, layer_idx=layer,
                          page_size=ps)
    want = _gather_oracle(q, kp, vp, pt, pos, vl, ps, mp, layer)
    assert not np.isnan(np.asarray(got)).any()
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_paged_attention_padded_valid_lens_stay_clean():
    # prefill-shaped call: only valid_lens tokens of the s-wide chunk
    # are real; VALID rows must match the oracle and stay finite even
    # with every stale lane NaN-poisoned (the V-zero guard)
    (q, kp, vp, pt, pos, vl, ps, mp) = _paged_setup(s=4)
    vl = jnp.asarray(np.array([2, 3, 1], np.int32))
    got = np.asarray(paged_attention(q, kp, vp, pt, pos, vl,
                                     layer_idx=0, page_size=ps))
    want = np.asarray(_gather_oracle(q, kp, vp, pt, pos, vl, ps, mp, 0))
    for i, n in enumerate([2, 3, 1]):
        assert np.isfinite(got[i, :n]).all()
        np.testing.assert_allclose(got[i, :n], want[i, :n], atol=1e-5,
                                   rtol=1e-5)


def test_paged_attn_ctx_dispatch_parity_and_shared_writes():
    # the model-level dispatch: ctx within 1e-5 AND the cache WRITES
    # bitwise identical (the scatter is shared by both read paths)
    import dataclasses
    cfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=32, n_layers=2,
                          n_heads=2, d_model=16,
                          use_flash_attention=False, remat=False,
                          loss_chunk=0)
    rng = np.random.RandomState(1)
    b, s, ps, mp = 2, 2, 4, 8
    block = jax.tree_util.tree_map(
        jnp.asarray, {
            "qkv_kernel": rng.randn(16, 48).astype(np.float32),
            "qkv_bias": rng.randn(48).astype(np.float32),
            "proj_kernel": rng.randn(16, 16).astype(np.float32),
            "proj_bias": rng.randn(16).astype(np.float32),
        })
    x = jnp.asarray(rng.randn(b, s, 16).astype(np.float32))
    k_pool = jnp.asarray(rng.randn(9, 2, 2, ps, 8).astype(np.float32))
    v_pool = jnp.asarray(rng.randn(9, 2, 2, ps, 8).astype(np.float32))
    pt = np.zeros((b, mp), np.int32)
    pt[0, :2] = [1, 2]
    pt[1, :3] = [3, 4, 5]
    pos = jnp.asarray(np.array([5, 9], np.int32))
    vl = jnp.asarray(np.array([s, s], np.int32))
    outs = {}
    for kernel in ("xla", "pallas"):
        c = dataclasses.replace(cfg, paged_attention_kernel=kernel)
        outs[kernel] = _paged_attn_ctx(
            x, block, c, k_pool, v_pool, 1, pos, jnp.asarray(pt), vl, ps)
    ctx_x, kx, vx = outs["xla"]
    ctx_p, kp2, vp2 = outs["pallas"]
    np.testing.assert_allclose(np.asarray(ctx_p), np.asarray(ctx_x),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(kx), np.asarray(kp2))
    np.testing.assert_array_equal(np.asarray(vx), np.asarray(vp2))


def _tiny_model():
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=48, n_layers=2,
                          n_heads=2, d_model=32,
                          use_flash_attention=False, remat=False,
                          loss_chunk=0)
    return gpt2.make_gpt2_model(config=cfg)


_PAGED_BASE = {"max_batch_size": 2, "prefill_buckets": [8, 16],
               "dtype": "fp32", "greedy": True, "max_new_tokens": 4,
               "kv_layout": "paged", "kv_block_size": 4}


def test_engine_greedy_streams_byte_identical():
    # the acceptance bit: greedy serving streams equal with the kernel
    # on vs off (and both equal the slot-cache oracle)
    model = _tiny_model()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 128, size=n).tolist() for n in (5, 9, 3)]
    streams = {}
    for name, inf in (
            ("slot", {k: v for k, v in _PAGED_BASE.items()
                      if k not in ("kv_layout", "kv_block_size")}),
            ("paged_xla", dict(_PAGED_BASE, paged_attention_kernel="xla")),
            ("paged_pallas", dict(_PAGED_BASE,
                                  paged_attention_kernel="pallas"))):
        eng = deepspeed.init_inference(model=model,
                                       config={"inference": inf})
        streams[name] = eng.generate(prompts)
    assert streams["paged_pallas"] == streams["paged_xla"]
    assert streams["paged_pallas"] == streams["slot"]


def test_paged_attention_kernel_config_gate():
    model = _tiny_model()
    # invalid value raises at config parse
    from deepspeed_tpu.inference.config import (
        DeepSpeedInferenceConfig, DeepSpeedInferenceConfigError)
    with pytest.raises(DeepSpeedInferenceConfigError):
        DeepSpeedInferenceConfig(
            {"inference": {"paged_attention_kernel": "cuda"}})
    # auto resolves to the XLA gather path off-TPU
    eng = deepspeed.init_inference(
        model=model, config={"inference": dict(_PAGED_BASE)})
    assert eng.paged_attention_kernel == "xla"
    # explicit pallas resolves pallas (interpreter mode) on the paged
    # layout...
    eng = deepspeed.init_inference(
        model=model,
        config={"inference": dict(_PAGED_BASE,
                                  paged_attention_kernel="pallas")})
    assert eng.paged_attention_kernel == "pallas"
    # prefill stays on the oracle path even then
    assert eng.model_config.paged_attention_kernel == "xla"
    # ...and falls back LOUDLY on the slot layout
    with _capture_warnings() as messages:
        eng = deepspeed.init_inference(
            model=model,
            config={"inference": {"max_batch_size": 2, "dtype": "fp32",
                                  "paged_attention_kernel": "pallas"}})
    assert eng.paged_attention_kernel == "xla"
    assert any("has NO effect" in m for m in messages)


def test_decode_program_carries_pallas_and_audits_clean():
    # the decode family runs the kernel; prefill does not; the IR
    # walker classifies the call as a compute segment; audit is clean
    from deepspeed_tpu.analysis.ir import walk
    from deepspeed_tpu.analysis.programs import collect_inference_programs
    eng = deepspeed.init_inference(
        model=_tiny_model(),
        config={"inference": dict(_PAGED_BASE,
                                  paged_attention_kernel="pallas")})
    specs = {s.name: s for s in collect_inference_programs(eng)}
    decode = walk(jax.make_jaxpr(specs["decode"].build())
                  (*specs["decode"].args))
    calls = [e for e in decode.eqns if e.prim == "pallas_call"]
    assert len(calls) == eng.model_config.n_layers
    assert all(e.kind == "compute" for e in calls)
    prefill = walk(jax.make_jaxpr(specs["prefill/b8"].build())
                   (*specs["prefill/b8"].args))
    assert not [e for e in prefill.eqns if e.prim == "pallas_call"]
    report = eng.audit()
    assert report.findings == [], [f.key for f in report.findings]


# ========================================================== ring GEMMs

TOL_ROW = dict(atol=5e-6, rtol=5e-6)
TOL_GRAD = dict(atol=1e-4, rtol=1e-4)


def _xw(rng, b, s, d, f, dtype=np.float32):
    return (jnp.asarray(rng.randn(b, s, d).astype(dtype)),
            jnp.asarray(rng.randn(d, f).astype(dtype)))


@pytest.mark.parametrize("n", [1, 2, 4])
def test_ring_column_forward_bitwise(n):
    rng = np.random.RandomState(3)
    x, w = _xw(rng, 2, 8, 16, 8 * max(n, 1))
    got = tp_column_matmul(x, w, _binding(n, backend="pallas"))
    want = tp_column_matmul(x, w, _binding(n, backend="ppermute"))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("n", [1, 2, 4])
def test_ring_row_forward(n):
    rng = np.random.RandomState(4)
    f = 8 * max(n, 1)
    x, w = _xw(rng, 2, 8, f, 16)
    got = tp_row_matmul(x, w, _binding(n, backend="pallas"))
    want = tp_row_matmul(x, w, _binding(n, backend="ppermute"))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               **TOL_ROW)


@pytest.mark.parametrize("n", [2, 4])
@pytest.mark.parametrize("kind", ["column", "row"])
def test_ring_backward_matches_ppermute(n, kind):
    rng = np.random.RandomState(5)
    if kind == "column":
        x, w = _xw(rng, 1, 8, 8, 8 * n)
        op = tp_column_matmul
    else:
        x, w = _xw(rng, 1, 8, 8 * n, 8)
        op = tp_row_matmul
    gp = jax.grad(lambda x, w: jnp.sum(
        op(x, w, _binding(n, backend="pallas")) ** 2),
        argnums=(0, 1))(x, w)
    go = jax.grad(lambda x, w: jnp.sum(
        op(x, w, _binding(n, backend="ppermute")) ** 2),
        argnums=(0, 1))(x, w)
    for a, b in zip(gp, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   **TOL_GRAD)


def test_ring_bf16_wire_policy():
    # the lossy half-width hop: pallas matches the ppermute bf16 wire
    # closely (same cast points: rotated payloads only)
    rng = np.random.RandomState(6)
    x, w = _xw(rng, 2, 8, 16, 16)
    got = tp_column_matmul(x, w, _binding(4, backend="pallas",
                                          dtype="bf16"))
    want = tp_column_matmul(x, w, _binding(4, backend="ppermute",
                                           dtype="bf16"))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    # and stays a bf16-grade approximation of the exact product
    np.testing.assert_allclose(np.asarray(got), np.asarray(x @ w),
                               atol=0.3, rtol=0.05)


def test_ring_backend_config_validation():
    from deepspeed_tpu.runtime.comm.config import CollectiveMatmulConfig
    assert CollectiveMatmulConfig({"backend": "pallas"}).backend == \
        "pallas"
    assert CollectiveMatmulConfig({}).backend == "ppermute"
    with pytest.raises(ValueError):
        CollectiveMatmulConfig({"backend": "nccl"})
    # backend=pallas with TP fusion off is fully inert (the zero3 ring
    # gather deliberately stays ppermute): loud no-op, raise under strict
    with pytest.raises(ValueError):
        CollectiveMatmulConfig({"enabled": True, "backend": "pallas",
                                "tensor_parallel": False,
                                "strict": True})
    # chunks stays honored on every ppermute path (the zero gather and
    # the loud-fallback loops) — accepted under the pallas backend
    assert CollectiveMatmulConfig({"backend": "pallas",
                                   "chunks": 2}).chunks == 2


def test_ring_multi_axis_mesh_falls_back_loudly_off_tpu():
    # DP x TP mesh: the interpreter's remote-copy simulation addresses
    # one named axis, so off-TPU the dispatch warns and runs the
    # ppermute loop — outputs stay bitwise the oracle's
    import deepspeed_tpu.parallel.collective_matmul as cm
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "model"))
    bind = CollectiveMatmulBinding(mesh=mesh, axis="model",
                                   backend="pallas")
    rng = np.random.RandomState(7)
    x, w = _xw(rng, 2, 8, 16, 16)
    cm._warn_fallback_once.cache_clear()
    with _capture_warnings() as messages:
        got = tp_column_matmul(x, w, bind)
    want = tp_column_matmul(
        x, w, CollectiveMatmulBinding(mesh=mesh, axis="model",
                                      backend="ppermute"))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert any("multi-axis mesh" in m for m in messages)


def test_ring_walker_classifies_collective():
    from deepspeed_tpu.analysis.ir import walk
    from deepspeed_tpu.ops.pallas.ring_gemm import ag_matmul_pallas
    from deepspeed_tpu.parallel.topology import shard_map_compat
    mesh = _model_mesh(2)
    fn = shard_map_compat(
        lambda x, w: ag_matmul_pallas(x, w, "model"), mesh=mesh,
        in_specs=(P(None, "model", None), P(None, "model")),
        out_specs=P(None, None, "model"))
    res = walk(jax.make_jaxpr(fn)(jnp.zeros((2, 8, 16)),
                                  jnp.zeros((16, 16))))
    calls = [e for e in res.eqns if e.prim == "pallas_call"]
    assert calls and all(e.kind == "collective" for e in calls)


def test_ring_engine_training_matches_ppermute(tmp_path):
    # single-axis (pure TP) mesh so the kernels run for real on CPU:
    # fused-vs-fused losses match across 3 steps, the comm_overlap
    # telemetry reports the allgather class fused on BOTH backends, and
    # the shard-lint audit stays green with the kernels in the program
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    def run(backend):
        cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=32, n_layers=2,
                              n_heads=2, d_model=64,
                              use_flash_attention=False, remat=False,
                              loss_chunk=0)
        eng = DeepSpeedEngine(
            model=gpt2.make_gpt2_model(config=cfg),
            mesh=build_mesh(model=2),
            config_params={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 9,
                "telemetry": {"enabled": True,
                              "output_path": str(tmp_path / backend)},
                "comm": {"collective_matmul": {
                    "enabled": True, "backend": backend}}})
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, size=(1, 2, 32)).astype(np.int32)
        losses = [float(eng.train_batch(batch=(ids, ids.copy())))
                  for _ in range(3)]
        return eng, losses

    eng_p, lp = run("pallas")
    eng_o, lo = run("ppermute")
    np.testing.assert_allclose(lp, lo, atol=1e-5, rtol=1e-6)
    # comm_overlap is backend-INVARIANT: wire bytes and fused classes
    # depend on the decomposition, not on who constructs the overlap
    over_p = eng_p.telemetry_snapshot()["comm_overlap_last"]
    over_o = eng_o.telemetry_snapshot()["comm_overlap_last"]
    assert over_p is not None and set(over_p) == {"allgather", "reduce"}
    for cls in ("allgather", "reduce"):
        assert over_p[cls]["bytes"] == over_o[cls]["bytes"]
        assert over_p[cls]["fused"] == over_o[cls]["fused"]
    assert eng_p._cm_tp
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, size=(2, 32)).astype(np.int32)
    report = eng_p.audit(batch=(ids, ids.copy()))
    assert report.findings == [], [f.key for f in report.findings]


# ============================================================= DSL005

def test_dsl005_flags_pallas_call_outside_ops(tmp_path):
    from deepspeed_tpu.analysis import astlint
    pkg = tmp_path / "deepspeed_tpu"
    (pkg / "ops" / "pallas").mkdir(parents=True)
    (pkg / "models").mkdir(parents=True)
    body = ("from jax.experimental import pallas as pl\n"
            "def f(x):\n"
            "    return pl.pallas_call(lambda i, o: None,\n"
            "                          out_shape=None)(x)\n")
    (pkg / "ops" / "pallas" / "good.py").write_text(body)
    (pkg / "models" / "bad.py").write_text(body)
    findings = astlint.lint_paths([str(pkg)], base=str(tmp_path))
    keys = [k for k in findings if k.startswith("DSL005")]
    assert keys == ["DSL005:deepspeed_tpu/models/bad.py::f"], findings
