"""Flash attention numerics vs jnp reference (mirrors reference
test_cuda_forward/backward.py tolerance sweeps)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.attention import (
    causal_attention, reference_causal_attention)
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention


def rand_qkv(b, s, h, d, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d) * 0.5, jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("b,s,h,d", [(1, 128, 2, 32), (2, 256, 4, 64),
                                     (1, 384, 2, 64)])
def test_flash_forward_matches_reference(b, s, h, d):
    q, k, v = rand_qkv(b, s, h, d)
    ref = reference_causal_attention(q, k, v)
    out = causal_attention(q, k, v, use_flash=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_flash_backward_matches_reference():
    b, s, h, d = 1, 256, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=3)

    def loss_flash(q, k, v):
        out = causal_attention(q, k, v, use_flash=True, interpret=True)
        return jnp.sum(out * jnp.cos(out))

    def loss_ref(q, k, v):
        out = reference_causal_attention(q, k, v)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_flash_uneven_seq_blocks():
    # seq not a multiple of the q block: exercises grid cdiv + masking
    b, s, h, d = 1, 320, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=5)
    ref = reference_causal_attention(q, k, v)
    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out = flash_attention(fold(q), fold(k), fold(v), None, True, 128, True)
    out = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_flash_ragged_k_tail_grads():
    # seq with no nice divisor (2*prime) AND block_k < seq so K is truly
    # zero-padded (202 -> 4 blocks of 64): exercises the padded-tail
    # masking in BOTH kernels (fwd scores and bwd dk/dv slicing)
    b, s, h, d = 1, 202, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=11)
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    def loss_flash(q, k, v):
        out = flash_attention(fold(q), fold(k), fold(v), None, True, 64,
                              True, 64)
        return jnp.sum(out * jnp.sin(out))

    def loss_ref(q, k, v):
        out = reference_causal_attention(q, k, v)
        return jnp.sum(out * jnp.sin(out))

    np.testing.assert_allclose(np.asarray(loss_flash(q, k, v)),
                               np.asarray(loss_ref(q, k, v)),
                               rtol=1e-4, atol=1e-4)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_non_causal_mode():
    b, s, h, d = 1, 128, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=7)
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out = flash_attention(fold(q), fold(k), fold(v), None, False, 128, True)
    # reference non-causal
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    probs = jax.nn.softmax(scores, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    ref = fold(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("s", [202, 320, 130])
def test_packed_bshd_ragged_grads(s):
    """The packed (b,s,h*d) kernels' padding masks: seq lengths that are
    not multiples of block_q/block_k must produce reference-equal grads
    (padded q rows SUM into dk/dv if unmasked). Pins the path
    causal_attention actually routes to on TPU."""
    b, h, d = 1, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=11)
    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention_bshd)

    def loss_packed(q, k, v):
        out = flash_attention_bshd(q, k, v, None, True, 64, True, 64)
        return jnp.sum(out * jnp.sin(out))

    def loss_ref(q, k, v):
        out = reference_causal_attention(q, k, v)
        return jnp.sum(out * jnp.sin(out))

    np.testing.assert_allclose(np.asarray(loss_packed(q, k, v)),
                               np.asarray(loss_ref(q, k, v)),
                               rtol=1e-4, atol=1e-4)
    gp = jax.grad(loss_packed, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_streaming_fwd_matches_resident(monkeypatch):
    """The k-blocked streaming forward (long-seq path) must match the
    resident fast path; force it by shrinking the dispatch threshold."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    b, s, h, d = 1, 256, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=13)
    ref = reference_causal_attention(q, k, v)
    monkeypatch.setattr(fa, "RESIDENT_FWD_MAX_ELEMS", 0)
    out = fa.flash_attention_bshd(q, k, v, None, True, 64, True, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_streaming_fwd_bwd_grads(monkeypatch):
    """Streaming-forward lse feeds the split backward: gradients through
    the long-seq path must match the reference too."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    b, s, h, d = 1, 192, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=17)
    monkeypatch.setattr(fa, "RESIDENT_FWD_MAX_ELEMS", 0)

    def loss_stream(q, k, v):
        out = fa.flash_attention_bshd(q, k, v, None, True, 64, True, 64)
        return jnp.sum(out * jnp.sin(out))

    def loss_ref(q, k, v):
        out = reference_causal_attention(q, k, v)
        return jnp.sum(out * jnp.sin(out))

    gs = jax.grad(loss_stream, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_packed_bshd_key_padding_mask():
    """mask_bias (key-padding) path vs masked reference, fwd + grads."""
    b, s, h, d = 2, 192, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=19)
    rng = np.random.RandomState(19)
    keep = np.ones((b, s), np.float32)
    keep[0, 150:] = 0.0       # pad the tail of example 0
    keep[1, 100:] = 0.0
    bias = jnp.asarray((1.0 - keep) * -1e9)

    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention_bshd)

    def ref(q, k, v):
        scale = 1.0 / (d ** 0.5)
        scores = jnp.einsum("bqhd,bkhd->bhqk",
                            q.astype(jnp.float32) * scale,
                            k.astype(jnp.float32))
        scores = scores + bias[:, None, None, :]
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs,
                          v.astype(jnp.float32)).astype(q.dtype)

    def loss_flash(q, k, v):
        out = flash_attention_bshd(q, k, v, None, False, 64, True, 64,
                                   mask_bias=bias)
        return jnp.sum(out * jnp.sin(out))

    def loss_ref(q, k, v):
        out = ref(q, k, v)
        return jnp.sum(out * jnp.sin(out))

    np.testing.assert_allclose(np.asarray(loss_flash(q, k, v)),
                               np.asarray(loss_ref(q, k, v)),
                               rtol=1e-4, atol=1e-4)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_streaming_fwd_key_padding_mask(monkeypatch):
    """The STREAMING forward's bias BlockSpec indexes by k-block; pin it
    with a nonzero mask (the resident-path mask test can't catch a wrong
    index map there)."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    b, s, h, d = 1, 192, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=23)
    keep = np.ones((b, s), np.float32)
    keep[0, 120:] = 0.0
    bias = jnp.asarray((1.0 - keep) * -1e9)

    ref_out = fa.flash_attention_bshd(q, k, v, None, False, 64, True, 64,
                                      mask_bias=bias)   # resident path
    monkeypatch.setattr(fa, "RESIDENT_FWD_MAX_ELEMS", 0)
    stream_out = fa.flash_attention_bshd(q, k, v, None, False, 64, True, 64,
                                         mask_bias=bias)
    np.testing.assert_allclose(np.asarray(stream_out), np.asarray(ref_out),
                               rtol=1e-4, atol=1e-4)


def test_fused_ln_qkv_attention_matches_unfused():
    """fused_ln_qkv_attention (the remat-friendly custom_vjp: saves
    out/lse, recomputes LN+QKV in bwd) must match the straight-line
    LN -> QKV gemm -> flash composition in value and all five grads."""
    from deepspeed_tpu.ops.transformer.flash_attention import (
        fused_ln_qkv_attention, flash_attention_bshd)
    from deepspeed_tpu.ops.transformer.fused_ops import fused_layer_norm

    b, s, h, d = 2, 128, 4, 32
    dm = h * d
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(b, s, dm) * 0.3, jnp.float32)
    ln_s = jnp.asarray(1.0 + 0.1 * rng.randn(dm), jnp.float32)
    ln_b = jnp.asarray(0.1 * rng.randn(dm), jnp.float32)
    w = jnp.asarray(rng.randn(dm, 3 * dm) * 0.05, jnp.float32)
    bb = jnp.asarray(0.01 * rng.randn(3 * dm), jnp.float32)

    def loss_fused(x, ln_s, ln_b, w, bb):
        out = fused_ln_qkv_attention(x, ln_s, ln_b, w, bb, h,
                                     1e-5, True, 64, 64, True)
        return jnp.sum(out * jnp.sin(out))

    def loss_ref(x, ln_s, ln_b, w, bb):
        ln = fused_layer_norm(x, ln_s, ln_b, 1e-5)
        qkv = ln @ w + bb
        q, k, v = jnp.split(qkv, 3, axis=-1)
        rs = lambda t: t.reshape(b, s, h, d)
        out = flash_attention_bshd(rs(q), rs(k), rs(v), None, True,
                                   64, True, 64)
        return jnp.sum(out.reshape(b, s, dm)
                       * jnp.sin(out.reshape(b, s, dm)))

    np.testing.assert_allclose(
        np.asarray(loss_fused(x, ln_s, ln_b, w, bb)),
        np.asarray(loss_ref(x, ln_s, ln_b, w, bb)), rtol=1e-4, atol=1e-4)
    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(x, ln_s, ln_b, w, bb)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, ln_s, ln_b, w, bb)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_fused_attn_under_remat_matches():
    """jax.checkpoint around the consumer of the fused op: gradients must
    survive the remat rebuild unchanged (the whole point of the op)."""
    from deepspeed_tpu.ops.transformer.flash_attention import (
        fused_ln_qkv_attention)

    b, s, h, d = 2, 128, 4, 32
    dm = h * d
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(b, s, dm) * 0.3, jnp.float32)
    ln_s = jnp.ones((dm,), jnp.float32)
    ln_b = jnp.zeros((dm,), jnp.float32)
    w = jnp.asarray(rng.randn(dm, 3 * dm) * 0.05, jnp.float32)
    bb = jnp.zeros((3 * dm,), jnp.float32)

    def network(x, w, remat):
        ctx = fused_ln_qkv_attention(x, ln_s, ln_b, w, bb, h,
                                     1e-5, True, 64, 64, True)
        rest = lambda x, ctx: jnp.sum((x + ctx) ** 2)
        if remat:
            rest = jax.checkpoint(rest)
        return rest(x, ctx)

    g_plain = jax.grad(network, argnums=(0, 1))(x, w, False)
    g_remat = jax.grad(network, argnums=(0, 1))(x, w, True)
    for a, b_ in zip(g_plain, g_remat):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-5)


def test_auto_blocks_by_width(monkeypatch):
    """Width-aware block defaults, keyed to the backward path taken. AUTO
    mode (the default) runs the resident-dq fused kernel wherever its fp32
    dq slab fits VMEM — (256, 256)-class blocks, per head group past the
    single-call cap — and the split pair for long sequences or when
    forced (DS_FLASH_BWD_MODE=split)."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    monkeypatch.setattr(fa, "BWD_MODE", "split")
    assert fa._fused_plan(1024, 16, 1024) == "split"
    assert fa.auto_blocks(1024) == (256, 512)
    assert fa.auto_blocks(1280) == (256, 256)
    assert fa.auto_blocks(1600) == (128, 256)
    monkeypatch.setattr(fa, "BWD_MODE", "auto")
    # auto at model context lengths: fused family
    assert fa._fused_plan(1024, 16, 1024) == "fused"
    assert fa._fused_plan(1280, 20, 1024) == "fused"
    assert fa.auto_blocks(768, num_heads=12, seq_len=1024) == (256, 256)
    assert fa.auto_blocks(1024, num_heads=16, seq_len=1024) == (128, 256)
    assert fa.auto_blocks(1280, num_heads=20, seq_len=1024) == (256, 128)
    # gpt2-xl: 25 heads x 64 -> two fused groups (13+12, widths 832/768,
    # padded 896/768 -> fat blocks)
    assert fa._fused_plan(1600, 25, 1024) == "grouped"
    assert fa.auto_blocks(1600, num_heads=25) == (256, 256)
    # 20 heads x 80 groups 10+10 but PADS to 16 heads = width 1280: the
    # resident kernel there needs (256, 128), not the narrow-group blocks
    assert fa.auto_blocks(1600, num_heads=20, seq_len=1024) == (256, 128)
    assert fa.auto_blocks(1600) == (128, 256)   # no head info: split
    # long sequence: the resident dq slab outgrows VMEM -> split pair
    assert fa._fused_plan(1024, 16, 4096) == "split"
    assert fa.auto_blocks(1024, num_heads=16, seq_len=4096) == (256, 512)
    assert fa.auto_fwd_blocks(1024) == (256, 512)
    assert fa.auto_fwd_blocks(1600) == (256, 256)


def test_head_groups_partition():
    """Grouping covers all heads contiguously, balanced to one head, and
    every group's packed width fits the single-call fused cap."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    for h, d in [(16, 64), (25, 64), (20, 80), (32, 128), (12, 64),
                 (40, 64), (1, 64), (18, 112)]:
        groups = fa._head_groups(h, d)
        assert groups is not None
        assert sum(n for _, n in groups) == h
        assert groups[0][0] == 0
        for (s0, n0), (s1, _) in zip(groups, groups[1:]):
            assert s1 == s0 + n0
        sizes = [n for _, n in groups]
        assert max(sizes) - min(sizes) <= 1
        # the cap must hold for the width the kernel RUNS at (after
        # 128-lane alignment padding), not the on-paper group width
        assert max(fa._padded_heads(n, d) for n in sizes) * d \
            <= fa.FUSED_BWD_MAX_WIDTH
    # a single head wider than the cap cannot be grouped
    assert fa._head_groups(1, 2048) is None


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("variant", ["resident", "dma"])
def test_fused_bwd_matches_split(causal, variant, monkeypatch):
    """Both single-pass fused backwards (one walk, 5 dots/pair) — the
    default resident-dq kernel and the explicit-DMA HBM-accumulation
    variant it replaced — are numerically identical to the split
    dq + dk/dv kernels, including ragged seq (q-padding) and both mask
    polarities."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    if variant == "dma":
        monkeypatch.setattr(fa, "RESIDENT_DQ_MAX_BYTES", 0)
    rng = np.random.RandomState(0)
    b, s, h, d = 2, 192, 4, 32
    hd = h * d
    mk = lambda: jnp.asarray(rng.randn(b, s, hd) * 0.3, jnp.float32)
    q, k, v, do = mk(), mk(), mk(), mk()
    bias = jnp.zeros((b, 1, 128), jnp.float32)
    scale = 1.0 / d ** 0.5
    out, lse = fa._fwd_packed(q, k, v, bias, scale, causal, 128, 128,
                              True, h)
    ref = fa._bwd_split_packed(q, k, v, bias, out, do, lse, scale, causal,
                               128, 128, True, h)
    got = fa._bwd_fused_packed(q, k, v, bias, out, do, lse, scale, causal,
                               128, 128, True, h)
    for name, a, g in zip(("dq", "dk", "dv"), ref, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(g),
                                   atol=2e-4, rtol=2e-4, err_msg=name)


def test_bwd_packed_dispatch_plan():
    """Auto mode routes narrow widths to the single fused call and wide
    ones (gpt2-xl class) fused-per-head-group; sequences whose resident
    dq slab overflows VMEM fall back to the split pair. Forced modes
    override the fit logic."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    assert fa._fused_plan(16 * 64, 16, 1024, mode="auto") == "fused"
    assert fa._fused_plan(25 * 64, 25, 1024, mode="auto") == "grouped"
    assert len(fa._head_groups(25, 64)) == 2
    assert fa._fused_plan(16 * 64, 16, 8192, mode="auto") == "split"
    assert fa._fused_plan(16 * 64, 16, 8192, mode="fused") == "fused"
    assert fa._fused_plan(16 * 64, 16, 1024, mode="split") == "split"
    # resident fit boundary: 6 MB budget / fp32 -> s*hd <= 1.5M elements
    assert fa._resident_dq_fits(1024, 1536)
    assert not fa._resident_dq_fits(1024, 2048)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.slow
def test_grouped_fused_bwd_matches_split(causal):
    """gpt2-xl-width backward (25 heads x 64 = 1600 > single-call cap):
    the per-head-group fused path is numerically identical to the split
    kernels, including the ragged q tail."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    rng = np.random.RandomState(3)
    b, s, h, d = 1, 160, 25, 64
    hd = h * d
    mk = lambda: jnp.asarray(rng.randn(b, s, hd) * 0.2, jnp.float32)
    q, k, v, do = mk(), mk(), mk(), mk()
    bias = jnp.zeros((b, 1, 256), jnp.float32)
    scale = 1.0 / d ** 0.5
    out, lse = fa._fwd_packed(q, k, v, bias, scale, causal, 128, 128,
                              True, h)
    ref = fa._bwd_split_packed(q, k, v, bias, out, do, lse, scale, causal,
                               128, 128, True, h)
    got = fa._bwd_packed(q, k, v, bias, out, do, lse, scale, causal,
                         128, 128, True, h)
    for name, a, g in zip(("dq", "dk", "dv"), ref, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(g),
                                   atol=2e-4, rtol=2e-4, err_msg=name)




def _check_packed_bwd_matches_split(b, s, h, d, causal, seed,
                                    block_q=128, block_k=128):
    """Shared harness: fwd once, then split-pair reference vs whatever
    backward _bwd_fused_packed/_bwd_packed dispatches for this shape."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    rng = np.random.RandomState(seed)
    hd = h * d
    mk = lambda: jnp.asarray(rng.randn(b, s, hd) * 0.2, jnp.float32)
    q, k, v, do = mk(), mk(), mk(), mk()
    pad_k = ((s + block_k - 1) // block_k) * block_k
    bias = jnp.zeros((b, 1, pad_k), jnp.float32)
    scale = 1.0 / d ** 0.5
    out, lse = fa._fwd_packed(q, k, v, bias, scale, causal, block_q,
                              block_k, True, h)
    ref = fa._bwd_split_packed(q, k, v, bias, out, do, lse, scale, causal,
                               block_q, block_k, True, h)
    got = fa._bwd_fused_packed(q, k, v, bias, out, do, lse, scale, causal,
                               block_q, block_k, True, h)
    for name, a, g in zip(("dq", "dk", "dv"), ref, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(g),
                                   atol=2e-4, rtol=2e-4, err_msg=name)


@pytest.mark.parametrize("causal", [True, False])
def test_fused_bwd_chunked_rmw_d80(causal):
    """d_head 80 exercises the resident kernel's chunked dq
    read-modify-write with a NON-ZERO chunk offset: 128/gcd(80,128) = 8
    heads per chunk, so 10 heads write chunks at lane offsets 0 and 640
    (both 128-multiples — the Mosaic constraint on output-ref stores).
    Numerics must match the split pair exactly."""
    _check_packed_bwd_matches_split(1, 160, 10, 80, causal, seed=11)
