"""Flash attention numerics vs jnp reference (mirrors reference
test_cuda_forward/backward.py tolerance sweeps)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.attention import (
    causal_attention, reference_causal_attention)
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention


def rand_qkv(b, s, h, d, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d) * 0.5, jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("b,s,h,d", [(1, 128, 2, 32), (2, 256, 4, 64),
                                     (1, 384, 2, 64)])
def test_flash_forward_matches_reference(b, s, h, d):
    q, k, v = rand_qkv(b, s, h, d)
    ref = reference_causal_attention(q, k, v)
    out = causal_attention(q, k, v, use_flash=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_flash_backward_matches_reference():
    b, s, h, d = 1, 256, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=3)

    def loss_flash(q, k, v):
        out = causal_attention(q, k, v, use_flash=True, interpret=True)
        return jnp.sum(out * jnp.cos(out))

    def loss_ref(q, k, v):
        out = reference_causal_attention(q, k, v)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_flash_uneven_seq_blocks():
    # seq not a multiple of the q block: exercises grid cdiv + masking
    b, s, h, d = 1, 320, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=5)
    ref = reference_causal_attention(q, k, v)
    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out = flash_attention(fold(q), fold(k), fold(v), None, True, 128, True)
    out = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_flash_ragged_k_tail_grads():
    # seq with no nice divisor (2*prime) AND block_k < seq so K is truly
    # zero-padded (202 -> 4 blocks of 64): exercises the padded-tail
    # masking in BOTH kernels (fwd scores and bwd dk/dv slicing)
    b, s, h, d = 1, 202, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=11)
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    def loss_flash(q, k, v):
        out = flash_attention(fold(q), fold(k), fold(v), None, True, 64,
                              True, 64)
        return jnp.sum(out * jnp.sin(out))

    def loss_ref(q, k, v):
        out = reference_causal_attention(q, k, v)
        return jnp.sum(out * jnp.sin(out))

    np.testing.assert_allclose(np.asarray(loss_flash(q, k, v)),
                               np.asarray(loss_ref(q, k, v)),
                               rtol=1e-4, atol=1e-4)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_non_causal_mode():
    b, s, h, d = 1, 128, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=7)
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out = flash_attention(fold(q), fold(k), fold(v), None, False, 128, True)
    # reference non-causal
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    probs = jax.nn.softmax(scores, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    ref = fold(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("s", [202, 320, 130])
def test_packed_bshd_ragged_grads(s):
    """The packed (b,s,h*d) kernels' padding masks: seq lengths that are
    not multiples of block_q/block_k must produce reference-equal grads
    (padded q rows SUM into dk/dv if unmasked). Pins the path
    causal_attention actually routes to on TPU."""
    b, h, d = 1, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=11)
    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention_bshd)

    def loss_packed(q, k, v):
        out = flash_attention_bshd(q, k, v, None, True, 64, True, 64)
        return jnp.sum(out * jnp.sin(out))

    def loss_ref(q, k, v):
        out = reference_causal_attention(q, k, v)
        return jnp.sum(out * jnp.sin(out))

    np.testing.assert_allclose(np.asarray(loss_packed(q, k, v)),
                               np.asarray(loss_ref(q, k, v)),
                               rtol=1e-4, atol=1e-4)
    gp = jax.grad(loss_packed, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_streaming_fwd_matches_resident(monkeypatch):
    """The k-blocked streaming forward (long-seq path) must match the
    resident fast path; force it by shrinking the dispatch threshold."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    b, s, h, d = 1, 256, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=13)
    ref = reference_causal_attention(q, k, v)
    monkeypatch.setattr(fa, "RESIDENT_FWD_MAX_ELEMS", 0)
    out = fa.flash_attention_bshd(q, k, v, None, True, 64, True, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_streaming_fwd_bwd_grads(monkeypatch):
    """Streaming-forward lse feeds the split backward: gradients through
    the long-seq path must match the reference too."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    b, s, h, d = 1, 192, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=17)
    monkeypatch.setattr(fa, "RESIDENT_FWD_MAX_ELEMS", 0)

    def loss_stream(q, k, v):
        out = fa.flash_attention_bshd(q, k, v, None, True, 64, True, 64)
        return jnp.sum(out * jnp.sin(out))

    def loss_ref(q, k, v):
        out = reference_causal_attention(q, k, v)
        return jnp.sum(out * jnp.sin(out))

    gs = jax.grad(loss_stream, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_packed_bshd_key_padding_mask():
    """mask_bias (key-padding) path vs masked reference, fwd + grads."""
    b, s, h, d = 2, 192, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=19)
    rng = np.random.RandomState(19)
    keep = np.ones((b, s), np.float32)
    keep[0, 150:] = 0.0       # pad the tail of example 0
    keep[1, 100:] = 0.0
    bias = jnp.asarray((1.0 - keep) * -1e9)

    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention_bshd)

    def ref(q, k, v):
        scale = 1.0 / (d ** 0.5)
        scores = jnp.einsum("bqhd,bkhd->bhqk",
                            q.astype(jnp.float32) * scale,
                            k.astype(jnp.float32))
        scores = scores + bias[:, None, None, :]
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs,
                          v.astype(jnp.float32)).astype(q.dtype)

    def loss_flash(q, k, v):
        out = flash_attention_bshd(q, k, v, None, False, 64, True, 64,
                                   mask_bias=bias)
        return jnp.sum(out * jnp.sin(out))

    def loss_ref(q, k, v):
        out = ref(q, k, v)
        return jnp.sum(out * jnp.sin(out))

    np.testing.assert_allclose(np.asarray(loss_flash(q, k, v)),
                               np.asarray(loss_ref(q, k, v)),
                               rtol=1e-4, atol=1e-4)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_streaming_fwd_key_padding_mask(monkeypatch):
    """The STREAMING forward's bias BlockSpec indexes by k-block; pin it
    with a nonzero mask (the resident-path mask test can't catch a wrong
    index map there)."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa
    b, s, h, d = 1, 192, 2, 32
    q, k, v = rand_qkv(b, s, h, d, seed=23)
    keep = np.ones((b, s), np.float32)
    keep[0, 120:] = 0.0
    bias = jnp.asarray((1.0 - keep) * -1e9)

    ref_out = fa.flash_attention_bshd(q, k, v, None, False, 64, True, 64,
                                      mask_bias=bias)   # resident path
    monkeypatch.setattr(fa, "RESIDENT_FWD_MAX_ELEMS", 0)
    stream_out = fa.flash_attention_bshd(q, k, v, None, False, 64, True, 64,
                                         mask_bias=bias)
    np.testing.assert_allclose(np.asarray(stream_out), np.asarray(ref_out),
                               rtol=1e-4, atol=1e-4)
