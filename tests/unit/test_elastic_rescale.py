"""Elastic self-healing tests (runtime/elastic/, docs/elasticity.md).

Proves the preemption-native rescale contract end to end on the virtual
8-device CPU mesh:

* resharded optimizer-state restore is BIT-EXACT vs a never-rescaled
  oracle across 8→4→8 and 8→2→8 — master weights, Adam moments, 1-bit
  Adam error feedback (via the pristine sidecar), qgZ ``qg_error``,
  and the loss scaler;
* a SimulatedKill mid-checkpoint becomes a recorded rescale-down +
  resume (not a crash), surfaced by the fleet doctor as rescale events
  with zero straggler false positives;
* the eviction policy needs k CONSECUTIVE flagged windows and a clean
  window resets the streak;
* an incompatible world size is refused BEFORE teardown with
  ``ElasticityIncompatibleWorldSize`` and the engine untouched;
* a divergent program fingerprint is refused enrollment by name;
* the rescale-event schema is pinned across its three copies
  (events.py, the stdlib fleet merger, bin/check_bench_schema.py) and
  the crash bundle gains the ``topology`` section.
"""
import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.elasticity import ElasticityIncompatibleWorldSize
from deepspeed_tpu.parallel.topology import build_mesh
from deepspeed_tpu.runtime.elastic import (
    ElasticDecision, ElasticRunner, ElasticityMonitor, EnrollmentRefused,
    EvictionPolicy, KIND_RESCALE_EVENT, RESCALE_EVENT_KEYS,
    RESCALE_EVENTS_JSONL, enroll_check, events as events_mod,
    make_rescale_event, read_rescale_events, validate_rescale_event)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.model import Model
from deepspeed_tpu.telemetry.fleet import aggregate
from deepspeed_tpu.telemetry.fleet.aggregate import (merge_run,
                                                     write_host_manifest)
from deepspeed_tpu.telemetry.recorder import validate_crash_bundle
from deepspeed_tpu.utils.fault_injection import SimulatedKill, inject_faults

pytestmark = pytest.mark.elastic_rescale

LR = 1e-2


def _model_factory():
    return Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                 {"w": jnp.zeros((16, 4))})


def _data(seed=0):
    rs = np.random.RandomState(seed)
    W = rs.randn(16, 4).astype(np.float32)
    x = jnp.asarray(rs.randn(32, 16).astype(np.float32))
    return x, x @ jnp.asarray(W)


def _config(opt=None, **extra):
    config = {"train_batch_size": 32, "steps_per_print": 10 ** 9,
              "bf16": {"enabled": True},
              "optimizer": opt or {"type": "Adam", "params": {"lr": LR}},
              "zero_optimization": {"stage": 2}}
    config.update(extra)
    return config


def _engine(world, config):
    return DeepSpeedEngine(model=_model_factory(), config_params=config,
                           mesh=build_mesh(data=world))


def _steps(engine, x, y, n):
    for _ in range(n):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    return float(loss)


def _flat(tree):
    return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(tree)]


def _assert_trees_bitwise(a, b, msg):
    for la, lb in zip(_flat(a), _flat(b)):
        np.testing.assert_array_equal(la, lb, err_msg=msg)


# ------------------------------------------- resharded restore numerics
@pytest.mark.parametrize("inter", [4, 2])
def test_onebit_rescale_bit_exact_vs_unrescaled_oracle(tmp_path, inter):
    """8→inter→8 with 1-bit Adam INSIDE the compressed regime: master,
    momentum, both error-feedback tensors, and continued training all
    bitwise equal to a run that never rescaled. The worker residuals
    ride the pristine sidecar through the intermediate world (no step
    consumed them there), so the 8-way decomposition — which feeds the
    compression NONLINEARLY — comes back exactly."""
    opt = {"type": "OneBitAdam", "params": {"lr": LR, "freeze_step": 2}}
    x, y = _data()

    oracle = _engine(8, _config(opt))
    _steps(oracle, x, y, 6)                      # 2 warmup + 4 compressed

    a = _engine(8, _config(opt))
    _steps(a, x, y, 4)
    a.save_checkpoint(str(tmp_path), tag="down")
    b = _engine(inter, _config(opt))
    b.load_checkpoint(str(tmp_path), tag="down")
    assert b.loaded_checkpoint_dp_world_size == 8
    # momentum and the flattened error residual are world-agnostic
    # content: bitwise at the intermediate world already
    numel = 16 * 4
    np.testing.assert_array_equal(
        np.asarray(a.state["opt"]["exp_avg"]["_flat"])[:numel],
        np.asarray(b.state["opt"]["exp_avg"]["_flat"])[:numel])
    np.testing.assert_array_equal(
        np.asarray(a.state["opt"]["server_error"]["_flat"]).reshape(-1)[
            :numel],
        np.asarray(b.state["opt"]["server_error"]["_flat"]).reshape(-1)[
            :numel])
    b.save_checkpoint(str(tmp_path), tag="up")

    c = _engine(8, _config(opt))
    c.load_checkpoint(str(tmp_path), tag="up")
    _steps(c, x, y, 2)

    for key in ("exp_avg", "worker_error", "server_error", "step"):
        np.testing.assert_array_equal(
            np.asarray(jax.tree_util.tree_leaves(
                oracle.state["opt"][key])[0]),
            np.asarray(jax.tree_util.tree_leaves(c.state["opt"][key])[0]),
            err_msg=key)
    _assert_trees_bitwise(oracle.state["opt"]["exp_avg_sq"],
                          c.state["opt"]["exp_avg_sq"], "exp_avg_sq")
    _assert_trees_bitwise(oracle.state["master"], c.state["master"],
                          "master")
    _assert_trees_bitwise(oracle.state["params"], c.state["params"],
                          "params")
    assert float(oracle.state["scaler"].cur_scale) == \
        float(c.state["scaler"].cur_scale)


def test_qg_error_and_fp16_scaler_survive_rescale_bitwise(tmp_path):
    """qgZ gradient-quantization error feedback (now checkpointed —
    docs/zeropp.md) and the DYNAMIC fp16 loss-scaler state reshard
    bitwise across 8→4→8."""
    config = _config()
    del config["bf16"]
    config["fp16"] = {"enabled": True, "initial_scale_power": 4}
    config["zero_optimization"]["zero_quantized_gradients"] = True
    x, y = _data()

    a = _engine(8, config)
    _steps(a, x, y, 4)
    qg_saved = jax.tree_util.tree_map(np.asarray, a.state["qg_error"])
    assert any(np.any(leaf != 0) for leaf in _flat(qg_saved)), \
        "qg_error never exercised — the test would prove nothing"
    a.save_checkpoint(str(tmp_path), tag="t")

    b = _engine(4, dict(config))
    b.load_checkpoint(str(tmp_path), tag="t")
    _assert_trees_bitwise(qg_saved, b.state["qg_error"], "qg_error 8->4")
    b.save_checkpoint(str(tmp_path), tag="t2")

    c = _engine(8, dict(config))
    c.load_checkpoint(str(tmp_path), tag="t2")
    _assert_trees_bitwise(qg_saved, c.state["qg_error"], "qg_error 8->4->8")
    for field in ("cur_scale", "cur_hysteresis", "last_overflow_iter",
                  "cur_iter"):
        assert float(getattr(a.state["scaler"], field)) == \
            float(getattr(c.state["scaler"], field)), field
    _steps(c, x, y, 1)                          # training continues


# ------------------------------------------------ fault-harness rescale
def test_kill_during_checkpoint_becomes_recorded_rescale(tmp_path):
    """The acceptance flow: train at 8, SimulatedKill mid-save → the
    runner rescales to 4 from the last COMPLETE tag, training resumes
    finite, a second rescale returns to 8 — and the fleet doctor shows
    two completed rescale events and ZERO straggler flags."""
    run_dir = str(tmp_path / "run")
    ckpt_dir = str(tmp_path / "ckpt")
    config = _config(telemetry={"enabled": True, "output_path": run_dir})
    x, y = _data()

    def one_step(engine):
        return _steps(engine, x, y, 1)

    runner = ElasticRunner(_model_factory, config, ckpt_dir,
                           candidate_worlds=[2, 4, 8],
                           sleep=lambda s: None)
    assert runner.world == 8
    for _ in range(3):
        runner.train_step(one_step)
    runner.checkpoint(tag="pre")

    with inject_faults(kill_after_files=0):
        runner.checkpoint(tag="torn")          # kill → rescale, NOT a crash
    assert runner.world == 4
    assert runner.rescales == 1
    assert runner.engine.global_steps == 3     # restored, no data loss

    loss, _ = runner.train_step(one_step)
    assert loss == loss and abs(loss) != float("inf")

    runner.rescale(8, "capacity restored", save_first=True)
    assert runner.world == 8
    loss, _ = runner.train_step(one_step)
    assert loss == loss
    host_dir = runner.engine.telemetry.output_dir
    runner.close()

    # all three engine generations shared ONE host dir (close releases
    # the collector's claim) — no phantom hosts in the fleet view
    assert sorted(os.listdir(run_dir)) == [os.path.basename(host_dir)]
    events = read_rescale_events(host_dir)
    assert [e["event"] for e in events] == [
        "preemption_notice", "rescale_attempt", "rescale",
        "rescale_attempt", "rescale"]
    assert all(validate_rescale_event(e) == [] for e in events)
    completed = [e for e in events if e["event"] == "rescale"]
    assert [(e["old_world"], e["new_world"]) for e in completed] == \
        [(8, 4), (4, 8)]
    assert completed[0]["new_mesh"] == {"data": 4}

    report = merge_run(run_dir)
    assert report["rescale"]["count"] == 5
    assert report["rescale"]["completed"] == 2
    assert report["straggler"]["flags"] == []
    hosts = {e["host"] for e in report["rescale"]["events"]}
    assert hosts == {os.path.basename(host_dir)}


def test_rescale_attempts_ride_retry_and_give_up_loudly(tmp_path):
    """Restore failures inside a rescale are retried with backoff and
    every attempt lands in the event history; an empty checkpoint dir
    exhausts the budget and surfaces the underlying RescaleError."""
    from deepspeed_tpu.runtime.elastic import RescaleError
    from deepspeed_tpu.utils.retry import RetryPolicy
    runner = ElasticRunner(
        _model_factory, _config(), str(tmp_path / "nothing-here"),
        candidate_worlds=[2, 4, 8],
        retry_policy=RetryPolicy(retries=2, backoff_seconds=0.0),
        sleep=lambda s: None)
    with pytest.raises(RescaleError):
        runner.rescale(4, "forced", save_first=False)
    attempts = [e for e in runner.events
                if e["event"] == "rescale_attempt"]
    assert len(attempts) >= 3                   # 1 first + 2 retries
    runner.close()


# -------------------------------------------------------- eviction policy
def test_eviction_needs_k_consecutive_windows_and_resets():
    policy = EvictionPolicy(severity=2.0, windows=3)
    flag = [{"host": "tpu-b", "metric": "step_wall", "worst_ratio": 3.0}]
    assert policy.observe(flag) is None
    assert policy.observe(flag) is None
    assert policy.observe([]) is None           # clean window resets
    assert policy.observe(flag) is None
    assert policy.observe(flag) is None
    decision = policy.observe(flag)             # 3rd consecutive window
    assert decision is not None and decision.action == "evict"
    assert decision.hosts == ("tpu-b",)
    assert "tpu-b" in decision.reason
    # once evicted, the same host never re-triggers
    assert policy.observe(flag) is None


def test_eviction_severity_floor_filters_mild_flags():
    policy = EvictionPolicy(severity=2.0, windows=1)
    mild = [{"host": "tpu-c", "metric": "step_wall", "worst_ratio": 1.6}]
    assert policy.observe(mild) is None         # flagged but below floor
    hot = [{"host": "tpu-c", "metric": "step_wall", "worst_ratio": 2.5}]
    assert policy.observe(hot).hosts == ("tpu-c",)


def test_flagged_host_proactively_evicted_via_runner(tmp_path):
    """A host flagged for k consecutive fleet windows is evicted WITHOUT
    data loss: the runner checkpoints first, rescales down, and the
    restored engine carries the same global step."""
    monitor = ElasticityMonitor(
        eviction=EvictionPolicy(severity=2.0, windows=2))
    runner = ElasticRunner(_model_factory, _config(),
                           str(tmp_path / "ckpt"),
                           candidate_worlds=[2, 4, 8], monitor=monitor,
                           sleep=lambda s: None)
    x, y = _data()
    for _ in range(2):
        runner.train_step(lambda e: _steps(e, x, y, 1))
    flags = {"straggler": {"flags": [
        {"host": "train", "metric": "step_wall", "worst_ratio": 4.0}]}}
    runner.observe_fleet(flags)
    assert runner.maybe_rescale() is None       # one window: streak only
    runner.observe_fleet(flags)
    decision = runner.maybe_rescale()
    assert decision is not None and decision.action == "evict"
    assert runner.world == 4
    assert runner.engine.global_steps == 2      # checkpointed first
    assert [e["event"] for e in runner.events][:1] == ["eviction"]
    assert any(e["event"] == "rescale" for e in runner.events)
    runner.close()


# ------------------------------------------------- preflight / candidates
def test_incompatible_world_refused_before_teardown(tmp_path):
    runner = ElasticRunner(_model_factory, _config(),
                           str(tmp_path / "ckpt"),
                           candidate_worlds=[2, 4, 8],
                           sleep=lambda s: None)
    live = runner.engine
    with pytest.raises(ElasticityIncompatibleWorldSize):
        runner.rescale(5, "bad target")
    assert runner.engine is live                # untouched, still world 8
    assert runner.world == 8
    assert runner.events[-1]["event"] == "rescale_refused"
    assert runner.events[-1]["outcome"] == "refused"
    runner.close()


def test_validate_elastic_world_size_elastic_and_plain():
    """runtime/config.py candidate-batch math, init AND rescale: an
    elastic config accepts exactly its HCN-valid worlds; a plain config
    accepts worlds preserving train_batch via re-derived grad-accum."""
    elastic_cfg = _config(elasticity={
        "enabled": True, "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 16], "min_gpus": 1, "max_gpus": 64,
        "version": 0.1})
    elastic_cfg.pop("train_batch_size")         # the solver owns batching
    elastic = _engine(8, elastic_cfg)
    batch, micro, accum = elastic._config.validate_elastic_world_size(4)
    assert batch == micro * accum * 4
    with pytest.raises(ElasticityIncompatibleWorldSize):
        elastic._config.validate_elastic_world_size(10 ** 9)

    plain = _engine(4, _config())               # batch 32, micro derived
    # a DERIVED micro (8 at world 4) must not veto world 8
    assert plain._config.validate_elastic_world_size(8) == (32, 4, 1)
    with pytest.raises(ElasticityIncompatibleWorldSize):
        plain._config.validate_elastic_world_size(7)

    pinned = _engine(4, _config(
        train_micro_batch_size_per_gpu=8,
        gradient_accumulation_steps=1))         # micro EXPLICIT
    with pytest.raises(ElasticityIncompatibleWorldSize):
        pinned._config.validate_elastic_world_size(8)   # 8*8 > 32


def test_runner_derives_candidates_from_elastic_config(tmp_path):
    config = _config(elasticity={
        "enabled": True, "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 16], "min_gpus": 1, "max_gpus": 8,
        "version": 0.1})
    config.pop("train_batch_size")
    runner = ElasticRunner(_model_factory, config, str(tmp_path),
                           sleep=lambda s: None)
    assert runner.candidate_worlds              # solver-provided
    assert all(isinstance(w, int) for w in runner.candidate_worlds)
    runner.close()


# ------------------------------------------------------ enrollment gate
def test_divergent_fingerprint_refused_enrollment_by_name(tmp_path):
    # families as raw counts (not token lists) — the detail derivation
    # must fall back to the digest message, never crash
    fp = {"digest": "aaaa", "version": 1, "families": {"psum:data": 1}}
    bad = {"digest": "ffff", "version": 1, "families": {"psum:data": 1}}
    for name in ("host-0", "host-1", "host-2"):
        write_host_manifest(str(tmp_path / name), job_name=name,
                            fingerprint=fp)
    with pytest.raises(EnrollmentRefused) as err:
        enroll_check(str(tmp_path), "host-3", bad)
    assert err.value.host == "host-3"
    assert "host-3" in str(err.value)           # actionable, names host
    # an agreeing host enrolls and sees the full comparison
    comparison = enroll_check(str(tmp_path), "host-3", fp)
    assert not comparison["mismatch"]
    assert comparison["published"] == 4


def test_monitor_preemption_notice_file_and_world_change(tmp_path):
    notice = str(tmp_path / "preempt-notice")
    monitor = ElasticityMonitor(notice_file=notice)
    assert monitor.poll() is None
    open(notice, "w").close()
    decision = monitor.poll()
    assert decision.action == "rescale" and decision.target_world is None
    assert "notice" in decision.reason
    change = monitor.check_world(8, 4)
    assert change == ElasticDecision(
        action="rescale", reason="device count changed: 8 -> 4",
        target_world=4)
    assert monitor.check_world(8, 8) is None


# ------------------------------------------------ schema pins / surfaces
def _load_checker():
    path = os.path.join(os.path.dirname(__file__), "..", "..", "bin",
                        "check_bench_schema.py")
    spec = importlib.util.spec_from_file_location("check_bench_schema",
                                                  os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_rescale_event_schema_pinned_across_copies():
    checker = _load_checker()
    assert events_mod.RESCALE_EVENT_KEYS == aggregate.RESCALE_EVENT_KEYS
    assert events_mod.RESCALE_EVENT_KEYS == checker.RESCALE_EVENT_KEYS
    assert events_mod.RESCALE_EVENTS_JSONL == aggregate.RESCALE_EVENTS_JSONL
    assert events_mod.KIND_RESCALE_EVENT == aggregate.KIND_RESCALE_EVENT
    assert "rescale" in aggregate.FLEET_REPORT_KEYS
    assert aggregate.FLEET_REPORT_KEYS == checker.FLEET_REPORT_KEYS


def test_rescale_event_validation_and_tolerant_read(tmp_path):
    event = make_rescale_event("rescale", "why", old_world=8, new_world=4,
                               old_mesh={"data": 8}, new_mesh={"data": 4},
                               attempt=1, outcome="ok")
    assert tuple(event.keys()) == RESCALE_EVENT_KEYS
    assert validate_rescale_event(event) == []
    assert validate_rescale_event({"kind": "nope"}) != []
    bad = dict(event, event="made_up")
    assert any("made_up" in p for p in validate_rescale_event(bad))

    events_mod.append_rescale_event(str(tmp_path), event)
    path = os.path.join(str(tmp_path), RESCALE_EVENTS_JSONL)
    with open(path, "a") as fh:
        fh.write('{"torn half-li')                  # crash mid-append
    assert read_rescale_events(str(tmp_path)) == [event]
    # the fleet checker accepts the merged report's rescale section
    checker = _load_checker()
    report = {"rescale": {"count": 1, "completed": 1, "events": [
        dict(event, host="h0")]}}
    assert checker.check_fleet_report.__name__  # smoke: checker loaded


def test_crash_bundle_gains_topology_section(tmp_path):
    """The flight recorder's bundle carries which topology was LIVE plus
    the elastic rescale history — pinned in CRASH_BUNDLE_KEYS and
    accepted by the stdlib checker copy."""
    config = _config(telemetry={
        "enabled": True, "output_path": str(tmp_path / "run"),
        "flight_recorder": {}})
    engine = _engine(8, config)
    engine._rescale_history.append(
        make_rescale_event("rescale", "test", old_world=8, new_world=4))
    x, y = _data()
    _steps(engine, x, y, 1)
    engine.telemetry.recorder.dump("manual")
    crash_dir = os.path.join(engine.telemetry.output_dir, "crash")
    bundles = [os.path.join(crash_dir, n)
               for n in sorted(os.listdir(crash_dir))
               if n.endswith(".json")]
    bundle = json.load(open(bundles[-1]))
    assert validate_crash_bundle(bundle) == []
    topo = bundle["topology"]
    assert topo["mesh"] == {"data": 8}
    assert topo["dp_world_size"] == 8
    assert topo["zero_plan"]["stage"] == 2
    assert topo["zero_plan"]["dp_size"] == 8
    assert topo["rescale_history"][0]["kind"] == KIND_RESCALE_EVENT
    assert _load_checker().check_crash_bundle(bundle) == []
    engine.close()


def test_zero_plan_topology_summary():
    plan = _engine(8, _config()).zero_plan
    topo = plan.topology()
    assert topo == {"mesh": {"data": 8}, "stage": 2, "dp_size": 8,
                    "param_shard_size": 8, "data_axes": ["data"],
                    "hierarchical": False}
    assert json.dumps(topo)                     # JSON-able by contract


def test_engine_close_is_idempotent_and_releases_claim(tmp_path):
    run_dir = str(tmp_path / "run")
    config = _config(telemetry={"enabled": True, "output_path": run_dir})
    e1 = _engine(8, config)
    first = e1.telemetry.output_dir
    e1.close()
    e1.close()                                  # idempotent
    e2 = _engine(8, config)
    # the claim was released: the successor reuses the SAME host dir
    assert e2.telemetry.output_dir == first
    e2.close()
