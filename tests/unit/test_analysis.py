"""Shard-lint auditor tests (ISSUE 10, docs/analysis.md).

The injected-defect matrix: every rule class is proven by a defect that
makes it fire (strip a sharding constraint, drop a donation, force an
fp32 leak, add a host callback, unbound the jit key space, read after
donation) AND by the clean engine configs staying silent. Plus: the
report/suppression schema (pinned equal to bin/check_bench_schema.py's
stdlib copy), the repo AST linter (each DSL rule + the tier-1 self-run
against the committed baseline), and the HLO census ground-truthing the
wire estimator.
"""
import dataclasses
import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.analysis import (AnalysisReport, AuditFindingsError,
                                    Finding, ProgramSpec, Suppressions,
                                    audit_program,
                                    recompile_storm_finding,
                                    replicated_leaf_finding,
                                    validate_analysis_report)
from deepspeed_tpu.analysis import astlint
from deepspeed_tpu.analysis import programs as collectors
from deepspeed_tpu.analysis.auditor import audit_programs
from deepspeed_tpu.analysis.findings import (ANALYSIS_REPORT_KEYS,
                                             FINDING_KEYS, SEVERITIES)
from deepspeed_tpu.analysis.rules import sequence_findings
from deepspeed_tpu.models import gpt2

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _tiny_cfg():
    return gpt2.GPT2Config(vocab_size=256, max_seq_len=64, n_layers=2,
                           n_heads=2, d_model=64,
                           use_flash_attention=False, remat=False,
                           loss_chunk=0)


def _make_engine(extra=None, zero=None):
    cp = {"train_micro_batch_size_per_gpu": 2,
          "gradient_accumulation_steps": 1,
          "bf16": {"enabled": True},
          "zero_optimization": dict({"stage": 2}, **(zero or {})),
          "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
          "steps_per_print": 10 ** 9}
    cp.update(extra or {})
    engine, _, _, _ = deepspeed.initialize(
        model=gpt2.make_gpt2_model(config=_tiny_cfg()), config_params=cp)
    return engine


def _batch():
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, size=(16, 64)).astype(np.int32)
    return (ids, ids.copy())


# --------------------------------------------------------- shared core
def test_shared_rule_core_thresholds():
    assert replicated_leaf_finding("p", "x", 100, 8, threshold=101) is None
    assert replicated_leaf_finding("p", "x", 100, 1, threshold=10) is None
    f = replicated_leaf_finding("p", "arg0", 1 << 20, 8, threshold=1024)
    assert f is not None and f.check == "replicated_leaf"
    assert "REPLICATED" in f.message and "8x" in f.message
    assert recompile_storm_finding("fam", 3, threshold=3) is None
    f = recompile_storm_finding("fam", 4, threshold=3)
    assert f is not None and f.key == "recompile_storm:fam"


def test_runtime_observatory_shares_rule_core():
    """telemetry/programs.py imports the rule implementations (and the
    default thresholds) from analysis/rules.py — one implementation,
    one threshold config, no drift."""
    from deepspeed_tpu.telemetry import programs as tele_programs
    from deepspeed_tpu.analysis import rules
    assert tele_programs.RECOMPILE_STORM_THRESHOLD_DEFAULT is \
        rules.RECOMPILE_STORM_THRESHOLD_DEFAULT
    assert tele_programs.REPLICATED_LEAF_BYTES_DEFAULT is \
        rules.REPLICATED_LEAF_BYTES_DEFAULT
    assert tele_programs.recompile_storm_finding is \
        rules.recompile_storm_finding
    assert tele_programs.replicated_leaf_finding is \
        rules.replicated_leaf_finding
    # and the shared threshold config feeds BOTH paths
    engine = _make_engine({"telemetry": {
        "enabled": False, "programs": {"recompile_storm_threshold": 7,
                                       "replicated_leaf_bytes": 4096}}})
    acfg = engine._config.analysis_config
    assert acfg.storm_threshold == 7
    assert acfg.replicated_leaf_bytes == 4096


# ------------------------------------------------------- clean configs
@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_clean_stages_are_silent(stage):
    engine = _make_engine(zero={"stage": stage})
    report = engine.audit(batch=_batch())
    assert report.findings == [], [f.key for f in report.findings]
    assert set(report.programs) == {"micro", "apply", "fused_train"}


def test_clean_offload_family():
    engine = _make_engine(zero={"stage": 2, "cpu_offload": True})
    report = engine.audit(batch=_batch())
    assert report.findings == [], [f.key for f in report.findings]
    # ISSUE 13: the audit also validates the lowered executor plan and
    # records its shape as plan/<name> alongside the program families
    assert set(report.programs) == {"micro", "fused_micros",
                                    "offload_check",
                                    "plan/offload_apply"}
    assert all(m["family"] == "offload"
               for name, m in report.programs.items()
               if not name.startswith("plan/"))
    assert report.programs["plan/offload_apply"]["family"] == "plan"
    assert report.programs["plan/offload_apply"]["plan_segments"] > 2


def test_clean_streamed_family():
    engine = _make_engine(zero={
        "stage": 3, "cpu_offload": True, "cpu_offload_params": True,
        "stage3_max_live_parameters": 120000})
    report = engine.audit(batch=_batch())
    assert report.findings == [], [f.key for f in report.findings]
    assert set(report.programs) == {
        "stream/e_fwd", "stream/g_fwd", "stream/h_grad", "stream/g_bwd",
        "stream/e_bwd", "plan/streamed_micro"}
    # the audited donation sets ARE the executed ones (one declaration)
    from deepspeed_tpu.runtime.zero.stream import STREAM_DONATE
    assert report.programs["stream/g_bwd"]["donate_argnums"] == \
        list(STREAM_DONATE["g_bwd"]) == [2]
    assert report.programs["stream/h_grad"]["donate_argnums"] == \
        list(STREAM_DONATE["h_grad"]) == [1]


def test_clean_inference_family():
    engine = deepspeed.init_inference(
        model=gpt2.make_gpt2_model(config=_tiny_cfg()),
        config={"inference": {"max_batch_size": 2,
                              "prefill_buckets": [8, 16],
                              "dtype": "fp32", "greedy": True}},
        audit=False)
    report = engine.audit()
    assert report.findings == [], [f.key for f in report.findings]
    # plan/serving_step: the lowered scheduler-step plan is audited
    # alongside the jit programs (docs/executor.md)
    assert set(report.programs) == {"prefill/b8", "prefill/b16",
                                    "decode", "plan/serving_step"}


def test_inference_spec_verify_program_audited():
    model = gpt2.make_gpt2_model(config=_tiny_cfg())
    engine = deepspeed.init_inference(
        model=model, draft_model=model,
        config={"inference": {
            "max_batch_size": 2, "prefill_buckets": [8],
            "dtype": "fp32", "greedy": True, "kv_layout": "paged",
            "kv_block_size": 4,
            "speculative": {"enabled": True, "method": "model",
                            "num_draft_tokens": 2}}})
    report = engine.audit()
    assert report.findings == [], [f.key for f in report.findings]
    assert "spec_verify" in report.programs
    assert "decode" in report.programs


def test_init_inference_audit_flag_runs_audit():
    engine = deepspeed.init_inference(
        model=gpt2.make_gpt2_model(config=_tiny_cfg()),
        config={"inference": {"max_batch_size": 2,
                              "prefill_buckets": [8],
                              "dtype": "fp32", "greedy": True}},
        audit=True)
    assert engine is not None    # findings would have warned, not raised


def test_clean_pipeline_family():
    from deepspeed_tpu.models import gpt2_pipe
    net = gpt2_pipe.make_gpt2_pipeline(
        config=_tiny_cfg(), num_stages=2, num_dp=4, num_mp=1,
        activation_checkpoint_interval=0)
    engine, _, _, _ = deepspeed.initialize(
        model=net, config_params={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9})
    rng = np.random.RandomState(0)
    # one MICRO batch (global batch x seq); the collector derives the
    # (micro_batches, ...) stack the pipe loop consumes
    ids = rng.randint(0, 256, size=(8, 64)).astype(np.int32)
    report = engine.audit(batch=(ids, ids.copy()))
    # plan/pipe_step: the lowered 1F1B step plan is audited alongside
    # the jit program (docs/executor.md)
    assert set(report.programs) == {"pipe_train", "plan/pipe_step"}
    assert report.programs["pipe_train"]["family"] == "pipeline"
    assert report.findings == [], [f.key for f in report.findings]


# ---------------------------------------------------- injected defects
def test_defect_stripped_sharding_constraint_fires():
    engine = _make_engine()
    orig = engine.zero_plan.constrain
    engine.zero_plan.constrain = lambda tree, kind: tree
    try:
        report = engine.audit(batch=_batch())
    finally:
        engine.zero_plan.constrain = orig
    checks = {f.check for f in report.findings}
    assert "missing_sharding_constraint" in checks, checks


def test_defect_dropped_donation_fires():
    engine = _make_engine({"analysis": {"donation_min_bytes": 1024}})
    specs = collectors.collect_train_programs(engine, batch=_batch())
    micro = next(s for s in specs if s.name == "micro")
    bad = dataclasses.replace(micro, donate=())
    _, _, findings = audit_program(bad, engine._config.analysis_config)
    assert any(f.check == "donation_miss" for f in findings), \
        [f.key for f in findings]
    # and the engine's REAL donation set keeps the same program silent
    _, _, clean = audit_program(micro, engine._config.analysis_config)
    assert not any(f.check == "donation_miss" for f in clean)


def test_defect_unhonorable_donation_fires():
    engine = _make_engine({"analysis": {"donation_min_bytes": 1024}})
    specs = collectors.collect_train_programs(engine, batch=_batch())
    micro = next(s for s in specs if s.name == "micro")
    bad = dataclasses.replace(micro, donate=(0, 1))
    _, _, findings = audit_program(bad, engine._config.analysis_config)
    assert any(f.check == "donation_unhonored" for f in findings), \
        [f.key for f in findings]


def test_defect_read_after_donation_fires():
    seq = [{"program": "a", "reads": ("state",), "donates": ("state",)},
           {"program": "b", "reads": ("state",)}]
    findings = sequence_findings(seq)
    assert [f.check for f in findings] == ["read_after_donation"]
    assert findings[0].severity == "error"
    # a rebind between donation and read keeps the sequence clean
    seq = [{"program": "a", "reads": ("state",), "donates": ("state",),
            "produces": ("state",)},
           {"program": "b", "reads": ("state",)}]
    assert sequence_findings(seq) == []


def test_defect_fp32_leak_fires():
    engine = _make_engine()
    specs = collectors.collect_train_programs(engine, batch=_batch())
    micro = next(s for s in specs if s.name == "micro")
    orig_build = micro.build

    def bad_build():
        fn = orig_build()

        def wrapped(state, batch, rng, pld_theta=None):
            state = dict(state)
            # the classic leak: weights upcast to fp32 before the GEMMs
            state["params"] = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.float32), state["params"])
            return fn(state, batch, rng, pld_theta)

        return wrapped

    bad = dataclasses.replace(micro, build=bad_build)
    _, _, findings = audit_program(bad, engine._config.analysis_config)
    assert any(f.check == "fp32_gemm_from_bf16" for f in findings), \
        [f.key for f in findings]
    # the intentional fp32 stability island (attention scores/softmax
    # over ACTIVATIONS) does NOT fire on the clean program
    _, _, clean = audit_program(micro, engine._config.analysis_config)
    assert not any(f.check == "fp32_gemm_from_bf16" for f in clean)


def test_defect_host_callback_fires():
    engine = _make_engine()
    orig_fn = engine.model.apply_fn

    def cb_apply(params, x, y, **kw):
        out = orig_fn(params, x, y, **kw)
        jax.debug.print("loss {l}", l=out)
        return out

    engine.model.apply_fn = cb_apply
    report = engine.audit(batch=_batch())
    assert any(f.check == "host_callback" for f in report.findings), \
        [f.key for f in report.findings]


def test_defect_weak_typed_operand_fires():
    def fn(x, t):
        return x * t

    spec = ProgramSpec(name="w", family="micro", build=lambda: fn,
                       args=(jax.ShapeDtypeStruct((4,), np.float32), 2.0))
    _, _, findings = audit_program(spec, None)
    assert [f.check for f in findings] == ["weak_typed_operand"]
    # the declared-stable exemption silences it
    spec = dataclasses.replace(spec, allow_weak=("1",))
    _, _, findings = audit_program(spec, None)
    assert findings == []


def test_defect_aot_recompile_storm_fires():
    engine = deepspeed.init_inference(
        model=gpt2.make_gpt2_model(config=_tiny_cfg()),
        config={"inference": {"max_batch_size": 2,
                              "prefill_buckets": [8, 16, 32],
                              "dtype": "fp32", "greedy": True},
                "telemetry": {"programs":
                              {"recompile_storm_threshold": 2}}})
    report = engine.audit()
    storms = [f for f in report.findings if f.check == "recompile_storm"]
    assert storms, [f.key for f in report.findings]
    assert "key space" in storms[0].message


def test_defect_replicated_leaf_fires():
    engine = _make_engine({"telemetry": {
        "enabled": False, "programs": {"replicated_leaf_bytes": 1024}}})
    report = engine.audit(batch=_batch())
    repl = [f for f in report.findings if f.check == "replicated_leaf"]
    assert repl, [f.key for f in report.findings]
    assert all(f.rule == "sharding_drift" for f in repl)


def test_strict_disposition_raises():
    engine = _make_engine({"analysis": {"strict": True}})
    orig = engine.zero_plan.constrain
    engine.zero_plan.constrain = lambda tree, kind: tree
    try:
        with pytest.raises(AuditFindingsError) as err:
            engine.audit(batch=_batch())
    finally:
        engine.zero_plan.constrain = orig
    assert "missing_sharding_constraint" in str(err.value)
    # argument override beats the config
    engine.zero_plan.constrain = lambda tree, kind: tree
    try:
        report = engine.audit(batch=_batch(), strict=False)
    finally:
        engine.zero_plan.constrain = orig
    assert report.findings


# --------------------------------------------------------- suppressions
def test_suppression_file_routes_findings(tmp_path):
    engine = _make_engine()
    sup = tmp_path / "suppressions.json"
    sup.write_text(json.dumps({"version": 1, "suppressions": [
        {"key": "missing_sharding_constraint:*",
         "reason": "intentional defect under test"}]}))
    engine._config.analysis_config.suppressions = str(sup)
    orig = engine.zero_plan.constrain
    engine.zero_plan.constrain = lambda tree, kind: tree
    try:
        report = engine.audit(batch=_batch())
    finally:
        engine.zero_plan.constrain = orig
    assert not any(f.check == "missing_sharding_constraint"
                   for f in report.findings)
    assert any(f.check == "missing_sharding_constraint"
               for f, _ in report.suppressed)


def test_stale_suppressions_surface_in_report(tmp_path):
    engine = _make_engine()
    sup = tmp_path / "suppressions.json"
    sup.write_text(json.dumps({"version": 1, "suppressions": [
        {"key": "never_matches:*", "reason": "left over"}]}))
    engine._config.analysis_config.suppressions = str(sup)
    report = engine.audit(batch=_batch())
    assert report.stale_suppressions == ["never_matches:*"]
    assert report.to_dict()["stale_suppressions"] == ["never_matches:*"]
    # stale entries never fail the audit (prunable, not fatal)
    assert report.findings == []


def test_ds_lint_cli_runs_without_jax_and_classifies_by_baseline(
        tmp_path):
    """The repo-lint CLI path must never import jax (runs on jax-less
    CI boxes), and its --json artifact must split occurrences the same
    way diff_baseline does (baselined occurrence i < allowed count ->
    suppressed, the rest -> findings)."""
    import subprocess
    import sys as _sys
    dirty = tmp_path / "dirty.py"
    base = tmp_path / "baseline.json"
    out = tmp_path / "report.json"
    script = (
        "import sys, importlib.util\n"
        "spec = importlib.util.spec_from_file_location('ds_lint', "
        "{lint!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "open({dirty!r}, 'w').write({src!r})\n"
        "m.run_repo_lint([{dirty!r}], {base!r}, True, None)\n"
        "open({dirty!r}, 'a').write({src2!r})\n"
        "rc = m.run_repo_lint([{dirty!r}], {base!r}, False, {out!r})\n"
        "assert 'jax' not in sys.modules, 'jax imported on lint path'\n"
        "sys.exit(rc)\n").format(
            lint=os.path.join(REPO, "bin", "ds_lint.py"),
            dirty=str(dirty), base=str(base), out=str(out),
            src=_DIRTY_SOURCE,
            src2=_DIRTY_SOURCE.replace("class Engine", "class Engine2"))
    proc = subprocess.run([_sys.executable, "-c", script],
                          capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr  # new hits
    payload = json.loads(out.read_text())
    assert validate_analysis_report(payload) == []
    # the 4 baselined (Engine) occurrences stay suppressed; only the
    # duplicated class's 4 are findings — the artifact agrees with
    # diff_baseline instead of flipping whole keys to "new"
    assert payload["summary"]["suppressed"] == 4, payload["summary"]
    assert payload["summary"]["findings"] == 4, payload["summary"]


def test_suppressions_require_reason(tmp_path):
    with pytest.raises(ValueError, match="reason"):
        Suppressions([{"key": "x"}])
    sup = Suppressions([{"key": "a:*", "reason": "r"}])
    assert sup.match(Finding(rule="r", check="a", program="p",
                             message="m", key="a:p")) is not None
    assert sup.stale() == []
    assert sup.match(Finding(rule="r", check="b", program="p",
                             message="m", key="b:p")) is None


# --------------------------------------------------------- report shape
def test_report_roundtrip_and_schema(tmp_path):
    engine = _make_engine()
    path = tmp_path / "report.json"
    report = engine.audit(batch=_batch(), report_path=str(path))
    assert isinstance(report, AnalysisReport)
    payload = json.loads(path.read_text())
    assert validate_analysis_report(payload) == []
    assert payload["summary"]["programs_audited"] == 3
    # a corrupted report is rejected
    bad = dict(payload)
    bad.pop("summary")
    assert validate_analysis_report(bad)
    bad2 = dict(payload, findings=[{"rule": "x"}])
    assert validate_analysis_report(bad2)


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_bench_schema", os.path.join(REPO, "bin",
                                           "check_bench_schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_schema_checker_tables_pinned_equal():
    """bin/check_bench_schema.py's stdlib copies cannot drift from the
    writer-side source of truth."""
    checker = _load_checker()
    assert tuple(checker.ANALYSIS_REPORT_KEYS) == \
        tuple(ANALYSIS_REPORT_KEYS)
    assert tuple(checker.ANALYSIS_FINDING_KEYS) == tuple(FINDING_KEYS)
    assert tuple(checker.ANALYSIS_SEVERITIES) == tuple(SEVERITIES)


def test_schema_checker_validates_report_artifact(tmp_path):
    engine = _make_engine()
    path = tmp_path / "report.json"
    engine.audit(batch=_batch(), report_path=str(path))
    checker = _load_checker()
    assert checker.check_file(str(path)) == []
    # ds_lint --json artifacts take the same shape
    from deepspeed_tpu.analysis.findings import AnalysisReport as AR
    r = AR(job="repo-lint")
    r.findings.append(Finding(rule="DSL002", check="device-put-in-loop",
                              program="x.py", message="m",
                              key="DSL002:x.py::f"))
    lint_path = tmp_path / "lint.json"
    r.write(str(lint_path))
    assert checker.check_file(str(lint_path)) == []


# ------------------------------------------------------------ AST lint
_DIRTY_SOURCE = '''
import time
import jax

class Engine:
    def _micro_step_fn(self):
        def micro(state, batch):
            t0 = time.time()                 # DSL001
            return state, t0
        return micro

    def upload(self, leaves, dev):
        for leaf in leaves:
            jax.device_put(leaf, dev)        # DSL002
        while True:
            fn = jax.jit(lambda x: x)        # DSL004
            break

    def emit(self, rec):
        self.telemetry.add(rec)              # DSL003

    def emit_gated(self, rec):
        if self.telemetry is not None:
            self.telemetry.add(rec)          # gated: clean

    def emit_alias_gated(self, rec):
        tel = self.telemetry
        if tel is None:
            return
        tel.add(rec)                         # alias-gated: clean

    def emit_truthy_gated(self, rec):
        if self.telemetry:
            self.telemetry.add(rec)          # truthiness gate: clean

    def emit_not_gated(self, rec):
        if not self.telemetry:
            return
        self.telemetry.add(rec)              # not-gate: clean
'''

_CLEAN_SOURCE = '''
import time
import jax

def host_loop(items):
    t0 = time.time()                         # not in a traced builder
    return [x + 1 for x in items]

def _step_fn():
    def step(x):
        return x * 2                         # no wall clock inside
    return step
'''


def test_astlint_rules_fire_and_stay_quiet(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text(_DIRTY_SOURCE)
    clean = tmp_path / "clean.py"
    clean.write_text(_CLEAN_SOURCE)
    findings = astlint.lint_paths([str(dirty)], base=str(tmp_path))
    rules = sorted({key.split(":")[0] for key in findings})
    assert rules == ["DSL001", "DSL002", "DSL003", "DSL004"], findings
    # the gated variants did NOT fire
    dsl3 = [k for k in findings if k.startswith("DSL003")]
    assert dsl3 == ["DSL003:dirty.py::Engine.emit"], dsl3
    assert astlint.lint_paths([str(clean)], base=str(tmp_path)) == {}


def test_astlint_baseline_diff(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text(_DIRTY_SOURCE)
    findings = astlint.lint_paths([str(dirty)], base=str(tmp_path))
    base_path = tmp_path / "baseline.json"
    astlint.write_baseline(str(base_path), findings)
    new, stale = astlint.diff_baseline(
        findings, astlint.load_baseline(str(base_path)))
    assert new == [] and stale == []
    # a NEW occurrence of a baselined rule still fails
    key = next(iter(findings))
    findings[key] = findings[key] + findings[key]
    new, _ = astlint.diff_baseline(
        findings, astlint.load_baseline(str(base_path)))
    assert len(new) == len(findings[key]) // 2
    # removing a hazard only reports the baseline entry as stale
    findings.pop(key)
    new, stale = astlint.diff_baseline(
        findings, astlint.load_baseline(str(base_path)))
    assert new == [] and stale == [key]


def test_repo_self_lint_clean_against_committed_baseline():
    """The tier-1 wiring of the ISSUE's CI satellite: bin/ds_lint.py's
    rule set over deepspeed_tpu/ must be clean against the committed
    baseline — new hot-path anti-patterns fail the suite."""
    findings = astlint.lint_paths(
        [os.path.join(REPO, "deepspeed_tpu")], base=REPO)
    baseline = astlint.load_baseline(
        os.path.join(REPO, "bin", "ds_lint_baseline.json"))
    new, _ = astlint.diff_baseline(findings, baseline)
    assert new == [], "new hot-path lint violations:\n" + "\n".join(
        f.message for f in new)


# ----------------------------------------------------------- HLO layer
def test_hlo_census_parsers():
    from deepspeed_tpu.analysis.hlo import (_parse_permute_groups,
                                            _parse_replica_groups,
                                            _shape_bytes, _wire_bytes)
    assert _shape_bytes("f32[8,4]") == 128
    assert _shape_bytes("(bf16[4]{0}, s32[2])") == 16
    assert _parse_replica_groups("replica_groups={{0,1},{2,3}}") == \
        [frozenset({0, 1}), frozenset({2, 3})]
    iota = _parse_replica_groups("replica_groups=[2,4]<=[8]")
    assert iota == [frozenset({0, 1, 2, 3}), frozenset({4, 5, 6, 7})]
    trans = _parse_replica_groups("replica_groups=[2,4]<=[4,2]T(1,0)")
    assert trans == [frozenset({0, 2, 4, 6}), frozenset({1, 3, 5, 7})]
    pairs = _parse_permute_groups(
        "source_target_pairs={{0,2},{2,0},{1,3},{3,1}}")
    assert sorted(pairs, key=min) == [frozenset({0, 2}),
                                      frozenset({1, 3})]
    assert _wire_bytes("all-gather", 800, 8) == 700
    assert _wire_bytes("all-reduce", 800, 8) == 1400
    assert _wire_bytes("reduce-scatter", 100, 8) == 700
    assert _wire_bytes("collective-permute", 100, 8) == 100


def test_hlo_census_async_start_ops_not_overpriced():
    """TPU backends emit async `-start` pairs whose tuple shape bundles
    operand + result (+ scratch): the census must price the RESULT
    only, not the sum."""
    from deepspeed_tpu.analysis.hlo import _result_bytes, collective_census
    # (operand bf16[64], result bf16[512]) all-gather-start at g=8
    assert _result_bytes("(bf16[64], bf16[512])", "all-gather",
                         True) == 1024
    # reduce-scatter-start: result is the SMALL element
    assert _result_bytes("(f32[512], f32[64])", "reduce-scatter",
                         True) == 256
    # u32 scratch in a permute pair is ignored in favor of the payload
    assert _result_bytes("(bf16[256], bf16[256], u32[], u32[])",
                         "collective-permute", True) == 512
    # sync single-shape path unchanged
    assert _result_bytes("f32[128]", "all-reduce", False) == 512
    hlo = (
        "  %ag = (bf16[1024]{0}, bf16[8192]{0}) all-gather-start("
        "bf16[1024]{0} %p), replica_groups=[1,8]<=[8], dimensions={0}\n"
        "  %done = bf16[8192]{0} all-gather-done((bf16[1024]{0}, "
        "bf16[8192]{0}) %ag)\n")
    census = collective_census(hlo, min_bytes=1)
    assert len(census["ops"]) == 1
    # ring price of the 16384-byte gathered result: 16384 * 7/8
    assert census["ops"][0]["wire_bytes"] == 14336


def test_mesh_axis_groups():
    from deepspeed_tpu.parallel.topology import (build_mesh,
                                                 mesh_axis_groups)
    mesh = build_mesh(data=4, model=2)
    data_groups = mesh_axis_groups(mesh, "data")
    model_groups = mesh_axis_groups(mesh, "model")
    assert len(data_groups) == 2 and all(len(g) == 4
                                         for g in data_groups)
    assert len(model_groups) == 4 and all(len(g) == 2
                                          for g in model_groups)
    both = mesh_axis_groups(mesh, ("data", "model"))
    assert both == [frozenset(range(8))]


def test_tp_ways():
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.zero.partition import ZeroShardingPlan
    from jax.sharding import PartitionSpec as P
    mesh = build_mesh(data=4, model=2)
    plan = ZeroShardingPlan(
        mesh, stage=3,
        model_spec_fn=lambda path, shape:
        P(None, "model") if path == "w" else None)
    assert plan.tp_ways("w", (64, 64)) == 2
    assert plan.tp_ways("b", (64,)) == 1


@pytest.mark.slow
def test_hlo_census_ground_truths_wire_estimator():
    """The byte-for-byte contract: on the explicit-ring (cm) path the
    HLO ppermute census equals the estimator's allgather class exactly;
    the reconciliation payload lands in the report."""
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    mesh = build_mesh(data=8)
    engine = DeepSpeedEngine(
        model=gpt2.make_gpt2_model(config=_tiny_cfg()), mesh=mesh,
        config_params={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3,
                                  "stage3_param_persistence_threshold": 0},
            "comm": {"collective_matmul": {"enabled": True, "chunks": 1}},
            "analysis": {"census_min_bytes": 1,
                         "suppressions": os.path.join(
                             REPO, "tests", "unit",
                             "analysis_suppressions.json")},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9})
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, size=(8, 64)).astype(np.int32)
    report = engine.audit(batch=(ids, ids.copy()), hlo=True)
    census = report.census
    assert census is not None, report.to_dict()
    assert census["match_ring_allgather"] is True, census
    assert census["hlo"]["ring_bytes"] == \
        census["estimator"]["allgather_bytes"] > 0, census
    assert report.findings == [], [f.key for f in report.findings]


@pytest.mark.slow
def test_defect_output_sharding_drift_fires():
    """Force the apply step to hand back a REPLICATED master: the
    compiled output-drift check must catch the un-sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    engine = _make_engine()
    specs = collectors.collect_train_programs(engine, batch=_batch())
    apply_spec = next(s for s in specs if s.name == "apply")
    repl = NamedSharding(engine.mesh, P())
    orig_build = apply_spec.build

    def bad_build():
        fn = orig_build()

        def wrapped(state, hyper):
            new_state, metrics = fn(state, hyper)
            new_state = dict(new_state)
            new_state["master"] = jax.tree_util.tree_map(
                lambda m: jax.lax.with_sharding_constraint(m, repl),
                new_state["master"])
            return new_state, metrics

        return wrapped

    bad = dataclasses.replace(apply_spec, build=bad_build)
    report = audit_programs([bad], engine._config.analysis_config,
                            hlo=True, mesh=engine.mesh)
    drift = [f for f in report.findings
             if f.check == "output_sharding_drift"]
    assert drift, [f.key for f in report.findings]
    assert "REPLICATED" in drift[0].message
    # the clean spec compiles drift-free
    clean = audit_programs([apply_spec], engine._config.analysis_config,
                           hlo=True, mesh=engine.mesh)
    assert not any(f.check == "output_sharding_drift"
                   for f in clean.findings)


def test_h2d_split_program_donation_audit():
    """The ISSUE 10 satellite: audit-verify the H2D bucket split
    program's donated-buffer list. The flat staging buffer has NO
    aliasable output (every output is a reshaped slice), so donating it
    is provably unhonorable — the program now (correctly) donates
    nothing, and the auditor proves re-adding the donation would be a
    defect."""
    from deepspeed_tpu.runtime.zero.transfer import _split_fn_for
    import warnings
    layout = ((512 * 512, (512, 512)), (512 * 512, (512, 512)))
    fn = _split_fn_for(layout)
    # the jitted program runs donation-warning-free on every backend
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        fn(jnp.arange(2 * 512 * 512, dtype=jnp.float32))
    flat = jax.ShapeDtypeStruct((2 * 512 * 512,), np.float32)
    clean = ProgramSpec(name="h2d_split", family="streamed",
                        build=lambda: fn.__wrapped__, args=(flat,),
                        donate=())
    _, _, findings = audit_program(clean, None)
    assert findings == [], [f.key for f in findings]
    donated = dataclasses.replace(clean, donate=(0,))
    _, _, findings = audit_program(donated, None)
    assert [f.check for f in findings] == ["donation_unhonored"]


def test_decode_step_donation_audit():
    """Satellite twin: the fused decode program's donated-buffer list
    is exactly the KV pair — the auditor confirms nothing else above
    threshold could alias, and dropping the KV donation is flagged as
    an HBM-doubling miss."""
    engine = deepspeed.init_inference(
        model=gpt2.make_gpt2_model(config=_tiny_cfg()),
        config={"inference": {"max_batch_size": 2,
                              "prefill_buckets": [8],
                              "dtype": "fp32", "greedy": True},
                "analysis": {"donation_min_bytes": 1024}})
    specs = collectors.collect_inference_programs(engine)
    decode = next(s for s in specs if s.name == "decode")
    assert decode.donate_argnums == (1, 2)       # k_cache, v_cache
    _, _, clean = audit_program(decode, engine.analysis_config)
    assert not any(f.rule == "donation" for f in clean), \
        [f.key for f in clean]
    bad = dataclasses.replace(decode, donate=())
    _, _, findings = audit_program(bad, engine.analysis_config)
    missed = [f for f in findings if f.check == "donation_miss"]
    assert len(missed) >= 2, [f.key for f in findings]


# -------------------------------------------------------- audit errors
def test_untraceable_program_reports_audit_error():
    def broken():
        raise RuntimeError("builder exploded")

    spec = ProgramSpec(name="boom", family="micro", build=broken,
                       args=())
    _, _, findings = audit_program(spec, None)
    assert [f.check for f in findings] == ["audit_error"]
    assert findings[0].severity == "error"


def test_audit_without_batch_needs_sample():
    engine = _make_engine()
    with pytest.raises(ValueError, match="sample batch"):
        engine.audit()
    # an EVAL forward must not stand in for the training micro-batch
    # (eval rows are arbitrary and often replicated)
    engine.eval()
    x = np.zeros((3, 64), np.int32)
    engine(x, x.copy())
    engine.train()
    with pytest.raises(ValueError, match="sample batch"):
        engine.audit()


def test_census_counts_data_axis_all_to_all():
    """A data-axis collective in no wire class (a GSPMD resharding
    all-to-all) still counts toward the reconciled total — the
    'unplanned collective behind your back' must be flaggable."""
    from deepspeed_tpu.analysis.hlo import census_classes, reconcile_wire
    census = {"ops": [
        {"opcode": "all-to-all", "wire_bytes": 1 << 20, "axis": "data"},
        {"opcode": "all-gather", "wire_bytes": 2048, "axis": "data"},
        {"opcode": "all-to-all", "wire_bytes": 4096, "axis": "model"},
    ]}
    classes = census_classes(census, {"data"})
    assert classes["data_other_bytes"] == 1 << 20
    assert classes["data_total_bytes"] == (1 << 20) + 2048
    assert classes["other_axis_bytes"] == 4096
    payload, findings = reconcile_wire(
        [census], {"allgather_bytes_per_step": 2048,
                   "reduce_bytes_per_step": 0}, {"data"})
    assert [f.check for f in findings] == ["unpriced_collective"]
    assert payload["delta_total_bytes"] == 1 << 20


def test_audit_after_step_needs_no_batch():
    engine = _make_engine()
    ids, labels = _batch()
    loss = engine(ids, labels)
    engine.backward(loss)
    engine.step()
    report = engine.audit()
    assert set(report.programs) == {"micro", "apply", "fused_train"}
    assert report.findings == [], [f.key for f in report.findings]
