"""Flops profiler + wall-clock breakdown tests (reference
tests/unit/test_flops_profiler.py)."""
import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.profiling.flops_profiler.profiler import (
    get_model_profile, cost_analysis_of)
from deepspeed_tpu.runtime.model import Model


def test_cost_analysis_counts_matmul_flops():
    def fn(a, b):
        return a @ b

    a = jnp.ones((64, 128))
    b = jnp.ones((128, 32))
    costs = cost_analysis_of(fn, a, b)
    # 2*M*N*K FMA-counted flops, allow backend accounting slack
    assert costs.get("flops", 0) >= 64 * 128 * 32


def test_get_model_profile():
    def fn(x):
        return (x @ jnp.ones((32, 8))).sum()

    flops, macs, params = get_model_profile(fn, (jnp.ones((16, 32)),),
                                            print_profile=False,
                                            as_string=False)
    assert flops > 0


def test_engine_profiles_at_profile_step():
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "flops_profiler": {"enabled": True, "profile_step": 1},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((16, 4))}),
        config_params=config)
    x, y = jnp.ones((8, 16)), jnp.ones((8, 4))
    for _ in range(3):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    costs = engine.flops_profiler.profile_engine_step()
    assert costs.get("flops", 0) > 0
    assert engine.flops_profiler.flops == costs["flops"]


def test_wall_clock_breakdown_timers():
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "wall_clock_breakdown": True,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((16, 4))}),
        config_params=config)
    x, y = jnp.ones((8, 16)), jnp.ones((8, 4))
    for _ in range(2):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    fwd = engine.timers("forward_microstep")
    assert fwd.elapsed(reset=False) > 0.0


def test_per_module_table_for_gpt2():
    """Per-module aggregated table (reference profiler.py:515-677): every
    GPT-2 module appears with nonzero flops, blocks dominate, and the
    depth/top_modules controls prune the output."""
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.profiling.flops_profiler.profiler import (
        profile_module_tree, format_module_profile)

    cfg = gpt2.config_for("gpt2_small", max_seq_len=128, n_layers=2,
                          vocab_size=512, use_flash_attention=False,
                          remat=False)
    spec = gpt2.profile_spec(cfg, batch_size=2)
    tree = profile_module_tree(spec)

    names = {c.name: c for c in tree.children}
    assert set(names) == {"embedding", "block", "final_norm", "lm_head+ce"}
    assert tree.total_flops > 0
    block = names["block"]
    assert block.count == 2 and block.flops > 0
    sub = {c.name: c for c in block.children}
    assert sub["mlp"].flops > 0 and sub["attention"].flops > 0
    # the transformer blocks dominate a fwd pass at tiny vocab
    assert block.total_flops > names["embedding"].total_flops
    # params roll up: root total matches the analytic count
    assert tree.total_params == gpt2.num_params(cfg)

    table = format_module_profile(tree, module_depth=-1, top_modules=10)
    for name in ("embedding", "block (x2)", "attention", "mlp",
                 "final_norm", "lm_head+ce"):
        assert name in table, table
    # depth filter removes the block's children
    shallow = format_module_profile(tree, module_depth=1, top_modules=10)
    assert "attention" not in shallow and "block (x2)" in shallow
    # top_modules=1 keeps only the biggest child per level
    top1 = format_module_profile(tree, module_depth=-1, top_modules=1)
    assert "smaller module(s) not shown" in top1


def test_per_module_table_for_bert():
    """BERT ships a profile spec too (VERDICT r2: attribution was
    GPT-2-only): every module appears with nonzero flops and params roll
    up to the analytic count."""
    from deepspeed_tpu.models import bert
    from deepspeed_tpu.profiling.flops_profiler.profiler import (
        profile_module_tree, format_module_profile)

    cfg = bert.config_for("bert_base", max_seq_len=64, n_layers=2,
                          vocab_size=512, d_model=64, n_heads=2,
                          d_intermediate=256, remat=False)
    spec = bert.profile_spec(cfg, batch_size=2)
    tree = profile_module_tree(spec)
    names = {c.name: c for c in tree.children}
    assert set(names) == {"embedding", "layer", "mlm_head", "pooler+nsp"}
    layer = names["layer"]
    assert layer.count == 2 and layer.flops > 0
    sub = {c.name: c for c in layer.children}
    assert sub["attention"].flops > 0 and sub["mlp"].flops > 0
    assert tree.total_params == bert.num_params(cfg)
    table = format_module_profile(tree, module_depth=-1, top_modules=10)
    assert "layer (x2)" in table and "mlm_head" in table

    # the squad engine's spec prices the span head instead
    squad = bert.profile_spec(cfg, batch_size=2, head="squad")
    squad_tree = profile_module_tree(squad)
    kids = {c.name for c in squad_tree.children}
    assert "squad_head" in kids and "mlm_head" not in kids


def test_bert_engine_ships_profile_spec():
    from deepspeed_tpu.models import bert
    cfg = bert.config_for("bert_base", max_seq_len=64, n_layers=2,
                          vocab_size=512, d_model=64, n_heads=2,
                          d_intermediate=256, remat=False)
    model = bert.make_bert_model(config=cfg)
    spec = model.profile_spec_fn(2, seq=32)
    assert spec["name"].startswith("bert")
    assert any(c["name"] == "layer" for c in spec["children"])


def test_pipeline_engine_forwards_profile_spec():
    """The PipelineEngine's wrapped Model exposes the PipelineModule's
    profile spec, so pipelined GPT-2 configs get the per-module table."""
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2, gpt2_pipe
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=32, n_layers=2,
                          n_heads=2, d_model=32, use_flash_attention=False,
                          remat=False)
    net = gpt2_pipe.make_gpt2_pipeline(config=cfg, num_stages=2, num_dp=4,
                                       activation_checkpoint_interval=0)
    engine, _, _, _ = deepspeed.initialize(model=net, config_params={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
    })
    spec_fn = getattr(engine.model, "profile_spec_fn", None)
    assert spec_fn is not None
    spec = spec_fn(2)
    assert spec["name"].startswith("gpt2")


def test_engine_prints_module_table(caplog):
    """The engine's flops_profiler config prints the per-module table for
    models that ship a profile spec."""
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.config_for("gpt2_small", max_seq_len=64, n_layers=2,
                          vocab_size=256, d_model=64, n_heads=2,
                          use_flash_attention=False, remat=False)
    model = gpt2.make_gpt2_model(config=cfg)
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "flops_profiler": {"enabled": True, "profile_step": 1},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config_params=config)
    import logging
    from deepspeed_tpu.utils.logging import logger as ds_logger

    records = []

    class _Cap(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    cap = _Cap(level=logging.INFO)
    ds_logger.addHandler(cap)
    try:
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, size=(8, 64)).astype(np.int32)
        for _ in range(3):
            loss = engine(ids, ids)
            engine.backward(loss)
            engine.step()
    finally:
        ds_logger.removeHandler(cap)
    joined = "\n".join(records)
    assert "flops profiler" in joined
    assert "block (x2)" in joined and "lm_head+ce" in joined
