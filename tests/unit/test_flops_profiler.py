"""Flops profiler + wall-clock breakdown tests (reference
tests/unit/test_flops_profiler.py)."""
import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.profiling.flops_profiler.profiler import (
    get_model_profile, cost_analysis_of)
from deepspeed_tpu.runtime.model import Model


def test_cost_analysis_counts_matmul_flops():
    def fn(a, b):
        return a @ b

    a = jnp.ones((64, 128))
    b = jnp.ones((128, 32))
    costs = cost_analysis_of(fn, a, b)
    # 2*M*N*K FMA-counted flops, allow backend accounting slack
    assert costs.get("flops", 0) >= 64 * 128 * 32


def test_get_model_profile():
    def fn(x):
        return (x @ jnp.ones((32, 8))).sum()

    flops, macs, params = get_model_profile(fn, (jnp.ones((16, 32)),),
                                            print_profile=False,
                                            as_string=False)
    assert flops > 0


def test_engine_profiles_at_profile_step():
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "flops_profiler": {"enabled": True, "profile_step": 1},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((16, 4))}),
        config_params=config)
    x, y = jnp.ones((8, 16)), jnp.ones((8, 4))
    for _ in range(3):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    costs = engine.flops_profiler.profile_engine_step()
    assert costs.get("flops", 0) > 0
    assert engine.flops_profiler.flops == costs["flops"]


def test_wall_clock_breakdown_timers():
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "wall_clock_breakdown": True,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((16, 4))}),
        config_params=config)
    x, y = jnp.ones((8, 16)), jnp.ones((8, 4))
    for _ in range(2):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    fwd = engine.timers("forward_microstep")
    assert fwd.elapsed(reset=False) > 0.0
