"""Fused transformer layer + BERT model tests.

Mirrors reference tests/unit/test_cuda_forward.py / test_cuda_backward.py:
the fused layer is checked against a plain python/jnp BERT layer reference,
and the BERT model trains end-to-end through the engine.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer)
from deepspeed_tpu.ops.transformer.transformer import init_transformer_params
from deepspeed_tpu.models import bert


def small_config(**overrides):
    kw = dict(batch_size=2, hidden_size=64, heads=4, intermediate_size=256,
              attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
              num_hidden_layers=2, initializer_range=0.02, seed=7,
              pre_layer_norm=True)
    kw.update(overrides)
    return DeepSpeedTransformerConfig(**kw)


def reference_layer(params, x, mask, config):
    """Unfused jnp encoder layer — the numerics spec (mirrors the python
    BERT layer of reference test_cuda_forward.py)."""
    def ln(t, w, b):
        mu = t.mean(-1, keepdims=True)
        var = ((t - mu) ** 2).mean(-1, keepdims=True)
        return (t - mu) / jnp.sqrt(var + config.layer_norm_eps) * w + b

    b_, s, d = x.shape
    h = config.heads
    attn_in = ln(x, params["attn_nw"], params["attn_nb"]) \
        if config.pre_layer_norm else x
    qkv = attn_in @ params["attn_qkvw"] + params["attn_qkvb"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    sh = lambda t: t.reshape(b_, s, h, d // h)
    scores = jnp.einsum("bqhd,bkhd->bhqk", sh(q), sh(k)) / np.sqrt(d // h)
    if mask is not None:
        keep = mask.astype(jnp.float32)
        scores = scores + ((1.0 - keep) * -1e9)[:, None, None, :]
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, sh(v)).reshape(b_, s, d)
    x = x + ctx @ params["attn_ow"] + params["attn_ob"]
    if not config.pre_layer_norm:
        x = ln(x, params["attn_nw"], params["attn_nb"])
    ffn_in = ln(x, params["norm_w"], params["norm_b"]) \
        if config.pre_layer_norm else x
    inter = jax.nn.gelu(ffn_in @ params["inter_w"] + params["inter_b"],
                        approximate=True)
    x = x + inter @ params["output_w"] + params["output_b"]
    if not config.pre_layer_norm:
        x = ln(x, params["norm_w"], params["norm_b"])
    return x


@pytest.mark.parametrize("pre_ln", [True, False])
def test_forward_matches_reference(pre_ln):
    config = small_config(pre_layer_norm=pre_ln)
    layer = DeepSpeedTransformerLayer(config)
    params = layer.init_params()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 64),
                    dtype=jnp.float32)
    mask = jnp.asarray(np.random.RandomState(1).rand(2, 16) > 0.2,
                       dtype=jnp.int32)
    out = layer(params, x, mask, train=False)
    ref = reference_layer(params, x, mask, config)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("flag", ["gelu_checkpoint", "attn_dropout_checkpoint",
                                  "normalize_invertible"])
def test_checkpoint_flags_preserve_grads(flag):
    base = small_config()
    opt = small_config(**{flag: True})
    layer = DeepSpeedTransformerLayer(base)
    params = layer.init_params()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 64),
                    dtype=jnp.float32)

    def loss(cfg):
        lyr = DeepSpeedTransformerLayer(cfg)
        return lambda p: (lyr(p, x, None, train=False) ** 2).mean()

    g_base = jax.grad(loss(base))(params)
    g_opt = jax.grad(loss(opt))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=1e-5), g_base, g_opt)


def test_initial_weight_loading():
    config = small_config()
    d, di = config.hidden_size, config.intermediate_size
    rs = np.random.RandomState(3)
    # torch-layout (out, in) weights as module_inject hands them over
    weights = [rs.randn(d, d) for _ in range(4)] + [rs.randn(d)] + \
              [rs.randn(di, d), rs.randn(d, di)] + [rs.randn(d)]
    biases = [rs.randn(d) for _ in range(5)] + [rs.randn(di)] + \
             [rs.randn(d), rs.randn(d)]
    layer = DeepSpeedTransformerLayer(config, initial_weights=weights,
                                      initial_biases=biases)
    params = layer.init_params()
    np.testing.assert_allclose(np.asarray(params["attn_qkvw"][:, :d]),
                               weights[0].T, atol=1e-6)
    np.testing.assert_allclose(np.asarray(params["inter_w"]), weights[5].T,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(params["attn_qkvb"][d:2 * d]),
                               biases[1], atol=1e-6)


def test_layer_id_assignment():
    DeepSpeedTransformerLayer.layer_count = 0
    config = small_config()
    layers = [DeepSpeedTransformerLayer(config) for _ in range(3)]
    assert [l.config.layer_id for l in layers] == [0, 1, 2]


def _bert_batch(rs, config, batch=4, seq=32):
    ids = rs.randint(0, config.vocab_size, size=(batch, seq))
    types = rs.randint(0, 2, size=(batch, seq))
    mask = np.ones((batch, seq), dtype=np.int32)
    mlm_labels = np.where(rs.rand(batch, seq) < 0.15, ids, -100)
    nsp = rs.randint(0, 2, size=(batch,))
    return (jnp.asarray(ids), jnp.asarray(types), jnp.asarray(mask),
            jnp.asarray(mlm_labels), jnp.asarray(nsp))


@pytest.mark.slow
def test_bert_pretrain_engine_convergence():
    config_dict = {
        "train_batch_size": 8,
        "steps_per_print": 10,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "bf16": {"enabled": True},
    }
    model = bert.make_bert_model(size="bert_base", n_layers=2, d_model=64,
                                 n_heads=4, d_intermediate=128,
                                 vocab_size=128, max_seq_len=64,
                                 dropout=0.0, attn_dropout=0.0)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config_params=config_dict)
    rs = np.random.RandomState(0)
    batch = _bert_batch(rs, model.config, batch=8)
    losses = []
    for _ in range(8):
        loss = engine(*batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_bert_squad_loss_runs():
    model = bert.make_bert_squad_model(size="bert_base", n_layers=2,
                                       d_model=64, n_heads=4,
                                       d_intermediate=128, vocab_size=128,
                                       max_seq_len=64)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 128, size=(2, 32)))
    types = jnp.zeros_like(ids)
    mask = jnp.ones_like(ids)
    start = jnp.asarray(rs.randint(0, 32, size=(2,)))
    end = jnp.asarray(rs.randint(0, 32, size=(2,)))
    loss = model.apply_fn(model.params, ids, types, mask, start, end,
                          train=False)
    assert np.isfinite(float(loss))


def test_bert_tp_partition_specs_place():
    """Stacked (n_layers, ...) params must shard hidden dims, not the layer
    dim, on the model axis."""
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.zero.partition import ZeroShardingPlan
    mesh = build_mesh(data=2, model=4)
    config = bert.config_for("bert_base", vocab_size=128, max_seq_len=64,
                             n_layers=2, d_model=64, n_heads=4,
                             d_intermediate=128)
    params = bert.init_params(config)
    plan = ZeroShardingPlan(mesh, stage=0,
                            model_spec_fn=bert.partition_spec_fn)
    shardings = plan.tree_shardings(params, "param")
    placed = jax.tree_util.tree_map(jax.device_put, params, shardings)
    qkvw = placed["layers"]["attn_qkvw"]
    assert qkvw.sharding.spec == jax.sharding.PartitionSpec(
        None, None, "model")


def test_bert_num_params_matches():
    config = bert.config_for("bert_base", vocab_size=128, max_seq_len=64,
                             n_layers=2, d_model=64, n_heads=4,
                             d_intermediate=128)
    params = bert.init_params(config)
    from deepspeed_tpu.runtime.utils import count_parameters
    assert count_parameters(params) == bert.num_params(config)


def test_encoder_activations_follow_param_dtype():
    """Regression: activations must follow the (engine-cast) param dtype,
    not BertConfig.dtype (the init dtype). A config.dtype cast after the
    embedding LN silently ran the whole encoder in fp32 under a bf16
    engine — a ~30% throughput loss before it was caught."""
    import jax.numpy as jnp
    from deepspeed_tpu.models import bert

    cfg = bert.config_for("bert_base", vocab_size=64, max_seq_len=16,
                          n_layers=1, n_heads=2, d_model=32,
                          d_intermediate=64, dropout=0.0, attn_dropout=0.0,
                          remat=False)
    assert cfg.dtype == jnp.float32          # init dtype stays fp32
    params = bert.init_params(cfg, seed=0)
    params_bf16 = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), params)
    ids = jnp.zeros((2, 16), jnp.int32)
    hidden = bert.encode(params_bf16, ids, None, None, cfg, None, False)
    assert hidden.dtype == jnp.bfloat16, hidden.dtype


def test_stochastic_mode_is_a_pinned_no_op():
    """Formal closure of the reference's stochastic transformer
    (op_builder/stochastic_transformer.py, reference transformer.py:95-139):
    on TPU the determinism-for-speed trade has no distinct kernel to
    select — XLA owns scheduling/reassociation — so the flag is a LOUD
    documented no-op. This pins the warning so the config key can never
    go silently dead."""
    with pytest.warns(UserWarning,
                      match="stochastic_mode has no distinct kernel on TPU"):
        cfg = small_config(stochastic_mode=True)
    assert cfg.stochastic_mode is True  # accepted + carried, not dropped
    # and the layer still runs under the flag
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init_params()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 64), jnp.float32)
    out = layer(params, x, train=False)
    assert np.isfinite(np.asarray(out)).all()
