"""CSRTensor tests (reference tests/unit/test_csr.py)."""
import numpy as np

import jax.numpy as jnp

from deepspeed_tpu.runtime.csr_tensor import CSRTensor, all_gather_concat


def _sparse_dense(rs, rows=32, cols=8, active=5):
    dense = np.zeros((rows, cols), dtype=np.float32)
    idx = rs.choice(rows, size=active, replace=False)
    dense[idx] = rs.randn(active, cols)
    return dense


def test_from_dense_roundtrip():
    rs = np.random.RandomState(0)
    dense = _sparse_dense(rs)
    csr = CSRTensor.from_dense(dense)
    stored, total = csr.sparse_size()
    assert stored == 5 * 8 and total == 32 * 8
    np.testing.assert_allclose(np.asarray(csr.to_dense()), dense)


def test_empty():
    csr = CSRTensor.from_dense(np.zeros((16, 4), dtype=np.float32))
    assert csr.sparse_size()[0] == 0
    np.testing.assert_allclose(np.asarray(csr.to_dense()), 0.0)


def test_add():
    rs = np.random.RandomState(1)
    a, b = _sparse_dense(rs), _sparse_dense(rs)
    out = CSRTensor.from_dense(a).add(CSRTensor.from_dense(b))
    np.testing.assert_allclose(np.asarray(out.to_dense()), a + b, atol=1e-6)


def test_all_gather_concat_sums_ranks():
    rs = np.random.RandomState(2)
    shards = [_sparse_dense(rs) for _ in range(4)]
    csrs = [CSRTensor.from_dense(s) for s in shards]
    out = all_gather_concat(csrs)
    np.testing.assert_allclose(np.asarray(out), sum(shards), atol=1e-6)
