"""Sparse attention tests (mirrors reference tests/unit/test_sparse_attention.py
— triton ops vs dense reference — plus layout-shape checks)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention import (
    SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
    VariableSparsityConfig, BigBirdSparsityConfig,
    BSLongformerSparsityConfig, make_block_sparse_attention,
    build_block_index, SparseSelfAttention, SparseAttentionUtils)


# --- layout generators ------------------------------------------------------

def test_dense_layout_all_ones():
    cfg = DenseSparsityConfig(num_heads=2, block=16)
    layout = cfg.make_layout(64)
    assert layout.shape == (2, 4, 4)
    assert layout.sum() == 2 * 16


def test_layout_requires_divisible_seq():
    with pytest.raises(ValueError):
        DenseSparsityConfig(num_heads=1, block=16).make_layout(50)


def test_fixed_bidirectional_layout():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=4,
                              num_global_blocks=1)
    layout = cfg.make_layout(16 * 8)
    # local: dense 4x4 windows on the diagonal
    assert (layout[0, :4, :4] == 1).all()
    assert (layout[0, 4:, 4:] == 1).all()
    # global: last block of each window is a full column
    assert (layout[0, :, 3] == 1).all()
    assert (layout[0, :, 7] == 1).all()
    # off-window, non-global blocks stay empty
    assert layout[0, 0, 4] == 0
    assert layout[0, 5, 1] == 0
    # heads share one layout by default
    assert (layout[0] == layout[1]).all()


def test_fixed_unidirectional_layout():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=4,
                              num_global_blocks=1,
                              attention="unidirectional")
    layout = cfg.make_layout(16 * 8)
    # strictly-upper blocks never attended
    assert np.triu(layout[0], 1).sum() == 0
    # lower-tri local window + global col visible only from rows below it
    assert layout[0, 2, 1] == 1
    assert layout[0, 1, 2] == 0
    assert layout[0, 7, 3] == 1  # global col from a later row
    assert layout[0, 2, 3] == 0  # global col not visible from above


def test_fixed_different_patterns_per_head():
    cfg = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=4,
                              num_global_blocks=1,
                              different_layout_per_head=True,
                              num_different_global_patterns=4)
    layout = cfg.make_layout(16 * 8)
    # head h uses global column (3 - h) within each window
    for h in range(4):
        assert (layout[h, :, 3 - h] == 1).all()
    assert not (layout[0] == layout[1]).all()


def test_variable_layout():
    cfg = VariableSparsityConfig(num_heads=1, block=16, num_random_blocks=0,
                                 local_window_blocks=[2, 4],
                                 global_block_indices=[0])
    layout = cfg.make_layout(16 * 10)
    assert (layout[0, :2, :2] == 1).all()     # first window: 2 blocks
    assert (layout[0, 2:6, 2:6] == 1).all()   # second window: 4 blocks
    assert (layout[0, 6:10, 6:10] == 1).all()  # last width repeats
    assert (layout[0, :, 0] == 1).all()       # global col 0
    assert layout[0, 1, 3] == 0


def test_variable_global_ranges():
    cfg = VariableSparsityConfig(num_heads=1, block=16,
                                 global_block_indices=[0, 4],
                                 global_block_end_indices=[2, 5],
                                 horizontal_global_attention=True)
    layout = cfg.make_layout(16 * 8)
    for c in (0, 1, 4):
        assert (layout[0, :, c] == 1).all()
        assert (layout[0, c, :] == 1).all()


def test_bigbird_layout():
    cfg = BigBirdSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1, seed=0)
    layout = cfg.make_layout(16 * 8)
    nb = 8
    rows = np.arange(nb)
    window = np.abs(rows[:, None] - rows[None, :]) <= 1
    assert (layout[0][window] == 1).all()
    assert (layout[0, 0, :] == 1).all()
    assert (layout[0, :, 0] == 1).all()
    # every row has >= 1 random block beyond structure (may overlap)
    assert (layout[0].sum(-1) >= window.sum(-1)).all()


def test_bslongformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[0])
    layout = cfg.make_layout(16 * 8)
    assert (layout[0, 0, :] == 1).all()
    assert (layout[0, :, 0] == 1).all()
    assert layout[0, 4, 3] == 1 and layout[0, 4, 5] == 1
    assert layout[0, 4, 6] == 0


def test_build_block_index():
    layout = np.array([[[1, 0, 1], [0, 1, 0], [1, 1, 1]]])
    counts, idx = build_block_index(layout)
    assert counts.tolist() == [[2, 1, 3]]
    assert idx[0, 0, :2].tolist() == [0, 2]
    assert idx[0, 2].tolist() == [0, 1, 2]


# --- kernel vs dense reference ---------------------------------------------

def _dense_reference(q, k, v, layout, block, causal=False, kpm=None,
                     bias=None):
    """Plain-jnp attention with the block layout expanded to an element
    mask."""
    mask = np.kron(np.asarray(layout), np.ones((block, block))) > 0
    s = q.shape[2]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if kpm is not None:
        scores = scores + kpm[:, None, None, :]
    if bias is not None:
        scores = scores + bias[None, None]
    if causal:
        cm = np.tril(np.ones((s, s), bool))
        mask = mask & cm[None]
    scores = jnp.where(mask[None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_kernel_matches_dense(causal):
    block, nb, heads, batch, d = 16, 4, 2, 2, 32
    seq = block * nb
    cfg = FixedSparsityConfig(num_heads=heads, block=block,
                              num_local_blocks=2, num_global_blocks=1,
                              attention="unidirectional" if causal
                              else "bidirectional")
    layout = cfg.make_layout(seq)
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(batch, heads, seq, d), jnp.float32)
               for _ in range(3))
    attn = make_block_sparse_attention(layout, block, causal=causal,
                                       interpret=True)
    out = attn(q, k, v)
    ref = _dense_reference(q, k, v, layout, block, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_kernel_gradients_match_dense():
    block, nb, heads, batch, d = 16, 4, 1, 1, 16
    seq = block * nb
    layout = BSLongformerSparsityConfig(
        num_heads=heads, block=block,
        num_sliding_window_blocks=3).make_layout(seq)
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(batch, heads, seq, d), jnp.float32)
               for _ in range(3))
    attn = make_block_sparse_attention(layout, block, interpret=True)

    def loss_sparse(q, k, v):
        return (attn(q, k, v) ** 2).sum()

    def loss_dense(q, k, v):
        return (_dense_reference(q, k, v, layout, block) ** 2).sum()

    g_sparse = jax.grad(loss_sparse, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gs, gd in zip(g_sparse, g_dense):
        np.testing.assert_allclose(gs, gd, atol=1e-4, rtol=1e-4)


def test_kernel_causal_fully_masked_row():
    # A q block whose only active k block sits strictly above the causal
    # diagonal: every score is masked, output must be 0 with 0 gradients
    # (not exp(NEG_INF - NEG_INF) = 1 garbage).
    block, d = 16, 16
    layout = np.array([[[0, 1], [1, 1]]])  # q block 0 sees only k block 1
    rng = np.random.RandomState(5)
    q, k, v = (jnp.asarray(rng.randn(1, 1, 32, d), jnp.float32)
               for _ in range(3))
    attn = make_block_sparse_attention(layout, block, causal=True,
                                       interpret=True)
    out = attn(q, k, v)
    assert not np.isnan(np.asarray(out)).any()
    np.testing.assert_allclose(out[0, 0, :block], 0.0, atol=1e-6)
    grads = jax.grad(lambda *a: (attn(*a) ** 2).sum(), argnums=(0, 1, 2))(
        q, k, v)
    for g in grads:
        assert not np.isnan(np.asarray(g)).any()
    np.testing.assert_allclose(grads[0][0, 0, :block], 0.0, atol=1e-6)


def test_kernel_with_masks():
    block, nb, heads, batch, d = 16, 2, 1, 2, 16
    seq = block * nb
    layout = DenseSparsityConfig(num_heads=heads,
                                 block=block).make_layout(seq)
    rng = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rng.randn(batch, heads, seq, d), jnp.float32)
               for _ in range(3))
    kpm = jnp.asarray(rng.randn(batch, seq), jnp.float32)
    bias = jnp.asarray(rng.randn(seq, seq), jnp.float32)
    attn = make_block_sparse_attention(layout, block, has_kpm=True,
                                       has_bias=True, interpret=True)
    out = attn(q, k, v, kpm, bias)
    ref = _dense_reference(q, k, v, layout, block, kpm=kpm, bias=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_pair_index_balanced_worklist():
    """build_pair_index flattens exactly the active pairs (the sdd_segment
    analogue): grid work equals layout.sum(), rows stay contiguous, empty
    rows get one masked dummy so their output block is still visited."""
    from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
        build_pair_index)
    layout = np.zeros((1, 4, 4), np.int64)
    layout[0, 0, :] = 1          # global row: 4 actives
    layout[0, 2, 1:3] = 1        # 2 actives
    layout[0, 3, 3] = 1          # 1 active
    # row 1 empty
    rows, cols, valid = build_pair_index(layout)
    assert valid.sum() == layout.sum()              # no padded work
    assert rows.shape[-1] == int(layout.sum()) + 1  # + one dummy (row 1)
    real = [(r, c) for r, c, v in zip(rows[0], cols[0], valid[0]) if v]
    assert real == [(0, 0), (0, 1), (0, 2), (0, 3), (2, 1), (2, 2), (3, 3)]
    # every q-row appears (dummy included), and rows are sorted/contiguous
    assert set(rows[0].tolist()) == {0, 1, 2, 3}
    assert (np.diff(rows[0]) >= 0).all()


def test_pair_index_per_head_padding():
    from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
        build_pair_index)
    layout = np.zeros((2, 3, 3), np.int64)
    layout[0] = np.eye(3, dtype=np.int64)           # 3 pairs
    layout[1, :, :] = 1                             # 9 pairs
    rows, cols, valid = build_pair_index(layout)
    assert rows.shape == (2, 9)
    assert valid[0].sum() == 3 and valid[1].sum() == 9
    # head-0 pads repeat its last real pair (keeps run bounds intact)
    assert (rows[0, 3:] == rows[0, 2]).all()
    assert (valid[0, 3:] == 0).all()


def test_sliding_window_layout_and_class():
    from deepspeed_tpu.ops.sparse_attention import SlidingWindowSparsityConfig
    cfg = SlidingWindowSparsityConfig(num_heads=2, block=16,
                                      num_sliding_window_blocks=3)
    layout = cfg.make_layout(16 * 6)
    assert layout.shape == (2, 6, 6)
    # causal by construction: nothing above the diagonal
    assert np.triu(layout[0], 1).sum() == 0
    # each row attends exactly its previous min(window, row+1) blocks
    for r in range(6):
        assert layout[0, r].sum() == min(3, r + 1)
        assert layout[0, r, max(0, r - 2):r + 1].all()
    assert cfg.requires_causal


def test_sliding_window_end_to_end_from_ds_config():
    """ds_config dict -> DeepSpeedConfig -> sparsity_config_from_dict ->
    SparseSelfAttention, numerically matched against dense attention with
    the same window mask — the full blessed path for the measured-fastest
    sparse mode."""
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    from deepspeed_tpu.ops.sparse_attention import sparsity_config_from_dict
    import jax as _jax
    world = _jax.device_count()
    heads, block, seq, d = 2, 16, 96, 16
    cfg = DeepSpeedConfig(None, param_dict={
        "train_batch_size": world,
        "sparse_attention": {"mode": "sliding_window", "block": block,
                             "num_sliding_window_blocks": 2},
    })
    sparsity = sparsity_config_from_dict(cfg.sparse_attention, heads)
    module = SparseSelfAttention(sparsity, max_seq_length=seq * 2,
                                 interpret=True)
    # the module picked up intra-block causality from the layout class
    assert module.causal
    rng = np.random.RandomState(11)
    q, k, v = (jnp.asarray(rng.randn(1, heads, seq, d), jnp.float32)
               for _ in range(3))
    out = module(q, k, v)
    ref = _dense_reference(q, k, v, sparsity.make_layout(seq), block,
                           causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


# --- module API -------------------------------------------------------------

def test_sparse_self_attention_module():
    heads, block, seq, d = 2, 16, 64, 16
    cfg = FixedSparsityConfig(num_heads=heads, block=block,
                              num_local_blocks=2)
    module = SparseSelfAttention(cfg, max_seq_length=128, interpret=True)
    rng = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.randn(1, heads, seq, d), jnp.float32)
               for _ in range(3))
    out = module(q, k, v)
    assert out.shape == q.shape
    layout = cfg.make_layout(seq)
    ref = _dense_reference(q, k, v, layout, block)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_sparse_self_attention_mul_key_padding():
    heads, block, seq, d = 1, 16, 32, 16
    module = SparseSelfAttention(DenseSparsityConfig(num_heads=heads,
                                                     block=block),
                                 key_padding_mask_mode="mul",
                                 max_seq_length=64, interpret=True)
    rng = np.random.RandomState(4)
    q, k, v = (jnp.asarray(rng.randn(2, heads, seq, d), jnp.float32)
               for _ in range(3))
    keep = jnp.asarray(rng.rand(2, seq) > 0.3, jnp.float32)
    out = module(q, k, v, key_padding_mask=keep)
    kpm_bias = jnp.where(keep != 0, 0.0, -1e30)
    layout = np.ones((heads, seq // block, seq // block))
    ref = _dense_reference(q, k, v, layout, block, kpm=kpm_bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


# --- utils ------------------------------------------------------------------

def test_pad_to_block_size_roundtrip():
    ids = jnp.arange(2 * 30).reshape(2, 30)
    mask = jnp.ones((2, 30), jnp.int32)
    pad_len, p_ids, p_mask, _, _, _ = SparseAttentionUtils.pad_to_block_size(
        block_size=16, input_ids=ids, attention_mask=mask, pad_token_id=7)
    assert pad_len == 2 and p_ids.shape == (2, 32)
    assert (p_ids[:, 30:] == 7).all() and (p_mask[:, 30:] == 0).all()
    out = SparseAttentionUtils.unpad_sequence_output(
        pad_len, p_ids[:, :, None])
    assert out.shape == (2, 30, 1)


def test_extend_position_embedding():
    w = jnp.arange(8.0).reshape(4, 2)
    ext = SparseAttentionUtils.extend_position_embedding(w, 8)
    assert ext.shape == (8, 2)
    np.testing.assert_allclose(ext[4:], w)


@pytest.mark.slow
def test_per_head_different_layouts_match_reference():
    """different_layout_per_head=True exercises the NON-shared prefetch
    path (per-head SMEM index lists + hsel index maps) — every head's
    output must match the dense masked reference for ITS layout."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention import (
        make_block_sparse_attention)

    b, h, s, d, block = 2, 3, 128, 32, 16
    nb = s // block
    rng = np.random.RandomState(3)
    # hand-built, genuinely different per-head layouts (diag + head-dep)
    layout = np.zeros((h, nb, nb), np.int64)
    for hi in range(h):
        for qi in range(nb):
            layout[hi, qi, qi] = 1                       # diagonal
            layout[hi, qi, (qi * (hi + 2)) % nb] = 1     # head-dependent
    assert not (layout == layout[:1]).all()

    q = jnp.asarray(rng.randn(b, h, s, d) * 0.3, jnp.float32)
    k = jnp.asarray(rng.randn(b, h, s, d) * 0.3, jnp.float32)
    v = jnp.asarray(rng.randn(b, h, s, d) * 0.3, jnp.float32)

    attn = make_block_sparse_attention(layout, block, causal=False,
                                       interpret=True)
    out = attn(q, k, v, None, None)

    # dense masked reference per head
    scale = 1.0 / (d ** 0.5)
    mask = np.kron(layout, np.ones((block, block))).astype(bool)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    scores = jnp.where(jnp.asarray(mask)[None], scores, -1e30)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    # gradients flow through the per-head path too
    g = jax.grad(lambda q: attn(q, k, v, None, None).sum())(q)
    gr = jax.grad(lambda q: jnp.einsum(
        "bhqk,bhkd->bhqd",
        jax.nn.softmax(jnp.where(jnp.asarray(mask)[None],
                                 jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale,
                                 -1e30), axis=-1), v).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=2e-3, atol=2e-3)


def test_causal_sliding_window_layout():
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        causal_sliding_window_layout)
    lay = causal_sliding_window_layout(2, 6, 3)
    assert lay.shape == (2, 6, 6)
    # row 4 attends blocks 2..4 only
    assert lay[0, 4].tolist() == [0, 0, 1, 1, 1, 0]
    # constant active count once past the ramp-in
    assert (lay[0].sum(-1)[2:] == 3).all()
    # strictly causal
    assert not np.triu(lay[0], 1).any()


def test_build_group_index_packs_rows():
    """build_group_index chunks each row's active columns into packs of
    G, pads partial groups with repeats marked invalid, and gives empty
    rows one all-invalid group (the kernel's per-step worklist)."""
    from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
        build_group_index)
    layout = np.zeros((1, 4, 4), np.int64)
    layout[0, 0, :] = 1          # 4 actives -> 2 groups of 2
    layout[0, 2, 1:3] = 1        # 2 actives -> 1 full group
    layout[0, 3, 3] = 1          # 1 active  -> 1 group, 1 pad slot
    # row 1 empty               -> 1 all-invalid group
    rows, cols, valid = build_group_index(layout, 2)
    assert rows.shape == (1, 5) and cols.shape == (1, 5, 2)
    assert valid.sum() == layout.sum()          # pads carry no work
    assert rows[0].tolist() == [0, 0, 1, 2, 3]  # sorted, runs contiguous
    assert cols[0, 0].tolist() == [0, 1] and cols[0, 1].tolist() == [2, 3]
    assert valid[0, 2].tolist() == [0, 0]       # empty row: all masked
    assert cols[0, 4].tolist() == [3, 3]        # pad repeats last real col
    assert valid[0, 4].tolist() == [1, 0]


def test_build_group_index_head_padding():
    from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
        build_group_index)
    layout = np.zeros((2, 3, 3), np.int64)
    layout[0] = np.eye(3, dtype=np.int64)       # 3 groups (pack 2)
    layout[1, :, :] = 1                         # 6 groups
    rows, cols, valid = build_group_index(layout, 2)
    assert rows.shape == (2, 6)
    assert valid[0].sum() == 3 and valid[1].sum() == 9
    # head-0's pad groups repeat its last row, all-invalid
    assert (rows[0, 3:] == rows[0, 2]).all()
    assert (valid[0, 3:] == 0).all()


def test_pack_sizes_agree_with_reference():
    """The same layout must produce identical attention at every pack
    (pack is a pure execution-shape knob)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention import (
        FixedSparsityConfig, make_block_sparse_attention)
    rng = np.random.RandomState(3)
    H, S, D, block = 2, 128, 16, 16
    cfg = FixedSparsityConfig(num_heads=H, block=block, num_local_blocks=2,
                              num_global_blocks=1,
                              attention="unidirectional")
    layout = np.asarray(cfg.make_layout(S))
    q = jnp.asarray(rng.randn(1, H, S, D) * 0.3, jnp.float32)
    outs = []
    grads = []
    for pack in (1, 2, 4):
        attn = make_block_sparse_attention(layout, block, causal=True,
                                           interpret=True, pack=pack)
        outs.append(np.asarray(attn(q, q, q, None, None)))
        grads.append(np.asarray(jax.grad(
            lambda t, a=attn: a(t, t, t, None, None).sum())(q)))
    for o in outs[1:]:
        np.testing.assert_allclose(o, outs[0], rtol=1e-5, atol=1e-5)
    for g in grads[1:]:
        np.testing.assert_allclose(g, grads[0], rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_packed_heads_path_matches_dense(causal):
    """The packed-heads kernels (shared layout, (H*d) % 128 == 0: all
    heads per grid step on (block, H*d) slabs) match the dense reference
    exactly — forward and gradients."""
    block, nb, heads, batch, d = 16, 4, 4, 2, 32     # H*d = 128
    seq = block * nb
    cfg = FixedSparsityConfig(num_heads=heads, block=block,
                              num_local_blocks=2, num_global_blocks=1,
                              attention="unidirectional" if causal
                              else "bidirectional")
    layout = cfg.make_layout(seq)
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(batch, heads, seq, d) * 0.3,
                           jnp.float32) for _ in range(3))
    attn = make_block_sparse_attention(layout, block, causal=causal,
                                       interpret=True)
    out = attn(q, k, v)
    ref = _dense_reference(q, k, v, layout, block, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss(fn, q, k, v):
        return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

    g_pk = jax.grad(loss, argnums=(1, 2, 3))(attn, q, k, v)
    ref_fn = lambda q, k, v: _dense_reference(q, k, v, layout, block,
                                              causal=causal)
    g_ref = jax.grad(loss, argnums=(1, 2, 3))(ref_fn, q, k, v)
    for name, a, b in zip("qkv", g_pk, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4,
                                   err_msg=name)


@pytest.mark.slow
def test_packed_heads_path_with_masks_matches_per_head(monkeypatch):
    """kpm/bias handling is identical across the packed and per-head
    paths (DS_SPARSE_PACKED=0 forces per-head)."""
    block, nb, heads, batch, d = 16, 4, 4, 2, 32
    seq = block * nb
    layout = FixedSparsityConfig(
        num_heads=heads, block=block, num_local_blocks=2,
        num_global_blocks=1, attention="bidirectional").make_layout(seq)
    rng = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rng.randn(batch, heads, seq, d) * 0.3,
                           jnp.float32) for _ in range(3))
    kpm = jnp.asarray(rng.randn(batch, seq), jnp.float32)
    bias = jnp.asarray(rng.randn(seq, seq) * 0.2, jnp.float32)
    monkeypatch.delenv("DS_SPARSE_PACKED", raising=False)
    attn_pk = make_block_sparse_attention(layout, block, has_kpm=True,
                                          has_bias=True, interpret=True)
    monkeypatch.setenv("DS_SPARSE_PACKED", "0")
    attn_ph = make_block_sparse_attention(layout, block, has_kpm=True,
                                          has_bias=True, interpret=True)
    monkeypatch.delenv("DS_SPARSE_PACKED")
    out_pk = attn_pk(q, k, v, kpm, bias)
    out_ph = attn_ph(q, k, v, kpm, bias)
    np.testing.assert_allclose(out_pk, out_ph, atol=2e-5, rtol=2e-5)

    def loss(fn, q, k, v):
        return (fn(q, k, v, kpm, bias).astype(jnp.float32) ** 2).sum()

    g_pk = jax.grad(loss, argnums=(1, 2, 3))(attn_pk, q, k, v)
    g_ph = jax.grad(loss, argnums=(1, 2, 3))(attn_ph, q, k, v)
    for name, a, b in zip("qkv", g_pk, g_ph):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=name)
