"""Streamed parameter offload (cpu_offload_params) correctness.

Numerics contract (pinned here):
  * segmenting the forward is EXACT — in fp32 the segment composition
    bit-matches the monolithic lm_loss even across separate jit calls;
  * in bf16 compute, separate jit programs materialize the boundary
    activation in bf16 where one fused program may keep a wider
    intermediate, so streamed-vs-monolithic losses agree to ~1e-4 (the
    double-rounding is the ONLY divergence source — the streaming
    machinery itself adds zero error, pinned by the bit-exact
    reference comparison below);
  * the transfer machinery (double-buffered uploads, coalescing
    buckets, sub_group chunking) is value-preserving: any two transfer
    configurations over the same group layout produce bit-identical
    steps.
"""
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import gpt2


CFG = gpt2.GPT2Config(vocab_size=256, max_seq_len=64, n_layers=4,
                      n_heads=2, d_model=64, use_flash_attention=False,
                      remat=False, loss_chunk=0)


def _engine(zero_extra=None, gas=1):
    zero = {"stage": 3, "cpu_offload": True}
    zero.update(zero_extra or {})
    engine, _, _, _ = deepspeed.initialize(
        model=gpt2.make_gpt2_model(config=CFG),
        config_params={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": gas,
            "bf16": {"enabled": True},
            "zero_optimization": zero,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 10 ** 9,
        })
    return engine


def _stream_engine(extra=None, gas=1):
    zero = {"cpu_offload_params": True}
    zero.update(extra or {})
    return _engine(zero, gas=gas)


def _ids(n_rows=2):
    rng = np.random.RandomState(0)
    return rng.randint(0, CFG.vocab_size,
                       size=(n_rows, CFG.max_seq_len)).astype(np.int32)


# ------------------------------------------------------ exact segmentation
def test_fp32_segmented_forward_bitmatches_monolithic():
    """The StreamSpec decomposition is exact: in fp32 even separately
    jitted segments reproduce lm_loss bit for bit."""
    params = gpt2.init_params(CFG, seed=0)
    spec = gpt2.stream_spec_for(CFG)
    ids = jnp.asarray(_ids(4))
    mono = float(jax.jit(
        lambda p, i: gpt2.lm_loss(p, i, i, CFG, rng=None,
                                  train=True))(params, ids))
    e, blocks, h = spec.split(params)
    x = jax.jit(lambda e, b: spec.embed_apply(e, b, None, True))(
        e, (ids, ids))
    for bt in blocks:
        x = jax.jit(lambda bt, x: spec.block_apply(bt, x, None, True))(
            bt, x)
    seg = float(jax.jit(
        lambda h, x, b: spec.head_apply(h, x, b, None, True))(
            h, x, (ids, ids)))
    assert seg == mono


def test_streamed_step_matches_segment_reference_bitwise():
    """The full streaming machinery (coalesced uploads, double-buffered
    prefetch, packed grad D2H) adds ZERO numeric error: the engine's
    streamed loss bit-matches a plain segment-by-segment recomputation
    from the same host masters."""
    engine = _stream_engine({"stage3_max_live_parameters": 120_000})
    assert len(engine.stream_runner.groups) > 1
    spec = engine.model.stream_spec
    masters, _, _ = engine.stream_runner._host_trees()
    cd = np.dtype(engine.compute_dtype)
    ref_params = jax.tree_util.tree_map(lambda p: p.astype(cd), masters)
    ids = _ids()
    loss = float(engine(ids, ids.copy()))

    e, blocks, h = spec.split(ref_params)
    x = jax.jit(lambda e, b: spec.embed_apply(e, b, None, True))(
        e, (jnp.asarray(ids), jnp.asarray(ids)))
    # group-for-group like the runner (jit boundaries must line up for
    # bf16 boundary materialization to agree)
    for start, stop in engine.stream_runner.groups:
        group = blocks[start:stop]

        def gfn(group, x):
            for bt in group:
                x = spec.block_apply(bt, x, None, True)
            return x
        x = jax.jit(gfn)(group, x)
    ref = float(jax.jit(
        lambda h, x, b: spec.head_apply(h, x, b, None, True))(
            h, x, (jnp.asarray(ids), jnp.asarray(ids))))
    assert loss == ref


# --------------------------------------------- streamed vs classic offload
def test_streamed_tracks_classic_offload():
    """Streamed and classic-offload engines agree to bf16-boundary
    tolerance across steps (see module docstring for why not bitwise)."""
    classic = _engine()
    streamed = _stream_engine()
    ids = _ids()
    for _ in range(3):
        lc = classic(ids, ids.copy())
        classic.backward(lc)
        classic.step()
        lst = streamed(ids, ids.copy())
        streamed.backward(lst)
        streamed.step()
        assert np.isfinite(float(lst))
        assert abs(float(lst) - float(lc)) / abs(float(lc)) < 2e-4, \
            (float(lst), float(lc))
    # eval parity too
    classic.eval()
    streamed.eval()
    runner = streamed.stream_runner
    # transfer_snapshot is a read-only probe: calling it twice (a user
    # debugging mid-step) must not zero the counters the telemetry emit
    # path will embed — only reset_step_counters() opens a new window
    assert runner.transfer_snapshot() == runner.transfer_snapshot()
    before = (dict(runner.phase_times), runner._step_upload_batches,
              runner._step_upload_elems)
    ec, es = float(classic(ids, ids.copy())), float(streamed(ids,
                                                             ids.copy()))
    assert abs(es - ec) / abs(ec) < 2e-4
    # eval uploads must not leak into the NEXT train step's telemetry
    # (phase clocks and transfer counters are per-optimizer-step)
    after = (dict(runner.phase_times), runner._step_upload_batches,
             runner._step_upload_elems)
    assert after == before


# --------------------------------------------- double-buffer correctness
def test_transfer_config_is_value_preserving():
    """Same group layout, radically different transfer machinery
    (1-element coalescing buckets forcing one flush per leaf vs one
    giant bucket; tiny sub_group Adam chunks) -> bit-identical steps.
    This is the double-buffer correctness pin: overlap can reorder
    transfers, never values."""
    live = {"stage3_max_live_parameters": 120_000}
    a = _stream_engine({**live, "stage3_prefetch_bucket_size": 1,
                        "sub_group_size": 256})
    b = _stream_engine({**live, "stage3_prefetch_bucket_size": 10 ** 9})
    assert a.stream_runner.groups == b.stream_runner.groups
    ids = _ids()
    for _ in range(2):
        la = a(ids, ids.copy()); a.backward(la); a.step()
        lb = b(ids, ids.copy()); b.backward(lb); b.step()
        assert float(la) == float(lb)
    for pa, pb in zip(
            jax.tree_util.tree_leaves(a.get_master_params()),
            jax.tree_util.tree_leaves(b.get_master_params())):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


# ------------------------------------------------------- budget / groups
def test_live_budget_sizes_groups():
    one = _stream_engine({"stage3_max_live_parameters": 10 ** 9})
    many = _stream_engine({"stage3_max_live_parameters": 120_000})
    assert len(one.stream_runner.groups) == 1
    assert len(many.stream_runner.groups) > 1
    ids = _ids()
    l1 = float(one(ids, ids.copy()))
    assert np.isfinite(l1)


# ----------------------------------------------------- accumulation, ckpt
def test_gas2_train_batch_and_checkpoint_resume():
    ids = np.stack([_ids(), _ids()])        # (gas, batch, seq)
    a = _stream_engine(gas=2)
    l1 = a.train_batch(batch=(ids, ids.copy()))
    assert np.isfinite(float(l1))
    with tempfile.TemporaryDirectory() as d:
        a.save_checkpoint(d, tag="t1")
        l2 = a.train_batch(batch=(ids, ids.copy()))
        b = _stream_engine(gas=2)
        path, _ = b.load_checkpoint(d, tag="t1")
        assert path is not None
        l2b = b.train_batch(batch=(ids, ids.copy()))
        assert float(l2) == float(l2b)


def test_grad_norm_prices_tied_leaves_once():
    """The streamed grad norm must be ||sum of contributions||, not the
    per-segment sum of squares (wte appears in embed AND head): it has
    to match the classic engine's norm to bf16-boundary tolerance."""
    classic = _engine()
    streamed = _stream_engine()
    ids = _ids()
    for eng in (classic, streamed):
        loss = eng(ids, ids.copy())
        eng.backward(loss)
        eng.step()
    gn_c = classic.get_global_grad_norm()
    gn_s = streamed.get_global_grad_norm()
    assert abs(gn_s - gn_c) / gn_c < 1e-3, (gn_s, gn_c)


def test_tied_wte_gets_both_grad_contributions():
    """GPT-2's wte is used by the embed AND head segments; the streamed
    grads must sum both (a missing contribution would diverge from the
    classic engine within one step)."""
    engine = _stream_engine()
    ids = _ids()
    loss = engine(ids, ids.copy())
    engine.backward(loss)
    runner = engine.stream_runner
    # before the optimizer step the wte slot buffer must be populated
    # from two segment fetches: embed (wte+wpe) and head (ln_f+wte)
    wte_slots = [i for i, s in enumerate(runner._e_slots)
                 if s in runner._h_slots]
    assert wte_slots, "embed and head must share the wte slot"
    engine.step()
