"""Pallas kernel tier, training side (ISSUE 18): flash + block-sparse
attention and the fused Adam/LAMB apply kernels vs their XLA oracles,
in interpreter mode on CPU.

Contracts pinned here (docs/pallas_kernels.md):

* flash attention matches the dense softmax oracle forward and backward
  at causal, key-padded, and odd-tile shapes (seq not a multiple of the
  kernel blocks);
* training through the engine with ``transformer.flash_attention:
  "pallas"`` produces the SAME fp32 loss as the dense XLA oracle
  (first step <= 1e-6; later steps track through the param updates);
* block-sparse attention matches masked-dense per layout family
  (fixed / BSLongformer / BigBird / variable), forward and gradients,
  and composes with the ring over ``sequence`` at world 2 and 4;
* the fused Adam kernel is BITWISE-identical to the jnp oracle at fp32
  (same jit scope); LAMB is bitwise on tile-aligned leaves and within
  1 ulp on ragged ones (the trust-ratio norm reduces over the padded
  (rows, 128) tile layout, a different summation order than the
  oracle's original-shape reduce) — including the zero-norm leaf
  (trust ratio 1.0) and the fp16 overflow-skip step;
* ``pl.CostEstimate`` declarations are what MFU pricing charges when
  XLA ``cost_analysis`` prices the custom call at zero flops
  (``pallas_declared_costs`` jaxpr walk, merged in
  ``costs_of_compiled``);
* ``bin/ds_lint.py`` DSL011 flags ``pl.pallas_call`` sites under
  ``deepspeed_tpu/ops/`` that drop ``cost_estimate=``, and the repo
  itself stays green under the rule.
"""
import contextlib
import functools
import logging
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.ops.adam.fused_adam import FusedAdam, adam_init, \
    adam_update
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb, lamb_init, \
    lamb_update
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, FixedSparsityConfig,
    VariableSparsityConfig, make_block_sparse_attention)
from deepspeed_tpu.ops.transformer.attention import (
    NEG_INF, resolve_flash_backend)
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention_bshd
from deepspeed_tpu.parallel import (build_mesh,
                                    sequence_parallel_sparse_attention)
from deepspeed_tpu.telemetry import mfu_of
from deepspeed_tpu.telemetry.collector import (costs_of_compiled,
                                               pallas_declared_costs)
from deepspeed_tpu.utils.logging import logger as ds_logger

pytestmark = pytest.mark.pallas

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


@contextlib.contextmanager
def _capture_warnings():
    """The DS logger has propagate=False, so caplog can't see it; attach
    a handler directly (the repo's test_telemetry idiom)."""
    messages = []

    class _Cap(logging.Handler):
        def emit(self, record):
            messages.append(record.getMessage())

    cap = _Cap(level=logging.WARNING)
    ds_logger.addHandler(cap)
    try:
        yield messages
    finally:
        ds_logger.removeHandler(cap)


# ------------------------------------------------------------ flash vs dense

def _dense_bshd(q, k, v, causal=True, mask_bias=None, sm_scale=None):
    """Dense softmax oracle over (b, s, h, d) with the flash kernel's key
    bias convention."""
    b, s, h, d = q.shape
    scale = sm_scale or 1.0 / d ** 0.5
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                    preferred_element_type=jnp.float32) * scale
    if mask_bias is not None:
        sc = sc + mask_bias[:, None, None, :]
    if causal:
        sc = jnp.where(jnp.tril(jnp.ones((s, s), bool))[None, None],
                       sc, NEG_INF)
    p = jax.nn.softmax(sc, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(p.dtype)).astype(q.dtype)


def _qkv(b, s, h, d, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d) * 0.5, jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("b,s,h,d,pad", [
    (2, 160, 2, 32, 0),      # odd tile: s % 128 != 0
    (1, 192, 4, 32, 48),     # key padding via mask_bias
    (2, 136, 2, 24, 0),      # odd tile AND odd head dim
])
def test_flash_matches_dense_causal_padded_odd_tile(b, s, h, d, pad):
    q, k, v = _qkv(b, s, h, d)
    mb = None
    if pad:
        m = np.zeros((b, s), np.float32)
        m[:, s - pad:] = -1e9
        mb = jnp.asarray(m)
    out = flash_attention_bshd(q, k, v, None, True, interpret=True,
                               mask_bias=mb)
    ref = _dense_bshd(q, k, v, True, mb)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)
    # padded keys must not leak mass into the visible region
    if pad:
        assert np.isfinite(np.asarray(out)).all()

    g_fl = jax.grad(lambda q: (flash_attention_bshd(
        q, k, v, None, True, interpret=True, mask_bias=mb) ** 2).sum())(q)
    g_ref = jax.grad(lambda q: (_dense_bshd(q, k, v, True, mb) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g_fl), np.asarray(g_ref),
                               atol=5e-6, rtol=5e-6)


def test_engine_flash_pallas_training_loss_matches_dense_oracle():
    """The acceptance bar: the dryrun-shaped GPT-2 trained with
    ``transformer.flash_attention: "pallas"`` (interpret off-TPU) holds
    fp32 loss parity with the dense XLA oracle — step 1 within 1e-6,
    later steps tracking through the (slightly diverging) updates."""
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    def make(backend):
        conf = {
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 10 ** 9,
            "transformer": {"flash_attention": backend},
        }
        cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=32, n_layers=1,
                              n_heads=2, d_model=32, dropout=0.0,
                              use_flash_attention=False, remat=False,
                              loss_chunk=0)
        return DeepSpeedEngine(model=gpt2.make_gpt2_model(config=cfg),
                               config_params=conf)

    e_flash = make("pallas")
    e_dense = make("xla")
    assert e_flash.flash_attention_backend == "interpret"
    assert e_dense.flash_attention_backend == "xla"

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 128, size=(4, 2, 33))
    diffs = []
    for i, tok in enumerate(tokens):
        x, y = tok[:, :-1], tok[:, 1:]
        l1 = e_flash(x, y)
        e_flash.backward(l1)
        e_flash.step()
        l2 = e_dense(x, y)
        e_dense.backward(l2)
        e_dense.step()
        diffs.append(abs(float(l1) - float(l2)))
    assert diffs[0] <= 1e-6, diffs
    assert max(diffs) <= 5e-5, diffs


# ----------------------------------------------------- block-sparse vs dense

def _dense_sparse_ref(q, k, v, layout, block, causal):
    """Masked-dense oracle over (b, h, s, d): layout expanded to an
    element mask, softmax over the visible scores only."""
    mask = np.kron(np.asarray(layout), np.ones((block, block))) > 0
    s = q.shape[2]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        mask = mask & np.tril(np.ones((s, s), bool))[None]
    scores = jnp.where(mask[None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


_PATTERNS = {
    "fixed": lambda h, blk: FixedSparsityConfig(
        num_heads=h, block=blk, num_local_blocks=2, num_global_blocks=1),
    "bslongformer": lambda h, blk: BSLongformerSparsityConfig(
        num_heads=h, block=blk, num_sliding_window_blocks=3,
        global_block_indices=[0]),
    "bigbird": lambda h, blk: BigBirdSparsityConfig(
        num_heads=h, block=blk, num_random_blocks=1,
        num_sliding_window_blocks=3, num_global_blocks=1),
    "variable": lambda h, blk: VariableSparsityConfig(
        num_heads=h, block=blk, num_random_blocks=0,
        local_window_blocks=[2, 4], global_block_indices=[0]),
}


@pytest.mark.parametrize("pattern", sorted(_PATTERNS))
@pytest.mark.parametrize("causal", [False, True])
def test_block_sparse_matches_masked_dense_per_pattern(pattern, causal):
    block, nb, heads, batch, d = 16, 6, 2, 2, 32
    seq = block * nb
    layout = _PATTERNS[pattern](heads, block).make_layout(seq)
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(batch, heads, seq, d), jnp.float32)
               for _ in range(3))
    attn = make_block_sparse_attention(layout, block, causal=causal,
                                       interpret=True)
    out = attn(q, k, v)
    ref = _dense_sparse_ref(q, k, v, layout, block, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    g_sp = jax.grad(lambda q: (attn(q, k, v) ** 2).sum())(q)
    g_ref = jax.grad(lambda q: (_dense_sparse_ref(
        q, k, v, layout, block, causal) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g_sp), np.asarray(g_ref),
                               atol=1e-4, rtol=1e-4)


# ------------------------------------------------------------- ring + sparse

def _ring_sparse_oracle(q, k, v, layout, block, causal, scale):
    """Masked-dense over global (b, s, h, d) with the ring convention:
    rows with NO active key anywhere return 0 (the online-softmax
    accumulator never receives mass), not a uniform distribution."""
    b, s, h, d = q.shape
    L = np.asarray(layout, bool)
    if L.shape[0] == 1:
        L = np.broadcast_to(L, (h,) + L.shape[1:])
    em = np.repeat(np.repeat(L, block, 1), block, 2)
    if causal:
        em = em & np.tril(np.ones((s, s), bool))[None]
    em = jnp.asarray(em)[None]
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    sc = jnp.where(em, sc, NEG_INF)
    m = jnp.max(sc, -1, keepdims=True)
    p = jnp.where(em, jnp.exp(sc - m), 0.0)
    l = jnp.sum(p, -1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bhqd", p, v) / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(o, 1, 2)


@pytest.mark.parametrize("world", [2, 4])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_sparse_matches_masked_dense(world, causal):
    b, s, h, d, block = 2, 256, 4, 16, 16
    q, k, v = _qkv(b, s, h, d, seed=1)
    cfg = FixedSparsityConfig(
        num_heads=h, block=block, num_local_blocks=4, num_global_blocks=1,
        attention="unidirectional" if causal else "bidirectional")
    layout = np.asarray(cfg.make_layout(s))
    mesh = build_mesh(sequence=world)
    out = sequence_parallel_sparse_attention(q, k, v, mesh, layout, block,
                                             causal=causal)
    ref = _ring_sparse_oracle(q, k, v, layout, block, causal, 1.0 / d ** 0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_sparse_gradients_flow():
    b, s, h, d, block = 1, 128, 2, 16, 16
    q, k, v = _qkv(b, s, h, d, seed=2)
    layout = np.asarray(FixedSparsityConfig(
        num_heads=h, block=block, num_local_blocks=2,
        num_global_blocks=1).make_layout(s))
    mesh = build_mesh(sequence=2)

    def loss(q):
        return (sequence_parallel_sparse_attention(
            q, k, v, mesh, layout, block) ** 2).sum()

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).max()) > 0


# --------------------------------------------------------- fused Adam / LAMB

def _tree(shapes, seed=0):
    rng = np.random.RandomState(seed)
    return {f"p{i}": jnp.asarray(rng.randn(*shape), jnp.float32)
            for i, shape in enumerate(shapes)}


def _max_ulp(a, b):
    return int(np.abs(
        np.asarray(a).view(np.int32).astype(np.int64).ravel() -
        np.asarray(b).view(np.int32).astype(np.int64).ravel()).max())


def test_fused_adam_bitwise_vs_jnp_oracle():
    """Same jit scope on both sides (eager dispatch skips the FMA fusion
    jit applies, which alone costs 1 ulp) — the kernel is elementwise,
    so fp32 parity is exact."""
    shapes = [(8, 128), (33, 7), (231,), (5,), (4, 4)]
    params = _tree(shapes)
    grads = _tree(shapes, seed=1)
    st = adam_init(params)
    step = jax.jit(functools.partial(adam_update, use_pallas=False))
    step_pl = jax.jit(functools.partial(adam_update, use_pallas=True,
                                        interpret=True))
    hp = (1e-3, 0.9, 0.999, 1e-8, 0.01)
    p_ref, s_ref = step(grads, st, params, *hp)
    p_pl, s_pl = step_pl(grads, st, params, *hp)
    for kk in params:
        assert _max_ulp(p_ref[kk], p_pl[kk]) == 0, kk
        assert _max_ulp(s_ref["exp_avg"][kk], s_pl["exp_avg"][kk]) == 0, kk
        assert _max_ulp(s_ref["exp_avg_sq"][kk],
                        s_pl["exp_avg_sq"][kk]) == 0, kk


def test_fused_lamb_bitwise_vs_jnp_oracle_incl_zero_norm_leaf():
    shapes = [(8, 128), (16, 128), (1024,)]
    params = _tree(shapes)
    params["zero"] = jnp.zeros((4, 4), jnp.float32)  # trust-ratio-1.0 leaf
    grads = {k: jnp.asarray(np.random.RandomState(3).randn(*v.shape),
                            jnp.float32) for k, v in params.items()}
    st = lamb_init(params)
    step = jax.jit(functools.partial(lamb_update, use_pallas=False))
    step_pl = jax.jit(functools.partial(lamb_update, use_pallas=True,
                                        interpret=True))
    hp = (1e-3, 0.9, 0.999, 1e-8, 0.01)
    p_ref, _ = step(grads, st, params, *hp)
    p_pl, _ = step_pl(grads, st, params, *hp)
    for kk in params:
        assert _max_ulp(p_ref[kk], p_pl[kk]) == 0, kk
    # the zero-norm leaf took the trust_ratio=1.0 branch, not a 0/0
    assert np.isfinite(np.asarray(p_pl["zero"])).all()
    assert float(jnp.abs(p_pl["zero"]).max()) > 0   # grads still applied


def test_fused_lamb_ragged_leaf_within_one_ulp():
    """A ragged 1-D leaf reduces its trust-ratio norms over the padded
    (rows, 128) tile layout — a different summation order than the
    oracle's original-shape reduce; 1 ulp is the contract."""
    params = {"w": jnp.asarray(np.random.RandomState(0).randn(231),
                               jnp.float32)}
    grads = {"w": jnp.asarray(np.random.RandomState(1).randn(231),
                              jnp.float32)}
    st = lamb_init(params)
    hp = (1e-3, 0.9, 0.999, 1e-8, 0.01)
    p_ref, _ = jax.jit(functools.partial(lamb_update, use_pallas=False))(
        grads, st, params, *hp)
    p_pl, _ = jax.jit(functools.partial(
        lamb_update, use_pallas=True, interpret=True))(
        grads, st, params, *hp)
    assert _max_ulp(p_ref["w"], p_pl["w"]) <= 1


@pytest.mark.parametrize("opt_cls", [FusedAdam, FusedLamb])
def test_fp16_overflow_skip_with_pallas_kernel(opt_cls):
    """An inf gradient under the loss scaler skips the step with the
    pallas apply kernel enabled: params unchanged, scale halved."""
    from deepspeed_tpu.runtime.fp16.fused_optimizer import FP16_Optimizer
    opt = FP16_Optimizer(opt_cls(lr=1e-2, use_pallas=True),
                         dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 2 ** 8})
    params = {"w": jnp.ones((4, 4), dtype=jnp.bfloat16)}
    opt.initialize_state(params)
    bad = {"w": jnp.full((4, 4), jnp.inf, dtype=jnp.float32)}
    new_params, overflow = opt.step(bad, params)
    assert overflow
    assert opt.loss_scale == 2 ** 7
    np.testing.assert_array_equal(
        np.asarray(new_params["w"], np.float32),
        np.asarray(params["w"], np.float32))
    # ...and a clean step afterwards actually moves the params
    good = {"w": jnp.ones((4, 4), dtype=jnp.float32)}
    moved, overflow = opt.step(good, params)
    assert not overflow
    assert float(jnp.abs(moved["w"].astype(jnp.float32) -
                         params["w"].astype(jnp.float32)).max()) > 0


def test_optimizer_fused_kernel_config_key():
    """optimizer.params.fused_kernel tri-state: validated, observable on
    the engine, and loud when pallas is forced off-TPU."""
    import deepspeed_tpu as ds
    from simple_model import make_simple_model

    def engine(fused_kernel=None):
        params = {"lr": 1e-3}
        if fused_kernel is not None:
            params["fused_kernel"] = fused_kernel
        conf = {"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": params},
                "steps_per_print": 10 ** 9}
        eng, _, _, _ = ds.initialize(model=make_simple_model(8),
                                     config_params=conf)
        return eng

    assert engine().fused_optimizer_kernel is None
    assert engine("xla").optimizer.use_pallas is False
    with _capture_warnings() as msgs:
        e = engine("pallas")
    assert e.fused_optimizer_kernel == "pallas"
    assert e.optimizer.use_pallas is True
    assert any("pallas" in m.lower() for m in msgs), msgs
    with pytest.raises(ValueError):
        engine("triton")


# ------------------------------------------------- CostEstimate -> MFU price

def test_pallas_declared_costs_walk_finds_nested_kernels():
    """The pallas_call eqns hide inside custom_vjp/pjit sub-jaxprs; the
    walk must recurse. Values pinned to the _attn_cost formula:
    2 * mults * (b*h*s*s) * d * 0.5 causal."""
    q, k, v = _qkv(2, 192, 4, 32)
    fwd = lambda q, k, v: flash_attention_bshd(q, k, v, None, True,
                                               interpret=True)
    d = pallas_declared_costs(fwd, q, k, v)
    assert d["flops"] == 2 * 2 * (2 * 4 * 192 * 192) * 32 * 0.5
    assert d["transcendentals"] == 2 * 4 * 192 * 192 * 0.5
    assert d["bytes accessed"] > 0

    grad = lambda q, k, v: jax.grad(
        lambda q: fwd(q, k, v).sum())(q)
    dg = pallas_declared_costs(grad, q, k, v)
    assert dg["flops"] > d["flops"]     # fwd replay + bwd kernels

    # a program with no pallas_call declares nothing
    assert pallas_declared_costs(lambda q, k, v: q + k + v, q, k, v) == {}


def test_costs_of_compiled_merges_declared_costs_into_mfu():
    """When cost_analysis prices the program at zero flops (opaque
    custom call), the declared CostEstimate is what StepRecord MFU
    accounting charges."""
    q, k, v = _qkv(1, 128, 2, 32)
    real = jax.jit(lambda q, k, v: flash_attention_bshd(
        q, k, v, None, True, interpret=True))

    class Opaque:
        """A backend that refuses to cost the program."""

        def __call__(self, *a):
            return real(*a)

        def lower(self, *a):
            class L:
                def cost_analysis(self):
                    return {}

                def compile(self):
                    return self
            return L()

    costs = costs_of_compiled(Opaque(), q, k, v)
    expected = 2 * 2 * (1 * 2 * 128 * 128) * 32 * 0.5
    assert costs["flops"] == expected
    # and the MFU math sees a nonzero utilization from it
    assert mfu_of(costs["flops"], 0.01, 1, 1e12) > 0


def test_adam_lamb_kernels_carry_cost_estimates():
    params = {"w": jnp.ones((8, 128), jnp.float32)}
    grads = {"w": jnp.ones((8, 128), jnp.float32)}
    n = 8 * 128
    st = adam_init(params)
    d = pallas_declared_costs(
        functools.partial(adam_update, use_pallas=True, interpret=True),
        grads, st, params, 1e-3, 0.9, 0.999, 1e-8, 0.0)
    assert d["flops"] == 18 * n
    assert d["transcendentals"] == n
    assert d["bytes accessed"] == 7 * n * 4
    st = lamb_init(params)
    d = pallas_declared_costs(
        functools.partial(lamb_update, use_pallas=True, interpret=True),
        grads, st, params, 1e-3, 0.9, 0.999, 1e-8, 0.0)
    assert d["flops"] == 20 * n


def test_sparse_kernels_price_active_blocks_only():
    """The sparse CostEstimate must scale with the ACTIVE block pairs,
    not the dense nb^2 — a half-density layout prices at half the
    flops."""
    block, nb, heads, batch, d = 16, 4, 1, 1, 32
    seq = block * nb
    dense = np.ones((heads, nb, nb), np.int64)
    half = np.tril(np.ones((nb, nb), np.int64))[None]
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(batch, heads, seq, d), jnp.float32)
               for _ in range(3))

    def flops_of(layout):
        attn = make_block_sparse_attention(layout, block, interpret=True)
        return pallas_declared_costs(lambda q, k, v: attn(q, k, v),
                                     q, k, v)["flops"]

    f_dense, f_half = flops_of(dense), flops_of(half)
    assert f_half == f_dense * half.sum() / dense.sum()


# ----------------------------------------------------------- tri-state seams

def test_resolve_flash_backend_tristate_and_warns_once():
    from deepspeed_tpu.ops.transformer import attention as attn_mod
    assert resolve_flash_backend("xla") == "xla"
    assert resolve_flash_backend("auto") == "xla"      # CPU host
    assert resolve_flash_backend(False) == "xla"       # legacy bool
    assert resolve_flash_backend(True) == "xla"        # legacy bool = auto
    with pytest.raises(ValueError):
        resolve_flash_backend("triton")

    attn_mod._warned_forced_pallas.discard(jax.default_backend())
    with _capture_warnings() as msgs:
        assert resolve_flash_backend("pallas") == "interpret"
        assert resolve_flash_backend("pallas") == "interpret"
    assert len([m for m in msgs if "INTERPRETER" in m]) == 1, msgs


def test_telemetry_snapshot_exposes_resolved_kernels(tmp_path):
    import deepspeed_tpu as ds
    from simple_model import make_simple_model
    conf = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam",
                      "params": {"lr": 1e-3, "fused_kernel": "xla"}},
        "telemetry": {"enabled": True, "output_path": str(tmp_path)},
        "steps_per_print": 10 ** 9,
    }
    eng, _, _, _ = ds.initialize(model=make_simple_model(8),
                                 config_params=conf)
    x = jnp.ones((2, 8))
    y = jnp.ones((2, 8))
    loss = eng(x, y)
    eng.backward(loss)
    eng.step()
    snap = eng.telemetry_snapshot()
    assert snap["kernels"] == {"flash_attention": None,
                               "fused_optimizer": "xla"}


# ------------------------------------------------------------------- DSL011

_DSL011_DEFECT = '''
from jax.experimental import pallas as pl


def _kern(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def priced(x):
    return pl.pallas_call(
        _kern, out_shape=x,
        cost_estimate=pl.CostEstimate(flops=1, bytes_accessed=2,
                                      transcendentals=0))(x)


def unpriced(x):
    return pl.pallas_call(_kern, out_shape=x)(x)
'''


def _lint(tmp_path, source, relpath):
    from deepspeed_tpu.analysis import astlint
    path = tmp_path / "defect.py"
    path.write_text(source)
    return astlint.lint_file(str(path), relpath)


def test_dsl011_fires_on_unpriced_pallas_call_under_ops(tmp_path):
    findings = _lint(tmp_path, _DSL011_DEFECT,
                     "deepspeed_tpu/ops/fake/defect.py")
    by_rule = {}
    for rule, qual, lineno, msg in findings:
        by_rule.setdefault(rule, []).append(qual)
    assert by_rule.get("DSL011") == ["unpriced"], findings
    assert "cost_estimate" in [
        msg for rule, _, _, msg in findings if rule == "DSL011"][0]


def test_dsl011_inert_outside_ops_and_when_priced(tmp_path):
    # outside ops/ the rule does not apply (DSL005 owns that placement)
    findings = _lint(tmp_path, _DSL011_DEFECT,
                     "deepspeed_tpu/runtime/defect.py")
    assert not [f for f in findings if f[0] == "DSL011"], findings
    # a priced call under ops/ is clean
    priced_only = _DSL011_DEFECT[:_DSL011_DEFECT.index("def unpriced")]
    findings = _lint(tmp_path, priced_only,
                     "deepspeed_tpu/ops/fake/defect.py")
    assert findings == []


def test_repo_self_lint_green_for_dsl011():
    """Every pallas_call the repo ships under ops/ is priced (no new
    DSL011 offenders over the baseline)."""
    from deepspeed_tpu.analysis import astlint
    findings = astlint.lint_paths(
        [os.path.join(_REPO, "deepspeed_tpu")], base=_REPO)
    baseline = astlint.load_baseline(
        os.path.join(_REPO, "bin", "ds_lint_baseline.json"))
    new, _stale = astlint.diff_baseline(findings, baseline)
    offenders = [f for f in new if f.rule == "DSL011"]
    assert offenders == [], offenders


# ------------------------------------------------- long-context rung
def _load_bin(name):
    import importlib.util
    path = os.path.join(_REPO, "bin", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _longctx_file(tmp_path, rung, tokens_per_sec, device="cpu",
                  dense_live=34359738368, budget=17179869184):
    import json
    payload = {
        "metric": "gpt2_longctx_sparse_tokens_per_sec",
        "value": tokens_per_sec, "unit": "tokens/s", "vs_baseline": None,
        "extra": {
            "device": device, "backend": device, "mfu": 0.1,
            "longctx": {
                "sparse": {"mode": "sliding_window", "block": 128},
                "rows": [
                    {"seq": 8192, "mode": "sparse", "fits": True,
                     "timed": True, "tokens_per_sec": tokens_per_sec},
                    {"seq": 16384, "mode": "dense",
                     "fits": dense_live <= budget, "timed": False,
                     "live_bytes": dense_live},
                    {"seq": 16384, "mode": "sparse", "fits": True,
                     "timed": False, "live_bytes": 10 ** 9},
                ],
                "dense_oom": {
                    "shape": {"batch": 1, "heads": 16, "seq": 16384,
                              "block": 128},
                    "hbm_budget_bytes": budget,
                    "dense_bwd_live_bytes": dense_live,
                    "sparse_bwd_live_bytes": 10 ** 9,
                    "dense_fits": dense_live <= budget,
                    "sparse_fits": True,
                },
            },
        },
    }
    path = tmp_path / "BENCH_LONGCTX_r{:02d}.json".format(rung)
    path.write_text(json.dumps(payload))
    return str(path)


def test_longctx_row_keys_pinned_across_bins():
    scoreboard = _load_bin("ds_scoreboard")
    checker = _load_bin("check_bench_schema")
    assert tuple(scoreboard.SCOREBOARD_ROW_KEYS) == \
        tuple(checker.SCOREBOARD_ROW_KEYS)
    assert tuple(scoreboard.LONGCTX_ROW_KEYS) == (
        "rung", "file", "seq", "mode", "device", "tokens_per_sec")


def test_longctx_scoreboard_gate(tmp_path):
    """The LONGCTX trajectory: headline = best timed row; >10%
    same-device tokens/s gate; cpu rungs exempt unless gate_cpu;
    accounting-only rows never gate."""
    scoreboard = _load_bin("ds_scoreboard")
    paths = [_longctx_file(tmp_path, 1, 500.0),
             _longctx_file(tmp_path, 2, 520.0)]
    board = scoreboard.build_longctx_board(paths)
    assert board["latest_tokens_per_sec"] == 520.0
    assert board["regression"] is False
    assert board["gate"].startswith("skipped: latest longctx rung is "
                                    "a cpu")
    board = scoreboard.build_longctx_board(paths, gate_cpu=True)
    assert board["gate"] == "passed"
    # >10% drop trips under --gate-cpu
    paths.append(_longctx_file(tmp_path, 3, 400.0))
    tripped = scoreboard.build_longctx_board(paths, gate_cpu=True)
    assert tripped["regression"] is True
    assert tripped["best_prior_tokens_per_sec"] == 520.0
    # untimed accounting rows are in the table but not the headline
    assert [r for r in tripped["rows"]
            if r["tokens_per_sec"] is None]


def test_longctx_schema_checker_rejects_inconsistent_accounting(
        tmp_path):
    """check_bench_schema re-derives the dense-OOM fits booleans from
    their own published operands — a rung claiming dense fits (or
    contradicting its numbers) fails validation."""
    import json
    checker = _load_bin("check_bench_schema")
    good = _longctx_file(tmp_path, 1, 500.0)
    assert checker.check_file(good) == []
    # dense "fits" at 16k: the rung no longer demonstrates the wall
    fits = _longctx_file(tmp_path, 2, 500.0, dense_live=10 ** 9)
    assert any("dense" in p for p in checker.check_file(fits))
    # a fits flag contradicting its operands is a schema failure
    payload = json.loads((tmp_path / "BENCH_LONGCTX_r01.json")
                         .read_text())
    payload["extra"]["longctx"]["dense_oom"]["dense_fits"] = True
    bad = tmp_path / "BENCH_LONGCTX_r04.json"
    bad.write_text(json.dumps(payload))
    assert any("contradicts" in p for p in checker.check_file(str(bad)))
    # the scoreboard artifact with a longctx section round-trips
    scoreboard = _load_bin("ds_scoreboard")
    board = scoreboard.build_scoreboard(
        [], longctx_paths=[good])
    board["rows"] = [dict.fromkeys(
        scoreboard.SCOREBOARD_ROW_KEYS)]        # minimal main table
    board["rows"][0].update(rung=1, rc=0)
    board["regression"] = False
    art = tmp_path / "scoreboard.json"
    art.write_text(json.dumps(board))
    assert checker.check_file(str(art)) == []


def test_repo_longctx_artifact_validates():
    """The committed BENCH_LONGCTX rung (tests/perf/bench_longctx.py)
    passes its own schema checker, and its dense-OOM accounting says
    what the docs claim: dense attention does not fit 16k, sparse
    does."""
    import json
    path = os.path.join(_REPO, "tests", "perf",
                        "BENCH_LONGCTX_r01.json")
    checker = _load_bin("check_bench_schema")
    assert checker.check_file(path) == []
    with open(path) as fh:
        oom = json.load(fh)["extra"]["longctx"]["dense_oom"]
    assert oom["dense_fits"] is False and oom["sparse_fits"] is True


# ------------------------------------- one Adam, three apply paths


def _ulps(x):
    """Monotonic integer view of fp32 — adjacent floats differ by 1."""
    i = np.asarray(x).view(np.int32).astype(np.int64)
    return np.where(i < 0, (np.int64(1) << 31) - i, i)


def test_adam_bitwise_across_fused_and_host_offload_paths():
    """ISSUE acceptance: one Adam, three apply paths. The fused device
    apply's jnp oracle (ops/adam) is BITWISE-identical at fp32 to the
    host apply that the classic-offload and streamed plans share
    (``runtime/zero/transfer.host_adam_chunk`` — executor/offload.py
    and executor/stream.py both call it), so a checkpoint moved
    between apply paths never perturbs training. Dyadic betas keep the
    host's float64 bias correction exactly representable in fp32; the
    jnp side runs eagerly on purpose — op-by-op dispatch matches
    numpy's unfused multiply-add order. The Pallas kernel compiles its
    whole body as ONE program, so XLA fuses the decay fold ``g + wd*p``
    into an FMA (single rounding) — a few ulp from the host apply here
    (params stay within 1), and exactly bitwise vs the jnp oracle
    inside a shared jit scope
    (``test_fused_adam_bitwise_vs_jnp_oracle``)."""
    from deepspeed_tpu.runtime.zero.transfer import host_adam_chunk

    hyper = {"lr": 1e-3, "beta1": 0.5, "beta2": 0.75, "eps": 1e-8,
             "weight_decay": 0.01}
    for adam_w in (0, 1):
        rng = np.random.RandomState(7 + adam_w)
        p0 = rng.randn(257).astype(np.float32)
        host = {"p": p0.copy(), "m": np.zeros(257, np.float32),
                "v": np.zeros(257, np.float32)}
        params = {"w": jnp.asarray(p0)}
        st = {"jnp": adam_init(params), "pallas": adam_init(params)}
        ps = {"jnp": params, "pallas": params}
        kw = dict(lr=hyper["lr"], beta1=hyper["beta1"],
                  beta2=hyper["beta2"], eps=hyper["eps"],
                  weight_decay=hyper["weight_decay"],
                  adam_w_mode=bool(adam_w))
        for step in range(1, 4):
            g = rng.randn(257).astype(np.float32)
            bc1 = 1.0 - hyper["beta1"] ** step
            bc2 = 1.0 - hyper["beta2"] ** step
            host_adam_chunk(None, host["p"], g.copy(), host["m"],
                            host["v"], hyper, bc1, bc2, adam_w)
            for path in ("jnp", "pallas"):
                ps[path], st[path] = adam_update(
                    {"w": jnp.asarray(g)}, st[path], ps[path],
                    use_pallas=(path == "pallas"),
                    interpret=(path == "pallas"), **kw)
                for name, got, want in (
                        ("params", ps[path]["w"], host["p"]),
                        ("exp_avg", st[path]["exp_avg"]["w"],
                         host["m"]),
                        ("exp_avg_sq", st[path]["exp_avg_sq"]["w"],
                         host["v"])):
                    where = "%s/%s step %d adam_w=%d" % (
                        path, name, step, adam_w)
                    if path == "jnp":
                        np.testing.assert_array_equal(
                            np.asarray(got).view(np.uint32),
                            want.view(np.uint32), err_msg=where)
                    else:
                        # FMA single-rounding in the one-program kernel
                        # vs numpy's two roundings: observed max 1 ulp
                        # on params, 4 on the squared-gradient moment
                        ulp = np.abs(_ulps(got) - _ulps(want)).max()
                        bound = 2 if name == "params" else 8
                        assert ulp <= bound, (where, int(ulp))


# --------------------------------- audit + census with kernels on


def test_audit_clean_with_all_kernel_families_enabled():
    """ISSUE acceptance: ``engine.audit()`` and the HLO collective
    census stay clean with the kernel tier fully on. Sparse attention
    replaces the dense path inside the model, so the two attention
    families ride separate engines: flash ``"pallas"`` + fused Adam
    ``"pallas"`` on the dense GPT-2, block-sparse + fused ``"pallas"``
    on the long-context one — the shard-lint walks both step programs
    (pallas_call abstract-evals like any other primitive) and reports
    no drift. The census leg is pinned as a DELTA: the fused-pallas
    step moves byte-identical data-axis collectives to the fused-xla
    step, i.e. the kernel adds zero unplanned wire. The interpreter-
    emulated ATTENTION kernels are excluded from the census claim on
    purpose: emulation is not batch-partitionable, so XLA gathers the
    sharded activations around the interpreted call — an off-TPU
    artifact the estimator correctly refuses to price (on hardware the
    Mosaic kernel lowers sharded; there is no gather to plan)."""
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    sparse = {"mode": "sliding_window", "block": 16,
              "num_sliding_window_blocks": 2}

    def conf(extra):
        c = {"train_micro_batch_size_per_gpu": 8,
             "gradient_accumulation_steps": 1,
             "optimizer": {"type": "Adam",
                           "params": {"lr": 1e-3,
                                      "fused_kernel": "pallas"}},
             "steps_per_print": 10 ** 9}
        c.update(extra)
        return c

    def model_cfg(**kw):
        return gpt2.GPT2Config(vocab_size=128, max_seq_len=32,
                               n_layers=1, n_heads=2, d_model=32,
                               dropout=0.0, use_flash_attention=False,
                               remat=False, loss_chunk=0, **kw)

    rng = np.random.RandomState(0)
    # batch 8 = one shard per device of the 8-way data mesh, so the
    # gradient collectives the wire estimator prices are actually
    # emitted and the census has something real to match
    x = rng.randint(0, 128, size=(8, 32)).astype(np.int32)

    flash_eng = DeepSpeedEngine(
        model=gpt2.make_gpt2_model(config=model_cfg()),
        config_params=conf(
            {"transformer": {"flash_attention": "pallas"}}))
    assert flash_eng.flash_attention_backend == "interpret"
    report = flash_eng.audit(batch=(x, x.copy()))
    assert report.findings == [], [f.key for f in report.findings]
    assert report.programs, report.to_dict()

    sparse_eng = DeepSpeedEngine(
        model=gpt2.make_gpt2_model(
            config=model_cfg(sparse_attention=dict(sparse))),
        config_params=conf({"sparse_attention": dict(sparse)}))
    report = sparse_eng.audit(batch=(x, x.copy()))
    assert report.findings == [], [f.key for f in report.findings]
    assert report.programs, report.to_dict()

    # census delta: the fused Adam kernel must be wire-invisible —
    # byte-identical data-axis collectives vs the fused-xla step
    # (strict=False: the tiny stage-0 model has a pre-existing
    # estimator gap either way; what this pins is that the kernel
    # does not widen it by a single byte)
    deltas = {}
    for fused in ("pallas", "xla"):
        eng = DeepSpeedEngine(
            model=gpt2.make_gpt2_model(config=model_cfg()),
            config_params=conf({
                "transformer": {"flash_attention": "xla"},
                "optimizer": {"type": "Adam",
                              "params": {"lr": 1e-3,
                                         "fused_kernel": fused}}}))
        rep = eng.audit(batch=(x, x.copy()), hlo=True, strict=False)
        assert rep.census is not None, rep.to_dict()
        deltas[fused] = (rep.census["hlo"]["data_total_bytes"],
                         rep.census["delta_total_bytes"])
    assert deltas["pallas"] == deltas["xla"], deltas
