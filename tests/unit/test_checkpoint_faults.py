"""Checkpoint fault-injection tests (docs/checkpoint_recovery.md).

Proves the save→kill→resume contract: a kill injected after each of the
K files of a tag leaves ``load_checkpoint`` resuming from the newest
COMPLETE tag with all checksums verified — for plain, ZeRO-sharded, and
async-save checkpoints, at every injection point. Also covers bit-rot
detection (CRC32), truncation, transient-IO retry, and retention GC.

All faults are counter-based (utils/fault_injection.py) — no timing, no
randomness — so these run fast, CPU-only, and deterministically in the
tier-1 ``-m 'not slow'`` selection under the ``faults`` marker.
"""
import os
import pickle

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.runtime import checkpointing as ckpt
from deepspeed_tpu.utils.fault_injection import inject_faults, SimulatedKill
from simple_model import make_simple_model, SimpleDataset, base_config

pytestmark = pytest.mark.faults

HIDDEN = 8
WORLD = 8


def _cfg(zero=False):
    cfg = base_config(WORLD)
    # no sleeping between injected transient failures
    cfg["checkpoint"] = {"io_retries": 3, "io_retry_backoff_seconds": 0}
    if zero:
        cfg["bf16"] = {"enabled": True}
        cfg["zero_optimization"] = {"stage": 2}
    return cfg


def make_engine(config, seed=0):
    model = make_simple_model(HIDDEN, seed=seed)
    engine, _, _, _ = deepspeed.initialize(model=model, config_params=config)
    return engine


def run_steps(engine, dataset, steps, offset=0):
    mb = engine.train_micro_batch_size_per_gpu() * WORLD
    for s in range(steps):
        base = (offset + s) * mb
        x = np.stack([dataset[(base + i) % len(dataset)][0]
                      for i in range(mb)])
        y = np.stack([dataset[(base + i) % len(dataset)][1]
                      for i in range(mb)])
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()


# --------------------------------------------------------------- kill tests
@pytest.mark.parametrize("mode", ["plain", "zero", "async"])
def test_kill_at_every_injection_point(tmp_path, mode):
    """Acceptance criterion: for every k, killing the writer after k
    complete files of tag 'later' leaves `latest` on tag 'good', tag
    'good' checksum-verified, and load_checkpoint resuming from it."""
    cfg = _cfg(zero=(mode == "zero"))
    dataset = SimpleDataset(64, HIDDEN)
    e1 = make_engine(cfg)
    run_steps(e1, dataset, 1)

    # how many write ops a full tag takes: content files + manifest,
    # plus the `latest` pointer as the final injection point
    probe = str(tmp_path / "probe")
    e1.save_checkpoint(probe, tag="p")
    n_files = len(ckpt.read_manifest(probe, "p")["files"])
    assert n_files >= (2 if mode == "zero" else 1)
    total_writes = n_files + 2

    e2 = make_engine(cfg, seed=9)
    for k in range(total_writes):
        d = str(tmp_path / "k{}".format(k))
        e1.save_checkpoint(d, tag="good")
        with inject_faults(kill_after_files=k):
            with pytest.raises(SimulatedKill):
                if mode == "async":
                    e1.save_checkpoint(d, tag="later", async_save=True)
                    e1.wait_pending_writes()
                else:
                    e1.save_checkpoint(d, tag="later")
        # `latest` still names the last complete tag and it verifies
        assert ckpt.read_latest(d) == "good"
        ok, why = ckpt.verify_tag(d, "good")
        assert ok, why
        path, _ = e2.load_checkpoint(d)
        assert path is not None and os.sep + "good" + os.sep in path
        assert e2.global_steps == e1.global_steps

    # no injection: the same save completes and moves the pointer
    d = str(tmp_path / "clean")
    e1.save_checkpoint(d, tag="good")
    e1.save_checkpoint(d, tag="later")
    assert ckpt.read_latest(d) == "later"
    assert ckpt.verify_tag(d, "later")[0]


# ----------------------------------------------------- corruption / bit-rot
def test_bitrot_rejected_and_falls_back_to_prior_tag(tmp_path):
    """A bit-flip landing AFTER a file was fully written (storage rot —
    atomic rename can't help) fails CRC verification; load walks back to
    the newest complete tag."""
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(_cfg())
    run_steps(e1, dataset, 1)
    e1.save_checkpoint(save_dir, tag="t1")
    run_steps(e1, dataset, 1, offset=1)
    with inject_faults(corrupt_substr="model_states", corrupt_mode="flip"):
        e1.save_checkpoint(save_dir, tag="t2")
    assert ckpt.read_latest(save_dir) == "t2"
    ok, why = ckpt.verify_tag(save_dir, "t2")
    assert not ok and "checksum mismatch" in why

    e2 = make_engine(_cfg(), seed=3)
    path, _ = e2.load_checkpoint(save_dir)
    assert path is not None and os.sep + "t1" + os.sep in path
    assert e2.global_steps == 1


def test_fallback_scans_to_newest_complete_not_oldest(tmp_path):
    """With t1 < t2 < t3 and only t3 corrupted, the fallback lands on t2
    (newest complete), not t1."""
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(_cfg(zero=True))
    run_steps(e1, dataset, 1)
    e1.save_checkpoint(save_dir, tag="t1")
    run_steps(e1, dataset, 1, offset=1)
    e1.save_checkpoint(save_dir, tag="t2")
    run_steps(e1, dataset, 1, offset=2)
    with inject_faults(corrupt_substr="optim_states",
                       corrupt_mode="truncate"):
        e1.save_checkpoint(save_dir, tag="t3")
    ok, why = ckpt.verify_tag(save_dir, "t3")
    assert not ok and "size mismatch" in why

    e2 = make_engine(_cfg(zero=True), seed=3)
    path, _ = e2.load_checkpoint(save_dir)
    assert path is not None and os.sep + "t2" + os.sep in path
    assert e2.global_steps == 2


def test_truncated_shard_raises_corruption_error_naming_file(tmp_path):
    """load_state_dict on a torn pickle raises CheckpointCorruptionError
    naming the file and pointing at the fallback path — not a bare
    EOFError."""
    path = str(tmp_path / "shard.pt")
    with open(path, "wb") as f:
        pickle.dump({"x": np.arange(100)}, f, protocol=4)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(ckpt.CheckpointCorruptionError) as err:
        ckpt.load_state_dict(path)
    assert "shard.pt" in str(err.value)
    assert "falls back" in str(err.value)


# ------------------------------------------------------------ transient IO
def test_transient_write_failures_are_retried(tmp_path):
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(_cfg())  # io_retries=3
    run_steps(e1, dataset, 1)
    with inject_faults(fail_substr="model_states", n_failures=2) as fi:
        e1.save_checkpoint(save_dir, tag="t")
    assert [e for e, _ in fi.events].count("write_fail") == 2
    ok, why = ckpt.verify_tag(save_dir, "t")
    assert ok, why


def test_write_failures_beyond_retry_budget_keep_latest_intact(tmp_path):
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    cfg = _cfg()
    cfg["checkpoint"]["io_retries"] = 1
    e1 = make_engine(cfg)
    run_steps(e1, dataset, 1)
    e1.save_checkpoint(save_dir, tag="good")
    with inject_faults(fail_substr="model_states", n_failures=5):
        with pytest.raises(OSError):
            e1.save_checkpoint(save_dir, tag="bad")
    assert ckpt.read_latest(save_dir) == "good"
    e2 = make_engine(cfg, seed=3)
    path, _ = e2.load_checkpoint(save_dir)
    assert path is not None and os.sep + "good" + os.sep in path


def test_transient_read_failures_are_retried(tmp_path):
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(_cfg())
    run_steps(e1, dataset, 1)
    e1.save_checkpoint(save_dir, tag="t")
    e2 = make_engine(_cfg(), seed=3)
    with inject_faults(fail_substr="model_states", n_failures=2,
                       fail_reads=True) as fi:
        path, _ = e2.load_checkpoint(save_dir)
    assert path is not None
    assert [e for e, _ in fi.events].count("read_fail") == 2


# ------------------------------------------------------------- retention GC
def test_retention_gc_keeps_last_n_and_never_eats_latest(tmp_path):
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    cfg = _cfg()
    cfg["checkpoint"]["keep_last_n"] = 2
    e1 = make_engine(cfg)
    for i in range(4):
        run_steps(e1, dataset, 1, offset=i)
        e1.save_checkpoint(save_dir)  # tags global_step1..4
    tags = set(ckpt.list_tags(save_dir))
    assert tags == {"global_step3", "global_step4"}
    assert ckpt.read_latest(save_dir) == "global_step4"
    e2 = make_engine(cfg, seed=3)
    path, _ = e2.load_checkpoint(save_dir)
    assert path is not None and e2.global_steps == 4


def test_prune_protects_latest_and_anything_newer(tmp_path):
    """Direct unit check of the GC invariant: with `latest` pinned to an
    OLD tag (crash landed between a newer tag's manifest and the pointer
    update), neither the pinned tag nor the newer ones are deleted."""
    save_dir = str(tmp_path / "ckpt")
    for step, tag in enumerate(["a", "b", "c"], start=1):
        rec = ckpt.save_state_dict(
            ckpt.model_ckpt_name(save_dir, tag), {"step": step})
        ckpt.write_manifest(save_dir, tag, [rec], {"global_step": step})
    ckpt.save_latest(save_dir, "b")
    deleted = ckpt.prune_checkpoints(save_dir, keep_last_n=1)
    assert deleted == ["a"]
    assert set(ckpt.list_tags(save_dir)) == {"b", "c"}


# --------------------------------------------- latest-pointer edge cases
def test_read_latest_tolerates_empty_and_dangling_pointer(tmp_path):
    save_dir = str(tmp_path / "ckpt")
    os.makedirs(save_dir)
    latest = os.path.join(save_dir, "latest")
    with open(latest, "w") as f:
        f.write("  \n\t")
    assert ckpt.read_latest(save_dir) is None
    with open(latest, "w") as f:
        f.write("ghost_tag")
    assert ckpt.read_latest(save_dir) is None


def test_dangling_latest_falls_back_to_complete_tag(tmp_path):
    """A `latest` pointer naming a pruned/vanished tag dir must not
    produce a confusing missing-file error — load scans for the newest
    complete tag instead."""
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(_cfg())
    run_steps(e1, dataset, 1)
    e1.save_checkpoint(save_dir, tag="real")
    with open(os.path.join(save_dir, "latest"), "w") as f:
        f.write("vanished")
    e2 = make_engine(_cfg(), seed=3)
    path, _ = e2.load_checkpoint(save_dir)
    assert path is not None and os.sep + "real" + os.sep in path


def test_explicit_tag_failure_does_not_substitute_another_tag(tmp_path):
    """The last-good fallback applies to resume-from-latest loads only:
    a caller naming a tag explicitly must get those weights or (None,
    None) — never a silent substitution."""
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(_cfg())
    run_steps(e1, dataset, 1)
    e1.save_checkpoint(save_dir, tag="good")
    e2 = make_engine(_cfg(), seed=3)
    path, state = e2.load_checkpoint(save_dir, tag="no_such_tag")
    assert path is None and state is None
    # tag=None on the same dir does resume
    path, _ = e2.load_checkpoint(save_dir)
    assert path is not None and os.sep + "good" + os.sep in path


# ---------------------------------------------------------- async plumbing
def test_wait_pending_writes_lands_queued_files(tmp_path):
    """The module-level pool barrier (also registered via atexit) makes
    every queued async write visible on disk without touching engine
    future bookkeeping."""
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(_cfg())
    run_steps(e1, dataset, 1)
    e1.save_checkpoint(save_dir, tag="t", async_save=True)
    ckpt.wait_pending_writes()
    ok, why = ckpt.verify_tag(save_dir, "t")
    assert ok, why
    assert ckpt.read_latest(save_dir) == "t"


# --------------------------------------------------- kill during RESTORE
@pytest.mark.parametrize("mode", ["plain", "zero"])
def test_kill_at_every_read_point_leaves_tag_loadable(tmp_path, mode):
    """The elastic-rescale counterpart of the save matrix: a kill
    injected after each of the K reads of a restore (manifest, CRC
    verifies, shard loads) leaves the tag itself untouched — a fresh
    ``load_checkpoint`` afterwards restores from the SAME tag with the
    right counters. Restores never mutate the checkpoint, so a
    preempted restore costs a retry, not a fallback."""
    cfg = _cfg(zero=(mode == "zero"))
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(cfg)
    run_steps(e1, dataset, 2)
    e1.save_checkpoint(save_dir, tag="good")

    # probe how many read ops one restore performs
    probe = make_engine(cfg, seed=7)
    with inject_faults() as fi:
        probe.load_checkpoint(save_dir)
    total_reads = fi.files_read
    assert total_reads >= 2   # at least manifest + one shard

    for k in range(total_reads):
        victim = make_engine(cfg, seed=9)
        with inject_faults(kill_after_reads=k) as fi:
            with pytest.raises(SimulatedKill):
                victim.load_checkpoint(save_dir)
        assert ("kill_read", fi.events[-1][1]) == fi.events[-1]
        # the tag is still complete and verified — a torn LOAD must
        # not invalidate it
        assert ckpt.read_latest(save_dir) == "good"
        ok, why = ckpt.verify_tag(save_dir, "good")
        assert ok, why
        # the same engine retries the restore and lands whole
        path, _ = victim.load_checkpoint(save_dir)
        assert path is not None and os.sep + "good" + os.sep in path
        assert victim.global_steps == e1.global_steps


def test_kill_mid_restore_falls_back_to_prior_tag_when_newest_rots(
        tmp_path):
    """Kill mid-restore, then bit-rot the newest tag: the next load
    walks back to the prior COMPLETE tag — the preempted restore did
    not consume or corrupt the fallback chain."""
    dataset = SimpleDataset(64, HIDDEN)
    save_dir = str(tmp_path / "ckpt")
    e1 = make_engine(_cfg())
    run_steps(e1, dataset, 1)
    e1.save_checkpoint(save_dir, tag="t1")
    run_steps(e1, dataset, 1, offset=1)
    e1.save_checkpoint(save_dir, tag="t2")

    victim = make_engine(_cfg(), seed=5)
    with inject_faults(kill_after_reads=1):
        with pytest.raises(SimulatedKill):
            victim.load_checkpoint(save_dir)
    # storage rot hits t2 AFTER the torn restore
    for name in os.listdir(os.path.join(save_dir, "t2")):
        if "model_states" in name:
            p = os.path.join(save_dir, "t2", name)
            with open(p, "r+b") as f:
                f.seek(max(os.path.getsize(p) // 2, 0))
                byte = f.read(1)
                f.seek(-1, os.SEEK_CUR)
                f.write(bytes([byte[0] ^ 0xFF]))
    path, _ = victim.load_checkpoint(save_dir)
    assert path is not None and os.sep + "t1" + os.sep in path
    assert victim.global_steps == 1
