"""Batch-triple inference and config validation.

Mirrors reference tests/unit/test_config.py + test_ds_config.py semantics,
with world_size = the 8-device CPU mesh data axis.
"""
import json
import pytest

import jax

from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                          DeepSpeedConfigError)

WORLD = None  # resolved lazily (8 on the CPU test mesh)


def world():
    return jax.device_count()


def base_dict(**kwargs):
    d = {"fp16": {"enabled": False}}
    d.update(kwargs)
    return d


def test_only_train_batch():
    cfg = DeepSpeedConfig(None, param_dict=base_dict(train_batch_size=world() * 4))
    assert cfg.train_batch_size == world() * 4
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.gradient_accumulation_steps == 1


def test_only_micro_batch():
    cfg = DeepSpeedConfig(None,
                          param_dict=base_dict(train_micro_batch_size_per_gpu=2))
    assert cfg.train_batch_size == 2 * world()
    assert cfg.gradient_accumulation_steps == 1


def test_train_and_micro():
    cfg = DeepSpeedConfig(None, param_dict=base_dict(
        train_batch_size=world() * 8, train_micro_batch_size_per_gpu=2))
    assert cfg.gradient_accumulation_steps == 4


def test_train_and_grad_acc():
    cfg = DeepSpeedConfig(None, param_dict=base_dict(
        train_batch_size=world() * 8, gradient_accumulation_steps=2))
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_micro_and_grad_acc():
    cfg = DeepSpeedConfig(None, param_dict=base_dict(
        train_micro_batch_size_per_gpu=3, gradient_accumulation_steps=5))
    assert cfg.train_batch_size == 3 * 5 * world()


def test_all_three_consistent():
    cfg = DeepSpeedConfig(None, param_dict=base_dict(
        train_batch_size=world() * 6,
        train_micro_batch_size_per_gpu=3,
        gradient_accumulation_steps=2))
    assert cfg.train_batch_size == world() * 6


def test_all_three_inconsistent():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(None, param_dict=base_dict(
            train_batch_size=world() * 100,
            train_micro_batch_size_per_gpu=3,
            gradient_accumulation_steps=2))


def test_none_given():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(None, param_dict=base_dict())


def test_only_grad_accum_given():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(None, param_dict=base_dict(gradient_accumulation_steps=4))


def test_config_from_file(tmp_config_file):
    path = tmp_config_file({"train_batch_size": world() * 2,
                            "fp16": {"enabled": True, "loss_scale": 128}})
    cfg = DeepSpeedConfig(path)
    assert cfg.fp16_enabled
    assert cfg.loss_scale == 128


def test_config_duplicate_key(tmp_path):
    path = tmp_path / "dup.json"
    path.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(path))


def test_zero_requires_mixed_precision():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(None, param_dict={
            "train_batch_size": world(),
            "zero_optimization": {"stage": 2},
        })


def test_zero_config_parsing():
    cfg = DeepSpeedConfig(None, param_dict={
        "train_batch_size": world(),
        "fp16": {"enabled": True},
        "zero_optimization": {
            "stage": 3,
            "overlap_comm": True,
            "cpu_offload": True,
            "stage3_max_live_parameters": 500,
            "stage3_param_persistence_threshold": 42,
        },
    })
    assert cfg.zero_enabled
    assert cfg.zero_optimization_stage == 3
    assert cfg.zero_config.overlap_comm is True
    assert cfg.zero_config.cpu_offload is True
    assert cfg.zero_config.max_live_parameters == 500
    assert cfg.zero_config.param_persistence_threshold == 42


def test_zero_deprecated_bool_format():
    cfg = DeepSpeedConfig(None, param_dict={
        "train_batch_size": world(),
        "fp16": {"enabled": True},
        "zero_optimization": True,
    })
    assert cfg.zero_optimization_stage == 1


def test_bf16_block():
    cfg = DeepSpeedConfig(None, param_dict={
        "train_batch_size": world(),
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
    })
    assert cfg.bf16_enabled
    assert cfg.zero_enabled


def test_dynamic_loss_scale_args():
    cfg = DeepSpeedConfig(None, param_dict={
        "train_batch_size": world(),
        "fp16": {"enabled": True, "initial_scale_power": 16,
                 "loss_scale_window": 500, "hysteresis": 2,
                 "min_loss_scale": 1},
    })
    args = cfg.dynamic_loss_scale_args
    assert args["init_scale"] == 2 ** 16
    assert args["scale_window"] == 500
    assert args["delayed_shift"] == 2
    assert args["min_scale"] == 1


def test_scheduler_optimizer_parsing():
    cfg = DeepSpeedConfig(None, param_dict={
        "train_batch_size": world(),
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 10}},
    })
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params["lr"] == 1e-3
    assert cfg.scheduler_name == "WarmupLR"
    assert cfg.scheduler_params["warmup_num_steps"] == 10


def test_sparse_attention_fixed_mode():
    cfg = DeepSpeedConfig(None, param_dict={
        "train_batch_size": world(),
        "sparse_attention": {"mode": "fixed", "block": 32,
                             "num_local_blocks": 8},
    })
    sa = cfg.sparse_attention
    assert sa["mode"] == "fixed"
    assert sa["block"] == 32
    assert sa["num_local_blocks"] == 8
    # defaults fill in
    assert sa["num_global_blocks"] == 1


def test_sparse_attention_sliding_window_mode():
    """The TPU-extension sliding_window mode is reachable from ds_config
    (VERDICT r2: the one measured-profitable layout must be expressible
    through the blessed config surface)."""
    cfg = DeepSpeedConfig(None, param_dict={
        "train_batch_size": world(),
        "sparse_attention": {"mode": "sliding_window", "block": 64,
                             "num_sliding_window_blocks": 8},
    })
    sa = cfg.sparse_attention
    assert sa["mode"] == "sliding_window"
    assert sa["block"] == 64
    assert sa["num_sliding_window_blocks"] == 8
    # defaults fill in
    cfg2 = DeepSpeedConfig(None, param_dict={
        "train_batch_size": world(),
        "sparse_attention": {"mode": "sliding_window"},
    })
    assert cfg2.sparse_attention["num_sliding_window_blocks"] == 3


def test_checkpoint_tag_validation_modes():
    for mode, enabled, fail in [("Warn", True, False), ("Ignore", False, False),
                                ("Fail", True, True)]:
        cfg = DeepSpeedConfig(None, param_dict={
            "train_batch_size": world(),
            "checkpoint": {"tag_validation": mode},
        })
        assert cfg.checkpoint_tag_validation_enabled == enabled
        assert cfg.checkpoint_tag_validation_fail == fail


def test_unknown_key_warns_by_default():
    import logging
    from deepspeed_tpu.utils.logging import logger as ds_logger

    records = []

    class _Cap(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    cfg_dict = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "definitely_not_a_key": True,
                "fp16": {"enabled": True, "loss_scael": 0}}
    cap = _Cap(level=logging.WARNING)
    ds_logger.addHandler(cap)
    try:
        DeepSpeedConfig(None, param_dict=cfg_dict)
    finally:
        ds_logger.removeHandler(cap)
    joined = " ".join(records)
    assert "definitely_not_a_key" in joined
    assert "loss_scael" in joined


def test_unknown_key_strict_raises():
    cfg_dict = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "config_validation": "strict",
                "zero_optimization": {"stgae": 2}}
    with pytest.raises(DeepSpeedConfigError, match="stgae"):
        DeepSpeedConfig(None, param_dict=cfg_dict)


def test_unknown_key_ignore_mode():
    cfg_dict = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "config_validation": "ignore",
                "whatever": 1}
    DeepSpeedConfig(None, param_dict=cfg_dict)  # no raise, no warning needed


def test_doc_covers_every_known_key():
    """docs/_pages/config-json.md must mention every accepted key (and the
    parser must accept every key the doc shows) — the strict-or-warn
    validator makes this the single source of truth."""
    import os
    doc_path = os.path.join(os.path.dirname(__file__), "..", "..",
                            "docs", "_pages", "config-json.md")
    doc = open(doc_path).read()
    for key in DeepSpeedConfig.KNOWN_TOP_LEVEL_KEYS:
        assert "`{}`".format(key) in doc or '"{}"'.format(key) in doc, \
            "top-level key {} undocumented".format(key)
    for section, keys in DeepSpeedConfig.KNOWN_SUBDICT_KEYS.items():
        for key in keys:
            assert "`{}`".format(key) in doc or '"{}"'.format(key) in doc, \
                "{}.{} undocumented".format(section, key)


def test_doc_covers_reference_doc_keys():
    """Reverse-direction doc audit (VERDICT r3 #8): every key name the
    REFERENCE's config-json.md documents (its ***key*** markers and
    quoted "key" tokens) must appear somewhere in the repo doc — as a
    supported key, a documented value, or an explicit N/A note — so doc
    parity cannot silently regress when either doc changes."""
    import os
    import re
    ref_path = "/root/reference/docs/_pages/config-json.md"
    if not os.path.isfile(ref_path):
        import pytest
        pytest.skip("reference tree not present")
    ref = open(ref_path).read()
    keys = set(re.findall(r"\*\*\*([a-z0-9_\\]+)\*\*\*", ref))
    keys |= set(re.findall(r'"([a-z0-9_]+)"', ref))
    keys = {k.replace("\\", "") for k in keys}
    # len > 2 drops prose fragments like "on"/"it" that the quoted-token
    # net also catches; every real config key is longer
    keys = {k for k in keys if re.fullmatch(r"[a-z0-9_]+", k) and len(k) > 2}
    doc_path = os.path.join(os.path.dirname(__file__), "..", "..",
                            "docs", "_pages", "config-json.md")
    doc = open(doc_path).read()
    missing = sorted(k for k in keys if k not in doc)
    assert not missing, (
        "reference-documented key(s) missing from docs/_pages/"
        "config-json.md (document them or add an explicit N/A note): "
        + ", ".join(missing))
