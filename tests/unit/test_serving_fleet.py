"""Disaggregated serving fleet: roles, handoff codec, router, adapters.

The acceptance spec for ISSUE 17:

  * the fp page-slice codec is BITWISE: serialize -> deserialize moves
    the page payloads verbatim, so greedy streams through prefill ->
    handoff -> decode are byte-identical to the single-engine paged
    path;
  * the int8 handoff codec stays within the documented tolerance
    (``0.5 * blockwise_absmax / 127`` per lane, plus fp rounding);
  * torn/truncated/corrupted payloads are rejected LOUDLY
    (HandoffError) — never a silently wrong cache;
  * every schema copy pins equal: telemetry/record.py SERVING_ROLES /
    the nullable ``role`` field vs aggregate.py and
    bin/check_bench_schema.py; inference/fleet/events.py router-event
    vocabulary vs both stdlib copies;
  * the router refuses divergent fingerprints, denies by predicted
    cost, routes away from flagged hosts, and preempt-migrates live
    streams intact;
  * multi-tenant adapters: id 0 is the byte-identical base, tenants
    diverge, and the prefix cache never cross-hits namespaces;
  * DSL010 flags serving_step fields outside the pinned schema.
"""
import importlib.util
import json
import os
import struct

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.inference.fleet import events
from deepspeed_tpu.inference.fleet.adapters import AdapterSet
from deepspeed_tpu.inference.fleet.handoff import (
    HandoffError, PageSlice, deserialize_slice, export_slice,
    serialize_slice)
from deepspeed_tpu.inference.fleet.router import FleetRouter
from deepspeed_tpu.inference.fleet.serve import DisaggServer
from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.telemetry import record
from deepspeed_tpu.telemetry.fleet import aggregate

pytestmark = pytest.mark.serving_fleet

TINY = dict(vocab_size=128, max_seq_len=64, n_layers=2, n_heads=2,
            d_model=32, use_flash_attention=False, remat=False)
PS = 8                                   # page size used throughout

_REPO = os.path.join(os.path.dirname(__file__), "..", "..")


def tiny_model(seed=0, **over):
    cfg = gpt2.GPT2Config(**{**TINY, **over})
    return gpt2.make_gpt2_model(config=cfg, seed=seed)


def make_engine(model, **inference):
    inference.setdefault("max_batch_size", 3)
    inference.setdefault("prefill_buckets", [8, 16, 32])
    inference.setdefault("dtype", "fp32")
    inference.setdefault("greedy", True)
    return deepspeed.init_inference(model=model,
                                    config={"inference": inference})


def paged_engine(model, **inference):
    inference.setdefault("kv_layout", "paged")
    inference.setdefault("kv_block_size", PS)
    return make_engine(model, **inference)


@pytest.fixture(scope="module")
def model():
    return tiny_model()


def greedy_chain(model, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        ids = jnp.asarray(np.asarray(seq, np.int32)[None])
        hidden = gpt2.forward_hidden(model.params, ids, model.config,
                                     train=False)
        seq.append(int(np.asarray(hidden[0, -1] @ model.params["wte"].T)
                       .argmax()))
    return seq[len(prompt):]


def load_checker():
    path = os.path.join(_REPO, "bin", "check_bench_schema.py")
    spec = importlib.util.spec_from_file_location("_cbs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def random_slice(rs, n_pages=3, layers=2, heads=2, dh=16, length=17,
                 dtype=np.float32):
    shape = (n_pages, layers, heads, PS, dh)
    return PageSlice(
        rs.normal(size=shape).astype(dtype),
        rs.normal(size=shape).astype(dtype),
        PS, length, pending_token=int(rs.randint(0, 128)),
        context=rs.randint(0, 128, size=length).tolist())


# --------------------------------------------------- handoff codec


def test_fp_roundtrip_is_bitwise():
    """The fp codec moves page payloads VERBATIM: every byte of K and V
    survives serialize -> deserialize, along with the table metadata a
    decode host needs to resume."""
    rs = np.random.RandomState(0)
    sl = random_slice(rs)
    out = deserialize_slice(serialize_slice(sl))
    assert out.k_pages.tobytes() == sl.k_pages.tobytes()
    assert out.v_pages.tobytes() == sl.v_pages.tobytes()
    assert out.k_pages.shape == sl.k_pages.shape
    assert out.k_pages.dtype == sl.k_pages.dtype
    assert out.page_size == sl.page_size
    assert out.length == sl.length
    assert out.pending_token == sl.pending_token
    assert out.context == sl.context


def test_quantized_roundtrip_within_documented_tolerance():
    """The int8 path reconstructs every lane within the documented
    ``0.5 * blockwise_absmax / 127`` quantization step (plus fp
    rounding) and ships meaningfully fewer payload bytes than fp32."""
    rs = np.random.RandomState(1)
    sl = random_slice(rs, n_pages=4)
    block = 64
    data = serialize_slice(sl, quantize=True, block_size=block)
    out = deserialize_slice(data)
    for orig, got in ((sl.k_pages, out.k_pages),
                      (sl.v_pages, out.v_pages)):
        flat = orig.reshape(-1).astype(np.float64)
        pad = (-len(flat)) % block
        padded = np.pad(flat, (0, pad))
        absmax = np.abs(padded.reshape(-1, block)).max(axis=1)
        bound = 0.5 * absmax / 127.0 + 1e-5
        err = np.abs(np.pad(got.reshape(-1).astype(np.float64),
                            (0, pad)) - padded).reshape(-1, block)
        assert (err <= bound[:, None]).all(), \
            "max err {} vs bound {}".format(err.max(), bound.min())
    # int8 blocks + fp32 scales: well under the fp32 wire
    assert len(data) < 0.5 * len(serialize_slice(sl))
    assert out.context == sl.context and out.length == sl.length


@pytest.mark.faults
def test_torn_payloads_rejected_loudly():
    """Every way a handoff can tear — short head, bad magic, version
    skew, truncated header, corrupt header JSON, truncated payload,
    flipped payload byte — raises HandoffError instead of importing a
    silently wrong cache."""
    rs = np.random.RandomState(2)
    data = serialize_slice(random_slice(rs))
    head = struct.Struct(">4sHI")
    _magic, _version, header_len = head.unpack_from(data)

    with pytest.raises(HandoffError, match="shorter"):
        deserialize_slice(data[:head.size - 1])
    with pytest.raises(HandoffError, match="bad magic"):
        deserialize_slice(b"XXXX" + data[4:])
    with pytest.raises(HandoffError, match="version"):
        deserialize_slice(
            head.pack(b"DSKV", 99, header_len) + data[head.size:])
    with pytest.raises(HandoffError, match="truncated header"):
        deserialize_slice(data[:head.size + header_len // 2])
    corrupt = bytearray(data)
    corrupt[head.size + 2] ^= 0xFF          # inside the JSON header
    with pytest.raises(HandoffError):
        deserialize_slice(bytes(corrupt))
    with pytest.raises(HandoffError, match="truncated payload"):
        deserialize_slice(data[:-3])
    torn = bytearray(data)
    torn[-5] ^= 0x01                        # inside the payload
    with pytest.raises(HandoffError, match="checksum"):
        deserialize_slice(bytes(torn))
    # the pristine buffer still round-trips after all that
    assert deserialize_slice(data).length > 0


def test_export_import_roundtrip_through_engines(model):
    """export_slice lifts a live slot's pages bitwise: prefill on one
    paged engine, export, serialize, import into ANOTHER engine, and
    the decode continuation matches the host-side greedy oracle."""
    from deepspeed_tpu.inference.fleet.handoff import (can_import,
                                                       import_slice)
    src = paged_engine(model, max_batch_size=2)
    dst = paged_engine(model, max_batch_size=2)
    prompt = list(range(1, 20))
    token = src.prefill(0, prompt)
    sl = export_slice(src, 0, context=prompt, pending_token=token)
    out = deserialize_slice(serialize_slice(sl))
    assert out.k_pages.tobytes() == sl.k_pages.tobytes()
    assert can_import(dst, out)
    pending = import_slice(dst, 1, out)
    chain = greedy_chain(model, prompt, 5)
    assert pending == chain[0]
    got = [pending]
    for _ in range(4):
        assert dst.ensure_pages(1, int(dst.lengths[1]) + 1)
        toks = np.zeros(dst.num_slots, np.int32)
        toks[1] = got[-1]
        nxt = dst.decode_step(toks)
        dst.advance(1)
        got.append(int(nxt[1]))
    assert got == chain


# ----------------------------------------------- disaggregated server


def test_disagg_streams_byte_identical_to_monolith(model):
    """Greedy streams through prefill -> serialized handoff -> decode
    equal the monolithic paged scheduler's streams token for token."""
    rs = np.random.RandomState(3)
    prompts = [rs.randint(0, 128, size=n).tolist()
               for n in (5, 11, 17, 26)]
    mono = paged_engine(model, max_batch_size=4, prefill_chunk_tokens=8)
    sched = ContinuousBatchingScheduler(mono)
    uids = [sched.submit(p, max_new_tokens=6) for p in prompts]
    oracle = sched.run()

    server = DisaggServer(
        {"pre0": paged_engine(model, max_batch_size=2,
                              prefill_chunk_tokens=8)},
        {"dec0": paged_engine(model, max_batch_size=2),
         "dec1": paged_engine(model, max_batch_size=2)})
    for p in prompts:
        server.submit(p, max_new_tokens=6)
    out = server.run()
    assert [out[u] for u in sorted(out)] == [oracle[u] for u in uids]
    stats = server.handoff_stats()
    assert stats["handoffs"] == len(prompts)
    assert stats["payload_bytes"] > 0 and not stats["quantized"]
    counts = server.router.decision_counts()
    assert counts["admit"] == len(prompts)
    assert counts["enroll"] == 3


def test_disagg_migration_keeps_stream_intact(model):
    """Flagging a decode host mid-run preempt-migrates its youngest
    stream to the healthy host; outputs stay byte-identical and the
    flagged host receives no further decode placements."""
    rs = np.random.RandomState(4)
    prompts = [rs.randint(0, 128, size=n).tolist()
               for n in (7, 13, 21, 9)]
    oracle = [greedy_chain(model, p, 8) for p in prompts]
    server = DisaggServer(
        {"pre0": paged_engine(model, max_batch_size=2,
                              prefill_chunk_tokens=8)},
        {"dec0": paged_engine(model, max_batch_size=3),
         "dec1": paged_engine(model, max_batch_size=3)})
    for p in prompts:
        server.submit(p, max_new_tokens=8)
    # pump until the first-choice host (dec0: free-slot tie broken by
    # name) holds live decode work, then flag it
    for _ in range(30):
        server.step()
        if server.decode_roles["dec0"].active:
            break
    assert server.decode_roles["dec0"].active
    server.router.mark_straggler("dec0")
    assigned_before = server.router.hosts["dec0"].decode_assignments
    out = server.run()
    assert [out[u] for u in sorted(out)] == oracle
    assert server.router.migrations >= 1
    counts = server.router.decision_counts()
    assert counts.get("preempt_migrate", 0) >= 1
    assert counts.get("route_away", 0) >= 1
    # no new decode work landed on the flagged host
    assert server.router.hosts["dec0"].decode_assignments == \
        assigned_before


def test_disagg_quantized_handoff_opt_in(model):
    """quantize=True rides the int8 codec end to end: every request
    completes with sane token ids and the wire admits it shipped
    quantized payloads."""
    rs = np.random.RandomState(5)
    prompts = [rs.randint(0, 128, size=n).tolist() for n in (6, 14)]
    server = DisaggServer(
        {"pre0": paged_engine(model, max_batch_size=2)},
        {"dec0": paged_engine(model, max_batch_size=2)},
        quantize=True, block_size=64)
    for p in prompts:
        server.submit(p, max_new_tokens=5)
    out = server.run()
    assert sorted(out) == [0, 1]
    for toks in out.values():
        assert len(toks) == 5
        assert all(0 <= t < TINY["vocab_size"] for t in toks)
    assert server.handoff_stats()["quantized"]


# ------------------------------------------------------------- router


class _FakeRole:
    def __init__(self, free=1):
        self.free = free

    def free_slots(self):
        return self.free


def test_router_refuses_divergent_fingerprint():
    router = FleetRouter()
    fp = {"version": 1, "digest": "ref-digest", "families": []}
    bad = {"version": 1, "digest": "DIVERGENT", "families": []}
    assert router.enroll("a", "prefill", fingerprint=fp)
    assert router.enroll("b", "decode", fingerprint=fp)
    assert not router.enroll("c", "decode", fingerprint=bad)
    assert "c" not in router.hosts
    counts = router.decision_counts()
    assert counts == {"enroll": 2, "enroll_refusal": 1}
    refusal = [e for e in router.events.events
               if e["decision"] == "enroll_refusal"][0]
    assert refusal["host"] == "c"
    assert refusal["detail"]["reference"] == "ref-digest"


def test_router_admission_prices_buckets_against_slo():
    router = FleetRouter(ttft_slo_s=0.1, admit_budget_factor=1.0)
    bucket_for = lambda n: 16                         # noqa: E731
    # no prices yet: admit on faith
    assert router.admit(0, 10, bucket_for)
    router.observe_prefill(16, 0.06)
    # 0.06 * (1 + 0 queued) fits the 0.1s budget
    assert router.admit(1, 10, bucket_for, queue_depth=0)
    # 0.06 * (1 + 2 queued) = 0.18 > 0.1: denied at the door
    assert not router.admit(2, 10, bucket_for, queue_depth=2)
    assert router.denied == [2]
    deny = [e for e in router.events.events
            if e["decision"] == "deny"][0]
    assert deny["request_uid"] == 2
    assert deny["predicted_cost_s"] == pytest.approx(0.06)
    # EWMA folds new walls in at alpha=0.4
    router.observe_prefill(16, 0.01)
    assert router.predicted_cost(10, bucket_for) == \
        pytest.approx(0.4 * 0.01 + 0.6 * 0.06)
    # unpriced buckets interpolate linearly from the nearest priced one
    assert router.predicted_cost(30, lambda n: 32) == \
        pytest.approx(router.predicted_cost(10, bucket_for) * 2)


def test_router_routes_away_from_flagged_hosts():
    router = FleetRouter()
    router.enroll("d0", "decode", role=_FakeRole(2))
    router.enroll("d1", "decode", role=_FakeRole(2))
    router.mark_straggler("d0")
    for _ in range(3):
        assert router.pick_decode_host(uid=7) == "d1"
    assert router.hosts["d0"].decode_assignments == 0
    counts = router.decision_counts()
    assert counts["route_away"] == 3
    away = [e for e in router.events.events
            if e["decision"] == "route_away"][0]
    assert away["host"] == "d0" and "straggler" in away["reason"]
    # clearing the flag restores eligibility (least-loaded wins)
    router.mark_straggler("d0", flagged=False)
    assert router.pick_decode_host() == "d0"


def test_router_ingests_fleet_report_flags():
    router = FleetRouter()
    router.enroll("d0", "decode", role=_FakeRole())
    router.enroll("d1", "decode", role=_FakeRole())
    router.ingest_fleet_report(
        {"straggler": {"flags": [{"host": "d0", "z": 4.0}]}})
    assert router.hosts["d0"].straggler
    assert not router.hosts["d1"].straggler
    router.observe_healthz("d1", {"status": "degraded"})
    assert router.pick_decode_host() is None         # nobody eligible
    router.ingest_fleet_report({"straggler": {"flags": []}})
    router.observe_healthz("d1", {"status": "ok"})
    assert router.pick_decode_host() in ("d0", "d1")


def test_router_events_land_on_disk_schema_valid(tmp_path):
    router = FleetRouter(event_dir=str(tmp_path))
    router.enroll("d0", "decode", role=_FakeRole())
    router.admit(0, 5, lambda n: 8)
    path = os.path.join(str(tmp_path), events.ROUTER_EVENTS_JSONL)
    with open(path) as fh:
        lines = [json.loads(line) for line in fh]
    assert len(lines) == 2
    for ev in lines:
        assert events.validate_router_event(ev) == [], ev


# ----------------------------------------------------- schema pinning


def test_router_event_validator_catches_drift():
    ev = events.make_router_event(decision="admit", request_uid=3,
                                  predicted_cost_s=0.01)
    assert events.validate_router_event(ev) == []
    bad = dict(ev)
    bad["decision"] = "shrug"
    assert any("decision" in p for p in
               events.validate_router_event(bad))
    missing = dict(ev)
    del missing["host"]
    assert any("missing" in p for p in
               events.validate_router_event(missing))
    extra = dict(ev, freelance=1)
    assert any("unexpected" in p for p in
               events.validate_router_event(extra))
    wrong_wall = dict(ev, wall="yesterday")
    assert any("wall" in p for p in
               events.validate_router_event(wrong_wall))
    assert events.validate_router_event("not a dict")


def test_serving_role_field_pinned_across_schema_copies():
    """The nullable ``role`` StepRecord field and SERVING_ROLES
    vocabulary stay identical across telemetry/record.py, the fleet
    merger's stdlib copy, and bin/check_bench_schema.py's copy."""
    assert "role" in record.SERVING_STEP_KEYS
    assert record.SERVING_ROLES == aggregate.SERVING_ROLES
    cbs = load_checker()
    assert cbs.SERVING_ROLES == record.SERVING_ROLES
    # a roled record validates; a freelance role does not
    kw = dict(step=0, slot_occupancy=0.5, queue_depth=0, active_slots=1,
              prefill_tokens=8, prefill_tokens_per_sec=1.0,
              decode_tokens=4, decode_steps=4,
              decode_tokens_per_sec=1.0)
    for role in record.SERVING_ROLES + (None,):
        rec = record.make_serving_record(role=role, **kw)
        assert record.validate_step_record(rec) == [], role
    bogus = record.make_serving_record(role="sidecar", **kw)
    assert any("role" in p for p in record.validate_step_record(bogus))


def test_router_event_schema_pinned_across_stdlib_copies():
    """events.py is the source of truth; aggregate.py and
    bin/check_bench_schema.py carry stdlib-only copies that must never
    drift (doctoring a crashed run can't import jax)."""
    assert aggregate.ROUTER_EVENT_KEYS == events.ROUTER_EVENT_KEYS
    assert aggregate.ROUTER_DECISIONS == events.ROUTER_DECISIONS
    assert aggregate.ROUTER_EVENTS_JSONL == events.ROUTER_EVENTS_JSONL
    assert aggregate.KIND_ROUTER_EVENT == events.KIND_ROUTER_EVENT
    cbs = load_checker()
    assert cbs.ROUTER_EVENT_KEYS == events.ROUTER_EVENT_KEYS
    assert cbs.ROUTER_DECISIONS == events.ROUTER_DECISIONS


# ----------------------------------------------------------- adapters


def test_adapter_set_registry_and_oracle():
    ads = AdapterSet(d_model=32, vocab_size=128, rank=4)
    assert len(ads) == 1 and ads.id_of("base") == 0
    aid = ads.add("tenant-a")
    assert aid == 1 and ads.id_of("tenant-a") == 1
    with pytest.raises(AssertionError):
        ads.add("tenant-a")
    hidden = np.random.RandomState(0).normal(size=(3, 32))
    # base delta is exactly zero; a fresh LoRA adapter (B=0) too
    assert not ads.logits_delta(hidden, 0).any()
    assert not ads.logits_delta(hidden, 1).any()
    B = np.random.RandomState(1).normal(size=(128, 4)).astype(np.float32)
    ads.add("tenant-b", B=B)
    delta = ads.logits_delta(hidden, 2)
    assert delta.shape == (3, 128) and np.abs(delta).sum() > 0


def test_adapter_zero_is_byte_identical_base(model):
    """Attaching adapters switches the engine onto the adapter-aware
    program family; adapter id 0 (the all-zero BASE row) must still be
    byte-identical to the adapter-free engine."""
    plain = paged_engine(model, max_batch_size=2)
    adapted = paged_engine(model, max_batch_size=2)
    ads = AdapterSet(d_model=TINY["d_model"],
                     vocab_size=TINY["vocab_size"], rank=4)
    ads.add("tenant-a")
    adapted.attach_adapters(ads)
    prompts = [[3, 1, 4, 1, 5], list(range(2, 22))]
    assert adapted.generate(prompts, max_new_tokens=6) == \
        plain.generate(prompts, max_new_tokens=6)


def test_adapter_tenants_diverge_and_base_unpolluted(model):
    """A tenant with a trained (nonzero-B) adapter serves a different
    stream than the base, in the SAME mixed batch, while base traffic
    through the same engine stays on the oracle stream."""
    eng = paged_engine(model, max_batch_size=2)
    ads = AdapterSet(d_model=TINY["d_model"],
                     vocab_size=TINY["vocab_size"], rank=4)
    rs = np.random.RandomState(7)
    ads.add("tenant-a",
            A=rs.normal(0, 1.0, size=(4, TINY["d_model"])),
            B=rs.normal(0, 2.0, size=(TINY["vocab_size"], 4)))
    eng.attach_adapters(ads)
    prompt = [9, 2, 6, 5, 3, 5]
    sched = ContinuousBatchingScheduler(eng)
    u_base = sched.submit(prompt, max_new_tokens=6)
    u_ten = sched.submit(prompt, max_new_tokens=6,
                         adapter=ads.id_of("tenant-a"))
    out = sched.run()
    assert out[u_base] == greedy_chain(model, prompt, 6)
    assert out[u_ten] != out[u_base]


def test_adapter_prefix_cache_namespaced(model):
    """Two tenants with the SAME prompt never cross-hit each other's
    cached prefix pages; same-tenant re-use still hits."""
    eng = paged_engine(model, max_batch_size=1, prefix_caching=True,
                      prefill_buckets=[8, 16, 32])
    ads = AdapterSet(d_model=TINY["d_model"],
                     vocab_size=TINY["vocab_size"], rank=4)
    ads.add("tenant-a")
    eng.attach_adapters(ads)
    prompt = [5, 6, 7] * 5
    sched = ContinuousBatchingScheduler(eng)

    def one(adapter):
        uid = sched.submit(prompt, max_new_tokens=3, adapter=adapter)
        sched.run()
        return uid

    one(0)
    base_hits = eng.prefix_cache.hits
    one(1)                       # other tenant, same prompt: MUST miss
    assert eng.prefix_cache.hits == base_hits
    one(1)                       # same tenant again: hits
    assert eng.prefix_cache.hits > base_hits


# ------------------------------------------------------------- DSL010


_FREELANCE = '''
def emit():
    return {"kind": "serving_step", "step": 1, "wall": 0.0,
            "ttft_budget_burn": 0.9}
'''


def test_dsl010_flags_field_outside_serving_schema(tmp_path):
    from deepspeed_tpu.analysis import astlint
    schema = astlint.load_serving_schema(_REPO)
    assert schema is not None and "role" in schema
    assert "page_pool" in schema and "ttft" in schema
    path = str(tmp_path / "mod.py")
    with open(path, "w") as fh:
        fh.write(_FREELANCE)
    hits = [v for v in astlint.lint_file(path, relpath="mod.py",
                                         serving_schema=schema)
            if v[0] == "DSL010"]
    assert len(hits) == 1
    assert "ttft_budget_burn" in hits[0][3]
    # inert without a schema (partial checkout), and record.py itself
    # (the schema's home) is exempt
    assert not [v for v in astlint.lint_file(path, relpath="mod.py")
                if v[0] == "DSL010"]
    assert not [v for v in astlint.lint_file(
        path, relpath="deepspeed_tpu/telemetry/record.py",
        serving_schema=schema) if v[0] == "DSL010"]


def test_dsl010_accepts_schema_conformant_literal(tmp_path):
    from deepspeed_tpu.analysis import astlint
    schema = astlint.load_serving_schema(_REPO)
    path = str(tmp_path / "ok.py")
    with open(path, "w") as fh:
        fh.write('def emit():\n'
                 '    return {"kind": "serving_step", "step": 1,\n'
                 '            "role": "prefill", "ttft": None}\n')
    assert not [v for v in astlint.lint_file(path, relpath="ok.py",
                                             serving_schema=schema)
                if v[0] == "DSL010"]
