"""Pipeline engine correctness: pipeline == sequential training
(mirrors reference test_pipe.py convergence-vs-reference pattern)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.pipe import PipelineModule, LayerSpec, Layer
from deepspeed_tpu.runtime.model import Model
from deepspeed_tpu.runtime.pipe.engine import PipelineError

DIM = 16


class TanhLinear:
    """Simple pipeline-able layer."""

    def __init__(self, dim, seed_scale=1.0):
        self.dim = dim
        self.seed_scale = seed_scale

    def init(self, rng):
        w = jax.random.normal(rng, (self.dim, self.dim)) * 0.3
        return {"w": w, "b": jnp.zeros((self.dim,))}

    def apply(self, params, x):
        return jnp.tanh(x @ params["w"].astype(x.dtype) +
                        params["b"].astype(x.dtype))


def mse_loss(out, labels):
    return jnp.mean((out.astype(jnp.float32) -
                     labels.astype(jnp.float32)) ** 2)


def pipe_config(gas):
    return {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "steps_per_print": 1000,
    }


def make_batches(M, batch, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(M, batch, DIM).astype(np.float32)
    y = np.tanh(x @ (rng.randn(DIM, DIM) * 0.3).astype(np.float32))
    return x, y


def test_pipeline_module_partitioning():
    net = PipelineModule(layers=[LayerSpec(TanhLinear, DIM) for _ in range(8)],
                         num_stages=2, loss_fn=mse_loss)
    d = net.describe()
    assert d["num_stages"] == 2
    assert d["layers_per_stage"] == 4
    assert d["pre"] == 0 and d["post"] == 0
    # body stacked with (stages, layers_per_stage) prefix
    w = net.params["body"]["w"]
    assert w.shape == (2, 4, DIM, DIM)


def test_pipeline_ragged_partition():
    """A body that does not divide the stage count partitions raggedly:
    stage depths sum to the body and differ by at most one (uniform)."""
    net = PipelineModule(layers=[LayerSpec(TanhLinear, DIM) for _ in range(5)],
                         num_stages=2, loss_fn=mse_loss)
    assert sorted(net.stage_depths.tolist()) == [2, 3]
    assert net.layers_per_stage == 3          # padded to the deepest stage
    # stacked body carries the padded slot
    leaf = jax.tree_util.tree_leaves(net.params["body"])[0]
    assert leaf.shape[:2] == (2, 3)


def test_pipeline_matches_sequential_training():
    """2-stage pipeline trains identically to the plain engine on the same
    stacked model."""
    M = 4  # micro batches
    net = PipelineModule(layers=[LayerSpec(TanhLinear, DIM) for _ in range(4)],
                         num_stages=2, loss_fn=mse_loss, num_dp=4)
    ref_params = jax.tree_util.tree_map(jnp.copy, net.params)

    pipe_engine, _, _, _ = deepspeed.initialize(
        model=net, config_params=pipe_config(gas=M))

    # reference: same params, sequential apply, classic engine on 8-dev DP
    def ref_apply(params, x, y):
        return mse_loss(net_seq_apply(params, x), y)

    def net_seq_apply(params, x):
        for s in range(2):
            stage = jax.tree_util.tree_map(lambda t: t[s], params["body"])

            def one(x, lp):
                return TanhLinear(DIM).apply(lp, x), None
            x, _ = jax.lax.scan(one, x, stage)
        return x

    ref_engine, _, _, _ = deepspeed.initialize(
        model=Model(ref_apply, ref_params),
        config_params=pipe_config(gas=M))

    batch_per_micro = 16  # 4 per gpu * 4 dp
    for step in range(3):
        x, y = make_batches(M, batch_per_micro, seed=step)
        pipe_loss = float(pipe_engine.train_batch(batch=(x, y)))
        ref_losses = []
        for m in range(M):
            loss = ref_engine(x[m], y[m])
            ref_engine.backward(loss)
            ref_engine.step()
            ref_losses.append(float(loss))
        assert pipe_loss == pytest.approx(np.mean(ref_losses), rel=2e-2,
                                          abs=2e-3)

    for a, b in zip(jax.tree_util.tree_leaves(pipe_engine.get_params()),
                    jax.tree_util.tree_leaves(
                        ref_engine.get_params()["body"])):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-2, atol=2e-2)


def test_pipeline_converges():
    M = 2
    net = PipelineModule(layers=[LayerSpec(TanhLinear, DIM) for _ in range(4)],
                         num_stages=2, loss_fn=mse_loss, num_dp=4)
    engine, _, _, _ = deepspeed.initialize(model=net,
                                           config_params=pipe_config(gas=M))
    x, y = make_batches(M, 16, seed=1)
    losses = [float(engine.train_batch(batch=(x, y))) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.5, losses


def test_pipeline_forbids_micro_api():
    net = PipelineModule(layers=[LayerSpec(TanhLinear, DIM) for _ in range(2)],
                         num_stages=2, loss_fn=mse_loss, num_dp=4)
    engine, _, _, _ = deepspeed.initialize(model=net,
                                           config_params=pipe_config(gas=2))
    with pytest.raises(PipelineError):
        engine.forward(np.ones((4, DIM)))
    with pytest.raises(PipelineError):
        engine.backward(None)
    with pytest.raises(PipelineError):
        engine.step()


def test_pipeline_eval_batch():
    M = 2
    net = PipelineModule(layers=[LayerSpec(TanhLinear, DIM) for _ in range(4)],
                         num_stages=2, loss_fn=mse_loss, num_dp=4)
    engine, _, _, _ = deepspeed.initialize(model=net,
                                           config_params=pipe_config(gas=M))
    x, y = make_batches(M, 16, seed=2)
    ev1 = float(engine.eval_batch(batch=(x, y)))
    tr = float(engine.train_batch(batch=(x, y)))
    assert ev1 == pytest.approx(tr, rel=5e-2, abs=5e-3)
    ev2 = float(engine.eval_batch(batch=(x, y)))
    assert ev2 < ev1  # training improved the model


def test_ragged_pipeline_matches_sequential_training():
    """Unequal-depth stages (5 layers over 2 stages -> 3+2) must train
    identically to a plain-engine run of the same 5-layer network — the
    milestone-5-class check for the ragged partitioning path."""
    M = 4
    net = PipelineModule(layers=[LayerSpec(TanhLinear, DIM) for _ in range(5)],
                         num_stages=2, loss_fn=mse_loss, num_dp=4)
    depths = net.stage_depths.tolist()
    assert sorted(depths) == [2, 3]
    parts = net.parts

    # reference params: the REAL layers only, in global order
    ref_body = {
        "w": jnp.stack([net.params["body"]["w"][s, i - parts[s]]
                        for s in range(2)
                        for i in range(parts[s], parts[s + 1])]),
        "b": jnp.stack([net.params["body"]["b"][s, i - parts[s]]
                        for s in range(2)
                        for i in range(parts[s], parts[s + 1])]),
    }

    pipe_engine, _, _, _ = deepspeed.initialize(
        model=net, config_params=pipe_config(gas=M))

    def ref_apply(params, x, y):
        def one(x, lp):
            return TanhLinear(DIM).apply(lp, x), None
        out, _ = jax.lax.scan(one, x, params)
        return mse_loss(out, y)

    ref_engine, _, _, _ = deepspeed.initialize(
        model=Model(ref_apply, ref_body),
        config_params=pipe_config(gas=M))

    batch_per_micro = 16
    for step in range(3):
        x, y = make_batches(M, batch_per_micro, seed=step)
        pipe_loss = float(pipe_engine.train_batch(batch=(x, y)))
        ref_losses = []
        for m in range(M):
            loss = ref_engine(x[m], y[m])
            ref_engine.backward(loss)
            ref_engine.step()
            ref_losses.append(float(loss))
        assert pipe_loss == pytest.approx(np.mean(ref_losses), rel=2e-2,
                                          abs=2e-3)

    # trained REAL layers match the reference layer-for-layer; padded slots
    # received zero gradient (only decayless Adam state drift is possible)
    pipe_body = pipe_engine.get_params()["body"]
    for name in ("w", "b"):
        trained = np.stack([np.asarray(pipe_body[name][s, i - parts[s]],
                                       np.float32)
                            for s in range(2)
                            for i in range(parts[s], parts[s + 1])])
        np.testing.assert_allclose(
            trained, np.asarray(ref_engine.get_params()[name], np.float32),
            rtol=2e-2, atol=2e-2)


def test_pipelined_eval_matches_sequential():
    """eval_batch runs THROUGH the pipe loop (InferenceSchedule parity);
    its loss must equal the sequential-apply loss exactly, including on a
    ragged (2+1) partition."""
    M = 3
    net = PipelineModule(layers=[LayerSpec(TanhLinear, DIM) for _ in range(3)],
                         num_stages=2, loss_fn=mse_loss, num_dp=4)
    engine, _, _, _ = deepspeed.initialize(model=net,
                                           config_params=pipe_config(gas=M))
    x, y = make_batches(M, 16, seed=5)
    ev = float(engine.eval_batch(batch=(x, y)))

    params = engine.state["params"]
    seq_losses = [
        float(mse_loss(net.apply_sequential(
            jax.tree_util.tree_map(lambda t: jnp.asarray(t), params),
            jnp.asarray(x[m], params["body"]["w"].dtype)), y[m]))
        for m in range(M)]
    assert ev == pytest.approx(np.mean(seq_losses), rel=1e-3, abs=1e-4)


def test_pipeline_with_cpu_offload():
    """ZeRO-Offload under pipeline parallelism: the pipe loop jits only
    grad accumulation and the optimizer step runs on host (shard-wise) —
    training must converge like the on-device pipeline."""
    M = 2
    net = PipelineModule(layers=[LayerSpec(TanhLinear, DIM) for _ in range(4)],
                         num_stages=2, loss_fn=mse_loss, num_dp=4)
    config = pipe_config(gas=M)
    config["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    engine, _, _, _ = deepspeed.initialize(model=net, config_params=config)
    assert engine.host_state is not None
    losses = []
    for step in range(40):
        x, y = make_batches(M, 16, seed=step % 5)
        losses.append(float(engine.train_batch(batch=(x, y))))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    assert engine.host_state["step"] == 40


def test_uncertified_combos_rejected():
    """The support-matrix guard (docs/_tutorials/parallelism.md): ZeRO
    stage >= 2 with PP x TP deadlocks at runtime under one-program SPMD,
    so PipelineEngine must reject it at construction — loudly, with a
    pointer to the matrix."""
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2, gpt2_pipe
    from deepspeed_tpu.runtime.pipe.engine import PipelineError

    cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=64, n_layers=2,
                          n_heads=4, d_model=64, use_flash_attention=False,
                          remat=False)
    for stage in (2, 3):
        net = gpt2_pipe.make_gpt2_pipeline(
            config=cfg, num_stages=2, num_dp=2, num_mp=2,
            activation_checkpoint_interval=0)
        with pytest.raises(PipelineError, match="not .*certified"):
            deepspeed.initialize(model=net, config_params={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": stage},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "steps_per_print": 10 ** 9})

    # elasticity x PP: reference restriction, rejected the same way
    net = gpt2_pipe.make_gpt2_pipeline(
        config=cfg, num_stages=2, num_dp=4, num_mp=1,
        activation_checkpoint_interval=0)
    with pytest.raises(PipelineError, match="[Ee]lasticity"):
        deepspeed.initialize(model=net, config_params={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "elasticity": {"enabled": True, "max_train_batch_size": 64,
                           "ignore_non_elastic_batch_info": True,
                           "micro_batch_sizes": [2],
                           "min_gpus": 1, "max_gpus": 8,
                           "min_time": 20, "version": 0.1},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9})


@pytest.mark.parametrize("layers,v", [(8, 2), (7, 2)])
def test_interleaved_matches_v1(layers, v):
    """Interleaved (v virtual chunks per rank) pipelines train and eval
    IDENTICALLY to v=1 on the same layer list — including a ragged
    virtual partition (7 layers over 4 virtual stages)."""
    M = 4

    def build(num_virtual):
        net = PipelineModule(
            layers=[LayerSpec(TanhLinear, DIM) for _ in range(layers)],
            num_stages=2, loss_fn=mse_loss, num_dp=4,
            num_virtual_stages=num_virtual)
        engine, _, _, _ = deepspeed.initialize(
            model=net, config_params=pipe_config(gas=M))
        return engine

    e1, ev = build(1), build(v)
    leaf1 = jax.tree_util.tree_leaves(ev.state["params"]["body"])[0]
    assert leaf1.ndim == 4 and leaf1.shape[:2] == (2, v)
    for step in range(3):
        x, y = make_batches(M, 16, seed=step)
        l1 = float(e1.train_batch(batch=(x, y)))
        lv = float(ev.train_batch(batch=(x, y)))
        assert lv == pytest.approx(l1, rel=2e-2, abs=2e-3), step
    x, y = make_batches(M, 16, seed=99)
    assert float(ev.eval_batch(batch=(x, y))) == pytest.approx(
        float(e1.eval_batch(batch=(x, y))), rel=2e-2, abs=2e-3)


def test_interleaved_3d_with_tp():
    """v=2 interleaving under the full 3D mesh (pipe x data x model,
    ZeRO-1) runs and produces a finite loss with pipe-sharded params."""
    import dataclasses
    from deepspeed_tpu.models import gpt2, gpt2_pipe
    cfg = gpt2.GPT2Config(vocab_size=512, max_seq_len=64, n_layers=4,
                          n_heads=4, d_model=64, use_flash_attention=False,
                          remat=False)
    net = gpt2_pipe.make_gpt2_pipeline(
        config=cfg, num_stages=2, num_dp=2, num_mp=2,
        activation_checkpoint_interval=1, num_virtual_stages=2)
    engine, _, _, _ = deepspeed.initialize(model=net, config_params={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9})
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 512, size=(2, 4, 64)).astype(np.int32)
    loss = float(engine.train_batch(batch=(ids, ids.copy())))
    assert np.isfinite(loss)
    body_w = engine.state["params"]["body"]["attn"]["qkv_kernel"]
    assert body_w.ndim >= 4 and "pipe" in str(body_w.sharding.spec)


def test_interleaved_checkpoint_cross_layout(tmp_path):
    """A checkpoint saved by a v=2 engine loads into a v=1 engine (and
    back) — the pipe_layout metadata carries the virtual partition, so
    restacking is exact."""
    M = 4
    save_dir = str(tmp_path / "ckpt")

    def build(num_virtual, seed=1234):
        net = PipelineModule(
            layers=[LayerSpec(TanhLinear, DIM) for _ in range(8)],
            num_stages=2, loss_fn=mse_loss, num_dp=4,
            num_virtual_stages=num_virtual, base_seed=seed)
        engine, _, _, _ = deepspeed.initialize(
            model=net, config_params=pipe_config(gas=M))
        return engine

    ev = build(2)
    x, y = make_batches(M, 16, seed=0)
    ev.train_batch(batch=(x, y))
    ev.save_checkpoint(save_dir)
    ref = float(ev.eval_batch(batch=(x, y)))

    e1 = build(1, seed=777)       # different init; must load v=2 files
    path, _ = e1.load_checkpoint(save_dir)
    assert path is not None
    got = float(e1.eval_batch(batch=(x, y)))
    assert got == pytest.approx(ref, rel=1e-2, abs=1e-3)


@pytest.mark.slow
def test_save_stage_residuals_matches_default():
    """save_stage_residuals=True (no-recompute backward: fwd-phase vjp
    pullbacks buffered in the W-slot ring) trains identically to the
    default recompute backward — with interleaving too."""
    M = 4
    for v in (1, 2):
        losses = {}
        for save in (False, True):
            net = PipelineModule(
                layers=[LayerSpec(TanhLinear, DIM) for _ in range(8)],
                num_stages=2, loss_fn=mse_loss, num_dp=4,
                num_virtual_stages=v, save_stage_residuals=save)
            engine, _, _, _ = deepspeed.initialize(
                model=net, config_params=pipe_config(gas=M))
            ls = []
            for step in range(3):
                x, y = make_batches(M, 16, seed=step)
                ls.append(float(engine.train_batch(batch=(x, y))))
            losses[save] = ls
        for a, b in zip(losses[False], losses[True]):
            assert b == pytest.approx(a, rel=2e-2, abs=2e-3), v
