"""Unified step telemetry (ISSUE 5): StepRecord golden schema, MFU math
pinned against XLA cost_analysis, sinks, trace windows, monitor
lifecycle, memory_breakdown strictness, and zero-overhead-off."""
import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.model import Model
from deepspeed_tpu.telemetry import (flops_of_compiled, mfu_of,
                                     peak_flops_for, validate_step_record)
from deepspeed_tpu.telemetry.config import DeepSpeedTelemetryConfig
from deepspeed_tpu.telemetry.trace import TraceWindow
from deepspeed_tpu.utils.monitor import SummaryMonitor

pytestmark = pytest.mark.telemetry


import contextlib  # noqa: E402
import logging  # noqa: E402
from deepspeed_tpu.utils.logging import logger as ds_logger  # noqa: E402


@contextlib.contextmanager
def _capture_warnings():
    """The DS logger has propagate=False, so caplog can't see it; attach
    a handler directly (the repo's test_flops_profiler idiom)."""
    messages = []

    class _Cap(logging.Handler):
        def emit(self, record):
            messages.append(record.getMessage())

    cap = _Cap(level=logging.WARNING)
    ds_logger.addHandler(cap)
    try:
        yield messages
    finally:
        ds_logger.removeHandler(cap)


def _toy_model():
    return Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                 {"w": jnp.zeros((4, 2))})


def _engine(tmp_path, extra=None, gas=1, telemetry=True):
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "wall_clock_breakdown": True,
    }
    if telemetry:
        config["telemetry"] = {"enabled": True,
                               "output_path": str(tmp_path)}
    config.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=_toy_model(),
                                               config_params=config)
    return engine


def _records(engine):
    return [json.loads(line) for line in open(engine.telemetry.jsonl_path)]


def _batch():
    return jnp.ones((8, 4)), jnp.ones((8, 2))


# --------------------------------------------------------------- schema

def test_step_record_golden_schema_and_phase_sum(tmp_path):
    engine = _engine(tmp_path, gas=2)
    x, y = _batch()
    for _ in range(4):                     # 2 optimizer steps at gas=2
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    recs = _records(engine)
    assert len(recs) == 2                  # one record per OPTIMIZER step
    for rec in recs:
        assert validate_step_record(rec) == []
        assert rec["kind"] == "train_step"
        assert rec["micro_steps"] == 2
        # 2 micros x (8 x 4) first-leaf elements
        assert rec["tokens_per_step"] == 2 * 8 * 4
        assert rec["model_flops_per_step"] > 0
        assert rec["loss"] is not None and rec["loss_scale"] > 0
        assert rec["overflow"] is False
        # phase times are present (wall_clock_breakdown), disjoint, and
        # sum to phase_total_s <= ~the measured window wall
        assert rec["phases"] and rec["phase_total_s"] > 0
        assert abs(sum(rec["phases"].values()) - rec["phase_total_s"]) \
            < 1e-9
        assert rec["phase_total_s"] <= rec["step_time_s"] * 1.05
    assert recs[0]["step"] == 0 and recs[1]["step"] == 1
    snap = engine.telemetry_snapshot()
    assert snap["steps"] == 2
    for dist_key in ("step_time_s", "mfu", "tokens_per_sec_per_chip"):
        for stat in ("last", "mean", "p50", "p95"):
            assert snap[dist_key][stat] >= 0
    assert snap["hbm_last"]["available"] in (True, False)


def test_step_time_clock_reads_after_device_fetches(tmp_path, monkeypatch):
    """step_time_s prices device execution, not host dispatch: the
    loss/grad_norm/overflow value fetches (which block on the async step
    program) must ALL run before _emit_train_telemetry reads the wall
    clock, or on async backends the record would stop the clock while
    the step is still running and overstate MFU/tokens-per-sec."""
    from deepspeed_tpu.runtime import engine as engine_mod

    engine = _engine(tmp_path)
    x, y = _batch()
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()

    log = []

    class _Fetch:
        def __init__(self, val):
            self._val = val

        def __float__(self):
            log.append("fetch")
            return self._val

        def __bool__(self):
            log.append("fetch")
            return False

    class _Clock:
        @staticmethod
        def time():
            log.append("clock")
            return 123.0

    monkeypatch.setattr(engine_mod, "time", _Clock)
    engine._step_metrics = {"grad_norm": _Fetch(1.0),
                            "overflow": _Fetch(0.0),
                            "loss_scale": 1.0}
    engine._window_t0 = 100.0
    engine._emit_train_telemetry(_Fetch(0.5))
    assert log.count("fetch") == 3 and log.count("clock") == 1
    assert log.index("clock") > max(
        i for i, entry in enumerate(log) if entry == "fetch")


def test_mfu_pinned_against_cost_analysis(tmp_path):
    engine = _engine(tmp_path)
    x, y = _batch()
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    rec = _records(engine)[-1]

    # hand-compute the step's flops from the SAME compiled programs the
    # engine ran: the micro (fwd+bwd) program + the optimizer apply
    batch_dev = engine._to_device((x, y))
    micro = engine._jit_cache["micro"]
    apply_fn = engine._jit_cache["apply"]
    expected = flops_of_compiled(micro, engine.state, batch_dev,
                                 jax.random.PRNGKey(0),
                                 engine._pld_theta()) + \
        flops_of_compiled(apply_fn, engine.state, engine._hyper())
    assert expected > 0
    assert rec["model_flops_per_step"] == pytest.approx(expected)

    # the record's MFU is exactly flops / (dt * n_devices * peak)
    peak = peak_flops_for(jax.devices()[0])
    assert rec["peak_flops_per_chip"] == peak
    assert rec["mfu"] == pytest.approx(
        rec["model_flops_per_step"] /
        (rec["step_time_s"] * rec["n_devices"] * peak), rel=1e-6)
    assert mfu_of(0.0, 1.0, 8, peak) == 0.0


def test_train_batch_fused_path_emits_records(tmp_path):
    engine = _engine(tmp_path, extra={"train_batch_size": 8})
    x, y = np.ones((1, 8, 4), np.float32), np.ones((1, 8, 2), np.float32)
    engine.train_batch(batch=(x, y))
    engine.train_batch(batch=(x, y))
    recs = _records(engine)
    assert len(recs) == 2
    for rec in recs:
        assert validate_step_record(rec) == []
        assert rec["model_flops_per_step"] > 0
        assert rec["tokens_per_step"] == 8 * 4


def test_telemetry_off_is_zero_overhead(tmp_path):
    engine = _engine(tmp_path, telemetry=False)
    assert engine.telemetry is None
    assert engine.telemetry_snapshot() == {}
    x, y = _batch()
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    # no telemetry dirs, no flops lowering, no window accounting
    assert engine._tele_flops_cache == {}
    assert engine._window_t0 is None
    assert not os.path.exists(str(tmp_path / "train"))


# ------------------------------------------------------ monitor lifecycle

def test_monitor_close_idempotent_and_atexit_deregistered(tmp_path,
                                                          monkeypatch):
    import atexit
    # warm the torch/tensorboard imports first: their FIRST import
    # registers their own atexit handlers, which would pollute the
    # patched registry below
    SummaryMonitor(str(tmp_path), "warmup").close()
    registered, unregistered = [], []

    def fake_register(fn, *args, **kwargs):
        registered.append(fn)
        return fn

    monkeypatch.setattr(atexit, "register", fake_register)
    monkeypatch.setattr(atexit, "unregister",
                        lambda fn: unregistered.append(fn))
    mon = SummaryMonitor(str(tmp_path), "job")
    assert registered == [mon._atexit_handler]   # exactly one handler
    mon.add_scalar("x", 1.0, 0)
    mon.close()
    mon.close()                            # idempotent
    # the SAME object that was registered is unregistered, exactly once
    assert unregistered == registered
    # writes after close are silently dropped, not crashes
    mon.add_scalar("y", 2.0, 1)
    lines = open(tmp_path / "job" / "events.jsonl").readlines()
    assert len(lines) == 1


def test_multi_engine_monitors_write_distinct_files(tmp_path):
    """Train + inference monitors in ONE process: distinct events.jsonl
    files, independent close."""
    train = SummaryMonitor(str(tmp_path), "train")
    serve = SummaryMonitor(str(tmp_path), "serve")
    train.add_scalar("Train/loss", 1.0, 1)
    serve.add_scalar("Serve/queue_depth", 3.0, 1)
    train.close()
    serve.add_scalar("Serve/queue_depth", 2.0, 2)    # serve still live
    serve.close()
    t = [json.loads(l) for l in open(tmp_path / "train" / "events.jsonl")]
    s = [json.loads(l) for l in open(tmp_path / "serve" / "events.jsonl")]
    assert [e["tag"] for e in t] == ["Train/loss"]
    assert [e["tag"] for e in s] == ["Serve/queue_depth"] * 2


# -------------------------------------------------- memory_breakdown key

def test_memory_breakdown_unavailable_warns(tmp_path):
    """CPU backend has no memory_stats(): memory_breakdown=true warns
    LOUDLY instead of silently no-oping."""
    with _capture_warnings() as messages:
        _engine(tmp_path, extra={"memory_breakdown": True})
    assert any("memory_breakdown" in m and "memory_stats" in m
               for m in messages)


def test_memory_breakdown_raises_under_strict(tmp_path):
    with pytest.raises(ValueError, match="memory_breakdown"):
        _engine(tmp_path, extra={
            "memory_breakdown": True,
            "telemetry": {"enabled": True, "strict": True,
                          "output_path": str(tmp_path)}})


# ------------------------------------------------------- config section

def test_telemetry_config_unknown_key_warns_and_strict_raises():
    with _capture_warnings() as messages:
        DeepSpeedTelemetryConfig({"telemetry": {"enabled": True,
                                                "output_path": "x",
                                                "bogus_key": 1}})
    assert any("bogus_key" in m for m in messages)
    with pytest.raises(ValueError, match="bogus_key"):
        DeepSpeedTelemetryConfig({"telemetry": {"enabled": True,
                                                "strict": True,
                                                "output_path": "x",
                                                "bogus_key": 1}})
    with pytest.raises(ValueError, match="window"):
        DeepSpeedTelemetryConfig({"telemetry": {"window": 0}})
    with pytest.raises(ValueError, match="num_steps"):
        DeepSpeedTelemetryConfig({"telemetry": {
            "trace": {"start_step": 1, "num_steps": 0}}})
    # a trace block that can never arm is a loud no-op, strict raises
    with pytest.raises(ValueError, match="trace"):
        DeepSpeedTelemetryConfig({"telemetry": {"strict": True,
                                                "trace": {}}})


def test_unknown_telemetry_key_hits_config_validation():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    with pytest.raises(DeepSpeedConfigError, match="telemetry"):
        DeepSpeedConfig(None, param_dict={
            "train_batch_size": 8,
            "config_validation": "strict",
            "telemetry": {"enabled": True, "output_path": "x",
                          "not_a_key": True}})


# --------------------------------------------------------- trace windows

class _FakeProfiler:
    def __init__(self, fail_start=False):
        self.calls = []
        self.fail_start = fail_start

    def start_trace(self, path):
        if self.fail_start:
            raise RuntimeError("no profiler here")
        self.calls.append(("start", path))

    def stop_trace(self):
        self.calls.append(("stop",))


def test_trace_window_step_range(tmp_path, monkeypatch):
    win = TraceWindow(str(tmp_path / "trace"), start_step=2, num_steps=2)
    fake = _FakeProfiler()
    monkeypatch.setattr(win, "_profiler", lambda: fake)
    for step in range(6):
        win.on_step_begin(step)
        win.on_step_end(step)
    assert fake.calls == [("start", str(tmp_path / "trace")), ("stop",)]
    assert win.windows_completed == 1
    assert not win.active


def test_trace_window_trigger_file_consumed(tmp_path, monkeypatch):
    trigger = tmp_path / "trace.now"
    win = TraceWindow(str(tmp_path / "trace"), start_step=None,
                      num_steps=1, trigger_file=str(trigger))
    fake = _FakeProfiler()
    monkeypatch.setattr(win, "_profiler", lambda: fake)
    win.on_step_begin(0)
    win.on_step_end(0)
    assert fake.calls == []                # not armed yet
    trigger.write_text("")
    win.on_step_begin(1)
    win.on_step_end(1)
    assert fake.calls == [("start", str(tmp_path / "trace")), ("stop",)]
    assert not trigger.exists()            # consumed: one touch, one window


def test_trace_window_loud_noop_without_profiler(tmp_path, monkeypatch):
    win = TraceWindow(str(tmp_path / "trace"), start_step=0, num_steps=1)
    monkeypatch.setattr(win, "_profiler",
                        lambda: _FakeProfiler(fail_start=True))
    with _capture_warnings() as messages:
        win.on_step_begin(0)
        win.on_step_end(0)
    assert not win.active and win.windows_completed == 0
    assert any("profiler unavailable" in m for m in messages)


def test_trace_window_process_global_ownership(tmp_path, monkeypatch):
    """The jax profiler is process-global: with a train and a serving
    window in one process, the second to open skips LOUDLY instead of
    crashing or truncating the first's window."""
    one = TraceWindow(str(tmp_path / "tr1"), start_step=0)
    two = TraceWindow(str(tmp_path / "tr2"), start_step=0)
    f1, f2 = _FakeProfiler(), _FakeProfiler()
    monkeypatch.setattr(one, "_profiler", lambda: f1)
    monkeypatch.setattr(two, "_profiler", lambda: f2)
    one.on_step_begin(0)
    with _capture_warnings() as messages:
        two.on_step_begin(0)
    assert one.active and not two.active
    assert any("process-global" in m for m in messages)
    one.on_step_end(0)
    assert one.windows_completed == 1 and f2.calls == []
    # ownership released: the other engine may trace the NEXT window
    two._armed_at = 1
    two.on_step_begin(1)
    two.on_step_end(1)
    assert two.windows_completed == 1


def test_explicit_job_name_multi_engine_files_stay_apart(tmp_path):
    """An explicit telemetry.job_name shared by two engines in one
    process must not point both at the same telemetry.jsonl."""
    from deepspeed_tpu.telemetry.collector import TelemetryCollector

    def tc():
        return DeepSpeedTelemetryConfig({"telemetry": {
            "enabled": True, "output_path": str(tmp_path),
            "job_name": "myjob"}})

    train = TelemetryCollector(tc(), job_name="train")
    serve = TelemetryCollector(tc(), job_name="serve")
    twin = TelemetryCollector(tc(), job_name="train")   # same-role dup
    try:
        assert len({train.jsonl_path, serve.jsonl_path,
                    twin.jsonl_path}) == 3
        assert train.job_name == "myjob"
        assert serve.job_name == "myjob-serve"
    finally:
        train.close()
        serve.close()
        twin.close()
    # close() releases the claim: a fresh engine gets the bare name back
    fresh = TelemetryCollector(tc(), job_name="train")
    try:
        assert fresh.job_name == "myjob"
        # a different SPELLING of the same directory must still collide
        # (the guard compares normalized paths, not raw strings)
        spelled = TelemetryCollector(
            DeepSpeedTelemetryConfig({"telemetry": {
                "enabled": True,
                "output_path": os.path.join(str(tmp_path), "."),
                "job_name": "myjob"}}),
            job_name="train")
        try:
            assert (os.path.realpath(spelled.output_dir)
                    != os.path.realpath(fresh.output_dir))
        finally:
            spelled.close()
    finally:
        fresh.close()


def test_device_synchronize_rebuilds_stale_scratch():
    """A stale cached sync scalar (backend reset) must be rebuilt and
    the fence retried — not silently skipped for that interval."""
    from deepspeed_tpu.utils import timer as timer_mod

    class Dead:
        def __add__(self, other):
            raise RuntimeError("buffer on a dead backend")

    old = timer_mod._sync_scratch
    try:
        timer_mod._sync_scratch = Dead()
        timer_mod._device_synchronize()     # must not raise
        assert not isinstance(timer_mod._sync_scratch, Dead)
        assert timer_mod._sync_scratch is not None
    finally:
        timer_mod._sync_scratch = old


# ------------------------------------------------------------- serving

def test_serving_records_through_same_sinks(tmp_path):
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=32, n_layers=1,
                          n_heads=2, d_model=16, use_flash_attention=False,
                          remat=False)
    engine = deepspeed_tpu.init_inference(
        model=gpt2.make_gpt2_model(config=cfg),
        config={"inference": {"max_batch_size": 2, "prefill_buckets": [8],
                              "dtype": "fp32", "greedy": True,
                              "max_new_tokens": 3},
                "telemetry": {"enabled": True,
                              "output_path": str(tmp_path)}})
    outs = engine.generate([[1, 2, 3], [4, 5]])
    assert all(len(o) == 3 for o in outs)
    recs = [json.loads(line) for line in open(engine.telemetry.jsonl_path)]
    assert recs and all(r["kind"] == "serving_step" for r in recs)
    for rec in recs:
        assert validate_step_record(rec) == []
    # 0-based like train records, so the two JSONLs join on `step`
    assert [r["step"] for r in recs] == list(range(len(recs)))
    # the index is ENGINE-lifetime: a second generate() call (fresh
    # scheduler) must keep counting, not restart at 0
    engine.generate([[6, 7]])
    recs = [json.loads(line) for line in open(engine.telemetry.jsonl_path)]
    assert [r["step"] for r in recs] == list(range(len(recs)))
    # ... and the embedded counters share that lifetime (cumulative
    # across generate() calls): per-step deltas must never go negative
    # at a call boundary
    toks = [r["decode_tokens"] for r in recs]
    assert toks == sorted(toks) and toks[-1] > toks[0]
    snap = engine.telemetry_snapshot()
    assert snap["serving_steps"] == len(recs) >= 2
    assert snap["serving"]["decode_tokens_per_sec"] > 0
    assert 0 < snap["serving"]["slot_occupancy"]["mean"] <= 1


def test_idle_scheduler_steps_emit_no_records(tmp_path):
    """A polling serve loop drives step() while idle; zero-work steps
    (empty queue, no active slots) must not append serving records or
    advance the engine-lifetime record index — otherwise the JSONL
    grows without bound and the snapshot's occupancy/queue p50/p95
    collapse to the idle value."""
    from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=32, n_layers=1,
                          n_heads=2, d_model=16, use_flash_attention=False,
                          remat=False)
    engine = deepspeed_tpu.init_inference(
        model=gpt2.make_gpt2_model(config=cfg),
        config={"inference": {"max_batch_size": 2, "prefill_buckets": [8],
                              "dtype": "fp32", "greedy": True,
                              "max_new_tokens": 2},
                "telemetry": {"enabled": True,
                              "output_path": str(tmp_path)}})
    engine.generate([[1, 2, 3]])
    n_records = len(open(engine.telemetry.jsonl_path).readlines())
    assert n_records > 0
    step_index = engine.serving_record_steps
    sched = ContinuousBatchingScheduler(engine)
    for _ in range(5):
        assert sched.step() == []
    assert len(open(engine.telemetry.jsonl_path).readlines()) == n_records
    assert engine.serving_record_steps == step_index


def test_serving_trace_window_wraps_decode_work(tmp_path, monkeypatch):
    """An armed serving trace must OPEN before the scheduler step's
    prefill/decode work and CLOSE after it — begin/end back-to-back at
    emit time would trace an empty window."""
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=32, n_layers=1,
                          n_heads=2, d_model=16, use_flash_attention=False,
                          remat=False)
    engine = deepspeed_tpu.init_inference(
        model=gpt2.make_gpt2_model(config=cfg),
        config={"inference": {"max_batch_size": 2, "prefill_buckets": [8],
                              "dtype": "fp32", "greedy": True,
                              "max_new_tokens": 3},
                "telemetry": {"enabled": True,
                              "output_path": str(tmp_path),
                              "trace": {"start_step": 1, "num_steps": 1}}})
    events = []
    fake = _FakeProfiler()
    real_start, real_stop = fake.start_trace, fake.stop_trace
    fake.start_trace = lambda p: (events.append("start"), real_start(p))
    fake.stop_trace = lambda: (events.append("stop"), real_stop())
    monkeypatch.setattr(engine.telemetry.trace, "_profiler", lambda: fake)
    real_decode = engine.decode_step

    def logging_decode(*args, **kwargs):
        events.append("decode")
        return real_decode(*args, **kwargs)

    monkeypatch.setattr(engine, "decode_step", logging_decode)
    engine.generate([[1, 2, 3], [4, 5]])
    assert engine.telemetry.trace.windows_completed == 1
    i_start, i_stop = events.index("start"), events.index("stop")
    assert any(i_start < i < i_stop
               for i, e in enumerate(events) if e == "decode"), events


# ------------------------------------------------------------- pipeline

def test_pipeline_bubble_stats():
    from deepspeed_tpu.models import gpt2, gpt2_pipe
    cfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=16, n_layers=2,
                          n_heads=2, d_model=16, use_flash_attention=False,
                          remat=False)
    net = gpt2_pipe.make_gpt2_pipeline(config=cfg, num_stages=2, num_dp=4,
                                       num_mp=1)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=net, config_params={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 4,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        })
    stats = engine._pipe_telemetry_stats(step_time_s=1.0)
    assert stats["num_stages"] == 2 and stats["micro_batches"] == 4
    # executed bubble (S-1)/(vM) = 1/4
    assert stats["bubble_fraction"] == pytest.approx(0.25)
    assert stats["warmup_cycles"] + stats["steady_cycles"] + \
        stats["drain_cycles"] == stats["total_cycles"]
    assert stats["cycle_time_s"] == pytest.approx(
        1.0 / stats["total_cycles"], abs=1e-6)


# ----------------------------------------------------- transfer metrics

def test_h2d_batcher_occupancy():
    from deepspeed_tpu.runtime.zero.transfer import H2DBatcher
    dev = jax.local_devices()[0]
    batcher = H2DBatcher(bucket_elems=8, dtype=np.float32)
    assert batcher.occupancy() is None
    for i in range(4):
        batcher.add(i, np.ones((4,), np.float32), dev)
    res = batcher.finish()
    assert set(res) == {0, 1, 2, 3}
    assert batcher.elems == 16
    assert batcher.batches == 2            # two full 8-element buckets
    assert batcher.occupancy() == pytest.approx(1.0)


# --------------------------------------------------------- timer fix

def test_device_synchronize_no_fresh_transfer(monkeypatch):
    """The sync used by wall_clock_breakdown must not device_put a fresh
    scalar per call (the measurement perturbing the measured)."""
    from deepspeed_tpu.utils import timer as timer_mod
    calls = {"n": 0}
    real_put = jax.device_put

    def counting_put(*args, **kwargs):
        calls["n"] += 1
        return real_put(*args, **kwargs)

    monkeypatch.setattr(jax, "device_put", counting_put)
    timer_mod._sync_scratch = None         # fresh cache for this test
    for _ in range(5):
        timer_mod._device_synchronize()
    assert calls["n"] <= 1                 # cached scratch only


# ------------------------------------------------- bench schema checker

def _load_checker():
    path = os.path.join(os.path.dirname(__file__), "..", "..", "bin",
                        "check_bench_schema.py")
    spec = importlib.util.spec_from_file_location("check_bench_schema",
                                                  os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_bench_schema_validates_shapes(tmp_path):
    checker = _load_checker()
    good = {"metric": "m", "value": 1.0, "unit": "tokens/s/chip",
            "vs_baseline": 0.5,
            "extra": {"telemetry": {
                "steps": 2, "serving_steps": 0, "window": 50,
                "phases_mean_s": {"forward_microstep": 0.1},
                "step_time_s": {"last": 1, "mean": 1, "p50": 1, "p95": 1},
                "mfu": {"last": .1, "mean": .1, "p50": .1, "p95": .1},
                "tokens_per_sec_per_chip": {"last": 1, "mean": 1,
                                            "p50": 1, "p95": 1}}}}
    assert checker.check_bench_payload(good) == []
    assert checker.check_bench_payload({"metric": 7, "unit": "u",
                                        "value": None})
    bad_tele = dict(good)
    bad_tele["extra"] = {"telemetry": {}}
    assert checker.check_bench_payload(bad_tele)
    # end-to-end over the repo's committed artifacts
    assert checker.main(["check_bench_schema.py"]) == 0
