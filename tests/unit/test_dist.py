"""Distributed-init tests (reference tests/unit/test_dist.py).

Multi-host rendezvous can't run in CI; what's locked here is the env
contract: the launcher surface (MASTER_ADDR/RANK/WORLD_SIZE) and MPI
discovery resolve to the right jax.distributed arguments, and
single-process runs skip initialization.
"""
import os

import pytest

from deepspeed_tpu.utils import distributed as dist


@pytest.fixture(autouse=True)
def clean_env(monkeypatch):
    for var in ("MASTER_ADDR", "MASTER_PORT", "RANK", "WORLD_SIZE",
                "OMPI_COMM_WORLD_SIZE", "OMPI_COMM_WORLD_RANK",
                "SLURM_NTASKS", "SLURM_PROCID", "PMI_SIZE", "PMI_RANK"):
        monkeypatch.delenv(var, raising=False)
    dist._initialized = False
    yield
    dist._initialized = True  # suite runs single-process; keep it marked


def test_single_process_skips_init():
    dist.init_distributed(verbose=False)
    assert dist.is_initialized()


def test_world_size_one_skips_init(monkeypatch):
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
    monkeypatch.setenv("WORLD_SIZE", "1")
    monkeypatch.setenv("RANK", "0")
    dist.init_distributed(verbose=False)
    assert dist.is_initialized()


def test_idempotent():
    dist.init_distributed(verbose=False)
    dist.init_distributed(verbose=False)  # second call is a no-op
    assert dist.is_initialized()


def test_mpi_env_detection(monkeypatch):
    assert not dist._in_mpi_env()
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
    assert dist._in_mpi_env()


def test_mpi_discovery_openmpi(monkeypatch):
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "2")
    addr, world, rank = dist._mpi_discovery(29500, "10.0.0.9:29500")
    assert (addr, world, rank) == ("10.0.0.9:29500", 4, 2)


def test_mpi_discovery_slurm(monkeypatch):
    monkeypatch.setenv("SLURM_NTASKS", "8")
    monkeypatch.setenv("SLURM_PROCID", "5")
    addr, world, rank = dist._mpi_discovery(29501, "head:29501")
    assert (addr, world, rank) == ("head:29501", 8, 5)
