"""Pipeline activation memory stays FLAT in micro_batches (the 1F1B
property; reference TrainSchedule bounds in-flight buffers at
min(stages - stage_id + 1, M), schedule.py:243-247).

The guard compiles the full pipeline train step at gas=4 and gas=16 and
asserts the compiled program's temp (activation/workspace) memory barely
moves — a whole-loop ``jax.grad`` executor (per-step scan residuals, the
round-2 design) fails this with temp memory ~linear in gas. No execution
needed: XLA's buffer assignment is computed at compile time.
"""
import numpy as np
import pytest

import jax
import jax.random as jrandom

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import gpt2, gpt2_pipe

TINY = dict(vocab_size=128, max_seq_len=64, n_layers=4, n_heads=2,
            d_model=64, use_flash_attention=False, remat=False)


def _compiled_temp_bytes(gas, num_virtual=1):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    net = gpt2_pipe.make_gpt2_pipeline(config=gpt2.GPT2Config(**TINY),
                                       num_stages=2, num_dp=4,
                                       activation_checkpoint_interval=0,
                                       num_virtual_stages=num_virtual)
    engine, _, _, _ = deepspeed.initialize(model=net, config_params=cfg)
    ids = np.zeros((gas, 8, 64), np.int32)
    batch = engine._to_device_stacked((ids, ids.copy()))
    fused = engine._get_jit("pipe_train", engine._fused_train_fn,
                            donate=(0,))
    compiled = fused.lower(engine.state, batch, jrandom.PRNGKey(0),
                           engine._hyper()).compile()
    stats = compiled.memory_analysis()
    assert stats.temp_size_in_bytes > 0, "backend reported no temp stats"
    return stats.temp_size_in_bytes


@pytest.mark.slow
def test_pipeline_memory_flat_in_micro_batches():
    t4 = _compiled_temp_bytes(4)
    t16 = _compiled_temp_bytes(16)
    # 4x the microbatches must NOT grow activation memory; allow 10% slack
    # for bookkeeping (schedule tables, loop counters)
    assert t16 <= t4 * 1.10, (t4, t16)


@pytest.mark.slow
def test_interleaved_pipeline_memory_flat_in_micro_batches():
    """The interleaved executor keeps the 1F1B property too: its ring
    holds more slots ((v, W) per chunk) but the count is M-independent,
    so temp memory stays flat as microbatches grow."""
    t4 = _compiled_temp_bytes(4, num_virtual=2)
    t16 = _compiled_temp_bytes(16, num_virtual=2)
    assert t16 <= t4 * 1.10, (t4, t16)
