"""Progressive layer drop tests (reference tests/unit/test_pld.py)."""
import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.runtime.model import Model


def test_theta_schedule():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.001)
    assert pld.get_theta() == 1.0
    thetas = []
    for step in range(0, 5000, 500):
        pld.update_state(step)
        thetas.append(pld.get_theta())
    assert all(b <= a for a, b in zip(thetas, thetas[1:]))
    assert thetas[0] == 1.0  # exp(0)
    assert thetas[-1] > 0.5  # asymptote is theta_bar
    pld.update_state(10 ** 9)
    np.testing.assert_allclose(pld.get_theta(), 0.5, atol=1e-6)


def test_pld_state_kwargs():
    pld = ProgressiveLayerDrop(theta=0.6)
    state = pld.get_state()
    assert state["progressive_layer_drop"] is True
    assert state["pld_theta"] == pld.get_theta()


def test_pld_through_engine():
    """Engine forwards pld kwargs into the model each step
    (reference engine.py:899-900) and updates theta per global step."""
    seen = []

    def apply_fn(params, x, y, progressive_layer_drop=False, pld_theta=1.0):
        seen.append((progressive_layer_drop, float(pld_theta)))
        keep = jnp.asarray(pld_theta, dtype=jnp.float32)
        return jnp.mean((x @ (params["w"] * keep) - y) ** 2)

    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 0.01},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(apply_fn, {"w": jnp.zeros((4, 2))}),
        config_params=config)
    assert engine.progressive_layer_drop is not None
    x, y = jnp.ones((8, 4)), jnp.ones((8, 2))
    for _ in range(3):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    assert seen and all(flag for flag, _ in seen)
    assert engine.progressive_layer_drop.get_theta() < 1.0
