"""Progressive layer drop tests (reference tests/unit/test_pld.py)."""
import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.runtime.model import Model


def test_theta_schedule():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.001)
    assert pld.get_theta() == 1.0
    thetas = []
    for step in range(0, 5000, 500):
        pld.update_state(step)
        thetas.append(pld.get_theta())
    assert all(b <= a for a, b in zip(thetas, thetas[1:]))
    assert thetas[0] == 1.0  # exp(0)
    assert thetas[-1] > 0.5  # asymptote is theta_bar
    pld.update_state(10 ** 9)
    np.testing.assert_allclose(pld.get_theta(), 0.5, atol=1e-6)


def test_pld_state_kwargs():
    pld = ProgressiveLayerDrop(theta=0.6)
    state = pld.get_state()
    assert state["progressive_layer_drop"] is True
    assert state["pld_theta"] == pld.get_theta()


def test_pld_through_engine():
    """Engine forwards pld kwargs into the model each step
    (reference engine.py:899-900) with theta as a TRACED operand — the
    loss below returns the theta the compiled step actually used, so a
    constant-folded schedule would show as a flat loss."""

    def apply_fn(params, x, y, progressive_layer_drop=False, pld_theta=1.0):
        assert progressive_layer_drop
        theta = jnp.asarray(pld_theta, dtype=jnp.float32)
        return jnp.mean((x @ params["w"] - y) ** 2) * 0.0 + theta

    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 0.1},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(apply_fn, {"w": jnp.zeros((4, 2))}),
        config_params=config)
    assert engine.progressive_layer_drop is not None
    x, y = jnp.ones((8, 4)), jnp.ones((8, 2))
    executed_thetas = []
    for _ in range(5):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        executed_thetas.append(float(loss))
    # the model-side theta must follow the host schedule, not the
    # trace-time constant 1.0; forward at step i sees theta(i-1) (the
    # engine updates theta after each optimizer step)
    host = [1.0, 1.0] + [(1.0 - 0.5) * np.exp(-0.1 * s) + 0.5
                         for s in range(1, 4)]
    np.testing.assert_allclose(executed_thetas, host, rtol=1e-5)
    assert executed_thetas[-1] < 0.9, executed_thetas
