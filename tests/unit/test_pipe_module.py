"""PipelineModule structure tests (reference tests/unit/test_pipe_module.py:
LayerSpec deferred build, tied layers, partition methods)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.pipe.module import (Layer, LayerSpec,
                                               TiedLayerSpec, PipelineModule)


class DenseBlock(Layer):
    """A tiny named layer class so type:regex has something to match."""

    built = 0

    def __init__(self, dim):
        DenseBlock.built += 1
        self.dim = dim

        def init(rng):
            return {"w": jax.random.normal(rng, (dim, dim)) * 0.02}

        def apply(p, x):
            return jnp.tanh(x @ p["w"])

        super().__init__(init, apply, name="DenseBlock")


class Emb(Layer):
    def __init__(self, vocab, dim):
        def init(rng):
            return {"wte": jax.random.normal(rng, (vocab, dim)) * 0.02}

        def apply(p, x):
            return p["wte"][x]

        super().__init__(init, apply, name="Emb")


def _specs(n_blocks=4, vocab=32, dim=16):
    return ([LayerSpec(Emb, vocab, dim)] +
            [LayerSpec(DenseBlock, dim) for _ in range(n_blocks)])


def test_layer_spec_defers_build():
    before = DenseBlock.built
    spec = LayerSpec(DenseBlock, 8)
    assert DenseBlock.built == before  # not built yet
    layer = spec.build()
    assert DenseBlock.built == before + 1
    assert isinstance(layer, DenseBlock)
    assert "DenseBlock" in repr(spec)


def test_layer_spec_requires_callable():
    with pytest.raises(RuntimeError):
        LayerSpec("not-a-class", 8)


@pytest.mark.parametrize("method", ["uniform", "parameters",
                                    "type:DenseBlock"])
def test_partition_methods(method):
    net = PipelineModule(_specs(), num_stages=2, partition_method=method)
    assert net.num_stages == 2
    assert net.layers_per_stage == 2
    assert len(net.pre_layers) == 1      # embedding hoisted to all stages
    assert len(net.post_layers) == 0
    # stacked body: (stages, layers_per_stage, dim, dim)
    assert net.body_params["w"].shape[:2] == (2, 2)


def test_type_regex_no_match_raises():
    with pytest.raises(AssertionError):
        PipelineModule(_specs(), num_stages=2,
                       partition_method="type:NoSuchLayer")


def test_ragged_body_partitions():
    net = PipelineModule(_specs(n_blocks=3), num_stages=2)
    assert sorted(net.stage_depths.tolist()) == [1, 2]
    assert net.parts[-1] == 3
    # sequential apply still runs every real layer exactly once
    assert net.layers_per_stage == 2


def test_tied_layer_spec_shares_params():
    specs = ([TiedLayerSpec("embed", Emb, 32, 16)] +
             [LayerSpec(DenseBlock, 16) for _ in range(2)] +
             [TiedLayerSpec("embed", Emb, 32, 16)])
    net = PipelineModule(specs, num_stages=2)
    # one shared parameter tree for the tied key
    assert list(net.tied_params.keys()) == ["embed"]
    assert list(net.tied_keys.keys()) == ["embed"]
    # both tied entries reference the same key (no second build/params)
    tied_entries = [e for e in net.layers if e[0] == "tied"]
    assert len(tied_entries) == 2
    assert all(e[1] == "embed" for e in tied_entries)


def test_seed_layers_reproducible():
    net1 = PipelineModule(_specs(), num_stages=2, seed_layers=True,
                          base_seed=7)
    net2 = PipelineModule(_specs(), num_stages=2, seed_layers=True,
                          base_seed=7)
    np.testing.assert_allclose(np.asarray(net1.body_params["w"]),
                               np.asarray(net2.body_params["w"]))
