"""Native C++ SIMD CPU Adam vs the XLA adam_update (reference
tests/unit/test_cpu_adam.py compares against torch.optim.Adam)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.fused_adam import (adam_init, adam_update,
                                               DeepSpeedCPUAdam)

pytest.importorskip("ctypes")


def _tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(37, 19).astype(np.float32)),
        "b": jnp.asarray(rng.randn(64).astype(np.float32)),
        "nested": {"k": jnp.asarray(rng.randn(8, 4, 3).astype(np.float32))},
    }


def _builder_ok():
    from deepspeed_tpu.ops.op_builder.cpu_adam import CPUAdamBuilder
    return CPUAdamBuilder().is_compatible()


@pytest.mark.skipif(not _builder_ok(), reason="no host toolchain")
@pytest.mark.parametrize("adam_w_mode", [True, False])
@pytest.mark.parametrize("weight_decay", [0.0, 0.01])
def test_native_matches_xla(adam_w_mode, weight_decay):
    from deepspeed_tpu.ops.adam.cpu_adam_native import native_adam_update
    params = _tree()
    grads = _tree(seed=1)
    state = adam_init(params)
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              weight_decay=weight_decay)
    p_n, s_n = params, state
    p_x, s_x = params, state
    for _ in range(5):
        p_n, s_n = native_adam_update(grads, s_n, p_n,
                                      adam_w_mode=adam_w_mode, **kw)
        p_x, s_x = adam_update(grads, s_x, p_x, adam_w_mode=adam_w_mode,
                               use_pallas=False, **kw)
    for a, b in zip(jax.tree_util.tree_leaves(p_n),
                    jax.tree_util.tree_leaves(p_x)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(s_n["exp_avg"]),
                    jax.tree_util.tree_leaves(s_x["exp_avg"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.skipif(not _builder_ok(), reason="no host toolchain")
def test_native_under_jit():
    from deepspeed_tpu.ops.adam.cpu_adam_native import native_adam_update
    params = _tree()
    grads = _tree(seed=2)
    state = adam_init(params)

    @jax.jit
    def step(p, s, g):
        return native_adam_update(g, s, p, lr=1e-3, beta1=0.9, beta2=0.999,
                                  eps=1e-8, weight_decay=0.0)

    p1, s1 = step(params, state, grads)
    p2, s2 = adam_update(grads, state, params, lr=1e-3, beta1=0.9,
                         beta2=0.999, eps=1e-8, weight_decay=0.0,
                         use_pallas=False)
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                               atol=1e-6)
    assert int(s1["step"]) == 1


def test_cpu_adam_optimizer_falls_back_cleanly():
    # use_native=None -> try native, silently fall back if unbuildable.
    opt = DeepSpeedCPUAdam(lr=1e-3)
    params = _tree()
    state = opt.init_state(params)
    grads = _tree(seed=3)
    new_p, new_s = opt.update(grads, state, params, lr=1e-3, beta1=0.9,
                              beta2=0.999, eps=1e-8, weight_decay=0.0)
    assert int(new_s["step"]) == 1
    assert np.isfinite(np.asarray(new_p["w"])).all()


@pytest.mark.skipif(not _builder_ok(), reason="no host toolchain")
def test_zero_offload_through_engine():
    """ds_config cpu_offload=true routes the optimizer step through the
    native host kernel; training must still converge."""
    import deepspeed_tpu
    from simple_model import make_simple_model, SimpleDataset, base_config

    model = make_simple_model(16, seed=0)
    config = base_config(8, fp16={"enabled": True},
                         zero_optimization={"stage": 2, "cpu_offload": True})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config_params=config)
    assert isinstance(engine.optimizer, DeepSpeedCPUAdam)
    dataset = SimpleDataset(256, 16, seed=0)
    mb = engine.train_micro_batch_size_per_gpu() * 8
    losses = []
    for s in range(30):
        x = np.stack([dataset[(s * mb + i) % len(dataset)][0]
                      for i in range(mb)])
        y = np.stack([dataset[(s * mb + i) % len(dataset)][1]
                      for i in range(mb)])
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


@pytest.mark.skipif(not _builder_ok(), reason="no host toolchain")
def test_bf16_copyback_kernel():
    """ds_cpu_adam_step_bf16_copy: fused step + bf16 param stream-out,
    NaN-preserving rounding."""
    import ctypes
    from deepspeed_tpu.ops.op_builder.cpu_adam import CPUAdamBuilder
    lib = CPUAdamBuilder().load()
    n = 1024
    rng = np.random.RandomState(0)
    p = rng.randn(n).astype(np.float32)
    p[7] = np.float32(np.nan)
    g = rng.randn(n).astype(np.float32)
    g[7] = 0.0
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    out16 = np.zeros(n, np.uint16)
    lib.ds_cpu_adam_step_bf16_copy(
        p.ctypes.data, g.ctypes.data, m.ctypes.data, v.ctypes.data,
        out16.ctypes.data, n, 1e-3, 0.9, 0.999, 1e-8, 0.0, 0.1, 0.001, 1)
    as_bf16 = out16.view(np.uint16).astype(np.uint32) << 16
    back = as_bf16.view(np.uint32).astype(np.uint32)
    f32 = np.frombuffer(back.astype(np.uint32).tobytes(), dtype=np.float32)
    # NaN stays NaN (not inf)
    assert np.isnan(f32[7])
    # everything else within bf16 rounding of the fp32 params
    mask = np.ones(n, bool); mask[7] = False
    np.testing.assert_allclose(f32[mask], p[mask], rtol=1e-2, atol=1e-2)


def test_pallas_lamb_matches_jnp():
    """Pallas LAMB (interpret mode on the CPU mesh) vs the jnp reference
    (mirrors the fused Adam parity tests; real-TPU parity is covered by the
    same kernel in bench/verify runs)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.lamb.fused_lamb import lamb_init, lamb_update
    from deepspeed_tpu.ops.lamb.pallas_lamb import fused_lamb_shard

    rs = np.random.RandomState(0)
    # "big" exercises a ragged last grid block (rows > BLOCK_ROWS,
    # rows % BLOCK_ROWS != 0) whose reduction must be masked
    params = {"w": jnp.asarray(rs.randn(100, 30), dtype=jnp.float32),
              "b": jnp.asarray(rs.randn(7), dtype=jnp.float32),
              "big": jnp.asarray(rs.randn(1100, 128) * 0.1,
                                 dtype=jnp.float32)}
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rs.randn(*p.shape), dtype=jnp.float32), params)
    state = lamb_init(params)
    ref_p, ref_s = lamb_update(grads, state, params, 1e-2, 0.9, 0.999,
                               1e-8, 0.01, use_pallas=False)
    for k in params:
        p2, m2, v2 = fused_lamb_shard(
            params[k], grads[k], state["exp_avg"][k], state["exp_avg_sq"][k],
            1e-2, 0.9, 0.999, 1e-8, 0.01,
            bc1=1.0 - 0.9, bc2=1.0 - 0.999, interpret=True)
        np.testing.assert_allclose(np.asarray(p2), np.asarray(ref_p[k]),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(m2),
                                   np.asarray(ref_s["exp_avg"][k]),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(v2),
                                   np.asarray(ref_s["exp_avg_sq"][k]),
                                   atol=1e-6)
