"""Topology/mesh math (mirrors reference tests/unit/test_topology.py)."""
import pytest
import jax

from deepspeed_tpu.parallel.topology import (
    ProcessTopology as Topo, PipeDataParallelTopology,
    PipeModelDataParallelTopology, MeshGrid, build_mesh, _prime_factors)


def test_topology_2d():
    topo = Topo(axes=["row", "col"], dims=[2, 2])
    assert topo.world_size() == 4
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3
    assert topo.get_axis_list(axis="row", idx=0) == [0, 1]
    assert topo.get_axis_list(axis="row", idx=1) == [2, 3]
    assert topo.get_axis_list(axis="col", idx=0) == [0, 2]
    assert topo.get_axis_list(axis="col", idx=1) == [1, 3]


def test_topology_dims():
    topo = Topo(axes=["a", "b", "c"], dims=[2, 3, 4])
    assert topo.world_size() == 24
    assert topo.get_dim("a") == 2
    assert topo.get_dim("b") == 3
    assert topo.get_dim("c") == 4


def test_topology_match():
    topo = Topo(axes=["pipe", "data", "model"], dims=[2, 2, 2])
    assert topo.filter_match(pipe=0, data=1) == [2, 3]


def test_topology_rank_repr():
    topo = Topo(axes=["a", "b"], dims=[2, 2])
    assert topo.get_rank_repr(rank=0) == "a_00-b_00"
    assert topo.get_rank_repr(rank=1) == "a_00-b_01"
    assert topo.get_rank_repr(rank=2) == "a_01-b_00"
    assert topo.get_rank_repr(rank=3) == "a_01-b_01"
    assert topo.get_rank_repr(rank=3, inner_sep="+") == "a+01-b+01"

    topo = Topo(axes=["pipe", "data"], dims=[2, 2])
    for r in range(4):
        assert topo.get_rank_repr(rank=r) == ""


def test_topology_3d():
    topo = Topo(axes=["a", "b", "c"], dims=[2, 2, 2])
    assert topo.get_rank(a=0, b=0, c=0) == 0
    assert topo.get_rank(a=0, b=0, c=1) == 1
    assert topo.get_rank(a=0, b=1, c=0) == 2
    assert topo.get_rank(a=1, b=0, c=0) == 4
    assert topo.get_axis_list("a", 0) == [0, 1, 2, 3]
    assert topo.get_coord(rank=5) == topo.ProcessCoord(a=1, b=0, c=1)


def test_topology_comm_list():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.world_size() == 8
    # pipe groups: ranks that differ only in pipe coordinate
    pipe_list = topo.get_axis_comm_lists("pipe")
    for group in pipe_list:
        assert len(group) == 2
        coords = [topo.get_coord(r) for r in group]
        assert coords[0].data == coords[1].data
        assert coords[0].model == coords[1].model
    data_list = topo.get_axis_comm_lists("data")
    assert len(data_list) == 4
    model_list = topo.get_axis_comm_lists("model")
    assert len(model_list) == 4
    # bogus axis
    assert topo.get_axis_comm_lists("bogus") == []


def test_primes():
    assert _prime_factors(12) == [2, 2, 3]
    assert _prime_factors(97) == [97]
    assert _prime_factors(8) == [2, 2, 2]
    with pytest.raises(ValueError):
        _prime_factors(0)


def test_build_mesh_2d():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=4)
    mesh = build_mesh(topo)
    assert mesh.shape["pipe"] == 2
    assert mesh.shape["data"] == 4


def test_build_mesh_default_data_axis():
    mesh = build_mesh()
    assert mesh.shape["data"] == jax.device_count()


def test_grid_pipe_data():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=4)
    grid = MeshGrid(topology=topo, process_rank=0)
    assert grid.get_pipe_parallel_world_size() == 2
    assert grid.get_data_parallel_world_size() == 4
    assert grid.get_model_parallel_world_size() == 1
    assert grid.is_first_stage()


def test_stage_to_global():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    grid = MeshGrid(topology=topo, process_rank=0)
    assert grid.stage_to_global(stage_id=0, data=0) == 0
    assert grid.stage_to_global(stage_id=0, data=1) == 1
    assert grid.stage_to_global(stage_id=1, data=0) == 2
    assert grid.stage_to_global(stage_id=1, data=1) == 3


def test_mesh_grid_3d():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    grid = MeshGrid(topology=topo, process_rank=0)
    assert grid.get_model_parallel_world_size() == 2
    assert grid.mesh.shape["model"] == 2
    assert grid.mesh.shape["pipe"] == 2
    assert grid.mesh.shape["data"] == 2
