"""Sparse embedding-gradient exchange (ops/sparse_grads.py — the
reference's CSR allreduce, engine.py:1285-1341, made TPU-native)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.ops.sparse_grads import sparse_embedding_lookup
from deepspeed_tpu.parallel.topology import build_mesh


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(data=8)


def test_sparse_lookup_grads_match_dense(mesh):
    vocab, d, b, s = 64, 16, 8, 12
    rng = np.random.RandomState(0)
    wte = jnp.asarray(rng.randn(vocab, d), jnp.float32)
    ids = jnp.asarray(rng.randint(0, vocab, size=(b, s)), jnp.int32)

    def loss_sparse(w):
        out = sparse_embedding_lookup(w, ids, mesh=mesh)
        return jnp.sum(out * jnp.cos(out))

    def loss_dense(w):
        out = jnp.take(w, ids, axis=0)
        return jnp.sum(out * jnp.cos(out))

    np.testing.assert_allclose(float(loss_sparse(wte)),
                               float(loss_dense(wte)), rtol=1e-6)
    gs = jax.grad(loss_sparse)(wte)
    gd = jax.grad(loss_dense)(wte)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gd),
                               rtol=1e-5, atol=1e-5)


def test_sparse_lookup_handles_duplicate_ids(mesh):
    """Duplicate token ids across AND within shards must scatter-add."""
    vocab, d = 32, 8
    wte = jnp.asarray(np.random.RandomState(1).randn(vocab, d), jnp.float32)
    ids = jnp.full((8, 4), 7, jnp.int32)     # every position = token 7

    g = jax.grad(lambda w: sparse_embedding_lookup(w, ids, mesh=mesh)
                 .sum())(wte)
    expect = np.zeros((vocab, d), np.float32)
    expect[7] = 32.0                          # 8*4 occurrences
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-6)


def test_sparse_lookup_falls_back_off_mesh():
    """No mesh / trivial axis / indivisible batch -> plain dense lookup."""
    wte = jnp.ones((16, 4))
    ids = jnp.zeros((3, 2), jnp.int32)       # 3 not divisible by 8
    out = sparse_embedding_lookup(wte, ids, mesh=build_mesh(data=8))
    assert out.shape == (3, 2, 4)
    out2 = sparse_embedding_lookup(wte, ids, mesh=None)
    assert out2.shape == (3, 2, 4)


@pytest.mark.slow
def test_gpt2_sparse_embedding_grads_end_to_end(mesh):
    """GPT-2 with sparse_embedding_grads trains identically to the dense
    path through the engine, and the engine records the CSR module name."""
    from deepspeed_tpu.models import gpt2

    def make(sparse):
        cfg = gpt2.config_for("gpt2_small", max_seq_len=32, n_layers=2,
                              n_heads=2, d_model=32, vocab_size=128,
                              use_flash_attention=False, remat=False,
                              sparse_embedding_grads=sparse,
                              embedding_grad_mesh=mesh if sparse else None)
        model = gpt2.make_gpt2_model(config=cfg)
        config = {
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "sparse_gradients": sparse,
            "steps_per_print": 1000,
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                   config_params=config)
        return engine

    e_sparse, e_dense = make(True), make(False)
    assert e_sparse.csr_tensor_module_names == {"wte"}
    assert e_dense.csr_tensor_module_names == set()

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, size=(8, 32)).astype(np.int32)
    for _ in range(3):
        ls = e_sparse(ids, ids)
        e_sparse.backward(ls)
        e_sparse.step()
        ld = e_dense(ids, ids)
        e_dense.backward(ld)
        e_dense.step()
        np.testing.assert_allclose(float(ls), float(ld), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(e_sparse.get_params()["wte"], np.float32),
        np.asarray(e_dense.get_params()["wte"], np.float32),
        rtol=1e-3, atol=1e-3)
