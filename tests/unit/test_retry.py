"""utils/retry.py: exponential backoff, jitter, retry budget, exception
filtering. Deterministic — sleeps and RNG are injected."""
import random

import pytest

from deepspeed_tpu.utils.retry import (NO_RETRY, RetryPolicy, backoff_delays,
                                       retry_call, retryable)


class _Flaky:
    def __init__(self, failures, exc=OSError):
        self.failures = failures
        self.exc = exc
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc("transient {}".format(self.calls))
        return "ok"


def _policy(retries=3):
    return RetryPolicy(retries=retries, backoff_seconds=0.1,
                       max_backoff_seconds=1.0, jitter=0.0)


def test_succeeds_after_transient_failures():
    fn = _Flaky(failures=2)
    sleeps = []
    assert retry_call(fn, policy=_policy(), sleep=sleeps.append) == "ok"
    assert fn.calls == 3
    # exponential: 0.1, then 0.2
    assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]


def test_exhausted_budget_reraises_last_error():
    fn = _Flaky(failures=10)
    with pytest.raises(OSError, match="transient 4"):
        retry_call(fn, policy=_policy(retries=3), sleep=lambda _: None)
    assert fn.calls == 4  # 1 try + 3 retries


def test_zero_retries_tries_exactly_once():
    fn = _Flaky(failures=1)
    with pytest.raises(OSError):
        retry_call(fn, policy=NO_RETRY)
    assert fn.calls == 1


def test_non_matching_exceptions_propagate_immediately():
    fn = _Flaky(failures=5, exc=ValueError)
    with pytest.raises(ValueError):
        retry_call(fn, policy=_policy(), sleep=lambda _: None)
    assert fn.calls == 1


def test_backoff_caps_at_max():
    policy = RetryPolicy(retries=6, backoff_seconds=0.1,
                         max_backoff_seconds=0.5, jitter=0.0)
    delays = backoff_delays(policy)
    assert delays == pytest.approx([0.1, 0.2, 0.4, 0.5, 0.5, 0.5])


def test_jitter_is_bounded_and_deterministic_with_seeded_rng():
    policy = RetryPolicy(retries=4, backoff_seconds=0.1,
                         max_backoff_seconds=1.0, jitter=0.25)
    a = backoff_delays(policy, rng=random.Random(7))
    b = backoff_delays(policy, rng=random.Random(7))
    assert a == b
    bases = [0.1, 0.2, 0.4, 0.8]
    for delay, base in zip(a, bases):
        assert base <= delay <= base * 1.25


def test_on_retry_observes_each_attempt():
    fn = _Flaky(failures=2)
    seen = []
    retry_call(fn, policy=_policy(),
               on_retry=lambda attempt, exc, delay: seen.append(attempt),
               sleep=lambda _: None)
    assert seen == [0, 1]


def test_retryable_decorator_passes_arguments():
    calls = {"n": 0}

    @retryable(policy=_policy())
    def flaky_add(a, b):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient")
        return a + b

    # sleep not injectable through the decorator: keep the schedule tiny
    assert flaky_add(2, 3) == 5
    assert calls["n"] == 2
