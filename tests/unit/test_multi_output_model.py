"""Multi-output model tests (reference tests/unit/test_multi_output_model.py:
models returning (loss, aux...) tuples train correctly)."""
import numpy as np

import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.model import Model


def test_tuple_output_first_element_is_loss():
    def apply_fn(params, x, y):
        pred = x @ params["w"]
        loss = jnp.mean((pred - y) ** 2)
        aux = jnp.mean(jnp.abs(pred))
        return loss, aux

    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(apply_fn, {"w": jnp.zeros((16, 4))}),
        config_params=config)
    rs = np.random.RandomState(0)
    W = rs.randn(16, 4).astype(np.float32)
    x = jnp.asarray(rs.randn(8, 16).astype(np.float32))
    y = x @ jnp.asarray(W)
    losses = []
    for _ in range(30):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < 0.2 * losses[0], losses


def test_weighted_multi_loss():
    """Two losses combined with weights (the reference's multi-output
    pattern)."""
    w1, w2 = 0.7, 0.3

    def apply_fn(params, x, y1, y2):
        h = x @ params["w"]
        loss1 = jnp.mean((h[:, :2] - y1) ** 2)
        loss2 = jnp.mean((h[:, 2:] - y2) ** 2)
        return w1 * loss1 + w2 * loss2

    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(apply_fn, {"w": jnp.zeros((8, 4))}),
        config_params=config)
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(8, 8).astype(np.float32))
    y1 = jnp.asarray(rs.randn(8, 2).astype(np.float32))
    y2 = jnp.asarray(rs.randn(8, 2).astype(np.float32))
    first = last = None
    for _ in range(30):
        loss = engine(x, y1, y2)
        engine.backward(loss)
        engine.step()
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first
