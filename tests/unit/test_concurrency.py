"""Concurrency sanitizer + SPMD divergence tests (ISSUE 15;
docs/concurrency.md): the injected-defect matrix — a constructed AB/BA
deadlock, a guarded-write-without-lock, a signal-handler non-reentrant
acquisition, a two-host divergent plan — each firing exactly once as a
schema-valid finding that raises under ``analysis.strict``; the clean
engine config silent; the DSL008/DSL009 repo self-check green; the
fleet doctor's divergence path proven jax-less by subprocess.

Marker: ``concurrency`` (tier-1 — fast, CPU-only; one tiny engine
build for the clean-config/audit-integration tests)."""
import importlib.util
import json
import os
import subprocess
import sys
import threading

import pytest

from deepspeed_tpu.analysis import astlint
from deepspeed_tpu.analysis.concurrency import divergence, locksan
from deepspeed_tpu.analysis.config import DeepSpeedAnalysisConfig
from deepspeed_tpu.analysis.auditor import AuditFindingsError, dispose
from deepspeed_tpu.analysis.findings import (AnalysisReport,
                                             FINDING_KEYS,
                                             validate_analysis_report)
from deepspeed_tpu.telemetry.fleet import aggregate
from deepspeed_tpu.telemetry.fleet.aggregate import (
    compare_fingerprints, validate_host_manifest, write_host_manifest)

pytestmark = pytest.mark.concurrency

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_bin(name):
    path = os.path.join(_REPO, "bin", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def san():
    """A fresh installed sanitizer, uninstalled at teardown."""
    s = locksan.install(locksan.LockSanitizer())
    try:
        yield s
    finally:
        locksan.uninstall()


def _assert_schema_valid(findings):
    """Every finding serializes into the analysis-report shape."""
    report = AnalysisReport(job="concurrency")
    report.extend(findings)
    payload = report.to_dict()
    assert validate_analysis_report(payload) == [], payload
    for f in findings:
        d = f.to_dict()
        for key in FINDING_KEYS:
            assert isinstance(d.get(key), str) and d[key], (key, d)


def _strict_cfg(tmp_path=None):
    return DeepSpeedAnalysisConfig({"analysis": {"strict": True}})


# ------------------------------------------------- off = structurally absent
def test_off_is_structurally_absent():
    assert locksan.current() is None
    lock = locksan.new_lock("x")
    assert type(lock).__name__ in ("lock", "LockType")
    rl = locksan.new_rlock("x")
    assert not isinstance(rl, locksan.SanLock)

    class Box:
        _GUARDED_BY = {"items": "_lock"}

    b = Box.__new__(Box)
    items = []
    assert locksan.guarded(b, "items", items) is items
    locksan.note_blocking("noop")            # must not raise
    with locksan.signal_scope():
        pass


# -------------------------------------------------- defect 1: AB/BA cycle
def test_abba_deadlock_cycle_fires_exactly_once(san):
    a = locksan.new_lock("A")
    b = locksan.new_lock("B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        t.join()
    findings = san.report()
    cycles = [f for f in findings if f.check == "lock_order_cycle"]
    assert len(cycles) == 1, [f.key for f in findings]
    assert cycles[0].key == "lock_order_cycle:A:B"
    assert cycles[0].severity == "error"
    # first-seen acquisition stacks ride the details, per edge
    assert set(cycles[0].details["edges"]) == {"A->B", "B->A"}
    _assert_schema_valid(findings)
    # raises under analysis.strict through the standard disposition
    report = AnalysisReport(job="concurrency")
    report.extend(findings)
    with pytest.raises(AuditFindingsError):
        dispose(report, _strict_cfg())


def test_same_named_locks_do_not_conflate(san):
    """Two DISTINCT locks sharing a name (a second engine's
    'recorder.ring') must not fold into one order-graph node — nesting
    them consistently is NOT a self-cycle."""
    a1 = locksan.new_lock("recorder.ring")
    a2 = locksan.new_lock("recorder.ring")
    assert a2.name == "recorder.ring#2"     # unique graph node
    with a1:
        with a2:
            pass
    assert [f.key for f in san.report()] == []
    # a GENUINE opposite-order nesting of the pair still flags
    t = threading.Thread(target=lambda: a2.acquire() and
                         (a1.acquire(), a1.release(), a2.release()),
                         daemon=True)
    t.start()
    t.join()
    cycles = [f for f in san.report()
              if f.check == "lock_order_cycle"]
    assert len(cycles) == 1, [f.key for f in san.report()]


def test_guarded_dict_item_reads_are_checked(san):
    """dict-shaped guarded state read via .items()/.keys()/.values()
    without the lock is the changed-size-during-render class."""
    class Table:
        _GUARDED_BY = {"d": "_lock"}

        def __init__(self):
            self._lock = locksan.new_lock("table")
            self.d = locksan.guarded(self, "d", {"a": 1})

    t = Table()
    list(t.d.items())               # unlocked snapshot = race
    with t._lock:
        assert sorted(t.d.keys()) == ["a"]      # locked: silent
    keys = [f.key for f in san.report()]
    assert "guarded_race:Table.d:items" in keys
    assert not any(k.endswith(":keys") for k in keys)


def test_dsl008_mutator_set_pinned_to_dynamic_checker():
    """The AST rule's mutator table is a copy of the dynamic proxy's
    (astlint must stay import-light for the jax-less mount) — pinned
    equal so the static and dynamic twins cannot drift."""
    assert astlint._DSL008_MUTATORS == locksan._MUTATORS
    assert astlint._GUARDED_BY_NAME == locksan.GUARDED_BY_ATTR


def test_publish_fingerprint_preserves_wall_start(tmp_path):
    fp = _fp(["psum@data"])
    p1 = write_host_manifest(str(tmp_path), job_name="h",
                             wall_start=123.5)
    p2 = write_host_manifest(str(tmp_path), job_name="h",
                             fingerprint=fp, wall_start=123.5)
    assert p1 == p2
    with open(p2) as fh:
        manifest = json.load(fh)
    assert manifest["wall_start"] == 123.5
    assert manifest["program_fingerprint"] == fp


def test_consistent_order_and_reentrancy_are_silent(san):
    a = locksan.new_lock("A")
    b = locksan.new_lock("B")
    r = locksan.new_rlock("R")
    for _ in range(3):
        with a:
            with b:
                pass
    with r:
        with r:                 # reentrant re-acquisition: no self-edge
            pass
    assert san.report() == []
    # 3 x (a, b) + the two r acquisitions (the nested one reentrant)
    assert san.snapshot()["acquisitions"] == 8


# ----------------------------------------- defect 2: guarded-state race
def test_guarded_write_without_lock_fires_exactly_once(san):
    class Ring:
        _GUARDED_BY = {"items": "_lock"}

        def __init__(self):
            self._lock = locksan.new_lock("ring")
            self.items = locksan.guarded(self, "items", [])

    ring = Ring()
    ring.items.append(1)            # race
    ring.items.append(2)            # same site: still ONE finding
    with ring._lock:
        ring.items.append(3)        # guarded: silent
        assert list(ring.items) == [1, 2, 3]
    findings = san.report()
    races = [f for f in findings if f.check == "guarded_race"]
    assert [f.key for f in races] == ["guarded_race:Ring.items:append"]
    assert races[0].details["count"] == 2
    _assert_schema_valid(findings)


def test_guarded_iteration_without_lock_flags(san):
    class Ring:
        _GUARDED_BY = {"items": "_lock"}

        def __init__(self):
            self._lock = locksan.new_lock("ring2")
            self.items = locksan.guarded(self, "items", [4, 5])

    ring = Ring()
    assert list(ring.items) == [4, 5]       # unlocked snapshot = race
    keys = [f.key for f in san.report()]
    assert "guarded_race:Ring.items:__iter__" in keys
    # undeclared attributes pass through untouched
    assert locksan.guarded(ring, "other", [1]) == [1]


# ------------------------------- defect 3: signal-handler acquisition
def test_signal_handler_nonreentrant_acquisition_fires(san):
    plain = locksan.new_lock("handler.plain")
    rlock = locksan.new_rlock("handler.rlock")
    with locksan.signal_scope():
        with rlock:                 # reentrant: allowed in a handler
            pass
        with plain:                 # non-reentrant: the deadlock class
            pass
    findings = san.report()
    sigs = [f for f in findings if f.check == "signal_unsafe"]
    assert [f.key for f in sigs] == ["signal_unsafe:handler.plain"]
    assert sigs[0].severity == "error"
    _assert_schema_valid(findings)


# ------------------------------------------------ held-blocking events
def test_held_blocking_fires_and_is_silent_unheld(san):
    lock = locksan.new_lock("io.lock")
    locksan.note_blocking("free.call")     # nothing held: silent
    with lock:
        locksan.note_blocking("bundle.write")
    findings = san.report()
    held = [f for f in findings if f.check == "held_blocking"]
    assert [f.key for f in held] == \
        ["held_blocking:io.lock:bundle.write"]
    assert held[0].details["locks"] == ["io.lock"]


# ------------------------------- defect 4: two-host divergent program
def _fp(tokens):
    return divergence.canonical_fingerprint({"step": tokens})


def test_two_host_divergent_plan_fires_exactly_once():
    fp_ref = _fp(["psum@data", "all_gather@model"])
    fp_div = _fp(["psum@data", "ppermute@model"])
    cmp = compare_fingerprints({"h0": fp_ref, "h1": fp_div})
    assert cmp["mismatch"] and cmp["published"] == 2
    findings = divergence.divergence_findings(cmp)
    assert len(findings) == 1
    f = findings[0]
    assert f.check == "fleet_divergence" and f.severity == "error"
    # names the first differing family/token against the reference
    assert f.details["family"] == "step" and f.details["index"] == 1
    assert "all_gather@model" in f.message or \
        f.details["reference_token"] == "all_gather@model"
    _assert_schema_valid(findings)
    with pytest.raises(AuditFindingsError):
        divergence.audit_fleet(cmp, _strict_cfg())


def test_matching_fingerprints_are_silent():
    fp = _fp(["psum@data"])
    cmp = compare_fingerprints({"h0": fp, "h1": fp, "h2": fp})
    assert not cmp["mismatch"] and cmp["divergent_hosts"] == []
    assert divergence.divergence_findings(cmp) == []
    report = divergence.audit_fleet({"divergence": cmp}, _strict_cfg())
    assert report.findings == []


def test_majority_reference_names_the_single_divergent_host():
    fp_ref = _fp(["psum@data"])
    fp_div = _fp(["pmax@data"])
    fps = {"host{}".format(i): fp_ref for i in range(7)}
    fps["host3"] = fp_div
    cmp = compare_fingerprints(fps)
    assert cmp["divergent_hosts"] == ["host3"]
    assert cmp["reference"] != "host3"
    # unpublished hosts are a coverage gap, never a flag
    fps["host9"] = None
    cmp = compare_fingerprints(fps)
    assert cmp["unpublished_hosts"] == ["host9"]
    assert cmp["divergent_hosts"] == ["host3"]


def test_fingerprint_canonical_and_validated():
    fp1 = divergence.canonical_fingerprint(
        {"b": ["x"], "a": ["y", "z"]})
    fp2 = divergence.canonical_fingerprint(
        {"a": ["y", "z"], "b": ["x"]})
    assert fp1 == fp2                       # order-insensitive canon
    assert divergence.validate_fingerprint(fp1) == []
    assert divergence.validate_fingerprint({"digest": "x"}) != []
    assert divergence.FINGERPRINT_KEYS == aggregate.FINGERPRINT_KEYS


# ------------------------------------------------- manifest + fleet doctor
def _host_with_fp(root, name, fp):
    d = os.path.join(str(root), name)
    os.makedirs(d, exist_ok=True)
    write_host_manifest(d, job_name=name, fingerprint=fp)
    with open(os.path.join(d, aggregate.JSONL_NAME), "w") as fh:
        rec = {"kind": "train_step", "step": 0, "wall": 1000.0}
        fh.write(json.dumps(rec) + "\n")
    return d


def test_manifest_carries_and_validates_fingerprint(tmp_path):
    fp = _fp(["psum@data"])
    path = write_host_manifest(str(tmp_path), job_name="h",
                               fingerprint=fp)
    with open(path) as fh:
        manifest = json.load(fh)
    assert validate_host_manifest(manifest) == []
    assert manifest["program_fingerprint"] == fp
    # a malformed fingerprint is flagged
    manifest["program_fingerprint"] = {"digest": "x"}
    assert validate_host_manifest(manifest) != []
    # manifests without one stay valid (absence = coverage gap)
    del manifest["program_fingerprint"]
    assert validate_host_manifest(manifest) == []


def test_merge_run_reports_divergence_section(tmp_path):
    fp_ref = _fp(["psum@data"])
    _host_with_fp(tmp_path, "h0", fp_ref)
    _host_with_fp(tmp_path, "h1", fp_ref)
    _host_with_fp(tmp_path, "h2", _fp(["pmax@data"]))
    report = aggregate.merge_run(str(tmp_path))
    div = report["divergence"]
    assert div["mismatch"] and div["divergent_hosts"] == ["h2"]
    assert div["published"] == 3
    # the full merged report accepts findings conversion
    findings = divergence.divergence_findings(div)
    assert [f.key for f in findings] == ["fleet_divergence:h2"]
    # audit_fleet accepts the full report shape too
    with pytest.raises(AuditFindingsError):
        divergence.audit_fleet(report, _strict_cfg())


def test_fleet_report_keys_pinned_to_checker():
    checker = _load_bin("check_bench_schema")
    assert tuple(aggregate.FLEET_REPORT_KEYS) == \
        tuple(checker.FLEET_REPORT_KEYS)
    assert tuple(aggregate.HOST_MANIFEST_KEYS) == \
        tuple(checker.HOST_MANIFEST_KEYS)
    assert tuple(aggregate.FINGERPRINT_KEYS) == \
        tuple(checker.FINGERPRINT_KEYS)


def test_checker_validates_fleet_report_and_manifest(tmp_path):
    checker = _load_bin("check_bench_schema")
    fp = _fp(["psum@data"])
    _host_with_fp(tmp_path, "h0", fp)
    _host_with_fp(tmp_path, "h1", fp)
    report = aggregate.merge_run(str(tmp_path))
    rpath = os.path.join(str(tmp_path), "fleet_report.json")
    with open(rpath, "w") as fh:
        json.dump(report, fh)
    assert checker.check_file(rpath) == []
    mpath = os.path.join(str(tmp_path), "h0", aggregate.MANIFEST_NAME)
    assert checker.check_file(mpath) == []
    # a report missing its divergence section fails
    del report["divergence"]
    with open(rpath, "w") as fh:
        json.dump(report, fh)
    assert checker.check_file(rpath) != []


def test_ds_fleet_strict_exits_2_on_divergence_without_jax(tmp_path):
    """The whole divergence path — manifest read, comparison, report,
    strict exit — must run on a jax-less box (the stdlib contract)."""
    fp_ref = _fp(["psum@data"])
    _host_with_fp(tmp_path, "h0", fp_ref)
    _host_with_fp(tmp_path, "h1", _fp(["pmax@data"]))
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        "raise ImportError('no jax on this box (test_concurrency)')\n")
    env = dict(os.environ, PYTHONPATH=str(poison))
    cmd = [sys.executable, os.path.join(_REPO, "bin", "ds_fleet.py"),
           str(tmp_path), "--strict"]
    out = subprocess.run(cmd, capture_output=True, text=True, env=env)
    assert out.returncode == 2, (out.stdout, out.stderr)
    assert "PROGRAM DIVERGENCE" in out.stdout
    assert "h1" in out.stdout
    # agreeing fleet: strict passes
    _host_with_fp(tmp_path, "h1", fp_ref)
    out = subprocess.run(cmd, capture_output=True, text=True, env=env)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "all agree" in out.stdout


# -------------------------------------------------- collective_in_branch
def test_collective_in_branch_fires_and_loops_exempt():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.analysis.ir import walk
    from deepspeed_tpu.parallel.topology import shard_map_compat

    devs = jax.devices()[:2]
    mesh = Mesh(devs, ("data",))

    def branchy(flag, x):
        def collective(v):
            return jax.lax.psum(v, "data")

        def local(v):
            return v * 2.0

        return jax.lax.cond(flag, collective, local, x)

    fn = shard_map_compat(branchy, mesh=mesh,
                          in_specs=(P(), P("data")),
                          out_specs=P("data"), axis_names={"data"})
    closed = jax.make_jaxpr(fn)(
        jnp.bool_(True), jnp.zeros((2, 4), jnp.float32))
    findings = divergence.control_flow_findings("demo", walk(closed))
    assert [f.check for f in findings] == ["collective_in_branch"]
    assert findings[0].details["prim"] == "psum"

    def loopy(x):
        def body(_, v):
            return jax.lax.psum(v, "data")
        return jax.lax.fori_loop(0, 3, body, x)

    fn2 = shard_map_compat(loopy, mesh=mesh, in_specs=(P("data"),),
                           out_specs=P("data"), axis_names={"data"})
    closed2 = jax.make_jaxpr(fn2)(jnp.zeros((2, 4), jnp.float32))
    assert divergence.control_flow_findings("demo2", walk(closed2)) \
        == []


# ----------------------------------------------------- config section
def test_analysis_concurrency_config_matrix():
    cfg = DeepSpeedAnalysisConfig({})
    assert cfg.concurrency_enabled is False
    assert cfg.concurrency_fingerprint is True
    cfg = DeepSpeedAnalysisConfig({"analysis": {"concurrency": True}})
    assert cfg.concurrency_enabled is True
    cfg = DeepSpeedAnalysisConfig(
        {"analysis": {"concurrency": {"stack_depth": 4,
                                      "fingerprint": False}}})
    assert cfg.concurrency_enabled is True      # presence = opt-in
    assert cfg.concurrency_stack_depth == 4
    assert cfg.concurrency_fingerprint is False
    cfg = DeepSpeedAnalysisConfig(
        {"analysis": {"concurrency": {"enabled": False}}})
    assert cfg.concurrency_enabled is False
    with pytest.raises(ValueError):
        DeepSpeedAnalysisConfig(
            {"analysis": {"concurrency": {"stack_depth": 0}}})
    with pytest.raises(ValueError):
        DeepSpeedAnalysisConfig({"analysis": {"concurrency": 3}})
    # unknown sub-keys raise under strict (the no-silent-no-ops policy)
    with pytest.raises(ValueError):
        DeepSpeedAnalysisConfig(
            {"analysis": {"strict": True,
                          "concurrency": {"enalbed": True}}})


# ------------------------------------------------------- DSL008/DSL009
_DSL_DEFECT = '''
import threading

class Ring:
    _GUARDED_BY = {"items": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self.items = []          # __init__ exempt

    def bad(self, x):
        self.items.append(x)

    def bad_sub(self, k, v):
        self.items[k] = v

    def good(self, x):
        with self._lock:
            self.items.append(x)

    def spawn_bad(self):
        threading.Thread(target=self.good).start()

    def spawn_good(self):
        threading.Thread(target=self.good, daemon=True).start()
'''


def test_dsl008_dsl009_fire_on_defects(tmp_path):
    path = tmp_path / "defect.py"
    path.write_text(_DSL_DEFECT)
    violations = astlint.lint_file(str(path), "defect.py")
    by_rule = {}
    for rule, qual, lineno, msg in violations:
        by_rule.setdefault(rule, []).append(qual)
    assert sorted(by_rule.get("DSL008", [])) == \
        ["Ring.bad", "Ring.bad_sub"]
    assert by_rule.get("DSL009") == ["Ring.spawn_bad"]
    assert set(by_rule) == {"DSL008", "DSL009"}


def test_dsl008_inert_without_declaration(tmp_path):
    path = tmp_path / "nodecl.py"
    path.write_text(
        "class Plain:\n"
        "    def __init__(self):\n"
        "        self.items = []\n"
        "    def mutate(self, x):\n"
        "        self.items.append(x)\n")
    assert astlint.lint_file(str(path), "nodecl.py") == []


def test_repo_self_check_green_for_new_rules():
    """DSL008/DSL009 over deepspeed_tpu/ vs the committed baseline:
    zero NEW occurrences (the declarations added with the sanitizer
    are all lock-disciplined, and every thread declares daemon=)."""
    findings = astlint.lint_paths(
        [os.path.join(_REPO, "deepspeed_tpu")], base=_REPO)
    baseline = astlint.load_baseline(
        os.path.join(_REPO, "bin", "ds_lint_baseline.json"))
    new, _stale = astlint.diff_baseline(findings, baseline)
    offenders = [f.key for f in new
                 if f.rule in ("DSL008", "DSL009")]
    assert offenders == [], offenders


# ----------------------------------------- clean engine + audit seam
@pytest.fixture(scope="module")
def clean_engine():
    import numpy as np

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import gpt2
    locksan.uninstall()         # a fresh process-global sanitizer
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=32, n_layers=1,
                          n_heads=2, d_model=32,
                          use_flash_attention=False, remat=False,
                          loss_chunk=0)
    engine, _, _, _ = deepspeed.initialize(
        model=gpt2.make_gpt2_model(config=cfg), config_params={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9,
            "telemetry": {"enabled": True,
                          "output_path": "/tmp/ds_test_concurrency",
                          "metrics": {"enabled": True, "port": 0},
                          "flight_recorder": {},
                          "watchdog": {"nan_streak": True}},
            "analysis": {"concurrency": {"enabled": True}},
        })
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, size=(engine.train_batch_size(),
                                    32)).astype(np.int32)
    try:
        yield engine, ids
    finally:
        if engine.telemetry is not None:
            engine.telemetry.close()
        locksan.uninstall()


def test_clean_engine_config_is_silent(clean_engine):
    engine, ids = clean_engine
    san_ = locksan.current()
    assert san_ is not None, "engine init must install the sanitizer"
    for _ in range(2):
        loss = engine(ids, ids.copy())
        engine.backward(loss)
        engine.step()
    assert san_.snapshot()["acquisitions"] > 0, \
        "instrumented locks never exercised — the shim is not wired"
    assert [f.key for f in san_.report()] == []


def test_audit_publishes_fingerprint_and_stays_clean(clean_engine):
    engine, ids = clean_engine
    report = engine.audit(batch=(ids, ids.copy()))
    assert report.findings == []
    fp = report.fingerprint
    assert fp is not None and divergence.validate_fingerprint(fp) == []
    assert any(t.startswith("#ops:")
               for toks in fp["families"].values() for t in toks)
    payload = report.to_dict()
    assert payload["fingerprint"]["digest"] == fp["digest"]
    assert validate_analysis_report(payload) == []
    # published into the live host manifest, still schema-valid
    man_path = os.path.join(engine.telemetry.output_dir,
                            aggregate.MANIFEST_NAME)
    with open(man_path) as fh:
        manifest = json.load(fh)
    assert validate_host_manifest(manifest) == []
    assert manifest["program_fingerprint"]["digest"] == fp["digest"]
    # deterministic: a second audit derives the identical digest
    report2 = engine.audit(batch=(ids, ids.copy()))
    assert report2.fingerprint["digest"] == fp["digest"]


def test_instrumented_collector_scrape_and_recorder_dump(clean_engine):
    """The wrapped fleet locks and guarded rings keep working: a
    scrape renders through SanLocks, and a recorder dump snapshots the
    proxied rings without findings."""
    engine, _ = clean_engine
    tel = engine.telemetry
    assert isinstance(tel.metrics.registry._lock, locksan.SanLock)
    assert isinstance(tel.recorder._lock, locksan.SanLock)
    assert tel.recorder._lock.reentrant
    scrape = tel.metrics_scrape()
    assert scrape["series"] >= 1 and "# TYPE " in scrape["scrape"]
    path = tel.recorder.dump("concurrency-test")
    assert path is not None and os.path.exists(path)
    assert [f.key for f in locksan.current().report()] == []
