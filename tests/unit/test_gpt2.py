"""GPT-2 model family: forward, training, TP sharding specs."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import gpt2

TINY = dict(vocab_size=256, max_seq_len=64, n_layers=2, n_heads=2,
            d_model=64, use_flash_attention=False, remat=False)


def tiny_model(seed=0, **over):
    cfg = {**TINY, **over}
    return gpt2.make_gpt2_model(config=gpt2.GPT2Config(**cfg), seed=seed)


def make_batch(b, s, vocab, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, size=(b, s)).astype(np.int32)
    return ids, ids.copy()


def test_forward_loss_near_uniform():
    model = tiny_model()
    ids, labels = make_batch(4, 64, 256)
    loss = model.apply_fn(model.params, ids, labels, train=False)
    # random init -> loss ~ log(vocab)
    assert abs(float(loss) - np.log(256)) < 1.0


def test_gpt2_trains_with_engine():
    model = tiny_model()
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed.initialize(model=model, config_params=cfg)
    # fixed batch -> loss must drop fast (memorization)
    ids, labels = make_batch(16, 64, 256)
    losses = []
    for _ in range(10):
        loss = engine(ids, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, losses


def test_partition_specs():
    fn = gpt2.partition_spec_fn
    assert fn("wte", (256, 64)) == P("model", None)
    assert fn("blocks/0/attn/qkv_kernel", (64, 192)) == P(None, "model")
    assert fn("blocks/0/attn/proj_kernel", (64, 64)) == P("model", None)
    assert fn("blocks/0/mlp/fc_kernel", (64, 256)) == P(None, "model")
    assert fn("blocks/0/mlp/proj_kernel", (256, 64)) == P("model", None)
    assert fn("blocks/0/ln1/scale", (64,)) is None
    assert fn("wpe", (64, 64)) is None


@pytest.mark.slow
def test_tp_mesh_matches_dp_only():
    """2-way TP x 4-way DP must produce the same loss trajectory as 8-way DP."""
    from deepspeed_tpu.parallel.topology import (PipeModelDataParallelTopology,
                                                 MeshGrid)
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
    }
    ids, labels = make_batch(8, 64, 256)

    e_dp, _, _, _ = deepspeed.initialize(model=tiny_model(seed=1),
                                         config_params=dict(cfg))
    topo = PipeModelDataParallelTopology(num_pp=1, num_mp=2, num_dp=4)
    grid = MeshGrid(topology=topo, process_rank=0)
    cfg_tp = dict(cfg)
    cfg_tp["train_micro_batch_size_per_gpu"] = 4  # dp=4 now: 4*... batch 16?
    e_tp, _, _, _ = deepspeed.initialize(model=tiny_model(seed=1),
                                         config_params=cfg_tp, mpu=grid)
    assert e_tp.dp_world_size == 4
    assert e_tp.mp_world_size == 2

    l_dp, l_tp = [], []
    for _ in range(3):
        loss = e_dp(ids, labels); e_dp.backward(loss); e_dp.step()
        l_dp.append(float(loss))
        loss = e_tp(ids, labels); e_tp.backward(loss); e_tp.step()
        l_tp.append(float(loss))
    np.testing.assert_allclose(l_dp, l_tp, rtol=2e-2, atol=2e-2)

    # TP params actually sharded over the model axis
    qkv = e_tp.state["params"]["blocks"][0]["attn"]["qkv_kernel"]
    assert "model" in str(qkv.sharding.spec)


def test_num_params_formula():
    cfg = gpt2.config_for("gpt2_small")
    n = gpt2.num_params(cfg)
    assert 120e6 < n < 170e6  # 125M class (padded vocab)


def test_chunked_lm_loss_matches_dense():
    """loss_chunk CE == dense-logits CE in value and gradient."""
    kw = dict(vocab_size=512, max_seq_len=256, n_layers=2, n_heads=4,
              d_model=128, use_flash_attention=False, remat=False)
    cfg_c = gpt2.GPT2Config(loss_chunk=64, **kw)
    cfg_d = gpt2.GPT2Config(loss_chunk=0, **kw)
    params = gpt2.init_params(cfg_c, seed=0)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 512, size=(2, 256)))
    labels = ids.at[:, 5].set(-100)  # exercise the -100 mask
    l_c = gpt2.lm_loss(params, ids, labels, cfg_c)
    l_d = gpt2.lm_loss(params, ids, labels, cfg_d)
    np.testing.assert_allclose(float(l_c), float(l_d), rtol=1e-5)
    g_c = jax.grad(lambda p: gpt2.lm_loss(p, ids, labels, cfg_c))(params)
    g_d = jax.grad(lambda p: gpt2.lm_loss(p, ids, labels, cfg_d))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_c),
                    jax.tree_util.tree_leaves(g_d)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_scan_blocks_matches_unrolled():
    """scan_blocks encoder == python-loop encoder in value and grads."""
    kw = dict(vocab_size=256, max_seq_len=128, n_layers=3, n_heads=4,
              d_model=64, use_flash_attention=False, remat=True,
              loss_chunk=0)
    cfg_loop = gpt2.GPT2Config(scan_blocks=False, **kw)
    cfg_scan = gpt2.GPT2Config(scan_blocks=True, **kw)
    p_loop = gpt2.init_params(cfg_loop, seed=3)
    p_scan = gpt2.init_params(cfg_scan, seed=3)
    # same numbers, different layout
    np.testing.assert_allclose(
        np.asarray(p_scan["blocks"]["attn"]["qkv_kernel"][1]),
        np.asarray(p_loop["blocks"][1]["attn"]["qkv_kernel"]))
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 256, size=(2, 128)))
    l1 = gpt2.lm_loss(p_loop, ids, ids, cfg_loop)
    l2 = gpt2.lm_loss(p_scan, ids, ids, cfg_scan)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    g1 = jax.grad(lambda p: gpt2.lm_loss(p, ids, ids, cfg_loop))(p_loop)
    g2 = jax.grad(lambda p: gpt2.lm_loss(p, ids, ids, cfg_scan))(p_scan)
    np.testing.assert_allclose(
        np.asarray(g2["blocks"]["mlp"]["fc_kernel"][2]),
        np.asarray(g1["blocks"][2]["mlp"]["fc_kernel"]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(g2["wte"]), np.asarray(g1["wte"]),
                               atol=1e-5)


def test_scan_blocks_tp_specs_place():
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.zero.partition import ZeroShardingPlan
    mesh = build_mesh(data=2, model=4)
    cfg = gpt2.GPT2Config(vocab_size=256, max_seq_len=64, n_layers=2,
                          n_heads=4, d_model=64, scan_blocks=True,
                          use_flash_attention=False, remat=False)
    params = gpt2.init_params(cfg, seed=0)
    plan = ZeroShardingPlan(mesh, stage=0,
                            model_spec_fn=gpt2.partition_spec_fn)
    placed = jax.tree_util.tree_map(
        jax.device_put, params, plan.tree_shardings(params, "param"))
    qkv = placed["blocks"]["attn"]["qkv_kernel"]
    assert qkv.sharding.spec == P(None, None, "model")


@pytest.mark.slow
def test_sparse_attention_through_engine():
    """The ds_config "sparse_attention" dict drives the model's attention
    (reference BingBertSquad flow: engine.sparse_attention_config() ->
    model): GPT-2 with a sliding-window layout trains through
    initialize(), loss drops, and the config round-trips through the
    engine accessor."""
    import deepspeed_tpu
    sa = {"mode": "sliding_window", "block": 64,
          "num_sliding_window_blocks": 2}
    cfg = gpt2.GPT2Config(vocab_size=256, n_layers=2, n_heads=4,
                          d_model=128, max_seq_len=256,
                          sparse_attention=sa, remat=False)
    ds = {"train_micro_batch_size_per_gpu": 1,
          "bf16": {"enabled": True},
          "zero_optimization": {"stage": 2},
          "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
          "sparse_attention": sa}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.make_gpt2_model(config=cfg), config_params=ds)
    assert engine.sparse_attention_config() == sa
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, 256, (8, 256)))
    y = jnp.roll(x, -1, axis=1)
    losses = []
    for _ in range(20):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_sparse_attention_rejects_sequence_parallel():
    sa = {"mode": "sliding_window", "block": 64,
          "num_sliding_window_blocks": 2}
    cfg = gpt2.GPT2Config(vocab_size=256, n_layers=1, n_heads=4,
                          d_model=128, max_seq_len=128,
                          sparse_attention=sa, sequence_parallel="ring",
                          remat=False)
    params = gpt2.init_params(cfg, seed=0)
    x = jnp.zeros((2, 128), jnp.int32)
    import pytest
    with pytest.raises(ValueError, match="incompatible"):
        gpt2.lm_loss(params, x, x, cfg, rng=None, train=False)
