"""Compressed optimizer comm: error-compensated 1-bit Adam + in-collective
quantized collectives (ISSUE 12).

Covers the compressed-comm tier end to end: the in-collective /
hierarchical quantized all-reduce vs the one-shot collective across
world sizes, OneBitAdam's warmup == exact Adam, the warmup->compressed
transition + checkpoint save/resume bit-stability of the error-feedback
state, overflow reset, convergence on a toy quadratic vs uncompressed
Adam, wire-formula pins, loud rejections, and the shard-lint walk of the
quantized shard_map bodies.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel.topology import build_mesh, factor_data_axis
from deepspeed_tpu.runtime.comm.quantize import (QuantizedCollectives,
                                                 qc_padded_size)
from deepspeed_tpu.runtime.comm.wire import (onebit_exchange_bytes,
                                             quantized_allreduce_bytes)
from deepspeed_tpu.runtime.model import Model

pytestmark = pytest.mark.comm

LR = 1e-2


def _quadratic_model(out_dim=4):
    return Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                 {"w": jnp.zeros((16, out_dim))})


def _quadratic_data(n=32, out_dim=4):
    rs = np.random.RandomState(0)
    W_true = rs.randn(16, out_dim).astype(np.float32)
    x = jnp.asarray(rs.randn(n, 16).astype(np.float32))
    return x, x @ jnp.asarray(W_true)


def _engine(opt, zero=None, comm=None, batch=32, out_dim=4, **extra):
    config = {"train_batch_size": batch, "steps_per_print": 10 ** 9,
              "bf16": {"enabled": True}, "optimizer": opt}
    if zero is not None:
        config["zero_optimization"] = zero
    if comm is not None:
        config["comm"] = comm
    config.update(extra)
    engine, _, _, _ = deepspeed.initialize(
        model=_quadratic_model(out_dim), config_params=config)
    return engine


def _steps(engine, x, y, n):
    losses = []
    for _ in range(n):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


# ---------------------------------------------- in-collective numerics
@pytest.mark.parametrize("world", [1, 2, 4, 8])
def test_quantized_allreduce_matches_oneshot(world):
    """The in-collective ring (per-hop dequantize-accumulate-requantize)
    == the one-shot sum within the codec's per-hop half-scale bound, and
    every rank lands on bitwise the SAME result (the replica-invariance
    the engine's out_specs rely on)."""
    mesh = build_mesh(data=world)
    qc = QuantizedCollectives(mesh, block_size=16)
    n = qc_padded_size(64, world, 16)
    rs = np.random.RandomState(world)
    vals = jnp.asarray(
        rs.randint(-1, 2, size=(world, n)).astype(np.float32))
    out = qc.all_reduce(vals)
    true = np.asarray(vals).sum(axis=0)
    # per-lane bound: each of the <= world-1 requantized hops rounds to
    # a grid of absmax/127 — half a grid point of error per hop, absmax
    # <= world on these lanes
    atol = max(world - 1, 1) * world / 127.0 + 1e-6
    np.testing.assert_allclose(np.asarray(out[0]), true, atol=atol)
    # every rank agrees bitwise
    np.testing.assert_array_equal(np.asarray(out[0]),
                                  np.asarray(out[-1]))


@pytest.mark.parametrize("world", [2, 4, 8])
def test_quantized_allreduce_random_error_bounded(world):
    mesh = build_mesh(data=world)
    qc = QuantizedCollectives(mesh, block_size=64)
    n = qc_padded_size(1000, world, 64)
    rs = np.random.RandomState(world)
    vals = jnp.asarray(rs.randn(world, n).astype(np.float32))
    out = qc.all_reduce(vals)
    true = np.asarray(vals).sum(axis=0)
    rel = np.abs(np.asarray(out[0]) - true).mean() / np.abs(true).mean()
    assert rel < 0.02, rel


@pytest.mark.parametrize("shard", [2, 4])
def test_hierarchical_matches_oneshot(shard):
    """Two-level (hpZ-factored) decomposition == the one-shot collective
    within codec bounds, across the factored (replica, shard) sub-axes
    the engine's hpZ/qc meshes use — and bitwise-identical on every
    rank."""
    mesh = factor_data_axis(build_mesh(data=8), shard)
    qc = QuantizedCollectives(mesh, block_size=16)
    assert qc.hierarchical and qc.world_size == 8
    n = qc_padded_size(64, 8, 16)
    rs = np.random.RandomState(shard)
    ints = jnp.asarray(rs.randint(-1, 2, size=(8, n)).astype(np.float32))
    out = qc.all_reduce(ints)
    np.testing.assert_allclose(np.asarray(out[0]),
                               np.asarray(ints).sum(axis=0),
                               atol=8 * 8 / 127.0)
    np.testing.assert_array_equal(np.asarray(out[0]),
                                  np.asarray(out[-1]))
    rnd = jnp.asarray(rs.randn(8, n).astype(np.float32))
    outr = qc.all_reduce(rnd)
    true = np.asarray(rnd).sum(axis=0)
    rel = np.abs(np.asarray(outr[0]) - true).mean() / np.abs(true).mean()
    assert rel < 0.05, rel


# ------------------------------------------------------ wire formulas
def test_wire_formulas_hand_computed():
    # flat: padded 2048, world 8, block 256 -> chunk 256, 1 block/chunk
    # RS: 7 hops * (256 + 4) ; AG: 7*256 + 7*4
    assert quantized_allreduce_bytes(2000, 8, 256) == \
        7 * (256 + 4) + 7 * 256 + 7 * 4
    # hierarchical (shard 4, replica 2) on the same padded buffer:
    # level s: payload 2048 g 4 -> chunk 512 (2 blocks)
    ls = 3 * (512 + 8) + 3 * 512 + 3 * 8
    # level r: payload 512 g 2 -> chunk 256 (1 block)
    lr = 1 * (256 + 4) + 1 * 256 + 1 * 4
    assert quantized_allreduce_bytes(2000, 8, 256, levels=(4, 2)) == \
        ls + lr
    # min_component drops the per-hop 4-byte scale ppermutes but keeps
    # the 28-byte scales all-gather (one instruction >= the floor)
    assert quantized_allreduce_bytes(2000, 8, 256, min_component=16) == \
        7 * 256 + 7 * 256 + 7 * 4
    # onebit: padded 2048 -> 256 packed bytes; a2a + AG at (w-1)/w,
    # two scalar-scale gathers of w*4 bytes
    ring = 7.0 / 8.0
    assert onebit_exchange_bytes(2000, 8) == \
        2 * int(round(256 * ring)) + 2 * int(round(32 * ring))
    # fp32-equivalent prices the same exchange at 32 bits/lane
    assert onebit_exchange_bytes(2000, 8, itemsize_bits=32) == \
        2 * int(round(2048 * 4 * ring)) + 2 * int(round(32 * ring))


# ------------------------------------------------------ engine: warmup
def test_warmup_matches_exact_adam():
    """Below freeze_step OneBitAdam IS exact Adam (L2 mode): the local-
    grad shard_map micro + stacked-mean averaging must track the GSPMD
    Adam engine to reduction-order noise."""
    x, y = _quadratic_data()
    ob = _engine({"type": "OneBitAdam",
                  "params": {"lr": LR, "freeze_step": 10 ** 6}})
    ad = _engine({"type": "Adam",
                  "params": {"lr": LR, "adam_w_mode": False}})
    lo = _steps(ob, x, y, 8)
    la = _steps(ad, x, y, 8)
    np.testing.assert_allclose(lo, la, rtol=2e-5)


def test_convergence_vs_uncompressed_adam_on_quadratic():
    """Error feedback keeps the compressed regime converging on the toy
    quadratic: noisy (1-bit at 64 params is violent) but descending,
    and within shouting distance of exact Adam's trajectory."""
    x, y = _quadratic_data()
    ob = _engine({"type": "OneBitAdam",
                  "params": {"lr": LR, "freeze_step": 10}})
    ad = _engine({"type": "Adam",
                  "params": {"lr": LR, "adam_w_mode": False}})
    lo = _steps(ob, x, y, 60)
    la = _steps(ad, x, y, 60)
    assert min(lo[-10:]) < 0.7 * lo[0], lo
    assert min(lo[-10:]) < 4.0 * la[-1] + 1.0, (min(lo[-10:]), la[-1])
    # error-feedback state is live once frozen
    werr = ob.state["opt"]["worker_error"]["_flat"]
    assert werr.shape[0] == ob.dp_world_size
    assert float(jnp.abs(werr).sum()) > 0.0


# ------------------------------- transition + checkpoint bit-stability
def test_transition_and_checkpoint_bit_exact(tmp_path):
    """The warmup->compressed transition is a plain re-jit over
    identical state, and a save/resume INSIDE the compressed regime
    restores the worker/server error feedback bit-exactly: the resumed
    run's params and error state equal the continuous run's, bit for
    bit."""
    x, y = _quadratic_data()
    cont = _engine({"type": "OneBitAdam",
                    "params": {"lr": LR, "freeze_step": 4}},
                   zero={"stage": 2})
    _steps(cont, x, y, 6)       # 4 warmup + 2 compressed
    saver = _engine({"type": "OneBitAdam",
                     "params": {"lr": LR, "freeze_step": 4}},
                    zero={"stage": 2})
    _steps(saver, x, y, 6)
    saver.save_checkpoint(str(tmp_path), tag="mid_frozen")
    resumed = _engine({"type": "OneBitAdam",
                       "params": {"lr": LR, "freeze_step": 4}},
                      zero={"stage": 2})
    resumed.load_checkpoint(str(tmp_path), tag="mid_frozen")
    # error state resumed bit-exactly
    for key in ("worker_error", "server_error", "exp_avg"):
        np.testing.assert_array_equal(
            np.asarray(saver.state["opt"][key]["_flat"]),
            np.asarray(resumed.state["opt"][key]["_flat"]), err_msg=key)
    assert resumed._onebit_frozen()
    _steps(cont, x, y, 2)
    _steps(resumed, x, y, 2)
    np.testing.assert_array_equal(
        np.asarray(cont.state["params"]["w"]),
        np.asarray(resumed.state["params"]["w"]))
    np.testing.assert_array_equal(
        np.asarray(cont.state["opt"]["worker_error"]["_flat"]),
        np.asarray(resumed.state["opt"]["worker_error"]["_flat"]))


# ------------------------------------------------------ overflow reset
def test_overflow_resets_error_state():
    """An overflowed window poisons the compression residuals: the skip
    must keep params/momentum AND zero both error tensors (the qgZ
    reset, reference parity)."""
    x, y = _quadratic_data()
    engine = _engine({"type": "OneBitAdam",
                      "params": {"lr": LR, "freeze_step": 2}})
    _steps(engine, x, y, 5)
    werr = engine.state["opt"]["worker_error"]["_flat"]
    assert float(jnp.abs(werr).sum()) > 0.0
    params_before = np.asarray(engine.state["params"]["w"])
    loss = engine(x, y)
    engine.backward(loss)
    engine.state["acc_grads"] = jax.tree_util.tree_map(
        lambda a: jnp.full_like(a, jnp.inf), engine.state["acc_grads"])
    skipped = int(engine.state["skip_count"])
    engine.step()
    # bf16 engines read the overflow flag back lazily; the DEVICE skip
    # counter is the exact record
    assert int(engine.state["skip_count"]) == skipped + 1
    np.testing.assert_array_equal(
        np.asarray(engine.state["params"]["w"]), params_before)
    np.testing.assert_array_equal(
        np.asarray(engine.state["opt"]["worker_error"]["_flat"]), 0.0)
    np.testing.assert_array_equal(
        np.asarray(engine.state["opt"]["server_error"]["_flat"]), 0.0)


# --------------------------------------- hpZ / hierarchical composition
def test_engine_hierarchical_qc_composes():
    """zero2 + OneBitAdam + hierarchical quantized collectives on the
    factored (replica, shard) mesh: steps stay finite through the
    transition, and the wire estimator reports the two-level exchange +
    per-class reduction ratios."""
    from deepspeed_tpu.runtime.comm.wire import estimate_engine_comm_bytes
    # wide enough that padding + scale overhead is marginal (8K params)
    x, y = _quadratic_data(out_dim=512)
    engine = _engine({"type": "OneBitAdam",
                      "params": {"lr": LR, "freeze_step": 2}},
                     zero={"stage": 2}, out_dim=512,
                     comm={"quantized_collectives": {
                         "enabled": True, "block_size": 16,
                         "hierarchical": 4}})
    assert dict(engine.mesh.shape) == {"data_replica": 2, "data_shard": 4}
    losses = _steps(engine, x, y, 5)
    assert all(np.isfinite(losses)), losses
    wire = estimate_engine_comm_bytes(engine)
    assert wire["onebit_regime"] == "frozen"
    assert wire["quantized_collectives"]["hierarchical"] is True
    assert wire["optimizer_bytes_per_step"] > 0
    assert wire["reduce_bytes_per_step"] == 0
    assert wire["reduction_x"]["gradient"] >= 4.0
    assert wire["reduction_x"]["optimizer"] >= 4.0


def test_qc_exchange_mode_with_plain_adam():
    """quantized_collectives + FusedAdam: the micro step averages local
    grads through the in-collective ring; training tracks the GSPMD
    engine and the estimator reprices the gradient class."""
    from deepspeed_tpu.runtime.comm.wire import estimate_engine_comm_bytes
    x, y = _quadratic_data(out_dim=512)
    qc = _engine({"type": "Adam", "params": {"lr": LR}},
                 zero={"stage": 2}, out_dim=512,
                 comm={"quantized_collectives": {"enabled": True,
                                                 "block_size": 256}})
    base = _engine({"type": "Adam", "params": {"lr": LR}},
                   zero={"stage": 2}, out_dim=512)
    assert qc._local_grad_mode() == "exchange"
    lq = _steps(qc, x, y, 10)
    lb = _steps(base, x, y, 10)
    rel = abs(lq[-1] - lb[-1]) / max(abs(lb[-1]), 1e-9)
    assert rel < 0.01, (lq[-1], lb[-1])
    wire = estimate_engine_comm_bytes(qc)
    assert wire["quantized_collectives"]["enabled"]
    # stage 2's fp32 baseline is the one-way reduce-scatter; the
    # in-collective exchange pays RS + AG (grads come back replicated
    # for the local-grad body), so the honest stage-2 win is ~2x —
    # the >=4x acceptance class is the 1-bit momentum exchange
    assert 0 < wire["reduce_bytes_per_step"] < \
        wire["fp32_flat_reduce_bytes_per_step"]
    assert wire["reduction_x"]["gradient"] > 1.5


# --------------------------------------------------------- shard-lint
def test_audit_walks_quantized_bodies_clean():
    """engine.audit() abstract-evals the local-grad shard_map micro and
    the compressed apply (both regimes' live one) with ZERO findings —
    in particular fp32_gemm_from_bf16 stays silent on the fp32
    error-feedback accumulators and exchange math."""
    x, y = _quadratic_data()
    engine = _engine({"type": "OneBitAdam",
                      "params": {"lr": LR, "freeze_step": 2}},
                     zero={"stage": 2},
                     comm={"quantized_collectives": {"enabled": True,
                                                     "block_size": 16}})
    _steps(engine, x, y, 3)     # frozen regime live
    assert engine._onebit_frozen()
    report = engine.audit()
    assert report.findings == [], [f.key for f in report.findings]
    qc_engine = _engine({"type": "Adam", "params": {"lr": LR}},
                        zero={"stage": 2},
                        comm={"quantized_collectives": {
                            "enabled": True, "block_size": 16}})
    l = qc_engine(x, y)
    qc_engine.backward(l)
    qc_engine.step()
    report = qc_engine.audit()
    assert report.findings == [], [f.key for f in report.findings]


# --------------------------------------------------------- rejections
def test_loud_rejections():
    x, y = _quadratic_data()
    with pytest.raises(ValueError, match="cuda_aware"):
        _engine({"type": "OneBitAdam",
                 "params": {"lr": LR, "cuda_aware": True}})
    with pytest.raises(ValueError, match="not compatible with ZeRO"):
        _engine({"type": "OneBitAdam", "params": {"lr": LR}},
                zero={"stage": 3})
    with pytest.raises(ValueError, match="gradient_clipping"):
        _engine({"type": "OneBitAdam", "params": {"lr": LR}},
                gradient_clipping=1.0)
    with pytest.raises(ValueError, match="weight_decay"):
        _engine({"type": "OneBitAdam",
                 "params": {"lr": LR, "weight_decay": 0.01}},
                zero={"stage": 1})
    with pytest.raises(ValueError, match="qgZ|quantized_gradients"):
        _engine({"type": "OneBitAdam", "params": {"lr": LR}},
                zero={"stage": 2, "zero_quantized_gradients": True})
    with pytest.raises(ValueError, match="cuda_aware"):
        _engine({"type": "Adam", "params": {"lr": LR}},
                comm={"quantized_collectives": {"enabled": True,
                                                "cuda_aware": True}})
    with pytest.raises(ValueError, match="ZeRO-3|zero_quantized"):
        _engine({"type": "Adam", "params": {"lr": LR}},
                zero={"stage": 3},
                comm={"quantized_collectives": {"enabled": True}})
    with pytest.raises(ValueError, match="hierarchical"):
        _engine({"type": "Adam", "params": {"lr": LR}},
                comm={"quantized_collectives": {"enabled": True,
                                                "hierarchical": 1}})
    with pytest.raises(ValueError, match="dtype"):
        _engine({"type": "Adam", "params": {"lr": LR}},
                comm={"quantized_collectives": {"enabled": True,
                                                "dtype": "int4"}})
    # unknown qc key: warn by default, raise under strict
    with pytest.raises(ValueError, match="NO effect"):
        _engine({"type": "Adam", "params": {"lr": LR}},
                comm={"quantized_collectives": {"enabled": True,
                                                "bogus_key": 1,
                                                "strict": True}})
    # weight_decay at stage 0 (replicated params) is ACCEPTED
    wd = _engine({"type": "OneBitAdam",
                  "params": {"lr": LR, "weight_decay": 0.01,
                             "freeze_step": 2}})
    losses = _steps(wd, x, y, 4)
    assert all(np.isfinite(losses)), losses
