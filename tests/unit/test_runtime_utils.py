"""Partition math + norm helpers (mirrors reference test_runtime_utils.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime import utils as ds_utils


def check_partition(weights, num_parts, eps=1e-3):
    parts = ds_utils.partition_balanced(weights, num_parts, eps)
    assert len(parts) == num_parts + 1
    assert parts[0] == 0
    assert parts[-1] == len(weights)
    for p in range(1, len(parts)):
        assert parts[p] >= parts[p - 1]
    # near-optimal bottleneck: heaviest chunk within (1+eps) of best possible
    chunk_weights = [sum(weights[parts[p]:parts[p + 1]])
                     for p in range(num_parts)]
    assert max(chunk_weights) <= (1 + 2 * eps) * _optimal_bottleneck(
        weights, num_parts) + 1e-9


def _optimal_bottleneck(weights, num_parts):
    best = sum(weights)
    # brute force over all boundary placements for small cases
    n = len(weights)
    import itertools
    for cuts in itertools.combinations(range(1, n), num_parts - 1):
        bounds = (0,) + cuts + (n,)
        bottleneck = max(sum(weights[bounds[i]:bounds[i + 1]])
                         for i in range(num_parts))
        best = min(best, bottleneck)
    return best


def test_partition_uniform():
    parts = ds_utils.partition_uniform(10, 5)
    assert parts == [0, 2, 4, 6, 8, 10]
    parts = ds_utils.partition_uniform(10, 3)
    assert parts[0] == 0 and parts[-1] == 10 and len(parts) == 4
    # fewer items than parts
    parts = ds_utils.partition_uniform(2, 4)
    assert parts == [0, 1, 2, 2, 2]


def test_partition_balanced_uniform_weights():
    check_partition([1] * 8, 4)


def test_partition_balanced_skewed():
    check_partition([1, 1, 1, 1, 10], 2)
    check_partition([10, 1, 1, 1, 1], 2)
    check_partition([1, 5, 1, 5, 1, 5], 3)


def test_partition_balanced_more_parts_than_items():
    parts = ds_utils.partition_balanced([5, 5], 4)
    assert parts[0] == 0 and parts[-1] == 2


def test_grad_norm():
    grads = {"a": jnp.ones((3, 4)), "b": jnp.full((2,), 2.0)}
    norm = ds_utils.get_grad_norm(grads)
    expected = np.sqrt(12 * 1.0 + 2 * 4.0)
    np.testing.assert_allclose(float(norm), expected, rtol=1e-6)


def test_clip_grad_norm():
    grads = {"w": jnp.full((4,), 10.0)}
    clipped, total = ds_utils.clip_grad_norm_(grads, max_norm=1.0)
    np.testing.assert_allclose(float(ds_utils.get_grad_norm(clipped)), 1.0,
                               rtol=1e-4)
    # under the cap -> untouched
    grads = {"w": jnp.full((4,), 0.01)}
    clipped, _ = ds_utils.clip_grad_norm_(grads, max_norm=1.0)
    np.testing.assert_allclose(np.asarray(clipped["w"]),
                               np.asarray(grads["w"]))


def test_check_overflow():
    ok = {"a": jnp.ones(4)}
    bad = {"a": jnp.array([1.0, float("inf")])}
    nan = {"a": jnp.array([1.0, float("nan")])}
    assert not bool(ds_utils.CheckOverflow.has_overflow(ok))
    assert bool(ds_utils.CheckOverflow.has_overflow(bad))
    assert bool(ds_utils.CheckOverflow.has_overflow(nan))


def test_call_to_str():
    assert ds_utils.call_to_str("foo") == "foo()"
    assert ds_utils.call_to_str("foo", 1, 2) == "foo(1, 2)"
    assert ds_utils.call_to_str("foo", 1, b=2) == "foo(1, b=2)"


def test_partitioned_tensor_roundtrip():
    """PartitionedTensor shards over an axis and reassembles exactly
    (reference runtime/utils.py:396-503)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.utils import PartitionedTensor

    mesh = build_mesh(data=2, model=4)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(5, 7), dtype=jnp.float32)  # 35: pads to 36
    pt = PartitionedTensor(x, mesh, axis="model")
    assert "model" in str(pt.local_data.sharding.spec)
    np.testing.assert_allclose(np.asarray(pt.full()), np.asarray(x))

    # meta round-trip (what the reference ships between pipeline stages)
    meta = pt.to_meta()
    pt2 = PartitionedTensor.from_meta(meta, pt.local_data, mesh,
                                      axis="model")
    np.testing.assert_allclose(np.asarray(pt2.full()), np.asarray(x))


def test_partitioned_tensor_axisless_mesh():
    """Meshes without the requested axis replicate instead of crashing."""
    import jax.numpy as jnp
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.utils import PartitionedTensor

    mesh = build_mesh(data=8)
    x = jnp.arange(12.0).reshape(3, 4)
    pt = PartitionedTensor(x, mesh, axis="model")
    np.testing.assert_allclose(np.asarray(pt.full()), np.asarray(x))
