"""Dataloader tests (reference tests/unit/test_data.py)."""
import numpy as np

import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              RepeatingLoader)
from deepspeed_tpu.runtime.model import Model


class RandomDataset:
    """(x, y) tuples (mirrors reference random_dataloader fixtures)."""

    def __init__(self, n=64, dim=8, seed=0):
        rs = np.random.RandomState(seed)
        self.x = rs.randn(n, dim).astype(np.float32)
        self.y = rs.randn(n, 2).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]


def test_repeating_loader():
    loader = RepeatingLoader([1, 2, 3])
    out = [next(loader) for _ in range(7)]
    assert out == [1, 2, 3, 1, 2, 3, 1]
    assert len(loader) == 3


def test_dataloader_batches():
    ds = RandomDataset(n=64, dim=8)
    loader = DeepSpeedDataLoader(ds, batch_size=16, shuffle=False)
    batches = list(loader)
    assert len(batches) == 4 == len(loader)
    x, y = batches[0]
    assert x.shape == (16, 8) and y.shape == (16, 2)
    np.testing.assert_allclose(x, ds.x[:16])


def test_dataloader_epoch_shuffle():
    ds = RandomDataset(n=32, dim=4)
    loader = DeepSpeedDataLoader(ds, batch_size=8, shuffle=True)
    loader.set_epoch(0)
    first = np.concatenate([b[0] for b in loader])
    loader.set_epoch(1)
    second = np.concatenate([b[0] for b in loader])
    # same multiset of rows, different order
    assert not np.allclose(first, second)
    np.testing.assert_allclose(np.sort(first.sum(axis=1)),
                               np.sort(second.sum(axis=1)), rtol=1e-5)


def test_dataloader_dp_sharding():
    """Each process sees 1/world of the dataset (reference
    DistributedSampler semantics)."""
    ds = RandomDataset(n=64, dim=4)
    shards = []
    for rank in range(2):
        loader = DeepSpeedDataLoader(ds, batch_size=8, shuffle=False,
                                     data_parallel_world_size=2,
                                     data_parallel_rank=rank)
        shards.append(np.concatenate([b[0] for b in loader]))
    assert shards[0].shape[0] == 32
    merged = np.concatenate(shards)
    np.testing.assert_allclose(np.sort(merged.sum(axis=1)),
                               np.sort(ds.x.sum(axis=1)), rtol=1e-5)


def test_training_data_through_initialize():
    """initialize(training_data=...) returns the engine's dataloader
    (reference __init__.py return tuple)."""
    ds = RandomDataset(n=64, dim=8)
    config = {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((8, 2))}),
        training_data=ds, config_params=config)
    assert loader is not None
    it = iter(loader)
    x, y = next(it)
    assert x.shape[0] == 16
    loss = engine(jnp.asarray(x), jnp.asarray(y))
    engine.backward(loss)
    engine.step()
