"""Flight recorder + run doctor (ISSUE 8): span-tree shape per engine
path, the watchdog trip/action matrix, crash-bundle round-trip under the
PR 1 fault-injection harness, recompile-storm detection, JSONL rotation,
and the off-is-zero-overhead structural contract."""
import contextlib
import importlib.util
import json
import logging
import os
import signal
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.model import Model
from deepspeed_tpu.telemetry.config import DeepSpeedTelemetryConfig
from deepspeed_tpu.telemetry.recorder import (CRASH_BUNDLE_KEYS,
                                              validate_crash_bundle)
from deepspeed_tpu.telemetry.spans import SpanTracer, validate_span
from deepspeed_tpu.telemetry.watchdog import Watchdog, WatchdogError
from deepspeed_tpu.utils.fault_injection import SimulatedKill
from deepspeed_tpu.utils.logging import logger as ds_logger

pytestmark = pytest.mark.diagnostics


@contextlib.contextmanager
def _capture_warnings():
    """The DS logger has propagate=False, so caplog can't see it; attach
    a handler directly (the repo's test_telemetry idiom)."""
    messages = []

    class _Cap(logging.Handler):
        def emit(self, record):
            messages.append(record.getMessage())

    cap = _Cap(level=logging.WARNING)
    ds_logger.addHandler(cap)
    try:
        yield messages
    finally:
        ds_logger.removeHandler(cap)


def _toy_model():
    return Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                 {"w": jnp.zeros((4, 2))})


def _diag_telemetry(tmp_path, **extra):
    tele = {"enabled": True, "output_path": str(tmp_path),
            "spans": {}, "flight_recorder": {}}
    tele.update(extra)
    return tele


def _engine(tmp_path, telemetry=None, extra=None):
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "wall_clock_breakdown": True,
    }
    if telemetry is not None:
        config["telemetry"] = telemetry
    config.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=_toy_model(),
                                               config_params=config)
    return engine


def _batch():
    return jnp.ones((8, 4)), jnp.ones((8, 2))


def _train_steps(engine, n):
    x, y = _batch()
    for _ in range(n):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()


def _spans_of(engine):
    path = os.path.join(engine.telemetry.output_dir, "spans.jsonl")
    return [json.loads(line) for line in open(path)]


def _crash_dir(engine):
    return os.path.join(engine.telemetry.output_dir, "crash")


def _bundles(engine):
    d = _crash_dir(engine)
    if not os.path.isdir(d):
        return []
    return [os.path.join(d, name) for name in sorted(os.listdir(d))
            if name.endswith(".json")]


def _serve_engine(tmp_path, paged=True, telemetry=None, max_new_tokens=3):
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=32, n_layers=1,
                          n_heads=2, d_model=16, use_flash_attention=False,
                          remat=False)
    inf = {"max_batch_size": 2, "prefill_buckets": [8, 16], "dtype": "fp32",
           "greedy": True, "max_new_tokens": max_new_tokens}
    if paged:
        inf.update(kv_layout="paged", kv_block_size=4, prefix_caching=True)
    config = {"inference": inf}
    if telemetry is not None:
        config["telemetry"] = telemetry
    return deepspeed_tpu.init_inference(
        model=gpt2.make_gpt2_model(config=cfg), config=config)


# ------------------------------------------------------------ span tracer

def test_span_tracer_tree_export_and_schema():
    exported = []

    class Sink:
        def emit(self, rec):
            exported.append(rec)

        def close(self):
            pass

    tracer = SpanTracer([Sink()], max_events=4)
    root = tracer.begin("serving_request", uid=7)
    root.event("admit", slot=0)
    child = root.child("prefill_chunk", tokens=8)
    child.end()
    root.timed_child("decode", 1.0, 2.0, step=3)
    root.end()
    assert len(exported) == 3                      # depth-first, root first
    assert exported[0]["name"] == "serving_request"
    assert exported[0]["parent_id"] is None
    for rec in exported:
        assert validate_span(rec) == []
        assert rec["trace_id"] == exported[0]["trace_id"]
    assert {rec["parent_id"] for rec in exported[1:]} == \
        {exported[0]["span_id"]}
    assert exported[2]["dur_s"] == pytest.approx(1.0)
    assert exported[0]["events"][0]["name"] == "admit"
    assert tracer.trees_exported == 1 and not tracer._open_roots


def test_span_event_cap_bounds_long_requests():
    tracer = SpanTracer([], max_events=3)
    root = tracer.begin("serving_request")
    for i in range(10):
        root.event("decode", step=i)
    assert len(root.events) == 3
    root.end()
    assert root.to_dict()["attrs"]["dropped_events"] == 7


def test_open_spans_snapshot_for_crash_bundles():
    tracer = SpanTracer([])
    root = tracer.begin("serving_request", uid=1)
    root.child("prefill_chunk")
    open_spans = tracer.open_snapshot()
    assert len(open_spans) == 2
    for rec in open_spans:
        assert rec["end_s"] is None and validate_span(rec) == []
    root.end()
    assert tracer.open_snapshot() == []


# ------------------------------------------------------- train span trees

def test_train_step_span_tree_matches_phases(tmp_path):
    engine = _engine(tmp_path, telemetry=_diag_telemetry(tmp_path))
    _train_steps(engine, 2)
    spans = _spans_of(engine)
    roots = [s for s in spans if s["parent_id"] is None]
    assert len(roots) == 2
    assert {r["name"] for r in roots} == {"train_step"}
    assert [r["attrs"]["step"] for r in roots] == [0, 1]
    assert roots[0]["trace_id"] != roots[1]["trace_id"]
    recs = [json.loads(line) for line in open(engine.telemetry.jsonl_path)]
    for root, rec in zip(roots, recs):
        assert root["attrs"]["path"] == "micro"
        kids = [s for s in spans if s["parent_id"] == root["span_id"]]
        # one child per phase clock, durations EQUAL to the record's
        assert {k["name"] for k in kids} == set(rec["phases"])
        for kid in kids:
            assert kid["dur_s"] == pytest.approx(
                rec["phases"][kid["name"]])
            assert root["start_s"] - 1e-6 <= kid["start_s"] and \
                kid["end_s"] <= root["end_s"] + 1e-6
        assert root["dur_s"] == pytest.approx(rec["step_time_s"])
    for s in spans:
        assert validate_span(s) == []


def test_offload_span_tree_is_the_executed_segment_plan(tmp_path):
    """ISSUE 13: on the executor-lowered paths the step's span tree IS
    the executed segment plan — one child per segment, named by its
    plan node with its kind attr — so trace durations and plan nodes
    cannot drift (phase-derived trees remain the micro/fused
    fallback)."""
    from deepspeed_tpu.runtime.executor import plan_for_engine
    engine = _engine(tmp_path, telemetry=_diag_telemetry(tmp_path),
                     extra={"zero_optimization": {
                         "stage": 2, "cpu_offload": True},
                         "bf16": {"enabled": True}})
    plan_names = [s.name for s in plan_for_engine(engine).segments]
    plan_kinds = {s.name: s.kind
                  for s in plan_for_engine(engine).segments}
    _train_steps(engine, 2)
    spans = _spans_of(engine)
    roots = [s for s in spans if s["parent_id"] is None]
    assert len(roots) == 2
    for root in roots:
        assert root["attrs"]["path"] == "offload"
        kids = [s for s in spans if s["parent_id"] == root["span_id"]]
        # tree == plan: same node names (the async launch order may
        # permute the record order, never the node set)
        assert sorted(k["name"] for k in kids) == sorted(plan_names)
        for kid in kids:
            assert kid["attrs"]["kind"] == plan_kinds[kid["name"]]
            assert kid["dur_s"] is not None and kid["dur_s"] >= 0
    for s in spans:
        assert validate_span(s) == []


def test_fused_path_span_labeled(tmp_path):
    engine = _engine(tmp_path, telemetry=_diag_telemetry(tmp_path),
                     extra={"train_batch_size": 8})
    x, y = np.ones((1, 8, 4), np.float32), np.ones((1, 8, 2), np.float32)
    engine.train_batch(batch=(x, y))
    roots = [s for s in _spans_of(engine) if s["parent_id"] is None]
    assert roots and roots[0]["attrs"]["path"] == "fused"


def test_chrome_trace_file_valid(tmp_path):
    engine = _engine(tmp_path, telemetry=_diag_telemetry(tmp_path))
    _train_steps(engine, 2)
    engine.telemetry.close()
    path = os.path.join(engine.telemetry.output_dir, "trace_events.json")
    events = json.load(open(path))              # closed file: strict JSON
    assert events
    checker = _load_checker()
    assert checker.check_trace_events(open(path).read()) == []
    # truncated mid-write (a crashed run): still validates leniently
    text = open(path).read()
    cut = text.rindex("},") + 2
    assert checker.check_trace_events(text[:cut]) == []


# ---------------------------------------------------- serving span trees

def test_serving_request_span_tree(tmp_path):
    engine = _serve_engine(tmp_path,
                           telemetry=_diag_telemetry(tmp_path))
    system = list(range(1, 13))                  # 3 full 4-token pages
    engine.generate([system + [20, 21, 22]])
    engine.generate([system + [30, 31]])        # prefix hit on pages
    spans = _spans_of(engine)
    roots = [s for s in spans if s["parent_id"] is None]
    assert len(roots) == 2
    for root in roots:
        assert root["name"] == "serving_request"
        events = [e["name"] for e in root["events"]]
        assert events[0] == "admit" and events[-1] == "retire"
        assert "page_alloc" in events
        kids = [s["name"] for s in spans
                if s["parent_id"] == root["span_id"]]
        assert "prefill_chunk" in kids and "decode" in kids
        # 3 new tokens => first from prefill + 2 decode steps
        assert kids.count("decode") == 2
    assert any("prefix_hit" in [e["name"] for e in r["events"]]
               for r in roots)
    for s in spans:
        assert validate_span(s) == []


def test_preemption_event_rides_request_span(tmp_path):
    """A pool-exhaustion preemption lands as an event on the victim's
    span, and the resumed request keeps ONE trace (second admit event)."""
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=64, n_layers=1,
                          n_heads=2, d_model=16, use_flash_attention=False,
                          remat=False)
    # 3 slots x up to ~40 tokens each, but only 9 pages (72 tokens):
    # the shapes of test_serving's preemption test
    engine = deepspeed_tpu.init_inference(
        model=gpt2.make_gpt2_model(config=cfg),
        config={"inference": {
            "max_batch_size": 3, "prefill_buckets": [8, 16, 32],
            "dtype": "fp32", "greedy": True, "kv_layout": "paged",
            "kv_block_size": 8, "num_pages": 9},
            "telemetry": _diag_telemetry(tmp_path, watchdog={
                "pool_exhaustion": {"every": 1, "action": "warn"}})})
    rs = np.random.RandomState(5)
    prompts = [rs.randint(0, 128, size=n).tolist() for n in (12, 14, 10)]
    with _capture_warnings() as messages:
        engine.generate(prompts, max_new_tokens=24)
    spans = _spans_of(engine)
    roots = [s for s in spans if s["parent_id"] is None]
    preempted = [r for r in roots
                 if "preempted" in [e["name"] for e in r["events"]]]
    assert preempted, [r["events"] for r in roots]
    events = [e["name"] for e in preempted[0]["events"]]
    assert events.count("admit") == 2            # admitted, then resumed
    assert any("pool_exhaustion" in m for m in messages)
    assert engine.telemetry.watchdog.snapshot()["pool_events"] >= 1
    engine.telemetry.close()                     # stops the watchdog thread


# ------------------------------------------------------ watchdog matrix

def _rec(step, loss, overflow=False):
    return {"kind": "train_step", "step": step, "loss": loss,
            "overflow": overflow}


class _FakeRecorder:
    def __init__(self):
        self.dumps = []

    def dump(self, reason, exc=None):
        self.dumps.append(reason)
        return "/dev/null"


def test_watchdog_nan_streak_actions():
    for action, dumps, raises in (("warn", 0, False), ("dump", 1, False),
                                  ("raise", 1, True)):
        rec = _FakeRecorder()
        wd = Watchdog({"nan_streak": {"threshold": 2, "action": action}},
                      recorder=rec)
        with _capture_warnings() as messages:
            wd.observe_train(_rec(0, float("nan")))
            assert not wd.trips                   # streak of 1: no trip
            if raises:
                with pytest.raises(WatchdogError, match="nan_streak"):
                    wd.observe_train(_rec(1, float("nan")))
            else:
                wd.observe_train(_rec(1, float("nan")))
            # the streak trips ONCE, not on every further bad step
            wd.observe_train(_rec(2, float("nan")))
        assert len(wd.trips) == 1
        assert len(rec.dumps) == dumps
        assert any("nan_streak" in m and "TRIPPED" in m for m in messages)
        # a finite step resets the streak; a fresh streak re-trips
        wd.observe_train(_rec(3, 1.0))
        if raises:
            with pytest.raises(WatchdogError):
                wd.observe_train(_rec(4, float("nan")))
                wd.observe_train(_rec(5, float("nan")))
        else:
            wd.observe_train(_rec(4, float("nan")))
            wd.observe_train(_rec(5, float("nan")))
        assert len(wd.trips) == 2
        wd.close()


def test_watchdog_overflow_counts_toward_streak():
    wd = Watchdog({"nan_streak": {"threshold": 2, "action": "warn"}})
    wd.observe_train(_rec(0, 1.0, overflow=True))
    wd.observe_train(_rec(1, 1.0, overflow=True))
    assert len(wd.trips) == 1
    wd.close()


def test_watchdog_loss_spike_zscore():
    wd = Watchdog({"loss_spike": {"zscore": 4.0, "window": 16,
                                  "min_steps": 4, "action": "warn"}})
    for i in range(8):
        wd.observe_train(_rec(i, 1.0 + 0.01 * (i % 2)))
    assert not wd.trips
    wd.observe_train(_rec(8, 50.0))              # >> 4 sigma
    assert len(wd.trips) == 1
    assert wd.trips[0]["watchdog"] == "loss_spike"
    # cooldown: the window refills before another trip can fire
    wd.observe_train(_rec(9, 60.0))
    assert len(wd.trips) == 1
    wd.close()


def test_watchdog_ttft_slo_and_pool_events():
    rec = _FakeRecorder()
    wd = Watchdog({"ttft_slo": {"slo_s": 0.5, "every": 2,
                                "action": "dump"},
                   "pool_exhaustion": {"every": 1, "action": "warn"}},
                  recorder=rec)
    wd.observe_ttft(0.1)
    assert not wd.trips
    wd.observe_ttft(0.9)                         # violation 1 -> trip
    wd.observe_ttft(0.9)                         # violation 2 (every=2)
    wd.observe_ttft(0.9)                         # violation 3 -> trip
    assert len([t for t in wd.trips
                if t["watchdog"] == "ttft_slo"]) == 2
    assert rec.dumps == ["watchdog:ttft_slo"] * 2
    wd.observe_pool_event("admission_blocked")
    assert wd.trips[-1]["watchdog"] == "pool_exhaustion"
    snap = wd.snapshot()
    assert snap["ttft_violations"] == 3 and snap["pool_events"] == 1
    wd.close()


def test_watchdog_step_deadline_thread_trips_on_hang():
    before = {id(t) for t in threading.enumerate()}
    rec = _FakeRecorder()
    wd = Watchdog({"step_deadline": {
        "factor": 2.0, "min_steps": 3, "floor_s": 0.2, "poll_s": 0.02,
        "action": "dump"}}, recorder=rec)
    for step in range(3):                        # build the median
        wd.step_begin(step)
        time.sleep(0.01)
        wd.step_end()
    with _capture_warnings() as messages:
        wd.step_begin(3)                         # armed now
        deadline = time.monotonic() + 2.0
        while not rec.dumps and time.monotonic() < deadline:
            time.sleep(0.02)                     # the "hang"
        wd.step_end()
    assert wd.trips and wd.trips[0]["watchdog"] == "step_deadline"
    assert rec.dumps == ["watchdog:step_deadline"]
    assert any("has not completed" in m for m in messages)
    wd.close()
    # close() joined THIS watchdog's thread (other tests' daemon
    # threads, from engines whose collectors outlive their test, are
    # not this test's concern)
    assert not any(t.name.startswith("ds-watchdog")
                   for t in threading.enumerate()
                   if t.is_alive() and id(t) not in before)


def test_watchdog_step_deadline_clean_steps_no_trip():
    wd = Watchdog({"step_deadline": {
        "factor": 50.0, "min_steps": 2, "floor_s": 5.0, "poll_s": 0.02,
        "action": "warn"}})
    for step in range(6):
        wd.step_begin(step)
        time.sleep(0.005)
        wd.step_end()
    time.sleep(0.1)                              # let the thread poll
    assert not wd.trips
    wd.close()


def test_watchdog_dump_action_without_recorder_warns():
    wd = Watchdog({"nan_streak": {"threshold": 1, "action": "dump"}},
                  recorder=None)
    with _capture_warnings() as messages:
        wd.observe_train(_rec(0, float("nan")))
    assert any("flight_recorder" in m for m in messages)
    wd.close()


# ------------------------------------------------------- crash bundles

def test_mid_step_kill_yields_schema_valid_bundle(tmp_path, monkeypatch):
    """PR 1 fault-injection harness: a SimulatedKill (BaseException,
    like a real preemption) mid-step must leave a schema-valid crash
    bundle with >= 1 StepRecord, the span tree, and the program
    registry — then re-raise untouched."""
    engine = _engine(tmp_path, telemetry=_diag_telemetry(tmp_path))
    _train_steps(engine, 2)                      # ring holds 2 records

    def boom(lr_kwargs=None):
        raise SimulatedKill("injected mid-step kill")

    monkeypatch.setattr(engine, "_take_model_step", boom)
    x, y = _batch()
    with pytest.raises(SimulatedKill):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    paths = _bundles(engine)
    assert len(paths) == 1
    bundle = json.load(open(paths[0]))
    assert validate_crash_bundle(bundle) == []
    assert bundle["reason"] == "exception:train_step"
    assert bundle["exception"]["type"] == "SimulatedKill"
    assert "injected mid-step kill" in bundle["exception"]["traceback"]
    assert len(bundle["records"]) >= 1
    assert all(r["kind"] == "train_step" for r in bundle["records"])
    assert any(s["name"] == "train_step" for s in bundle["spans"])
    assert "micro" in bundle["programs"]["programs"]
    assert bundle["env"]["jax_version"] == jax.__version__
    assert bundle["ds_config"]["train_micro_batch_size_per_gpu"] == 1
    assert bundle["state"]["engine"]["global_steps"] == 2
    # the stdlib checker in bin/ accepts the same bundle
    assert _load_checker().check_crash_bundle(bundle) == []


def test_nested_step_path_wrappers_dump_once(tmp_path, monkeypatch):
    """forward() raising inside train-path code that an outer wrapper
    also guards must produce ONE bundle, not one per wrapper."""
    engine = _engine(tmp_path, telemetry=_diag_telemetry(tmp_path))
    _train_steps(engine, 1)
    err = RuntimeError("boom")

    def boom(*args, **kwargs):
        raise err

    monkeypatch.setattr(engine, "_forward_impl", boom)
    x, y = _batch()
    with pytest.raises(RuntimeError):
        engine(x, y)
    with pytest.raises(RuntimeError):
        engine(x, y)                             # same exception object
    assert len(_bundles(engine)) == 1


def test_debug_dump_and_bundle_retention(tmp_path):
    tele = _diag_telemetry(tmp_path)
    tele["flight_recorder"] = {"max_bundles": 2, "capacity": 3}
    engine = _engine(tmp_path, telemetry=tele)
    _train_steps(engine, 5)
    for i in range(3):
        assert engine.debug_dump("probe{}".format(i)) is not None
    paths = _bundles(engine)
    assert len(paths) == 2                       # retention pruned oldest
    assert "probe1" in paths[0] and "probe2" in paths[1]
    bundle = json.load(open(paths[-1]))
    assert validate_crash_bundle(bundle) == []
    assert len(bundle["records"]) == 3           # ring capacity bound


def test_debug_dump_without_recorder_is_loud_noop(tmp_path):
    engine = _engine(tmp_path, telemetry={"enabled": True,
                                          "output_path": str(tmp_path)})
    with _capture_warnings() as messages:
        assert engine.debug_dump() is None
    assert any("flight_recorder" in m for m in messages)


def test_bundle_counter_survives_process_restart(tmp_path):
    """A crash-looping job restarts with a fresh recorder every time;
    it must neither overwrite the previous crash's bundle nor grow the
    directory past max_bundles."""
    from deepspeed_tpu.telemetry.recorder import FlightRecorder
    crash = str(tmp_path / "crash")
    first = FlightRecorder(crash, max_bundles=2)
    p0 = first.dump("crash")
    first.close()
    second = FlightRecorder(crash, max_bundles=2)   # "restarted" process
    p1 = second.dump("crash")
    assert p1 != p0 and os.path.exists(p0) and os.path.exists(p1)
    second.dump("crash")                            # retention: 2 kept
    second.close()
    kept = sorted(os.listdir(crash))
    assert len(kept) == 2 and os.path.basename(p0) not in kept


def test_watchdog_thread_raise_covers_induced_interrupt(tmp_path):
    """A raise-trip from the deadline thread dumps ONCE: the induced
    KeyboardInterrupt reaching the step-path hook must not write a
    second bundle for the same trip."""
    from deepspeed_tpu.telemetry.recorder import FlightRecorder
    rec = FlightRecorder(str(tmp_path / "crash"))
    assert rec.dump("watchdog:step_deadline") is not None
    rec.cover_interrupt()
    assert rec.dump("exception:forward", exc=KeyboardInterrupt()) is None
    # a LATER real interrupt (window expired) still dumps
    rec._interrupt_covered_until = 0.0
    assert rec.dump("exception:forward",
                    exc=KeyboardInterrupt()) is not None
    rec.close()


def test_warn_log_events_ride_the_bundle(tmp_path):
    engine = _engine(tmp_path, telemetry=_diag_telemetry(tmp_path))
    _train_steps(engine, 1)
    ds_logger.warning("synthetic warning for the ring %d", 7)
    bundle = json.load(open(engine.debug_dump()))
    assert any("synthetic warning for the ring 7" == e["message"]
               for e in bundle["log_events"])


def test_sigterm_handler_dumps_and_chains(tmp_path):
    tele = _diag_telemetry(tmp_path)
    tele["flight_recorder"] = {"on_sigterm": True}
    chained = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
    try:
        engine = _engine(tmp_path, telemetry=tele)
        _train_steps(engine, 1)
        handler = signal.getsignal(signal.SIGTERM)
        assert handler == engine.telemetry.recorder._on_sigterm
        handler(signal.SIGTERM, None)
        assert len(_bundles(engine)) == 1
        assert "sigterm" in _bundles(engine)[0]
        assert chained == [signal.SIGTERM]       # previous handler ran
        engine.telemetry.close()                 # uninstalls the handler
        assert signal.getsignal(signal.SIGTERM) not in \
            (handler, signal.SIG_DFL) or \
            signal.getsignal(signal.SIGTERM) != handler
    finally:
        signal.signal(signal.SIGTERM, prev)


# -------------------------------------------------- compile observatory

def test_program_registry_prices_engine_programs(tmp_path):
    engine = _engine(tmp_path, telemetry=_diag_telemetry(tmp_path))
    _train_steps(engine, 3)
    snap = engine.telemetry.programs.snapshot()
    assert set(snap["programs"]) >= {"micro", "apply"}
    micro = snap["programs"]["micro"]
    assert micro["calls"] == 3
    assert micro["flops"] > 0 and micro["cost_analysis"]["flops"] > 0
    assert micro["price_wall_s"] is not None
    # the first call's fresh-state signature may legitimately differ
    # from the steady state's (one extra executable); a STABLE loop must
    # not keep recompiling
    assert micro["recompiles"] <= 1
    assert not snap["flags"]


def test_recompile_storm_flagged_on_prefill_bucket_explosion(tmp_path):
    tele = _diag_telemetry(tmp_path)
    tele["programs"] = {"recompile_storm_threshold": 2}
    engine = _serve_engine(tmp_path, paged=False, telemetry=tele,
                           max_new_tokens=1)
    with _capture_warnings() as messages:
        # 8- and 16-token buckets at two sampling configs -> 3 distinct
        # prefill traces: past the tiny threshold
        engine.generate([[1, 2, 3]])
        engine.generate([list(range(1, 11))])
        engine.generate([[4, 5]], sampling={"greedy": False, "top_k": 2})
    snap = engine.telemetry.programs.snapshot()
    assert snap["families"]["prefill"]["count"] >= 3
    assert snap["families"]["prefill"]["storm"] is True
    assert any(f["key"] == "recompile_storm:prefill"
               for f in snap["flags"])
    assert any("recompile storm" in m for m in messages)
    assert "program_flags" in engine.telemetry_snapshot()


def test_replicated_leaf_audit_flags_large_replicated_inputs():
    from deepspeed_tpu.telemetry.programs import ProgramRegistry
    reg = ProgramRegistry(replicated_leaf_bytes=1024)
    big = jax.device_put(jnp.ones((64, 64), jnp.float32))  # replicated
    fn = jax.jit(lambda x: x * 2)
    fn(big)
    with _capture_warnings() as messages:
        reg.observe_call("grow", fn, (big,))
    if jax.device_count() > 1:
        assert any(f["key"].startswith("replicated_leaf")
                   for f in reg.flags)
        assert any("REPLICATED" in m for m in messages)
    small = jnp.ones((2,), jnp.float32)
    reg.observe_call("ok", fn, (small,))
    assert not any(f["key"].startswith("replicated_leaf:ok")
                   for f in reg.flags)


def test_registry_counts_recompiles_via_jit_cache():
    from deepspeed_tpu.telemetry.programs import ProgramRegistry
    reg = ProgramRegistry(storm_threshold=4)
    fn = jax.jit(lambda x: x + 1)
    fn(jnp.ones((2,)))
    reg.observe_call("k", fn, None)
    assert reg.programs["k"]["recompiles"] == 0
    for n in range(3, 9):                        # 6 new shapes
        fn(jnp.ones((n,)))
        reg.observe_call("k", fn, None)
    entry = reg.programs["k"]
    assert entry["executables"] == 7 and entry["recompiles"] == 6
    assert any(f["key"] == "recompile_storm:k" for f in reg.flags)


# ----------------------------------------------------- bounded JSONL

def test_jsonl_rotation_keeps_schema_valid_files(tmp_path):
    from deepspeed_tpu.telemetry.record import validate_step_record
    tele = _diag_telemetry(tmp_path, jsonl_max_bytes=4096)
    engine = _engine(tmp_path, telemetry=tele)
    _train_steps(engine, 12)                     # records ~> 1 KB each
    main_path = engine.telemetry.jsonl_path
    rotated = main_path + ".1"
    assert os.path.exists(rotated)
    assert os.path.getsize(main_path) <= 4096
    assert os.path.getsize(rotated) <= 4096
    n = 0
    for path in (main_path, rotated):
        for line in open(path):
            assert validate_step_record(json.loads(line)) == []
            n += 1
    assert 0 < n <= 12                           # oldest rotation dropped
    with pytest.raises(ValueError, match="jsonl_max_bytes"):
        DeepSpeedTelemetryConfig({"telemetry": {"jsonl_max_bytes": 10}})


# ------------------------------------------------- config validation

def test_diagnostics_config_unknown_keys_warn_and_strict_raises():
    base = {"enabled": True, "output_path": "x"}
    for section in ("spans", "flight_recorder", "watchdog", "programs"):
        with _capture_warnings() as messages:
            DeepSpeedTelemetryConfig({"telemetry": dict(
                base, **{section: {"bogus": 1}})})
        assert any("bogus" in m for m in messages), section
        with pytest.raises(ValueError, match="bogus"):
            DeepSpeedTelemetryConfig({"telemetry": dict(
                base, strict=True, **{section: {"bogus": 1}})})
    with pytest.raises(ValueError, match="action"):
        DeepSpeedTelemetryConfig({"telemetry": dict(base, watchdog={
            "nan_streak": {"action": "explode"}})})
    with pytest.raises(ValueError, match="threshold"):
        DeepSpeedTelemetryConfig({"telemetry": dict(base, watchdog={
            "nan_streak": {"threshold": -1}})})
    cfg = DeepSpeedTelemetryConfig({"telemetry": dict(base, watchdog={
        "step_deadline": False, "ttft_slo": {"slo_s": 2.0}})})
    assert cfg.watchdog["step_deadline"] is None
    assert cfg.watchdog["ttft_slo"]["slo_s"] == 2.0
    # ttft_slo without an slo_s can never trip: parsed away
    cfg = DeepSpeedTelemetryConfig({"telemetry": dict(base,
                                                      watchdog={})})
    assert cfg.watchdog["ttft_slo"] is None
    assert cfg.watchdog["nan_streak"]["threshold"] == 3


# --------------------------------------------- off-is-zero-overhead

def test_diagnostics_off_is_structurally_absent(tmp_path):
    from deepspeed_tpu.inference.scheduler import \
        ContinuousBatchingScheduler
    before_threads = {t.name for t in threading.enumerate()}
    before_handler = signal.getsignal(signal.SIGTERM)
    n_handlers = len(ds_logger.handlers)
    # telemetry ON but no diagnostics sections: registry only
    engine = _engine(tmp_path, telemetry={"enabled": True,
                                          "output_path": str(tmp_path)})
    tel = engine.telemetry
    assert tel.spans is None and tel.recorder is None and \
        tel.watchdog is None
    assert tel.programs is not None              # observatory rides along
    _train_steps(engine, 1)
    assert not os.path.exists(os.path.join(tel.output_dir, "spans.jsonl"))
    assert not os.path.exists(os.path.join(tel.output_dir, "crash"))
    serve = _serve_engine(tmp_path / "srv", paged=False,
                          telemetry=None)
    sched = ContinuousBatchingScheduler(serve)
    assert sched._spans is None and sched._watchdog is None
    assert len(ds_logger.handlers) == n_handlers
    assert signal.getsignal(signal.SIGTERM) == before_handler
    assert {t.name for t in threading.enumerate()
            if t.name.startswith("ds-watchdog")} - before_threads == set()
    # telemetry fully OFF keeps the one-is-not-None contract
    off = _engine(tmp_path / "off", telemetry=None)
    assert off.telemetry is None


# ------------------------------------------------ checker pinned copies

def _load_checker():
    path = os.path.join(os.path.dirname(__file__), "..", "..", "bin",
                        "check_bench_schema.py")
    spec = importlib.util.spec_from_file_location("check_bench_schema",
                                                  os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_checker_local_copies_pinned_to_source_of_truth():
    checker = _load_checker()
    assert tuple(checker.CRASH_BUNDLE_KEYS) == tuple(CRASH_BUNDLE_KEYS)


def test_checker_rejects_malformed_diagnostics_artifacts():
    checker = _load_checker()
    assert checker.check_crash_bundle({"kind": "crash_bundle"})
    assert checker.check_trace_events("not json at all [")
    assert checker.check_trace_events("[]")      # no events
    bad_event = json.dumps([{"name": "x", "ph": "X", "ts": 1.0,
                             "pid": 0}])         # no tid/dur
    assert checker.check_trace_events(bad_event)
    good = json.dumps([{"name": "x", "ph": "X", "ts": 1.0, "dur": 2.0,
                        "pid": 0, "tid": 1}])
    assert checker.check_trace_events(good) == []


# ------------------------------------------------- env report satellite

def test_collect_env_is_bundle_ready():
    from deepspeed_tpu.env_report import collect_env, main
    env = collect_env()
    json.dumps(env)                              # JSON-serializable
    assert env["jax_version"] == jax.__version__
    assert env["device_count"] == jax.device_count()
    assert env["devices"][0]["kind"]
    assert "python_version" in env and "platform" in env
    import io
    out = io.StringIO()
    assert main(out) == 0
    text = out.getvalue()
    assert "jax version" in text and "HBM per device" in text


# --------------------------------------------- flops profiler satellite

def test_flops_profiler_loud_when_costs_missing(tmp_path, monkeypatch):
    from deepspeed_tpu.profiling.flops_profiler import profiler as prof_mod
    engine = _engine(tmp_path, telemetry={"enabled": True,
                                          "output_path": str(tmp_path)})
    prof = prof_mod.FlopsProfiler(engine)
    with _capture_warnings() as messages:
        assert prof.profile_engine_step() == {}
        assert prof.get_total_flops() is None
    assert sum("flops_profiler" in m and "cost_analysis" in m
               for m in messages) == 2
    # under telemetry.strict the same no-ops raise
    engine._config.telemetry_config.strict = True
    with pytest.raises(ValueError, match="flops_profiler"):
        prof.profile_engine_step()
    engine._config.telemetry_config.strict = False

    # pricing delegates to telemetry's costs_of_compiled (one home)
    calls = []
    real = prof_mod.cost_analysis_of
    from deepspeed_tpu.telemetry import collector as coll_mod

    def spy(fn, *args):
        calls.append("delegated")
        return {"flops": 7.0}

    monkeypatch.setattr(coll_mod, "costs_of_compiled", spy)
    costs = real(lambda x: x * 2, jnp.ones((2,)))
    assert calls == ["delegated"] and costs["flops"] == 7.0
