"""Sequence/context parallelism: ring + all-to-all attention vs dense
reference, forward and gradients, on the 8-device CPU mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel import build_mesh, sequence_parallel_attention
from deepspeed_tpu.parallel.ring_attention import (
    _dense_reference_attention)


def _qkv(b=2, s=64, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.5
    return mk(), mk(), mk()


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense(impl, causal):
    q, k, v = _qkv()
    mesh = build_mesh(sequence=4)
    out = sequence_parallel_attention(q, k, v, mesh, impl=impl,
                                      causal=causal)
    ref = _dense_reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_gradients_match_dense(impl):
    q, k, v = _qkv(b=1, s=32, h=4, d=8)
    mesh = build_mesh(sequence=4)

    def loss_sp(q, k, v):
        out = sequence_parallel_attention(q, k, v, mesh, impl=impl)
        return jnp.sum(out * out)

    def loss_ref(q, k, v):
        out = _dense_reference_attention(q, k, v)
        return jnp.sum(out * out)

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_ring_uneven_heads_ok():
    # ring has no head-divisibility constraint (unlike ulysses)
    q, k, v = _qkv(b=1, s=40, h=3, d=8)
    mesh = build_mesh(sequence=8)
    out = sequence_parallel_attention(q, k, v, mesh, impl="ring")
    ref = _dense_reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_bad_heads():
    q, k, v = _qkv(b=1, s=32, h=3, d=8)
    mesh = build_mesh(sequence=4)
    with pytest.raises(ValueError):
        sequence_parallel_attention(q, k, v, mesh, impl="ulysses")


def test_gpt2_with_sequence_parallel_matches_dense():
    from deepspeed_tpu.models import gpt2
    mesh = build_mesh(sequence=4)
    base = dict(vocab_size=256, max_seq_len=64, n_layers=2, n_heads=4,
                d_model=64, use_flash_attention=False, remat=False)
    cfg_sp = gpt2.GPT2Config(sequence_parallel="ring", sp_mesh=mesh, **base)
    cfg_ref = gpt2.GPT2Config(**base)
    params = gpt2.init_params(cfg_ref, seed=0)
    ids = np.random.RandomState(0).randint(0, 256, (2, 64)).astype(np.int32)
    loss_sp = gpt2.lm_loss(params, ids, ids, cfg_sp, train=False)
    loss_ref = gpt2.lm_loss(params, ids, ids, cfg_ref, train=False)
    np.testing.assert_allclose(np.asarray(loss_sp), np.asarray(loss_ref),
                               rtol=1e-5)


def test_gpt2_sequence_parallel_with_remat_eager():
    # remat=True wraps blocks in jax.checkpoint; the shard_map inside must
    # still evaluate eagerly (ring_attention jits its shard_map).
    from deepspeed_tpu.models import gpt2
    mesh = build_mesh(sequence=4)
    cfg = gpt2.GPT2Config(vocab_size=256, max_seq_len=64, n_layers=1,
                          n_heads=4, d_model=64, use_flash_attention=False,
                          remat=True, sequence_parallel="ring", sp_mesh=mesh)
    params = gpt2.init_params(cfg, seed=0)
    ids = np.random.RandomState(0).randint(0, 256, (2, 64)).astype(np.int32)
    loss = gpt2.lm_loss(params, ids, ids, cfg, train=False)
    assert np.isfinite(float(loss))


def test_dp_sp_composition_keeps_batch_sharded():
    # With a (data, sequence) mesh the output must keep 'data' on dim 0.
    mesh = build_mesh(data=2, sequence=4)
    q, k, v = _qkv(b=4, s=32, h=4, d=8)
    out = sequence_parallel_attention(q, k, v, mesh, impl="ring")
    spec = out.sharding.spec
    assert spec[0] == "data", spec
    ref = _dense_reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
