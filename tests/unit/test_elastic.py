"""Elasticity solver tests (mirrors reference tests/unit/test_elastic.py)."""
import pytest

import deepspeed_tpu
from deepspeed_tpu import elasticity
from deepspeed_tpu.version import __version__ as ds_version


def base_config():
    return {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 10000,
            "micro_batch_sizes": [8, 12, 16, 17],
            "min_gpus": 32,
            "max_gpus": 1500,
            "min_time": 20,
            "version": 0.1,
        }
    }


def test_basic_10k():
    ds_config = base_config()
    final_batch_size, valid_gpus = elasticity.compute_elastic_config(
        ds_config=ds_config, target_deepspeed_version=ds_version)

    for gpu_num in valid_gpus:
        assert final_batch_size % gpu_num == 0
        batch_per_gpu = final_batch_size // gpu_num
        assert any(batch_per_gpu % mb == 0
                   for mb in ds_config["elasticity"]["micro_batch_sizes"])

    assert len(valid_gpus) == 23
    assert final_batch_size == 9792


def test_disabled():
    ds_config = base_config()
    ds_config["elasticity"]["enabled"] = False
    with pytest.raises(elasticity.ElasticityError):
        elasticity.compute_elastic_config(ds_config=ds_config,
                                          target_deepspeed_version=ds_version)


def test_valid_world_size():
    final_batch_size, valid_gpus, mbsize = elasticity.compute_elastic_config(
        ds_config=base_config(), target_deepspeed_version=ds_version,
        world_size=64)
    assert mbsize == 17


def test_invalid_world_size():
    with pytest.raises(elasticity.ElasticityIncompatibleWorldSize):
        elasticity.compute_elastic_config(ds_config=base_config(),
                                          target_deepspeed_version=ds_version,
                                          world_size=128)


def test_future_elastic_version():
    ds_config = base_config()
    ds_config["elasticity"]["version"] = "0.2"
    with pytest.raises(elasticity.ElasticityError):
        elasticity.compute_elastic_config(ds_config=ds_config,
                                          target_deepspeed_version=ds_version)


def test_missing_max_batch():
    ds_config = base_config()
    del ds_config["elasticity"]["max_train_batch_size"]
    with pytest.raises(elasticity.ElasticityConfigError):
        elasticity.compute_elastic_config(ds_config=ds_config,
                                          target_deepspeed_version=ds_version)


def test_missing_micro_batch():
    ds_config = base_config()
    del ds_config["elasticity"]["micro_batch_sizes"]
    with pytest.raises(elasticity.ElasticityConfigError):
        elasticity.compute_elastic_config(ds_config=ds_config,
                                          target_deepspeed_version=ds_version)


def test_empty_config():
    with pytest.raises(elasticity.ElasticityConfigError):
        elasticity.compute_elastic_config(ds_config={"elasticity": {}},
                                          target_deepspeed_version=ds_version)


def test_proper_mbsz():
    ds_config = base_config()
    ds_config["elasticity"]["max_train_batch_size"] = 32
    ds_config["elasticity"]["micro_batch_sizes"] = [1, 2, 3, 7]
    ds_config["elasticity"]["min_gpus"] = 1
    final_batch_size, valid_gpus, mbsize = elasticity.compute_elastic_config(
        ds_config=ds_config, target_deepspeed_version=ds_version, world_size=7)
    assert mbsize == 3


def test_non_elastic_batch_params_w_override(tmp_config_file):
    """Batch params + elasticity coexist only with ignore_non_elastic_batch_info."""
    import jax
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    world = jax.device_count()
    ds_config = {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 4,
            "micro_batch_sizes": [1, 2, 4],
            "min_gpus": 1,
            "max_gpus": 4,
            "version": 0.1,
            "ignore_non_elastic_batch_info": True,
        },
    }
    # world=8 is not a valid gpu count for max batch 4 -> incompatible
    if world == 8:
        with pytest.raises(elasticity.ElasticityIncompatibleWorldSize):
            DeepSpeedConfig(None, param_dict=ds_config)


def test_non_elastic_batch_params_conflict():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    ds_config = {
        "train_batch_size": 8,
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 1000,
            "micro_batch_sizes": [1, 2, 4],
            "version": 0.1,
        },
    }
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(None, param_dict=ds_config)
