"""GPT-2 pipeline (3D-parallel smoke): PP x DP training on the CPU mesh."""
import numpy as np
import pytest

import jax

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import gpt2, gpt2_pipe

TINY = dict(vocab_size=128, max_seq_len=32, n_layers=4, n_heads=2,
            d_model=32, use_flash_attention=False, remat=False)


def make_net(num_stages=2, num_dp=4, num_mp=None):
    cfg = gpt2.GPT2Config(**TINY)
    return gpt2_pipe.make_gpt2_pipeline(config=cfg, num_stages=num_stages,
                                        num_dp=num_dp, num_mp=num_mp,
                                        activation_checkpoint_interval=0)


def batches(M, b, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, 128, size=(M, b, 32)).astype(np.int32)
    return ids, ids.copy()


def cfg(gas):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }


@pytest.mark.slow
def test_gpt2_pipeline_trains():
    net = make_net(num_stages=2, num_dp=4)
    engine, _, _, _ = deepspeed.initialize(model=net, config_params=cfg(2))
    x, y = batches(2, 8)
    losses = [float(engine.train_batch(batch=(x, y))) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.3, losses
    # tied embed params sharded/replicated sanely + body on pipe axis
    body_w = engine.state["params"]["body"]["attn"]["qkv_kernel"]
    assert "pipe" in str(body_w.sharding.spec)


@pytest.mark.slow
def test_gpt2_pipeline_3d():
    """PP=2 x DP=2 x TP=2 mesh: full 3D parallel one-step smoke."""
    net = make_net(num_stages=2, num_dp=2, num_mp=2)
    engine, _, _, _ = deepspeed.initialize(model=net, config_params=cfg(2))
    assert dict(engine.mesh.shape) == {"pipe": 2, "data": 2, "model": 2}
    x, y = batches(2, 4)
    l0 = float(engine.train_batch(batch=(x, y)))
    l1 = float(engine.train_batch(batch=(x, y)))
    assert np.isfinite(l0) and np.isfinite(l1)
    assert l1 < l0


def test_gpt2_pipeline_matches_sequential():
    """Pipeline loss == sequential eval loss on the same params/batch."""
    net = make_net(num_stages=2, num_dp=4)
    engine, _, _, _ = deepspeed.initialize(model=net, config_params=cfg(2))
    x, y = batches(2, 8, seed=3)
    ev = float(engine.eval_batch(batch=(x, y)))
    tr = float(engine.train_batch(batch=(x, y)))
    assert tr == pytest.approx(ev, rel=5e-2, abs=5e-3)
